"""Transactional-lakehouse concurrency tests: snapshot-isolated reads,
OCC commit-retry with rebase, vacuum under reader leases, crash hygiene,
and the deterministic two-thread interleaving harness (reference
semantics: Iceberg/Delta under Spark — snapshot isolation, commit-conflict
retry, snapshot expiry; nds/nds_maintenance.py:118-202,
nds_rollback.py:46-51)."""

import json
import os
import posixpath
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine.session import Session
from nds_tpu.lakehouse import table as TBL
from nds_tpu.lakehouse.leases import LEASES, ReaderLeases
from nds_tpu.lakehouse.table import (
    CommitConflictError,
    LakehouseError,
    LakehouseTable,
)
from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer
from nds_tpu.report import BenchReport


@pytest.fixture(autouse=True)
def _clean_faults_and_hook():
    faults.reset()
    TBL._COMMIT_HOOK = None
    os.environ["NDS_LAKE_COMMIT_BACKOFF"] = "0"
    yield
    faults.reset()
    TBL._COMMIT_HOOK = None
    os.environ.pop("NDS_LAKE_COMMIT_BACKOFF", None)
    os.environ.pop("NDS_LAKE_COMMIT_RETRIES", None)
    os.environ.pop("NDS_LAKE_CONFLICT_RETRIES", None)


def _ints(*vals):
    return pa.table({"a": pa.array(list(vals), type=pa.int64())})


def _make(tmp_path, *vals):
    path = str(tmp_path / "t")
    return LakehouseTable.create(path, _ints(*vals)), path


def _data_files(path):
    return sorted(os.listdir(os.path.join(path, "data")))


def _manifests(path):
    return sorted(
        f for f in os.listdir(os.path.join(path, "_manifests"))
        if f.startswith("v")
    )


# ---------------------------------------------------------------------------
# snapshot-isolated reads
# ---------------------------------------------------------------------------


def test_snapshot_handle_pins_version(tmp_path):
    lt, path = _make(tmp_path, 1, 2, 3)
    snap = lt.snapshot()
    lt.replace(_ints(9))
    # the handle still reads the pinned manifest, the table reads the head
    assert snap.dataset().count_rows() == 3
    assert snap.num_rows() == 3
    assert lt.dataset().count_rows() == 1
    # explicit version resolution
    assert lt.snapshot(1).dataset().count_rows() == 3


def test_session_pin_survives_racing_replace(tmp_path):
    """The acceptance oracle: a statement pinned at version N returns
    bit-identical results whether a racing commit lands before, during
    (between plan and execution, cache wiped), or after it."""
    lt, path = _make(tmp_path, *range(15))
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("t", path)
    baseline = s.sql("select a from t order by a").collect()

    # plan (pins the snapshot) ... then the replace lands ... then execute
    r = s.sql("select a from t order by a")
    LakehouseTable(path).replace(_ints(99))
    # wipe every cached device column: execution must re-read through the
    # PIN, not survive on cache luck
    s.recover_memory("test: force reload through the pin")
    assert r.collect().equals(baseline)

    # scanning twice inside one racing window: same pin, same answer
    r2 = s.sql("select a from t order by a")
    assert r2.collect().equals(r2.collect())

    # a FRESH statement re-pins and sees the new head
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 1}]


def test_pin_registers_and_releases_reader_lease(tmp_path):
    lt, path = _make(tmp_path, 1, 2)
    root = LakehouseTable(path).root
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("t", path)
    before = LEASES.live_count(root)
    s.sql("select count(*) c from t").collect()
    assert LEASES.live_count(root) == before + 1
    e = s.catalog.entries["t"]
    assert e.pinned_version == 1 and e.lease_id is not None
    # DML invalidation releases the pin's lease
    s.catalog.invalidate("t")
    assert LEASES.live_count(root) == before
    assert e.pinned_version is None and e.lease_id is None


def test_dml_delete_reads_one_snapshot_and_aborts_on_conflict(tmp_path):
    """A DELETE's row count and survivor scan resolve ONE snapshot, and a
    commit racing the transaction aborts it (overwrite/* never rebases)
    instead of silently dropping the winner's rows."""
    lt, path = _make(tmp_path, *range(10))
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("t", path)

    def land_append(name, op, version):
        TBL._COMMIT_HOOK = None  # fire once
        LakehouseTable(path).append(_ints(1000))

    TBL._COMMIT_HOOK = land_append
    with pytest.raises(CommitConflictError):
        s.sql("delete from t where a >= 5")
    assert faults.classify(CommitConflictError("x")) == faults.COMMIT_CONFLICT
    # nothing published by the loser: the winner's append is the head
    vals = sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )
    assert vals == sorted(list(range(10)) + [1000])


# ---------------------------------------------------------------------------
# OCC conflict matrix
# ---------------------------------------------------------------------------


def test_append_append_rebase_converges_both_rows(tmp_path):
    """Two appends race onto the same version: the loser rebases onto the
    winner's head and BOTH row sets land (Iceberg fast-append retry)."""
    lt, path = _make(tmp_path, 0)
    tracer = Tracer()
    fired = []

    def land_competitor(name, op, version):
        if not fired:
            fired.append(version)
            TBL._COMMIT_HOOK = None
            LakehouseTable(path).append(_ints(100))

    from nds_tpu.obs import trace as obs_trace

    TBL._COMMIT_HOOK = land_competitor
    with obs_trace.bind(tracer):
        LakehouseTable(path).append(_ints(200))
    vals = sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )
    assert vals == [0, 100, 200]
    # the loser's lake_commit records the rebase
    mine = [
        e for e in tracer.events
        if e["kind"] == "lake_commit" and e.get("rebased")
    ]
    assert mine and mine[0]["attempts"] == 2
    assert [v for v, _, _ in LakehouseTable(path).versions()] == [1, 2, 3]


def test_overwrite_conflict_aborts_and_discards_staged(tmp_path):
    lt, path = _make(tmp_path, 1, 2, 3)

    def land_append(name, op, version):
        TBL._COMMIT_HOOK = None
        LakehouseTable(path).append(_ints(50))

    before_files = set(_data_files(path))
    TBL._COMMIT_HOOK = land_append
    with pytest.raises(CommitConflictError):
        LakehouseTable(path).replace(_ints(7))
    # the loser's staged file was discarded; only the winner's file is new
    after = set(_data_files(path))
    assert len(after - before_files) == 1
    # the winner's commit is intact (never lost to the aborted overwrite)
    vals = sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )
    assert vals == [1, 2, 3, 50]


def test_two_inprocess_writers_same_version_oracle(tmp_path):
    """The pre-rebase commit-conflict oracle (previously untested): two
    writers claiming the same version -> exactly one wins, the loser
    raises a LakehouseError. With retries disabled even an append must
    surface the conflict — the rebase loop preserves this contract for
    overwrite/overwrite unconditionally."""
    lt, path = _make(tmp_path, 0)
    os.environ["NDS_LAKE_COMMIT_RETRIES"] = "0"

    def land_append(name, op, version):
        TBL._COMMIT_HOOK = None
        LakehouseTable(path).append(_ints(1))

    TBL._COMMIT_HOOK = land_append
    with pytest.raises(LakehouseError) as ei:
        LakehouseTable(path).append(_ints(2))
    assert "concurrent commit conflict" in str(ei.value)
    # exactly one commit won version 2
    assert [v for v, _, _ in LakehouseTable(path).versions()] == [1, 2]

    # overwrite/overwrite with DEFAULT retries: still an abort, never a
    # rebase (the matrix the new loop must preserve)
    os.environ.pop("NDS_LAKE_COMMIT_RETRIES")

    def land_replace(name, op, version):
        TBL._COMMIT_HOOK = None
        LakehouseTable(path).replace(_ints(77))

    TBL._COMMIT_HOOK = land_replace
    with pytest.raises(CommitConflictError):
        LakehouseTable(path).replace(_ints(88))
    vals = [
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    ]
    assert vals == [77]  # the winner's replace, untouched


def test_deterministic_two_thread_schedule(tmp_path):
    """Schedule-controlled two-thread harness: the commit hook is a
    deterministic commit point — thread A parks AT its publish attempt,
    thread B commits, then A resumes and rebases. No timing luck."""
    lt, path = _make(tmp_path, 0)
    a_at_commit = threading.Event()
    b_done = threading.Event()

    def hook(name, op, version):
        if threading.current_thread().name == "writer-a":
            TBL._COMMIT_HOOK = None
            a_at_commit.set()
            assert b_done.wait(10)

    TBL._COMMIT_HOOK = hook
    errs = []

    def writer_a():
        try:
            LakehouseTable(path).append(_ints(1))
        except Exception as e:  # pragma: no cover - failure surfaces below
            errs.append(e)

    ta = threading.Thread(target=writer_a, name="writer-a")
    ta.start()
    assert a_at_commit.wait(10)
    LakehouseTable(path).append(_ints(2))
    b_done.set()
    ta.join(10)
    assert not errs
    vals = sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )
    assert vals == [0, 1, 2]


# ---------------------------------------------------------------------------
# the commit_rebase_retry ladder rung
# ---------------------------------------------------------------------------


class _Sess:
    """Minimal session facade for BenchReport."""

    def __init__(self):
        self.conf = {}
        self.tracer = None
        self.metrics = None

    def register_listener(self, cb):
        pass

    def unregister_listener(self, cb):
        pass


def test_commit_conflict_walks_ladder_then_succeeds():
    s = _Sess()
    attempts = []

    def txn():
        attempts.append(1)
        if len(attempts) == 1:
            raise CommitConflictError(
                "concurrent commit conflict at version 9; retry"
            )

    rep = BenchReport(s)
    summary = rep.report_on(txn, retry_oom=True, name="txn")
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in summary["ladder"]] == ["commit_rebase_retry"]
    assert summary["ladder"][0]["kind"] == faults.COMMIT_CONFLICT
    assert len(attempts) == 2


def test_commit_conflict_budget_exhausts_to_hard_failure():
    os.environ["NDS_LAKE_CONFLICT_RETRIES"] = "2"
    s = _Sess()

    def txn():
        raise CommitConflictError("concurrent commit conflict at version 3")

    rep = BenchReport(s)
    summary = rep.report_on(txn, retry_oom=True, name="txn")
    assert summary["queryStatus"] == ["Failed"]
    assert summary["failureKind"] == faults.COMMIT_CONFLICT
    assert [r["rung"] for r in summary["ladder"]] == [
        "commit_rebase_retry", "commit_rebase_retry",
    ]


def test_commit_conflict_without_retry_opt_in_fails_fast():
    s = _Sess()

    def txn():
        raise CommitConflictError("concurrent commit conflict at version 3")

    summary = BenchReport(s).report_on(txn, name="txn")  # no retry_oom
    assert summary["queryStatus"] == ["Failed"]
    assert "ladder" not in summary


# ---------------------------------------------------------------------------
# crash hygiene + fault sites
# ---------------------------------------------------------------------------


def test_crash_at_commit_during_replace_is_all_or_nothing(tmp_path):
    """Satellite regression: a crash fault at commit:<table> during
    replace() (explicit base_files) leaves the PREVIOUS snapshot fully
    readable — staged files orphaned, no manifest published — pinning the
    all-or-nothing guarantee the commit-site comment promises."""
    lt, path = _make(tmp_path, 1, 2, 3)
    manifests_before = _manifests(path)
    files_before = _data_files(path)
    faults.install("crash:commit:t")
    with pytest.raises(faults.InjectedCrash):
        LakehouseTable(path).replace(_ints(9))
    faults.reset()
    # no manifest published; previous snapshot intact and readable
    assert _manifests(path) == manifests_before
    lt2 = LakehouseTable(path)
    assert lt2.current_version() == 1
    assert sorted(
        x["a"] for x in lt2.dataset().to_table().to_pylist()
    ) == [1, 2, 3]
    # the staged file IS orphaned on disk (crash landed pre-publish)
    orphans = set(_data_files(path)) - set(files_before)
    assert len(orphans) == 1


def test_crash_at_stage_never_loses_committed_snapshot(tmp_path):
    lt, path = _make(tmp_path, 1, 2, 3)
    manifests_before = _manifests(path)
    faults.install("crash:stage:t")
    with pytest.raises(faults.InjectedCrash):
        LakehouseTable(path).append(_ints(4))
    faults.reset()
    assert _manifests(path) == manifests_before
    assert sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    ) == [1, 2, 3]


def test_stage_write_io_fault_walks_io_ladder(tmp_path):
    """An io fault at the stage:<table> site classifies io_transient and
    the ladder's backoff rung retries the transaction to completion."""
    lt, path = _make(tmp_path, 1)
    faults.install("io:stage:t:1")
    s = _Sess()

    def txn():
        LakehouseTable(path).append(_ints(2))

    summary = BenchReport(s).report_on(txn, retry_oom=True, name="txn")
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in summary["ladder"]] == ["io_backoff_retry"]
    assert LakehouseTable(path).num_rows() == 2


def test_manifest_read_io_fault_site(tmp_path):
    lt, path = _make(tmp_path, 1)
    faults.install("io:manifest:t:1")
    with pytest.raises(faults.TransientIOError):
        LakehouseTable(path).snapshot()
    faults.reset()
    assert LakehouseTable(path).snapshot().version == 1


def test_vacuum_crash_never_loses_committed_snapshot(tmp_path):
    lt, path = _make(tmp_path, 1, 2)
    lt.replace(_ints(3))
    lt.replace(_ints(4))
    faults.install("crash:vacuum:t")
    with pytest.raises(faults.InjectedCrash):
        LakehouseTable(path).vacuum(retain_last=1)
    faults.reset()
    # every retained manifest still resolves and its files exist
    lt2 = LakehouseTable(path)
    for v, _, _ in lt2.versions():
        for f in lt2.snapshot(v).files():
            assert os.path.exists(f)
    # a re-run completes the job
    res = lt2.vacuum(retain_last=1)
    assert res["manifests_removed"] == 2 and res["files_removed"] == 2
    assert lt2.dataset().to_table().to_pylist() == [{"a": 4}]


def test_orphan_sweep_units(tmp_path):
    lt, path = _make(tmp_path, 1, 2)
    data = os.path.join(path, "data")
    mans = os.path.join(path, "_manifests")
    dead_stage = "part-999999-abcdefabcdef.parquet"
    live_stage = f"part-{os.getpid()}-abcdefabcdef.parquet"
    torn_tmp = ".tmp-999999-deadbeef.json"
    live_tmp = f".tmp-{os.getpid()}-deadbeef.json"
    foreign = "somebody-elses.file"
    legacy = "part-abcdefabcdef.parquet"  # pre-pid format: unattributable
    for n in (dead_stage, live_stage, foreign, legacy):
        open(os.path.join(data, n), "w").close()
    for n in (torn_tmp, live_tmp):
        open(os.path.join(mans, n), "w").close()
    assert lt.sweep_orphans() == 2
    remaining = set(os.listdir(data))
    assert dead_stage not in remaining
    assert {live_stage, foreign, legacy} <= remaining
    man_remaining = set(os.listdir(mans))
    assert torn_tmp not in man_remaining and live_tmp in man_remaining
    # committed (referenced) files are never sweep candidates
    assert lt.dataset().count_rows() == 2


def test_session_start_sweep_removes_crashed_writer_orphans(tmp_path):
    lt, path = _make(tmp_path, 1)
    orphan = "part-999999-abcdefabcdef.parquet"
    open(os.path.join(path, "data", orphan), "w").close()
    s = Session(conf={})
    s.register_lakehouse("t", path)
    assert orphan not in _data_files(path)
    # file-set equality against the retained manifests
    referenced = set()
    lt2 = LakehouseTable(path)
    for v, _, _ in lt2.versions():
        referenced.update(
            posixpath.basename(f) for f in lt2.snapshot(v).files()
        )
    assert set(_data_files(path)) == referenced


# ---------------------------------------------------------------------------
# vacuum + leases
# ---------------------------------------------------------------------------


def test_vacuum_respects_retention_and_reader_leases(tmp_path):
    lt, path = _make(tmp_path, *range(10))
    lt.replace(_ints(1, 2))   # v2
    lt.replace(_ints(3))      # v3
    lt.replace(_ints(4))      # v4
    root = LakehouseTable(path).root
    snap1 = lt.snapshot(1)
    lease = LEASES.acquire(root, 1, snap1.rel_files, ttl_s=60)
    res = lt.vacuum(retain_last=2)
    # v2 expired + collected; v1 survives whole (leased version keeps its
    # manifest), v3/v4 retained
    assert res["manifests_removed"] == 1 and res["files_removed"] == 1
    assert [v for v, _, _ in lt.versions()] == [1, 3, 4]
    for v, _, _ in lt.versions():
        for f in lt.snapshot(v).files():
            assert os.path.exists(f)
    # lease-file protection proper: even with the manifest gone, a leased
    # file is never deleted
    os.unlink(os.path.join(path, "_manifests", "v000001.json"))
    res2 = lt.vacuum(retain_last=2)
    assert res2["files_leased"] == 1
    assert posixpath.basename(snap1.rel_files[0]) in set(_data_files(path))
    LEASES.release(lease)


def test_expired_lease_no_longer_blocks_vacuum(tmp_path):
    lt, path = _make(tmp_path, 1)
    lt.replace(_ints(2))
    root = LakehouseTable(path).root
    snap1 = lt.snapshot(1)
    LEASES.acquire(root, 1, snap1.rel_files, ttl_s=0.05)
    time.sleep(0.1)
    res = lt.vacuum(retain_last=1)
    assert res["manifests_removed"] == 1 and res["files_removed"] == 1
    assert posixpath.basename(snap1.rel_files[0]) not in set(
        _data_files(path)
    )


def test_vacuum_never_deletes_file_under_live_session_pin(tmp_path):
    """End to end: a session's plan-time pin (not a hand-made lease) is
    what protects the files its query still reads."""
    lt, path = _make(tmp_path, *range(5))
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("t", path)
    r = s.sql("select a from t order by a")  # pins v1 + leases its files
    baseline = r.collect()
    LakehouseTable(path).replace(_ints(9))           # v2: head moves on
    res = LakehouseTable(path).vacuum(retain_last=1)  # tries to drop v1
    # v1's manifest is leased -> retained; its files still exist
    s.recover_memory("test: force re-read through the pin")
    assert r.collect().equals(baseline)
    assert res["files_removed"] == 0


def test_expire_snapshots_keeps_head_always(tmp_path):
    lt, path = _make(tmp_path, 1)
    assert lt.expire_snapshots(retain_last=1) == []
    assert [v for v, _, _ in lt.versions()] == [1]


def test_lease_table_units():
    lt = ReaderLeases()
    i1 = lt.acquire("/r", 3, ["data/a", "data/b"], ttl_s=60)
    i2 = lt.acquire("/r", 4, ["data/c"], ttl_s=60)
    lt.acquire("/other", 1, ["data/z"], ttl_s=60)
    assert lt.held_versions("/r") == {3, 4}
    assert lt.held_files("/r") == {"data/a", "data/b", "data/c"}
    assert lt.live_count("/r") == 2
    assert lt.release(i1) and not lt.release(i1)
    assert lt.held_files("/r") == {"data/c"}
    assert lt.renew(i2, ttl_s=60)
    i3 = lt.acquire("/r", 5, ["data/d"], ttl_s=0.01)
    time.sleep(0.05)
    assert 5 not in lt.held_versions("/r")
    assert not lt.renew(i3, ttl_s=60)


def test_versions_tolerates_concurrently_expired_manifest(tmp_path):
    """A manifest vanishing between the listing and its read (a racing
    expire_snapshots) must read as the post-expiry log, not crash the
    reader with FileNotFoundError."""
    lt, path = _make(tmp_path, 1)
    lt.append(_ints(2))

    class _FlakyFS:
        def __init__(self, inner, fail_substr):
            self._inner = inner
            self._sub = fail_substr
            self._fired = False

        def open(self, p, *a, **kw):
            if not self._fired and self._sub in str(p):
                self._fired = True
                raise FileNotFoundError(p)
            return self._inner.open(p, *a, **kw)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    lt.fs = _FlakyFS(lt.fs, "v000001.json")
    assert [v for v, _, _ in lt.versions()] == [2]  # v1 skipped, no crash
    assert lt.current_version() == 2  # filename-derived: no reads at all


def test_remote_warehouse_never_pid_attributes(tmp_path):
    """Pid liveness is host-local: on a shared (remote) warehouse the
    sweep is a no-op and vacuum protects every never-referenced stage —
    a live writer on another host must not lose its in-flight commit."""
    import uuid as _uuid

    root = f"memory://lake-{_uuid.uuid4().hex}/t"
    lt = LakehouseTable.create(root, _ints(1))
    lt.replace(_ints(2))
    # a "dead-pid" staged file: on a local table this would be swept
    lt.fs.pipe_file(
        lt.data_dir + "/part-999999-abcdefabcdef.parquet", b"x"
    )
    assert not lt._is_local()
    assert lt.sweep_orphans() == 0
    res = lt.vacuum(retain_last=1)
    names = {
        f.rsplit("/", 1)[-1]
        for f in lt.fs.ls(lt.data_dir, detail=False)
    }
    assert "part-999999-abcdefabcdef.parquet" in names  # protected
    assert res["files_removed"] == 1  # v1's committed-then-expired file


def test_conflict_knob_parsing_single_home():
    from nds_tpu.lakehouse.table import (
        commit_backoff_base,
        resolve_conflict_retries,
    )

    os.environ["NDS_LAKE_CONFLICT_RETRIES"] = "7"
    assert resolve_conflict_retries() == 7
    os.environ["NDS_LAKE_CONFLICT_RETRIES"] = "junk"
    assert resolve_conflict_retries() == 2
    del os.environ["NDS_LAKE_CONFLICT_RETRIES"]
    assert commit_backoff_base() == 0.0  # fixture sets backoff env to 0


def test_shared_session_concurrent_repin_serves_plan_version(tmp_path):
    """The detached-load guard: a plan pinned at vN on a session whose
    entry another statement re-pinned to vM still reads vN — including
    through the all-columns-cached path."""
    lt, path = _make(tmp_path, 1, 2, 3)
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("t", path)
    r_old = s.sql("select a from t order by a")  # pins v1
    old = r_old.collect()
    LakehouseTable(path).replace(_ints(9))
    # a second statement re-pins the ENTRY to v2 and loads its columns
    # into the shared device cache
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 1}]
    # the v1 plan re-executes against the re-pinned, fully-cached entry:
    # the detached load must serve v1, not the cached v2 columns
    r_old._table = None  # force a fresh execution of the same pinned plan
    assert r_old.collect().equals(old)


# ---------------------------------------------------------------------------
# rollback semantics (satellite)
# ---------------------------------------------------------------------------


def test_rollback_timestamp_tie_selects_that_snapshot(tmp_path):
    lt, path = _make(tmp_path, 1, 2)
    v1_ts = lt.versions()[0][1]
    lt.append(_ints(3))
    # ts EXACTLY equal to v1's strictly-monotonic stamp selects v1
    v = lt.rollback_to_timestamp(v1_ts)
    assert v == 3
    assert lt.dataset().count_rows() == 2
    # one ms earlier: nothing at-or-before -> loud error
    with pytest.raises(LakehouseError):
        LakehouseTable(path).rollback_to_timestamp(v1_ts - 1)


def test_rollback_of_rollback_replays_right_file_list(tmp_path):
    lt, path = _make(tmp_path, 1, 2)        # v1
    lt.append(_ints(3))                     # v2
    v3 = lt.rollback_to_version(1)          # v3 == v1's files
    lt.append(_ints(4))                     # v4
    v3_ts = dict(
        (v, ts) for v, ts, _ in lt.versions()
    )[v3]
    v5 = lt.rollback_to_timestamp(v3_ts)    # rollback OF the rollback
    assert v5 == 5
    m1 = lt.snapshot(1).rel_files
    m5 = lt.snapshot(5).rel_files
    assert m5 == m1  # replays v1's exact file list (via v3)
    assert sorted(
        x["a"] for x in lt.dataset().to_table().to_pylist()
    ) == [1, 2]


# ---------------------------------------------------------------------------
# events + metrics
# ---------------------------------------------------------------------------


def test_lake_events_schema_and_metrics(tmp_path):
    from nds_tpu.obs import trace as obs_trace
    from nds_tpu.obs.metrics import MetricsSink
    from nds_tpu.obs.reader import validate_events

    lt, path = _make(tmp_path, 1)
    sink = MetricsSink()
    tracer = Tracer(sink=sink)
    with obs_trace.bind(tracer):
        lt.append(_ints(2))
        lt.replace(_ints(3))
        lt.vacuum(retain_last=1)
    kinds = [e["kind"] for e in tracer.events]
    assert kinds.count("lake_commit") == 2
    assert kinds.count("lake_vacuum") == 1
    assert validate_events(tracer.events) == []
    for e in tracer.events:
        if e["kind"] == "lake_commit":
            for field in EVENT_SCHEMA["lake_commit"]:
                assert field in e
        if e["kind"] == "lake_vacuum":
            for field in EVENT_SCHEMA["lake_vacuum"]:
                assert field in e
    reg = sink.registry
    assert reg.counter_value(
        "nds_lake_commit_total", operation="append", status="ok"
    ) == 1
    assert reg.counter_value(
        "nds_lake_commit_total", operation="overwrite", status="ok"
    ) == 1
    assert reg.counter_value("nds_lake_commit_attempts_total") == 2
    assert reg.counter_value("nds_lake_vacuum_total", table="t") == 1


def test_profile_tallies_lake_events(tmp_path):
    from nds_tpu.obs.reader import profile_events

    lt, path = _make(tmp_path, 1)
    tracer = Tracer()
    from nds_tpu.obs import trace as obs_trace

    with obs_trace.bind(tracer):
        lt.append(_ints(2))
        try:
            def clash(name, op, version):
                TBL._COMMIT_HOOK = None
                LakehouseTable(path).append(_ints(7))

            TBL._COMMIT_HOOK = clash
            lt.replace(_ints(3))
        except CommitConflictError:
            pass
        # a successful replace detaches the old files, so vacuum has work
        LakehouseTable(path).replace(_ints(9))
        lt.vacuum(retain_last=1)
    prof = profile_events(tracer.events)
    t = prof["tallies"]
    # create is untraced; append + clash-append + final replace succeed
    assert t["lake_commits"] == 3
    assert t["lake_commit_conflicts"] == 1
    assert t["lake_vacuums"] == 1
    assert t["lake_vacuum_files"] >= 1
