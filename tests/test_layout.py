"""Data-layout tests (PR 16): zone-map stats + pruning, the ledgered
parallel-ingest path, compaction/OPTIMIZE, and the scan-path-listing lint
rule. The reference gets all of this from Iceberg/Delta data skipping +
OPTIMIZE under Spark; here the write side is lakehouse/zonemap.py feeding
the manifest `stats` key at commit, the read side is the planner's
`_prune_lake_scans` pass, and compaction is `LakehouseTable.compact`."""

import math
import os

import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.analysis import lint as L
from nds_tpu.engine.session import Session
from nds_tpu.lakehouse import table as TBL
from nds_tpu.lakehouse import zonemap as Z
from nds_tpu.lakehouse.table import CommitConflictError, LakehouseTable
from nds_tpu.maintenance import optimize_warehouse
from nds_tpu.obs.trace import Tracer


@pytest.fixture(autouse=True)
def _clean_faults_and_hook():
    faults.reset()
    TBL._COMMIT_HOOK = None
    os.environ["NDS_LAKE_COMMIT_BACKOFF"] = "0"
    yield
    faults.reset()
    TBL._COMMIT_HOOK = None
    os.environ.pop("NDS_LAKE_COMMIT_BACKOFF", None)


def _ints(*vals):
    return pa.table({"a": pa.array(list(vals), type=pa.int64())})


def _acc(tbl):
    acc = Z.StatsAccumulator()
    for b in tbl.to_batches():
        acc.update(b)
    return acc.finish()


# ---------------------------------------------------------------------------
# write side: StatsAccumulator
# ---------------------------------------------------------------------------


def test_stats_min_max_nulls_across_batches():
    tbl = pa.table({
        "i": pa.array([5, None, 2, 9], type=pa.int64()),
        "s": pa.array(["m", "a", None, "z"]),
        "b": pa.array([True, False, True, None]),
    })
    st = _acc(tbl)
    assert st["rows"] == 4
    assert st["columns"]["i"] == {"nulls": 1, "min": 2, "max": 9}
    assert st["columns"]["s"] == {"nulls": 1, "min": "a", "max": "z"}
    assert st["columns"]["b"] == {"nulls": 1, "min": False, "max": True}


def test_stats_nan_handling():
    # mixed: NaN is excluded from bounds (safe — NaN never satisfies a
    # SQL comparison, so pruning on the non-NaN envelope is sound)
    st = _acc(pa.table({"f": pa.array([1.0, float("nan"), None, 3.0])}))
    assert st["columns"]["f"] == {"nulls": 1, "min": 1.0, "max": 3.0}
    # all-NaN: the reduction collapses to the inverted identity interval
    # (inf, -inf); bounds must be dropped, not recorded
    nan = float("nan")
    st = _acc(pa.table({"f": pa.array([nan, nan, None])}))
    assert st["columns"]["f"] == {"nulls": 1}
    assert not math.isinf(st["columns"]["f"].get("min", 0.0))


def test_stats_unboundable_type_records_nulls_only():
    tbl = pa.table({
        "d": pa.array([1, None], type=pa.decimal128(7, 2)),
    })
    st = _acc(tbl)
    assert st["columns"]["d"] == {"nulls": 1}


def test_stats_all_null_column_has_no_bounds():
    tbl = pa.table({"i": pa.array([None, None], type=pa.int64())})
    st = _acc(tbl)
    assert st["columns"]["i"] == {"nulls": 2}


def test_string_truncation_bounds_stay_safe():
    long_min = "a" * 100
    long_max = "b" * 100
    st = _acc(pa.table({"s": pa.array([long_min, long_max])}))
    ent = st["columns"]["s"]
    # truncated min is a prefix (sorts <= the real min); truncated max is
    # rounded UP past everything sharing the prefix
    assert ent["min"] == "a" * Z._STR_BOUND_LIMIT
    assert ent["min"] <= long_min
    assert ent["max"] > long_max
    assert len(ent["max"]) <= Z._STR_BOUND_LIMIT


def test_string_max_at_codepoint_ceiling_drops_bounds():
    ceiling = chr(Z._MAX_CODEPOINT) * (Z._STR_BOUND_LIMIT + 5)
    st = _acc(pa.table({"s": pa.array([ceiling])}))
    assert st["columns"]["s"] == {"nulls": 0}  # unbounded above: no bounds


def test_trunc_max_rounds_up_or_none():
    assert Z._trunc_max("short") == "short"
    rolled = Z._trunc_max("a" * 70)
    assert rolled == "a" * (Z._STR_BOUND_LIMIT - 1) + "b"
    assert Z._trunc_max(chr(Z._MAX_CODEPOINT) * 70) is None


# ---------------------------------------------------------------------------
# read side: conjunct evaluation
# ---------------------------------------------------------------------------


def _fstats(rows, **cols):
    return {"rows": rows, "columns": cols}


def test_interval_logic_each_operator():
    st = _fstats(10, a={"nulls": 0, "min": 10, "max": 20})
    keep = Z.file_may_match
    assert keep(st, [("cmp", "a", "=", 15)])
    assert not keep(st, [("cmp", "a", "=", 9)])
    assert not keep(st, [("cmp", "a", "<", 10)])
    assert keep(st, [("cmp", "a", "<=", 10)])
    assert not keep(st, [("cmp", "a", ">", 20)])
    assert keep(st, [("cmp", "a", ">=", 20)])
    assert keep(st, [("between", "a", 18, 30)])
    assert not keep(st, [("between", "a", 21, 30)])
    assert keep(st, [("in", "a", (1, 12))])
    assert not keep(st, [("in", "a", (1, 2, 30))])
    # conjunction: any failing conjunct prunes
    assert not keep(st, [("cmp", "a", "=", 15), ("cmp", "a", ">", 20)])


def test_all_null_file_pruned_by_null_rejecting_predicates():
    st = _fstats(5, a={"nulls": 5})
    for pred in (("cmp", "a", "=", 1), ("between", "a", 0, 9),
                 ("in", "a", (1,)), ("notnull", "a")):
        assert not Z.file_may_match(st, [pred])
    # a present-null but not all-null column without bounds always keeps
    st2 = _fstats(5, a={"nulls": 3})
    assert Z.file_may_match(st2, [("cmp", "a", "=", 1)])
    assert Z.file_may_match(st2, [("notnull", "a")])


def test_missing_information_always_keeps():
    # no stats entry for the file, no column entry, type-mismatched
    # literal: every gap reads "may match"
    assert Z.file_may_match(None, [("cmp", "a", "=", 1)])
    assert Z.file_may_match({}, [("cmp", "a", "=", 1)])
    assert Z.file_may_match(_fstats(3), [("cmp", "a", "=", 1)])
    st = _fstats(3, a={"nulls": 0, "min": 1, "max": 9})
    assert Z.file_may_match(st, [("cmp", "a", "=", "x")])  # str vs int
    # bool bounds only compare against bool literals (True == 1 trap)
    bt = _fstats(3, a={"nulls": 0, "min": False, "max": False})
    assert Z.file_may_match(bt, [("cmp", "a", "=", 1)])
    assert not Z.file_may_match(bt, [("cmp", "a", "=", True)])


def test_prune_files_exact_pruned_rows_and_statless_manifest():
    stats = {
        "data/f1": _fstats(10, a={"nulls": 0, "min": 0, "max": 9}),
        "data/f2": _fstats(7, a={"nulls": 0, "min": 100, "max": 200}),
        # data/f3 absent: old-format manifest file — never pruned
    }
    files = ["data/f1", "data/f2", "data/f3"]
    keep, pruned = Z.prune_files(files, stats, [("cmp", "a", "<", 10)])
    assert keep == ["data/f1", "data/f3"]
    assert pruned == 7
    # a fully statless (pre-PR16) manifest prunes nothing
    keep, pruned = Z.prune_files(files, {}, [("cmp", "a", "<", 10)])
    assert keep == files and pruned == 0


# ---------------------------------------------------------------------------
# commit integration: stats + ledger travel with the manifest
# ---------------------------------------------------------------------------


def test_commit_records_stats_and_append_inherits(tmp_path):
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(path, _ints(1, 2, 3))
    snap = lt.snapshot()
    [f] = snap.rel_files
    assert snap.file_stats()[f]["columns"]["a"] == {
        "nulls": 0, "min": 1, "max": 3}
    lt.append(_ints(10, 11))
    snap2 = lt.snapshot()
    stats = snap2.file_stats()
    assert len(stats) == 2 and f in stats  # base file's stats inherited
    news = [s for r, s in stats.items() if r != f]
    assert news[0]["columns"]["a"] == {"nulls": 0, "min": 10, "max": 11}


def test_old_manifest_without_stats_key_reads_fine(tmp_path):
    import json

    path = str(tmp_path / "t")
    lt = LakehouseTable.create(path, _ints(1, 2))
    mpath = os.path.join(path, "_manifests", "v000001.json")
    with open(mpath) as fh:
        m = json.load(fh)
    m.pop("stats", None)
    m.pop("ingest_chunks", None)
    with open(mpath, "w") as fh:
        json.dump(m, fh)
    snap = LakehouseTable(path).snapshot()
    assert snap.file_stats() == {}
    assert snap.ingest_chunks() == set()
    assert sorted(
        x["a"] for x in snap.dataset().to_table().to_pylist()) == [1, 2]


def test_ingest_chunk_ledger_exactly_once(tmp_path):
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(
        path, schema=pa.schema([("a", pa.int64())]))
    v = lt.ingest_chunk(_ints(1, 2), "t:c0")
    assert v == 2  # create was v1
    assert lt.ingest_chunk(_ints(1, 2), "t:c0") is None  # pre-flight skip
    snap = lt.snapshot()
    assert snap.ingest_chunks() == {"t:c0"}
    assert snap.num_rows() == 2


def test_ingest_chunk_race_exactly_once_at_commit_point(tmp_path):
    """Two writers replay the SAME chunk; the loser must discover the
    winner's ledger entry at the commit point (its pre-flight check ran
    before the winner published) and publish nothing."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, schema=pa.schema([("a", pa.int64())]))
    a, b = LakehouseTable(path), LakehouseTable(path)
    fired = []

    def land_competitor(name, op, version):
        if fired:
            return
        fired.append(1)
        TBL._COMMIT_HOOK = None  # the competitor's own commit skips the hook
        try:
            assert b.ingest_chunk(_ints(7, 8), "t:c0") is not None
        finally:
            TBL._COMMIT_HOOK = land_competitor

    TBL._COMMIT_HOOK = land_competitor
    assert a.ingest_chunk(_ints(7, 8), "t:c0") is None
    snap = LakehouseTable(path).snapshot()
    assert snap.num_rows() == 2  # not doubled
    assert sorted(
        x["a"] for x in snap.dataset().to_table().to_pylist()) == [7, 8]
    # the loser's staged files were discarded, not left as debris
    assert len(os.listdir(os.path.join(path, "data"))) == 1


def test_stage_clustered_narrow_ranges(tmp_path):
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(
        path, schema=pa.schema([("k", pa.int64()), ("v", pa.int64())]))
    n = 400
    tbl = pa.table({
        "k": pa.array([(i * 37) % 100 for i in range(n)]),
        "v": pa.array(list(range(n))),
    })
    lt.ingest_chunk(tbl, "t:c0", cluster_by="k",
                    max_file_bytes=tbl.nbytes // 4)
    snap = lt.snapshot()
    assert len(snap.rel_files) >= 3
    stats = snap.file_stats()
    spans = []
    for rel in snap.rel_files:
        ent = stats[rel]["columns"]["k"]
        spans.append((ent["min"], ent["max"]))
        assert ent["max"] - ent["min"] < 100  # narrower than the domain
    # clustered: file ranges are disjoint-ish ascending, data intact
    assert spans == sorted(spans)
    got = snap.dataset().to_table()
    assert sorted(got.column("v").to_pylist()) == list(range(n))


# ---------------------------------------------------------------------------
# planner integration: pruning on vs off, value-identical + budget
# ---------------------------------------------------------------------------


def _clustered_session(tmp_path, conf=None):
    path = str(tmp_path / "t")
    if not LakehouseTable.is_table(path):
        lt = LakehouseTable.create(
            path, schema=pa.schema([("k", pa.int64()), ("v", pa.int64())]))
        n = 1000
        tbl = pa.table({
            "k": pa.array(list(range(n))),
            "v": pa.array([i * 3 for i in range(n)]),
        })
        lt.ingest_chunk(tbl, "t:c0", cluster_by="k", max_file_bytes=2000)
    s = Session(conf={"lakehouse.warehouse": str(tmp_path), **(conf or {})})
    s.tracer = Tracer()  # in-memory event stream for assertions
    s.register_lakehouse("t", path)
    return s, path


def test_sql_pruning_value_identical_and_majority_pruned(tmp_path):
    s_on, path = _clustered_session(tmp_path)
    s_off, _ = _clustered_session(tmp_path, {"engine.lake_prune": "off"})
    q = "select k, v from t where k between 100 and 150 order by k"
    on = s_on.sql(q).collect().to_pydict()
    off = s_off.sql(q).collect().to_pydict()
    assert on == off
    assert on["k"][0] == 100 and on["k"][-1] == 150
    evs = [e for e in s_on.tracer.events if e["kind"] == "scan_prune"]
    assert evs, "pruning session must emit scan_prune"
    ev = evs[0]
    assert ev["files_pruned"] * 2 >= ev["files_total"]  # >= 50% skipped
    assert ev["rows_bound"] >= 51  # upper bound covers the true 51 rows
    assert not [e for e in s_off.tracer.events if e["kind"] == "scan_prune"]


def test_pruning_tightens_the_budget(tmp_path):
    """A COLD lakehouse table answers cardinality from its manifest
    (CatalogStats must not degrade a fleet's admission verdicts to
    `unknown` before first touch); the pruned row bound is a strictly
    TIGHTER hard upper bound than the full-table model. The table here
    is sized well past the bucket floor (_MIN_CAP) so the tightening is
    visible in peak bytes, not swallowed by bucket rounding."""
    path = str(tmp_path / "big")
    lt = LakehouseTable.create(
        path, schema=pa.schema([("k", pa.int64()), ("v", pa.int64())]))
    n = 20000
    lt.ingest_chunk(pa.table({
        "k": pa.array(list(range(n))),
        "v": pa.array([i * 3 for i in range(n)]),
    }), "big:c0", cluster_by="k", max_file_bytes=20000)
    q = "select k, v from big where k between 100 and 150"
    s_off = Session(conf={"engine.lake_prune": "off"})
    s_off.register_lakehouse("big", path)
    _, rec_off = s_off.plan_sql(q)
    s_on = Session()
    s_on.register_lakehouse("big", path)
    _, rec_on = s_on.plan_sql(q)
    assert rec_off["verdict"] != "unknown"  # cold: manifest num_rows
    assert rec_on["verdict"] != "unknown"
    assert 0 < rec_on["peak_bytes"] < rec_off["peak_bytes"]


def test_pruned_count_star_is_exact(tmp_path):
    # zero-projection subset load: count(*) must reflect the FULL table
    # minus nothing (pruning keeps every file that may match; the filter
    # re-applies on survivors)
    s_on, _ = _clustered_session(tmp_path)
    s_off, _ = _clustered_session(tmp_path, {"engine.lake_prune": "off"})
    q = "select count(*) as c from t where k between 100 and 150"
    on = s_on.sql(q).collect().to_pydict()
    off = s_off.sql(q).collect().to_pydict()
    assert on == off and on["c"] == [51]


# ---------------------------------------------------------------------------
# compaction / OPTIMIZE
# ---------------------------------------------------------------------------


def _fragment(tmp_path, chunks=5, rows=60):
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(
        path, schema=pa.schema([("a", pa.int64())]))
    n = 0
    for c in range(chunks):
        tbl = pa.table({"a": pa.array(list(range(n, n + rows)))})
        lt.ingest_chunk(tbl, f"t:c{c}", max_file_bytes=1)  # 1 file each
        n += rows
    return lt, path, n


def test_compact_merges_small_files_and_regenerates_stats(tmp_path):
    lt, path, n = _fragment(tmp_path)
    before = lt.snapshot()
    assert len(before.rel_files) >= 5
    res = lt.compact(target_bytes=1 << 20, min_input_files=2)
    assert res["version"] is not None
    after = lt.snapshot()
    assert len(after.rel_files) < len(before.rel_files)
    assert after.num_rows() == n
    assert after.manifest["operation"] == "optimize"
    # ledger survives compaction (resume-safety), stats regenerated
    assert after.ingest_chunks() == before.ingest_chunks()
    for rel in after.rel_files:
        ent = after.file_stats()[rel]["columns"]["a"]
        assert 0 <= ent["min"] <= ent["max"] < n
    assert sorted(
        x["a"] for x in after.dataset().to_table().to_pylist()
    ) == list(range(n))


def test_compact_under_concurrent_pinned_reader(tmp_path):
    lt, path, n = _fragment(tmp_path)
    pinned = lt.snapshot()  # reader pinned BEFORE the rewrite
    assert lt.compact(target_bytes=1 << 20)["version"] is not None
    # the pinned snapshot still reads its own (pre-compaction) file set,
    # value-identical — compaction publishes a new version, deletes nothing
    assert sorted(
        x["a"] for x in pinned.dataset().to_table().to_pylist()
    ) == list(range(n))


def test_compact_aborts_on_racing_commit_and_optimize_retries(tmp_path):
    lt, path, n = _fragment(tmp_path)
    fired = []

    def land_append(name, op, version):
        if op != "optimize" or fired:
            return
        fired.append(1)
        TBL._COMMIT_HOOK = None
        try:
            LakehouseTable(path).append(_ints(9999))
        finally:
            TBL._COMMIT_HOOK = land_append

    TBL._COMMIT_HOOK = land_append
    # compaction is an explicit-base transaction: the racing append wins,
    # the compaction aborts (never the other writer)
    with pytest.raises(CommitConflictError):
        lt.compact(target_bytes=1 << 20)
    assert fired
    # the warehouse-level pass re-plans against the new head and lands
    results = optimize_warehouse(str(tmp_path), target_bytes=1 << 20)
    assert [r for r in results if r["version"] is not None]
    final = LakehouseTable(path).snapshot()
    assert sorted(
        x["a"] for x in final.dataset().to_table().to_pylist()
    ) == list(range(n)) + [9999]


def test_compact_noop_below_min_files(tmp_path):
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(path, _ints(1, 2, 3))
    res = lt.compact(target_bytes=1 << 20, min_input_files=4)
    assert res["version"] is None and res["files_in"] == 0
    assert len(lt.snapshot().rel_files) == 1


# ---------------------------------------------------------------------------
# ingest machinery: prefetch + resume (in-process)
# ---------------------------------------------------------------------------


def _write_dat(dirpath, name, rows):
    os.makedirs(dirpath, exist_ok=True)
    p = os.path.join(dirpath, name)
    with open(p, "w") as f:
        for sk in rows:
            f.write(f"{sk}|{sk * 10}|{sk * 10 + 9}|\n")
    return p


def test_prefetch_preserves_order_and_propagates_errors(tmp_path):
    from nds_tpu.schema import get_schemas
    from nds_tpu.transcode import _Prefetch

    schema = get_schemas(True)["income_band"]
    src = str(tmp_path / "raw")
    paths = [
        _write_dat(src, f"c{i}.dat", range(i * 10, i * 10 + 3))
        for i in range(4)
    ]
    got = list(_Prefetch(paths, schema, True))
    assert [p for p, _, _ in got] == paths
    assert [t.num_rows for _, t, _ in got] == [3, 3, 3, 3]
    assert all(ms >= 0 for _, _, ms in got)
    with pytest.raises(Exception):
        list(_Prefetch([str(tmp_path / "missing.dat")], schema, True))


def test_transcode_lakehouse_resume_exactly_once(tmp_path):
    from nds_tpu.schema import get_schemas
    from nds_tpu.transcode import transcode_table

    schema = get_schemas(True)["income_band"]
    src = str(tmp_path / "raw" / "income_band")
    for c in range(3):
        _write_dat(src, f"income_band_{c + 1}_3.dat",
                   range(c * 20, c * 20 + 20))
    rows = transcode_table(str(tmp_path / "raw"), str(tmp_path / "wh"),
                           "income_band", schema,
                           output_format="lakehouse")
    assert rows == 60
    dst = str(tmp_path / "wh" / "income_band")
    lt = LakehouseTable(dst)
    assert lt.snapshot().num_rows() == 60
    assert len(lt.snapshot().ingest_chunks()) == 3
    # re-run without --resume refuses (table exists)
    with pytest.raises(FileExistsError):
        transcode_table(str(tmp_path / "raw"), str(tmp_path / "wh"),
                        "income_band", schema, output_format="lakehouse")
    # --resume replays nothing: the ledger is complete
    rows2 = transcode_table(str(tmp_path / "raw"), str(tmp_path / "wh"),
                            "income_band", schema,
                            output_format="lakehouse", resume=True)
    assert rows2 == 0
    assert lt.snapshot().num_rows() == 60
    # a NEW generator chunk appears (e.g. a widened dataset): resume
    # ingests exactly it
    _write_dat(src, "income_band_4_3.dat", range(60, 70))
    rows3 = transcode_table(str(tmp_path / "raw"), str(tmp_path / "wh"),
                            "income_band", schema,
                            output_format="lakehouse", resume=True)
    assert rows3 == 10
    snap = LakehouseTable(dst).snapshot()
    assert snap.num_rows() == 70
    assert sorted(
        x["ib_income_band_sk"]
        for x in snap.dataset().to_table().to_pylist()
    ) == list(range(70))


def test_ingest_emits_ledgered_trace_events(tmp_path):
    from nds_tpu.obs import trace as obs_trace
    from nds_tpu.obs.trace import Tracer
    from nds_tpu.schema import get_schemas
    from nds_tpu.transcode import _ingest_chunks

    schema = get_schemas(True)["income_band"]
    src = str(tmp_path / "raw")
    paths = [_write_dat(src, "c0.dat", range(5))]
    dst = str(tmp_path / "t")
    LakehouseTable.create(dst, schema=pa.schema(
        [(f.name, f.dtype.to_arrow(True)) for f in schema]))
    tracer = Tracer(None)
    with obs_trace.bind(tracer):
        rows, committed = _ingest_chunks(
            dst, "income_band", schema, True, paths, None)
    assert (rows, committed) == (5, 1)
    evs = [e for e in tracer.events if e["kind"] == "ingest_chunk"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["table"] == "income_band" and ev["rows"] == 5
    assert ev["chunk"] == "income_band:c0.dat"
    assert not ev["skipped"] and ev["decode_ms"] >= 0


# ---------------------------------------------------------------------------
# lint: scan-path-listing
# ---------------------------------------------------------------------------


def test_scan_path_listing_rule_flags_raw_listings():
    src = (
        "import glob, os\n"
        "from glob import iglob\n"
        "def bad(d):\n"
        "    a = glob.glob(d + '/*.parquet')\n"
        "    b = os.listdir(d)\n"
        "    c = list(iglob(d))\n"
        "    return a, b, c\n"
    )
    findings = L.lint_source(src, "engine/session.py")
    hits = [f for f in findings if f.rule == "scan-path-listing"]
    assert len(hits) == 3
    # out of scope: the same source in a non-scan-path module is clean
    assert not [
        f for f in L.lint_source(src, "engine/aotcache.py")
        if f.rule == "scan-path-listing"
    ]


def test_scan_path_modules_are_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("engine/session.py", "engine/exec.py"):
        with open(os.path.join(repo, "nds_tpu", rel)) as fh:
            findings = L.lint_source(fh.read(), rel)
        assert not [
            f for f in findings if f.rule == "scan-path-listing"
        ], rel
