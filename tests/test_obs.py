"""Observability subsystem: the structured event log (golden schema +
nesting invariants), memory high-water sampling, child-stream fold-in /
subprocess failure classification, and the operator-level profiler CLI.

The event schema is a CONTRACT (nds_tpu/obs/trace.py:EVENT_SCHEMA): the
profiler, the throughput parent's fold-in, and full_bench's phase-failure
classification all parse these files, so every kind's required fields are
asserted here against events produced by the real emission sites."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu import full_bench as FB
from nds_tpu import throughput as TP
from nds_tpu.cli import profile as profile_cli
from nds_tpu.engine.session import Session
from nds_tpu.obs import reader as R
from nds_tpu.obs.memwatch import MemorySampler
from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer, bind, tracer_from_conf
from nds_tpu.report import BenchReport

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("NDS_TRACE_DIR", raising=False)
    monkeypatch.delenv("NDS_FAULT_SPEC", raising=False)
    faults.reset()
    yield
    faults.reset()


def _events(path_or_dir):
    return R.read_events(path_or_dir, strict=True)


def _traced_session(tmp_path, **conf):
    conf = {"engine.trace_dir": str(tmp_path / "trace"), **conf}
    s = Session(conf=conf)
    s.register_arrow(
        "t",
        pa.table({"a": [1, 2, 3, 4, 2, 1], "b": [10, 20, 30, 40, 50, 60]}),
    )
    return s


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_tracer_disabled_by_default():
    s = Session()
    assert s.tracer is None
    assert tracer_from_conf({}) is None


def test_tracer_writes_meta_and_appends(tmp_path):
    tr = tracer_from_conf({"engine.trace_dir": str(tmp_path)})
    tr.emit("io_retry", path="/x", error="e", delay_s=0.0)
    tr.close()
    evs = _events(tr.path)
    assert [e["kind"] for e in evs] == ["trace_meta", "io_retry"]
    assert evs[0]["pid"] == os.getpid()
    assert all("ts" in e and e["app"] == tr.app_id for e in evs)


def test_tracer_auto_scopes_query(tmp_path):
    tr = tracer_from_conf({"engine.trace_dir": str(tmp_path)})
    with faults.scope("query42"):
        tr.emit("plan_cache", node="Aggregate", hit=True)
    tr.emit("plan_cache", node="Aggregate", hit=False)
    evs = _events(tr.path)
    assert evs[1]["query"] == "query42"
    assert "query" not in evs[2]


def test_memory_tracer_collects_in_process():
    tr = Tracer()  # no dir: in-memory (tools/trace_query.py mode)
    tr.emit("plan_cache", node="Distinct", hit=False)
    assert tr.path is None
    assert [e["kind"] for e in tr.events] == ["plan_cache"]


def test_tracer_thread_binding():
    tr = Tracer()
    seen = {}

    def worker():
        from nds_tpu.obs.trace import current

        seen["inner"] = current()

    with bind(tr):
        from nds_tpu.obs.trace import current

        assert current() is tr
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    from nds_tpu.obs.trace import current

    assert current() is None
    assert seen["inner"] is None  # thread-locals do not inherit


# ---------------------------------------------------------------------------
# golden schema + engine emission sites
# ---------------------------------------------------------------------------


def test_engine_events_golden_schema(tmp_path):
    s = _traced_session(tmp_path)
    with bind(s.tracer):
        with faults.scope("q_agg"):
            s.sql("select a, sum(b) sb from t group by a order by a").collect()
        with faults.scope("q_agg2"):  # plan-cache hit on the aggregate
            s.sql("select a, sum(b) sb from t group by a order by a").collect()
        with faults.scope("q_scan"):  # catalog cache hit (columns resident)
            s.sql("select a, b from t").collect()
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    kinds = {e["kind"] for e in evs}
    assert {"trace_meta", "op_span", "catalog_load", "plan_cache"} <= kinds
    # plan cache: one miss (first aggregate) then one hit (second)
    pc = [e for e in evs if e["kind"] == "plan_cache"]
    assert [e["hit"] for e in pc] == [False, True]
    # catalog: first load is a miss, the later full-resident load is a hit
    cl = [e for e in evs if e["kind"] == "catalog_load"]
    assert cl[0]["cache"] == "miss" and cl[-1]["cache"] == "hit"
    assert all(e["table"] == "t" for e in cl)
    # op spans carry rows + bytes and are query-scoped
    ops = [e for e in evs if e["kind"] == "op_span"]
    assert all(e["query"].startswith("q_") for e in ops)
    assert any(e["rows"] is not None and e["rows"] > 0 for e in ops)
    assert all(e["est_bytes"] >= 0 for e in ops)


def test_op_span_nesting_invariants(tmp_path):
    s = _traced_session(tmp_path)
    with faults.scope("q"):
        s.sql(
            "select a, sum(b) sb from t where b > 10 group by a order by a"
        ).collect()
    ops = [e for e in _events(s.tracer.path) if e["kind"] == "op_span"]
    by_exec = {}
    for e in ops:
        by_exec.setdefault(e["exec_id"], []).append(e)
    for spans in by_exec.values():
        spans.sort(key=lambda e: e["seq"])
        # seq is 1..n with no gaps; completion (post-) order means a parent
        # at depth d completes after its depth-d+1 children
        assert [e["seq"] for e in spans] == list(range(1, len(spans) + 1))
        assert spans[-1]["depth"] == 0  # the root completes last
        acc = {}
        for e in spans:
            d = e["depth"]
            child_ms = acc.pop(d + 1, 0.0)
            # inclusive timing: a parent's span covers its children
            assert e["dur_ms"] >= child_ms - 1e-6
            acc[d] = acc.get(d, 0.0) + e["dur_ms"]
        # nothing left dangling deeper than the root
        assert set(acc) == {0}
    withx = R.op_spans_with_exclusive(ops)
    assert all(e["excl_ms"] >= 0 for e in withx)
    # exclusive sums to the root inclusive time per executor
    for eid, spans in by_exec.items():
        root = max(e["dur_ms"] for e in spans if e["depth"] == 0)
        tot_excl = sum(
            e["excl_ms"] for e in withx if e["exec_id"] == eid
        )
        roots = sum(
            e["dur_ms"] for e in spans if e["depth"] == 0
        )
        assert abs(tot_excl - roots) < 1e-3


def test_blocked_union_event(tmp_path):
    s = _traced_session(tmp_path)
    rng = np.random.default_rng(7)
    for t in ("u1", "u2"):
        s.register_arrow(
            t,
            pa.table({
                "k": pa.array(rng.integers(1, 5, 3000), pa.int32()),
                "v": pa.array(rng.integers(-50, 50, 3000), pa.int32()),
            }),
        )
    s.conf["engine.union_agg_window_rows"] = 512
    with faults.scope("q_union"):
        s.sql(
            "select k, sum(v) sv from (select k, v from u1 union all "
            "select k, v from u2) u group by k order by k"
        ).collect()
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    bu = [e for e in evs if e["kind"] == "blocked_union"]
    assert bu and bu[0]["windows"] > 1 and bu[0]["window_rows"] == 512
    assert bu[0]["total_rows"] == 6000
    assert bu[0]["query"] == "q_union"


def test_report_events_ladder_fault_and_query_span(tmp_path):
    s = _traced_session(tmp_path)
    faults.install("oom:q_flaky:1")
    with bind(s.tracer):
        def fn():
            faults.maybe_fire("q_flaky")

        summary = BenchReport(s).report_on(fn, retry_oom=True, name="q_flaky")
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    # engineConf/engineVersion aliases mirror the spark-named compat keys
    assert summary["env"]["engineConf"] == summary["env"]["sparkConf"]
    assert summary["env"]["engineVersion"] == summary["env"]["sparkVersion"]
    assert summary["memoryHighWater"]["bytes"] > 0
    assert summary["memoryHighWater"]["source"] in ("device", "rss")
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    fi = [e for e in evs if e["kind"] == "fault_injected"]
    assert fi and fi[0]["site"] == "q_flaky" and fi[0]["fault_kind"] == "oom"
    lr = [e for e in evs if e["kind"] == "ladder_rung"]
    assert [e["rung"] for e in lr] == ["recover_retry"]
    assert lr[0]["failure_kind"] == faults.DEVICE_OOM
    qs = [e for e in evs if e["kind"] == "query_span"]
    assert qs[-1]["query"] == "q_flaky"
    assert qs[-1]["status"] == "CompletedWithTaskFailures"
    assert qs[-1]["retries"] == 1
    assert qs[-1]["mem_hw_bytes"] == summary["memoryHighWater"]["bytes"]


def test_watchdog_fire_event(tmp_path):
    s = _traced_session(tmp_path, **{"engine.query_timeout": 0.3})

    def hang():
        time.sleep(5)

    summary = BenchReport(s).report_on(hang, name="q_hang")
    assert summary["failureKind"] == faults.TIMEOUT
    evs = _events(s.tracer.path)
    wf = [e for e in evs if e["kind"] == "watchdog_fire"]
    assert wf and wf[0]["query"] == "q_hang" and wf[0]["budget_s"] == 0.3
    qs = [e for e in evs if e["kind"] == "query_span"]
    assert qs[-1]["status"] == "Failed"
    assert qs[-1]["failure_kind"] == faults.TIMEOUT


def test_io_retry_event(tmp_path, monkeypatch):
    import fsspec

    from nds_tpu.io.fs import fs_open

    monkeypatch.setenv("NDS_IO_BACKOFF", "0")
    monkeypatch.setenv("NDS_IO_RETRIES", "2")
    fs = fsspec.filesystem("memory")
    with fs.open("/obs_retry/data.txt", "w") as f:
        f.write("payload")
    faults.install("io:obs_retry:1")
    tr = Tracer()
    with bind(tr):
        with fs_open("memory://obs_retry/data.txt") as f:
            assert f.read() == "payload"
    io_evs = [e for e in tr.events if e["kind"] == "io_retry"]
    assert len(io_evs) == 1
    assert "obs_retry" in io_evs[0]["path"]
    assert "transient io" in io_evs[0]["error"]


def test_memwatch_sampler_reads_a_peak():
    with MemorySampler(interval_s=0.005) as ms:
        _ = [0] * 100000
        time.sleep(0.03)
    assert ms.peak_bytes is not None and ms.peak_bytes > 0
    assert ms.source in ("device", "rss")


# ---------------------------------------------------------------------------
# reader: parsing contracts + fold-in + failure classification
# ---------------------------------------------------------------------------


def _write_jsonl(path, events, torn_tail=None):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        if torn_tail is not None:
            f.write(torn_tail)  # no newline: a crash mid-write


def _ev(kind, **kw):
    base = {"ts": 1, "kind": kind, "app": "app-x"}
    base.update(kw)
    return base


def test_iter_events_tolerates_torn_final_line_only(tmp_path):
    p = tmp_path / "events-a.jsonl"
    _write_jsonl(
        p, [_ev("trace_meta", pid=1, version="0")], torn_tail='{"ts": 2, "ki'
    )
    assert len(list(R.iter_events(p, strict=True))) == 1
    # a malformed MIDDLE line is corruption, not a crash artifact
    with open(p, "a") as f:
        f.write("\n{broken}\n" + json.dumps(_ev("plan_cache", node="x", hit=True)) + "\n")
    with pytest.raises(R.MalformedEventError):
        list(R.iter_events(p, strict=True))
    assert len(list(R.iter_events(p, strict=False))) >= 1


def test_validate_events_flags_missing_fields():
    ok = _ev("query_span", query="q1", dur_ms=1.0, status="Completed",
             retries=0)
    bad = _ev("query_span", query="q1")
    unknown = _ev("not_a_kind")
    probs = R.validate_events([ok, bad, unknown])
    assert len(probs) == 2
    assert "missing fields" in probs[0] and "unknown kind" in probs[1]
    assert set(EVENT_SCHEMA) >= {"op_span", "query_span", "child_stream"}


def test_failure_kind_from_events_prefers_failed_query_span():
    evs = [
        _ev("query_span", query="q1", dur_ms=1, status="Completed", retries=0),
        _ev("fault_injected", site="q2", fault_kind="io"),
        _ev("query_span", query="q2", dur_ms=1, status="Failed", retries=0,
            failure_kind=faults.DEVICE_OOM),
    ]
    assert R.failure_kind_from_events(evs) == faults.DEVICE_OOM
    # no failed span: the last injected fault's mapped kind
    assert (
        R.failure_kind_from_events(evs[:2]) == faults.IO_TRANSIENT
    )
    assert R.failure_kind_from_events([]) is None
    # a recorded query failure BEATS a later (recovered) injected fault
    evs2 = [
        _ev("query_span", query="q3", dur_ms=1, status="Failed", retries=0,
            failure_kind=faults.PLANNER),
        _ev("fault_injected", site="q4", fault_kind="io"),
        _ev("query_span", query="q4", dur_ms=1, status="Completed",
            retries=1),
    ]
    assert R.failure_kind_from_events(evs2) == faults.PLANNER


def test_profile_multi_stream_sums_per_query(tmp_path):
    """Profiling several streams' files together (a throughput trace dir)
    must SUM per query name — not mix one stream's wall with all streams'
    operator times — and a single failed run marks the query Failed."""
    d = tmp_path / "tt"
    d.mkdir()
    for app, status, mem in (("s1", "Completed", 500), ("s2", "Failed", 900)):
        _write_jsonl(d / f"events-{app}.jsonl", [
            _ev("op_span", app=app, query="query1", exec_id=1, seq=1,
                depth=0, node="Aggregate", explain="Aggregate",
                dur_ms=100.0, rows=5, est_bytes=40),
            _ev("query_span", app=app, query="query1", dur_ms=120.0,
                status=status, retries=0, mem_hw_bytes=mem,
                mem_source="rss",
                **({"failure_kind": faults.DEVICE_OOM}
                   if status == "Failed" else {})),
        ])
    prof = R.profile_events(R.read_events(str(d)))
    q1 = prof["queries"]["query1"]
    assert q1["runs"] == 2
    assert q1["wall_ms"] == 240.0  # summed across streams
    assert q1["root_incl_ms"] == 200.0  # plan time stays <= wall time
    assert q1["root_incl_ms"] <= q1["wall_ms"]
    assert q1["status"] == "Failed"  # any failed run surfaces
    assert q1["failure_kind"] == faults.DEVICE_OOM
    assert q1["mem_hw_bytes"] == 900  # max, not last-wins


def test_fold_child_streams_emits_summary_and_classifies(tmp_path):
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    pid = 54321
    child = trace_dir / f"events-nds-tpu-{pid}-1-abc.jsonl"
    _write_jsonl(child, [
        _ev("trace_meta", pid=pid, version="0"),
        _ev("query_span", query="query1", dur_ms=5, status="Completed",
            retries=0),
        _ev("query_span", query="query5", dur_ms=9, status="Failed",
            retries=2, failure_kind=faults.DEVICE_OOM),
    ], torn_tail='{"torn')

    class FakeProc:
        def __init__(self, pid):
            self.pid = pid

    parent = Tracer()
    kinds = TP._fold_child_streams(
        parent, str(trace_dir), pre_existing=set(),
        procs={3: (FakeProc(pid), None)},
    )
    assert kinds == {3: faults.DEVICE_OOM}
    cs = [e for e in parent.events if e["kind"] == "child_stream"]
    assert len(cs) == 1
    assert cs[0]["stream"] == 3
    assert cs[0]["queries"] == 2 and cs[0]["completed"] == 1
    assert cs[0]["failed"] == {"query5": faults.DEVICE_OOM}
    assert R.validate_events(cs) == []


def test_phase_failure_classified_from_child_events(tmp_path, monkeypatch):
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    monkeypatch.setenv("NDS_TRACE_DIR", str(trace_dir))
    monkeypatch.setenv("NDS_PHASE_RETRIES", "1")
    monkeypatch.setenv("NDS_PHASE_BACKOFF", "0")
    state = FB.BenchState(str(tmp_path / "state.json"), "fp")
    calls = {"n": 0}

    def phase_fn():
        calls["n"] += 1
        # simulate a child process that wrote events then died opaquely
        _write_jsonl(
            trace_dir / f"events-nds-tpu-99-{calls['n']}-x.jsonl",
            [_ev("query_span", query="q", dur_ms=1, status="Failed",
                 retries=0, failure_kind=faults.IO_TRANSIENT)],
        )
        if calls["n"] == 1:
            raise subprocess.CalledProcessError(1, ["child"])  # opaque

    tracer = Tracer()
    FB._run_phase(state, "power_test", None, phase_fn, tracer=tracer)
    # opaque exit reclassified io_transient from the child's events -> retried
    assert calls["n"] == 2
    assert state.is_done("power_test")
    ph = [e for e in tracer.events if e["kind"] == "phase"]
    assert [e["event"] for e in ph] == ["begin", "end"]
    assert ph[-1]["status"] == "ok" and ph[-1]["attempts"] == 2
    assert R.validate_events(ph) == []


def test_phase_deterministic_failure_still_fails_fast(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_PHASE_RETRIES", "3")
    monkeypatch.setenv("NDS_PHASE_BACKOFF", "0")
    state = FB.BenchState(str(tmp_path / "state.json"), "fp")
    calls = {"n": 0}

    def phase_fn():
        calls["n"] += 1
        raise ValueError("ExecError: deterministic")

    tracer = Tracer()
    with pytest.raises(FB.PhaseError):
        FB._run_phase(state, "load_test", None, phase_fn, tracer=tracer)
    assert calls["n"] == 1
    ph = [e for e in tracer.events if e["kind"] == "phase"]
    assert ph[-1]["status"] == "failed"
    assert ph[-1]["failure_kind"] == faults.PLANNER


# ---------------------------------------------------------------------------
# profiler: aggregation + A/B compare + CLI
# ---------------------------------------------------------------------------


def _synthetic_run(tmp_path, name, scale=1.0, fail_q2=False):
    d = tmp_path / name
    d.mkdir()
    spans = [
        _ev("trace_meta", pid=1, version="0"),
        _ev("op_span", query="query1", exec_id=1, seq=1, depth=1,
            node="Scan", explain="Scan t", dur_ms=40 * scale, rows=100,
            est_bytes=800),
        _ev("op_span", query="query1", exec_id=1, seq=2, depth=1,
            node="MultiJoin", explain="MultiJoin", dur_ms=100 * scale,
            rows=50, est_bytes=400),
        _ev("op_span", query="query1", exec_id=1, seq=3, depth=0,
            node="Aggregate", explain="Aggregate", dur_ms=200 * scale,
            rows=5, est_bytes=40),
        _ev("query_span", query="query1", dur_ms=250 * scale,
            status="Completed", retries=0, mem_hw_bytes=1000,
            mem_source="rss"),
        _ev("catalog_load", table="t", columns=2, loaded=2, rows=100,
            dur_ms=3.0, cache="miss"),
        _ev("catalog_load", table="t", columns=2, loaded=0, rows=100,
            dur_ms=0.1, cache="hit"),
        _ev("plan_cache", node="Aggregate", hit=False),
    ]
    if fail_q2:
        spans.append(
            _ev("query_span", query="query2", dur_ms=10, status="Failed",
                retries=1, failure_kind=faults.DEVICE_OOM)
        )
    else:
        spans.append(
            _ev("query_span", query="query2", dur_ms=80, status="Completed",
                retries=0)
        )
    _write_jsonl(d / "events-run.jsonl", spans)
    return d


def test_profile_aggregation_and_exclusive_time(tmp_path):
    d = _synthetic_run(tmp_path, "run")
    prof = R.profile_events(R.read_events(str(d)))
    q1 = prof["queries"]["query1"]
    assert q1["wall_ms"] == 250.0
    assert q1["root_incl_ms"] == 200.0  # root span <= recorded wall
    assert q1["root_incl_ms"] <= q1["wall_ms"]
    # Aggregate exclusive = 200 - (40 + 100) children
    assert q1["ops"]["Aggregate"]["excl_ms"] == pytest.approx(60.0)
    assert q1["ops"]["Scan"]["rows"] == 100
    assert q1["mem_hw_bytes"] == 1000
    assert prof["op_totals"]["MultiJoin"]["excl_ms"] == pytest.approx(100.0)
    t = prof["tallies"]
    assert t["catalog_loads"] == 2 and t["catalog_cache_hits"] == 1
    assert t["plan_cache_misses"] == 1


def test_profile_compare_flags_regressions(tmp_path):
    old = _synthetic_run(tmp_path, "old", scale=1.0)
    new = _synthetic_run(tmp_path, "new", scale=3.0, fail_q2=True)
    regs = R.compare_profiles(
        R.profile_events(R.read_events(str(old))),
        R.profile_events(R.read_events(str(new))),
        ratio=1.25, min_ms=50.0,
    )
    changes = {(r["level"], r.get("node"), r["query"]): r for r in regs}
    assert ("query", None, "query1") in changes
    assert changes[("query", None, "query1")]["ratio"] == pytest.approx(3.0)
    assert ("operator", "Aggregate", "query1") in changes
    q2 = [r for r in regs if r["query"] == "query2"]
    assert q2 and q2[0]["change"] == "status_change"
    # identical runs: clean
    assert R.compare_profiles(
        R.profile_events(R.read_events(str(old))),
        R.profile_events(R.read_events(str(old))),
    ) == []


def test_profile_cli_renders_and_compares(tmp_path, capsys):
    old = _synthetic_run(tmp_path, "old", scale=1.0)
    new = _synthetic_run(tmp_path, "new", scale=3.0)
    profile_cli.main([str(old), "--per_query"])
    out = capsys.readouterr().out
    assert "query1" in out and "Aggregate" in out and "top" in out
    assert "catalog 2 loads (1 cache-hit)" in out
    profile_cli.main(["--compare", str(old), str(new)])
    out = capsys.readouterr().out
    assert "regression" in out and "query1" in out
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([
            "--compare", str(old), str(new), "--fail_on_regression",
        ])
    assert exc.value.code == 1


def test_profile_cli_fails_on_malformed_log(tmp_path, capsys):
    d = tmp_path / "bad"
    d.mkdir()
    (d / "events-x.jsonl").write_text('{"ts": 1}\n{broken}\n{"ts": 2}\n')
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(d)])
    assert exc.value.code == 2


def test_profile_cli_check_flags_schema_problems(tmp_path):
    d = tmp_path / "odd"
    d.mkdir()
    _write_jsonl(d / "events-x.jsonl", [_ev("not_a_kind")])
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(d), "--check"])
    assert exc.value.code == 2
    profile_cli.main([str(d)])  # without --check: warn only


# ---------------------------------------------------------------------------
# end-to-end: a traced power run over real (tiny) data + the profiler CLI
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    mini = tmp_path_factory.mktemp("mini_wh")
    for t in ("store_sales", "date_dim"):
        os.symlink(os.path.join(DATA, t), mini / t)
    return str(mini)


STREAM = """-- start query 1 in stream 0 using template query96.tpl
select count(*) cnt from store_sales where ss_quantity > 0
;
-- end query 1 in stream 0 using template query96.tpl

-- start query 2 in stream 0 using template query3.tpl
select d_year, count(*) c from date_dim group by d_year order by d_year limit 5
;
-- end query 2 in stream 0 using template query3.tpl

-- start query 3 in stream 0 using template query42.tpl
select d_moy, sum(ss_ext_sales_price) s from store_sales, date_dim
where ss_sold_date_sk = d_date_sk and d_year = 2000
group by d_moy order by d_moy
;
-- end query 3 in stream 0 using template query42.tpl

-- start query 4 in stream 0 using template query55.tpl
select d_year, count(*) c from date_dim where d_moy = 11
group by d_year order by d_year limit 5
;
-- end query 4 in stream 0 using template query55.tpl
"""


@pytest.mark.slow
def test_traced_power_run_end_to_end(data_dir, tmp_path, monkeypatch, capsys):
    """Acceptance: a traced power run over >= 3 queries produces a parseable
    event log whose root operator spans fit inside the recorded query wall
    time, with catalog-load and cache-hit events, and the profiler renders a
    per-operator breakdown from it."""
    from nds_tpu.power import gen_sql_from_stream, run_query_stream

    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("NDS_TRACE_DIR", str(trace_dir))
    stream = tmp_path / "query_0.sql"
    stream.write_text(STREAM)
    run_query_stream(
        input_prefix=data_dir,
        property_file=None,
        query_dict=gen_sql_from_stream(str(stream)),
        time_log_output_path=str(tmp_path / "time.csv"),
        input_format="csv",
        json_summary_folder=str(tmp_path / "json"),
    )
    files = R.discover_event_files(str(trace_dir))
    assert len(files) == 1
    evs = R.read_events(files, strict=True)  # parseable, line by line
    assert R.validate_events(evs) == []
    kinds = {e["kind"] for e in evs}
    assert {"op_span", "query_span", "catalog_load"} <= kinds
    assert any(
        e["kind"] == "catalog_load" and e["cache"] == "hit" for e in evs
    ), "repeated table loads must produce a cache-hit event"
    prof = R.profile_events(evs)
    assert set(prof["queries"]) == {"query96", "query3", "query42", "query55"}
    for q, rec in prof["queries"].items():
        assert rec["status"] == "Completed"
        assert rec["ops"], f"{q}: no operator spans"
        # inclusive root operator time fits inside the recorded wall time
        assert rec["root_incl_ms"] <= rec["wall_ms"] + 1.0, q
        assert rec.get("mem_hw_bytes", 0) > 0
    # every per-query summary carries the memory high-water too
    jdir = tmp_path / "json"
    for f in os.listdir(jdir):
        s = json.load(open(jdir / f))
        assert s["memoryHighWater"]["bytes"] > 0
        assert s["env"]["engineConf"] == s["env"]["sparkConf"]
    # the profiler CLI renders a per-operator breakdown from the real log
    # (q42's Aggregate fuses into a Pipeline since the agg-tail fusion, so
    # the MultiJoin is the stable named operator to look for)
    profile_cli.main([str(trace_dir), "--per_query", "--check"])
    out = capsys.readouterr().out
    assert "query42" in out and "MultiJoin" in out and "Pipeline" in out
    assert "tallies" in out
    # the budgeter's statement verdicts surface in the profile summary
    assert "plan budget" in out and "direct" in out
