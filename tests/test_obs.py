"""Observability subsystem: the structured event log (golden schema +
nesting invariants), memory high-water sampling, child-stream fold-in /
subprocess failure classification, and the operator-level profiler CLI.

The event schema is a CONTRACT (nds_tpu/obs/trace.py:EVENT_SCHEMA): the
profiler, the throughput parent's fold-in, and full_bench's phase-failure
classification all parse these files, so every kind's required fields are
asserted here against events produced by the real emission sites."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu import full_bench as FB
from nds_tpu import throughput as TP
from nds_tpu.cli import profile as profile_cli
from nds_tpu.engine.session import Session
from nds_tpu.obs import metrics as M
from nds_tpu.obs import reader as R
from nds_tpu.obs.memwatch import MemorySampler
from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer, bind, tracer_from_conf
from nds_tpu.report import BenchReport

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("NDS_TRACE_DIR", raising=False)
    monkeypatch.delenv("NDS_FAULT_SPEC", raising=False)
    monkeypatch.delenv("NDS_METRICS_PORT", raising=False)
    monkeypatch.delenv("NDS_TRACE_ROTATE_BYTES", raising=False)
    monkeypatch.delenv("NDS_TRACE_CONTEXT", raising=False)
    faults.reset()
    yield
    faults.reset()
    # the metrics sink/server and the flight ring are process-wide
    # singletons by design; tests must not leak one test's counters (or a
    # bound port, or ring events) into the next
    M.reset_shared()
    from nds_tpu.obs import flight as FL

    FL.reset_shared()


def _scrape(port, path):
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.read().decode("utf-8")


def _events(path_or_dir):
    return R.read_events(path_or_dir, strict=True)


def _traced_session(tmp_path, **conf):
    conf = {"engine.trace_dir": str(tmp_path / "trace"), **conf}
    s = Session(conf=conf)
    s.register_arrow(
        "t",
        pa.table({"a": [1, 2, 3, 4, 2, 1], "b": [10, 20, 30, 40, 50, 60]}),
    )
    return s


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_tracer_defaults_to_ring_only(monkeypatch):
    """With nothing configured the session still gets a RING-ONLY tracer
    (the always-on flight recorder): no file, no in-memory list, events
    land in the process-wide bounded ring. NDS_FLIGHT_RECORDER=off
    restores the historical fully-disabled None."""
    from nds_tpu.obs import flight as FL

    FL.reset_shared()
    s = Session()
    assert s.tracer is not None
    assert s.tracer.path is None and s.tracer.events is None
    assert s.tracer.ring is FL.recorder()
    assert s.tracer.context.trace_id
    before = len(FL.recorder().snapshot())
    s.tracer.emit("plan_cache", node="Aggregate", hit=True)
    ring = FL.recorder().snapshot()
    assert len(ring) == before + 1
    assert ring[-1]["trace_id"] == s.tracer.context.trace_id
    monkeypatch.setenv("NDS_FLIGHT_RECORDER", "off")
    assert tracer_from_conf({}) is None
    assert Session().tracer is None
    FL.reset_shared()


def test_tracer_writes_meta_and_appends(tmp_path):
    tr = tracer_from_conf({"engine.trace_dir": str(tmp_path)})
    tr.emit("io_retry", path="/x", error="e", delay_s=0.0)
    tr.close()
    evs = _events(tr.path)
    assert [e["kind"] for e in evs] == ["trace_meta", "io_retry"]
    assert evs[0]["pid"] == os.getpid()
    assert all("ts" in e and e["app"] == tr.app_id for e in evs)


def test_tracer_auto_scopes_query(tmp_path):
    tr = tracer_from_conf({"engine.trace_dir": str(tmp_path)})
    with faults.scope("query42"):
        tr.emit("plan_cache", node="Aggregate", hit=True)
    tr.emit("plan_cache", node="Aggregate", hit=False)
    evs = _events(tr.path)
    assert evs[1]["query"] == "query42"
    assert "query" not in evs[2]


def test_memory_tracer_collects_in_process():
    tr = Tracer()  # no dir: in-memory (tools/trace_query.py mode)
    tr.emit("plan_cache", node="Distinct", hit=False)
    assert tr.path is None
    assert [e["kind"] for e in tr.events] == ["plan_cache"]


def test_tracer_thread_binding():
    tr = Tracer()
    seen = {}

    def worker():
        from nds_tpu.obs.trace import current

        seen["inner"] = current()

    with bind(tr):
        from nds_tpu.obs.trace import current

        assert current() is tr
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    from nds_tpu.obs.trace import current

    assert current() is None
    assert seen["inner"] is None  # thread-locals do not inherit


# ---------------------------------------------------------------------------
# golden schema + engine emission sites
# ---------------------------------------------------------------------------


def test_engine_events_golden_schema(tmp_path):
    s = _traced_session(tmp_path)
    with bind(s.tracer):
        with faults.scope("q_agg"):
            s.sql("select a, sum(b) sb from t group by a order by a").collect()
        with faults.scope("q_agg2"):  # plan-cache hit on the aggregate
            s.sql("select a, sum(b) sb from t group by a order by a").collect()
        with faults.scope("q_scan"):  # catalog cache hit (columns resident)
            s.sql("select a, b from t").collect()
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    kinds = {e["kind"] for e in evs}
    assert {"trace_meta", "op_span", "catalog_load", "plan_cache"} <= kinds
    # plan cache: one miss (first aggregate) then one hit (second)
    pc = [e for e in evs if e["kind"] == "plan_cache"]
    assert [e["hit"] for e in pc] == [False, True]
    # catalog: first load is a miss, the later full-resident load is a hit
    cl = [e for e in evs if e["kind"] == "catalog_load"]
    assert cl[0]["cache"] == "miss" and cl[-1]["cache"] == "hit"
    assert all(e["table"] == "t" for e in cl)
    # op spans carry rows + bytes and are query-scoped
    ops = [e for e in evs if e["kind"] == "op_span"]
    assert all(e["query"].startswith("q_") for e in ops)
    assert any(e["rows"] is not None and e["rows"] > 0 for e in ops)
    assert all(e["est_bytes"] >= 0 for e in ops)


def test_op_span_nesting_invariants(tmp_path):
    s = _traced_session(tmp_path)
    with faults.scope("q"):
        s.sql(
            "select a, sum(b) sb from t where b > 10 group by a order by a"
        ).collect()
    ops = [e for e in _events(s.tracer.path) if e["kind"] == "op_span"]
    by_exec = {}
    for e in ops:
        by_exec.setdefault(e["exec_id"], []).append(e)
    for spans in by_exec.values():
        spans.sort(key=lambda e: e["seq"])
        # seq is 1..n with no gaps; completion (post-) order means a parent
        # at depth d completes after its depth-d+1 children
        assert [e["seq"] for e in spans] == list(range(1, len(spans) + 1))
        assert spans[-1]["depth"] == 0  # the root completes last
        acc = {}
        for e in spans:
            d = e["depth"]
            child_ms = acc.pop(d + 1, 0.0)
            # inclusive timing: a parent's span covers its children
            assert e["dur_ms"] >= child_ms - 1e-6
            acc[d] = acc.get(d, 0.0) + e["dur_ms"]
        # nothing left dangling deeper than the root
        assert set(acc) == {0}
    withx = R.op_spans_with_exclusive(ops)
    assert all(e["excl_ms"] >= 0 for e in withx)
    # exclusive sums to the root inclusive time per executor
    for eid, spans in by_exec.items():
        root = max(e["dur_ms"] for e in spans if e["depth"] == 0)
        tot_excl = sum(
            e["excl_ms"] for e in withx if e["exec_id"] == eid
        )
        roots = sum(
            e["dur_ms"] for e in spans if e["depth"] == 0
        )
        assert abs(tot_excl - roots) < 1e-3


def test_blocked_union_event(tmp_path):
    s = _traced_session(tmp_path)
    rng = np.random.default_rng(7)
    for t in ("u1", "u2"):
        s.register_arrow(
            t,
            pa.table({
                "k": pa.array(rng.integers(1, 5, 3000), pa.int32()),
                "v": pa.array(rng.integers(-50, 50, 3000), pa.int32()),
            }),
        )
    s.conf["engine.union_agg_window_rows"] = 512
    with faults.scope("q_union"):
        s.sql(
            "select k, sum(v) sv from (select k, v from u1 union all "
            "select k, v from u2) u group by k order by k"
        ).collect()
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    bu = [e for e in evs if e["kind"] == "blocked_union"]
    assert bu and bu[0]["windows"] > 1 and bu[0]["window_rows"] == 512
    assert bu[0]["total_rows"] == 6000
    assert bu[0]["query"] == "q_union"


def test_report_events_ladder_fault_and_query_span(tmp_path):
    s = _traced_session(tmp_path)
    faults.install("oom:q_flaky:1")
    with bind(s.tracer):
        def fn():
            faults.maybe_fire("q_flaky")

        summary = BenchReport(s).report_on(fn, retry_oom=True, name="q_flaky")
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    # engineConf/engineVersion aliases mirror the spark-named compat keys
    assert summary["env"]["engineConf"] == summary["env"]["sparkConf"]
    assert summary["env"]["engineVersion"] == summary["env"]["sparkVersion"]
    assert summary["memoryHighWater"]["bytes"] > 0
    assert summary["memoryHighWater"]["source"] in ("device", "rss")
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    fi = [e for e in evs if e["kind"] == "fault_injected"]
    assert fi and fi[0]["site"] == "q_flaky" and fi[0]["fault_kind"] == "oom"
    lr = [e for e in evs if e["kind"] == "ladder_rung"]
    assert [e["rung"] for e in lr] == ["recover_retry"]
    assert lr[0]["failure_kind"] == faults.DEVICE_OOM
    qs = [e for e in evs if e["kind"] == "query_span"]
    assert qs[-1]["query"] == "q_flaky"
    assert qs[-1]["status"] == "CompletedWithTaskFailures"
    assert qs[-1]["retries"] == 1
    assert qs[-1]["mem_hw_bytes"] == summary["memoryHighWater"]["bytes"]


def test_watchdog_fire_event(tmp_path):
    s = _traced_session(tmp_path, **{"engine.query_timeout": 0.3})

    def hang():
        time.sleep(5)

    summary = BenchReport(s).report_on(hang, name="q_hang")
    assert summary["failureKind"] == faults.TIMEOUT
    evs = _events(s.tracer.path)
    wf = [e for e in evs if e["kind"] == "watchdog_fire"]
    assert wf and wf[0]["query"] == "q_hang" and wf[0]["budget_s"] == 0.3
    qs = [e for e in evs if e["kind"] == "query_span"]
    assert qs[-1]["status"] == "Failed"
    assert qs[-1]["failure_kind"] == faults.TIMEOUT


def test_io_retry_event(tmp_path, monkeypatch):
    import fsspec

    from nds_tpu.io.fs import fs_open

    monkeypatch.setenv("NDS_IO_BACKOFF", "0")
    monkeypatch.setenv("NDS_IO_RETRIES", "2")
    fs = fsspec.filesystem("memory")
    with fs.open("/obs_retry/data.txt", "w") as f:
        f.write("payload")
    faults.install("io:obs_retry:1")
    tr = Tracer()
    with bind(tr):
        with fs_open("memory://obs_retry/data.txt") as f:
            assert f.read() == "payload"
    io_evs = [e for e in tr.events if e["kind"] == "io_retry"]
    assert len(io_evs) == 1
    assert "obs_retry" in io_evs[0]["path"]
    assert "transient io" in io_evs[0]["error"]


def test_memwatch_sampler_reads_a_peak():
    with MemorySampler(interval_s=0.005) as ms:
        _ = [0] * 100000
        time.sleep(0.03)
    assert ms.peak_bytes is not None and ms.peak_bytes > 0
    assert ms.source in ("device", "rss")


# ---------------------------------------------------------------------------
# reader: parsing contracts + fold-in + failure classification
# ---------------------------------------------------------------------------


def _write_jsonl(path, events, torn_tail=None):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        if torn_tail is not None:
            f.write(torn_tail)  # no newline: a crash mid-write


def _ev(kind, **kw):
    base = {"ts": 1, "kind": kind, "app": "app-x"}
    base.update(kw)
    return base


def test_iter_events_tolerates_torn_final_line_only(tmp_path):
    p = tmp_path / "events-a.jsonl"
    _write_jsonl(
        p, [_ev("trace_meta", pid=1, version="0")], torn_tail='{"ts": 2, "ki'
    )
    assert len(list(R.iter_events(p, strict=True))) == 1
    # a malformed MIDDLE line is corruption, not a crash artifact
    with open(p, "a") as f:
        f.write("\n{broken}\n" + json.dumps(_ev("plan_cache", node="x", hit=True)) + "\n")
    with pytest.raises(R.MalformedEventError):
        list(R.iter_events(p, strict=True))
    assert len(list(R.iter_events(p, strict=False))) >= 1


def test_validate_events_flags_missing_fields():
    ok = _ev("query_span", query="q1", dur_ms=1.0, status="Completed",
             retries=0)
    bad = _ev("query_span", query="q1")
    unknown = _ev("not_a_kind")
    probs = R.validate_events([ok, bad, unknown])
    assert len(probs) == 2
    assert "missing fields" in probs[0] and "unknown kind" in probs[1]
    assert set(EVENT_SCHEMA) >= {"op_span", "query_span", "child_stream"}


def test_failure_kind_from_events_prefers_failed_query_span():
    evs = [
        _ev("query_span", query="q1", dur_ms=1, status="Completed", retries=0),
        _ev("fault_injected", site="q2", fault_kind="io"),
        _ev("query_span", query="q2", dur_ms=1, status="Failed", retries=0,
            failure_kind=faults.DEVICE_OOM),
    ]
    assert R.failure_kind_from_events(evs) == faults.DEVICE_OOM
    # no failed span: the last injected fault's mapped kind
    assert (
        R.failure_kind_from_events(evs[:2]) == faults.IO_TRANSIENT
    )
    assert R.failure_kind_from_events([]) is None
    # a recorded query failure BEATS a later (recovered) injected fault
    evs2 = [
        _ev("query_span", query="q3", dur_ms=1, status="Failed", retries=0,
            failure_kind=faults.PLANNER),
        _ev("fault_injected", site="q4", fault_kind="io"),
        _ev("query_span", query="q4", dur_ms=1, status="Completed",
            retries=1),
    ]
    assert R.failure_kind_from_events(evs2) == faults.PLANNER


def test_profile_multi_stream_sums_per_query(tmp_path):
    """Profiling several streams' files together (a throughput trace dir)
    must SUM per query name — not mix one stream's wall with all streams'
    operator times — and a single failed run marks the query Failed."""
    d = tmp_path / "tt"
    d.mkdir()
    for app, status, mem in (("s1", "Completed", 500), ("s2", "Failed", 900)):
        _write_jsonl(d / f"events-{app}.jsonl", [
            _ev("op_span", app=app, query="query1", exec_id=1, seq=1,
                depth=0, node="Aggregate", explain="Aggregate",
                dur_ms=100.0, rows=5, est_bytes=40),
            _ev("query_span", app=app, query="query1", dur_ms=120.0,
                status=status, retries=0, mem_hw_bytes=mem,
                mem_source="rss",
                **({"failure_kind": faults.DEVICE_OOM}
                   if status == "Failed" else {})),
        ])
    prof = R.profile_events(R.read_events(str(d)))
    q1 = prof["queries"]["query1"]
    assert q1["runs"] == 2
    assert q1["wall_ms"] == 240.0  # summed across streams
    assert q1["root_incl_ms"] == 200.0  # plan time stays <= wall time
    assert q1["root_incl_ms"] <= q1["wall_ms"]
    assert q1["status"] == "Failed"  # any failed run surfaces
    assert q1["failure_kind"] == faults.DEVICE_OOM
    assert q1["mem_hw_bytes"] == 900  # max, not last-wins


def test_fold_child_streams_emits_summary_and_classifies(tmp_path):
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    pid = 54321
    child = trace_dir / f"events-nds-tpu-{pid}-1-abc.jsonl"
    now_ms = int(time.time() * 1000)
    _write_jsonl(child, [
        _ev("trace_meta", pid=pid, version="0", ts=now_ms,
            trace_id="tp-child-3"),
        _ev("query_span", query="query1", dur_ms=5, status="Completed",
            retries=0),
        _ev("query_span", query="query5", dur_ms=9, status="Failed",
            retries=2, failure_kind=faults.DEVICE_OOM),
    ], torn_tail='{"torn')
    # a leftover file from a RECYCLED pid (same pid, a different minted
    # trace_id, stamped long before this launch): must NOT fold in
    stale = trace_dir / f"events-nds-tpu-{pid}-0-old.jsonl"
    _write_jsonl(stale, [
        _ev("trace_meta", pid=pid, version="0", ts=now_ms - 86_400_000,
            trace_id="tp-dead-run"),
        _ev("query_span", query="query9", dur_ms=1, status="Failed",
            retries=0, failure_kind=faults.TIMEOUT),
    ])

    parent = Tracer()
    kinds = TP._fold_child_streams(
        parent, str(trace_dir), pre_existing=set(),
        launches={3: {"pid": pid, "ts_ms": now_ms - 100,
                      "trace_id": "tp-child-3"}},
    )
    assert kinds == {3: faults.DEVICE_OOM}
    cs = [e for e in parent.events if e["kind"] == "child_stream"]
    assert len(cs) == 1
    assert cs[0]["stream"] == 3
    assert cs[0]["queries"] == 2 and cs[0]["completed"] == 1
    assert cs[0]["failed"] == {"query5": faults.DEVICE_OOM}
    assert cs[0]["child_trace_id"] == "tp-child-3"
    assert R.validate_events(cs) == []


def test_fold_child_streams_pid_fallback_rejects_stale(tmp_path):
    """Pre-context children (no trace_id in the meta line) fold by pid
    PLUS launch-time verification: a recycled pid's leftover file from a
    long-dead process predates the launch record and is rejected."""
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    pid = 777
    now_ms = int(time.time() * 1000)
    fresh = trace_dir / f"events-nds-tpu-{pid}-2-new.jsonl"
    _write_jsonl(fresh, [
        _ev("trace_meta", pid=pid, version="0", ts=now_ms),
        _ev("query_span", query="q", dur_ms=1, status="Failed",
            retries=0, failure_kind=faults.IO_TRANSIENT),
    ])
    stale = trace_dir / f"events-nds-tpu-{pid}-1-old.jsonl"
    _write_jsonl(stale, [
        _ev("trace_meta", pid=pid, version="0", ts=now_ms - 86_400_000),
        _ev("query_span", query="q", dur_ms=1, status="Failed",
            retries=0, failure_kind=faults.TIMEOUT),
    ])
    # a child killed BEFORE its eager meta line landed leaves an empty
    # file: unverifiable, but still this pid's crash evidence — the
    # pid-filename fallback keeps it (only a READABLE mismatching meta
    # rejects)
    empty = trace_dir / f"events-nds-tpu-{pid}-3-empty.jsonl"
    empty.write_text("")
    parent = Tracer()
    kinds = TP._fold_child_streams(
        parent, str(trace_dir), pre_existing=set(),
        launches={1: {"pid": pid, "ts_ms": now_ms - 50}},
    )
    # only the fresh file's events attributed; the stale one never
    # mis-blames (its TIMEOUT kind must not win)
    assert kinds == {1: faults.IO_TRANSIENT}
    cs = [e for e in parent.events if e["kind"] == "child_stream"]
    assert len(cs) == 1
    assert sorted(cs[0]["files"]) == sorted(
        [os.path.basename(str(fresh)), os.path.basename(str(empty))]
    )


def test_phase_failure_classified_from_child_events(tmp_path, monkeypatch):
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    monkeypatch.setenv("NDS_TRACE_DIR", str(trace_dir))
    monkeypatch.setenv("NDS_PHASE_RETRIES", "1")
    monkeypatch.setenv("NDS_PHASE_BACKOFF", "0")
    state = FB.BenchState(str(tmp_path / "state.json"), "fp")
    calls = {"n": 0}

    def phase_fn():
        calls["n"] += 1
        # simulate a child process that wrote events then died opaquely;
        # the child ADOPTS the phase's exported context (trace_meta
        # trace_id), which is what the classifier now verifies against
        _write_jsonl(
            trace_dir / f"events-nds-tpu-99-{calls['n']}-x.jsonl",
            [_ev("trace_meta", pid=99, version="0",
                 ts=int(time.time() * 1000),
                 trace_id=os.environ["NDS_TRACE_CONTEXT"].split(",")[0]),
             _ev("query_span", query="q", dur_ms=1, status="Failed",
                 retries=0, failure_kind=faults.IO_TRANSIENT)],
        )
        if calls["n"] == 1:
            raise subprocess.CalledProcessError(1, ["child"])  # opaque

    tracer = Tracer()
    FB._run_phase(state, "power_test", None, phase_fn, tracer=tracer)
    # opaque exit reclassified io_transient from the child's events -> retried
    assert calls["n"] == 2
    assert state.is_done("power_test")
    ph = [e for e in tracer.events if e["kind"] == "phase"]
    assert [e["event"] for e in ph] == ["begin", "end"]
    assert ph[-1]["status"] == "ok" and ph[-1]["attempts"] == 2
    assert R.validate_events(ph) == []


def test_phase_deterministic_failure_still_fails_fast(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_PHASE_RETRIES", "3")
    monkeypatch.setenv("NDS_PHASE_BACKOFF", "0")
    state = FB.BenchState(str(tmp_path / "state.json"), "fp")
    calls = {"n": 0}

    def phase_fn():
        calls["n"] += 1
        raise ValueError("ExecError: deterministic")

    tracer = Tracer()
    with pytest.raises(FB.PhaseError):
        FB._run_phase(state, "load_test", None, phase_fn, tracer=tracer)
    assert calls["n"] == 1
    ph = [e for e in tracer.events if e["kind"] == "phase"]
    assert ph[-1]["status"] == "failed"
    assert ph[-1]["failure_kind"] == faults.PLANNER


# ---------------------------------------------------------------------------
# profiler: aggregation + A/B compare + CLI
# ---------------------------------------------------------------------------


def _synthetic_run(tmp_path, name, scale=1.0, fail_q2=False):
    d = tmp_path / name
    d.mkdir()
    spans = [
        _ev("trace_meta", pid=1, version="0"),
        _ev("op_span", query="query1", exec_id=1, seq=1, depth=1,
            node="Scan", explain="Scan t", dur_ms=40 * scale, rows=100,
            est_bytes=800),
        _ev("op_span", query="query1", exec_id=1, seq=2, depth=1,
            node="MultiJoin", explain="MultiJoin", dur_ms=100 * scale,
            rows=50, est_bytes=400),
        _ev("op_span", query="query1", exec_id=1, seq=3, depth=0,
            node="Aggregate", explain="Aggregate", dur_ms=200 * scale,
            rows=5, est_bytes=40),
        _ev("query_span", query="query1", dur_ms=250 * scale,
            status="Completed", retries=0, mem_hw_bytes=1000,
            mem_source="rss"),
        _ev("catalog_load", table="t", columns=2, loaded=2, rows=100,
            dur_ms=3.0, cache="miss"),
        _ev("catalog_load", table="t", columns=2, loaded=0, rows=100,
            dur_ms=0.1, cache="hit"),
        _ev("plan_cache", node="Aggregate", hit=False),
    ]
    if fail_q2:
        spans.append(
            _ev("query_span", query="query2", dur_ms=10, status="Failed",
                retries=1, failure_kind=faults.DEVICE_OOM)
        )
    else:
        spans.append(
            _ev("query_span", query="query2", dur_ms=80, status="Completed",
                retries=0)
        )
    _write_jsonl(d / "events-run.jsonl", spans)
    return d


def test_profile_aggregation_and_exclusive_time(tmp_path):
    d = _synthetic_run(tmp_path, "run")
    prof = R.profile_events(R.read_events(str(d)))
    q1 = prof["queries"]["query1"]
    assert q1["wall_ms"] == 250.0
    assert q1["root_incl_ms"] == 200.0  # root span <= recorded wall
    assert q1["root_incl_ms"] <= q1["wall_ms"]
    # Aggregate exclusive = 200 - (40 + 100) children
    assert q1["ops"]["Aggregate"]["excl_ms"] == pytest.approx(60.0)
    assert q1["ops"]["Scan"]["rows"] == 100
    assert q1["mem_hw_bytes"] == 1000
    assert prof["op_totals"]["MultiJoin"]["excl_ms"] == pytest.approx(100.0)
    t = prof["tallies"]
    assert t["catalog_loads"] == 2 and t["catalog_cache_hits"] == 1
    assert t["plan_cache_misses"] == 1


def test_profile_compare_flags_regressions(tmp_path):
    old = _synthetic_run(tmp_path, "old", scale=1.0)
    new = _synthetic_run(tmp_path, "new", scale=3.0, fail_q2=True)
    regs = R.compare_profiles(
        R.profile_events(R.read_events(str(old))),
        R.profile_events(R.read_events(str(new))),
        ratio=1.25, min_ms=50.0,
    )
    changes = {(r["level"], r.get("node"), r["query"]): r for r in regs}
    assert ("query", None, "query1") in changes
    assert changes[("query", None, "query1")]["ratio"] == pytest.approx(3.0)
    assert ("operator", "Aggregate", "query1") in changes
    q2 = [r for r in regs if r["query"] == "query2"]
    assert q2 and q2[0]["change"] == "status_change"
    # identical runs: clean
    assert R.compare_profiles(
        R.profile_events(R.read_events(str(old))),
        R.profile_events(R.read_events(str(old))),
    ) == []


def test_profile_cli_renders_and_compares(tmp_path, capsys):
    old = _synthetic_run(tmp_path, "old", scale=1.0)
    new = _synthetic_run(tmp_path, "new", scale=3.0)
    profile_cli.main([str(old), "--per_query"])
    out = capsys.readouterr().out
    assert "query1" in out and "Aggregate" in out and "top" in out
    assert "catalog 2 loads (1 cache-hit)" in out
    profile_cli.main(["--compare", str(old), str(new)])
    out = capsys.readouterr().out
    assert "regression" in out and "query1" in out
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([
            "--compare", str(old), str(new), "--fail_on_regression",
        ])
    assert exc.value.code == 1


def test_profile_cli_fails_on_malformed_log(tmp_path, capsys):
    d = tmp_path / "bad"
    d.mkdir()
    (d / "events-x.jsonl").write_text('{"ts": 1}\n{broken}\n{"ts": 2}\n')
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(d)])
    assert exc.value.code == 2


def test_profile_cli_check_flags_schema_problems(tmp_path):
    d = tmp_path / "odd"
    d.mkdir()
    _write_jsonl(d / "events-x.jsonl", [_ev("not_a_kind")])
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(d), "--check"])
    assert exc.value.code == 2
    profile_cli.main([str(d)])  # without --check: warn only


# ---------------------------------------------------------------------------
# live telemetry: registry, sink, HTTP endpoint
# ---------------------------------------------------------------------------


def test_metrics_registry_counters_gauges_histograms():
    reg = M.MetricsRegistry()
    reg.inc("nds_exec_cache_total", result="hit")
    reg.inc("nds_exec_cache_total", result="hit")
    reg.inc("nds_exec_cache_total", result="miss")
    reg.set_gauge("nds_heartbeat_rss_bytes", 100)
    reg.set_gauge("nds_heartbeat_rss_bytes", 50)  # gauges move both ways
    reg.max_gauge("nds_query_span_mem_hw_bytes", 10)
    reg.max_gauge("nds_query_span_mem_hw_bytes", 5)  # high-water ratchets
    reg.observe("nds_query_span_dur_ms", 3.0)
    reg.observe("nds_query_span_dur_ms", 999999.0)  # lands in +Inf, bounded
    assert reg.counter_value("nds_exec_cache_total", result="hit") == 2
    text = reg.render()
    assert M.validate_exposition(text) == []
    assert 'nds_exec_cache_total{result="hit"} 2' in text
    assert "nds_heartbeat_rss_bytes 50" in text
    assert "nds_query_span_mem_hw_bytes 10" in text
    assert 'nds_query_span_dur_ms_bucket{le="+Inf"} 2' in text
    assert "nds_query_span_dur_ms_count 2" in text
    # free-floating metric names are refused at runtime (lint's belt)
    with pytest.raises(ValueError):
        reg.inc("nds_made_up_total")
    # every registered family name embeds its source event kind
    for name, kind in M.METRIC_KINDS.items():
        assert kind in EVENT_SCHEMA and kind in name


def test_validate_exposition_flags_malformed():
    assert M.validate_exposition("# TYPE a counter\na 1\n") == []
    probs = M.validate_exposition(
        "# TYPE a counter\na{x=unquoted} 1\nb 2\nnot a line\n"
    )
    assert len(probs) == 3  # bad labels, undeclared family, junk line


def test_metrics_sink_records_events_and_status():
    sink = M.MetricsSink()
    sink.query_started("q1", app="app-x")  # _ev events carry app="app-x"
    st = sink.status_snapshot()
    assert st["query"]["query"] == "q1" and st["query"]["attempt"] == 1
    assert st["query"]["elapsed_ms"] >= 0
    sink.record(_ev("ladder_rung", query="q1", rung="recover_retry",
                    failure_kind=faults.DEVICE_OOM))
    sink.record(_ev("heartbeat", query="q1", elapsed_ms=40.0,
                    rss_bytes=2048))
    st = sink.status_snapshot()
    assert st["query"]["attempt"] == 2
    assert st["query"]["ladder"] == ["recover_retry"]
    assert st["rss_bytes"] == 2048
    assert st["heartbeat_age_ms"] is not None
    sink.record(_ev("query_span", query="q1", dur_ms=55.0,
                    status="Completed", retries=1, mem_hw_bytes=9000,
                    mem_source="rss"))
    sink.record(_ev("query_span", query="q2", dur_ms=5.0, status="Failed",
                    retries=0, failure_kind=faults.TIMEOUT))
    sink.record(_ev("exec_cache", pipeline="p", bucket=1024, hit=True))
    sink.record(_ev("exec_cache", pipeline="p", bucket=1024, hit=False))
    sink.record(_ev("phase", phase="power_test", event="begin", index=4,
                    total=8))
    st = sink.status_snapshot()
    assert st["query"] is None  # q1's span retired the in-flight record
    assert st["queries_completed"] == 1 and st["queries_failed"] == 1
    assert st["mem_hw_bytes"] == 9000 and st["mem_source"] == "rss"
    assert st["caches"]["exec_cache"] == {"hits": 1, "total": 2, "rate": 0.5}
    assert st["phase"]["name"] == "power_test" and st["phase"]["index"] == 4
    sink.record(_ev("phase", phase="power_test", event="end", status="ok"))
    st = sink.status_snapshot()
    assert st["phase"] is None
    assert st["last_phase"] == {"name": "power_test", "status": "ok"}
    reg = sink.registry
    assert reg.counter_value("nds_query_span_total", status="Completed") == 1
    assert reg.counter_value("nds_query_span_total", status="Failed") == 1
    assert M.validate_exposition(reg.render()) == []


def test_metrics_sink_in_flight_keyed_per_stream():
    """Thread-mode throughput: two streams running the SAME query name
    concurrently must keep independent in-flight records — one stream's
    finish must not retire (or its rungs mutate) the other's."""
    sink = M.MetricsSink()
    sink.query_started("query5", app="stream-a")
    sink.query_started("query5", app="stream-b")
    sink.record(_ev("ladder_rung", app="stream-b", query="query5",
                    rung="recover_retry", failure_kind=faults.DEVICE_OOM))
    sink.record(_ev("query_span", app="stream-a", query="query5",
                    dur_ms=10.0, status="Completed", retries=0))
    st = sink.status_snapshot()
    assert len(st["in_flight"]) == 1  # only stream-b's run still lives
    assert st["in_flight"][0]["app"] == "stream-b"
    assert st["in_flight"][0]["attempt"] == 2  # b's rung stayed with b
    sink.record(_ev("query_span", app="stream-b", query="query5",
                    dur_ms=20.0, status="Completed", retries=1))
    assert sink.status_snapshot()["in_flight"] == []


def test_metrics_sink_never_raises_on_garbage():
    sink = M.MetricsSink()
    sink.record({"kind": "query_span"})  # all fields missing
    sink.record({"kind": "no_such_kind"})
    sink.record({})
    assert sink.status_snapshot()["queries_completed"] == 1  # status=None != Failed


def test_metrics_server_endpoints():
    from nds_tpu.obs.httpserv import MetricsServer

    sink = M.MetricsSink()
    sink.record(_ev("plan_cache", node="Aggregate", hit=True))
    server = MetricsServer(sink, port=0, host="127.0.0.1").start()
    try:
        body = _scrape(server.port, "/metrics")
        assert M.validate_exposition(body) == []
        assert 'nds_plan_cache_total{result="hit"} 1' in body
        st = json.loads(_scrape(server.port, "/statusz"))
        assert st["caches"]["plan_cache"]["hits"] == 1
        assert _scrape(server.port, "/healthz").strip() == "ok"
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            _scrape(server.port, "/nope")
    finally:
        server.stop()


def test_session_metrics_without_trace_dir(monkeypatch, tmp_path):
    """The live-telemetry-only mode: NDS_METRICS_PORT set, no trace dir —
    the session gets a sink-only tracer (no file, no in-memory growth) and
    the shared endpoint serves live counters for its queries."""
    monkeypatch.setenv("NDS_METRICS_PORT", "0")
    s = Session()
    assert s.metrics is not None
    assert s.tracer is not None
    assert s.tracer.path is None and s.tracer.events is None
    s.register_arrow("t", pa.table({"a": [1, 2, 3], "b": [10, 20, 30]}))
    with bind(s.tracer):
        summary = BenchReport(s).report_on(
            lambda: s.sql("select a, sum(b) sb from t group by a").collect(),
            name="q_live",
        )
    assert summary["queryStatus"] == ["Completed"]
    server = M.active_server()
    assert server is not None
    body = _scrape(server.port, "/metrics")
    assert M.validate_exposition(body) == []
    assert 'nds_query_span_total{status="Completed"} 1' in body
    assert "nds_op_span_total" in body
    st = json.loads(_scrape(server.port, "/statusz"))
    assert st["queries_completed"] == 1
    # a second session in the same process reuses the shared sink/server
    s2 = Session()
    assert s2.metrics is s.metrics
    assert M.active_server() is server


def test_metrics_disabled_is_zero_cost(monkeypatch):
    monkeypatch.delenv("NDS_METRICS_PORT", raising=False)
    assert M.resolve_metrics_port({}) is None
    assert M.maybe_serve({}) is None
    # with the flight recorder ALSO off, the historical fully-disabled
    # zero-cost shape holds; by default the tracer is ring-only instead
    monkeypatch.setenv("NDS_FLIGHT_RECORDER", "off")
    assert tracer_from_conf({}) is None
    s = Session()
    assert s.metrics is None and s.tracer is None
    monkeypatch.delenv("NDS_FLIGHT_RECORDER", raising=False)
    s2 = Session()
    assert s2.metrics is None and s2.tracer is not None
    assert s2.tracer.sink is None and s2.tracer.path is None


def test_traced_session_feeds_sink_and_file(monkeypatch, tmp_path):
    """Trace dir AND metrics port: one tracer writes the event file and
    feeds the live registry — the same events, two surfaces."""
    monkeypatch.setenv("NDS_METRICS_PORT", "0")
    s = _traced_session(tmp_path)
    assert s.tracer.sink is s.metrics
    with faults.scope("q_both"):
        s.sql("select a, b from t").collect()
    evs = _events(s.tracer.path)
    n_cat = len([e for e in evs if e["kind"] == "catalog_load"])
    assert n_cat >= 1
    series = s.metrics.registry.counter_series("nds_catalog_load_total")
    assert sum(series.values()) == n_cat


def test_heartbeat_events_from_sampler(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_HEARTBEAT_INTERVAL_MS", "20")
    monkeypatch.setenv("NDS_TRACE_MEM_INTERVAL_MS", "5")
    s = _traced_session(tmp_path)

    def slow():
        time.sleep(0.15)

    BenchReport(s).report_on(slow, name="q_slow")
    evs = _events(s.tracer.path)
    assert R.validate_events(evs) == []
    hbs = [e for e in evs if e["kind"] == "heartbeat"]
    assert len(hbs) >= 2  # one immediate + periodic beats
    assert all(e["query"] == "q_slow" for e in hbs)
    assert hbs[-1]["elapsed_ms"] > hbs[0]["elapsed_ms"]
    # rss present on Linux (the honest liveness signal for a hang)
    assert hbs[-1]["rss_bytes"] is None or hbs[-1]["rss_bytes"] > 0


# ---------------------------------------------------------------------------
# trace-dir rotation + compaction
# ---------------------------------------------------------------------------


def test_tracer_rotates_segments_and_reader_reassembles(tmp_path):
    tr = Tracer(str(tmp_path), app_id="rot", rotate_bytes=400)
    for i in range(40):
        tr.emit("plan_cache", node=f"n{i:03d}", hit=False)
    tr.close()
    files = R.discover_event_files(str(tmp_path))
    assert len(files) > 2, "rotation must have produced segments"
    assert [R.segment_key(f) for f in files] == sorted(
        R.segment_key(f) for f in files
    )
    # segment 0 keeps the classic name; later segments carry the seq
    assert os.path.basename(files[0]) == "events-rot.jsonl"
    assert os.path.basename(files[1]) == "events-rot.0001.jsonl"
    # every segment under the threshold + one line of slack
    for f in files:
        assert os.path.getsize(f) <= 400 + 200
    # each segment opens with its own trace_meta (independently attributable)
    for f in files:
        first = next(R.iter_events(f, strict=True))
        assert first["kind"] == "trace_meta" and first["app"] == "rot"
    evs = R.read_events(str(tmp_path), strict=True)
    assert R.validate_events(evs) == []
    nodes = [e["node"] for e in evs if e["kind"] == "plan_cache"]
    assert nodes == [f"n{i:03d}" for i in range(40)], (
        "chain reassembly must preserve emission order"
    )


def test_reader_tolerates_torn_tail_of_non_final_segment(tmp_path):
    """Satellite: torn-line classification is PER-SEGMENT. A torn final
    line in a non-final rotated segment (crash evidence) must not
    hard-error strict mode; mid-file corruption still must."""
    _write_jsonl(
        tmp_path / "events-app.jsonl",
        [_ev("trace_meta", pid=1, version="0")],
        torn_tail='{"ts": 3, "ki',
    )
    _write_jsonl(
        tmp_path / "events-app.0001.jsonl",
        [_ev("plan_cache", node="x", hit=True)],
    )
    evs = R.read_events(str(tmp_path), strict=True)
    assert [e["kind"] for e in evs] == ["trace_meta", "plan_cache"]
    # mid-file corruption in any segment is still a hard error
    with open(tmp_path / "events-app.jsonl", "a") as f:
        f.write("\n{broken}\n" + json.dumps(_ev("plan_cache", node="y",
                                                hit=False)) + "\n")
    with pytest.raises(R.MalformedEventError):
        R.read_events(str(tmp_path), strict=True)


def test_concurrent_emit_under_rotation(tmp_path):
    """Satellite: N threads x M events through one rotating tracer — no
    torn/interleaved lines, stable per-thread ordering, exact counts
    after chain reassembly."""
    n_threads, n_events = 8, 150
    tr = Tracer(str(tmp_path), app_id="conc", rotate_bytes=2000)

    def worker(t):
        for i in range(n_events):
            tr.emit("plan_cache", node=f"t{t}:{i:04d}", hit=True)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.close()
    files = R.discover_event_files(str(tmp_path))
    assert len(files) > 2
    evs = R.read_events(str(tmp_path), strict=True)  # no torn/mixed lines
    assert R.validate_events(evs) == []
    pc = [e["node"] for e in evs if e["kind"] == "plan_cache"]
    assert len(pc) == n_threads * n_events
    for t in range(n_threads):
        mine = [n for n in pc if n.startswith(f"t{t}:")]
        assert mine == [f"t{t}:{i:04d}" for i in range(n_events)], (
            f"thread {t}'s events must reassemble in emission order"
        )


def test_tracer_emit_after_close_is_noop(tmp_path, capsys):
    """Satellite: a late emit after close() must not silently reopen the
    file (the old handle leak) — it drops the event with ONE warning."""
    tr = Tracer(str(tmp_path), app_id="late")
    tr.emit("plan_cache", node="a", hit=True)
    tr.close()
    before = open(tr.path).read()
    tr.emit("plan_cache", node="late1", hit=True)
    tr.emit("plan_cache", node="late2", hit=True)
    assert tr._fh is None, "post-close emit must not reopen the file"
    assert open(tr.path).read() == before
    out = capsys.readouterr().out
    assert out.count("after close()") == 1  # one-shot, not per event
    tr.close()  # idempotent


def test_compact_trace_dir_folds_closed_segments(tmp_path):
    tr = Tracer(str(tmp_path), app_id="cmp", rotate_bytes=500)
    for i in range(30):
        tr.emit("op_span", exec_id=1, seq=i + 1, depth=0, node="Scan",
                explain="Scan t", dur_ms=2.0, rows=10, est_bytes=80,
                query="q1")
    tr.emit("query_span", query="q1", dur_ms=99.0, status="Completed",
            retries=0)
    tr.close()
    before = R.load_profile(str(tmp_path))
    n_seg = len(R.discover_event_files(str(tmp_path)))
    assert n_seg > 2
    folded, skipped = R.compact_trace_dir(str(tmp_path))
    assert skipped == []
    assert len(folded) == 1 and len(folded[0][1]) == n_seg - 1
    remaining = R.discover_event_files(str(tmp_path))
    assert len(remaining) == 1  # only the open tail keeps raw spans
    assert R.discover_compact_files(str(tmp_path))
    # disk now bounded: raw spans <= one segment (the rotate threshold)
    raw = sum(os.path.getsize(f) for f in remaining)
    assert raw <= 500 + 200
    after = R.load_profile(str(tmp_path))
    assert after["tallies"] == before["tallies"]
    assert after["queries"]["q1"]["wall_ms"] == before["queries"]["q1"]["wall_ms"]
    assert after["queries"]["q1"]["status"] == "Completed"
    ops_b = before["queries"]["q1"]["ops"]["Scan"]
    ops_a = after["queries"]["q1"]["ops"]["Scan"]
    assert ops_a["count"] == ops_b["count"] == 30
    assert ops_a["incl_ms"] == pytest.approx(ops_b["incl_ms"])
    assert ops_a["rows"] == ops_b["rows"]
    # a second round folds the chain's remaining tail segment and MERGES
    # into the existing artifact (one artifact per app, accumulating)
    folded2, _ = R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert folded2 and len(R.discover_compact_files(str(tmp_path))) == 1
    assert R.discover_event_files(str(tmp_path)) == []
    final = R.load_profile(str(tmp_path))
    assert final["queries"]["q1"]["ops"]["Scan"]["count"] == 30
    assert final["tallies"] == before["tallies"]
    # a later tracer (fresh app id, as default_app_id guarantees) adds its
    # own chain; the dir profile sums across both apps' artifacts
    tr2 = Tracer(str(tmp_path), app_id="cmp2", rotate_bytes=500)
    for i in range(30):
        tr2.emit("op_span", exec_id=2, seq=i + 1, depth=0, node="Scan",
                 explain="Scan t", dur_ms=2.0, rows=10, est_bytes=80,
                 query="q1")
    tr2.close()
    R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert R.discover_event_files(str(tmp_path)) == []
    assert final["queries"]["q1"]["ops"]["Scan"]["count"] == 30
    total = R.load_profile(str(tmp_path))
    assert total["queries"]["q1"]["ops"]["Scan"]["count"] == 60


def test_compact_crash_between_write_and_delete_never_double_counts(
    tmp_path,
):
    """The artifact commits before the raw deletes; a crash in between
    leaves folded segments on disk. The next run must recognize them via
    the artifact's `segments` provenance and finish the delete WITHOUT
    re-merging (and the half-compacted dir must not profile double)."""
    tr = Tracer(str(tmp_path), app_id="crash", rotate_bytes=400)
    for i in range(20):
        tr.emit("plan_cache", node=f"n{i}", hit=True)
    tr.close()
    before = R.load_profile(str(tmp_path))
    folded, _ = R.compact_trace_dir(str(tmp_path), fold_open=True)
    deleted = folded[0][1]
    # simulate the crash: resurrect the folded raw segments post-artifact
    for i, f in enumerate(deleted):
        _write_jsonl(f, [_ev("plan_cache", app="crash", node=f"n{i}",
                             hit=True)])
    # even the half-compacted state profiles ONCE (load_profile drops raw
    # segments named in an artifact's provenance before aggregating)
    half = R.load_profile(str(tmp_path))
    assert half["tallies"]["plan_cache_hits"] == \
        before["tallies"]["plan_cache_hits"]
    folded2, skipped2 = R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert skipped2 == []
    assert sorted(folded2[0][1]) == sorted(deleted)  # delete finished
    assert R.discover_event_files(str(tmp_path)) == []
    after = R.load_profile(str(tmp_path))
    assert after["tallies"]["plan_cache_hits"] == \
        before["tallies"]["plan_cache_hits"] == 20


def test_compact_leaves_corrupt_segments_in_place(tmp_path):
    _write_jsonl(tmp_path / "events-bad.jsonl",
                 [_ev("plan_cache", node="a", hit=True)])
    with open(tmp_path / "events-bad.jsonl", "a") as f:
        f.write("{broken}\n")
        f.write(json.dumps(_ev("plan_cache", node="b", hit=True)) + "\n")
    _write_jsonl(tmp_path / "events-bad.0001.jsonl",
                 [_ev("plan_cache", node="c", hit=True)])
    folded, skipped = R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert len(skipped) == 1 and "events-bad.jsonl" in skipped[0][0]
    assert os.path.exists(tmp_path / "events-bad.jsonl"), (
        "compaction must never delete evidence it could not read"
    )
    assert not os.path.exists(tmp_path / "events-bad.0001.jsonl")


def test_compact_refuses_schema_dirty_segments(tmp_path):
    """`profile --check` must keep its teeth over compacted dirs: a
    segment with schema-breaking events is never absorbed into an
    artifact — it stays raw (where --check flags it) and is reported."""
    _write_jsonl(tmp_path / "events-dirty.jsonl",
                 [_ev("op_span", query="q")])  # missing required fields
    _write_jsonl(tmp_path / "events-dirty.0001.jsonl",
                 [_ev("plan_cache", node="x", hit=True)])
    folded, skipped = R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert len(skipped) == 1 and "schema" in skipped[0][1]
    assert os.path.exists(tmp_path / "events-dirty.jsonl")
    assert not os.path.exists(tmp_path / "events-dirty.0001.jsonl")
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(tmp_path), "--check"])
    assert exc.value.code == 2


def test_compact_and_profile_reject_structurally_bad_artifact(tmp_path):
    """An artifact with "profile": null (torn/hand-edited) must fail the
    ValueError path everywhere — never an AttributeError inside merge."""
    (tmp_path / "compact-app.json").write_text(
        json.dumps({"compact": 1, "app": "app", "segments": [],
                    "events": 0, "profile": None})
    )
    with pytest.raises(ValueError):
        R.read_compact(str(tmp_path / "compact-app.json"))
    _write_jsonl(tmp_path / "events-app.jsonl",
                 [_ev("plan_cache", node="a", hit=True)])
    folded, skipped = R.compact_trace_dir(str(tmp_path), fold_open=True)
    assert folded == [] and len(skipped) == 1  # chain skipped, not crashed
    with pytest.raises(SystemExit) as exc:  # CLI: exit 2, not a traceback
        profile_cli.main([str(tmp_path)])
    assert exc.value.code == 2
    # nested damage is caught too (profile.queries value not a mapping)
    (tmp_path / "compact-app.json").write_text(
        json.dumps({"compact": 1, "app": "app", "segments": [],
                    "events": 0, "profile": {"queries": {"q1": "junk"}}})
    )
    with pytest.raises(ValueError):
        R.read_compact(str(tmp_path / "compact-app.json"))


def test_profile_mem_source_tracks_high_water_through_compaction(tmp_path):
    """mem_source must describe the run HOLDING the high-water, and a
    compacted dir must agree with the raw profile on it."""
    tr = Tracer(str(tmp_path), app_id="mem", rotate_bytes=250)
    tr.emit("query_span", query="q1", dur_ms=1.0, status="Completed",
            retries=0, mem_hw_bytes=9000, mem_source="device")
    tr.emit("query_span", query="q1", dur_ms=1.0, status="Completed",
            retries=0, mem_hw_bytes=5000, mem_source="rss")
    tr.close()
    raw = R.load_profile(str(tmp_path))
    assert raw["queries"]["q1"]["mem_hw_bytes"] == 9000
    assert raw["queries"]["q1"]["mem_source"] == "device"
    R.compact_trace_dir(str(tmp_path), fold_open=True)
    compacted = R.load_profile(str(tmp_path))
    assert compacted["queries"]["q1"]["mem_hw_bytes"] == 9000
    assert compacted["queries"]["q1"]["mem_source"] == "device"


def test_compact_skips_chain_with_corrupt_prior_artifact(tmp_path, capsys):
    (tmp_path / "compact-app.json").write_text("{truncated")
    _write_jsonl(tmp_path / "events-app.jsonl",
                 [_ev("plan_cache", node="a", hit=True)])
    _write_jsonl(tmp_path / "events-other.jsonl",
                 [_ev("plan_cache", node="b", hit=True)])
    folded, skipped = R.compact_trace_dir(str(tmp_path), fold_open=True)
    # the bad artifact's chain is skipped (nothing overwritten/deleted)...
    assert len(skipped) == 1 and "compact-app.json" in skipped[0][0]
    assert os.path.exists(tmp_path / "events-app.jsonl")
    # ...while the other app's chain still folds
    assert [app for app, _ in folded] == ["other"]
    assert not os.path.exists(tmp_path / "events-other.jsonl")
    # and the CLI reports + exits nonzero instead of dying with a traceback
    with pytest.raises(SystemExit) as exc:
        profile_cli.main(["compact", str(tmp_path), "--all"])
    assert exc.value.code == 1


def test_profile_cli_compact_subcommand(tmp_path, capsys):
    tr = Tracer(str(tmp_path), app_id="cli", rotate_bytes=300)
    for i in range(25):
        tr.emit("plan_cache", node=f"n{i}", hit=True)
    tr.close()
    profile_cli.main(["compact", str(tmp_path), "--dry_run"])
    out = capsys.readouterr().out
    assert "would fold" in out
    assert len(R.discover_compact_files(str(tmp_path))) == 0
    profile_cli.main(["compact", str(tmp_path)])
    out = capsys.readouterr().out
    assert "folded" in out
    assert len(R.discover_compact_files(str(tmp_path))) == 1
    # the profiler renders a compacted dir transparently
    profile_cli.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert "plan-cache 25 hit" in out


# ---------------------------------------------------------------------------
# end-to-end: a traced power run over real (tiny) data + the profiler CLI
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    mini = tmp_path_factory.mktemp("mini_wh")
    for t in ("store_sales", "date_dim"):
        os.symlink(os.path.join(DATA, t), mini / t)
    return str(mini)


STREAM = """-- start query 1 in stream 0 using template query96.tpl
select count(*) cnt from store_sales where ss_quantity > 0
;
-- end query 1 in stream 0 using template query96.tpl

-- start query 2 in stream 0 using template query3.tpl
select d_year, count(*) c from date_dim group by d_year order by d_year limit 5
;
-- end query 2 in stream 0 using template query3.tpl

-- start query 3 in stream 0 using template query42.tpl
select d_moy, sum(ss_ext_sales_price) s from store_sales, date_dim
where ss_sold_date_sk = d_date_sk and d_year = 2000
group by d_moy order by d_moy
;
-- end query 3 in stream 0 using template query42.tpl

-- start query 4 in stream 0 using template query55.tpl
select d_year, count(*) c from date_dim where d_moy = 11
group by d_year order by d_year limit 5
;
-- end query 4 in stream 0 using template query55.tpl
"""


@pytest.mark.slow
def test_traced_power_run_end_to_end(data_dir, tmp_path, monkeypatch, capsys):
    """Acceptance: a traced power run over >= 3 queries produces a parseable
    event log whose root operator spans fit inside the recorded query wall
    time, with catalog-load and cache-hit events, and the profiler renders a
    per-operator breakdown from it."""
    from nds_tpu.power import gen_sql_from_stream, run_query_stream

    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("NDS_TRACE_DIR", str(trace_dir))
    stream = tmp_path / "query_0.sql"
    stream.write_text(STREAM)
    run_query_stream(
        input_prefix=data_dir,
        property_file=None,
        query_dict=gen_sql_from_stream(str(stream)),
        time_log_output_path=str(tmp_path / "time.csv"),
        input_format="csv",
        json_summary_folder=str(tmp_path / "json"),
    )
    files = R.discover_event_files(str(trace_dir))
    assert len(files) == 1
    evs = R.read_events(files, strict=True)  # parseable, line by line
    assert R.validate_events(evs) == []
    kinds = {e["kind"] for e in evs}
    assert {"op_span", "query_span", "catalog_load"} <= kinds
    assert any(
        e["kind"] == "catalog_load" and e["cache"] == "hit" for e in evs
    ), "repeated table loads must produce a cache-hit event"
    prof = R.profile_events(evs)
    assert set(prof["queries"]) == {"query96", "query3", "query42", "query55"}
    for q, rec in prof["queries"].items():
        assert rec["status"] == "Completed"
        assert rec["ops"], f"{q}: no operator spans"
        # inclusive root operator time fits inside the recorded wall time
        assert rec["root_incl_ms"] <= rec["wall_ms"] + 1.0, q
        assert rec.get("mem_hw_bytes", 0) > 0
    # every per-query summary carries the memory high-water too
    jdir = tmp_path / "json"
    for f in os.listdir(jdir):
        s = json.load(open(jdir / f))
        assert s["memoryHighWater"]["bytes"] > 0
        assert s["env"]["engineConf"] == s["env"]["sparkConf"]
    # the profiler CLI renders a per-operator breakdown from the real log
    # (q42's Aggregate fuses into a Pipeline since the agg-tail fusion, so
    # the MultiJoin is the stable named operator to look for)
    profile_cli.main([str(trace_dir), "--per_query", "--check"])
    out = capsys.readouterr().out
    assert "query42" in out and "MultiJoin" in out and "Pipeline" in out
    assert "tallies" in out
    # the budgeter's statement verdicts surface in the profile summary
    assert "plan budget" in out and "direct" in out


@pytest.mark.slow
def test_live_telemetry_power_run_end_to_end(data_dir, tmp_path, monkeypatch,
                                             capsys):
    """Acceptance (ISSUE 8): with NDS_METRICS_PORT set, a mid-flight power
    run answers /statusz with the currently executing query and /metrics
    with monotonically increasing query_span/exec_cache counters in valid
    exposition format; the tracer rotates segments at the configured byte
    cap; `profile compact` then bounds the raw-span disk while the
    profile over the compacted dir equals the uncompacted one for the
    summary fields."""
    from nds_tpu.power import gen_sql_from_stream, run_query_stream

    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("NDS_TRACE_DIR", str(trace_dir))
    monkeypatch.setenv("NDS_METRICS_PORT", "0")  # ephemeral bind
    rotate = 8000
    monkeypatch.setenv("NDS_TRACE_ROTATE_BYTES", str(rotate))
    monkeypatch.setenv("NDS_HEARTBEAT_INTERVAL_MS", "50")
    stream = tmp_path / "query_0.sql"
    stream.write_text(STREAM)
    snaps = {"statusz": [], "metrics": [], "errors": []}
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            server = M.active_server()
            if server is None:
                time.sleep(0.002)
                continue
            try:
                st = json.loads(_scrape(server.port, "/statusz"))
                body = _scrape(server.port, "/metrics")
            except Exception:
                time.sleep(0.002)
                continue
            snaps["errors"].extend(M.validate_exposition(body))
            snaps["statusz"].append(st)
            snaps["metrics"].append(body)
            time.sleep(0.002)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    try:
        run_query_stream(
            input_prefix=data_dir,
            property_file=None,
            query_dict=gen_sql_from_stream(str(stream)),
            time_log_output_path=str(tmp_path / "time.csv"),
            input_format="csv",
        )
    finally:
        stop.set()
        t.join(timeout=5)
    # -- live surface: scraped mid-run, well-formed, monotone ------------
    assert snaps["errors"] == []
    assert snaps["metrics"], "the endpoint must have answered mid-run"
    in_flight = [
        s["query"]["query"] for s in snaps["statusz"] if s.get("query")
    ]
    assert in_flight, "/statusz must have named an executing query mid-run"
    assert set(in_flight) <= {"query96", "query3", "query42", "query55"}

    def counter_total(body, family):
        total = 0.0
        for line in body.splitlines():
            if line.startswith("#"):
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            if name == family:
                total += float(line.rsplit(" ", 1)[1])
        return total

    qs = [counter_total(b, "nds_query_span_total") for b in snaps["metrics"]]
    ec = [counter_total(b, "nds_exec_cache_total") for b in snaps["metrics"]]
    assert qs == sorted(qs) and ec == sorted(ec), "counters must be monotone"
    sink = M.shared_sink()
    assert sum(
        sink.registry.counter_series("nds_query_span_total").values()
    ) == 4
    assert sum(
        sink.registry.counter_series("nds_exec_cache_total").values()
    ) >= 1
    assert sum(
        sink.registry.counter_series("nds_heartbeat_total").values()
    ) >= 4  # at least one beacon per query
    # -- rotation + compaction bound the trace dir -----------------------
    files = R.discover_event_files(str(trace_dir))
    assert len(files) >= 2, "the run must have rotated at the byte cap"
    evs = R.read_events(str(trace_dir), strict=True)
    assert R.validate_events(evs) == []
    assert any(e["kind"] == "heartbeat" for e in evs)
    before = R.load_profile(str(trace_dir))
    profile_cli.main(["compact", str(trace_dir)])
    capsys.readouterr()
    raw = sum(
        os.path.getsize(f) for f in R.discover_event_files(str(trace_dir))
    )
    assert raw <= rotate + 2048, "compacted raw spans must stay under the cap"
    after = R.load_profile(str(trace_dir))
    assert set(after["queries"]) == set(before["queries"])
    for q, rec in before["queries"].items():
        assert after["queries"][q]["status"] == rec["status"] == "Completed"
        assert after["queries"][q]["runs"] == rec["runs"]
        assert after["queries"][q]["wall_ms"] == pytest.approx(rec["wall_ms"])
    assert after["tallies"] == before["tallies"]
    # the profiler CLI re-profiles the compacted dir, schema-checked
    profile_cli.main([str(trace_dir), "--check"])
    out = capsys.readouterr().out
    assert "query42" in out and "tallies" in out
