"""Persistent AOT executable cache (engine/aotcache.py): the contract is
"a mismatched or damaged cache can cost a recompile, never a wrong result
or a crash" — every test here is one face of that, plus the fleet
behaviors (two-process warm, orphan sweep, eviction accounting,
promotion-memo persistence) ISSUE 11 requires."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp

from nds_tpu import faults
from nds_tpu.engine import aotcache as AC
from nds_tpu.engine.session import Session


@pytest.fixture(autouse=True)
def _hermetic_xla_cache(tmp_path_factory):
    """Pin the XLA persistent compilation cache to a fresh directory per
    TEST: an executable LOADED from a warm XLA cache serializes into an
    unreloadable payload (the store-time validation skips it), so any
    warm XLA cache — the ambient ~/.cache/nds_xla or even this module's
    own previous test — would make store/hit assertions order-dependent.
    A fresh dir means every compile here is real and every store
    validates."""
    import contextlib

    from nds_tpu.engine import session as S

    # trip the Session-construction once-latch FIRST: otherwise the first
    # Session built inside a test re-points the cache at the ambient
    # (possibly warm) default, silently overriding the pin below
    S._enable_persistent_compile_cache()
    prev = None
    with contextlib.suppress(Exception):
        prev = jax.config.jax_compilation_cache_dir
    jax.config.update(
        "jax_compilation_cache_dir",
        str(tmp_path_factory.mktemp("xla_cache")),
    )
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _table(n=2000, seed=1):
    r = np.random.default_rng(seed)
    ks = r.integers(0, 12, n)
    return pa.table({
        "k": pa.array(
            [None if i % 9 == 0 else int(x) for i, x in enumerate(ks)],
            pa.int32(),
        ),
        "k2": pa.array(r.integers(0, 6, n), pa.int32()),
        "v": pa.array(r.integers(-90, 90, n), pa.int64()),
        "cat": pa.array(
            [["Books", "Music", "Shoes"][int(x) % 3] for x in ks],
            pa.string(),
        ),
    })


def _session(tmp_path, **conf):
    sess = Session(conf={
        "engine.aot_cache_dir": str(tmp_path / "aot"), **conf,
    })
    sess.register_arrow("t", _table())
    return sess


def _tiny_compiled(mul=2.0):
    fn = lambda x: x * mul + 1.0  # noqa: E731
    return jax.jit(fn).lower(
        jax.ShapeDtypeStruct((16,), jnp.float32)
    ).compile()


def _cache(tmp_path, budget=1 << 30):
    return AC.AotCache(str(tmp_path / "aot"), budget)


def _key(cache, tag="a", cap=16):
    return cache.entry_key(
        "pipeline", f"fp-{tag}", [("live", False)],
        [((cap,), "float32")], (), ("on", "off"),
    )


# string PREDICATE but no string GROUP KEY: dictionary work runs at trace
# time, so this agg-tail executable serializes on the CPU backend (a
# string-keyed aggregate bakes rank tables whose executable does not —
# store-time validation keeps such shapes on the in-process path)
QUERY = (
    "select k, k2, sum(v) s, count(*) c from t "
    "where v > -50 and cat like 'B%' group by k, k2 order by k, k2"
)


# ---------------------------------------------------------------------------
# roundtrip + key discipline
# ---------------------------------------------------------------------------


def test_roundtrip_equality_vs_in_process_build(tmp_path):
    """A fresh session resolving from disk returns EXACTLY what the
    compiling session returned — serialize/deserialize is semantically
    invisible."""
    s1 = _session(tmp_path)
    ref = s1.sql(QUERY).collect().to_pylist()
    assert s1.aot_cache.stats["stores"] >= 1
    assert s1.aot_cache.stats["disk_hits"] == 0

    s2 = _session(tmp_path)
    out = s2.sql(QUERY).collect().to_pylist()
    assert out == ref
    assert s2.aot_cache.stats["disk_hits"] >= 1
    assert s2.aot_cache.stats["misses"] == 0


def test_environment_key_mismatch_is_clean_miss(tmp_path):
    """Any environment drift — jax version, device kind, conf flip — is a
    MISS, and the mismatched (valid) entry is left in place, never
    quarantined: another environment may still own it."""
    cache = _cache(tmp_path)
    key = _key(cache)
    assert cache.store(key, _tiny_compiled())
    assert cache.load(key) is not None

    for mutate in (
        lambda k: k["env"].__setitem__("jax", "0.0.1"),
        lambda k: k["env"].__setitem__("device_kind", "tpu-v9"),
        lambda k: k.__setitem__("conf", ["off", "off"]),
        lambda k: k.__setitem__("fp", "fp-other"),
    ):
        skew = json.loads(json.dumps(key))
        mutate(skew)
        assert cache.load(skew) is None
    # the original entry survived every mismatched probe
    assert cache.load(key) is not None
    assert cache.stats["quarantined"] == 0


def test_filename_collision_reads_as_miss_not_wrong_load(tmp_path):
    """A file whose NAME matches but whose recorded key differs (hash
    collision / foreign entry) must read as a miss: load verifies the
    full key dict, not the filename."""
    cache = _cache(tmp_path)
    key = _key(cache, "a")
    other = _key(cache, "b")
    assert cache.store(other, _tiny_compiled())
    # graft other's entry onto key's filename
    os.makedirs(cache.dir, exist_ok=True)
    os.replace(
        os.path.join(cache.dir, AC._entry_name(other)),
        os.path.join(cache.dir, AC._entry_name(key)),
    )
    assert cache.load(key) is None
    assert cache.stats["quarantined"] == 0


# ---------------------------------------------------------------------------
# corruption: clean miss + quarantine, never a crash
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("damage", ["truncate", "flip", "garbage", "empty"])
def test_corrupt_entry_is_quarantined_miss(tmp_path, damage):
    cache = _cache(tmp_path)
    key = _key(cache)
    assert cache.store(key, _tiny_compiled())
    path = os.path.join(cache.dir, AC._entry_name(key))
    raw = open(path, "rb").read()
    if damage == "truncate":
        blob = raw[: len(raw) // 2]  # torn write shape
    elif damage == "flip":
        mid = len(raw) - 20  # inside the pickled body: checksum must trip
        blob = raw[:mid] + bytes([raw[mid] ^ 0xFF]) + raw[mid + 1:]
    elif damage == "garbage":
        blob = b"not an entry at all"
    else:
        blob = b""
    with open(path, "wb") as f:
        f.write(blob)

    assert cache.load(key) is None  # never a crash
    assert cache.stats["quarantined"] == 1
    assert not os.path.exists(path)  # moved aside, not left to re-trip
    quarantined = [
        n for n in os.listdir(cache.dir) if n.startswith("quarantine-")
    ]
    assert len(quarantined) == 1
    # the slot is reusable immediately
    assert cache.store(key, _tiny_compiled())
    assert cache.load(key) is not None


def test_poisoned_entry_end_to_end_recompiles_correctly(tmp_path):
    """The acceptance contract at the session level: corrupt every stored
    entry behind a warmed cache dir — a fresh session must still return
    bit-identical results (recompile path), with the damage visible only
    as quarantine stats."""
    s1 = _session(tmp_path)
    ref = s1.sql(QUERY).collect().to_pylist()
    aot_dir = s1.aot_cache.dir
    entries = [n for n in os.listdir(aot_dir) if n.startswith("aot-")]
    assert entries
    for n in entries:
        with open(os.path.join(aot_dir, n), "r+b") as f:
            f.seek(max(os.path.getsize(os.path.join(aot_dir, n)) - 30, 0))
            f.write(b"\xde\xad\xbe\xef")

    s2 = _session(tmp_path)
    assert s2.sql(QUERY).collect().to_pylist() == ref
    assert s2.aot_cache.stats["quarantined"] >= 1
    assert s2.aot_cache.stats["disk_hits"] == 0


def test_vacuum_removes_quarantines_and_enforces_budget(tmp_path):
    cache = _cache(tmp_path)
    key = _key(cache)
    assert cache.store(key, _tiny_compiled())
    path = os.path.join(cache.dir, AC._entry_name(key))
    with open(path, "wb") as f:
        f.write(b"junk")
    assert cache.load(key) is None  # quarantines
    assert any(
        n.startswith("quarantine-") for n in os.listdir(cache.dir)
    )
    cache.vacuum()
    assert not any(
        n.startswith("quarantine-") for n in os.listdir(cache.dir)
    )
    # drop_all clears committed entries too
    assert cache.store(key, _tiny_compiled())
    cache.vacuum(drop_all=True)
    assert cache.usage() == (0, 0)


# ---------------------------------------------------------------------------
# concurrency + crash hygiene
# ---------------------------------------------------------------------------

_WARM_SCRIPT = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    # match the pytest parent's environment key (x64 + the 8-device CPU
    # host platform come from tests/conftest.py) — the parent asserts it
    # can load what the children stored
    jax.config.update("jax_enable_x64", True)
    import sys
    sys.path.insert(0, {repo!r})
    import jax.numpy as jnp
    from nds_tpu.engine import aotcache as AC

    cache = AC.AotCache({cache_dir!r}, 1 << 30)
    key = cache.entry_key(
        "pipeline", "fp-shared", [("live", False)],
        [((16,), "float32")], (), ("on", "off"),
    )
    compiled = jax.jit(lambda x: x * 2.0).lower(
        jax.ShapeDtypeStruct((16,), jnp.float32)
    ).compile()
    for _ in range(8):
        cache.store(key, compiled)
    loaded = cache.load(key)
    assert loaded is not None
    print("WARMED")
""")


def test_concurrent_two_process_warm_one_winner_no_torn_files(tmp_path):
    """Two processes racing store() on the SAME key: exactly one committed
    entry survives, it is loadable, and no .tmp- staging files leak."""
    cache_dir = str(tmp_path / "aot")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _WARM_SCRIPT.format(repo=repo, cache_dir=cache_dir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for _ in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]
        assert "WARMED" in out
    names = os.listdir(cache_dir)
    entries = [n for n in names if n.startswith("aot-") and n.endswith(".bin")]
    assert len(entries) == 1
    assert not any(".tmp-" in n for n in names)
    # the surviving entry is loadable by a third party
    cache = AC.AotCache(cache_dir, 1 << 30)
    key = cache.entry_key(
        "pipeline", "fp-shared", [("live", False)],
        [((16,), "float32")], (), ("on", "off"),
    )
    assert cache.load(key) is not None


def test_orphan_sweep_removes_dead_pid_temps_only(tmp_path):
    cache_dir = tmp_path / "aot"
    cache_dir.mkdir()
    dead = cache_dir / "aot-abc.bin.tmp-999999-aa"
    dead.write_bytes(b"torn")
    live = cache_dir / f"aot-def.bin.tmp-{os.getpid()}-bb"
    live.write_bytes(b"in-flight")
    committed = cache_dir / "aot-abc.bin"
    committed.write_bytes(b"committed")
    foreign = cache_dir / "something-else.tmp-999999-cc"
    foreign.write_bytes(b"foreign")
    removed = AC.sweep_orphans(str(cache_dir))
    assert removed == 1
    assert not dead.exists()
    assert live.exists() and committed.exists() and foreign.exists()


def test_eviction_accounting_lru_to_budget(tmp_path):
    cache = _cache(tmp_path)
    k1, k2, k3 = (_key(cache, t) for t in ("e1", "e2", "e3"))
    assert cache.store(k1, _tiny_compiled(1.0))
    size = cache.usage()[1]
    # room for ~two entries: the third store must evict the LRU one
    cache.budget = int(size * 2.5)
    assert cache.store(k2, _tiny_compiled(2.0))
    assert cache.load(k1) is not None  # refresh k1: k2 becomes LRU
    assert cache.store(k3, _tiny_compiled(3.0))
    n, total = cache.usage()
    assert total <= cache.budget
    assert cache.stats["evictions"] >= 1
    assert cache.load(k2) is None   # the LRU victim
    assert cache.load(k1) is not None
    assert cache.load(k3) is not None


# ---------------------------------------------------------------------------
# fault sites: aot:write / aot:read through the registry
# ---------------------------------------------------------------------------


def test_injected_io_fault_keeps_classifiable_identity(tmp_path):
    cache = _cache(tmp_path)
    key = _key(cache)
    try:
        faults.install("io:aot:write:1")
        with pytest.raises(faults.TransientIOError) as ei:
            cache.store(key, _tiny_compiled())
        assert faults.classify(ei.value) == faults.IO_TRANSIENT
        # the rule disarmed after one fire: the retry (the ladder's
        # io_backoff rung re-running the query) succeeds
        assert cache.store(key, _tiny_compiled())
        faults.install("io:aot:read:1")
        with pytest.raises(faults.TransientIOError):
            cache.load(key)
        assert cache.load(key) is not None
    finally:
        faults.reset()


def test_crash_mid_write_leaves_no_committed_entry(tmp_path):
    """The fs_open_atomic pattern under a crash rule: the injected crash
    (a BaseException, like SIGKILL) escapes every recovery layer, no
    committed entry appears, and the cache dir's only residue is what the
    next process's sweep removes."""
    cache = _cache(tmp_path)
    key = _key(cache)
    try:
        faults.install("crash:aot:write")
        with pytest.raises(faults.InjectedCrash):
            cache.store(key, _tiny_compiled())
    finally:
        faults.reset()
    assert cache.load(key) is None  # nothing half-published
    # a torn temp a crashed process DID leave behind (crash landing
    # mid-write rather than at the injection point) is swept once its
    # pid is dead — the committed namespace never sees it
    torn = os.path.join(
        cache.dir, f"{AC._entry_name(key)}.tmp-999999-zz"
    )
    os.makedirs(cache.dir, exist_ok=True)
    with open(torn, "wb") as f:
        f.write(b"half a header")
    assert AC.sweep_orphans(cache.dir) == 1
    assert cache.load(key) is None
    assert cache.store(key, _tiny_compiled())


def test_real_store_failure_degrades_never_raises(tmp_path, monkeypatch):
    """A REAL filesystem failure (not injected) disables stores for the
    process and returns False — queries keep running on in-process
    compiles."""
    cache = AC.AotCache(str(tmp_path / "missing" / "deep"), 1 << 30)
    monkeypatch.setattr(
        AC.os, "makedirs",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
    )
    assert cache.store(_key(cache), _tiny_compiled()) is False
    assert cache.stats["store_failures"] == 1
    assert cache._store_disabled


# ---------------------------------------------------------------------------
# promotion-memo persistence
# ---------------------------------------------------------------------------


def test_promotion_store_roundtrip(tmp_path):
    store = AC.PromotionStore(str(tmp_path / "aot"))
    key = AC.promotion_key_str(("sort_perm", 4096, 128))
    assert store.get(key) is None
    rec = {"jnp_ms": 1.0, "pallas_ms": 0.5, "use": True}
    store.record(key, rec)
    assert store.get(key) == rec
    # a fresh handle (fresh process) reads the same verdict
    assert AC.PromotionStore(str(tmp_path / "aot")).get(key) == rec


def test_promotion_store_tolerates_corruption(tmp_path):
    d = tmp_path / "aot"
    d.mkdir()
    (d / "promotions.json").write_text("{torn json")
    store = AC.PromotionStore(str(d))
    assert store.get("anything") is None
    store.record("k", {"use": False})
    assert AC.PromotionStore(str(d)).get("k") == {"use": False}


def test_persisted_promotion_verdict_skips_remeasure(tmp_path):
    """A fresh session consuming a persisted verdict must not re-measure:
    the fleet pays one A/B per (kernel, shape, backend), ever."""
    import nds_tpu.engine.exec as EX

    conf = {"engine.pallas_sort": "auto"}
    s1 = _session(tmp_path, **conf)
    sort_q = "select k, v from t where v > 0 order by k"
    ref = s1.sql(sort_q).collect().to_pylist()
    assert any(k[0] == "sort_perm" for k in s1.pallas_promotions)

    s2 = _session(tmp_path, **conf)
    orig = EX.Executor._measure_promotion

    def boom(*a, **kw):
        raise AssertionError("re-measured a persisted promotion verdict")

    EX.Executor._measure_promotion = boom
    try:
        assert s2.sql(sort_q).collect().to_pylist() == ref
    finally:
        EX.Executor._measure_promotion = orig
    assert any(k[0] == "sort_perm" for k in s2.pallas_promotions)


# ---------------------------------------------------------------------------
# observability + budget derivation satellites
# ---------------------------------------------------------------------------


def test_aot_events_ride_the_trace(tmp_path):
    from nds_tpu.obs import reader as R

    trace = tmp_path / "trace"
    s1 = _session(tmp_path, **{"engine.trace_dir": str(trace)})
    s1.sql(QUERY).collect()
    s1.tracer.close()
    prof = R.load_profile([str(trace)], strict=True)
    assert prof["tallies"]["aot_stores"] >= 1
    assert prof["tallies"]["aot_misses"] >= 1

    trace2 = tmp_path / "trace2"
    s2 = _session(tmp_path, **{"engine.trace_dir": str(trace2)})
    s2.sql(QUERY).collect()
    s2.tracer.close()
    prof2 = R.load_profile([str(trace2)], strict=True)
    assert prof2["tallies"]["aot_disk_hits"] >= 1
    assert R.aot_disk_hit_rate(prof2) == 1.0


def test_auto_budget_derivations_share_one_formula():
    from nds_tpu.analysis.budget import derive_share_bytes, host_ram_bytes
    from nds_tpu.engine.spill import resolve_pool_bytes

    # power-of-two, clamped, monotone in the resource
    assert derive_share_bytes(64 << 30, 4, 1 << 30, 64 << 30) == 16 << 30
    assert derive_share_bytes(100 << 30, 4, 1 << 30, 64 << 30) == 16 << 30
    assert derive_share_bytes(1 << 20, 4, 1 << 30, 64 << 30) == 1 << 30
    ram = host_ram_bytes()
    assert ram > 0
    auto = resolve_pool_bytes({"engine.spill_pool_bytes": "auto"})
    assert auto == derive_share_bytes(ram, 4, 1 << 30, 64 << 30)
    # auto never breaks the explicit paths
    assert resolve_pool_bytes({"engine.spill_pool_bytes": 123}) == 123
    aot = AC.resolve_aot_cache_bytes({"engine.aot_cache_bytes": "auto"}, "/")
    assert aot & (aot - 1) == 0  # power of two
    assert AC.resolve_aot_cache_bytes(
        {"engine.aot_cache_bytes": 4096}, "/"
    ) == 4096
