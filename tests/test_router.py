"""Fleet-router tests: verdict routing, edge rejects that provably
consume no replica worker slot, failover on replica death, the
anti-storm retry token bucket, fleet tenant caps, DML idempotency keys,
the degraded-DML circuit on coordinator loss, rolling reload, and the
router hop in the request trace.

Replicas are REAL QueryServices behind real ephemeral listeners (each on
its own obs/httpserv.MetricsServer over the process-shared sink) and the
router fronts them over actual HTTP — the wire contract is what is
asserted. Multi-process chaos (SIGKILL mid-query, coordinator loss with
a live tcp catalog) lives in tools/fleet_check.py."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine.session import Session
from nds_tpu.lakehouse.table import LakehouseTable
from nds_tpu.obs import httpserv as HS
from nds_tpu.obs import metrics as M
from nds_tpu.obs import trace as obs_trace
from nds_tpu.serve.router import QueryRouter, Replica
from nds_tpu.serve.service import QueryService


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    M.reset_shared()
    yield
    faults.reset()
    M.reset_shared()


def _fact_table(rows=64):
    return pa.table({
        "k": pa.array(np.arange(rows) % 8, type=pa.int64()),
        "v": pa.array(np.arange(rows), type=pa.int64()),
    })


def _mini_lake(tmp_path, rows=64):
    path = str(tmp_path / "fact")
    LakehouseTable.create(path, _fact_table(rows))
    return path


QUERY = "select k, count(*) c, sum(v) s from fact group by k order by k"
POINT = "select k, v from fact where v = 3 limit 1"

#: fast, prober-less router defaults for in-process tests
RCONF = {
    "engine.route_health_interval_s": 0,
    "engine.route_backoff_base_s": 0.005,
    "engine.route_backoff_cap_s": 0.02,
}


@pytest.fixture
def fleet():
    """Builder for in-process fleets; tears everything down in order
    (routers first so the prober stops, then services, then listeners)."""
    made = {"servers": [], "services": [], "routers": []}

    class F:
        @staticmethod
        def replica(conf=None, lake_path=None, templates=None, rows=64):
            conf = {"engine.metrics_port": 0, **(conf or {})}
            session = Session(conf=conf)
            if lake_path is not None:
                session.register_lakehouse("fact", lake_path)
            else:
                session.register_arrow("fact", _fact_table(rows))
            service = QueryService(session, templates=templates)
            # each replica needs its OWN listener (the process singleton
            # hosts at most one app); all share the process-wide sink
            srv = HS.MetricsServer(
                M.shared_sink(), 0, host="127.0.0.1"
            ).start()
            srv.attach_app(service)
            made["servers"].append(srv)
            made["services"].append(service)
            return service, srv.port, srv

        @staticmethod
        def router(ports, conf=None, mesh_port=None, trace_dir=None):
            rconf = {**RCONF, "engine.metrics_port": 0, **(conf or {})}
            if trace_dir:
                rconf["engine.trace_dir"] = str(trace_dir)
            tracer = obs_trace.tracer_from_conf(rconf, app_id="nds-route")
            router = QueryRouter(
                [f"127.0.0.1:{p}" for p in ports], conf=rconf,
                tracer=tracer,
                mesh_replica=(
                    f"127.0.0.1:{mesh_port}" if mesh_port else None
                ),
            )
            srv = HS.MetricsServer(
                M.shared_sink(), 0, host="127.0.0.1"
            ).start()
            srv.attach_app(router)
            made["servers"].append(srv)
            made["routers"].append(router)
            return router, srv.port

    yield F
    for r in made["routers"]:
        r.close()
    for s in made["services"]:
        try:
            s.close()
        except Exception:
            pass
    for srv in made["servers"]:
        try:
            srv.stop()
        except Exception:
            pass


def _post(port, payload, tenant="default", path="/query", timeout=120,
          headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 "X-NDS-Tenant": tenant, **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except ValueError:
            body = {}
        return e.code, body, dict(e.headers)


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# address parsing, classification, fingerprints (pure units)
# ---------------------------------------------------------------------------


def test_replica_parsing_and_payload_classification():
    assert Replica("http://127.0.0.1:1234/").name == "127.0.0.1:1234"
    assert Replica("host:80").name == "host:80"
    with pytest.raises(ValueError):
        Replica("no-port")
    cls = QueryRouter.classify_payload
    assert cls({"sql": QUERY}) == "select"
    assert cls({"sql": "  WITH t AS (select 1) select * from t"}) == "select"
    assert cls({"sql": "(select 1)"}) == "select"
    assert cls({"sql": "insert into fact select * from fact"}) == "dml"
    assert cls({"sql": "delete from fact where v < 0"}) == "dml"
    assert cls({"template": "query3"}) == "select"
    fp = QueryRouter.fingerprint
    assert fp({"sql": "select  1\nfrom fact"}) == \
        fp({"sql": "SELECT 1 FROM fact"})
    assert fp({"template": "q", "params": {"K": 1}}) != \
        fp({"template": "q", "params": {"K": 2}})
    assert fp({}) is None


# ---------------------------------------------------------------------------
# routed round trip + fleet view
# ---------------------------------------------------------------------------


def test_routed_select_roundtrip_and_fleet_view(fleet):
    _, p1, _ = fleet.replica()
    _, p2, _ = fleet.replica()
    router, rport = fleet.router([p1, p2])
    status, body, _ = _post(rport, {"sql": QUERY})
    assert status == 200 and body["status"] == "completed"
    assert body["columns"] == ["k", "c", "s"]
    assert body["route"]["attempts"] == 1
    assert body["route"]["replica"] in (f"127.0.0.1:{p1}",
                                        f"127.0.0.1:{p2}")
    status, raw = _get(rport, "/fleet")
    view = json.loads(raw)
    assert status == 200 and len(view["replicas"]) == 2
    assert all(r["healthy"] for r in view["replicas"])
    assert view["degraded"] == {} and view["draining"] is False


# ---------------------------------------------------------------------------
# verdict routing: reject answered at the edge, zero worker slots
# ---------------------------------------------------------------------------


def test_verdict_reject_429_at_edge_consumes_no_replica_slot(fleet):
    service, p1, _ = fleet.replica(
        conf={"engine.plan_budget_bytes": 1024,
              "engine.plan_budget_reject_bytes": 2048},
        rows=1 << 16,
    )
    router, rport = fleet.router([p1])
    heavy = {"sql": "select k + v from fact"}
    for i in range(2):
        status, body, headers = _post(rport, heavy, tenant="rej")
        assert status == 429
        assert body["status"] == "rejected" and body["verdict"] == "reject"
        assert body["peak_bytes"] > 2048
        assert body["budget_bytes"] == 1024
        assert body["retry_after_s"] > 0
        assert headers.get("Retry-After")
    # the second request hit the verdict cache (one fingerprint cached)
    assert router.fleet_snapshot()["verdict_cache_entries"] == 1
    # the proof the edge 429 never consumed a replica worker slot: the
    # /plan probe emits NO serve_request, so tenant "rej" never appears
    # in the replica-side accounting at all
    snap = M.shared_sink().status_snapshot()
    assert "rej" not in (snap.get("tenants") or {})
    series = M.shared_sink().registry.counter_series(
        "nds_serve_request_total"
    )
    assert not any(("tenant", "rej") in labels for labels in series)
    assert service._in_flight == 0
    # ... while the router-edge accounting saw both rejects
    assert snap["fleet"]["edge_rejected"] == 2
    assert snap["fleet"]["tenants"]["rej"]["rejected"] == 2


def test_plan_probe_is_slotless_on_the_replica(fleet):
    service, p1, _ = fleet.replica()
    status, body, _ = _post(p1, {"sql": QUERY}, path="/plan")
    assert status == 200
    assert body["kind"] == "select"
    assert body["verdict"] in ("direct", "unknown")
    status, body, _ = _post(
        p1, {"sql": "insert into fact select * from fact"}, path="/plan"
    )
    assert status == 200
    assert body["kind"] == "dml" and body["verdict"] is None
    status, _, _ = _post(p1, {"template": "nope"}, path="/plan")
    assert status == 404
    # no admission slot, no serve_request accounting, ever
    assert service._in_flight == 0
    assert M.shared_sink().registry.counter_series(
        "nds_serve_request_total"
    ) == {}


def test_spill_verdict_pins_to_mesh_replica(fleet):
    _, p1, _ = fleet.replica()
    _, p2, _ = fleet.replica()
    router, rport = fleet.router([p1, p2], mesh_port=p2)
    mesh = [r for r in router.replicas if r.mesh]
    assert [r.name for r in mesh] == [f"127.0.0.1:{p2}"]
    # _pick narrows to the mesh replica for capacity-demanding verdicts
    for v in ("spill", "blocked", "over"):
        assert router._pick({"verdict": v}).name == f"127.0.0.1:{p2}"
    picked = {router._pick({"verdict": "direct"}).name for _ in range(4)}
    assert picked == {f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"}


# ---------------------------------------------------------------------------
# failure detection + failover
# ---------------------------------------------------------------------------


def test_failover_on_replica_death_marks_unhealthy(fleet):
    _, p1, s1 = fleet.replica()
    _, p2, _ = fleet.replica()
    # verdict probing off so the FORWARD hop (not the /plan probe) is
    # what discovers the death — the failover path under test
    router, rport = fleet.router(
        [p1, p2], conf={"engine.route_verdict_cache": 0}
    )
    s1.stop()  # replica death: connect refused from now on
    dead = f"127.0.0.1:{p1}"
    # steer the round-robin tiebreak at the dead replica first so the
    # failover path is exercised deterministically
    router._rr = [r.name for r in router.replicas].index(dead)
    status, body, _ = _post(rport, {"sql": QUERY})
    assert status == 200 and body["status"] == "completed"
    assert body["route"]["attempts"] == 2
    assert body["route"]["replica"] == f"127.0.0.1:{p2}"
    view = router.fleet_snapshot()
    by_name = {r["replica"]: r for r in view["replicas"]}
    assert by_name[dead]["healthy"] is False
    assert by_name[f"127.0.0.1:{p2}"]["healthy"] is True
    # the retry left a classified metric behind
    _, text = _get(rport, "/metrics")
    assert 'nds_route_retry_total{reason="connect"}' in text
    # active prober agrees: dead stays dead, live probes healthy
    assert router.probe_replica(router.replicas[
        [r.name for r in router.replicas].index(dead)
    ]) is False
    assert router.probe_replica(router.replicas[
        [r.name for r in router.replicas].index(f"127.0.0.1:{p2}")
    ]) is True


def test_all_replicas_dead_fails_bounded_and_classified(fleet):
    _, p1, s1 = fleet.replica()
    _, p2, s2 = fleet.replica()
    router, rport = fleet.router([p1, p2])
    s1.stop()
    s2.stop()
    status, body, headers = _post(rport, {"sql": QUERY})
    assert status == 503
    assert body["status"] == "failed"
    assert body["failure_kind"] == faults.IO_TRANSIENT
    assert 2 <= body["route"]["attempts"] <= router.max_attempts
    assert sorted(body["route"]["tried"]) == sorted(
        [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
    )
    assert body["retry_after_s"] > 0 and headers.get("Retry-After")


def test_retry_token_bucket_bounds_the_failover_storm(fleet):
    _, p1, _ = fleet.replica()
    _, p2, _ = fleet.replica()
    router, rport = fleet.router(
        [p1, p2],
        conf={"engine.route_retry_burst": 1, "engine.route_retry_rate": 0},
    )
    # every forward hop fails like a dead replica
    faults.install("io:route:forward:100")
    n = 5
    attempts = []
    for _ in range(n):
        status, body, _ = _post(rport, {"sql": QUERY}, tenant="storm")
        assert status == 503 and body["status"] == "failed"
        assert "injected" in body["error"]
        attempts.append(body["route"]["attempts"])
    # first attempts are free; FAILOVER retries draw from the bucket:
    # with burst=1 and no refill the whole storm gets exactly one retry
    assert sum(attempts) == n + 1
    assert attempts[0] == 2 and set(attempts[1:]) == {1}


def test_upstream_drain_propagates_with_jittered_retry_after(fleet):
    service, p1, _ = fleet.replica()
    router, rport = fleet.router([p1])
    service.handle_drain()  # replica stops admitting
    ras = []
    status, body, headers = _post(rport, {"sql": QUERY})
    assert status == 503 and body["status"] == "draining"
    assert headers.get("Retry-After")
    ras.append(body["retry_after_s"])
    # passive detection: the 503-draining answer marked the replica
    assert router.fleet_snapshot()["replicas"][0]["draining"] is True
    for _ in range(4):
        status, body, _ = _post(rport, {"sql": QUERY})
        assert status == 503 and body["status"] == "failed"
        assert "no healthy replica" in body["error"]
        ras.append(body["retry_after_s"])
    # decorrelated jitter: shed clients must not re-arrive in lockstep
    assert len(set(ras)) >= 2


# ---------------------------------------------------------------------------
# fleet-wide tenant quota
# ---------------------------------------------------------------------------


def test_fleet_tenant_cap_sheds_at_edge(fleet):
    _, p1, _ = fleet.replica()
    router, rport = fleet.router(
        [p1], conf={"engine.route_tenant_cap": 1}
    )
    before = router.fleet_snapshot()["replicas"][0]["requests"]
    assert router._tenant_enter("cap")  # one slot held fleet-wide
    try:
        status, body, headers = _post(rport, {"sql": QUERY}, tenant="cap")
        assert status == 429 and body["status"] == "shed"
        assert "fleet in-flight cap" in body["error"]
        assert headers.get("Retry-After")
        assert router.fleet_snapshot()["tenant_in_flight"] == {"cap": 1}
        # shed at the edge: nothing was forwarded (not even a /plan)
        assert router.fleet_snapshot()["replicas"][0]["requests"] == before
        # other tenants are unaffected
        status, _, _ = _post(rport, {"sql": QUERY}, tenant="other")
        assert status == 200
    finally:
        router._tenant_leave("cap")
    status, _, _ = _post(rport, {"sql": QUERY}, tenant="cap")
    assert status == 200
    fl = M.shared_sink().status_snapshot()["fleet"]
    assert fl["tenants"]["cap"]["shed"] == 1
    assert fl["tenants"]["cap"]["completed"] == 1


# ---------------------------------------------------------------------------
# DML: idempotency keys, ambiguous mid-stream death
# ---------------------------------------------------------------------------


def test_dml_request_key_dedups_redelivery(fleet, tmp_path):
    path = _mini_lake(tmp_path, rows=8)
    _, p1, _ = fleet.replica(lake_path=path)
    dml = {"sql": "insert into fact select k, v + 1000 from fact"}
    key = {"X-NDS-Request-Key": "k" * 16}
    status, first, _ = _post(p1, dml, tenant="w", headers=key)
    assert status == 200 and first["status"] == "completed"
    assert first["rows_affected"] == 8 and first["version"] == 2
    assert "deduped" not in first
    # the re-delivered key answers the RECORDED envelope; nothing applies
    status, again, _ = _post(p1, dml, tenant="w", headers=key)
    assert status == 200 and again["deduped"] is True
    assert again["version"] == 2 and again["rows_affected"] == 8
    status, count, _ = _post(p1, {"sql": "select count(*) c from fact"})
    assert count["rows"][0][0] == 16  # applied exactly once
    # a DIFFERENT key applies again
    status, third, _ = _post(
        p1, dml, tenant="w", headers={"X-NDS-Request-Key": "x" * 16}
    )
    assert status == 200 and third["version"] == 3


def test_dml_midstream_death_is_ambiguous_then_keyed_retry_lands(
    fleet, tmp_path
):
    path = _mini_lake(tmp_path, rows=8)
    _, p1, _ = fleet.replica(lake_path=path)
    router, rport = fleet.router([p1])
    # the replica's connection thread dies mid-commit with no reply: the
    # router must NOT blind-retry a write whose outcome is unknown
    faults.install("crash:commit:fact")
    dml = {"sql": "insert into fact select k, v + 1000 from fact"}
    status, body, _ = _post(rport, dml, tenant="w")
    assert status == 503 and body["status"] == "failed"
    assert body["failure_kind"] == faults.IO_TRANSIENT
    assert "ambiguous" in body["error"]
    key = body["request_key"]
    assert key  # the router-minted idempotency key is echoed back
    # the documented client recovery: retry WITH the key — the replica
    # ledger + OCC statement path guarantee exactly-once application
    status, retry, _ = _post(
        p1, dml, tenant="w", headers={"X-NDS-Request-Key": key}
    )
    assert status == 200 and retry["status"] == "completed"
    status, count, _ = _post(p1, {"sql": "select count(*) c from fact"})
    applied_once = count["rows"][0][0]
    # ... and a SECOND keyed delivery replays, never re-applies
    status, replay, _ = _post(
        p1, dml, tenant="w", headers={"X-NDS-Request-Key": key}
    )
    assert status == 200 and replay["deduped"] is True
    status, count2, _ = _post(p1, {"sql": "select count(*) c from fact"})
    assert count2["rows"][0][0] == applied_once
    # a routed DML reply carries its minted key for exactly this recovery
    status, ok, _ = _post(rport, dml, tenant="w")
    assert status == 200 and ok["route"]["request_key"]


# ---------------------------------------------------------------------------
# coordinator loss: the degraded-DML circuit
# ---------------------------------------------------------------------------


def test_coordinator_loss_degrades_dml_keeps_selects(fleet, tmp_path):
    path = _mini_lake(tmp_path, rows=8)
    service, p1, _ = fleet.replica(lake_path=path)
    router, rport = fleet.router(
        [p1], conf={"engine.route_catalog_cooldown_s": 0.2}
    )
    dml = {"sql": "insert into fact select k, v + 1000 from fact"}
    real_run_dml = service._run_dml

    def unreachable(sql_text, tenant, rid, t0, qlabel, request_key=None):
        return service._reply(500, {
            "request_id": rid, "tenant": tenant, "status": "failed",
            "failure_kind": faults.IO_TRANSIENT,
            "error": "catalog unreachable at http://127.0.0.1:9 "
                     "(injected: coordinator down)",
        })

    service._run_dml = unreachable
    status, body, _ = _post(rport, dml, tenant="w")
    assert status == 500 and body["failure_kind"] == faults.IO_TRANSIENT
    # the circuit opened; /statusz names the degraded capability
    assert "dml" in router.fleet_snapshot()["degraded"]
    reqs = router.fleet_snapshot()["replicas"][0]["requests"]
    # further DML fast-fails AT THE EDGE (no replica round trip, no
    # per-request timeout), classified retryable
    status, body, _ = _post(rport, dml, tenant="w")
    assert status == 503 and body["status"] == "failed"
    assert body["failure_kind"] == faults.IO_TRANSIENT
    assert body["degraded"] == "dml"
    assert router.fleet_snapshot()["replicas"][0]["requests"] == reqs
    # pinned reads keep serving the whole time
    status, sel, _ = _post(rport, {"sql": QUERY})
    assert status == 200 and sel["status"] == "completed"
    # coordinator returns: after the cooldown ONE half-open probe rides
    # through; its success closes the circuit
    service._run_dml = real_run_dml
    time.sleep(0.25)
    status, body, _ = _post(rport, dml, tenant="w")
    assert status == 200 and body["status"] == "completed"
    assert router.fleet_snapshot()["degraded"] == {}


# ---------------------------------------------------------------------------
# fleet lifecycle: rolling drain + reload with zero dropped requests
# ---------------------------------------------------------------------------


def test_rolling_fleet_reload_drops_nothing(fleet):
    _, p1, _ = fleet.replica()
    _, p2, _ = fleet.replica()
    router, rport = fleet.router([p1, p2])
    stop = threading.Event()
    results = []

    def client():
        while not stop.is_set():
            status, body, _ = _post(rport, {"sql": POINT}, tenant="roll")
            results.append((status, body.get("status")))

    t = threading.Thread(target=client, daemon=True)
    t.start()
    time.sleep(0.2)  # traffic in flight before the roll starts
    status, body, _ = _post(rport, {}, path="/fleet/reload")
    stop.set()
    t.join(30)
    assert status == 200
    assert body["ok"] is True and body["rolled"] == 2
    for rec in body["replicas"]:
        assert rec["drained"] is True and rec["reloaded"] is True
    # ZERO dropped client requests across the whole roll
    assert results and all(s == 200 for s, _ in results)
    # both replicas are back in rotation (reload re-opened admission)
    assert _get(p1, "/healthz")[0] == 200
    assert _get(p2, "/healthz")[0] == 200
    view = router.fleet_snapshot()
    assert all(not r["draining"] for r in view["replicas"])
    # the router itself drains via its own verb
    status, body, _ = _post(rport, {}, path="/drain")
    assert status == 200 and router.draining is True
    status, _, _ = _post(rport, {"sql": POINT})
    assert status == 503


# ---------------------------------------------------------------------------
# observability: the router hop joins the request's trace
# ---------------------------------------------------------------------------


def test_route_hop_joins_the_request_trace(fleet, tmp_path):
    from nds_tpu.obs import reader as R

    trace = tmp_path / "trace"
    _, p1, _ = fleet.replica(conf={"engine.trace_dir": str(trace)})
    router, rport = fleet.router([p1], trace_dir=trace)
    status, body, _ = _post(rport, {"sql": QUERY}, tenant="tr")
    assert status == 200
    rid = body["request_id"]
    evs = R.read_events(str(trace), strict=True)
    assert R.validate_events(evs) == []
    mine = [e for e in evs if e.get("trace_id") == rid]
    kinds = {e["kind"] for e in mine}
    # ONE trace_id spans the router hop AND the replica's execution
    assert {"route_request", "serve_request", "query_span"} <= kinds
    route_ev = [e for e in mine if e["kind"] == "route_request"][0]
    assert route_ev["tenant"] == "tr"
    assert route_ev["status"] == "completed"
    assert route_ev["attempts"] == 1
    assert route_ev["replica"] == f"127.0.0.1:{p1}"
    assert route_ev["queue_ms"] >= 0 and route_ev["forward_ms"] >= 0


# ---------------------------------------------------------------------------
# CLI construction seams
# ---------------------------------------------------------------------------


def test_cli_build_router_wires_listener_and_fleet_provider():
    import argparse

    from nds_tpu.cli.route import build_router

    args = argparse.Namespace(
        replica=["127.0.0.1:9", "127.0.0.1:11"], port=0,
        mesh_replica="127.0.0.1:11", property_file=None,
    )
    router, server = build_router(args)
    try:
        assert server.port > 0
        assert [r.mesh for r in router.replicas] == [False, True]
        # /statusz's fleet section is the router's live view
        fl = M.shared_sink().status_snapshot()["fleet"]
        assert len(fl["replicas"]) == 2
        assert fl["tenant_cap"] == router.tenant_cap
    finally:
        router.close()


def test_cli_serve_aot_cache_dir_flag(tmp_path, monkeypatch):
    import argparse

    from nds_tpu.cli.serve import build_service

    monkeypatch.setenv("NDS_AOT_CACHE_DIR", "0")  # restored at teardown
    wh = tmp_path / "wh"
    wh.mkdir()
    LakehouseTable.create(str(wh / "store_sales"), _fact_table(4))
    aot = str(tmp_path / "aot")
    args = argparse.Namespace(
        warehouse_path=str(wh), input_format="lakehouse", port=0,
        property_file=None, stream=None, job_dir=None, floats=False,
        aot_cache_dir=aot,
    )
    service, server = build_service(args)
    try:
        import os

        assert os.environ["NDS_AOT_CACHE_DIR"] == aot
        # the session armed the shared cache — N replicas pointed at one
        # warmed dir deserialize instead of compiling
        assert service.session.aot_cache is not None
    finally:
        service.close()


def test_cache_warm_fleet_flag_accepted(tmp_path):
    from nds_tpu.cli import cache as cache_cli

    empty = tmp_path / "empty"
    empty.mkdir()
    rc = cache_cli.main([
        "warm", str(empty), "nope.sql",
        "--cache_dir", str(tmp_path / "c"), "--fleet", "--json",
    ])
    assert rc == 2  # parsed fine; failed on the empty warehouse
