"""Unit tests for the device kernel library against numpy oracles.

This exceeds the reference's test strategy on purpose (SURVEY.md §4: the
reference has no unit tests; we unit-test every kernel)."""

import jax.numpy as jnp
import numpy as np
import pytest

from nds_tpu.engine.columnar import bucket_cap
from nds_tpu.ops import kernels as K

rng = np.random.default_rng(42)


def _pad(a, cap, fill=0):
    return np.concatenate([a, np.full(cap - len(a), fill, a.dtype)])


def _live(n, cap):
    return jnp.arange(cap) < n


class TestCompact:
    def test_compact(self):
        n, cap = 1000, 1024
        mask = rng.random(cap) < 0.3
        mask[n:] = False
        count = K.mask_count(jnp.asarray(mask))
        assert count == mask.sum()
        idx = K.compact_indices(jnp.asarray(mask), bucket_cap(count))
        np.testing.assert_array_equal(
            np.asarray(idx)[:count], np.nonzero(mask)[0]
        )


class TestSort:
    def test_single_key_asc(self):
        n, cap = 900, 1024
        data = rng.integers(0, 100, cap).astype(np.int64)
        order = K.sort_indices(
            [(jnp.asarray(data), None, True, True)], _live(n, cap)
        )
        got = data[np.asarray(order)[:n]]
        np.testing.assert_array_equal(got, np.sort(data[:n]))

    def test_desc_and_nulls(self):
        n, cap = 500, 512
        data = rng.integers(0, 50, cap).astype(np.int64)
        valid = rng.random(cap) < 0.8
        order = K.sort_indices(
            [(jnp.asarray(data), jnp.asarray(valid), False, False)],
            _live(n, cap),
        )
        o = np.asarray(order)[:n]
        vals, vs = data[o], valid[o]
        # all invalids at the end (nulls last), values descending before that
        k = vs.sum()
        assert (~vs[k:]).all()
        assert (np.diff(vals[:k]) <= 0).all()

    def test_multi_key_stability(self):
        n = cap = 1024
        k1 = rng.integers(0, 4, cap).astype(np.int64)
        k2 = rng.integers(0, 1000, cap).astype(np.int64)
        order = np.asarray(
            K.sort_indices(
                [
                    (jnp.asarray(k1), None, True, True),
                    (jnp.asarray(k2), None, False, True),
                ],
                _live(n, cap),
            )
        )
        expect = np.lexsort((-k2, k1))
        np.testing.assert_array_equal(k1[order], k1[expect])
        np.testing.assert_array_equal(k2[order], k2[expect])


class TestGroup:
    def test_group_and_sum(self):
        n, cap = 3000, 4096
        keys = rng.integers(0, 37, cap).astype(np.int64)
        vals = rng.integers(0, 1000, cap).astype(np.int64)
        live = _live(n, cap)
        order, gid, ng = K.group_rows([jnp.asarray(keys)], [None], live)
        assert ng == len(np.unique(keys[:n]))
        o = np.asarray(order)
        sums = K.segment_reduce(
            jnp.asarray(vals)[order],
            gid,
            live[order],
            bucket_cap(ng),
            "sum",
        )
        expect = {k: vals[:n][keys[:n] == k].sum() for k in np.unique(keys[:n])}
        got_keys = keys[o[:n]][np.unique(np.asarray(gid)[:n], return_index=True)[1]]
        for g, k in enumerate(sorted(expect)):
            assert int(np.asarray(sums)[g]) == expect[k], (g, k)

    def test_group_nulls_form_one_group(self):
        n = cap = 1024
        keys = rng.integers(0, 5, cap).astype(np.int64)
        valid = rng.random(cap) < 0.7
        order, gid, ng = K.group_rows(
            [jnp.asarray(keys)], [jnp.asarray(valid)], _live(n, cap)
        )
        n_distinct = len(np.unique(keys[valid])) + (1 if (~valid).any() else 0)
        assert ng == n_distinct

    def test_min_max_count(self):
        n = cap = 2048
        keys = rng.integers(0, 10, cap).astype(np.int64)
        vals = rng.normal(size=cap)
        live = _live(n, cap)
        order, gid, ng = K.group_rows([jnp.asarray(keys)], [None], live)
        svals = jnp.asarray(vals)[order]
        w = live[order]
        mins = np.asarray(K.segment_reduce(svals, gid, w, bucket_cap(ng), "min"))
        maxs = np.asarray(K.segment_reduce(svals, gid, w, bucket_cap(ng), "max"))
        counts = np.asarray(K.segment_reduce(svals, gid, w, bucket_cap(ng), "count"))
        o = np.asarray(order)
        for g in range(ng):
            k = keys[o[np.asarray(gid)[:n] == g][0]]
            sel = vals[:n][keys[:n] == k]
            assert mins[g] == pytest.approx(sel.min())
            assert maxs[g] == pytest.approx(sel.max())
            assert counts[g] == len(sel)


class TestJoin:
    def _join_np(self, lk, rk):
        pairs = []
        for i, k in enumerate(lk):
            for j, k2 in enumerate(rk):
                if k == k2:
                    pairs.append((i, j))
        return set(pairs)

    def test_inner_join(self):
        ln, lcap = 700, 1024
        rn, rcap = 300, 512
        lk = rng.integers(0, 100, lcap).astype(np.int64)
        rk = rng.integers(0, 100, rcap).astype(np.int64)
        li, ri, pl, total = K.join_candidates(
            [jnp.asarray(lk)], [None], _live(ln, lcap),
            [jnp.asarray(rk)], [None], _live(rn, rcap),
        )
        ok = K.verify_pairs(
            li, ri, pl,
            [jnp.asarray(lk)], [None], _live(ln, lcap),
            [jnp.asarray(rk)], [None], _live(rn, rcap),
        )
        got = {
            (int(a), int(b))
            for a, b, m in zip(np.asarray(li), np.asarray(ri), np.asarray(ok))
            if m
        }
        assert got == self._join_np(lk[:ln], rk[:rn])

    def test_multi_key_join_with_nulls(self):
        ln = lcap = 512
        rn = rcap = 512
        lk1 = rng.integers(0, 20, lcap).astype(np.int64)
        lk2 = rng.integers(0, 5, lcap).astype(np.int64)
        rk1 = rng.integers(0, 20, rcap).astype(np.int64)
        rk2 = rng.integers(0, 5, rcap).astype(np.int64)
        lv = rng.random(lcap) < 0.9
        li, ri, pl, _ = K.join_candidates(
            [jnp.asarray(lk1), jnp.asarray(lk2)], [jnp.asarray(lv), None], _live(ln, lcap),
            [jnp.asarray(rk1), jnp.asarray(rk2)], [None, None], _live(rn, rcap),
        )
        ok = K.verify_pairs(
            li, ri, pl,
            [jnp.asarray(lk1), jnp.asarray(lk2)], [jnp.asarray(lv), None], _live(ln, lcap),
            [jnp.asarray(rk1), jnp.asarray(rk2)], [None, None], _live(rn, rcap),
        )
        got = {
            (int(a), int(b))
            for a, b, m in zip(np.asarray(li), np.asarray(ri), np.asarray(ok))
            if m
        }
        expect = {
            (i, j)
            for i in range(ln)
            if lv[i]
            for j in range(rn)
            if lk1[i] == rk1[j] and lk2[i] == rk2[j]
        }
        assert got == expect

    def test_semi_anti_mask(self):
        ln = lcap = 256
        rn = rcap = 128
        lk = rng.integers(0, 400, lcap).astype(np.int64)
        rk = rng.integers(0, 400, rcap).astype(np.int64)
        li, ri, pl, _ = K.join_candidates(
            [jnp.asarray(lk)], [None], _live(ln, lcap),
            [jnp.asarray(rk)], [None], _live(rn, rcap),
        )
        ok = K.verify_pairs(
            li, ri, pl,
            [jnp.asarray(lk)], [None], _live(ln, lcap),
            [jnp.asarray(rk)], [None], _live(rn, rcap),
        )
        present = np.asarray(K.matched_mask(li, ok, lcap))
        expect = np.isin(lk, rk[:rn])
        np.testing.assert_array_equal(present[:ln], expect[:ln])


class TestWindow:
    def test_running_position(self):
        gid = jnp.asarray(np.array([0, 0, 0, 1, 1, 2, 3, 3, 3, 3], np.int32))
        pos = np.asarray(K.running_position(gid))
        np.testing.assert_array_equal(pos, [0, 1, 2, 0, 1, 0, 0, 1, 2, 3])

    def test_segment_starts(self):
        gid = jnp.asarray(np.array([0, 0, 1, 1, 1, 2], np.int32))
        s = np.asarray(K.segment_starts(gid, 4))
        np.testing.assert_array_equal(s[:3], [0, 2, 5])


def test_sort_indices_single_key_max_value_ties_with_dead_tail():
    """The one-operand fast path folds dead rows to int64 max; stability
    must keep a LIVE max-valued row ahead of the dead tail."""
    import jax.numpy as jnp
    from nds_tpu.ops import kernels as K

    big = np.iinfo(np.int64).max
    data = jnp.asarray([5, big, 1, 777, 888], dtype=jnp.int64)  # idx 3,4 dead
    live = jnp.asarray([True, True, True, False, False])
    order = np.asarray(K.sort_indices([(data, None, True, True)], live))
    assert order.tolist()[:3] == [2, 0, 1]  # live sorted; big stays live-first
    assert set(order.tolist()[3:]) == {3, 4}

    # descending single key
    order = np.asarray(K.sort_indices([(data, None, False, True)], live))
    assert order.tolist()[:3] == [1, 0, 2]
