"""Regression tests for review findings: OR-disjunct subqueries (mark joins),
CTE visibility in subqueries, bare count(*), intersect nullability, right-join
residuals, window frames."""

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.register_arrow(
        "a",
        pa.table({"k": pa.array([1, 3], pa.int32()), "x": pa.array([10, 0], pa.int32())}),
    )
    s.register_arrow("b", pa.table({"k": pa.array([1], pa.int32())}))
    s.register_arrow(
        "nn",
        pa.table({"x": pa.array([1, 2, None, 4], pa.int32())}),
    )
    s.register_arrow("mm", pa.table({"x": pa.array([2, 4, 5], pa.int32())}))
    s.register_arrow(
        "j1",
        pa.table({"k": pa.array([1, 2], pa.int32()), "x": pa.array([10, 0], pa.int32())}),
    )
    s.register_arrow(
        "j2",
        pa.table({"k": pa.array([1, 2], pa.int32()), "y": pa.array([5, 5], pa.int32())}),
    )
    s.register_arrow(
        "w",
        pa.table(
            {
                "g": pa.array([1, 1, 1], pa.int32()),
                "o": pa.array([1, 2, 3], pa.int32()),
                "v": pa.array([1, 10, 100], pa.int32()),
            }
        ),
    )
    return s


def test_exists_under_or(sess):
    out = sess.sql(
        "select count(*) c from a where x = 0 or exists "
        "(select 1 from b where b.k = a.k)"
    ).collect()
    assert out.column("c").to_pylist() == [2]


def test_two_exists_or(sess):
    out = sess.sql(
        "select count(*) c from a where exists (select 1 from b where b.k = a.k)"
        " or exists (select 1 from mm where mm.x = a.k)"
    ).collect()
    # k=1 matches b; k=3 matches neither (mm has 2,4,5)
    assert out.column("c").to_pylist() == [1]


def test_cte_in_subquery(sess):
    out = sess.sql(
        """
        with v as (select k from b)
        select count(*) c from a where k in (select k from v)
        """
    ).collect()
    assert out.column("c").to_pylist() == [1]


def test_bare_count_star(sess):
    out = sess.sql("select count(*) c from a").collect()
    assert out.column("c").to_pylist() == [2]


def test_intersect_nullability_mismatch(sess):
    out = sess.sql(
        "select x from nn intersect select x from mm order by x"
    ).collect()
    assert out.column("x").to_pylist() == [2, 4]
    out2 = sess.sql(
        "select x from nn except select x from mm order by x nulls last"
    ).collect()
    assert out2.column("x").to_pylist() == [1, None]


def test_right_join_residual(sess):
    out = sess.sql(
        "select j2.k kk, j1.x from j1 right join j2 on j1.k = j2.k and j1.x < j2.y"
        " order by kk"
    ).collect()
    rows = out.to_pylist()
    assert rows == [{"kk": 1, "x": None}, {"kk": 2, "x": 0}]


def test_window_running_default_range(sess):
    out = sess.sql(
        "select o, sum(v) over (partition by g order by o) s from w order by o"
    ).collect()
    assert out.column("s").to_pylist() == [1, 11, 111]


def test_window_rows_bounded(sess):
    out = sess.sql(
        "select o, sum(v) over (partition by g order by o "
        "rows between 1 preceding and current row) s from w order by o"
    ).collect()
    assert out.column("s").to_pylist() == [1, 11, 110]


def test_window_rows_centered(sess):
    out = sess.sql(
        "select o, avg(v) over (partition by g order by o "
        "rows between 1 preceding and 1 following) s from w order by o"
    ).collect()
    got = out.column("s").to_pylist()
    assert got == [pytest.approx(5.5), pytest.approx(37.0), pytest.approx(55.0)]


def test_window_range_peers(sess):
    # ties in the order key: RANGE default includes peers
    s2 = Session()
    s2.register_arrow(
        "t",
        pa.table(
            {
                "o": pa.array([1, 1, 2], pa.int32()),
                "v": pa.array([1, 10, 100], pa.int32()),
            }
        ),
    )
    out = s2.sql("select o, sum(v) over (order by o) s from t order by o").collect()
    assert out.column("s").to_pylist() == [11, 11, 111]


# ---- second review round regressions ---------------------------------------


def test_not_in_with_nulls_in_subquery(sess):
    # SQL 3VL: NOT IN over a set containing NULL is never TRUE
    out = sess.sql(
        "select count(*) c from mm where x not in (select x from nn)"
    ).collect()
    assert out.column("c").to_pylist() == [0]
    # without nulls it behaves as plain anti join
    out2 = sess.sql(
        "select count(*) c from mm where x not in (select x from nn where x is not null)"
    ).collect()
    assert out2.column("c").to_pylist() == [1]  # only 5 not in {1,2,4}


def test_scalar_subquery_alias_collision(sess):
    out = sess.sql(
        "select count(*) c from j1 where x > (select avg(x) x from j1)"
    ).collect()
    assert out.column("c").to_pylist() == [1]  # avg=5; only 10 > 5


def test_float_join_keys():
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    s = Session()
    s.register_arrow("fa", pa.table({"f": pa.array([1.5, 1.7, 2.0])}))
    s.register_arrow("fb", pa.table({"f": pa.array([1.5, 2.0, 1.6])}))
    out = s.sql(
        "select count(*) c from fa, fb where fa.f = fb.f"
    ).collect()
    assert out.column("c").to_pylist() == [2]


def test_empty_rows_frame(sess):
    out = sess.sql(
        "select o, sum(v) over (partition by g order by o "
        "rows between 2 preceding and 1 preceding) s from w order by o"
    ).collect()
    assert out.column("s").to_pylist() == [None, 1, 11]


def test_flattened_on_scope():
    """Inner-JOIN flattening must bind ON conjuncts in the join's own
    operand scope: a bare column that collides with a sibling FROM item
    stays unambiguous, and forward references stay rejected."""
    import pyarrow as pa
    import pytest as _pt

    from nds_tpu.engine.binder import BindError
    from nds_tpu.engine.session import Session

    s = Session()
    s.register_arrow("sa", pa.table({"x": pa.array([1, 2], pa.int32())}))
    s.register_arrow("sb", pa.table({"bx": pa.array([1, 2], pa.int32())}))
    s.register_arrow("sc", pa.table(
        {"x": pa.array([9], pa.int32()), "cy": pa.array([7], pa.int32())}
    ))
    out = s.sql("select count(*) c from sa join sb on x = bx, sc").collect()
    assert out.column("c").to_pylist() == [2]
    with _pt.raises(BindError):
        s.sql("select * from sa join sb on sa.x = sc.cy, sc").collect()


def test_left_join_null_rejection_promotion():
    """TPC-DS q93 shape: a WHERE equality against a LEFT JOIN's right side
    null-rejects it, so the planner may treat the join as inner — the
    MultiJoin core must not disconnect into a cross join and results must
    match the filtered-inner semantics."""
    import pyarrow as pa

    from nds_tpu.engine.session import Session

    s = Session()
    s.register_arrow("f", pa.table({
        "k": pa.array([1, 2, 3], pa.int32()),
        "t": pa.array([10, 20, 30], pa.int32()),
    }))
    s.register_arrow("r", pa.table({
        "k2": pa.array([1, 3], pa.int32()),
        "rs": pa.array([5, 6], pa.int32()),
    }))
    s.register_arrow("d", pa.table({"rid": pa.array([5], pa.int32())}))
    out = s.sql(
        "select count(*) c, sum(t) st from f "
        "left outer join r on (k2 = k), d where rs = rid"
    ).collect()
    # only k=1 survives (rs=5 matches rid=5); k=2's null rs is rejected
    assert out.column("c").to_pylist() == [1]
    assert out.column("st").to_pylist() == [10]


def test_left_join_stays_outer_without_rejection():
    """Without a null-rejecting WHERE reference the LEFT JOIN must keep
    its null-extended rows (q72 shape: right side only read via IS NULL
    in the SELECT list)."""
    import pyarrow as pa

    from nds_tpu.engine.session import Session

    s = Session()
    s.register_arrow("f2", pa.table({"k": pa.array([1, 2], pa.int32())}))
    s.register_arrow("p2", pa.table({"pk": pa.array([1], pa.int32())}))
    out = s.sql(
        "select sum(case when pk is null then 1 else 0 end) nn, count(*) c "
        "from f2 left outer join p2 on (pk = k)"
    ).collect()
    assert out.column("c").to_pylist() == [2]
    assert out.column("nn").to_pylist() == [1]
