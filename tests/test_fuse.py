"""Fused operator pipelines + shape-bucketed executable reuse (engine/fuse.py).

Contract under test: a plan's Filter/Project chains collapse into Pipeline
nodes whose fused (single-jit) execution is BIT-IDENTICAL to the eager
per-stage path — across nulls, strings, decimals, empty inputs and bucket
boundaries — while structurally identical executions reuse compiled
executables (observable through exec_cache trace events), donation +
OOM-recovery wipes stay safe, and the chains the fuser must not touch
(blocked union-aggregation wrappers, shared CTE subtrees, untraceable
host-side casts) keep their exact prior semantics.

Satellite regressions ride along: Limit-over-Sort top-k gather, the
MultiJoin join-order replay memo, and the SF10 bench isolation helpers.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import fuse as F
from nds_tpu.engine import plan as P
from nds_tpu.engine.session import Session

rng = np.random.default_rng(7)


def _table(n, seed=0):
    r = np.random.default_rng(seed)
    ks = r.integers(0, 15, n)
    vs = r.integers(-80, 80, n)
    from decimal import Decimal

    return pa.table(
        {
            "k": pa.array(
                [None if i % 11 == 0 else int(v) for i, v in enumerate(ks)],
                pa.int32(),
            ),
            "v": pa.array(
                [None if i % 7 == 3 else int(v) for i, v in enumerate(vs)],
                pa.int64(),
            ),
            "cat": pa.array(
                [
                    None if i % 13 == 5 else ["Books", "Music", "Shoes", "Home"][int(x) % 4]
                    for i, x in enumerate(ks)
                ],
                pa.string(),
            ),
            "amt": pa.array(
                [Decimal(int(v) * 3) / 100 for v in vs], pa.decimal128(7, 2)
            ),
            "d": pa.array(
                [10957 + int(x) * 37 for x in ks], pa.int32()
            ),
        }
    )


def _sessions(n=2000, conf=None, conf_off=None):
    on = Session(conf=dict(conf or {}))
    off = Session(conf=dict(conf_off or {}, **{"engine.fuse": "off"}))
    t = _table(n)
    u = _table(n, seed=1)
    for s in (on, off):
        s.register_arrow("t", t)
        s.register_arrow("u", u)
    return on, off


EQUALITY_QUERIES = [
    # plain filter chain (mask-only pipeline, count mode)
    "select k, v from t where v > 10 and k is not null order by k, v",
    # filter + computed projection (string LIKE over dictionary)
    "select k, v * 2 vv, cat from t where cat like 'B%' and v between -50 and 50 "
    "order by k, vv",
    # IN list + CASE + decimal arithmetic
    "select k, case when v > 0 then amt else amt * -1 end aa from t "
    "where cat in ('Books', 'Shoes') order by k, aa",
    # null-sensitive predicates (three-valued logic through the fused mask)
    "select k, v from t where v <> 3 or k = 5 order by k, v",
    # chain feeding an aggregate (partial-agg input arrives fused)
    "select k, sum(v) sv, count(*) c, avg(amt) aa from t where v > -60 "
    "group by k order by k",
    # post-join linear wrappers (pipeline over a join output)
    "select x.k, x.s from (select t.k \"k\", t.v + u.v s from t, u "
    "where t.k = u.k and t.v > u.v) x where x.s > 20 order by x.k, x.s",
    # date function + projection-only pipeline
    "select k, year(cast(d as date)) y from t where v >= 0 order by k, y",
    # empty result through the fused mask
    "select k, v from t where v > 1000 order by k",
]


@pytest.mark.parametrize("qi", range(len(EQUALITY_QUERIES)))
def test_fused_path_equality(qi):
    q = EQUALITY_QUERIES[qi]
    on, off = _sessions()
    a = on.sql(q).collect()
    b = off.sql(q).collect()
    assert a.equals(b), q


def test_float_division_within_validator_epsilon():
    """The one permitted fused/unfused divergence: float64 expression
    chains may differ in the FINAL ULP (XLA's algebraic simplifier
    reassociates division chains it can see whole). Pin the bound at
    1e-12 relative — four orders of magnitude inside the validator's 1e-5
    epsilon contract (nds_tpu/validate.py:compare)."""
    import math

    on, off = _sessions()
    q = ("select k, sum(v) * 100 / (1 + sum(amt)) r from t "
         "where v > -70 group by k order by k")
    a = on.sql(q).collect().to_pylist()
    b = off.sql(q).collect().to_pylist()
    assert len(a) == len(b) and a
    for x, y in zip(a, b):
        assert x["k"] == y["k"]
        if x["r"] is None or y["r"] is None:
            assert x["r"] == y["r"]
        else:
            assert math.isclose(x["r"], y["r"], rel_tol=1e-12)


def test_fused_over_empty_table():
    on, off = _sessions()
    empty = _table(0)
    for s in (on, off):
        s.register_arrow("e", empty)
    q = "select k, v + 1 vv from e where v > 0 order by k"
    assert on.sql(q).collect().equals(off.sql(q).collect())


@pytest.mark.parametrize("n", [1023, 1024, 1025])
def test_bucket_boundary_rows(n):
    on, off = _sessions(n=n)
    q = ("select k, v - 1 w from t where v > 0 and k is not null "
         "order by k, w")
    assert on.sql(q).collect().equals(off.sql(q).collect())


def test_mark_pipelines_plan_shape():
    s, _ = _sessions()
    r = s.sql("select k, v * 2 vv from t where v > 0 and cat like 'B%'")
    # the chain collapsed into one Pipeline over the scan
    pipes = []

    def walk(n):
        if isinstance(n, P.Pipeline):
            pipes.append(n)
        for c in n.children():
            if c is not None:
                walk(c)

    walk(r.plan)
    assert len(pipes) == 1
    p = pipes[0]
    assert isinstance(p.child, P.Scan)
    # execution order: filter first, projection last
    assert isinstance(p.stages[0], P.Filter)
    assert isinstance(p.stages[-1], P.Project)
    assert all(st.child is None for st in p.stages)
    # scans alias catalog buffers: never donation-eligible
    assert p.donate_ok is False
    assert "Pipeline" in r.explain()


def test_pure_rename_chain_not_fused():
    s, _ = _sessions()
    r = s.sql("select k kk, v from t")
    assert not isinstance(r.plan, P.Pipeline)


def test_executable_reuse_and_trace_events(tmp_path):
    s = Session(conf={"engine.trace_dir": str(tmp_path)})
    s.register_arrow("t", _table(2000))
    q = "select k, v + 1 vv from t where v > 0 order by k, vv"
    s.sql(q).collect()
    s.conf["engine.plan_cache"] = "off"
    s.sql(q).collect()
    evs = [
        json.loads(line)
        for line in open(s.tracer.path, encoding="utf-8")
        if line.strip()
    ]
    ec = [e for e in evs if e["kind"] == "exec_cache"]
    ps = [e for e in evs if e["kind"] == "pipeline_span"]
    assert ec and ps
    assert ec[0]["hit"] is False and ec[-1]["hit"] is True
    assert all(e["fused"] for e in ps)
    assert all(isinstance(e["bucket"], int) for e in ec)


def test_executable_reuse_across_scale_factors():
    """Same structure + different SF (row count/bucket) => the SAME traced
    entry serves both; the trace machinery is not rebuilt (VERDICT items
    4+5: compiled-executable reuse across a stream)."""
    s = Session()
    s.register_arrow("t", _table(1500))
    q = "select k, v + 1 vv from t where v > 0 and k < 10 order by k, vv"
    expect_small = s.sql(q).collect()
    assert len(s.exec_cache.map) == 1
    entry_small = next(iter(s.exec_cache.map.values()))
    # "SF up": re-register the same schema at 8x the rows (numeric columns
    # carry no dictionaries, so the input signature is identical)
    s.register_arrow("t", _table(12000, seed=3))
    s.sql(q).collect()
    assert len(s.exec_cache.map) == 1  # same entry, no rebuild
    assert next(iter(s.exec_cache.map.values())) is entry_small
    # bucket accounting: two distinct buckets compiled, zero->more hits on
    # re-run
    assert s.exec_cache.misses >= 2
    s.conf["engine.plan_cache"] = "off"
    hits0 = s.exec_cache.hits
    s.sql(q).collect()
    assert s.exec_cache.hits > hits0
    # and the small result is reproducible after switching back
    s.register_arrow("t", _table(1500))
    assert s.sql(q).collect().equals(expect_small)


def test_unfusible_chain_pins_to_eager():
    """A numeric->string cast formats device values on host: the chain
    cannot trace, the build is attempted once, and results match the
    unfused path exactly."""
    on, off = _sessions()
    q = "select cast(v as varchar(10)) sv, k from t where v > 0 order by k, sv"
    assert on.sql(q).collect().equals(off.sql(q).collect())
    pinned = [v for v in on.exec_cache.map.values() if v is None]
    assert pinned  # the signature is pinned, not re-attempted
    # re-run still correct (eager fallback path)
    on.conf["engine.plan_cache"] = "off"
    assert on.sql(q).collect().equals(off.sql(q).collect())


def test_scalar_subquery_stays_unfused_and_correct():
    on, off = _sessions()
    q = ("select k, v from t where v > (select avg(v) from u) "
         "order by k, v")
    assert on.sql(q).collect().equals(off.sql(q).collect())


def test_blocked_union_agg_still_blocked_with_fusion():
    """The fused wrappers must stay visible to the blocked union-agg shape
    check (plan._peel_wrappers expands Pipeline nodes), and windowed
    results must equal the unfused oracle."""
    conf = {"engine.union_agg_window_rows": 512}
    on = Session(conf=dict(conf))
    off = Session(conf=dict(conf, **{"engine.fuse": "off"}))
    for s in (on, off):
        s.register_arrow("t", _table(3000))
        s.register_arrow("u", _table(3000, seed=1))
    q = """
    select k, sum(v) sv, count(*) c, avg(v) av
    from (select k, v from t where v > -70
          union all
          select k, v from u) x
    where v < 70
    group by k order by k
    """
    ra = on.sql(q)
    a = ra.collect()
    assert a.equals(off.sql(q).collect())
    # the blocked path actually engaged under fusion
    assert ra.executor.last_blocked_union is not None
    assert ra.executor.last_blocked_union["windows"] > 1


def test_donation_safety_and_oom_wipe():
    """fuse_donate=on over a join-fed pipeline (donate-eligible child):
    results stable across reruns, and an OOM-recovery wipe (new catalog
    buffers, new signatures) neither crashes nor changes results."""
    conf = {"engine.fuse_donate": "on"}
    on = Session(conf=dict(conf))
    off = Session(conf={"engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(2000))
        s.register_arrow("u", _table(2000, seed=1))
    q = ("select x.k, x.s + 1 s1 from (select t.k \"k\", t.v + u.v s "
         "from t, u where t.k = u.k and t.v > u.v) x where x.s > 10 "
         "order by x.k, s1")
    expect = off.sql(q).collect()
    assert on.sql(q).collect().equals(expect)
    on.conf["engine.plan_cache"] = "off"
    assert on.sql(q).collect().equals(expect)
    on.recover_memory("test: simulated OOM wipe")
    assert on.sql(q).collect().equals(expect)


def test_limit_over_sort_topk():
    on, off = _sessions()
    for q in (
        "select k, v from t order by v desc, k limit 7",
        "select k, v from t where v > 0 order by k, v limit 1",
        # limit beyond the row count
        "select k, v from t where v > 78 order by v, k limit 500",
        "select cat, amt from t order by cat, amt limit 13",
    ):
        assert on.sql(q).collect().equals(off.sql(q).collect()), q


def test_join_order_replay_memo():
    on, _ = _sessions()
    q = ("select t.k, sum(t.v) s from t, u where t.k = u.k and u.v > 0 "
         "group by t.k order by t.k")
    a = on.sql(q).collect()
    assert len(on.join_order_cache) >= 1
    recorded = [v for v in on.join_order_cache.values() if "steps" in v]
    assert recorded
    on.conf["engine.plan_cache"] = "off"
    assert on.sql(q).collect().equals(a)  # replayed order, same result
    # catalog change invalidates the memo
    on.register_arrow("w", _table(100))
    assert on.join_order_cache == {}


def test_sf10_isolation_helpers():
    import bench

    assert bench._last_json_line("junk\n{\"a\": 1}\nnot json") == {"a": 1}
    assert bench._last_json_line("") is None
    assert bench._OOM_EXIT_RC == 17


def test_input_signature_dictionary_identity():
    s, _ = _sessions()
    t = s.catalog.load("t")
    sig1 = F.input_signature(t)
    sig2 = F.input_signature(s.catalog.load("t"))
    assert sig1 == sig2  # cached catalog columns: same dictionary objects
