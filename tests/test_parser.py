"""SQL parser tests over representative TPC-DS query shapes."""

import pytest

from nds_tpu.engine import expr as E
from nds_tpu.engine.sql import ast as A
from nds_tpu.engine.sql.parser import parse_sql, parse_script


def test_simple_select():
    s = parse_sql("select a, b as x from t where a > 1 order by x limit 10")
    assert isinstance(s, A.SelectStmt)
    assert len(s.select_items) == 2
    assert s.select_items[1][1] == "x"
    assert s.limit == 10
    assert isinstance(s.where, E.BinOp)


def test_q3_shape():
    sql = """
    select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
           sum(ss_ext_sales_price) sum_agg
    from date_dim dt, store_sales, item
    where dt.d_date_sk = store_sales.ss_sold_date_sk
      and store_sales.ss_item_sk = item.i_item_sk
      and item.i_manufact_id = 128
      and dt.d_moy = 11
    group by dt.d_year, item.i_brand, item.i_brand_id
    order by dt.d_year, sum_agg desc, brand_id
    limit 100
    """
    s = parse_sql(sql)
    assert len(s.from_items) == 3
    assert len(s.group_by) == 3
    assert s.order_by[1].ascending is False
    agg = s.select_items[3][0]
    assert isinstance(agg, E.Agg) and agg.fn == "sum"


def test_cte_and_union():
    sql = """
    with a as (select 1 x), b as (select 2 x)
    select x from a union all select x from b order by x
    """
    s = parse_sql(sql)
    assert len(s.ctes) == 2
    assert s.set_ops[0][0] == "union all"


def test_explicit_join():
    s = parse_sql(
        "select * from t1 left outer join t2 on t1.a = t2.b join t3 on t3.c = t1.a"
    )
    j = s.from_items[0]
    assert isinstance(j, A.JoinClause)
    assert j.kind == "inner"
    assert j.left.kind == "left"


def test_case_between_in_like():
    sql = """
    select case when a between 1 and 2 then 'lo'
                when a in (3,4,5) then 'mid'
                else 'hi' end c
    from t where s like 'a%' and s not like '%z'
    """
    s = parse_sql(sql)
    c = s.select_items[0][0]
    assert isinstance(c, E.Case)
    assert isinstance(c.branches[0][0], E.Between)
    assert isinstance(c.branches[1][0], E.InList)
    assert isinstance(s.where.right, E.Like) and s.where.right.negated


def test_subqueries():
    sql = """
    select * from t where a in (select x from u)
      and b > (select avg(y) from v)
      and exists (select 1 from w where w.k = t.k)
    """
    s = parse_sql(sql)
    conj = s.where
    assert isinstance(conj.right, E.SubqueryExpr) and conj.right.kind == "exists"


def test_interval_arith():
    s = parse_sql(
        "select * from d where d_date between cast('1999-02-22' as date) "
        "and (cast('1999-02-22' as date) + interval 30 days)"
    )
    b = s.where
    assert isinstance(b, E.Between)
    assert isinstance(b.high, E.Func) and b.high.name == "date_add"


def test_rollup_having():
    sql = """
    select i_category, avg(ss_net_profit) p from store_sales, item
    where ss_item_sk = i_item_sk
    group by rollup(i_category, i_class)
    having avg(ss_net_profit) > 0
    """
    s = parse_sql(sql)
    assert s.rollup and len(s.group_by) == 2
    assert isinstance(s.having, E.BinOp)


def test_window_function():
    sql = """
    select i_category,
      sum(ss_sales_price) over (partition by i_category order by d_date
                                rows between unbounded preceding and current row) csum,
      rank() over (order by sum(ss_net_profit) desc) rk
    from x
    """
    s = parse_sql(sql)
    w = s.select_items[1][0]
    assert isinstance(w, E.WindowFn) and w.fn == "sum"
    assert w.frame == (("unbounded", "preceding"), ("current", None))
    rk = s.select_items[2][0]
    assert rk.fn == "rank" and rk.order_by[0][1] is False


def test_count_distinct_and_star():
    s = parse_sql("select count(*) c, count(distinct cd_gender) g from t")
    c = s.select_items[0][0]
    g = s.select_items[1][0]
    assert c.fn == "count" and c.arg is None
    assert g.distinct


def test_insert_delete_ddl():
    stmts = parse_script(
        """
        create temp view v as select * from t;
        insert into fact select * from v;
        delete from fact where d_sk between 10 and 20;
        drop view v;
        call spark_catalog.system.rollback_to_timestamp('tbl', '2020-01-01');
        """
    )
    kinds = [type(x).__name__ for x in stmts]
    assert kinds == [
        "CreateViewStmt",
        "InsertStmt",
        "DeleteStmt",
        "DropViewStmt",
        "CallStmt",
    ]


def test_intersect():
    s = parse_sql("select a from t1 intersect select a from t2")
    assert s.set_ops[0][0] == "intersect"


def test_decimal_literal():
    s = parse_sql("select * from t where p > 1.25")
    lit = s.where.right
    assert lit.dtype.is_decimal and lit.dtype.scale == 2


def test_nested_parens_from():
    s = parse_sql(
        "select * from (select a from t) x, (select b from u) y where x.a = y.b"
    )
    assert isinstance(s.from_items[0], A.SubqueryRef)
    assert s.from_items[0].alias == "x"


def test_substring_variants():
    s = parse_sql("select substr(s, 1, 2), substring(s, 1, 3) from t")
    assert s.select_items[0][0].name == "substr"
    assert s.select_items[1][0].name == "substr"


def test_quoted_identifiers():
    s = parse_sql('select `weird col`, "other col" from t')
    assert s.select_items[0][0].name == "weird col"
