"""Out-of-core execution: the host-RAM spill pool (engine/spill.py) and the
executor's spilled paths — partitioned hash join, external sort, spilling
distinct (exec._spilled_join/_spilled_take/_spilled_distinct).

Path-equality oracle (the test_blocked_union_agg pattern): every spilled
path must produce results identical to the direct path — bit-identical
ints/strings/decimals, exact row order for sorts (the spilled sort reuses
the direct path's own permutation) — across nulls, strings, decimals and
empty inputs. Plus the robustness wiring: the budgeter's `spill` verdict +
static partition counts, the verifier's spill-annotation invariants, the
report ladder's spill_retry rung (injected-OOM integration), spill-IO fault
backoff, the crash-orphan sweep, and the progress-aware watchdog.
"""

import glob
import json
import os
import subprocess
import time
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine import plan as P
from nds_tpu.engine import spill as SP
from nds_tpu.engine.session import Session, _Entry
from nds_tpu.report import BenchReport


@pytest.fixture(autouse=True)
def _reset_faults(monkeypatch):
    monkeypatch.delenv("NDS_FAULT_SPEC", raising=False)
    monkeypatch.delenv("NDS_SPILL_DIR", raising=False)
    faults.reset()
    yield
    faults.reset()


N = 4000


def _fact(seed):
    r = np.random.default_rng(seed)
    ks = r.integers(1, 40, N)
    vs = r.integers(-50, 50, N)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 13 == 0 else int(x) for i, x in enumerate(ks)],
                pa.int32(),
            ),
            "cat": pa.array(
                [["Books", "Music", "Shoes", None][int(x) % 4] for x in ks]
            ),
            "v": pa.array(
                [None if i % 7 == 0 else int(x) for i, x in enumerate(vs)],
                pa.int32(),
            ),
            "amt": pa.array(
                [Decimal(int(x) * 7) / 100 for x in vs], pa.decimal128(7, 2)
            ),
            "f": pa.array([float(x) / 3 for x in vs], pa.float64()),
        }
    )


def _dup_dim(seed=5):
    # DUPLICATED join keys: keeps the dense/packed fast paths out (they
    # need right-side uniqueness), so the generic sort join — the path the
    # out-of-core rewrite replaces — is what actually runs
    r = np.random.default_rng(seed)
    return pa.table(
        {
            "dk": pa.array(
                [int(x) for x in r.integers(1, 40, 300)], pa.int32()
            ),
            "dv": pa.array([int(x) for x in r.integers(0, 9, 300)], pa.int32()),
        }
    )


def _session(tmp_path, **conf):
    s = Session(conf={"engine.spill_dir": str(tmp_path / "spill"), **conf})
    s.register_arrow("t1", _fact(1))
    s.register_arrow("t2", _fact(2))
    s.register_arrow("d", _dup_dim())
    return s


def _pair(tmp_path, **spill_conf):
    direct = _session(tmp_path, **{"engine.spill": "off"})
    forced = _session(
        tmp_path,
        **{"engine.spill": "force", "engine.spill_partitions": 4, **spill_conf},
    )
    return direct, forced


def _oracle(tmp_path, sql, **spill_conf):
    direct, forced = _pair(tmp_path, **spill_conf)
    want = direct.sql(sql).collect().to_pylist()
    forced.last_spill = None
    got = forced.sql(sql).collect().to_pylist()
    assert got == want, (sql, want[:3], got[:3])
    return forced


# ---------------------------------------------------------------------------
# path-equality oracles
# ---------------------------------------------------------------------------


def test_spilled_inner_join_equals_direct(tmp_path):
    forced = _oracle(
        tmp_path,
        "select t1.k, d.dv, sum(t1.v) sv, count(*) c, sum(t1.amt) sa "
        "from t1, d where t1.k = d.dk group by t1.k, d.dv "
        "order by t1.k, d.dv",
    )
    assert forced.last_spill and forced.last_spill["ops"] >= 1
    assert forced.last_spill["partitions"] == 4
    assert forced.last_spill["bytes_in"] > 0


def test_spilled_left_join_equals_direct(tmp_path):
    # null-keyed left rows must null-extend exactly as the direct path's
    forced = _oracle(
        tmp_path,
        "select t1.k, t1.cat, d.dv from t1 left join d on t1.k = d.dk "
        "order by t1.k, t1.cat, d.dv",
    )
    assert forced.last_spill and forced.last_spill["ops"] >= 1


def test_spilled_join_empty_input(tmp_path):
    forced = _oracle(
        tmp_path,
        "select t1.k, d.dv from t1, d where t1.k = d.dk and t1.v > 1000 "
        "order by t1.k, d.dv",
    )
    assert forced.last_spill  # the spilled path ran, over zero rows


def test_spilled_sort_equals_direct_exact_order(tmp_path):
    # the external sort reuses the direct path's own permutation, so even
    # tie rows land in the identical order — exact list equality, no
    # order-by tie-breaking needed
    forced = _oracle(
        tmp_path,
        "select k, cat, v, amt, f from t1 order by cat, k",
    )
    assert forced.last_spill and forced.last_spill["ops"] >= 1


def test_spilled_distinct_and_union(tmp_path):
    forced = _oracle(
        tmp_path,
        "select distinct k, cat from t1 order by k, cat",
    )
    assert forced.last_spill and forced.last_spill["ops"] >= 1
    _oracle(
        tmp_path,
        "select k, v from t1 union select k, v from t2 order by k, v",
    )


def test_spilled_distinct_empty_after_filter(tmp_path):
    _oracle(
        tmp_path,
        "select distinct k from t1 where v > 1000 order by k",
    )


def test_disk_eviction_roundtrip_and_cleanup(tmp_path):
    # a 1-byte pool budget tiers every non-latest segment to disk; results
    # stay identical and released segment files are unlinked
    forced = _oracle(
        tmp_path,
        "select t1.k, d.dv, count(*) c from t1, d where t1.k = d.dk "
        "group by t1.k, d.dv order by t1.k, d.dv",
        **{"engine.spill_pool_bytes": 1},
    )
    assert forced.last_spill["evictions"] > 0
    assert not glob.glob(str(tmp_path / "spill" / "spill-*.npz"))


def test_annotation_driven_auto_mode(tmp_path):
    # default `auto` mode spills exactly the nodes the budgeter annotated
    direct = _session(tmp_path, **{"engine.spill": "off"})
    q = (
        "select t1.k, d.dv, count(*) c from t1, d where t1.k = d.dk "
        "group by t1.k, d.dv order by t1.k, d.dv"
    )
    want = direct.sql(q).collect().to_pylist()
    auto = _session(tmp_path)  # engine.spill defaults to auto
    res = auto.sql(q)
    assert auto.last_spill is None
    from nds_tpu.analysis.budget import _annotate_spill

    _annotate_spill(res.plan, 4)  # what a `spill` verdict would have done
    assert res.collect().to_pylist() == want
    assert auto.last_spill and auto.last_spill["ops"] >= 1
    # and UNANNOTATED plans never touch the pool in auto mode
    auto.last_spill = None
    assert auto.sql(q).collect().to_pylist() == want
    assert auto.last_spill is None


# ---------------------------------------------------------------------------
# spill events / live metrics
# ---------------------------------------------------------------------------


def test_spill_events_schema_and_metrics(tmp_path):
    from nds_tpu.obs.metrics import MetricsSink
    from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer

    forced = _session(
        tmp_path, **{"engine.spill": "force", "engine.spill_partitions": 4}
    )
    forced.tracer = Tracer()  # in-memory collector
    forced.sql(
        "select distinct k, cat from t1 order by k, cat"
    ).collect()
    evs = [e for e in forced.tracer.events if e["kind"] == "spill"]
    assert evs, "spilled ops must emit `spill` events"
    for ev in evs:
        assert set(EVENT_SCHEMA["spill"]) <= set(ev)
        assert ev["op"] in ("join", "sort", "distinct")
        assert ev["partitions"] == 4
    sink = MetricsSink()
    for ev in evs:
        sink.record(ev)
    total = sum(sink.registry.counter_series("nds_spill_total").values())
    assert total == len(evs)
    assert (
        sink.registry.counter_value("nds_spill_bytes_in_total")
        == sum(e["bytes_in"] for e in evs)
    )


def test_spill_tallies_in_profiler(tmp_path):
    from nds_tpu.obs.reader import profile_events
    from nds_tpu.obs.trace import Tracer

    forced = _session(
        tmp_path, **{"engine.spill": "force", "engine.spill_partitions": 4}
    )
    forced.tracer = Tracer()
    forced.sql("select k, cat from t1 order by cat, k").collect()
    prof = profile_events(forced.tracer.events)
    assert prof["tallies"]["spill_ops"] >= 1
    assert prof["tallies"]["spill_bytes_in"] > 0


# ---------------------------------------------------------------------------
# budgeter verdict + verifier invariants
# ---------------------------------------------------------------------------


def _schema_session(**conf):
    from nds_tpu.schema import get_schemas

    sess = Session(conf={"engine.plan_budget": "off", **conf})
    for name, schema in get_schemas(True).items():
        sess.catalog.entries[name] = _Entry(schema=schema)
    return sess


def _template_plans(sess, qnum, sf):
    from nds_tpu.datagen.query_streams import instantiate
    from nds_tpu.engine.sql.parser import parse_script

    rng = np.random.default_rng(np.random.SeedSequence([0, 0]))
    return [
        sess.run_stmt(s).plan
        for s in parse_script(instantiate(qnum, rng, sf))
    ]


def test_budget_spill_verdict_round5_set():
    from nds_tpu.analysis import budget as B

    # q6/q7: the round-5 SF10 OOM queries that previously landed on the
    # passive `over` verdict now pin onto `spill` with a statically sized
    # power-of-two partition count; q5 keeps its blocked seam; q14 stays
    # beyond the reject line (admission control is not bypassed by spill)
    for q, expect in ((5, "blocked"), (6, "spill"), (7, "spill")):
        sess = _schema_session()
        pbs = [
            B.analyze_plan(p, sess.catalog, scale_factor=10.0)
            for p in _template_plans(sess, q, 10.0)
        ]
        assert [pb.verdict for pb in pbs] == [expect], (q, pbs)
        for pb in pbs:
            assert pb.spillable
            if expect == "spill":
                sp = pb.spill_partitions
                assert sp and sp & (sp - 1) == 0 and 2 <= sp <= 256
    sess = _schema_session()
    pbs = [
        B.analyze_plan(p, sess.catalog, scale_factor=10.0)
        for p in _template_plans(sess, 14, 10.0)
    ]
    assert all(pb.verdict == "reject" for pb in pbs)
    # SF1 stays all-direct (zero false positives — the corpus gate's pin)
    sess1 = _schema_session()
    pb1 = B.analyze_plan(
        _template_plans(sess1, 6, 1.0)[0], sess1.catalog, scale_factor=1.0
    )
    assert pb1.verdict == "direct" and pb1.spill_partitions is None


def test_budget_plan_hook_annotates_and_arms_ladder():
    from nds_tpu.analysis.budget import budget_plan, spillable_node

    sess = _schema_session()
    sess.conf["engine.plan_budget"] = "on"
    sess.conf["engine.plan_budget_sf"] = 10.0
    (plan,) = _template_plans(sess, 6, 10.0)
    pb = budget_plan(plan, sess)
    assert pb.verdict == "spill"
    rec = sess.last_plan_budget
    assert rec["verdict"] == "spill" and rec["spillable"]
    assert rec["spill_partitions"] == pb.spill_partitions
    annotated = [
        v
        for v in P.walk_plan(plan)
        if isinstance(v, P.PlanNode)
        and getattr(v, "spill_partitions", None) is not None
    ]
    assert annotated and all(spillable_node(v) for v in annotated)
    # the verifier accepts the budgeter's own annotations
    from nds_tpu.analysis.verifier import verify_plan

    verify_plan(plan, sess.catalog)
    # warn mode is observe-only: no annotation lands
    sess2 = _schema_session()
    sess2.conf["engine.plan_budget"] = "warn"
    sess2.conf["engine.plan_budget_sf"] = 10.0
    (plan2,) = _template_plans(sess2, 6, 10.0)
    budget_plan(plan2, sess2)
    assert sess2.last_plan_budget["verdict"] == "spill"
    assert not [
        v
        for v in P.walk_plan(plan2)
        if isinstance(v, P.PlanNode)
        and getattr(v, "spill_partitions", None) is not None
    ]


def test_verifier_flags_bad_spill_annotations(tmp_path):
    from nds_tpu.analysis.verifier import PlanVerifyError, verify_plan

    sess = _session(tmp_path)
    res = sess.sql("select k, cat from t1 order by cat, k")
    sort = next(
        v for v in P.walk_plan(res.plan) if isinstance(v, P.Sort)
    )
    # wrong node class: a Project does not own an out-of-core rewrite
    proj = next(
        v for v in P.walk_plan(res.plan) if isinstance(v, P.Project)
    )
    proj.spill_partitions = 4
    with pytest.raises(PlanVerifyError, match="spill"):
        verify_plan(res.plan, sess.catalog)
    del proj.spill_partitions
    # non-power-of-two partition count
    sort.spill_partitions = 3
    with pytest.raises(PlanVerifyError, match="power of two"):
        verify_plan(res.plan, sess.catalog)
    sort.spill_partitions = 4  # sane: accepted
    verify_plan(res.plan, sess.catalog)


# ---------------------------------------------------------------------------
# ladder: spill_retry + spill-IO backoff
# ---------------------------------------------------------------------------


def _flaky(sequence):
    calls = {"n": 0}

    def fn():
        i = calls["n"]
        calls["n"] += 1
        err = sequence[i] if i < len(sequence) else None
        if err is not None:
            raise err

    fn.calls = calls
    return fn


def test_ladder_spill_retry_after_shrink():
    sess = Session()
    sess.last_plan_budget = {
        "verdict": "over", "spillable": True, "spill_partitions": 4,
    }
    oom = lambda: faults.InjectedOOM("RESOURCE_EXHAUSTED: x")
    fn = _flaky([oom(), oom(), oom()])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in s["ladder"]] == [
        "recover_retry", "shrink_union_window", "spill_retry",
    ]
    assert sess.conf["engine.spill"] == "force"
    assert sess.conf["engine.spill_partitions"] == 4
    # degradation persists for the rest of the stream's session, so the
    # rung is NOT offered again (re-forcing would waste an attempt)
    s2 = BenchReport(sess).report_on(
        _flaky([oom(), oom(), oom()]), retry_oom=True
    )
    assert s2["queryStatus"] == ["Failed"]
    assert [r["rung"] for r in s2["ladder"]] == [
        "recover_retry", "shrink_union_window",
    ]


def test_ladder_no_spill_retry_without_seam():
    # no budget record (or an unspillable plan): the pre-spill ladder
    sess = Session()
    oom = lambda: faults.InjectedOOM("RESOURCE_EXHAUSTED: x")
    s = BenchReport(sess).report_on(
        _flaky([oom(), oom(), oom()]), retry_oom=True
    )
    assert s["queryStatus"] == ["Failed"]
    assert [r["rung"] for r in s["ladder"]] == [
        "recover_retry", "shrink_union_window",
    ]


def test_injected_oom_completes_via_spill_retry(tmp_path):
    # the acceptance-criteria integration: a query that device-OOMs on an
    # unspilled join plan completes through the spill_retry rung, with
    # spill evidence on the session
    sess = _session(tmp_path)
    q = (
        "select t1.k, count(*) c from t1, d where t1.k = d.dk "
        "group by t1.k order by t1.k"
    )
    expect = sess.sql(q).collect().to_pylist()
    faults.install("oom:exec:qspill:3")

    def runq():
        with faults.scope("qspill"):
            assert sess.sql(q).collect().to_pylist() == expect

    s = BenchReport(sess).report_on(runq, retry_oom=True, name="qspill")
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in s["ladder"]] == [
        "recover_retry", "shrink_union_window", "spill_retry",
    ]
    assert sess.last_spill and sess.last_spill["ops"] >= 1


def test_spill_io_fault_retries_with_backoff(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_IO_RETRIES", "2")
    monkeypatch.setenv("NDS_IO_BACKOFF", "0")
    sess = _session(
        tmp_path,
        **{
            "engine.spill": "force",
            "engine.spill_partitions": 4,
            "engine.spill_pool_bytes": 1,  # every put tiers to disk
        },
    )
    faults.install("io:spill:write:1")

    def runq():
        sess.sql("select distinct k from t1 order by k").collect()

    s = BenchReport(sess).report_on(runq, retry_oom=True)
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert "io_backoff_retry" in [r["rung"] for r in s["ladder"]]
    # a real (wrapped) segment-IO failure classifies io_transient too
    assert faults.classify(SP.SpillIOError("disk went away")) == (
        faults.IO_TRANSIENT
    )


def test_spill_crash_rule_sails_through(tmp_path):
    sess = _session(
        tmp_path,
        **{
            "engine.spill": "force",
            "engine.spill_partitions": 4,
            "engine.spill_pool_bytes": 1,
        },
    )
    faults.install("crash:spill:write")
    with pytest.raises(faults.InjectedCrash):
        sess.sql("select distinct k from t1 order by k").collect()


# ---------------------------------------------------------------------------
# crash hygiene: orphan sweep
# ---------------------------------------------------------------------------


def _write_manifest(d, pid, app):
    with open(os.path.join(d, f"spill-manifest-{pid}.json"), "w") as f:
        json.dump({"magic": SP._MANIFEST_MAGIC, "pid": pid, "app": app}, f)


def test_sweep_removes_dead_process_segments(tmp_path):
    d = str(tmp_path)
    p = subprocess.Popen(["sleep", "0"])
    p.wait()
    _write_manifest(d, p.pid, "deadapp-abc")
    open(os.path.join(d, "spill-deadapp-abc-0.npz"), "wb").close()
    open(os.path.join(d, "spill-deadapp-abc-1.npz.tmp-1234"), "wb").close()
    _write_manifest(d, os.getpid(), "liveapp-xyz")
    open(os.path.join(d, "spill-liveapp-xyz-0.npz"), "wb").close()
    open(os.path.join(d, "unrelated.txt"), "w").close()
    # a foreign manifest (wrong magic) protects nothing and is untouched
    with open(os.path.join(d, "spill-manifest-99999999.json"), "w") as f:
        json.dump({"magic": "something-else", "pid": 1}, f)
    # a torn manifest write from the dead process is swept too; a torn
    # manifest of a LIVE process is kept
    open(
        os.path.join(d, f"spill-manifest-{p.pid}.json.tmp-abcd1234"), "w"
    ).close()
    open(
        os.path.join(d, f"spill-manifest-{os.getpid()}.json.tmp-ef567890"),
        "w",
    ).close()
    removed = SP.sweep_orphans(d)
    left = sorted(os.listdir(d))
    assert removed == 4
    assert "spill-liveapp-xyz-0.npz" in left  # live process: kept
    assert "unrelated.txt" in left  # foreign file: never touched
    assert "spill-manifest-99999999.json" in left  # wrong magic: untouched
    assert f"spill-manifest-{os.getpid()}.json.tmp-ef567890" in left
    assert not any("deadapp" in x for x in left)
    assert not any(f"manifest-{p.pid}" in x for x in left)


def test_session_start_sweeps_orphans(tmp_path, monkeypatch):
    d = str(tmp_path / "spill")
    os.makedirs(d)
    p = subprocess.Popen(["sleep", "0"])
    p.wait()
    _write_manifest(d, p.pid, "crashed-run")
    open(os.path.join(d, "spill-crashed-run-0.npz"), "wb").close()
    monkeypatch.setattr(SP, "_SWEPT_DIRS", set())  # fresh process view
    Session(conf={"engine.spill_dir": d})
    assert not glob.glob(os.path.join(d, "spill-crashed-run-*"))
    # crash -> restart regression: a pool in the restarted session reuses
    # the swept dir cleanly (write + read back through the disk tier)
    sess = _session(
        tmp_path,
        **{
            "engine.spill": "force",
            "engine.spill_partitions": 4,
            "engine.spill_pool_bytes": 1,
            "engine.spill_dir": d,
        },
    )
    out = sess.sql("select distinct k from t1 order by k").collect()
    assert out.num_rows > 0
    assert sess.last_spill["evictions"] > 0


# ---------------------------------------------------------------------------
# progress-aware watchdog (heartbeat-during-spill satellite)
# ---------------------------------------------------------------------------


def test_watchdog_spares_slow_but_beating_spill():
    sess = Session(conf={"engine.query_timeout": "0.4"})

    def beating():
        # a healthy external sort: total wall 0.9s >> the 0.4s budget, but
        # every merge/partition phase beats through the progress seam
        for _ in range(6):
            time.sleep(0.15)
            sess.spill_progress()

    s = BenchReport(sess).report_on(beating, retry_oom=True)
    assert s["queryStatus"] == ["Completed"]
    assert "ladder" not in s


def test_watchdog_still_fires_without_beats():
    sess = Session(conf={"engine.query_timeout": "0.4"})

    def silent():
        time.sleep(2.0)

    t0 = time.monotonic()
    s = BenchReport(sess).report_on(silent, retry_oom=True)
    elapsed = time.monotonic() - t0
    assert s["queryStatus"] == ["Failed"]
    assert s["failureKind"] == faults.TIMEOUT
    assert elapsed < 1.5  # abandoned well before the 2s hang ends


def test_stale_beat_does_not_extend_next_query():
    sess = Session(conf={"engine.query_timeout": "0.4"})
    sess.spill_progress()  # previous query's beat

    def silent():
        time.sleep(2.0)

    t0 = time.monotonic()
    s = BenchReport(sess).report_on(silent, retry_oom=True)
    assert s["queryStatus"] == ["Failed"]
    assert time.monotonic() - t0 < 1.5


def test_zombie_worker_beats_do_not_shield_a_hang():
    # an ABANDONED previous attempt's worker keeps beating on the shared
    # session; the next query's watchdog must ignore those beats (they
    # carry the zombie's thread identity) or a genuine hang could stall
    # the stream forever
    import threading

    sess = Session(conf={"engine.query_timeout": "0.4"})
    stop = threading.Event()

    def zombie():
        while not stop.wait(0.1):
            sess.spill_progress()

    z = threading.Thread(target=zombie, daemon=True)
    z.start()
    try:
        def silent():
            time.sleep(2.0)

        t0 = time.monotonic()
        s = BenchReport(sess).report_on(silent, retry_oom=True)
        assert s["queryStatus"] == ["Failed"]
        assert s["failureKind"] == faults.TIMEOUT
        assert time.monotonic() - t0 < 1.5
    finally:
        stop.set()
        z.join(2)


# ---------------------------------------------------------------------------
# pool units
# ---------------------------------------------------------------------------


def test_pool_put_read_release_accounting(tmp_path):
    import jax.numpy as jnp

    from nds_tpu.engine.columnar import Column, Table
    from nds_tpu.dtypes import INT64

    pool = SP.SpillPool(budget_bytes=1 << 20, spill_dir=str(tmp_path))
    t = Table(
        {"x": Column(jnp.arange(1024, dtype=jnp.int64), INT64)}, 1000
    )
    seg = pool.put(t)
    assert seg.nrows == 1000
    assert pool.stats["bytes_in"] == seg.nbytes == 8 * 1000
    out = SP.assemble_segments(pool, [seg, seg])
    assert out.nrows == 2000
    assert pool.stats["bytes_out"] == 2 * seg.nbytes
    pool.release([seg])
    assert pool.host_bytes == 0


def test_pool_ram_only_over_budget_keeps_data(tmp_path):
    import jax.numpy as jnp

    from nds_tpu.engine.columnar import Column, Table
    from nds_tpu.dtypes import INT64

    pool = SP.SpillPool(budget_bytes=1, spill_dir=None)  # no disk tier
    segs = [
        pool.put(
            Table({"x": Column(jnp.arange(1024, dtype=jnp.int64), INT64)}, 64)
        )
        for _ in range(3)
    ]
    assert pool.stats["evictions"] == 0  # nothing to evict to
    out = SP.assemble_segments(pool, segs)
    assert out.nrows == 192  # data never dropped
