"""Expression evaluator tests: arithmetic, 3VL, strings, decimals, dates."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.dtypes import DType, parse_dtype
from nds_tpu.engine import expr as E
from nds_tpu.engine.columnar import table_from_arrow, column_to_arrow


def _table(**cols):
    names = list(cols)
    arrays = [pa.array(v[1], type=v[0]) for v in cols.values()]
    return table_from_arrow(pa.table(arrays, names=names))


@pytest.fixture
def t():
    return _table(
        a=(pa.int32(), [1, 2, None, 4, 5]),
        b=(pa.int32(), [10, 20, 30, None, 50]),
        f=(pa.float64(), [1.5, 2.5, 3.5, 4.5, None]),
        d=(
            pa.decimal128(7, 2),
            [Decimal("1.10"), Decimal("2.20"), Decimal("3.30"), None, Decimal("5.50")],
        ),
        s=(pa.string(), ["apple", "banana", None, "cherry", "apple"]),
        dt=(pa.date32(), [0, 1, 2, 3, 4]),
    )


def _vals(col, t):
    return column_to_arrow(col, t.nrows).to_pylist()


def test_add(t):
    out = E.Evaluator(t).eval(E.BinOp("+", E.Col("a"), E.Col("b")))
    assert _vals(out, t) == [11, 22, None, None, 55]


def test_decimal_mul_scale(t):
    out = E.Evaluator(t).eval(E.BinOp("*", E.Col("d"), E.Col("d")))
    assert out.dtype.scale == 4
    got = _vals(out, t)
    assert str(got[0]) == "1.2100"
    assert got[3] is None


def test_division_null_on_zero():
    t = _table(x=(pa.int32(), [10, 10]), y=(pa.int32(), [2, 0]))
    out = E.Evaluator(t).eval(E.BinOp("/", E.Col("x"), E.Col("y")))
    assert _vals(out, t) == [5.0, None]


def test_compare_and_3vl(t):
    # (a > 1) AND (b > 10): row2 null AND true -> null; row3 true AND null -> null
    e = E.BinOp(
        "and",
        E.BinOp(">", E.Col("a"), E.Lit(1)),
        E.BinOp(">", E.Col("b"), E.Lit(10)),
    )
    out = E.Evaluator(t).eval(e)
    assert _vals(out, t) == [False, True, None, None, True]


def test_or_short_circuit_null():
    t = _table(a=(pa.int32(), [1, None]), b=(pa.int32(), [5, 5]))
    e = E.BinOp(
        "or",
        E.BinOp("=", E.Col("a"), E.Lit(99)),
        E.BinOp("=", E.Col("b"), E.Lit(5)),
    )
    out = E.Evaluator(t).eval(e)
    # null OR true -> true
    assert _vals(out, t) == [True, True]


def test_string_eq_literal(t):
    out = E.Evaluator(t).eval(E.BinOp("=", E.Col("s"), E.Lit("apple")))
    assert _vals(out, t) == [True, False, None, False, True]


def test_like(t):
    out = E.Evaluator(t).eval(E.Like(E.Col("s"), "%an%"))
    assert _vals(out, t) == [False, True, None, False, False]


def test_in_list_strings(t):
    out = E.Evaluator(t).eval(
        E.InList(E.Col("s"), (E.Lit("apple"), E.Lit("cherry")))
    )
    assert _vals(out, t) == [True, False, None, True, True]


def test_between(t):
    out = E.Evaluator(t).eval(E.Between(E.Col("a"), E.Lit(2), E.Lit(4)))
    assert _vals(out, t) == [False, True, None, True, False]


def test_case_when(t):
    e = E.Case(
        branches=(
            (E.BinOp(">", E.Col("a"), E.Lit(3)), E.Lit("big")),
            (E.BinOp(">", E.Col("a"), E.Lit(1)), E.Lit("mid")),
        ),
        default=E.Lit("small"),
    )
    out = E.Evaluator(t).eval(e)
    assert _vals(out, t) == ["small", "mid", "small", "big", "big"]


def test_substr(t):
    out = E.Evaluator(t).eval(E.Func("substr", (E.Col("s"), E.Lit(1), E.Lit(3))))
    assert _vals(out, t) == ["app", "ban", None, "che", "app"]


def test_coalesce(t):
    out = E.Evaluator(t).eval(E.Func("coalesce", (E.Col("a"), E.Lit(0))))
    assert _vals(out, t) == [1, 2, 0, 4, 5]


def test_is_null(t):
    out = E.Evaluator(t).eval(E.UnaryOp("isnull", E.Col("a")))
    assert _vals(out, t) == [False, False, True, False, False]


def test_date_interval(t):
    e = E.BinOp("+", E.Col("dt"), E.Func("date_days", (E.Lit(30),)))
    # date + int literal also works through the + path
    out = E.Evaluator(t).eval(E.BinOp("+", E.Col("dt"), E.Lit(30)))
    assert _vals(out, t)[0].isoformat() == "1970-01-31"


def test_date_compare_literal(t):
    e = E.BinOp(">=", E.Col("dt"), E.Lit("1970-01-03", parse_dtype("date")))
    out = E.Evaluator(t).eval(e)
    assert _vals(out, t) == [False, False, True, True, True]


def test_cast_decimal_to_float(t):
    out = E.Evaluator(t).eval(E.Cast(E.Col("d"), parse_dtype("float64")))
    got = _vals(out, t)
    assert got[0] == pytest.approx(1.10)


def test_concat_literal(t):
    out = E.Evaluator(t).eval(E.BinOp("||", E.Col("s"), E.Lit("-x")))
    assert _vals(out, t) == ["apple-x", "banana-x", None, "cherry-x", "apple-x"]


def test_round_decimal(t):
    out = E.Evaluator(t).eval(E.Func("round", (E.Col("d"), E.Lit(1))))
    got = _vals(out, t)
    assert str(got[0]) == "1.10"
    assert str(got[1]) == "2.20"


def test_year(t):
    out = E.Evaluator(t).eval(E.Func("year", (E.Col("dt"),)))
    assert _vals(out, t) == [1970] * 5


def test_civil_from_days_matches_numpy():
    """Device-side calendar split must agree with numpy datetime64 across
    four centuries (leap rules included)."""
    import jax.numpy as jnp
    from nds_tpu.engine.expr import _civil_from_days

    days = np.arange(-80000, 80000, 7, dtype=np.int64)
    y, m, d = _civil_from_days(jnp.asarray(days))
    dates = np.datetime64("1970-01-01") + days.astype("timedelta64[D]")
    np.testing.assert_array_equal(
        np.asarray(y), dates.astype("datetime64[Y]").astype(int) + 1970)
    np.testing.assert_array_equal(
        np.asarray(m), dates.astype("datetime64[M]").astype(int) % 12 + 1)
    np.testing.assert_array_equal(
        np.asarray(d), (dates - dates.astype("datetime64[M]")).astype(int) + 1)
