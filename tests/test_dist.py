"""Distributed primitive tests on the virtual 8-device CPU mesh.

Covers the two mesh patterns the engine uses (reference analogue: Spark
executor data parallelism + shuffle, nds/base.template:28-31):
  * sharded star-query step (partial agg + psum) vs single-device oracle
  * hash-partition exchange routing + overflow detection
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from nds_tpu.parallel.dist import (
    fused_query_step,
    make_mesh,
    partition_exchange,
    sharded_query_step,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV
    return make_mesh(N_DEV)


def test_sharded_star_agg_matches_oracle(mesh):
    rng = np.random.default_rng(7)
    n, n_dates, n_items, n_groups = 128 * N_DEV, 64, 32, 8
    shard = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    fd = jax.device_put(jnp.asarray(rng.integers(0, n_dates, n), jnp.int32), shard)
    fi = jax.device_put(jnp.asarray(rng.integers(0, n_items, n), jnp.int32), shard)
    fm = jax.device_put(jnp.asarray(rng.integers(0, 1000, n), jnp.int64), shard)
    fv = jax.device_put(jnp.asarray(rng.random(n) < 0.9), shard)
    ddf = jax.device_put(jnp.asarray(rng.random(n_dates) < 0.5), repl)
    dig = jax.device_put(jnp.asarray(rng.integers(-1, n_groups, n_items), jnp.int32), repl)

    step = sharded_query_step(mesh, n_groups)
    sums, counts = jax.block_until_ready(step(fd, fi, fm, fv, ddf, dig))
    ref_s, ref_c = fused_query_step(
        np.asarray(fd), np.asarray(fi), np.asarray(fm), np.asarray(fv),
        np.asarray(ddf), np.asarray(dig), n_groups=n_groups,
    )
    np.testing.assert_array_equal(np.asarray(sums), np.asarray(ref_s))
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_c))


def test_partition_exchange_routes_keys(mesh):
    rng = np.random.default_rng(3)
    n, cap = 64 * N_DEV, 64
    shard = NamedSharding(mesh, P("data"))
    keys = jax.device_put(jnp.asarray(rng.integers(0, 1000, n), jnp.int64), shard)
    vals = jax.device_put(jnp.asarray(rng.integers(0, 100, n), jnp.int64), shard)
    live = jax.device_put(jnp.asarray(rng.random(n) < 0.8), shard)

    ex = partition_exchange(mesh, cap)
    rk, rv, dropped = jax.block_until_ready(ex(keys, vals, live))
    assert int(dropped) == 0
    rk_np = np.asarray(rk).reshape(N_DEV, -1)
    for d in range(N_DEV):
        got = rk_np[d][rk_np[d] >= 0]
        assert (got % N_DEV == d).all()
    # conservation: every live key arrives exactly once
    sent = np.sort(np.asarray(keys)[np.asarray(live)])
    recvd = np.sort(np.asarray(rk)[np.asarray(rk) >= 0])
    np.testing.assert_array_equal(sent, recvd)
    # values ride with their keys
    rv_np = np.asarray(rv)
    kv = {}
    k_host, v_host, l_host = np.asarray(keys), np.asarray(vals), np.asarray(live)
    for k, v, l in zip(k_host, v_host, l_host):
        if l:
            kv.setdefault(k, []).append(v)
    got_kv = {}
    for k, v in zip(np.asarray(rk), rv_np):
        if k >= 0:
            got_kv.setdefault(k, []).append(v)
    assert {k: sorted(v) for k, v in kv.items()} == {
        k: sorted(v) for k, v in got_kv.items()
    }


def test_partition_exchange_detects_overflow(mesh):
    # all keys hash to device 0 -> bucket 0 needs n rows but cap is tiny
    n, cap = 16 * N_DEV, 2
    shard = NamedSharding(mesh, P("data"))
    keys = jax.device_put(jnp.zeros(n, jnp.int64) + 8, shard)  # 8 % 8 == 0
    vals = jax.device_put(jnp.arange(n, dtype=jnp.int64), shard)
    live = jax.device_put(jnp.ones(n, bool), shard)
    ex = partition_exchange(mesh, cap)
    _, _, dropped = jax.block_until_ready(ex(keys, vals, live))
    assert int(dropped) == n - cap * N_DEV


def test_sample_sort_global_order(mesh):
    from nds_tpu.parallel.dist import sample_sort

    rng = np.random.default_rng(9)
    n = 256 * N_DEV
    shard = NamedSharding(mesh, P("data"))
    keys = jax.device_put(
        jnp.asarray(rng.integers(-1000, 1000, n), jnp.int64), shard)
    vals = jax.device_put(jnp.arange(n, dtype=jnp.int64), shard)
    live = jax.device_put(jnp.asarray(rng.random(n) < 0.9), shard)

    fn = sample_sort(mesh, n_keys=1, n_cols=2, cap_route=64)
    live_out, k_out, v_out, counts, ov = jax.block_until_ready(
        fn(keys, live, keys, keys, vals))
    assert int(ov) == 0
    # skew evidence: per-device received counts cover every live row
    assert int(np.asarray(counts).sum()) == int(np.asarray(live).sum())
    k_host, v_host, l_host = (np.asarray(x) for x in (keys, vals, live))
    L = int(l_host.sum())
    lo, ko, vo = (np.asarray(x) for x in (live_out, k_out, v_out))
    # live rows first (the Table layout), globally sorted
    assert lo[:L].all() and not lo[L:].any()
    np.testing.assert_array_equal(ko[:L], np.sort(k_host[l_host]))
    # payload rides with its row
    got = sorted(zip(ko[:L].tolist(), vo[:L].tolist()))
    want = sorted(zip(k_host[l_host].tolist(), v_host[l_host].tolist()))
    assert got == want


def test_sample_sort_skew_overflow_and_max_cap(mesh):
    from nds_tpu.parallel.dist import sample_sort

    rng = np.random.default_rng(10)
    n = 256 * N_DEV
    local = n // N_DEV
    shard = NamedSharding(mesh, P("data"))
    # 95% of rows share one key: every one of them must land on one device
    raw = np.where(rng.random(n) < 0.95, 7, rng.integers(-500, 500, n))
    keys = jax.device_put(jnp.asarray(raw, jnp.int64), shard)
    live = jax.device_put(jnp.ones(n, bool), shard)

    small = sample_sort(mesh, n_keys=1, n_cols=1, cap_route=8)
    *_, ov = jax.block_until_ready(small(keys, live, keys, keys))
    assert int(ov) > 0  # skew detected, caller must retry

    big = sample_sort(mesh, n_keys=1, n_cols=1, cap_route=local)
    live_out, k_out, counts, ov = jax.block_until_ready(
        big(keys, live, keys, keys))
    assert int(ov) == 0  # cap == local rows can never overflow
    np.testing.assert_array_equal(np.asarray(k_out)[: n], np.sort(raw))
    # the hot key's rows all land on one device: skew is visible in the
    # received counts (max well above the balanced share)
    c = np.asarray(counts)
    assert c.max() > 2 * c.sum() / len(c)


def test_compact_indices_sharded_matches_replicated(mesh):
    """Regression (caught by the SF0.01 mesh gate on query77/query83):
    jax 0.4.37's SPMD partitioner mislowers the blocked-cumsum + scatter
    compaction over a row-sharded mask — cross-shard scatter writes drop
    and compaction silently truncates. Sharded masks must route through
    the sort-based variant and agree with the single-device kernel
    exactly (indices AND zero padding)."""
    from nds_tpu.ops import kernels as K

    shard = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(12)
    for n in (1024, 8192):
        for frac in (0.0, 0.3, 1.0):
            mask_np = rng.random(n) < frac
            mask_s = jax.device_put(jnp.asarray(mask_np), shard)
            mask_r = jnp.asarray(mask_np)
            for cap in (n // 2, n, 2 * n):
                a = np.asarray(K.compact_indices(mask_s, cap))
                b = np.asarray(K.compact_indices(mask_r, cap))
                np.testing.assert_array_equal(a, b, err_msg=str((n, frac, cap)))


def test_multihost_single_process_degenerates(mesh):
    """multihost utilities: in a 1-process world initialize() is a no-op,
    global_mesh covers the local devices, and shard_rows_across_hosts is a
    plain row-sharded device_put (the DCN path needs a real pod)."""
    from nds_tpu.parallel import multihost

    multihost.initialize()  # no cluster env: must not raise
    m = multihost.global_mesh()
    assert m.devices.size == len(jax.devices())
    rows = np.arange(16 * N_DEV, dtype=np.int64)
    arr = multihost.shard_rows_across_hosts(mesh, rows)
    assert arr.shape == rows.shape
    np.testing.assert_array_equal(np.asarray(arr), rows)
    # actually sharded: each device holds 1/N of the rows
    assert len(arr.sharding.device_set) == N_DEV
