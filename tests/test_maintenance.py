"""Lakehouse + Data Maintenance tests (reference behavior:
nds/nds_maintenance.py, nds/data_maintenance/*.sql, nds/nds_rollback.py)."""

import csv
import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.lakehouse.table import LakehouseTable
from nds_tpu.maintenance import (
    DM_FUNCS,
    replace_date,
    run_maintenance,
)

DATA = "/tmp/nds_test_sf001"
REFRESH = "/tmp/nds_test_sf001_refresh"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


@pytest.fixture(scope="module")
def refresh_dir():
    if not os.path.exists(os.path.join(REFRESH, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", REFRESH, "--update", "1",
             "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(REFRESH, ".complete"), "w").close()
    return REFRESH


@pytest.fixture(scope="module")
def warehouse(data_dir, tmp_path_factory):
    """Transcode every source table to a lakehouse warehouse once."""
    wh = tmp_path_factory.mktemp("lake")
    subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.transcode", data_dir, str(wh),
         str(wh / "load.report"), "--output_format", "lakehouse"],
        check=True, capture_output=True, cwd=REPO,
        env={**os.environ, "NDS_PLATFORM": "cpu"},
    )
    return wh


# ---- lakehouse table unit tests -----------------------------------------


def test_lakehouse_snapshot_cycle(tmp_path):
    t = pa.table({"a": np.arange(10, dtype=np.int64)})
    path = str(tmp_path / "t")
    lt = LakehouseTable.create(path, t)
    assert lt.num_rows() == 10
    v1_ts = lt.versions()[0][1]
    lt.append(pa.table({"a": np.arange(5, dtype=np.int64)}))
    assert lt.num_rows() == 15
    lt.replace(pa.table({"a": np.arange(3, dtype=np.int64)}), operation="delete")
    assert lt.num_rows() == 3
    lt.rollback_to_timestamp(v1_ts)
    assert lt.num_rows() == 10
    assert lt.dataset().count_rows() == 10
    ops = [op for _, _, op in lt.versions()]
    assert ops == ["create", "append", "delete", "rollback-to-v1"]


def test_dml_insert_delete_ctas_call(tmp_path):
    d = str(tmp_path)
    t = pa.table({"a": np.arange(10, dtype=np.int64)})
    LakehouseTable.create(os.path.join(d, "t"), t)
    s = Session(conf={"lakehouse.warehouse": d})
    s.register_lakehouse("t", os.path.join(d, "t"))
    # strftime truncates to seconds; wait first so before_ts > create time
    time.sleep(1.1)
    before_ts = time.strftime("%Y-%m-%d %H:%M:%S")
    r = s.sql("insert into t (select a + 10 a from t)")
    assert r.rows_affected == 10
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 20}]
    r = s.sql("delete from t where a >= 15")
    assert r.rows_affected == 5
    # survivors with NULL predicate stay (3VL)
    s.sql("create table t3 location '" + os.path.join(d, "t3") + "' as " +
          "select a, cast(null as int) n from t")
    s.register_lakehouse("t3", os.path.join(d, "t3"))
    r = s.sql("delete from t3 where n > 0")
    assert r.rows_affected == 0
    s.sql(f"call system.rollback_to_timestamp('t', timestamp '{before_ts}')")
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 10}]


def test_delete_all_rows_keeps_table_readable(tmp_path):
    """An all-rows DELETE leaves zero data files; the manifest-carried schema
    must keep the table readable (and truncate must work when empty)."""
    d = str(tmp_path)
    LakehouseTable.create(
        os.path.join(d, "t"), pa.table({"a": np.arange(5, dtype=np.int64)})
    )
    s = Session(conf={"lakehouse.warehouse": d})
    s.register_lakehouse("t", os.path.join(d, "t"))
    r = s.sql("delete from t where a >= 0")
    assert r.rows_affected == 5
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 0}]
    s.sql("delete from t")  # truncate on an already-empty table
    assert s.sql("select count(*) c from t").to_pylist() == [{"c": 0}]
    s.sql("insert into t (select 7 a)")
    assert s.sql("select a from t").to_pylist() == [{"a": 7}]


def test_delete_predicate_edge_paths(tmp_path):
    """Streaming-DELETE translator edges: a plain range uses the Arrow fast
    path; literal-folding predicates must fall back to the engine instead of
    crashing (code-review regression)."""
    d = str(tmp_path)
    LakehouseTable.create(
        os.path.join(d, "t"),
        pa.table({"a": pa.array([1, 2, None], type=pa.int64())}),
    )
    s = Session(conf={"lakehouse.warehouse": d})
    s.register_lakehouse("t", os.path.join(d, "t"))
    # arrow fast path: NULL predicate row survives (3VL)
    assert s.sql("delete from t where a >= 2").rows_affected == 1
    # literal-vs-literal comparison folds to a Python bool -> engine path
    assert s.sql("delete from t where 1 = 1").rows_affected == 2


def test_replace_date_normalizes_order():
    out = replace_date(
        ["x DATE1 y DATE2"], [("2000-05-20", "2000-05-10")]
    )
    assert out == ["x 2000-05-10 y 2000-05-20"]


# ---- full maintenance flow ----------------------------------------------


# per-function target fact tables (reference: nds/data_maintenance/*.sql)
LF_TARGETS = {
    "LF_CR": "catalog_returns",
    "LF_CS": "catalog_sales",
    "LF_I": "inventory",
    "LF_SR": "store_returns",
    "LF_SS": "store_sales",
    "LF_WR": "web_returns",
    "LF_WS": "web_sales",
}
DF_TARGETS = {
    "DF_SS": ("store_sales", "store_returns"),
    "DF_CS": ("catalog_sales", "catalog_returns"),
    "DF_WS": ("web_sales", "web_returns"),
    "DF_I": ("inventory",),
}
ALL_FACTS = sorted({t for ts in DF_TARGETS.values() for t in ts})


def _counts(warehouse, tables):
    return {
        t: LakehouseTable(str(warehouse / t)).dataset().count_rows()
        for t in tables
    }


def test_maintenance_all_functions(warehouse, refresh_dir, tmp_path):
    """Every one of the 11 refresh functions executes end-to-end against the
    warehouse, with per-function row-delta assertions (VERDICT r2 weak #5;
    reference: nds/nds_maintenance.py:204-265)."""
    import json

    from nds_tpu.maintenance import INSERT_FUNCS, DELETE_FUNCS

    before = _counts(warehouse, ALL_FACTS)

    # ---- all 7 LF_* (INSERT) functions ----------------------------------
    jdir = tmp_path / "json_lf"
    dm_time = run_maintenance(
        warehouse_path=str(warehouse),
        refresh_data_path=refresh_dir,
        time_log_output_path=str(tmp_path / "dm_lf.csv"),
        json_summary_folder=str(jdir),
        spec_queries=list(LF_TARGETS),
    )
    assert dm_time > 0
    statuses = {}
    for f in os.listdir(jdir):
        s = json.load(open(os.path.join(jdir, f)))
        statuses[s["query"]] = s["queryStatus"]
    assert statuses == {q: ["Completed"] for q in LF_TARGETS}
    after_lf = _counts(warehouse, ALL_FACTS)
    for fn, table in LF_TARGETS.items():
        assert after_lf[table] > before[table], (
            f"{fn} inserted no rows into {table}"
        )
        ops = [
            op for _, _, op in LakehouseTable(str(warehouse / table)).versions()
        ]
        assert "insert" in ops, (fn, table, ops)

    # ---- all 4 DF_* (ranged DELETE) functions ---------------------------
    jdir2 = tmp_path / "json_df"
    dm_time2 = run_maintenance(
        warehouse_path=str(warehouse),
        refresh_data_path=refresh_dir,
        time_log_output_path=str(tmp_path / "dm_df.csv"),
        json_summary_folder=str(jdir2),
        spec_queries=list(DF_TARGETS),
    )
    assert dm_time2 > 0
    statuses2 = {}
    for f in os.listdir(jdir2):
        s = json.load(open(os.path.join(jdir2, f)))
        statuses2[s["query"]] = s["queryStatus"]
    assert statuses2 == {q: ["Completed"] for q in DF_TARGETS}
    after_df = _counts(warehouse, ALL_FACTS)
    deleted_total = 0
    for fn, tables in DF_TARGETS.items():
        for table in tables:
            assert after_df[table] <= after_lf[table], (fn, table)
            deleted_total += after_lf[table] - after_df[table]
            ops = [
                op
                for _, _, op in LakehouseTable(
                    str(warehouse / table)
                ).versions()
            ]
            assert "delete" in ops, (fn, table, ops)
    # the generated delete-date ranges overlap the data: something must go
    assert deleted_total > 0

    rows = list(csv.reader((tmp_path / "dm_df.csv").open()))
    names = [r[1] for r in rows[1:]]
    assert "Data Maintenance Time" in names

    # ---- snapshot rollback restores every pre-maintenance count ---------
    from nds_tpu.maintenance import rollback

    import datetime

    ts = max(
        LakehouseTable(str(warehouse / t)).versions()[0][1] for t in ALL_FACTS
    )
    rollback(
        str(warehouse),
        datetime.datetime.fromtimestamp(ts / 1000 + 1).strftime(
            "%Y-%m-%d %H:%M:%S"
        ),
        tables=ALL_FACTS,
    )
    assert _counts(warehouse, ALL_FACTS) == before


def test_all_dm_functions_have_sql():
    from nds_tpu.maintenance import MAINTENANCE_SQL_DIR

    for q in DM_FUNCS:
        assert os.path.exists(os.path.join(MAINTENANCE_SQL_DIR, q + ".sql")), q
