"""Real multi-process DCN-tier test: two OS processes, a loopback
coordinator, and a global mesh spanning both processes' CPU devices.

The reference's multi-node story is YARN executors + Netty shuffle
(reference: nds/base.template:26-31); the TPU-native counterpart is
jax.distributed + GSPMD collectives. Prior rounds only exercised the
single-process degenerate branch of parallel/multihost.py — this spawns a
genuine 2-process cluster so `jax.make_array_from_process_local_data`
(multihost.shard_rows_across_hosts) and cross-process collectives execute
for real, and runs one SQL aggregation through the Session over the
multi-process mesh against a numpy oracle.
"""

import os
import socket
import subprocess
import sys

import pytest

# The CPU skip carried since PR 3 is RETIRED (ISSUE 13): multihost.initialize
# now selects the gloo cross-process collective implementation whenever the
# process is pinned to the CPU platform, so the two-process DCN tier runs
# for real on this container — a genuine 2-process cluster over a loopback
# coordinator, cross-process psum/segment-sum, and one SQL aggregation
# through the Session over the multi-process mesh. Marked slow (two cold
# jax processes cost ~a minute); ci/tier1-check runs it standalone so
# scale-out has a CI gate at all.
pytestmark = pytest.mark.slow

_WORKER = r"""
import os, sys
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

# sitecustomize may have imported jax already (which would pin the axon TPU
# platform): switch via jax.config BEFORE the backend initializes, and set
# the virtual device count through XLA_FLAGS (read lazily at client
# creation) — same pattern as tests/conftest.py and __graft_entry__.py
import re
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=2 " +
    re.sub(r"--xla_force_host_platform_device_count=\d+", "",
           os.environ.get("XLA_FLAGS", ""))).strip()
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.getcwd())  # Popen cwd = repo root
from nds_tpu.parallel import multihost

multihost.initialize(coordinator_address=coord, num_processes=2, process_id=pid)

import jax.numpy as jnp

assert jax.process_count() == 2, jax.process_count()
mesh = multihost.global_mesh()
assert mesh.devices.size == 4, mesh.devices.size

# --- primitive tier: host-sharded ingestion + global reduction ------------
rows = np.arange(64, dtype=np.int64)
local = rows[pid * 32:(pid + 1) * 32]  # each process contributes its half
garr = multihost.shard_rows_across_hosts(mesh, local)
total = int(jax.jit(jnp.sum)(garr))
assert total == int(rows.sum()), (total, rows.sum())

# --- group-by over the mesh: segment-sum of host-sharded fact rows --------
keys = (rows % 5).astype(np.int32)
vals = (rows * 3).astype(np.int64)
gk = multihost.shard_rows_across_hosts(mesh, keys[pid * 32:(pid + 1) * 32])
gv = multihost.shard_rows_across_hosts(mesh, vals[pid * 32:(pid + 1) * 32])
sums = jax.jit(
    lambda k, v: jax.ops.segment_sum(v, k, num_segments=5)
)(gk, gv)
expect = [int(vals[keys == g].sum()) for g in range(5)]
got = [int(x) for x in jax.device_get(sums)]
assert got == expect, (got, expect)

# --- one SQL aggregation through the Session over the multi-process mesh --
import pyarrow as pa
from nds_tpu.engine.session import Session

n = 4096  # divisible by the 4-device mesh so fact rows shard
rng = np.random.default_rng(7)
k = rng.integers(0, 8, n)
v = rng.integers(0, 100, n)
t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(v, pa.int64())})
sess = Session(mesh=mesh)
sess.register_arrow("t", t)
out = sess.sql(
    "select k, sum(v) s, count(*) c from t group by k order by k"
).to_pylist()
expect = [
    {"k": int(g), "s": int(v[k == g].sum()), "c": int((k == g).sum())}
    for g in sorted(set(k.tolist()))
]
assert out == expect, (out[:3], expect[:3])
print(f"WORKER{pid} OK", flush=True)
"""


def test_two_process_dcn_tier(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    from nds_tpu.parallel.multihost import worker_env

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), coord],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            # worker_env exports a per-worker trace context on top of the
            # sanitized env, so worker event files fold by trace_id
            env=worker_env(process_id=pid, base=env),
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=560)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"WORKER{pid} OK" in out
