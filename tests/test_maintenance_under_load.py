"""Maintenance-under-load tests: the harness that interleaves DM_*
refresh functions (and a lease-safe vacuum) against a live query stream,
plus the full_bench phase wiring and the tracer-lifecycle contract
(reference scenario: Iceberg/Delta maintenance racing queries under
Spark, which the serialized phases never exercised — ROADMAP item 5)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine.session import Session
from nds_tpu.lakehouse.table import LakehouseTable
from nds_tpu.maintenance import _p99_ms, run_maintenance

DATA = "/tmp/nds_test_sf001"
REFRESH = "/tmp/nds_test_sf001_refresh"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# units + wiring (fast)
# ---------------------------------------------------------------------------


def test_p99_nearest_rank():
    assert _p99_ms([]) is None
    assert _p99_ms([5.0]) == 5.0
    assert _p99_ms([1.0, 2.0, 3.0]) == 3.0
    ts = list(range(1, 201))
    assert _p99_ms(ts) == 198  # ceil(0.99*200) = 198th rank


def test_full_bench_phase_registered_and_opt_in():
    from nds_tpu.full_bench import PHASES, maintenance_under_load_test

    assert "maintenance_under_load" in PHASES
    assert PHASES.index("maintenance_under_load") == len(PHASES) - 1
    # opt-in contract: the orchestrator computes skip from `enabled`
    for params, expect_skip in (
        ({}, True),
        ({"maintenance_under_load": {}}, True),
        ({"maintenance_under_load": {"enabled": False}}, True),
        ({"maintenance_under_load": {"enabled": True}}, False),
    ):
        mul_cfg = params.get("maintenance_under_load") or {}
        assert (not mul_cfg.get("enabled")) == expect_skip
    assert callable(maintenance_under_load_test)


def test_cli_routes_under_load_mode(monkeypatch, tmp_path):
    from nds_tpu.cli import maintenance as cli_m

    calls = {}

    def fake_mul(**kw):
        calls.update(kw)

    monkeypatch.setattr(cli_m, "run_maintenance_under_load", fake_mul)
    cli_m.main([
        "/wh", "/refresh", str(tmp_path / "log.csv"),
        "--under_load_stream", "/streams/query_1.sql",
        "--under_load_report", str(tmp_path / "r.json"),
        "--under_load_queries", "query3,query7",
        "--maintenance_queries", "LF_SS,DF_SS",
    ])
    assert calls["stream_file"] == "/streams/query_1.sql"
    assert calls["sub_queries"] == ["query3", "query7"]
    assert calls["spec_queries"] == ["LF_SS", "DF_SS"]
    assert calls["report_path"] == str(tmp_path / "r.json")


def test_dm_statement_level_conflict_retry(monkeypatch):
    """A commit conflict inside a refresh function re-runs ONLY the
    aborted statement (never the whole function — earlier statements
    already committed), bounded by NDS_LAKE_CONFLICT_RETRIES."""
    from nds_tpu.lakehouse.table import CommitConflictError
    from nds_tpu.maintenance import run_dm_query

    monkeypatch.setenv("NDS_LAKE_COMMIT_BACKOFF", "0")
    monkeypatch.setenv("NDS_LAKE_CONFLICT_RETRIES", "2")
    runs = []

    class FakeSession:
        def run_script(self, q):
            runs.append(q)
            if q == "s2" and runs.count("s2") == 1:
                raise CommitConflictError(
                    "concurrent commit conflict at version 4"
                )

    run_dm_query(FakeSession(), ["s1", "s2", "s3"], "LF_X")
    # s1 once, s2 twice (conflict + re-run), s3 once — no whole-function
    # replay
    assert runs == ["s1", "s2", "s2", "s3"]

    # budget exhaustion surfaces the conflict
    class AlwaysConflict:
        def run_script(self, q):
            raise CommitConflictError("concurrent commit conflict at v9")

    with pytest.raises(CommitConflictError):
        run_dm_query(AlwaysConflict(), ["s1"], "LF_Y")


def test_run_maintenance_closes_tracer_in_finally(monkeypatch, tmp_path):
    """PR-8 contract (satellite): the maintenance harness closes its
    session tracer on ANY exit, so a child dying mid-phase leaves a
    complete, foldable event file instead of a dangling handle."""
    import nds_tpu.maintenance as M

    captured = {}
    real_session = M.Session

    def capturing_session(*a, **kw):
        s = real_session(*a, **kw)
        captured["session"] = s
        return s

    monkeypatch.setattr(M, "Session", capturing_session)
    monkeypatch.setenv("NDS_TRACE_DIR", str(tmp_path / "traces"))
    # a bogus refresh path fails fast inside the body (register_refresh_
    # views), which is exactly the mid-phase death the contract covers
    with pytest.raises(FileNotFoundError):
        run_maintenance(
            warehouse_path=str(tmp_path / "wh-missing"),
            refresh_data_path=str(tmp_path / "refresh-missing"),
            time_log_output_path=str(tmp_path / "t.csv"),
            spec_queries=["LF_SS"],
        )
    s = captured["session"]
    assert s.tracer is not None and s.tracer._closed
    # the event file exists and is complete (trace_meta flushed at close)
    files = os.listdir(tmp_path / "traces")
    assert any(f.startswith("events-") for f in files)


# ---------------------------------------------------------------------------
# deterministic interleaving harness (fast, synthetic warehouse)
# ---------------------------------------------------------------------------


def _mini_warehouse(tmp_path, rows=64):
    """A synthetic lakehouse 'warehouse' with one fact-like table."""
    path = str(tmp_path / "fact")
    LakehouseTable.create(
        path,
        pa.table({
            "k": pa.array(np.arange(rows) % 8, type=pa.int64()),
            "v": pa.array(np.arange(rows), type=pa.int64()),
        }),
    )
    s = Session(conf={"lakehouse.warehouse": str(tmp_path)})
    s.register_lakehouse("fact", path)
    return s, path


QUERY = "select k, count(*) c, sum(v) s from fact group by k order by k"


def test_query_stream_pinned_results_invariant_under_dm_commits(tmp_path):
    """The interleaving oracle: a query pinned at version N returns
    bit-identical results whether DM_* commits land before plan time,
    between plan and execution ('during'), or after — under deterministic
    schedule control (no timing luck)."""
    s, path = _mini_warehouse(tmp_path)
    before = s.sql(QUERY).collect()  # no commits yet

    # 'during': plan now (pin v1), land an insert + a delete + a second
    # insert, wipe caches, then execute
    r = s.sql(QUERY)
    writer = LakehouseTable(path)
    writer.append(pa.table({
        "k": pa.array([3], type=pa.int64()),
        "v": pa.array([10_000], type=pa.int64()),
    }))
    kept = writer.snapshot().dataset().to_table().filter(
        pa.compute.less(pa.compute.field("v"), 10)
    )
    writer.replace(kept, operation="delete")
    s.recover_memory("test: no cache luck")
    assert r.collect().equals(before)

    # 'after': a fresh statement sees the post-maintenance state
    after = s.sql(QUERY).collect()
    assert not after.equals(before)
    assert after.num_rows >= 1


def test_interleaved_writer_thread_with_schedule_and_vacuum(tmp_path):
    """Two-thread schedule: the reader pins, signals; the maintenance
    thread appends + vacuums; reader re-executes its pinned statement and
    gets the identical table; its pinned files survived the vacuum."""
    s, path = _mini_warehouse(tmp_path)
    r = s.sql(QUERY)
    baseline = r.collect()
    pinned = threading.Event()
    maintained = threading.Event()
    results = {}

    def maintenance_thread():
        assert pinned.wait(10)
        w = LakehouseTable(path)
        w.append(pa.table({
            "k": pa.array([0], type=pa.int64()),
            "v": pa.array([777], type=pa.int64()),
        }))
        w.replace(w.snapshot().dataset().to_table())  # copy-on-write churn
        results["vacuum"] = w.vacuum(retain_last=1)
        maintained.set()

    t = threading.Thread(target=maintenance_thread, daemon=True)
    t.start()
    pinned.set()
    assert maintained.wait(30)
    t.join(10)
    # the reader's pinned snapshot survived maintenance + vacuum: its
    # lease kept every file it references
    s.recover_memory("test: re-read pinned files post-vacuum")
    assert r.collect().equals(baseline)
    # and the vacuum DID collect something (the un-leased middle version)
    assert results["vacuum"]["manifests_removed"] >= 1


# ---------------------------------------------------------------------------
# SF0.01 end-to-end (slow: runs in ci/tier1-check's standalone gate)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


@pytest.fixture(scope="module")
def refresh_dir():
    if not os.path.exists(os.path.join(REFRESH, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", REFRESH, "--update", "1",
             "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(REFRESH, ".complete"), "w").close()
    return REFRESH


@pytest.fixture(scope="module")
def warehouse(data_dir, tmp_path_factory):
    wh = tmp_path_factory.mktemp("lake_mul")
    subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.transcode", data_dir, str(wh),
         str(wh / "load.report"), "--output_format", "lakehouse"],
        check=True, capture_output=True, cwd=REPO,
        env={**os.environ, "NDS_PLATFORM": "cpu"},
    )
    return wh


def _scrape(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.read().decode()


@pytest.mark.slow
def test_maintenance_under_load_e2e(warehouse, refresh_dir, tmp_path):
    """The full phase at SF0.01: DM functions + vacuum racing a real
    query stream, lake_commit/lake_vacuum events visible in the profile,
    nds_lake_* counters scrapeable from /metrics MID-RUN, and the report
    carrying maintenance throughput x p99 degradation."""
    from nds_tpu.datagen.query_streams import generate_streams
    from nds_tpu.maintenance import run_maintenance_under_load
    from nds_tpu.obs import metrics as M
    from nds_tpu.obs import reader as R

    streams = tmp_path / "streams"
    generate_streams(str(streams), 2, 0.01, rngseed=19620718)
    props = tmp_path / "mul.properties"
    trace_dir = tmp_path / "traces"
    props.write_text(
        "engine.metrics_port=0\n"
        f"engine.trace_dir={trace_dir}\n"
    )
    M.reset_shared()
    report_path = tmp_path / "mul_report.json"
    box = {}

    def run():
        box["report"] = run_maintenance_under_load(
            warehouse_path=str(warehouse),
            refresh_data_path=refresh_dir,
            stream_file=str(streams / "query_1.sql"),
            time_log_output_path=str(tmp_path / "mul_time.csv"),
            report_path=str(report_path),
            property_file=str(props),
            spec_queries=["LF_SS", "DF_SS"],
            sub_queries=["query3", "query7", "query52"],
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # mid-run scrape: wait for the endpoint, then for the first lake
    # commit counters to land while the run is still going
    deadline = time.monotonic() + 300
    exposition = None
    while time.monotonic() < deadline and t.is_alive():
        server = M.active_server()
        if server is not None:
            try:
                text = _scrape(server.port, "/metrics")
            except OSError:
                text = ""
            if "nds_lake_commit_total" in text:
                exposition = text
                break
        time.sleep(0.25)
    t.join(600)
    assert not t.is_alive(), "under-load run did not finish"
    assert exposition is not None, (
        "nds_lake_* counters never appeared on /metrics mid-run"
    )
    assert M.validate_exposition(exposition) == []
    assert "nds_lake_commit_attempts_total" in exposition

    report = box["report"]
    assert report == json.load(open(report_path))
    assert report["dm_functions"] == 2 and report["dm_failed"] == 0
    assert report["under_load_failed"] == 0 and report["solo_failed"] == 0
    assert report["query_p99_ms_solo"] > 0
    assert report["query_p99_ms_under_load"] > 0
    assert report["query_p99_degradation"] > 0
    assert report["dm_functions_per_s"] > 0
    assert report["vacuums"] > 0

    # the profile over the run's event files carries the lake evidence
    files = R.discover_event_files(str(trace_dir))
    assert files
    events = []
    for f in files:
        events.extend(R.iter_events(f))
    prof = R.profile_events(events)
    assert prof["tallies"]["lake_commits"] > 0
    assert prof["tallies"]["lake_vacuums"] > 0
    # time log rows cover solo, under_load and dm entries
    import csv

    rows = list(csv.reader(open(tmp_path / "mul_time.csv")))
    tags = {r[1].split(":")[0] for r in rows[1:] if len(r) >= 2}
    assert {"warmup", "solo", "under_load", "dm"} <= tags
    M.reset_shared()


@pytest.mark.slow
def test_under_load_dm_thread_failure_is_loud(warehouse, refresh_dir,
                                              tmp_path):
    """A maintenance-thread failure (here: an injected io fault escaping
    the under-load vacuum) must not read as a clean completion: the
    report carries dm_error AND the runner raises after writing it."""
    from nds_tpu.maintenance import run_maintenance_under_load

    faults.install("io:vacuum:store_sales:1")
    report_path = tmp_path / "fail_report.json"
    with pytest.raises(RuntimeError, match="DM thread failed"):
        run_maintenance_under_load(
            warehouse_path=str(warehouse),
            refresh_data_path=refresh_dir,
            stream_file=_mini_stream(tmp_path),
            time_log_output_path=str(tmp_path / "fail_time.csv"),
            report_path=str(report_path),
            spec_queries=["LF_SS"],
            sub_queries=["query52"],
        )
    report = json.load(open(report_path))
    assert "TransientIOError" in report["dm_error"]
    assert report["dm_functions"] == 1  # the function itself completed


def _mini_stream(tmp_path):
    from nds_tpu.datagen.query_streams import generate_streams

    d = tmp_path / "mini_streams"
    generate_streams(str(d), 1, 0.01, rngseed=19620718)
    return str(d / "query_0.sql")
