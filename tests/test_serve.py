"""Serve-mode tests: admission control, backpressure, snapshot-pinned
requests under racing DM commits, drain, per-tenant accounting, stream
jobs, fault-family robustness, and the closed-loop SF0.01 e2e (slow —
ci/tier1-check runs it in the standalone serve gate).

Most tests run against a synthetic in-memory (or mini-lakehouse)
session behind the REAL HTTP listener — the same obs/httpserv.py
process-wide endpoint production uses — so the wire contract (status
codes, Retry-After, envelope fields) is what is asserted, not internal
callables."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine.session import Session
from nds_tpu.lakehouse.table import LakehouseTable
from nds_tpu.obs import metrics as M
from nds_tpu.serve.service import QueryService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.reset()
    M.reset_shared()
    yield
    faults.reset()
    M.reset_shared()


def _fact_table(rows=64):
    return pa.table({
        "k": pa.array(np.arange(rows) % 8, type=pa.int64()),
        "v": pa.array(np.arange(rows), type=pa.int64()),
    })


def _make_service(conf=None, templates=None, lake_path=None, job_dir=None,
                  rows=64):
    """A service over one synthetic session behind a real ephemeral
    listener. Returns (service, port, session)."""
    conf = {"engine.metrics_port": 0, **(conf or {})}
    session = Session(conf=conf)
    if lake_path is not None:
        session.register_lakehouse("fact", lake_path)
    else:
        session.register_arrow("fact", _fact_table(rows))
    service = QueryService(
        session, templates=templates, job_dir=job_dir
    )
    server = M.active_server()
    assert server is not None, "ephemeral metrics listener failed to bind"
    server.attach_app(service)
    return service, server.port, session


def _post(port, payload, tenant="default", path="/query", timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 "X-NDS-Tenant": tenant},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except ValueError:
            body = {}
        return e.code, body, dict(e.headers)


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


QUERY = "select k, count(*) c, sum(v) s from fact group by k order by k"


# ---------------------------------------------------------------------------
# request round trip, pagination, templates
# ---------------------------------------------------------------------------


def test_query_roundtrip_and_pagination():
    service, port, _ = _make_service(conf={"engine.serve_row_cap": 3})
    status, body, _ = _post(port, {"sql": QUERY})
    assert status == 200
    assert body["status"] == "completed"
    assert body["columns"] == ["k", "c", "s"]
    # row cap truncates: 8 groups, cap 3
    assert body["row_count"] == 3 and body["total_rows"] == 8
    assert body["truncated"] is True
    assert body["verdict"] in ("direct", "unknown")
    assert body["admitted_degraded"] is False
    assert body["request_id"]
    # page 2
    status, page2, _ = _post(port, {"sql": QUERY, "offset": 3, "limit": 3})
    assert status == 200
    assert [r[0] for r in page2["rows"]] == [3, 4, 5]
    # final page is not truncated
    status, page3, _ = _post(port, {"sql": QUERY, "offset": 6, "limit": 3})
    assert page3["row_count"] == 2 and page3["truncated"] is False
    # limit 0 is a metadata-only probe: envelope without row payload
    status, meta, _ = _post(port, {"sql": QUERY, "limit": 0})
    assert meta["rows"] == [] and meta["row_count"] == 0
    assert meta["total_rows"] == 8 and meta["truncated"] is True
    service.close()


def test_template_resolution_and_errors():
    service, port, _ = _make_service(
        templates={"q_k": "select k from fact where k = ${K} limit 1"}
    )
    status, body, _ = _post(
        port, {"template": "q_k", "params": {"K": 3}}
    )
    assert status == 200 and body["rows"] == [[3]]
    status, body, _ = _post(port, {"template": "nope"})
    assert status == 404
    status, body, _ = _post(port, {})
    assert status == 400 and "sql" in body["error"]
    # multi-statement scripts and session-mutating DDL are refused
    status, body, _ = _post(
        port, {"sql": "select 1 from fact; select 2 from fact"}
    )
    assert status == 400
    status, body, _ = _post(
        port, {"sql": "create temp view z as select k from fact"}
    )
    assert status == 400 and "serve mode" in body["error"]
    service.close()


def test_unknown_route_404_and_parse_error_400():
    service, port, _ = _make_service()
    status, _, _ = _post(port, {}, path="/nope")
    assert status == 404
    status, body, _ = _post(port, {"sql": "selec k frm fact"})
    assert status == 400
    service.close()


# ---------------------------------------------------------------------------
# admission control (the budgeter verdict contract)
# ---------------------------------------------------------------------------


def test_admission_reject_429_carries_modeled_bytes():
    # budget + reject line far below what a 64Ki-row scan models: the
    # request must be refused BEFORE dispatch with the modeled bytes in
    # the body (the client can size its retry/shard decision from them)
    service, port, _ = _make_service(
        conf={
            "engine.plan_budget_bytes": 1024,
            "engine.plan_budget_reject_bytes": 2048,
        },
        rows=1 << 16,
    )
    status, body, _ = _post(port, {"sql": "select k + v from fact"})
    assert status == 429
    assert body["status"] == "rejected" and body["verdict"] == "reject"
    assert body["peak_bytes"] > 2048
    assert body["budget_bytes"] == 1024
    service.close()


def test_degraded_admit_echoes_verdict_in_envelope():
    # over budget but under the reject line, with an out-of-core seam
    # (ORDER BY): admitted DEGRADED — the verdict rides the envelope and
    # the result is still correct
    service, port, _ = _make_service(
        conf={
            "engine.plan_budget_bytes": 1024,
            "engine.serve_row_cap": 1 << 17,
        },
        rows=1 << 12,
    )
    status, body, _ = _post(
        port, {"sql": "select k, v from fact order by v desc"}
    )
    assert status == 200
    assert body["verdict"] in ("spill", "over", "blocked")
    assert body["admitted_degraded"] is True
    assert body["rows"][0][1] == (1 << 12) - 1  # sorted desc, correct
    service.close()


def test_backpressure_sheds_with_retry_after():
    # a 1-byte RSS watermark is always exceeded: every request is shed
    # with 429 + Retry-After BEFORE planning (backpressure, not failure)
    service, port, _ = _make_service(
        conf={"engine.host_rss_watermark": 1}
    )
    status, body, headers = _post(port, {"sql": QUERY})
    assert status == 429
    assert body["status"] == "shed"
    assert "watermark" in body["error"]
    assert headers.get("Retry-After")
    service.close()


def test_capacity_shed_and_tenant_flood_cap():
    service, port, _ = _make_service(
        conf={
            "engine.serve_workers": 2,
            "engine.serve_tenant_cap": 1,
            "engine.serve_admit_timeout_s": 0.05,
        }
    )
    # tenant flood: one slot held by tenant A caps A, other tenants pass
    service._enter("tenant-a")
    try:
        status, body, headers = _post(port, {"sql": QUERY}, tenant="tenant-a")
        assert status == 429 and body["status"] == "shed"
        assert "cap" in body["error"] and headers.get("Retry-After")
        status, _, _ = _post(port, {"sql": QUERY}, tenant="tenant-b")
        assert status == 200
    finally:
        service._leave("tenant-a")
    # capacity: both slots held -> every tenant sheds after the bounded
    # admission wait
    service._enter("x")
    service._enter("y")
    try:
        status, body, _ = _post(port, {"sql": QUERY}, tenant="tenant-c")
        assert status == 429 and "admission slot" in body["error"]
    finally:
        service._leave("x")
        service._leave("y")
    status, _, _ = _post(port, {"sql": QUERY}, tenant="tenant-c")
    assert status == 200
    service.close()


# ---------------------------------------------------------------------------
# drain + healthz
# ---------------------------------------------------------------------------


def test_drain_completes_in_flight_then_refuses():
    service, port, _ = _make_service(
        conf={"engine.serve_drain_timeout_s": 10}
    )
    assert _get(port, "/healthz") == (200, "ok\n")
    service._enter("t")  # simulated in-flight work
    box = {}

    def drain():
        box["resp"] = _post(port, {}, path="/drain")

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    # the drain flips healthz IMMEDIATELY (LBs stop routing before the
    # pool empties) and waits for the in-flight request
    deadline = time.monotonic() + 5
    while _get(port, "/healthz")[0] != 503 and time.monotonic() < deadline:
        time.sleep(0.02)
    code, text = _get(port, "/healthz")
    assert code == 503 and "draining" in text
    assert not box  # still waiting on the in-flight slot
    service._leave("t")
    t.join(10)
    status, body, _ = box["resp"]
    assert status == 200 and body["drained"] is True
    assert body["in_flight"] == 0
    # admissions now refuse with 503 + Retry-After
    status, body, headers = _post(port, {"sql": QUERY})
    assert status == 503 and body["status"] == "draining"
    assert headers.get("Retry-After")
    service.close()


def test_request_queued_before_drain_sheds_after_semaphore_wait():
    """A request blocked in the admission wait when /drain lands must
    SHED (503) when its slot frees, never start executing after the
    drain reported drained=true (the rolling-restart lost-work hole)."""
    service, port, _ = _make_service(
        conf={
            "engine.serve_workers": 1,
            "engine.serve_admit_timeout_s": 20,
            "engine.serve_drain_timeout_s": 10,
        }
    )
    service._enter("holder")  # occupy the only slot
    box = {}

    def queued():
        box["resp"] = _post(port, {"sql": QUERY}, tenant="queued")

    t = threading.Thread(target=queued, daemon=True)
    t.start()
    time.sleep(0.3)  # the request is now blocked in the semaphore wait
    drain_box = {}
    dt = threading.Thread(
        target=lambda: drain_box.update(r=_post(port, {}, path="/drain")),
        daemon=True,
    )
    dt.start()
    time.sleep(0.3)
    service._leave("holder")  # frees the slot: queued request acquires it
    t.join(30)
    dt.join(30)
    status, body, headers = box["resp"]
    assert status == 503 and body["status"] == "draining"
    assert headers.get("Retry-After")
    drain_status, drain_body, _ = drain_box["r"]
    assert drain_status == 200 and drain_body["drained"] is True
    service.close()


# ---------------------------------------------------------------------------
# snapshot isolation vs racing DM commits (the PR-10 seam)
# ---------------------------------------------------------------------------


def _mini_lake(tmp_path, rows=64):
    path = str(tmp_path / "fact")
    LakehouseTable.create(path, _fact_table(rows))
    return path


def test_snapshot_pinned_request_vs_racing_dm_commit(tmp_path):
    """A request planned at version N answers version-N rows even when a
    DM commit lands between its plan and its execution. The serve:exec
    hang opens a deterministic window; the lakehouse _COMMIT_HOOK seam
    records the racing commit's landing time so the interleaving is
    asserted, not assumed."""
    from nds_tpu.lakehouse import table as lake_table

    path = _mini_lake(tmp_path)
    service, port, _ = _make_service(lake_path=path)
    q = "select k, count(*) c, sum(v) s from fact group by k order by k"
    status, baseline, _ = _post(port, {"sql": q})
    assert status == 200

    commits = []
    lake_table._COMMIT_HOOK = (
        lambda name, op, version: commits.append(
            (name, op, version, time.monotonic())
        )
    )
    faults.install("hang:serve:exec:2")
    box = {}

    def request():
        box["t_planned"] = time.monotonic()
        box["resp"] = _post(port, {"sql": q})
        box["t_done"] = time.monotonic()

    try:
        t = threading.Thread(target=request, daemon=True)
        t.start()
        time.sleep(0.5)  # inside the 2s serve:exec hang: planned, pinned
        writer = LakehouseTable(path)
        writer.append(pa.table({
            "k": pa.array([0], type=pa.int64()),
            "v": pa.array([100_000], type=pa.int64()),
        }))
        t.join(60)
    finally:
        lake_table._COMMIT_HOOK = None
    status, body, _ = box["resp"]
    assert status == 200
    # the racing commit landed while the request was in flight
    assert commits and commits[0][1] == "append"
    assert box["t_planned"] < commits[0][3] < box["t_done"]
    # ... and the response is the PINNED snapshot, bit-equal to baseline
    assert body["rows"] == baseline["rows"]
    # a FRESH request reads the new head
    status, after, _ = _post(port, {"sql": q})
    assert status == 200 and after["rows"] != baseline["rows"]
    service.close()


def test_dml_commits_through_writer_path(tmp_path):
    path = _mini_lake(tmp_path, rows=8)
    service, port, session = _make_service(lake_path=path)
    status, before, _ = _post(port, {"sql": "select count(*) c from fact"})
    n0 = before["rows"][0][0]
    status, body, _ = _post(
        port,
        {"sql": "insert into fact select k, v + 1000 from fact where v < 8"},
        tenant="writer",
    )
    assert status == 200
    assert body["status"] == "completed" and body["statement"] == "dml"
    assert body["rows_affected"] == 8 and body["version"] == 2
    status, after, _ = _post(port, {"sql": "select count(*) c from fact"})
    assert after["rows"][0][0] == n0 + 8
    service.close()


def test_serve_request_traceable_by_single_trace_id(tmp_path):
    """ISSUE-14 acceptance: ONE trace_id (= the request id) follows a
    serve request end to end — the admission echo (serve_request), the
    ladder rung its injected OOM walked, every op/catalog span of its
    execution — and a DM request's lakehouse commit carries ITS id the
    same way. Proven by grepping the folded event log for exactly one
    trace_id per request."""
    from nds_tpu.obs import reader as R

    path = _mini_lake(tmp_path)
    trace = tmp_path / "trace"
    service, port, session = _make_service(
        conf={"engine.trace_dir": str(trace)}, lake_path=path
    )
    faults.install("oom:serve:exec")  # one rung of ladder evidence
    q = "select k, sum(v) s from fact group by k order by k"
    status, body, _ = _post(port, {"sql": q})
    assert status == 200 and body["retries"] == 1
    rid = body["request_id"]
    status, dm, _ = _post(
        port,
        {"sql": "insert into fact select k, v + 500 from fact where v < 4"},
        tenant="writer",
    )
    assert status == 200
    rid_dm = dm["request_id"]
    evs = R.read_events(str(trace), strict=True)
    assert R.validate_events(evs) == []
    mine = [
        e for e in evs
        if e.get("request_id") == rid or e.get("trace_id") == rid
    ]
    kinds = {e["kind"] for e in mine}
    assert {"serve_request", "op_span", "catalog_load", "query_span",
            "ladder_rung", "fault_injected"} <= kinds
    # exactly ONE trace_id across the request's whole event stream
    assert {e["trace_id"] for e in mine} == {rid}
    dm_evs = [e for e in evs if e.get("trace_id") == rid_dm]
    dm_kinds = {e["kind"] for e in dm_evs}
    assert {"serve_request", "lake_commit", "query_span"} <= dm_kinds
    assert {e["trace_id"] for e in dm_evs} == {rid_dm}
    # the two requests never alias
    assert rid != rid_dm
    service.close()


def test_debug_jaxprof_start_stop_on_live_service(tmp_path):
    """The on-demand jax.profiler verbs on the live listener: start
    writes a trace under the requested dir, a second start conflicts,
    stop ends it — all without touching in-flight query service."""
    import glob as _glob

    service, port, _ = _make_service()
    prof_dir = str(tmp_path / "prof")
    status, body, _ = _post(
        port, {"action": "start", "dir": prof_dir}, path="/debug/jaxprof"
    )
    assert status == 200 and body["running"] and body["dir"] == prof_dir
    status, body, _ = _post(port, {"action": "start"},
                            path="/debug/jaxprof")
    assert status == 409  # one profiler per process
    # the service still answers queries while profiling
    status, q, _ = _post(port, {"sql": "select count(*) c from fact"})
    assert status == 200
    status, body, _ = _post(port, {"action": "stop"},
                            path="/debug/jaxprof")
    assert status == 200 and body["running"] is False
    assert _glob.glob(os.path.join(prof_dir, "**", "*"), recursive=True)
    status, body, _ = _post(port, {"action": "bogus"},
                            path="/debug/jaxprof")
    assert status == 400
    service.close()


# ---------------------------------------------------------------------------
# fault family: the server survives what its requests do not
# ---------------------------------------------------------------------------


def test_serve_exec_oom_walks_ladder_and_pool_stays_healthy():
    service, port, _ = _make_service()
    faults.install("oom:serve:exec:1")
    status, body, _ = _post(port, {"sql": QUERY})
    assert status == 200 and body["status"] == "completed"
    assert body["retries"] >= 1  # the ladder recovered the injected OOM
    assert service.in_flight() == 0
    status, _, _ = _post(port, {"sql": QUERY})
    assert status == 200
    service.close()


def test_serve_admit_fault_sheds_not_crashes():
    service, port, _ = _make_service()
    faults.install("io:serve:admit:1")
    status, body, _ = _post(port, {"sql": QUERY})
    assert status == 429 and body["status"] == "shed"
    assert body["failure_kind"] == faults.IO_TRANSIENT
    status, _, _ = _post(port, {"sql": QUERY})
    assert status == 200
    service.close()


def test_disconnect_mid_query_leaves_worker_pool_healthy():
    service, port, _ = _make_service()
    payload = json.dumps({"sql": QUERY}).encode()
    request = (
        b"POST /query HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: " + str(len(payload)).encode() + b"\r\n\r\n"
        + payload
    )
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(request)
    s.close()  # hang up before the reply: the slow-client scenario
    deadline = time.monotonic() + 30
    while service.in_flight() > 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert service.in_flight() == 0
    status, body, _ = _post(port, {"sql": QUERY})
    assert status == 200 and body["status"] == "completed"
    service.close()


# ---------------------------------------------------------------------------
# per-tenant accounting + per-request in-flight isolation
# ---------------------------------------------------------------------------


def test_per_tenant_stats_on_statusz_and_metrics():
    service, port, _ = _make_service()
    for _ in range(2):
        _post(port, {"sql": QUERY}, tenant="alpha")
    _post(port, {"sql": QUERY}, tenant="beta")
    _post(port, {"sql": "selec nope"}, tenant="beta")  # 400 -> failed
    code, text = _get(port, "/statusz")
    st = json.loads(text)
    tenants = st["tenants"]
    assert tenants["alpha"]["requests"] == 2
    assert tenants["alpha"]["completed"] == 2
    assert tenants["beta"]["requests"] == 2
    assert tenants["beta"]["failed"] == 1
    # the repeated query hit the warm caches on its second run
    assert tenants["alpha"]["exec_cache_lookups"] > 0
    assert tenants["alpha"]["exec_cache_hit_rate"] is not None
    code, exposition = _get(port, "/metrics")
    assert M.validate_exposition(exposition) == []
    assert 'nds_serve_request_total{status="completed",tenant="alpha"} 2' in (
        exposition
    )
    assert "nds_serve_request_dur_ms_bucket" in exposition
    service.close()


def test_in_flight_records_keyed_per_request_id():
    """The satellite fix: two concurrent identical queries (same app id,
    same query name — one serve session, two tenants) hold SEPARATE
    in-flight records, and each query_span retires only its own."""
    sink = M.MetricsSink()
    sink.query_started("query3", app="app-1", request_id="r1")
    sink.query_started("query3", app="app-1", request_id="r2")
    st = sink.status_snapshot()
    assert len(st["in_flight"]) == 2
    assert {r.get("request_id") for r in st["in_flight"]} == {"r1", "r2"}
    sink.record({
        "kind": "ladder_rung", "app": "app-1", "query": "query3",
        "request_id": "r2", "rung": "recover_retry",
    })
    sink.record({
        "kind": "query_span", "app": "app-1", "query": "query3",
        "request_id": "r1", "dur_ms": 5.0, "status": "Completed",
        "retries": 0,
    })
    st = sink.status_snapshot()
    assert len(st["in_flight"]) == 1
    rec = st["in_flight"][0]
    assert rec["request_id"] == "r2" and rec["ladder"] == ["recover_retry"]
    # legacy callers (no request id) keep the (app, query) semantics
    sink.query_started("q", app="a")
    sink.record({
        "kind": "query_span", "app": "a", "query": "q", "dur_ms": 1.0,
        "status": "Completed", "retries": 0,
    })
    assert len(sink.status_snapshot()["in_flight"]) == 1  # r2 only


def test_concurrent_identical_queries_isolated_end_to_end():
    service, port, _ = _make_service(
        conf={"engine.serve_workers": 4}
    )
    results = []

    def go(tenant):
        results.append(_post(port, {"sql": QUERY}, tenant=tenant))

    threads = [
        threading.Thread(target=go, args=(f"t{i}",), daemon=True)
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert len(results) == 3
    assert all(status == 200 for status, _, _ in results)
    rows = [body["rows"] for _, body, _ in results]
    assert rows[0] == rows[1] == rows[2]
    rids = {body["request_id"] for _, body, _ in results}
    assert len(rids) == 3
    # nothing left dangling on /statusz
    st = json.loads(_get(port, "/statusz")[1])
    assert st["in_flight"] == []
    service.close()


# ---------------------------------------------------------------------------
# stream jobs (resumable, bench_state pattern)
# ---------------------------------------------------------------------------


def _mini_stream(tmp_path):
    p = tmp_path / "query_9.sql"
    p.write_text(
        "-- start query 1 in stream 9 using template query1.tpl\n"
        "select k, sum(v) s from fact group by k order by k;\n"
        "-- start query 2 in stream 9 using template query2.tpl\n"
        "select count(*) c from fact;\n"
    )
    return str(p)


def test_stream_job_runs_checkpoints_and_resumes(tmp_path):
    stream = _mini_stream(tmp_path)
    job_dir = str(tmp_path / "jobs")
    service, port, _ = _make_service(job_dir=job_dir)
    status, job, _ = _post(
        port, {"stream": stream, "job_id": "j1"}, path="/stream"
    )
    assert status == 202 and job["job_id"] == "j1"
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        _, job = _get_job(port, "j1")
        if job["state"] in ("completed", "failed"):
            break
        time.sleep(0.1)
    assert job["state"] == "completed"
    assert job["total"] == 2 and job["completed"] == 2 and job["failed"] == 0
    state_file = os.path.join(job_dir, "serve-job-j1.json")
    assert os.path.exists(state_file)
    # resubmission resumes from the checkpoint: everything already
    # completed, the job finishes without re-running a single query
    before = json.load(open(state_file))
    status, job2, _ = _post(
        port, {"stream": stream, "job_id": "j1"}, path="/stream"
    )
    assert status == 202
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        _, job2 = _get_job(port, "j1")
        if job2["state"] in ("completed", "failed"):
            break
        time.sleep(0.05)
    assert job2["state"] == "completed"
    # per-query records survived verbatim (nothing re-ran)
    after = json.load(open(state_file))
    assert after["queries"] == before["queries"]
    # a different stream under the same id is a loud 400, not a mixup
    other = tmp_path / "other.sql"
    other.write_text(
        "-- start query 1 in stream 0 using template query5.tpl\n"
        "select count(*) c from fact;\n"
    )
    status, body, _ = _post(
        port, {"stream": str(other), "job_id": "j1"}, path="/stream"
    )
    assert status == 400 and "different stream" in body["error"]
    status, body = _get_job(port, "missing")
    assert status == 404
    service.close()


def _get_job(port, job_id):
    code, text = _get(port, f"/jobs/{job_id}")
    return code, json.loads(text)


def test_reload_drops_pins_and_caches(tmp_path):
    path = _mini_lake(tmp_path, rows=8)
    service, port, session = _make_service(lake_path=path)
    _post(port, {"sql": "select count(*) c from fact"})
    assert session.catalog.entries["fact"].pinned_version is not None
    status, body, _ = _post(port, {}, path="/reload")
    assert status == 200 and body["reloaded"] is True
    assert session.catalog.entries["fact"].pinned_version is None
    status, body, _ = _post(port, {"sql": "select count(*) c from fact"})
    assert status == 200
    service.close()


def test_reload_releases_dropped_lease_after_last_in_flight(tmp_path):
    """Satellite (PR-12 leftover): a /reload with statements in flight
    keeps the dropped pin's reader lease alive while they run, then
    releases it when the LAST of them finishes — instead of abandoning
    it to the 300s TTL. With the pool idle, the release is immediate."""
    from nds_tpu.lakehouse.leases import LEASES

    path = _mini_lake(tmp_path, rows=8)
    root = LakehouseTable(path).root
    service, port, session = _make_service(lake_path=path)
    baseline = LEASES.live_count(root)
    q = "select k, count(*) c from fact group by k order by k"

    # idle reload: pin's lease released on the spot, not TTL-abandoned
    _post(port, {"sql": q})
    assert LEASES.live_count(root) == baseline + 1
    status, body, _ = _post(port, {}, path="/reload")
    assert status == 200 and body["leases_dropped"] == 1
    assert body["leases_deferred"] == 0
    assert LEASES.live_count(root) == baseline

    # reload WITH a statement in flight: deferred until it finishes
    faults.install("hang:serve:exec:1.5")
    box = {}

    def request():
        box["resp"] = _post(port, {"sql": q})

    t = threading.Thread(target=request, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while service.in_flight() == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert service.in_flight() == 1
    dropped_lid = session.catalog.entries["fact"].lease_id
    assert dropped_lid is not None
    status, body, _ = _post(port, {}, path="/reload")
    assert status == 200 and body["leases_deferred"] == body["leases_dropped"]
    # NOT released yet: the in-flight statement may still be scanning
    assert dropped_lid in LEASES._leases
    t.join(30)
    assert box["resp"][0] == 200
    # released promptly once the last in-flight statement finished — the
    # 300s TTL cannot explain this. (The entry may hold a FRESH pin from
    # the statement's execution-time re-pin; only the dropped lease must
    # be gone.)
    deadline = time.monotonic() + 5
    while dropped_lid in LEASES._leases and time.monotonic() < deadline:
        time.sleep(0.02)
    assert dropped_lid not in LEASES._leases
    service.close()


# ---------------------------------------------------------------------------
# knob derivations
# ---------------------------------------------------------------------------


def test_serve_concurrency_derives_from_budget():
    from nds_tpu.analysis.budget import SERVE_SLOT_BYTES, serve_concurrency

    assert serve_concurrency({"engine.serve_workers": 7}) == 7
    assert serve_concurrency(
        {"engine.plan_budget_bytes": 4 * SERVE_SLOT_BYTES}
    ) == 4
    assert serve_concurrency({"engine.plan_budget_bytes": 1}) == 1
    assert serve_concurrency(
        {"engine.plan_budget_bytes": 1 << 50}
    ) == 16  # clamped


def test_event_schema_has_serve_request():
    from nds_tpu.obs.trace import EVENT_SCHEMA

    assert set(EVENT_SCHEMA["serve_request"]) == {
        "tenant", "status", "dur_ms", "http_status",
    }
    for family in (
        "nds_serve_request_total", "nds_serve_request_dur_ms",
        "nds_serve_request_ms_total",
    ):
        assert M.METRIC_KINDS[family] == "serve_request"


# ---------------------------------------------------------------------------
# SF0.01 closed-loop e2e (slow: runs in ci/tier1-check's serve gate)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_closed_loop_sf001_e2e():
    """The acceptance scenario: >= 4 concurrent closed-loop clients
    (point lookups + heavy aggregates + DM writes) against the real
    service over the SF0.01 lakehouse — zero 5xx, zero snapshot
    violations under the racing commits, QPS x p99 reported, and the
    server-side p99 scraped from /metrics MID-RUN."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py")
    )
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    report = sb.run_bench(clients=4, smoke=True)
    assert report["requests"] >= 20
    assert report["completed"] > 0 and report["qps"] > 0
    assert report["http_5xx"] == 0
    assert report["rejected_429"] == 0
    assert report["snapshot_violations"] == 0
    assert report["final_snapshot_consistent"] is True
    assert report["dm_commits"] > 0  # commits actually raced the readers
    assert report["p99_ms"] > 0
    # the mid-run scrape saw the live histogram and it validated
    assert report["scraped_requests"] > 0
    assert report["exposition_valid"] is True
    assert report["by_class"]["heavy"]["completed"] > 0
    assert report["by_class"]["point"]["completed"] > 0
