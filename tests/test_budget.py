"""Static plan budgeter (analysis/budget.py) + the invariants that rode
along with it: budget-vs-actual calibration over real SF0.01 data, static
blocked-window sizing parity with the runtime derivation, the ladder's
budget_shrink rung, host-RSS watermark pre-emption, the sharding verifier
rule family (seeded violations per rule), and the new lint rules
(cache-lock-discipline, unread-conf-knob).
"""

import os
import subprocess
import sys
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.analysis import budget as B
from nds_tpu.analysis import lint as L
from nds_tpu.analysis.verifier import (
    PlanVerifier,
    PlanVerifyError,
    verify_plan,
)
from nds_tpu.engine import expr as E
from nds_tpu.engine import plan as P
from nds_tpu.engine.session import Session, _Entry
from nds_tpu.obs import memwatch
from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer
from nds_tpu.report import BenchReport
from nds_tpu.schema import get_schemas

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# row / width model
# ---------------------------------------------------------------------------


def test_spec_table_rows_matches_generator_model():
    # exact spec dims at the defined scale points
    assert B.spec_table_rows("date_dim", 1.0) == 73049
    assert B.spec_table_rows("item", 1.0) == 18000
    assert B.spec_table_rows("item", 10.0) == 102000
    assert B.spec_table_rows("store", 1.0) == 12
    assert B.spec_table_rows("store", 10.0) == 102
    assert B.spec_table_rows("customer_demographics", 100.0) == 1920800
    # facts: orders x average lines, linear in SF
    assert B.spec_table_rows("store_sales", 1.0) == 2880000
    assert B.spec_table_rows("store_sales", 10.0) == 28800000
    assert B.spec_table_rows("catalog_sales", 1.0) == 1440000
    assert B.spec_table_rows("web_sales", 1.0) == 720000
    # returns ~10% of sales lines; inventory is the weekly cross product
    assert B.spec_table_rows("store_returns", 1.0) == 288000
    assert B.spec_table_rows("inventory", 1.0) == 261 * 9000 * 5
    # interpolation between knots is monotone
    assert (
        B.spec_table_rows("customer", 1.0)
        < B.spec_table_rows("customer", 3.0)
        < B.spec_table_rows("customer", 10.0)
    )
    assert B.spec_table_rows("not_a_table", 1.0) is None


def test_width_model_mirrors_device_layout():
    from nds_tpu.dtypes import parse_dtype

    assert B.column_row_bytes(parse_dtype("int32")) == 5
    assert B.column_row_bytes(parse_dtype("date")) == 5
    assert B.column_row_bytes(parse_dtype("string")) == 5  # int32 codes
    assert B.column_row_bytes(parse_dtype("int64")) == 9
    assert B.column_row_bytes(parse_dtype("float64")) == 9
    assert B.column_row_bytes(parse_dtype("decimal(7,2)")) == 9


def test_default_window_rows_clamps_and_pow2():
    budget = 6 << 30
    w = B.default_window_rows(54, budget)
    assert w & (w - 1) == 0  # power of two
    assert 1 << 16 <= w <= 1 << 24
    # huge rows -> floor clamp; tiny rows -> ceiling clamp
    assert B.default_window_rows(1 << 40, budget) == 1 << 16
    assert B.default_window_rows(1, budget) == 1 << 24


def test_column_domain_table():
    assert B.column_domain_table("store.s_store_id") == "store"
    assert B.column_domain_table("ss_item_sk") == "item"  # FK suffix wins
    assert B.column_domain_table("x.ss_quantity") == "store_sales"
    assert B.column_domain_table("web_site.web_name") == "web_site"
    assert B.column_domain_table("made_up") is None


# ---------------------------------------------------------------------------
# schema-only analyzer verdicts (the corpus gate's calibration points)
# ---------------------------------------------------------------------------


def _schema_session(**conf):
    sess = Session(conf={"engine.plan_budget": "off", **conf})
    for name, schema in get_schemas(True).items():
        sess.catalog.entries[name] = _Entry(schema=schema)
    return sess


def _template_plan(sess, qnum, sf):
    from nds_tpu.datagen.query_streams import instantiate
    from nds_tpu.engine.sql.parser import parse_script

    rng = np.random.default_rng(np.random.SeedSequence([0, 0]))
    stmts = list(parse_script(instantiate(qnum, rng, sf)))
    return [sess.run_stmt(s).plan for s in stmts]


def test_query5_blocked_at_sf10_direct_at_sf1():
    sess = _schema_session()
    (plan,) = _template_plan(sess, 5, 10.0)
    pb = B.analyze_plan(plan, sess.catalog, scale_factor=10.0)
    assert pb.verdict == "blocked"
    assert pb.window_rows and pb.window_rows & (pb.window_rows - 1) == 0
    assert pb.peak_blocked_bytes < pb.peak_bytes
    assert pb.peak_bytes > pb.budget_bytes >= pb.peak_blocked_bytes
    # the estimate table renders every node + the verdict line
    table = pb.table()
    assert "verdict: blocked" in table and "window_rows" in table

    (plan1,) = _template_plan(_schema_session(), 5, 1.0)
    pb1 = B.analyze_plan(plan1, _schema_session().catalog, scale_factor=1.0)
    assert pb1.verdict == "direct"
    assert pb1.window_rows is None


def test_round5_oom_set_flagged_at_sf10():
    for q in (5, 6, 7):
        sess = _schema_session()
        verdicts = [
            B.analyze_plan(p, sess.catalog, scale_factor=10.0).verdict
            for p in _template_plan(sess, q, 10.0)
        ]
        assert all(v != "direct" for v in verdicts), (q, verdicts)


def test_mesh_mode_divides_sharded_bytes_by_mesh_width():
    """Per-device model (ISSUE 13): fact-scan bytes divide by the mesh
    width, replicated dimension bytes are charged in full per device, and
    the single-device model is byte-identical to mesh_devices=None."""
    sess = _schema_session()
    (plan,) = _template_plan(sess, 3, 10.0)
    pb1 = B.analyze_plan(plan, sess.catalog, scale_factor=10.0)
    pb8 = B.analyze_plan(plan, sess.catalog, scale_factor=10.0,
                         mesh_devices=8)
    assert pb8.mesh_devices == 8 and pb1.mesh_devices is None
    assert pb8.peak_bytes < pb1.peak_bytes
    by_desc1 = {id(n.node): n for n in pb1.nodes}
    fact = dim = False
    for n8 in pb8.nodes:
        n1 = by_desc1.get(id(n8.node))
        if n1 is None or not n8.desc.startswith("Scan"):
            continue
        if n8.sharded:
            fact = True
            assert n8.alloc_bytes == n1.alloc_bytes // 8, n8.desc
        else:
            dim = True
            assert n8.alloc_bytes == n1.alloc_bytes, n8.desc  # per device
    assert fact and dim
    # identity widths: mesh_devices absent or 1 changes nothing
    pb_one = B.analyze_plan(plan, sess.catalog, scale_factor=10.0,
                            mesh_devices=1)
    assert pb_one.peak_bytes == pb1.peak_bytes
    # the per-device table says so
    assert "per device" in pb8.table() and "[sharded]" in pb8.table()


def test_mesh_mode_sf10_oom_set_goes_direct_per_device():
    """The round-5 SF10 OOM set (q5 blocked, q6/q7 spill single-device)
    admits DIRECT on the 8-device mesh — each chip's share of the sharded
    fact work fits; same pins the corpus --budget gate holds."""
    for q, single in ((5, "blocked"), (6, "spill"), (7, "spill")):
        sess = _schema_session()
        (plan,) = _template_plan(sess, q, 10.0)
        pb1 = B.analyze_plan(plan, sess.catalog, scale_factor=10.0)
        assert pb1.verdict == single, (q, pb1.verdict)
        pb8 = B.analyze_plan(plan, sess.catalog, scale_factor=10.0,
                             mesh_devices=8)
        assert pb8.verdict == "direct", (q, pb8.verdict)
        assert pb8.peak_bytes <= pb8.budget_bytes


def test_session_mesh_devices_resolution():
    """Width resolution: live session mesh wins, engine.mesh_devices conf
    covers schema-only contexts ONLY, <= 1 means single-device model."""
    sess = _schema_session()
    assert B.session_mesh_devices(sess) is None
    sess.conf["engine.mesh_devices"] = 8
    assert B.session_mesh_devices(sess) == 8
    sess.conf["engine.mesh_devices"] = 1
    assert B.session_mesh_devices(sess) is None
    sess.conf["engine.mesh_devices"] = "bogus"
    assert B.session_mesh_devices(sess) is None
    sess.mesh = _FakeMesh(4)
    assert B.session_mesh_devices(sess) == 4
    # a session with REAL data but no mesh executes single-device: a
    # stray conf key must not buy per-device admission verdicts for
    # plans that will run on one chip
    import pyarrow as pa

    live = _schema_session()
    live.conf["engine.mesh_devices"] = 8
    live.register_arrow("t", pa.table({"a": [1, 2, 3]}))
    assert B.session_mesh_devices(live) is None
    live.mesh = _FakeMesh(8)  # the live mesh still wins over everything
    assert B.session_mesh_devices(live) == 8


def test_budget_plan_records_mesh_devices_on_session():
    """The in-session hook (the one serve-mode admission consumes) models
    per-device under engine.mesh_devices and records the width."""
    sess = _schema_session()
    sess.conf["engine.plan_budget"] = "on"
    sess.conf["engine.plan_budget_sf"] = 10.0
    sess.conf["engine.mesh_devices"] = 8
    _template_plan(sess, 5, 10.0)
    rec = sess.last_plan_budget
    assert rec["verdict"] == "direct" and rec["mesh_devices"] == 8
    # q14: reject single-device, admitted per-device at 8 chips
    _template_plan(sess, 14, 10.0)
    assert sess.last_plan_budget["verdict"] == "direct"


def test_reject_raises_classified_planner():
    # q14's SF10 estimate is far beyond the reject line; with the
    # in-session hook ON it must refuse the statement at plan time
    sess = _schema_session()
    sess.conf["engine.plan_budget"] = "on"
    sess.conf["engine.plan_budget_sf"] = 10.0
    with pytest.raises(B.PlanBudgetError) as exc:
        _template_plan(sess, 14, 10.0)
    assert faults.classify(exc.value) == faults.PLANNER
    # warn mode computes + records but never rejects
    sess2 = _schema_session()
    sess2.conf["engine.plan_budget"] = "warn"
    sess2.conf["engine.plan_budget_sf"] = 10.0
    plans = _template_plan(sess2, 14, 10.0)
    assert plans and sess2.last_plan_budget["verdict"] == "reject"


def test_unknown_tables_disable_enforcement():
    sess = Session(conf={})  # default: engine.plan_budget=on
    sess.catalog.entries["mystery"] = _Entry(
        schema=get_schemas(True)["store_sales"], path="/nope", fmt="csv"
    )
    sess.register_arrow(
        "mystery", pa.table({"ss_item_sk": pa.array([1, 2], pa.int32())})
    )
    del sess.catalog.entries["mystery"]
    sess.catalog.entries["mystery_csv"] = _Entry(
        schema=get_schemas(True)["date_dim"], path="/nope", fmt="csv"
    )
    res = sess.sql("select count(*) c from mystery_csv")
    assert res is not None  # admitted despite unknown cardinality
    assert sess.last_plan_budget["verdict"] == "unknown"


def test_plan_budget_event_emitted():
    sess = _schema_session()
    sess.conf["engine.plan_budget"] = "warn"
    sess.conf["engine.plan_budget_sf"] = 1.0
    sess.tracer = Tracer()  # in-memory
    _template_plan(sess, 3, 1.0)
    evs = [e for e in sess.tracer.events if e["kind"] == "plan_budget"]
    assert len(evs) == 1
    assert set(EVENT_SCHEMA["plan_budget"]) <= set(evs[0])
    assert evs[0]["verdict"] == "direct"


# ---------------------------------------------------------------------------
# blocked-window sizing: static annotation vs runtime derivation parity
# ---------------------------------------------------------------------------


def _channel(n, seed):
    r = np.random.default_rng(seed)
    ks = r.integers(1, 6, n)
    vs = r.integers(-50, 50, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 13 == 0 else int(v) for i, v in enumerate(ks)],
                pa.int32(),
            ),
            "v": pa.array(
                [None if i % 7 == 0 else int(v) for i, v in enumerate(vs)],
                pa.int32(),
            ),
            "amt": pa.array(
                [Decimal(int(v) * 7) / 100 for v in vs], pa.decimal128(7, 2)
            ),
        }
    )


UNION_AGG = """
select k, sum(v) sv, min(v) mn, max(v) mx, count(v) cv, avg(v) av,
       sum(amt) sa
from (select k, v, amt from t1
      union all
      select k, v, amt from t2 where v > -40
      union all
      select k, v, amt from t3) u
where v < 45
group by k
order by k
"""


def _union_session(**conf):
    s = Session(conf=conf)
    for i, t in enumerate(("t1", "t2", "t3")):
        s.register_arrow(t, _channel(3000, seed=100 + i))
    return s


def test_static_window_annotation_matches_runtime_sizing():
    # oracle: the unwindowed result
    oracle = _union_session().sql(UNION_AGG).to_pylist()

    # runtime-derived sizing (conf knob, the PR-1 path)
    runtime = _union_session(**{"engine.union_agg_window_rows": 512})
    r1 = runtime.sql(UNION_AGG)
    assert r1.to_pylist() == oracle
    rt_stats = runtime.last_blocked_union
    assert rt_stats and rt_stats["window_rows"] == 512

    # statically-chosen sizing: the budgeter's budget_window_rows
    # annotation (placed by _annotate_blocked_windows exactly as a
    # blocked verdict would) must route through the same windowed
    # executor with the same window and produce the identical result
    static = _union_session()
    res = static.sql(UNION_AGG)
    B._annotate_blocked_windows(res.plan, 512)
    assert res.to_pylist() == oracle
    st_stats = static.last_blocked_union
    assert st_stats and st_stats["window_rows"] == 512
    assert st_stats["windows"] == rt_stats["windows"]
    assert st_stats["max_table_cap"] == rt_stats["max_table_cap"]

    # explicit conf still wins over a static annotation
    both = _union_session(**{"engine.union_agg_window_rows": 1024})
    res2 = both.sql(UNION_AGG)
    B._annotate_blocked_windows(res2.plan, 512)
    assert res2.to_pylist() == oracle
    assert both.last_blocked_union["window_rows"] == 1024


def test_annotated_plan_verifies_clean():
    static = _union_session(**{"engine.verify_plans": "all"})
    res = static.sql(UNION_AGG)
    B._annotate_blocked_windows(res.plan, 512)
    verify_plan(res.plan, static.catalog)  # annotation coverage accepts it


# ---------------------------------------------------------------------------
# ladder: budget_shrink consumes the static prediction
# ---------------------------------------------------------------------------


def test_ladder_budget_shrink_first_rung():
    sess = _union_session()
    sess.last_plan_budget = {
        "verdict": "over",
        "peak_bytes": 5 << 30,
        "budget_bytes": 4 << 30,
        "window_rows": 2048,
    }
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    report = BenchReport(sess)
    summary = report.report_on(flaky, retry_oom=True, name="q")
    assert summary["queryStatus"][-1] == "CompletedWithTaskFailures"
    rungs = [r["rung"] for r in summary["ladder"]]
    assert rungs[0] == "budget_shrink"
    assert summary["ladder"][0]["window_rows"] == 2048
    assert sess.conf["engine.union_agg_window_rows"] == 2048
    assert len(attempts) == 2  # one failure + one recovered retry


def test_ladder_skips_budget_shrink_without_windowing_seam():
    # an `over` verdict on a plan with NO blocked-union seam carries no
    # window recommendation: budget_shrink would be recover_retry with a
    # conf side-effect later statements' static sizing can't undo
    sess = _union_session()
    sess.last_plan_budget = {"verdict": "over", "window_rows": None}
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    summary = BenchReport(sess).report_on(flaky, retry_oom=True, name="q")
    rungs = [r["rung"] for r in summary["ladder"]]
    assert rungs[0] == "recover_retry"
    assert "engine.union_agg_window_rows" not in sess.conf

    # an explicit window already at/below the recommendation means the
    # failed attempt ran it — re-applying the same value is pointless
    sess2 = _union_session(**{"engine.union_agg_window_rows": 2048})
    sess2.last_plan_budget = {"verdict": "blocked", "window_rows": 2048}
    attempts2 = []

    def flaky2():
        attempts2.append(1)
        if len(attempts2) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    summary2 = BenchReport(sess2).report_on(flaky2, retry_oom=True, name="q")
    assert [r["rung"] for r in summary2["ladder"]][0] == "recover_retry"

    # a blocked-verdict plan already ANNOTATED with the static window ran
    # it and OOM'd anyway: budget_shrink must not rerun the identical
    # configuration, and the shrink rung must halve BELOW the failed
    # static window instead of jumping to the (larger) degraded default
    sess3 = _union_session()
    sess3.last_plan_budget = {
        "verdict": "blocked", "window_rows": 65536, "annotated": True,
    }
    attempts3 = []

    def always_oom():
        attempts3.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    summary3 = BenchReport(sess3).report_on(
        always_oom, retry_oom=True, name="q"
    )
    rungs3 = [r["rung"] for r in summary3["ladder"]]
    assert rungs3 == ["recover_retry", "shrink_union_window"]
    assert sess3.conf["engine.union_agg_window_rows"] == 32768


def test_watermark_never_grows_past_static_recommendation(monkeypatch):
    # conf unset + a static window SMALLER than the degraded default
    # (annotated or not): the watermark write must clamp to it — conf
    # wins over the annotation, so a larger conf value would GROW windows
    monkeypatch.setattr(memwatch, "rss_bytes", lambda: 1 << 30)
    sess = _union_session(**{"engine.host_rss_watermark": 1})
    sess.last_plan_budget = {
        "verdict": "blocked", "window_rows": 65536, "annotated": True,
    }
    BenchReport(sess).report_on(lambda: None, name="q")
    assert sess.conf["engine.union_agg_window_rows"] == 65536


def test_watermark_fires_once_per_excursion(monkeypatch):
    # RSS stays above the watermark across queries: only the FIRST query
    # of the excursion shrinks; the latch re-arms after RSS drops below
    rss = {"v": 1 << 30}
    monkeypatch.setattr(memwatch, "rss_bytes", lambda: rss["v"])
    import nds_tpu.report as report_mod

    monkeypatch.setattr(report_mod, "rss_bytes", lambda: rss["v"],
                        raising=False)
    sess = _union_session(**{"engine.host_rss_watermark": 1000})
    s1 = BenchReport(sess).report_on(lambda: None, name="q1")
    assert any(
        r["rung"] == "host_watermark_shrink" for r in s1["ladder"]
    )
    first = sess.conf["engine.union_agg_window_rows"]
    s2 = BenchReport(sess).report_on(lambda: None, name="q2")
    assert "ladder" not in s2  # same excursion: no second shrink
    assert sess.conf["engine.union_agg_window_rows"] == first
    # excursion ends -> latch re-arms -> a new crossing shrinks again
    rss["v"] = 10
    BenchReport(sess).report_on(lambda: None, name="q3")
    assert sess._rss_above_watermark is False
    rss["v"] = 1 << 30
    s4 = BenchReport(sess).report_on(lambda: None, name="q4")
    assert any(
        r["rung"] == "host_watermark_shrink" for r in s4["ladder"]
    )
    assert sess.conf["engine.union_agg_window_rows"] == first // 2


def test_budget_shrink_applies_when_explicit_window_eclipsed_static():
    # conf pins a LARGE window, so the blocked-verdict annotation never
    # ran (conf wins): the prediction is still applicable and the first
    # rung must shrink to it
    sess = _union_session(**{"engine.union_agg_window_rows": 1 << 23})
    sess.last_plan_budget = {
        "verdict": "blocked", "window_rows": 65536, "annotated": False,
    }
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    summary = BenchReport(sess).report_on(flaky, retry_oom=True, name="q")
    assert [r["rung"] for r in summary["ladder"]][0] == "budget_shrink"
    assert sess.conf["engine.union_agg_window_rows"] == 65536


def test_budget_plan_annotated_false_under_explicit_window():
    # the in-session hook must record annotated=False when an explicit
    # window eclipses the annotation at execution time
    sess = _schema_session()
    sess.conf.update({
        "engine.plan_budget": "on",
        "engine.plan_budget_sf": 10.0,
        "engine.union_agg_window_rows": 1 << 23,
    })
    _template_plan(sess, 5, 10.0)
    rec = sess.last_plan_budget
    assert rec["verdict"] == "blocked" and rec["annotated"] is False
    # without the explicit window the annotation IS in effect
    sess2 = _schema_session()
    sess2.conf.update({
        "engine.plan_budget": "on", "engine.plan_budget_sf": 10.0,
    })
    _template_plan(sess2, 5, 10.0)
    assert sess2.last_plan_budget["annotated"] is True


def test_env_window_never_grows_under_watermark(monkeypatch):
    # an env-forced tiny window (conf unset) must not be eclipsed by a
    # larger conf value written by the watermark shrink
    monkeypatch.setattr(memwatch, "rss_bytes", lambda: 1 << 30)
    monkeypatch.setenv("NDS_UNION_AGG_WINDOW_ROWS", "4096")
    sess = _union_session(**{"engine.host_rss_watermark": 1})
    BenchReport(sess).report_on(lambda: None, name="q")
    assert sess.conf["engine.union_agg_window_rows"] <= 4096


def test_failed_parquet_count_still_falls_back_to_scale_model(tmp_path):
    sess = _schema_session()
    sess.catalog.entries["store_sales"] = _Entry(
        schema=get_schemas(True)["store_sales"],
        path=str(tmp_path / "nope"), fmt="parquet",
    )
    stats = B.CatalogStats(sess.catalog, scale_factor=None)
    assert stats.table_rows("store_sales") is None  # probe failed
    # the failed probe is memoized, but a declared scale factor must
    # still supply the cardinality instead of pinning `unknown`
    stats_sf = B.CatalogStats(sess.catalog, scale_factor=1.0)
    assert stats_sf.table_rows("store_sales") == 2880000


def test_ladder_unchanged_without_prediction():
    sess = _union_session()
    sess.last_plan_budget = {"verdict": "direct", "window_rows": None}
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    summary = BenchReport(sess).report_on(flaky, retry_oom=True, name="q")
    rungs = [r["rung"] for r in summary["ladder"]]
    assert rungs[0] == "recover_retry"  # the pre-budgeter ladder


# ---------------------------------------------------------------------------
# host-RSS watermark pre-emption
# ---------------------------------------------------------------------------


def test_memory_sampler_watermark_fires_once(monkeypatch):
    calls = []
    monkeypatch.setattr(memwatch, "rss_bytes", lambda: 1000)
    s = memwatch.MemorySampler(
        interval_s=0.001, watermark_bytes=500, on_watermark=calls.append
    )
    with s:
        import time

        time.sleep(0.05)
    assert s.watermark_fired
    assert calls == [1000]  # once, with the crossing sample


def test_report_on_watermark_preemption(monkeypatch):
    monkeypatch.setattr(memwatch, "rss_bytes", lambda: 1 << 30)
    sess = _union_session(**{"engine.host_rss_watermark": 1})
    sess.tracer = Tracer()
    report = BenchReport(sess)
    result = {}

    def run():
        result["rows"] = sess.sql(UNION_AGG).to_pylist()

    summary = report.report_on(run, name="uq")
    assert summary["queryStatus"][-1] == "CompletedWithTaskFailures"
    assert summary["retries"] == 0  # pre-emption is not a retry
    entries = [
        r for r in summary["ladder"]
        if r["rung"] == "host_watermark_shrink"
    ]
    assert entries and entries[0]["kind"] == faults.HOST_OOM
    # the window conf shrank for later statements
    assert sess.conf["engine.union_agg_window_rows"] >= 4096
    evs = [e for e in sess.tracer.events if e["kind"] == "mem_watermark"]
    assert evs and evs[0]["watermark_bytes"] == 1
    assert result["rows"]  # the query itself completed


def test_window_loop_shrinks_under_pressure():
    oracle = _union_session().sql(UNION_AGG).to_pylist()
    sess = _union_session(**{"engine.union_agg_window_rows": 8192})
    sess._mem_pressure = True  # as the watermark callback would set it
    res = sess.sql(UNION_AGG)
    assert res.to_pylist() == oracle
    stats = sess.last_blocked_union
    # the loop consumed the pressure flag and halved the remaining windows
    assert stats["window_cap"] == 4096
    assert sess._mem_pressure is False


# ---------------------------------------------------------------------------
# sharding verifier rules (seeded violation per rule)
# ---------------------------------------------------------------------------


class _FakeDevices:
    def __init__(self, n):
        self.size = n


class _FakeMesh:
    def __init__(self, n):
        self.devices = _FakeDevices(n)


def _catalog_with(nrows=None):
    sess = _schema_session()
    if nrows:
        for name, n in nrows.items():
            sess.catalog.entries[name].nrows = n
    return sess.catalog


def test_sharding_exchange_arity_non_pow2_mesh():
    cat = _catalog_with({"store_sales": 1000})
    plan = P.Scan("store_sales", "store_sales", ["ss_item_sk"])
    v = PlanVerifier(cat).verify(plan, mesh=_FakeMesh(3))
    assert any("exchange-arity" in x for x in v)
    # a fact cap that does not divide the mesh would silently replicate
    assert any("replicated-dim" in x and "store_sales" in x for x in v)
    # power-of-two mesh: clean
    assert PlanVerifier(cat).verify(plan, mesh=_FakeMesh(8)) == []


def test_sharding_replicated_dim_too_large():
    cat = _catalog_with({"customer": 1 << 29})  # ~0.5G rows, way past 2 GiB
    plan = P.Scan("customer", "customer", ["c_customer_sk", "c_birth_year"])
    v = PlanVerifier(cat).verify(plan, mesh=_FakeMesh(8))
    assert any(
        "replicated-dim" in x and "customer" in x for x in v
    )
    # without a mesh the sharding family does not run at all
    assert PlanVerifier(cat).verify(plan) == []


def test_sharding_axis_mixed_setop():
    cat = _catalog_with({"store_sales": 2048, "date_dim": 100})
    left = P.Project(
        [(E.Col("store_sales.ss_item_sk"), "x")],
        P.Scan("store_sales", "store_sales", ["ss_item_sk"]),
    )
    right = P.Project(
        [(E.Col("date_dim.d_date_sk"), "x")],
        P.Scan("date_dim", "date_dim", ["d_date_sk"]),
    )
    plan = P.SetOp("union_all", left, right)
    v = PlanVerifier(cat).verify(plan, mesh=_FakeMesh(8))
    assert any("sharding-axis" in x for x in v)


def test_physical_annotation_coverage():
    cat = _catalog_with({"date_dim": 100})
    scan = P.Scan("date_dim", "date_dim", ["d_date_sk"])
    proj = P.Project([(E.Col("date_dim.d_date_sk"), "x")], scan)
    proj._topk_safe = True  # stray: not a Sort
    v = PlanVerifier(cat).verify(proj)
    assert any("physical-annotation" in x and "_topk_safe" in x for x in v)

    agg = P.Aggregate(
        keys=[(E.Col("date_dim.d_date_sk"), "k")],
        aggs=[(E.Agg("count", None), "c")],
        child=P.Scan("date_dim", "date_dim", ["d_date_sk"]),
    )
    agg.budget_window_rows = 4096  # not a blocked-union aggregate
    v = PlanVerifier(cat).verify(agg)
    assert any(
        "physical-annotation" in x and "budget_window_rows" in x for x in v
    )

    agg2 = P.Aggregate(
        keys=[(E.Col("date_dim.d_date_sk"), "k")],
        aggs=[(E.Agg("count", None), "c")],
        child=P.Scan("date_dim", "date_dim", ["d_date_sk"]),
    )
    agg2.donate_ok = True  # only Pipelines own the donation contract
    v = PlanVerifier(cat).verify(agg2)
    assert any(
        "physical-annotation" in x and "donate_ok" in x for x in v
    )

    with pytest.raises(PlanVerifyError):
        verify_plan(proj, cat)


# ---------------------------------------------------------------------------
# lint: cache-lock-discipline + unread-conf-knob
# ---------------------------------------------------------------------------


def test_lint_cache_lock_discipline():
    # the rule retired into analysis/concurrency.py's guarded-by
    # (ISSUE 20 satellite): findings now carry the new name, and the
    # historical pragma keeps silencing via the alias table
    bad = (
        "def f(session, fp, sig):\n"
        "    session.exec_cache.map[(fp, sig)] = None\n"
        "    session.join_order_cache.setdefault(fp, {})\n"
        "    session.plan_cache.clear()\n"
    )
    findings = L.lint_source(bad, "engine/whatever.py")
    hits = [f for f in findings if f.rule == "guarded-by"]
    assert len(hits) == 3

    good = (
        "def f(session, fp, sig):\n"
        "    with session.cache_lock:\n"
        "        session.exec_cache.map[(fp, sig)] = None\n"
        "        session.plan_cache.clear()\n"
    )
    assert [
        f for f in L.lint_source(good, "engine/whatever.py")
        if f.rule == "guarded-by"
    ] == []

    # local-alias taint: a cache fetched into a variable is still a cache
    alias = (
        "def f(self, node, out):\n"
        "    cache = self._session_cache()\n"
        "    cache.put(node, out)\n"
    )
    hits = [
        f for f in L.lint_source(alias, "engine/whatever.py")
        if f.rule == "guarded-by"
    ]
    assert len(hits) == 1

    # pragma with justification silences a known-sound site
    pragma = (
        "def f(session):\n"
        "    # single-threaded init  # nds-lint: disable=cache-lock-discipline\n"
        "    session.plan_cache.clear()\n"
    )
    assert [
        f for f in L.lint_source(pragma, "engine/whatever.py")
        if f.rule == "guarded-by"
    ] == []


def test_lint_unread_conf_knob(tmp_path):
    pkg = tmp_path / "nds_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'X = conf.get("engine.real_knob", 1)\n', encoding="utf-8"
    )
    (tmp_path / "README.md").write_text(
        "| `engine.real_knob` | used |\n| `engine.ghost_knob` | dead |\n",
        encoding="utf-8",
    )
    findings = L.run_unread_knob_lint(str(tmp_path))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "unread-conf-knob"
    assert "engine.ghost_knob" in f.message and f.path == "README.md"
    # the live tree is clean (also covered by test_lint_clean_over_real_tree)
    assert L.run_unread_knob_lint() == []


# ---------------------------------------------------------------------------
# budget-vs-actual calibration over real SF0.01 data (the slack contract)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sf001_session():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    schemas = get_schemas(True)
    sess = Session(conf={})
    for t in ("store_sales", "store_returns", "date_dim", "item", "store"):
        sess.register_csv_dir(t, os.path.join(DATA, t), schemas[t])
    return sess


CALIBRATION_STREAM = (
    ("scan_filter_count",
     "select count(*) c from store_sales where ss_quantity > 0"),
    ("join_agg",
     "select d_year, sum(ss_ext_sales_price) s, count(*) c "
     "from store_sales, date_dim where ss_sold_date_sk = d_date_sk "
     "group by d_year order by d_year"),
    ("union_agg",
     "select k, sum(v) sv, count(*) c from "
     "(select ss_item_sk k, ss_quantity v from store_sales "
     " union all "
     " select sr_item_sk k, sr_return_quantity v from store_returns) u "
     "group by k order by k limit 20"),
    ("topk",
     "select i_item_id, i_current_price from item "
     "order by i_current_price desc limit 10"),
    ("star_join",
     "select s_store_name, d_moy, sum(ss_net_paid) t from store_sales, "
     "date_dim, store where ss_sold_date_sk = d_date_sk and "
     "ss_store_sk = s_store_sk and d_year = 2000 "
     "group by s_store_name, d_moy order by t desc limit 50"),
)


@pytest.mark.slow
def test_budget_vs_actual_calibration(sf001_session):
    """The calibration contract: for every query of the SF0.01 stream,
    run with memory high-water tracing on, the largest actually
    materialized plan-node working set (op_span est_bytes — the exact
    byte rule the plan cache budgets with) must stay within
    CALIBRATION_SLACK x the static peak estimate. A model change that
    starts under-estimating real materialization breaks here."""
    sess = sf001_session
    for name, sql in CALIBRATION_STREAM:
        sess.conf["engine.plan_cache"] = "off"
        sess.tracer = Tracer()  # fresh in-memory stream per query
        report = BenchReport(sess)
        box = {}

        def run():
            res = sess.sql(sql)
            box["plan"] = res.plan
            box["rows"] = res.to_pylist()

        with faults.scope(name):
            summary = report.report_on(run, name=name)
        assert summary["queryStatus"][-1] == "Completed", (name, summary)
        # memoryHighWater tracing was on and recorded a real peak
        assert summary.get("memoryHighWater", {}).get("bytes"), name
        pb = B.analyze_plan(box["plan"], sess.catalog)
        spans = [
            e for e in sess.tracer.events if e["kind"] == "op_span"
        ]
        assert spans, name
        actual_peak = max(int(e["est_bytes"] or 0) for e in spans)
        assert actual_peak <= pb.peak_bytes * B.CALIBRATION_SLACK, (
            f"{name}: actual node high-water {actual_peak} exceeds "
            f"{B.CALIBRATION_SLACK}x the static peak {pb.peak_bytes}"
        )
        # and the static estimate is not vacuous: within 4 orders of
        # magnitude of reality (a model regression to astronomic bounds
        # would admit nothing at real scale)
        assert pb.peak_bytes <= actual_peak * 10_000, name
        assert box["rows"], name
