import io
import os
import subprocess

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.csv as pv
import pytest

from nds_tpu.datagen.build import ensure_built
from nds_tpu.schema import get_schemas, get_maintenance_schemas

SCALE = "0.002"


@pytest.fixture(scope="module")
def datadir(tmp_path_factory):
    d = tmp_path_factory.mktemp("raw")
    from nds_tpu.cli.gen_data import main

    main(["local", "--scale", SCALE, "--parallel", "2", "--data_dir", str(d)])
    return d


@pytest.fixture(scope="module")
def updatedir(tmp_path_factory):
    d = tmp_path_factory.mktemp("raw_update")
    from nds_tpu.cli.gen_data import main

    main(["local", "--scale", SCALE, "--parallel", "2", "--data_dir", str(d), "--update", "1"])
    return d


def read_table(data_dir, table, schema):
    """Read a generated .dat table through its Arrow schema (the exact path
    the transcode phase uses)."""
    names = schema.names + ["_trailing"]
    types = {f.name: f.dtype.to_arrow() for f in schema}
    tables = []
    table_dir = os.path.join(data_dir, table)
    for fname in sorted(os.listdir(table_dir)):
        with open(os.path.join(table_dir, fname), "rb") as f:
            data = f.read()
        if not data:
            continue
        tables.append(pv.read_csv(
            io.BytesIO(data),
            read_options=pv.ReadOptions(column_names=names),
            parse_options=pv.ParseOptions(delimiter="|"),
            convert_options=pv.ConvertOptions(column_types=types, strings_can_be_null=True),
        ).drop_columns(["_trailing"]))
    return pa.concat_tables(tables)


def test_layout(datadir):
    for table in get_schemas():
        assert os.path.isdir(datadir / table), f"missing dir for {table}"


def test_all_tables_parse_with_schema(datadir):
    for table, schema in get_schemas().items():
        t = read_table(datadir, table, schema)
        assert t.num_rows > 0, table


def test_fixed_cross_product_tables(datadir):
    schemas = get_schemas()
    hd = read_table(datadir, "household_demographics", schemas["household_demographics"])
    assert hd.num_rows == 7200
    assert len(pc.unique(hd.column("hd_demo_sk"))) == 7200
    ib = read_table(datadir, "income_band", schemas["income_band"])
    assert ib.num_rows == 20


def test_date_dim_calendar(datadir):
    dd = read_table(datadir, "date_dim", get_schemas()["date_dim"])
    assert dd.num_rows == 73049
    import datetime

    row = dd.slice(0, 1).to_pylist()[0]
    assert row["d_date_sk"] == 2415022
    assert row["d_date"] == datetime.date(1900, 1, 2)
    # 2000-01-01 was a Saturday
    mask = pc.equal(dd.column("d_date_sk"), 2451545)
    y2k = dd.filter(mask).to_pylist()[0]
    assert y2k["d_year"] == 2000 and y2k["d_day_name"].strip() == "Saturday"
    assert y2k["d_quarter_name"].strip() == "2000Q1"


def test_referential_integrity(datadir):
    schemas = get_schemas()
    ss = read_table(datadir, "store_sales", schemas["store_sales"])
    item = read_table(datadir, "item", schemas["item"])
    store = read_table(datadir, "store", schemas["store"])
    item_sks = set(item.column("i_item_sk").to_pylist())
    assert set(x for x in ss.column("ss_item_sk").to_pylist()) <= item_sks
    store_sks = set(store.column("s_store_sk").to_pylist())
    assert set(x for x in ss.column("ss_store_sk").to_pylist() if x is not None) <= store_sks


def test_returns_reference_sales(datadir):
    schemas = get_schemas()
    ss = read_table(datadir, "store_sales", schemas["store_sales"])
    sr = read_table(datadir, "store_returns", schemas["store_returns"])
    # every return (ticket, item) must exist in sales
    sales_keys = set(zip(ss.column("ss_ticket_number").to_pylist(),
                         ss.column("ss_item_sk").to_pylist()))
    ret_keys = set(zip(sr.column("sr_ticket_number").to_pylist(),
                       sr.column("sr_item_sk").to_pylist()))
    assert ret_keys <= sales_keys
    # ~10% of lines return
    assert 0.02 < sr.num_rows / ss.num_rows < 0.25


def test_price_arithmetic(datadir):
    ss = read_table(datadir, "store_sales", get_schemas()["store_sales"])
    row = ss.slice(0, 200).to_pylist()
    for r in row:
        if r["ss_quantity"] is None:
            continue
        assert r["ss_ext_sales_price"] == r["ss_sales_price"] * r["ss_quantity"]
        assert r["ss_net_paid"] == r["ss_ext_sales_price"] - r["ss_coupon_amt"]
        assert r["ss_net_profit"] == r["ss_net_paid"] - r["ss_ext_wholesale_cost"]


def test_chunks_are_deterministic(tmp_path):
    binary = ensure_built()
    out1, out2 = tmp_path / "a", tmp_path / "b"
    out1.mkdir(), out2.mkdir()
    for out in (out1, out2):
        subprocess.run([binary, "-scale", "0.002", "-dir", str(out), "-table", "web_sales"],
                       check=True)
    f = "web_sales_1_1.dat"
    assert (out1 / f).read_bytes() == (out2 / f).read_bytes()


def test_update_refresh_sets(updatedir):
    schemas = get_maintenance_schemas()
    for table in schemas:
        assert os.path.isdir(updatedir / table), f"missing refresh table {table}"
    sp = read_table(updatedir, "s_purchase", schemas["s_purchase"])
    spl = read_table(updatedir, "s_purchase_lineitem", schemas["s_purchase_lineitem"])
    assert sp.num_rows > 0
    # every lineitem belongs to a purchase
    assert set(spl.column("plin_purchase_id").to_pylist()) <= set(
        sp.column("purc_purchase_id").to_pylist())
    dele = read_table(updatedir, "delete", schemas["delete"])
    assert dele.num_rows == 3  # 3 DATE1/DATE2 tuples per refresh set


def test_cluster_localhost_matches_local(tmp_path):
    """Cluster fan-out over a localhost hosts file is byte-identical to
    local generation (the shared-filesystem contract)."""
    from nds_tpu.cli import gen_data

    local = tmp_path / "local"
    gen_data.main(["local", "--scale", SCALE, "--parallel", "2",
                   "--data_dir", str(local)])
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("# comment\nlocalhost\n127.0.0.1\n")
    clus = tmp_path / "cluster"
    gen_data.main(["cluster", "--scale", SCALE, "--parallel", "2",
                   "--data_dir", str(clus), "--hosts", str(hosts)])
    for table in ("store_sales", "item", "date_dim"):
        a = sorted(os.listdir(local / table))
        assert a == sorted(os.listdir(clus / table))
        for f in a:
            assert (local / table / f).read_bytes() == (clus / table / f).read_bytes()


def test_cluster_retries_failed_chunk(tmp_path, monkeypatch):
    """A chunk whose process dies is re-launched on the next host and the
    run still completes; exhausting --retries raises."""
    from nds_tpu.cli import gen_data

    real_spawn = gen_data._spawn_on_host
    first_attempt_failed = set()

    def flaky(host, cmd):
        chunk = cmd[cmd.index("-child") + 1]
        if chunk not in first_attempt_failed:
            first_attempt_failed.add(chunk)
            return subprocess.Popen(["false"])
        return real_spawn("localhost", cmd)

    monkeypatch.setattr(gen_data, "_spawn_on_host", flaky)
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("hostA\nhostB\n")  # never ssh'd: spawn is patched
    out = tmp_path / "out"
    gen_data.main(["cluster", "--scale", SCALE, "--parallel", "2",
                   "--data_dir", str(out), "--hosts", str(hosts),
                   "--table", "item"])
    assert len(first_attempt_failed) == 2  # both chunks failed once
    assert sorted(os.listdir(out / "item")) == ["item_1_2.dat", "item_2_2.dat"]

    monkeypatch.setattr(gen_data, "_spawn_on_host",
                        lambda host, cmd: subprocess.Popen(["false"]))
    with pytest.raises(Exception, match="after 1 retries"):
        gen_data.main(["cluster", "--scale", SCALE, "--parallel", "2",
                       "--data_dir", str(tmp_path / "dead"), "--hosts", str(hosts),
                       "--retries", "1", "--table", "item"])


def test_range_generation(tmp_path):
    from nds_tpu.cli.gen_data import main

    d1 = tmp_path / "full"
    main(["local", "--scale", SCALE, "--parallel", "4", "--data_dir", str(d1)])
    d2 = tmp_path / "ranged"
    main(["local", "--scale", SCALE, "--parallel", "4", "--range", "1,2", "--data_dir", str(d2)])
    main(["local", "--scale", SCALE, "--parallel", "4", "--range", "3,4", "--data_dir", str(d2),
          "--overwrite_output"])
    a = sorted(os.listdir(d1 / "catalog_sales"))
    b = sorted(os.listdir(d2 / "catalog_sales"))
    assert a == b
    for f in a:
        assert (d1 / "catalog_sales" / f).read_bytes() == (d2 / "catalog_sales" / f).read_bytes()


# ---------------------------------------------------------------------------
# Spec fidelity (VERDICT r3 #7): TPC-DS Table 3-2 row counts, NULL rates,
# and official-toolkit (dsdgen) format interop.
# ---------------------------------------------------------------------------


def _count_dat_rows(data_dir, table):
    total = 0
    tdir = os.path.join(str(data_dir), table)
    for fn in os.listdir(tdir):
        with open(os.path.join(tdir, fn), "rb") as f:
            total += sum(1 for _ in f)
    return total


def test_fixed_tables_match_spec_rowcounts(datadir):
    """Scale-independent tables carry the TPC-DS Table 3-2 counts at any
    SF (reference contract: nds/nds_gen_data.py:183-244 expects official
    dsdgen table layouts)."""
    expected = {
        "date_dim": 73049,
        "time_dim": 86400,
        "customer_demographics": 1920800,
        "household_demographics": 7200,
        "income_band": 20,
        "ship_mode": 20,
    }
    for table, n in expected.items():
        assert _count_dat_rows(datadir, table) == n, table


def test_sf1_dimension_rowcounts(tmp_path):
    """SF1 dimension row counts match TPC-DS Table 3-2 exactly."""
    from nds_tpu.cli.gen_data import main

    expected = {
        "call_center": 6,
        "catalog_page": 11718,
        "customer_address": 50000,
        "customer": 100000,
        "item": 18000,
        "promotion": 300,
        "reason": 35,
        "store": 12,
        "warehouse": 5,
        "web_page": 60,
        "web_site": 30,
    }
    for table, n in expected.items():
        d = tmp_path / f"sf1_{table}"
        main(["local", "--scale", "1", "--parallel", "2",
              "--data_dir", str(d), "--table", table])
        assert _count_dat_rows(d, table) == n, table


def test_fact_rowcounts_scale_linearly(tmp_path):
    """Fact table sizes scale ~linearly with SF (TPC-DS fact scaling)."""
    from nds_tpu.cli.gen_data import main

    counts = {}
    for sf in ("0.01", "0.02"):
        d = tmp_path / f"sf{sf}"
        main(["local", "--scale", sf, "--parallel", "2",
              "--data_dir", str(d), "--table", "web_sales"])
        counts[sf] = _count_dat_rows(d, "web_sales")
    ratio = counts["0.02"] / counts["0.01"]
    assert 1.5 < ratio < 2.6, counts


def test_fact_null_rates_and_fk_domains(datadir):
    """Nullable fact FKs carry a small non-zero NULL rate (the query
    parameter generators assume mostly-populated joins), and non-null FKs
    stay inside the dimension surrogate domain."""
    schemas = get_schemas()
    ss = read_table(datadir, "store_sales", schemas["store_sales"])
    n = ss.num_rows
    for col in ("ss_customer_sk", "ss_store_sk", "ss_promo_sk",
                "ss_hdemo_sk", "ss_cdemo_sk", "ss_addr_sk"):
        nulls = ss.column(col).null_count
        assert 0 < nulls / n < 0.5, (col, nulls, n)
    # sold_date may be null (pre-history orders); domain check on non-nulls
    dd = read_table(datadir, "date_dim", schemas["date_dim"])
    dmin = pc.min(dd.column("d_date_sk")).as_py()
    dmax = pc.max(dd.column("d_date_sk")).as_py()
    dates = [x for x in ss.column("ss_sold_date_sk").to_pylist()
             if x is not None]
    assert min(dates) >= dmin and max(dates) <= dmax


def test_official_dsdgen_format_ingests(tmp_path):
    """A file in the official dsdgen output layout (pipe-delimited,
    trailing '|', ISO dates, empty string = NULL) ingests through the same
    reader the harness uses for its own generator output, so official
    toolkit data can be transcoded unchanged (reference:
    nds/nds_gen_data.py:183-244 consumes dsdgen output directly)."""
    from nds_tpu.io.csv import read_dat_dir

    wdir = tmp_path / "warehouse"
    wdir.mkdir()
    # dsdgen layout for `warehouse`: w_warehouse_sk|w_warehouse_id|...|
    rows = [
        "1|AAAAAAAABAAAAAAA|Conventional childr|977787|651|6th |Parkway|Suite 470|Midway|Williamson County|TN|31904|United States|-5.00|\n",
        "2|AAAAAAAACAAAAAAA||138504|600|View First|Avenue|Suite P|Midway|Williamson County|TN|31904|United States|-5.00|\n",
        "3|AAAAAAAADAAAAAAA|Doors canno|294242|534|Ash Laurel|Dr.|Suite 0|Midway|Williamson County|TN|31904|United States|-5.00|\n",
    ]
    (wdir / "warehouse_1_1.dat").write_text("".join(rows))
    schema = get_schemas()["warehouse"]
    arrow = read_dat_dir(str(wdir), schema, use_decimal=True)
    assert arrow.num_rows == 3
    assert arrow.column("w_warehouse_sk").to_pylist() == [1, 2, 3]
    assert arrow.column("w_warehouse_name").to_pylist()[1] is None  # empty=NULL
    assert arrow.column("w_state").to_pylist() == ["TN", "TN", "TN"]
    import decimal

    assert arrow.column("w_gmt_offset").to_pylist() == [
        decimal.Decimal("-5.00")] * 3

    # and it transcodes through the Load Test path unchanged
    from nds_tpu.transcode import transcode_table

    out = tmp_path / "pq"
    n = transcode_table(str(tmp_path), str(out), "warehouse", schema,
                        output_format="parquet", partition=False)
    assert n == 3


def test_fact_primary_keys_unique(datadir):
    """Declared TPC-DS primary keys hold in generated data (dsdgen samples
    items per ticket/order without replacement). The engine's catalog
    claims these as Table.unique_key for probe-style joins, so a violation
    here would silently corrupt join results, not just fidelity."""
    import numpy as np

    from nds_tpu.schema import TABLE_PRIMARY_KEYS

    schemas = get_schemas()
    for t in ("store_sales", "web_sales", "catalog_sales", "store_returns",
              "web_returns", "catalog_returns", "inventory"):
        pk = TABLE_PRIMARY_KEYS[t]
        tab = read_table(datadir, t, schemas[t])
        m = np.stack(
            [tab.column(c).to_numpy(zero_copy_only=False).astype(np.int64)
             for c in pk], 1,
        )
        assert len(np.unique(m, axis=0)) == tab.num_rows, t
