"""Query template/stream tests: every template instantiates, parses, and
executes against generated data (the engine's acceptance gate for new
templates)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from nds_tpu.datagen import query_streams as QS
from nds_tpu.engine.session import Session
from nds_tpu.engine.sql.parser import parse_sql
from nds_tpu.schema import get_schemas

DATA = "/tmp/nds_test_sf001"


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


@pytest.fixture(scope="module")
def sess(data_dir):
    s = Session()
    schemas = get_schemas()
    for t, sch in schemas.items():
        path = os.path.join(data_dir, t)
        if os.path.isdir(path):
            s.register_csv_dir(t, path, sch)
    return s


def test_all_templates_instantiate_and_parse():
    from nds_tpu.engine.sql.parser import parse_script

    rng = np.random.default_rng(42)
    for q in QS.available_templates():
        sql = QS.instantiate(q, rng, 1.0)
        # two-part templates (14/23/24/39) hold two `;`-separated statements
        stmts = parse_script(sql)
        assert len(stmts) >= 1, f"query{q}"


def test_stream_generation(tmp_path):
    qnums = QS.generate_streams(str(tmp_path), 2, 1.0, 12345)
    for s in (0, 1):
        text = (tmp_path / f"query_{s}.sql").read_text()
        assert text.count("-- start query") == len(qnums)
        assert text.count("-- end query") == len(qnums)
    # stream 1 is permuted relative to stream 0
    t0 = (tmp_path / "query_0.sql").read_text().split("\n")[0]
    assert "stream 0" in t0


def test_streams_deterministic(tmp_path):
    QS.generate_streams(str(tmp_path / "a"), 1, 1.0, 777)
    QS.generate_streams(str(tmp_path / "b"), 1, 1.0, 777)
    assert (tmp_path / "a" / "query_0.sql").read_text() == (
        tmp_path / "b" / "query_0.sql"
    ).read_text()


# Templates whose parameter predicates can select zero rows even on healthy
# SF0.01 data (tight multi-way filters / tiny dimension slices). Everything
# else must return at least one row — a template whose substituted parameters
# hit nothing fails the suite (VERDICT round-2 weak #4).
MAY_BE_EMPTY = {
    1, 3, 4, 6, 8, 10, 11, 16, 21, 23, 24, 25, 27, 29, 30, 31, 32, 33, 34,
    35, 36, 37, 39, 40, 41, 43, 44, 45, 46, 47, 48, 49, 54, 56, 57, 58, 60,
    61, 63, 64, 65, 68, 69, 72, 73, 79, 81, 82, 83, 84, 85, 89, 91, 92, 93,
    94, 95,
}


@pytest.mark.parametrize("qnum", QS.available_templates())
def test_template_executes(sess, qnum):
    from nds_tpu.engine.sql.parser import parse_script

    rng = np.random.default_rng(1000 + qnum)
    sql = QS.instantiate(qnum, rng, 0.01)
    out = None
    for stmt in parse_script(sql):
        r = sess.run_stmt(stmt)
        if r is not None:
            out = r.collect()
    assert out is not None
    if qnum not in MAY_BE_EMPTY:
        assert out.num_rows > 0, (
            f"query{qnum} returned no rows - parameters select nothing"
        )
