"""Distributed SQL execution: real queries on the 8-device CPU mesh must
produce identical results to the single-device engine (the project's core
TPU-first claim — reference analogue: Spark executor data parallelism,
nds/base.template:28-31)."""

import jax
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.parallel.dist import make_mesh

N_DEV = 8


def _synth_tables(n_fact=4096, n_dates=256, n_items=128, n_stores=8, seed=0):
    rng = np.random.default_rng(seed)
    date_dim = pa.table(
        {
            "d_date_sk": np.arange(2450000, 2450000 + n_dates, dtype=np.int64),
            "d_year": (1998 + (np.arange(n_dates) // 100)).astype(np.int64),
            "d_moy": (np.arange(n_dates) % 12 + 1).astype(np.int64),
        }
    )
    item = pa.table(
        {
            "i_item_sk": np.arange(1, n_items + 1, dtype=np.int64),
            "i_brand_id": rng.integers(1, 12, n_items),
            "i_manager_id": rng.integers(1, 20, n_items),
            "i_category": pa.array(
                rng.choice(["Books", "Music", "Sports", None], n_items)
            ),
        }
    )
    store = pa.table(
        {
            "s_store_sk": np.arange(1, n_stores + 1, dtype=np.int64),
            "s_state": pa.array(rng.choice(["TN", "CA", "TX"], n_stores)),
        }
    )
    price = np.round(rng.random(n_fact) * 100, 2)
    price[rng.random(n_fact) < 0.05] = np.nan
    tickets = rng.integers(1, n_fact // 2, n_fact)
    store_sales = pa.table(
        {
            "ss_sold_date_sk": rng.integers(2450000, 2450000 + n_dates, n_fact),
            "ss_item_sk": rng.integers(1, n_items + 1, n_fact),
            "ss_ticket_number": tickets,
            "ss_store_sk": pa.array(
                np.where(
                    rng.random(n_fact) < 0.03,
                    None,
                    rng.integers(1, n_stores + 1, n_fact).astype(object),
                )
            ).cast(pa.int64()),
            "ss_quantity": rng.integers(1, 100, n_fact),
            "ss_ext_sales_price": pa.array(
                np.where(np.isnan(price), None, price.astype(object)),
                type=pa.float64(),
            ),
        }
    )
    # returns: half sampled from real sales (matching ticket+item), half junk
    n_ret = n_fact // 2
    pick = rng.integers(0, n_fact, n_ret // 2)
    ret_items = np.concatenate(
        [
            np.asarray(store_sales.column("ss_item_sk"))[pick],
            rng.integers(1, n_items + 1, n_ret - n_ret // 2),
        ]
    )
    ret_tickets = np.concatenate(
        [tickets[pick], rng.integers(n_fact, 2 * n_fact, n_ret - n_ret // 2)]
    )
    store_returns = pa.table(
        {
            "sr_item_sk": ret_items,
            "sr_ticket_number": ret_tickets,
            "sr_return_amt": np.round(rng.random(n_ret) * 50, 2),
        }
    )
    return {
        "date_dim": date_dim,
        "item": item,
        "store": store,
        "store_sales": store_sales,
        "store_returns": store_returns,
    }


def _make_session(mesh):
    s = Session(mesh=mesh)
    for name, t in _synth_tables().items():
        s.register_arrow(name, t)
    return s


@pytest.fixture(scope="module")
def oracle():
    return _make_session(None)


@pytest.fixture(scope="module")
def dist():
    assert len(jax.devices()) >= N_DEV
    return _make_session(make_mesh(N_DEV))


QUERIES = {
    "star_agg_q3": """
        select d.d_year, i.i_brand_id brand_id, sum(ss_ext_sales_price) s,
               count(*) cnt
        from date_dim d, store_sales, item i
        where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
          and i.i_manager_id = 10 and d.d_moy = 11
        group by d.d_year, i.i_brand_id
        order by d.d_year, s desc, brand_id
    """,
    "filter_sort_limit": """
        select ss_item_sk, ss_quantity from store_sales
        where ss_quantity > 90 order by ss_quantity desc, ss_item_sk limit 20
    """,
    "left_join_nulls": """
        select s.s_state, count(*) c, avg(ss_quantity) aq
        from store_sales left join store s on ss_store_sk = s_store_sk
        group by s.s_state order by s.s_state
    """,
    "semi_anti": """
        select count(*) c from store_sales
        where ss_item_sk in (select i_item_sk from item where i_brand_id = 3)
          and ss_store_sk not in (select s_store_sk from store where s_state = 'TN')
    """,
    "global_agg": """
        select count(*) c, sum(ss_quantity) sq, min(ss_ext_sales_price) mn,
               max(ss_ext_sales_price) mx
        from store_sales
    """,
    "having_groups": """
        select ss_store_sk, count(*) c from store_sales
        group by ss_store_sk having count(*) > 10 order by ss_store_sk
    """,
    "window_rank": """
        select * from (
            select ss_store_sk, ss_item_sk, ss_quantity,
                   rank() over (partition by ss_store_sk
                                order by ss_quantity desc, ss_item_sk) rk
            from store_sales where ss_store_sk is not null
        ) w where rk <= 3 order by ss_store_sk, rk, ss_item_sk
    """,
    "window_running_sum": """
        select d_year, s_state, sum(sum(ss_quantity)) over
                   (partition by s_state order by d_year) cume
        from store_sales, date_dim, store
        where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        group by d_year, s_state order by s_state, d_year
    """,
    "rollup_groups": """
        select d_year, s_state, count(*) c from store_sales, date_dim, store
        where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        group by rollup(d_year, s_state) order by d_year, s_state
    """,
    "setop_except": """
        select ss_item_sk from store_sales where ss_quantity > 50
        except
        select ss_item_sk from store_sales where ss_quantity <= 50
        order by ss_item_sk
    """,
}


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_distributed_matches_oracle(oracle, dist, qname):
    q = QUERIES[qname]
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for col in a.schema.names:
        av, bv = a.column(col).to_pylist(), b.column(col).to_pylist()
        for x, y in zip(av, bv):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) < 1e-9 or (np.isnan(x) and np.isnan(y))
            else:
                assert x == y, (qname, col, x, y)


FACT_FACT_Q = """
    select ss_item_sk, count(*) c, sum(sr_return_amt) s
    from store_sales, store_returns
    where ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number
    group by ss_item_sk
    order by ss_item_sk
"""


def test_exchange_join_matches_oracle():
    """Mesh fact-fact join: both sides row-sharded, hash-partitioned over the
    exchange, joined locally — must equal the single-device sort join
    (VERDICT r2 item #6; reference analogue: Spark shuffle join)."""
    conf = {"engine.exchange_min_rows": 1}
    oracle = Session(conf=conf)
    dist = Session(mesh=make_mesh(N_DEV), conf=conf)
    for name, t in _synth_tables().items():
        oracle.register_arrow(name, t)
        dist.register_arrow(name, t)
    failures = []
    dist.register_listener(failures.append)
    a = oracle.sql(FACT_FACT_Q).collect()
    b = dist.sql(FACT_FACT_Q).collect()
    assert a.num_rows == b.num_rows and a.num_rows > 0
    for col in a.schema.names:
        for x, y in zip(a.column(col).to_pylist(), b.column(col).to_pylist()):
            if isinstance(x, float):
                assert abs(x - y) < 1e-6, (col, x, y)
            else:
                assert x == y, (col, x, y)


def test_exchange_join_overflow_retries():
    """Skewed keys overflow the first capacity guess; the join must retry
    with doubled caps, emit a task-failure event, and still be correct."""
    rng = np.random.default_rng(7)
    n = 4096
    # 90% of rows share ONE key: that destination's bucket (and its local
    # pair count) overflow the 2x-balanced initial capacity
    # sparse key domain keeps the dense star-join path out of the way
    skew = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 256, n))
    skew = skew * 1_000_003
    left = pa.table({"k": skew, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table(
        {"k": np.arange(256, dtype=np.int64) * 1_000_003,
         "rv": np.arange(256, dtype=np.int64)}
    )
    conf = {"engine.exchange_min_rows": 1}
    oracle = Session(conf=conf)
    dist = Session(mesh=make_mesh(N_DEV), conf=conf)
    for s in (oracle, dist):
        s.register_arrow("l", left)
        s.register_arrow("r", right)
    failures = []
    dist.register_listener(failures.append)
    q = "select count(*) c, sum(lv) sl, sum(rv) sr from l, r where l.k = r.k"
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.to_pylist() == b.to_pylist()
    assert any("exchange join" in f for f in failures)


def _assert_tables_equal(a, b, tol=1e-9, ctx=""):
    assert a.schema.names == b.schema.names, ctx
    assert a.num_rows == b.num_rows, ctx
    for col in a.schema.names:
        for x, y in zip(a.column(col).to_pylist(), b.column(col).to_pylist()):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) < tol or (np.isnan(x) and np.isnan(y)), (
                    ctx, col, x, y,
                )
            else:
                assert x == y, (ctx, col, x, y)


def _exchange_pair(conf=None, tables=None, mesh_devs=N_DEV):
    conf = {"engine.exchange_min_rows": 1, **(conf or {})}
    oracle = Session(conf=dict(conf))
    dist = Session(mesh=make_mesh(mesh_devs), conf=dict(conf))
    for name, t in (tables or {}).items():
        oracle.register_arrow(name, t)
        dist.register_arrow(name, t)
    return oracle, dist


def _spy_exchange(monkeypatch):
    """Record every _try_exchange_join outcome so tests can assert the
    exchange path actually carried the join (not a silent fallback)."""
    from nds_tpu.engine import exec as X

    taken = []
    orig = X.Executor._try_exchange_join

    def spy(self, *a, **kw):
        r = orig(self, *a, **kw)
        taken.append(r is not None)
        return r

    monkeypatch.setattr(X.Executor, "_try_exchange_join", spy)
    return taken


def test_exchange_left_join_null_keys_match_oracle(monkeypatch):
    """LEFT join through the exchange: null-keyed left rows never route but
    MUST survive null-extended, and shipped-but-unmatched rows null-extend
    from the received partition — bit-identical to the single-device path
    (ISSUE 13 satellite: null-keyed LEFT rows surviving the exchange)."""
    taken = _spy_exchange(monkeypatch)
    rng = np.random.default_rng(23)
    n = 4096
    # sparse key domain keeps the dense star-join fast path out of the way
    k = (rng.integers(0, 512, n) * 1_000_003).astype(object)
    k[rng.random(n) < 0.07] = None  # null keys: must null-extend, not drop
    left = pa.table({
        "k": pa.array(k, pa.int64()),
        "lv": np.arange(n, dtype=np.int64),
    })
    # right misses half the key domain -> plenty of unmatched left rows
    right = pa.table({
        "k": np.arange(0, 512, 2, dtype=np.int64) * 1_000_003,
        "rv": np.arange(256, dtype=np.int64) * 10,
    })
    oracle, dist = _exchange_pair(tables={"l": left, "r": right})
    q = ("select l.k, lv, rv from l left join r on l.k = r.k "
         "order by lv, rv")
    _assert_tables_equal(
        oracle.sql(q).collect(), dist.sql(q).collect(), ctx="left-null"
    )
    assert any(taken), "exchange join path was never exercised"
    # aggregate form too (null-keyed rows count, rv sums skip nulls)
    q2 = ("select count(*) c, count(rv) cr, sum(lv) sl, sum(rv) sr "
          "from l left join r on l.k = r.k")
    assert oracle.sql(q2).to_pylist() == dist.sql(q2).to_pylist()


def test_exchange_join_hot_key_skew_matches_oracle(monkeypatch):
    """One key owning >50% of the rows: the hot destination overflows the
    balanced capacity guess, the retry doubles it, and the result still
    equals the oracle — with the skew visible in the `exchange` event."""
    from nds_tpu.obs.trace import Tracer

    taken = _spy_exchange(monkeypatch)
    rng = np.random.default_rng(31)
    n = 8192
    hot = rng.random(n) < 0.6  # 60% of rows share ONE key
    k = np.where(hot, 13, rng.integers(0, 1024, n)) * 1_000_003
    left = pa.table({"k": k, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table({
        "k": np.arange(1024, dtype=np.int64) * 1_000_003,
        "rv": np.arange(1024, dtype=np.int64),
    })
    oracle, dist = _exchange_pair(tables={"l": left, "r": right})
    tracer = Tracer(None)  # in-memory collector
    dist.tracer = tracer
    q = ("select count(*) c, sum(lv) sl, sum(rv) sr from l, r "
         "where l.k = r.k")
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.to_pylist() == b.to_pylist()
    assert any(taken)
    ex = [e for e in tracer.events if e["kind"] == "exchange"]
    assert ex, "no exchange trace evidence"
    assert any(e["skew"] > 2.0 for e in ex), ex  # hot key -> imbalance
    assert all(e["bytes_moved"] > 0 and e["partitions"] == N_DEV
               for e in ex)


def test_exchange_join_skew_feedback_drops_retries_to_zero(
    monkeypatch, tmp_path
):
    """Recorded hot-key skew seeds the NEXT session's exchange capacity
    (analysis/feedback.py): run 1 (record mode) pays the overflow-retry
    doubling and persists the measured skew; run 2 (on mode, same store
    dir) pre-splits its capacity guess from the record and lands the
    identical oracle-equal answer with ZERO retries — the rediscovery
    cost is paid once per fleet, not once per session."""
    from nds_tpu.obs.trace import Tracer

    taken = _spy_exchange(monkeypatch)
    rng = np.random.default_rng(31)
    n = 8192
    hot = rng.random(n) < 0.6  # the same hot-key shape as the probe above
    k = np.where(hot, 13, rng.integers(0, 1024, n)) * 1_000_003
    left = pa.table({"k": k, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table({
        "k": np.arange(1024, dtype=np.int64) * 1_000_003,
        "rv": np.arange(1024, dtype=np.int64),
    })
    q = ("select count(*) c, sum(lv) sl, sum(rv) sr from l, r "
         "where l.k = r.k")

    def run(mode):
        oracle, dist = _exchange_pair(
            conf={"engine.feedback_dir": str(tmp_path / "fb"),
                  "engine.plan_feedback": mode},
            tables={"l": left, "r": right},
        )
        tracer = Tracer(None)
        dist.tracer = tracer
        a = oracle.sql(q).to_pylist()
        b = dist.sql(q).to_pylist()
        assert a == b, mode
        return ([e for e in tracer.events if e["kind"] == "exchange"],
                dist.feedback_store)

    ex1, store1 = run("record")
    assert ex1 and any(e["retries"] > 0 for e in ex1), ex1
    assert store1.stats["skew_records"] >= 1
    ex2, _store2 = run("on")
    assert ex2 and all(e["retries"] == 0 for e in ex2), ex2
    assert any(e["skew"] > 2.0 for e in ex2)  # data still skewed; no retry
    assert any(taken)


def test_exchange_join_empty_partitions_match_oracle(monkeypatch):
    """Keys covering only 2 of 8 destinations: six devices receive ZERO
    rows and the join must still equal the oracle (the empty-partition
    searchsorted/compaction edge)."""
    taken = _spy_exchange(monkeypatch)
    rng = np.random.default_rng(37)
    n = 4096
    # destination = hash(key) % n_dev: with only TWO distinct left keys at
    # most two devices receive left rows — at least six work on empty
    # received partitions (sparse values keep the dense path out)
    k = np.where(rng.random(n) < 0.5, 7, 11) * 1_000_003
    left = pa.table({"k": k, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table({
        "k": np.arange(0, 256, dtype=np.int64) * 1_000_003,
        "rv": np.arange(256, dtype=np.int64),
    })
    oracle, dist = _exchange_pair(tables={"l": left, "r": right})
    q = ("select count(*) c, sum(lv) sl, sum(rv) sr from l, r "
         "where l.k = r.k")
    assert oracle.sql(q).to_pylist() == dist.sql(q).to_pylist()
    # left-join flavor rides the same received partitions
    q2 = ("select count(*) c, count(rv) cr from l left join r "
          "on l.k = r.k")
    assert oracle.sql(q2).to_pylist() == dist.sql(q2).to_pylist()
    assert any(taken)


def test_exchange_persistent_overflow_tiers_through_spill_pool(monkeypatch):
    """Single-key-scale skew a hash partitioning can never split: every
    retry re-overflows, and the join must tier through the host spill pool
    (planned degradation composing with scale-out) instead of aborting —
    still oracle-equal, with spill evidence recorded."""
    from nds_tpu.engine import exec as X

    # force every attempt to report overflow so the retry loop exhausts
    taken = _spy_exchange(monkeypatch)
    n = 4096
    # ONE (sparse) key owns the table; sparse values decline the dense path
    k = np.full(n, 7 * 1_000_003, dtype=np.int64)
    left = pa.table({"k": k, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table({"k": np.array([7, 9], dtype=np.int64) * 1_000_003,
                      "rv": np.array([1, 2], dtype=np.int64)})
    monkeypatch.setattr(X.Executor, "_EXCHANGE_MAX_ATTEMPTS", 0)
    oracle, dist = _exchange_pair(tables={"l": left, "r": right})
    failures = []
    dist.register_listener(failures.append)
    q = "select count(*) c, sum(lv) sl, sum(rv) sr from l, r where l.k = r.k"
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.to_pylist() == b.to_pylist()
    assert any("spill pool" in f for f in failures), failures
    assert dist.last_spill is not None and dist.last_spill["ops"] >= 1
    assert any(taken)


def test_semi_filtered_dim_join_matches_oracle():
    """Regression for the query83/query77 mesh mismatch the SF0.01 gate
    caught: a sharded fact joined against a SEMI-filtered replicated dim
    compacts the masked dim through compact_indices — whose cumsum+scatter
    kernel the SPMD partitioner mislowers on sharded masks (rows silently
    dropped). The full shape must equal the single-device oracle."""
    rng = np.random.default_rng(5)
    nd = 73049
    dim_sk = np.arange(2415022, 2415022 + nd, dtype=np.int64)
    dval = np.array([f"v{i % 97}" for i in range(nd)])
    nf = 736  # the SF0.01 web_returns scale that exposed the truncation
    fact = pa.table({
        "wr_returned_date_sk": rng.choice(dim_sk, nf),
        "wr_return_quantity": rng.integers(1, 50, nf),
    })
    dim = pa.table({
        "d_date_sk": dim_sk, "d_date": dval,
        "d_week_seq": (np.arange(nd) // 7).astype(np.int64),
    })
    oracle_s = Session()
    dist_s = Session(mesh=make_mesh(N_DEV))
    for s in (oracle_s, dist_s):
        s.register_arrow("web_returns", fact)  # fact name -> row-sharded
        s.register_arrow("date_dim", dim)
    q = """select count(*) c, sum(wr_return_quantity) s
           from web_returns, date_dim
           where d_date in (select d_date from date_dim where d_week_seq in
               (select d_week_seq from date_dim where d_date in ('v3','v5')))
           and wr_returned_date_sk = d_date_sk"""
    a = oracle_s.sql(q).to_pylist()
    b = dist_s.sql(q).to_pylist()
    assert a == b and a[0]["c"] > 0, (a, b)


def test_sharded_agg_partial_merge_matches_oracle(dist, oracle):
    """Decomposable aggregates over a row-sharded fact reduce per shard and
    merge (the scatter-add lowers to per-chip partials + cross-chip merge
    under GSPMD) — sums/counts/extremes/avg must equal the oracle."""
    q = """
        select ss_quantity bucket, count(*) c, sum(ss_item_sk) s,
               min(ss_ext_sales_price) mn, max(ss_ext_sales_price) mx,
               avg(ss_ticket_number) aq
        from store_sales group by ss_quantity order by bucket
    """
    _assert_tables_equal(
        oracle.sql(q).collect(), dist.sql(q).collect(), ctx="agg-merge"
    )


def test_sharding_fallback_is_loud():
    """A mesh that can't divide the fact-table capacity must announce the
    replication fallback through the listener chain, never degrade silently
    (VERDICT r2 weak #3) — and since ISSUE 13 additionally emit a
    `mesh_fallback` trace event (schema-valid, metric-counted), record the
    fallback on the catalog entry, and have the verifier's replicated-dim
    rule flag every later plan scanning the replicated fact."""
    from nds_tpu.analysis.verifier import PlanVerifier
    from nds_tpu.engine import plan as P
    from nds_tpu.obs.metrics import MetricsSink
    from nds_tpu.obs.reader import validate_events
    from nds_tpu.obs.trace import Tracer

    s = Session(mesh=make_mesh(3))
    tracer = Tracer(None)  # in-memory collector
    tracer.sink = MetricsSink()
    s.tracer = tracer
    events = []
    s.register_listener(events.append)
    for name, t in _synth_tables().items():
        s.register_arrow(name, t)
    s.catalog.load("store_sales", ["ss_item_sk"])
    assert any("sharding fallback" in e for e in events)
    fb = [e for e in tracer.events if e["kind"] == "mesh_fallback"]
    assert fb and fb[0]["table"] == "store_sales" and fb[0]["n_dev"] == 3
    assert fb[0]["bytes"] > 0
    validate_events(tracer.events)  # schema contract holds
    assert (
        tracer.sink.registry.counter_value(
            "nds_mesh_fallback_total", table="store_sales"
        )
        == 1
    )
    assert s.catalog.entries["store_sales"].mesh_fallback
    # the verifier flags every later plan that scans the replicated fact
    plan = P.Scan("store_sales", "store_sales", ["ss_item_sk"])
    v = PlanVerifier(s.catalog).verify(plan, mesh=make_mesh(3))
    assert any(
        "replicated-dim" in x and "mesh fallback" in x for x in v
    ), v


def test_profile_compare_multichip_rounds(tmp_path):
    """`profile --bench` MULTICHIP mode: an old driver-wrapper round
    ({ok, tail} only — r01–r05 predate the metrics block) compares
    fail-soft (old_ratio null), a worsened mesh-vs-oracle ratio or an
    ok->not-ok flip flags regression, and the --bench handler routes
    multichip artifacts away from the sqlite_shared comparison."""
    import json

    from nds_tpu.cli.profile import _compare_multichip

    old_wrapper = tmp_path / "MULTICHIP_r05.json"
    old_wrapper.write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": "dryrun ok"}
    ))
    new_block = tmp_path / "gate.json"
    new_block.write_text(json.dumps({
        "n_devices": 8, "ok": True, "matched": 103,
        "mesh_vs_oracle_wall_ratio": 2.5,
    }))
    (rec,) = _compare_multichip(str(old_wrapper), str(new_block))
    assert rec["change"] == "headline" and rec["old_ratio"] is None
    assert rec["new_ratio"] == 2.5 and rec["queries"] == 103
    # ok -> not-ok is a regression even without ratios
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"n_devices": 8, "ok": False, "matched": 1}))
    (rec2,) = _compare_multichip(str(old_wrapper), str(bad))
    assert rec2["change"] == "regression"
    # ratio worsening > 25% between two metric rounds flags too
    older = tmp_path / "older.json"
    older.write_text(json.dumps({
        "n_devices": 8, "ok": True, "mesh_vs_oracle_wall_ratio": 1.5,
    }))
    (rec3,) = _compare_multichip(str(older), str(new_block))
    assert rec3["change"] == "regression"
    # unreadable new artifact degrades to a status_change record
    (rec4,) = _compare_multichip(str(old_wrapper), str(tmp_path / "nope"))
    assert rec4["change"] == "status_change"


def test_fact_columns_are_row_sharded(dist):
    t = dist.catalog.load("store_sales", ["ss_item_sk"])
    sharding = t.columns["ss_item_sk"].data.sharding
    assert len(sharding.device_set) == N_DEV
    # dims replicate
    d = dist.catalog.load("item", ["i_item_sk"])
    assert d.columns["i_item_sk"].data.sharding.is_fully_replicated


def test_distributed_sort_matches_oracle(monkeypatch):
    """Full-table ORDER BY under the mesh goes through the samplesort
    exchange (not an all-gathering lexsort) and matches the oracle."""
    from nds_tpu.engine import exec as X

    taken = []
    orig = X.Executor._try_dist_sort

    def spy(self, child, keys):
        r = orig(self, child, keys)
        taken.append(r is not None)
        return r

    monkeypatch.setattr(X.Executor, "_try_dist_sort", spy)
    conf = {"engine.dist_sort_min_rows": 1}
    dist_s = Session(mesh=make_mesh(N_DEV), conf=conf)
    oracle_s = Session(conf=conf)
    for name, t in _synth_tables(seed=5).items():
        dist_s.register_arrow(name, t)
        oracle_s.register_arrow(name, t)
    queries = [
        # non-null primary key, desc
        """select ss_item_sk, ss_quantity, ss_ticket_number from store_sales
           order by ss_quantity desc, ss_item_sk, ss_ticket_number""",
        # NULLABLE primary key (nulls first for asc), secondary ties
        """select ss_store_sk, ss_item_sk, ss_ticket_number from store_sales
           order by ss_store_sk, ss_item_sk, ss_ticket_number, ss_quantity""",
    ]
    for q in queries:
        got = dist_s.sql(q).collect()
        want = oracle_s.sql(q).collect()
        assert got.num_rows == want.num_rows > 0
        assert got.to_pylist() == want.to_pylist(), q
    assert any(taken), "distributed sort path was never exercised"
