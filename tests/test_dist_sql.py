"""Distributed SQL execution: real queries on the 8-device CPU mesh must
produce identical results to the single-device engine (the project's core
TPU-first claim — reference analogue: Spark executor data parallelism,
nds/base.template:28-31)."""

import jax
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.parallel.dist import make_mesh

N_DEV = 8


def _synth_tables(n_fact=4096, n_dates=256, n_items=128, n_stores=8, seed=0):
    rng = np.random.default_rng(seed)
    date_dim = pa.table(
        {
            "d_date_sk": np.arange(2450000, 2450000 + n_dates, dtype=np.int64),
            "d_year": (1998 + (np.arange(n_dates) // 100)).astype(np.int64),
            "d_moy": (np.arange(n_dates) % 12 + 1).astype(np.int64),
        }
    )
    item = pa.table(
        {
            "i_item_sk": np.arange(1, n_items + 1, dtype=np.int64),
            "i_brand_id": rng.integers(1, 12, n_items),
            "i_manager_id": rng.integers(1, 20, n_items),
            "i_category": pa.array(
                rng.choice(["Books", "Music", "Sports", None], n_items)
            ),
        }
    )
    store = pa.table(
        {
            "s_store_sk": np.arange(1, n_stores + 1, dtype=np.int64),
            "s_state": pa.array(rng.choice(["TN", "CA", "TX"], n_stores)),
        }
    )
    price = np.round(rng.random(n_fact) * 100, 2)
    price[rng.random(n_fact) < 0.05] = np.nan
    tickets = rng.integers(1, n_fact // 2, n_fact)
    store_sales = pa.table(
        {
            "ss_sold_date_sk": rng.integers(2450000, 2450000 + n_dates, n_fact),
            "ss_item_sk": rng.integers(1, n_items + 1, n_fact),
            "ss_ticket_number": tickets,
            "ss_store_sk": pa.array(
                np.where(
                    rng.random(n_fact) < 0.03,
                    None,
                    rng.integers(1, n_stores + 1, n_fact).astype(object),
                )
            ).cast(pa.int64()),
            "ss_quantity": rng.integers(1, 100, n_fact),
            "ss_ext_sales_price": pa.array(
                np.where(np.isnan(price), None, price.astype(object)),
                type=pa.float64(),
            ),
        }
    )
    # returns: half sampled from real sales (matching ticket+item), half junk
    n_ret = n_fact // 2
    pick = rng.integers(0, n_fact, n_ret // 2)
    ret_items = np.concatenate(
        [
            np.asarray(store_sales.column("ss_item_sk"))[pick],
            rng.integers(1, n_items + 1, n_ret - n_ret // 2),
        ]
    )
    ret_tickets = np.concatenate(
        [tickets[pick], rng.integers(n_fact, 2 * n_fact, n_ret - n_ret // 2)]
    )
    store_returns = pa.table(
        {
            "sr_item_sk": ret_items,
            "sr_ticket_number": ret_tickets,
            "sr_return_amt": np.round(rng.random(n_ret) * 50, 2),
        }
    )
    return {
        "date_dim": date_dim,
        "item": item,
        "store": store,
        "store_sales": store_sales,
        "store_returns": store_returns,
    }


def _make_session(mesh):
    s = Session(mesh=mesh)
    for name, t in _synth_tables().items():
        s.register_arrow(name, t)
    return s


@pytest.fixture(scope="module")
def oracle():
    return _make_session(None)


@pytest.fixture(scope="module")
def dist():
    assert len(jax.devices()) >= N_DEV
    return _make_session(make_mesh(N_DEV))


QUERIES = {
    "star_agg_q3": """
        select d.d_year, i.i_brand_id brand_id, sum(ss_ext_sales_price) s,
               count(*) cnt
        from date_dim d, store_sales, item i
        where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
          and i.i_manager_id = 10 and d.d_moy = 11
        group by d.d_year, i.i_brand_id
        order by d.d_year, s desc, brand_id
    """,
    "filter_sort_limit": """
        select ss_item_sk, ss_quantity from store_sales
        where ss_quantity > 90 order by ss_quantity desc, ss_item_sk limit 20
    """,
    "left_join_nulls": """
        select s.s_state, count(*) c, avg(ss_quantity) aq
        from store_sales left join store s on ss_store_sk = s_store_sk
        group by s.s_state order by s.s_state
    """,
    "semi_anti": """
        select count(*) c from store_sales
        where ss_item_sk in (select i_item_sk from item where i_brand_id = 3)
          and ss_store_sk not in (select s_store_sk from store where s_state = 'TN')
    """,
    "global_agg": """
        select count(*) c, sum(ss_quantity) sq, min(ss_ext_sales_price) mn,
               max(ss_ext_sales_price) mx
        from store_sales
    """,
    "having_groups": """
        select ss_store_sk, count(*) c from store_sales
        group by ss_store_sk having count(*) > 10 order by ss_store_sk
    """,
    "window_rank": """
        select * from (
            select ss_store_sk, ss_item_sk, ss_quantity,
                   rank() over (partition by ss_store_sk
                                order by ss_quantity desc, ss_item_sk) rk
            from store_sales where ss_store_sk is not null
        ) w where rk <= 3 order by ss_store_sk, rk, ss_item_sk
    """,
    "window_running_sum": """
        select d_year, s_state, sum(sum(ss_quantity)) over
                   (partition by s_state order by d_year) cume
        from store_sales, date_dim, store
        where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        group by d_year, s_state order by s_state, d_year
    """,
    "rollup_groups": """
        select d_year, s_state, count(*) c from store_sales, date_dim, store
        where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        group by rollup(d_year, s_state) order by d_year, s_state
    """,
    "setop_except": """
        select ss_item_sk from store_sales where ss_quantity > 50
        except
        select ss_item_sk from store_sales where ss_quantity <= 50
        order by ss_item_sk
    """,
}


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_distributed_matches_oracle(oracle, dist, qname):
    q = QUERIES[qname]
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for col in a.schema.names:
        av, bv = a.column(col).to_pylist(), b.column(col).to_pylist()
        for x, y in zip(av, bv):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) < 1e-9 or (np.isnan(x) and np.isnan(y))
            else:
                assert x == y, (qname, col, x, y)


FACT_FACT_Q = """
    select ss_item_sk, count(*) c, sum(sr_return_amt) s
    from store_sales, store_returns
    where ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number
    group by ss_item_sk
    order by ss_item_sk
"""


def test_exchange_join_matches_oracle():
    """Mesh fact-fact join: both sides row-sharded, hash-partitioned over the
    exchange, joined locally — must equal the single-device sort join
    (VERDICT r2 item #6; reference analogue: Spark shuffle join)."""
    conf = {"engine.exchange_min_rows": 1}
    oracle = Session(conf=conf)
    dist = Session(mesh=make_mesh(N_DEV), conf=conf)
    for name, t in _synth_tables().items():
        oracle.register_arrow(name, t)
        dist.register_arrow(name, t)
    failures = []
    dist.register_listener(failures.append)
    a = oracle.sql(FACT_FACT_Q).collect()
    b = dist.sql(FACT_FACT_Q).collect()
    assert a.num_rows == b.num_rows and a.num_rows > 0
    for col in a.schema.names:
        for x, y in zip(a.column(col).to_pylist(), b.column(col).to_pylist()):
            if isinstance(x, float):
                assert abs(x - y) < 1e-6, (col, x, y)
            else:
                assert x == y, (col, x, y)


def test_exchange_join_overflow_retries():
    """Skewed keys overflow the first capacity guess; the join must retry
    with doubled caps, emit a task-failure event, and still be correct."""
    rng = np.random.default_rng(7)
    n = 4096
    # 90% of rows share ONE key: that destination's bucket (and its local
    # pair count) overflow the 2x-balanced initial capacity
    # sparse key domain keeps the dense star-join path out of the way
    skew = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 256, n))
    skew = skew * 1_000_003
    left = pa.table({"k": skew, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table(
        {"k": np.arange(256, dtype=np.int64) * 1_000_003,
         "rv": np.arange(256, dtype=np.int64)}
    )
    conf = {"engine.exchange_min_rows": 1}
    oracle = Session(conf=conf)
    dist = Session(mesh=make_mesh(N_DEV), conf=conf)
    for s in (oracle, dist):
        s.register_arrow("l", left)
        s.register_arrow("r", right)
    failures = []
    dist.register_listener(failures.append)
    q = "select count(*) c, sum(lv) sl, sum(rv) sr from l, r where l.k = r.k"
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.to_pylist() == b.to_pylist()
    assert any("exchange join" in f for f in failures)


def test_sharding_fallback_is_loud():
    """A mesh that can't divide the fact-table capacity must announce the
    replication fallback through the listener chain, never degrade silently
    (VERDICT r2 weak #3)."""
    s = Session(mesh=make_mesh(3))
    events = []
    s.register_listener(events.append)
    for name, t in _synth_tables().items():
        s.register_arrow(name, t)
    s.catalog.load("store_sales", ["ss_item_sk"])
    assert any("sharding fallback" in e for e in events)


def test_fact_columns_are_row_sharded(dist):
    t = dist.catalog.load("store_sales", ["ss_item_sk"])
    sharding = t.columns["ss_item_sk"].data.sharding
    assert len(sharding.device_set) == N_DEV
    # dims replicate
    d = dist.catalog.load("item", ["i_item_sk"])
    assert d.columns["i_item_sk"].data.sharding.is_fully_replicated


def test_distributed_sort_matches_oracle(monkeypatch):
    """Full-table ORDER BY under the mesh goes through the samplesort
    exchange (not an all-gathering lexsort) and matches the oracle."""
    from nds_tpu.engine import exec as X

    taken = []
    orig = X.Executor._try_dist_sort

    def spy(self, child, keys):
        r = orig(self, child, keys)
        taken.append(r is not None)
        return r

    monkeypatch.setattr(X.Executor, "_try_dist_sort", spy)
    conf = {"engine.dist_sort_min_rows": 1}
    dist_s = Session(mesh=make_mesh(N_DEV), conf=conf)
    oracle_s = Session(conf=conf)
    for name, t in _synth_tables(seed=5).items():
        dist_s.register_arrow(name, t)
        oracle_s.register_arrow(name, t)
    queries = [
        # non-null primary key, desc
        """select ss_item_sk, ss_quantity, ss_ticket_number from store_sales
           order by ss_quantity desc, ss_item_sk, ss_ticket_number""",
        # NULLABLE primary key (nulls first for asc), secondary ties
        """select ss_store_sk, ss_item_sk, ss_ticket_number from store_sales
           order by ss_store_sk, ss_item_sk, ss_ticket_number, ss_quantity""",
    ]
    for q in queries:
        got = dist_s.sql(q).collect()
        want = oracle_s.sql(q).collect()
        assert got.num_rows == want.num_rows > 0
        assert got.to_pylist() == want.to_pylist(), q
    assert any(taken), "distributed sort path was never exercised"
