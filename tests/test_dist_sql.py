"""Distributed SQL execution: real queries on the 8-device CPU mesh must
produce identical results to the single-device engine (the project's core
TPU-first claim — reference analogue: Spark executor data parallelism,
nds/base.template:28-31)."""

import jax
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.parallel.dist import make_mesh

N_DEV = 8


def _synth_tables(n_fact=4096, n_dates=256, n_items=128, n_stores=8, seed=0):
    rng = np.random.default_rng(seed)
    date_dim = pa.table(
        {
            "d_date_sk": np.arange(2450000, 2450000 + n_dates, dtype=np.int64),
            "d_year": (1998 + (np.arange(n_dates) // 100)).astype(np.int64),
            "d_moy": (np.arange(n_dates) % 12 + 1).astype(np.int64),
        }
    )
    item = pa.table(
        {
            "i_item_sk": np.arange(1, n_items + 1, dtype=np.int64),
            "i_brand_id": rng.integers(1, 12, n_items),
            "i_manager_id": rng.integers(1, 20, n_items),
            "i_category": pa.array(
                rng.choice(["Books", "Music", "Sports", None], n_items)
            ),
        }
    )
    store = pa.table(
        {
            "s_store_sk": np.arange(1, n_stores + 1, dtype=np.int64),
            "s_state": pa.array(rng.choice(["TN", "CA", "TX"], n_stores)),
        }
    )
    price = np.round(rng.random(n_fact) * 100, 2)
    price[rng.random(n_fact) < 0.05] = np.nan
    store_sales = pa.table(
        {
            "ss_sold_date_sk": rng.integers(2450000, 2450000 + n_dates, n_fact),
            "ss_item_sk": rng.integers(1, n_items + 1, n_fact),
            "ss_store_sk": pa.array(
                np.where(
                    rng.random(n_fact) < 0.03,
                    None,
                    rng.integers(1, n_stores + 1, n_fact).astype(object),
                )
            ).cast(pa.int64()),
            "ss_quantity": rng.integers(1, 100, n_fact),
            "ss_ext_sales_price": pa.array(
                np.where(np.isnan(price), None, price.astype(object)),
                type=pa.float64(),
            ),
        }
    )
    return {
        "date_dim": date_dim,
        "item": item,
        "store": store,
        "store_sales": store_sales,
    }


def _make_session(mesh):
    s = Session(mesh=mesh)
    for name, t in _synth_tables().items():
        s.register_arrow(name, t)
    return s


@pytest.fixture(scope="module")
def oracle():
    return _make_session(None)


@pytest.fixture(scope="module")
def dist():
    assert len(jax.devices()) >= N_DEV
    return _make_session(make_mesh(N_DEV))


QUERIES = {
    "star_agg_q3": """
        select d.d_year, i.i_brand_id brand_id, sum(ss_ext_sales_price) s,
               count(*) cnt
        from date_dim d, store_sales, item i
        where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
          and i.i_manager_id = 10 and d.d_moy = 11
        group by d.d_year, i.i_brand_id
        order by d.d_year, s desc, brand_id
    """,
    "filter_sort_limit": """
        select ss_item_sk, ss_quantity from store_sales
        where ss_quantity > 90 order by ss_quantity desc, ss_item_sk limit 20
    """,
    "left_join_nulls": """
        select s.s_state, count(*) c, avg(ss_quantity) aq
        from store_sales left join store s on ss_store_sk = s_store_sk
        group by s.s_state order by s.s_state
    """,
    "semi_anti": """
        select count(*) c from store_sales
        where ss_item_sk in (select i_item_sk from item where i_brand_id = 3)
          and ss_store_sk not in (select s_store_sk from store where s_state = 'TN')
    """,
    "global_agg": """
        select count(*) c, sum(ss_quantity) sq, min(ss_ext_sales_price) mn,
               max(ss_ext_sales_price) mx
        from store_sales
    """,
    "having_groups": """
        select ss_store_sk, count(*) c from store_sales
        group by ss_store_sk having count(*) > 10 order by ss_store_sk
    """,
}


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_distributed_matches_oracle(oracle, dist, qname):
    q = QUERIES[qname]
    a = oracle.sql(q).collect()
    b = dist.sql(q).collect()
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for col in a.schema.names:
        av, bv = a.column(col).to_pylist(), b.column(col).to_pylist()
        for x, y in zip(av, bv):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) < 1e-9 or (np.isnan(x) and np.isnan(y))
            else:
                assert x == y, (qname, col, x, y)


def test_fact_columns_are_row_sharded(dist):
    t = dist.catalog.load("store_sales", ["ss_item_sk"])
    sharding = t.columns["ss_item_sk"].data.sharding
    assert len(sharding.device_set) == N_DEV
    # dims replicate
    d = dist.catalog.load("item", ["i_item_sk"])
    assert d.columns["i_item_sk"].data.sharding.is_fully_replicated
