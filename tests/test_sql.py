"""End-to-end SQL engine tests: parse -> bind -> execute on device tables,
checked against hand-computed results and pandas oracles."""

from decimal import Decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session

rng = np.random.default_rng(7)


@pytest.fixture(scope="module")
def sess():
    s = Session()
    n = 500
    item = pa.table(
        {
            "i_item_sk": pa.array(range(1, 51), pa.int32()),
            "i_brand_id": pa.array([i % 5 + 1 for i in range(50)], pa.int32()),
            "i_brand": pa.array([f"brand{i % 5 + 1}" for i in range(50)]),
            "i_category": pa.array(
                [["Books", "Music", "Shoes"][i % 3] for i in range(50)]
            ),
            "i_price": pa.array(
                [Decimal(f"{(i % 20) + 0.5:.2f}") for i in range(50)],
                pa.decimal128(7, 2),
            ),
        }
    )
    date_dim = pa.table(
        {
            "d_date_sk": pa.array(range(1, 101), pa.int32()),
            "d_year": pa.array([1998 + i // 50 for i in range(100)], pa.int32()),
            "d_moy": pa.array([i % 12 + 1 for i in range(100)], pa.int32()),
        }
    )
    sales_item = rng.integers(1, 51, n)
    sales_date = rng.integers(1, 101, n)
    qty = rng.integers(1, 10, n)
    price = rng.integers(100, 10000, n)  # cents
    cust = rng.integers(1, 21, n)
    store_sales = pa.table(
        {
            "ss_item_sk": pa.array(sales_item, pa.int32()),
            "ss_sold_date_sk": pa.array(
                [None if i % 17 == 0 else int(v) for i, v in enumerate(sales_date)],
                pa.int32(),
            ),
            "ss_customer_sk": pa.array(cust, pa.int32()),
            "ss_quantity": pa.array(qty, pa.int32()),
            "ss_price": pa.array(
                [Decimal(int(p)) / 100 for p in price], pa.decimal128(7, 2)
            ),
        }
    )
    s.register_arrow("item", item)
    s.register_arrow("date_dim", date_dim)
    s.register_arrow("store_sales", store_sales)
    s._pd = {
        "item": item.to_pandas(),
        "date_dim": date_dim.to_pandas(),
        "store_sales": store_sales.to_pandas(),
    }
    return s


def test_scan_filter_project(sess):
    out = sess.sql(
        "select i_item_sk, i_brand from item where i_brand_id = 2 order by i_item_sk"
    ).collect()
    pdf = sess._pd["item"]
    expect = pdf[pdf.i_brand_id == 2].sort_values("i_item_sk")
    assert out.column("i_item_sk").to_pylist() == expect.i_item_sk.tolist()
    assert out.column("i_brand").to_pylist() == expect.i_brand.tolist()


def test_join_group_order_limit(sess):
    # q3-shaped query
    out = sess.sql(
        """
        select d.d_year, i.i_brand_id brand_id, sum(ss_quantity) s
        from date_dim d, store_sales, item i
        where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
          and i.i_category = 'Books' and d.d_moy = 11
        group by d.d_year, i.i_brand_id
        order by d.d_year, s desc, brand_id
        limit 10
        """
    ).collect()
    pdf = sess._pd
    m = pdf["store_sales"].merge(
        pdf["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    ).merge(pdf["item"], left_on="ss_item_sk", right_on="i_item_sk")
    m = m[(m.i_category == "Books") & (m.d_moy == 11)]
    e = (
        m.groupby(["d_year", "i_brand_id"])["ss_quantity"]
        .sum()
        .reset_index()
        .sort_values(
            ["d_year", "ss_quantity", "i_brand_id"],
            ascending=[True, False, True],
        )
        .head(10)
    )
    assert out.column("d_year").to_pylist() == e.d_year.tolist()
    assert out.column("brand_id").to_pylist() == e.i_brand_id.tolist()
    assert out.column("s").to_pylist() == e.ss_quantity.tolist()


def test_decimal_agg(sess):
    out = sess.sql(
        "select sum(ss_price * ss_quantity) total from store_sales"
    ).collect()
    pdf = sess._pd["store_sales"]
    expect = (pdf.ss_price * pdf.ss_quantity).sum()
    got = out.column("total").to_pylist()[0]
    assert got == expect


def test_avg_and_count(sess):
    out = sess.sql(
        """
        select ss_customer_sk, count(*) c, avg(ss_quantity) a,
               count(distinct ss_item_sk) d
        from store_sales group by ss_customer_sk order by ss_customer_sk
        """
    ).collect()
    pdf = sess._pd["store_sales"]
    g = pdf.groupby("ss_customer_sk")
    e_c = g.size()
    e_a = g.ss_quantity.mean()
    e_d = g.ss_item_sk.nunique()
    assert out.column("c").to_pylist() == e_c.tolist()
    np.testing.assert_allclose(out.column("a").to_pylist(), e_a.tolist())
    assert out.column("d").to_pylist() == e_d.tolist()


def test_left_join_null_extension(sess):
    out = sess.sql(
        """
        select i.i_item_sk, d.d_year
        from item i left outer join
             (select distinct ss_item_sk, d_year
              from store_sales, date_dim where ss_sold_date_sk = d_date_sk
                and d_year = 1998 and ss_item_sk < 3) s
          on i.i_item_sk = s.ss_item_sk
        left outer join date_dim d on s.d_year = d.d_date_sk
        where i.i_item_sk <= 3 order by i.i_item_sk
        """
    ).collect()
    assert out.num_rows == 3


def test_subquery_scalar_uncorrelated(sess):
    out = sess.sql(
        """
        select count(*) c from store_sales
        where ss_quantity > (select avg(ss_quantity) from store_sales)
        """
    ).collect()
    pdf = sess._pd["store_sales"]
    expect = int((pdf.ss_quantity > pdf.ss_quantity.mean()).sum())
    assert out.column("c").to_pylist() == [expect]


def test_subquery_in(sess):
    out = sess.sql(
        """
        select count(*) c from store_sales
        where ss_item_sk in (select i_item_sk from item where i_category = 'Music')
        """
    ).collect()
    pdf = sess._pd
    music = set(
        pdf["item"][pdf["item"].i_category == "Music"].i_item_sk.tolist()
    )
    expect = int(pdf["store_sales"].ss_item_sk.isin(music).sum())
    assert out.column("c").to_pylist() == [expect]


def test_subquery_correlated_scalar(sess):
    # q1-style: rows above their group average
    out = sess.sql(
        """
        select count(*) c from store_sales s1
        where s1.ss_quantity > (
            select avg(s2.ss_quantity) * 1.2 from store_sales s2
            where s2.ss_customer_sk = s1.ss_customer_sk)
        """
    ).collect()
    pdf = sess._pd["store_sales"]
    avg = pdf.groupby("ss_customer_sk").ss_quantity.mean() * 1.2
    expect = int(
        (pdf.ss_quantity > pdf.ss_customer_sk.map(avg)).sum()
    )
    assert out.column("c").to_pylist() == [expect]


def test_exists_correlated(sess):
    out = sess.sql(
        """
        select count(*) c from item i
        where exists (select 1 from store_sales where ss_item_sk = i.i_item_sk
                      and ss_quantity > 8)
        """
    ).collect()
    pdf = sess._pd
    hot = set(
        pdf["store_sales"][pdf["store_sales"].ss_quantity > 8].ss_item_sk
    )
    expect = int(pdf["item"].i_item_sk.isin(hot).sum())
    assert out.column("c").to_pylist() == [expect]


def test_not_in_subquery(sess):
    out = sess.sql(
        """
        select count(*) c from item
        where i_item_sk not in (select ss_item_sk from store_sales)
        """
    ).collect()
    pdf = sess._pd
    sold = set(pdf["store_sales"].ss_item_sk)
    expect = int((~pdf["item"].i_item_sk.isin(sold)).sum())
    assert out.column("c").to_pylist() == [expect]


def test_union_all_and_intersect(sess):
    out = sess.sql(
        """
        select i_brand_id from item where i_category = 'Books'
        intersect
        select i_brand_id from item where i_category = 'Music'
        order by i_brand_id
        """
    ).collect()
    pdf = sess._pd["item"]
    b = set(pdf[pdf.i_category == "Books"].i_brand_id)
    m = set(pdf[pdf.i_category == "Music"].i_brand_id)
    assert out.column("i_brand_id").to_pylist() == sorted(b & m)

    out2 = sess.sql(
        """
        select count(*) c from (
          select i_item_sk from item where i_brand_id = 1
          union all
          select i_item_sk from item where i_category = 'Shoes') u
        """
    ).collect()
    expect = int((pdf.i_brand_id == 1).sum() + (pdf.i_category == "Shoes").sum())
    assert out2.column("c").to_pylist() == [expect]


def test_cte(sess):
    out = sess.sql(
        """
        with hot as (select ss_item_sk, sum(ss_quantity) q
                     from store_sales group by ss_item_sk)
        select count(*) c from hot where q > 50
        """
    ).collect()
    pdf = sess._pd["store_sales"]
    q = pdf.groupby("ss_item_sk").ss_quantity.sum()
    assert out.column("c").to_pylist() == [int((q > 50).sum())]


def test_rollup(sess):
    out = sess.sql(
        """
        select i_category, i_brand_id, sum(i_price) p
        from item group by rollup(i_category, i_brand_id)
        order by i_category nulls last, i_brand_id nulls last
        """
    ).collect()
    pdf = sess._pd["item"]
    detail = pdf.groupby(["i_category", "i_brand_id"]).i_price.sum()
    ncats = pdf.i_category.nunique()
    # detail rows + per-category subtotals + grand total
    assert out.num_rows == len(detail) + ncats + 1
    total_row = out.to_pylist()[-1]
    assert total_row["i_category"] is None and total_row["i_brand_id"] is None
    assert float(total_row["p"]) == pytest.approx(float(pdf.i_price.sum()))


def test_having(sess):
    out = sess.sql(
        """
        select ss_item_sk from store_sales group by ss_item_sk
        having count(*) > 12 order by ss_item_sk
        """
    ).collect()
    pdf = sess._pd["store_sales"]
    e = pdf.groupby("ss_item_sk").size()
    assert out.column("ss_item_sk").to_pylist() == sorted(e[e > 12].index.tolist())


def test_window_rank(sess):
    out = sess.sql(
        """
        select i_category, i_item_sk,
               rank() over (partition by i_category order by i_price desc) rk
        from item
        """
    ).collect()
    pdf = sess._pd["item"].copy()
    pdf["rk"] = pdf.groupby("i_category").i_price.rank(
        method="min", ascending=False
    )
    got = {
        (r["i_category"], r["i_item_sk"]): r["rk"] for r in out.to_pylist()
    }
    for _, row in pdf.iterrows():
        assert got[(row.i_category, row.i_item_sk)] == int(row.rk)


def test_window_sum_partition(sess):
    out = sess.sql(
        """
        select i_item_sk, sum(i_price) over (partition by i_category) t
        from item
        """
    ).collect()
    pdf = sess._pd["item"].copy()
    t = pdf.groupby("i_category").i_price.transform("sum")
    got = dict(zip(out.column("i_item_sk").to_pylist(), out.column("t").to_pylist()))
    for sk, expect in zip(pdf.i_item_sk, t):
        assert got[sk] == expect


def test_case_in_aggregation(sess):
    out = sess.sql(
        """
        select sum(case when d_year = 1998 then ss_quantity else 0 end) a,
               sum(case when d_year = 1999 then ss_quantity else 0 end) b
        from store_sales, date_dim where ss_sold_date_sk = d_date_sk
        """
    ).collect()
    pdf = sess._pd
    m = pdf["store_sales"].merge(
        pdf["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk"
    )
    assert out.column("a").to_pylist() == [
        int(m[m.d_year == 1998].ss_quantity.sum())
    ]
    assert out.column("b").to_pylist() == [
        int(m[m.d_year == 1999].ss_quantity.sum())
    ]


def test_distinct(sess):
    out = sess.sql(
        "select distinct i_category from item order by i_category"
    ).collect()
    assert out.column("i_category").to_pylist() == ["Books", "Music", "Shoes"]


def test_global_agg_empty_filter(sess):
    out = sess.sql(
        "select count(*) c, sum(ss_quantity) s from store_sales where ss_quantity > 1000"
    ).collect()
    assert out.column("c").to_pylist() == [0]
    assert out.column("s").to_pylist() == [None]
