"""Estimate-vs-actual cardinality feedback (analysis/feedback.py): the
contract is "a learned cardinality can sharpen a verdict, never corrupt
one" — the two-run gate proves budgeter error is a measured, SHRINKING
number (run 1 records, run 2 consumes, a misestimated plan's verdict
flips and the median |log(est/actual)| strictly drops), and the store
units prove the persistence discipline (corruption quarantines as a
miss, a foreign key is a clean miss, two processes share one dir, dead
temps sweep, the LRU byte budget holds)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.analysis import feedback as FB
from nds_tpu.engine.session import Session

FP_A = "a" * 40
FP_B = "b" * 40


def _store(tmp_path, budget=1 << 30):
    return FB.FeedbackStore(str(tmp_path / "fb"), budget)


def _misest_table(n=200_000, seed=5):
    """A table whose `k < 10` selectivity the static model misestimates
    by orders of magnitude: 50k distinct keys means the filter keeps
    ~n/5000 rows while the conjunction floor models vastly more."""
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 50_000, n).astype(np.int64),
        "v": rng.random(n),
    })


def _gate_session(tmp_path, mode, table=None, budget_bytes=8 << 20):
    s = Session(conf={
        "engine.feedback_dir": str(tmp_path / "fb"),
        "engine.plan_feedback": mode,
        "engine.plan_budget": "warn",
        "engine.plan_budget_bytes": budget_bytes,
    })
    s.register_arrow("t", table if table is not None else _misest_table())
    return s


GATE_Q = "select k, sum(v) s from t where k < 10 group by k order by k"


# ---------------------------------------------------------------------------
# the two-run gate: record, then consume; error strictly shrinks
# ---------------------------------------------------------------------------


def test_two_run_gate_verdict_flips_and_error_shrinks(tmp_path):
    """Run 1 (record): the static model's misestimate forces a `spill`
    verdict and records the actuals. Run 2 (on): the recorded actuals
    override the estimates, the verdict flips to `direct`, the result is
    identical, and the median |log(est/actual)| is STRICTLY smaller —
    the ISSUE 18 acceptance assertion."""
    s1 = _gate_session(tmp_path, "record")
    out1 = s1.sql(GATE_Q).to_pylist()
    pb1 = s1.last_plan_budget
    assert pb1["feedback_mode"] == "record"
    assert pb1["feedback_overrides"] == 0  # record NEVER changes estimates
    assert pb1["verdict"] == "spill", pb1
    med1, _mx1, n1 = s1.feedback_store.err_stats()
    assert n1 > 0 and med1 is not None
    entries, nbytes = s1.feedback_store.usage()
    assert entries > 0 and nbytes > 0

    s2 = _gate_session(tmp_path, "on")
    out2 = s2.sql(GATE_Q).to_pylist()
    pb2 = s2.last_plan_budget
    assert out2 == out1  # feedback may replan, never change answers
    assert pb2["feedback_hits"] > 0
    assert pb2["feedback_overrides"] >= 1
    assert pb2["verdict"] == "direct", pb2  # measured rows fit the budget
    assert pb2["peak_bytes"] < pb1["peak_bytes"]
    med2, _mx2, n2 = s2.feedback_store.err_stats()
    assert n2 > 0
    assert med2 < med1, (med1, med2)  # the error is a SHRINKING number


def test_feedback_off_is_static_and_silent(tmp_path):
    """Mode `off`: no store lookups, no recording, no annotations — the
    pre-feedback static model, byte-for-byte."""
    s = _gate_session(tmp_path, "off")
    s.sql(GATE_Q).to_pylist()
    pb = s.last_plan_budget
    assert pb["feedback_mode"] == "off"
    assert pb["feedback_hits"] == 0 and pb["feedback_overrides"] == 0
    assert not os.path.isdir(str(tmp_path / "fb"))  # nothing ever written


def test_scale_tag_change_invalidates_into_clean_miss(tmp_path):
    """Re-registering the table with DIFFERENT data (row count) changes
    the scale tag, so run 2's keys miss instead of consuming stale
    cardinalities recorded against the old data."""
    s1 = _gate_session(tmp_path, "record")
    s1.sql(GATE_Q).to_pylist()
    assert s1.feedback_store.usage()[0] > 0
    # same query, same store dir, but the table is a different size
    s2 = _gate_session(tmp_path, "on", table=_misest_table(n=100_000))
    s2.sql(GATE_Q).to_pylist()
    pb = s2.last_plan_budget
    assert pb["feedback_hits"] == 0 and pb["feedback_overrides"] == 0
    assert s2.feedback_store.stats["misses"] > 0


def test_mode_resolution_and_validation(monkeypatch):
    assert FB.resolve_feedback_mode({}) == "record"  # the default
    assert FB.resolve_feedback_mode({"engine.plan_feedback": "on"}) == "on"
    monkeypatch.setenv("NDS_PLAN_FEEDBACK", "off")
    assert FB.resolve_feedback_mode({}) == "off"
    with pytest.raises(ValueError):
        FB.resolve_feedback_mode({"engine.plan_feedback": "always"})
    monkeypatch.setenv("NDS_FEEDBACK_DIR", "0")
    assert FB.resolve_feedback_dir({}) is None  # "0" disables the store
    monkeypatch.setenv("NDS_FEEDBACK_DIR", "/some/dir")
    assert FB.resolve_feedback_dir({}) == "/some/dir"
    assert FB.resolve_feedback_dir(
        {"engine.feedback_dir": "/conf/dir"}
    ) == "/conf/dir"  # conf wins over env


# ---------------------------------------------------------------------------
# store units: the aot-cache persistence discipline, re-proven here
# ---------------------------------------------------------------------------


def test_record_flush_lookup_roundtrip(tmp_path):
    st = _store(tmp_path)
    err = st.record(FP_A, rows=1000, nbytes=8000, est_rows=10)
    assert err == pytest.approx(abs(np.log(10) - np.log(1000)))
    st.record(FP_A, rows=1200, nbytes=9600, est_rows=10)
    st.record_skew(FP_A, 5.16, retries=2)
    assert st.flush() == 1
    # a FRESH store instance (new process stand-in) reads it back
    st2 = _store(tmp_path)
    rec = st2.lookup(FP_A)
    assert rec["rows"]["n"] == 2
    assert rec["rows"]["max"] == 1200 and rec["rows"]["min"] == 1000
    assert rec["skew"]["max"] == pytest.approx(5.16)
    assert rec["skew"]["retries"] == 2
    assert st2.lookup(FP_B) is None
    assert st2.stats["hits"] == 1 and st2.stats["misses"] == 1
    assert st2.hit_rate() == 0.5


def test_corrupt_entry_quarantines_as_miss(tmp_path):
    st = _store(tmp_path)
    st.record(FP_A, rows=7, est_rows=7)
    st.flush()
    [name] = [n for n in os.listdir(st.dir) if n.startswith("fb-")]
    path = os.path.join(st.dir, name)
    with open(path, "wb") as f:
        f.write(b"{torn json" + os.urandom(16))
    st2 = _store(tmp_path)
    assert st2.lookup(FP_A) is None  # a miss, never a crash
    assert st2.stats["quarantined"] == 1
    names = os.listdir(st.dir)
    assert not any(n.startswith("fb-") for n in names)
    assert any(n.startswith("quarantine-") for n in names)
    # checksum mismatch (valid JSON, tampered body) quarantines too
    st2.record(FP_A, rows=7, est_rows=7)
    st2.flush()
    with open(path, "rb") as f:
        doc = json.loads(f.read())
    doc["body"]["rows"]["max"] = 999999
    with open(path, "wb") as f:
        f.write(json.dumps(doc).encode())
    st3 = _store(tmp_path)
    assert st3.lookup(FP_A) is None
    assert st3.stats["quarantined"] == 1


def test_foreign_key_is_clean_miss_not_quarantine(tmp_path):
    """A valid document whose embedded key is another fp (filename-hash
    collision stand-in): a clean miss — real data is never destroyed."""
    st = _store(tmp_path)
    st.record(FP_A, rows=7, est_rows=7)
    st.flush()
    src = os.path.join(st.dir, FB._entry_name(FP_A))
    os.rename(src, os.path.join(st.dir, FB._entry_name(FP_B)))
    st2 = _store(tmp_path)
    assert st2.lookup(FP_B) is None
    assert st2.stats["quarantined"] == 0
    assert os.path.exists(os.path.join(st.dir, FB._entry_name(FP_B)))


def test_two_process_share_through_one_dir(tmp_path):
    """A child PROCESS records and flushes; the parent's store sees the
    merged record — the serve-fleet sharing contract, minus jax."""
    st = _store(tmp_path)
    st.record(FP_A, rows=100, est_rows=10)
    st.flush()
    script = textwrap.dedent(f"""
        from nds_tpu.analysis.feedback import FeedbackStore
        st = FeedbackStore({str(tmp_path / "fb")!r}, 1 << 30)
        st.record({FP_A!r}, rows=400, est_rows=10)
        st.record_skew({FP_A!r}, 3.5, retries=1)
        assert st.flush() == 1
        print("SHARED")
    """)
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(sys.path))
    p = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "SHARED" in p.stdout
    st2 = _store(tmp_path)
    rec = st2.lookup(FP_A)
    assert rec["rows"]["n"] == 2  # parent's + child's observations merged
    assert rec["rows"]["max"] == 400
    assert rec["skew"]["retries"] == 1
    assert not any(".tmp-" in n for n in os.listdir(st.dir))


def test_vacuum_sweeps_dead_temps_and_quarantines(tmp_path):
    st = _store(tmp_path)
    st.record(FP_A, rows=7)
    st.flush()
    dead = os.path.join(st.dir, f"{FB._entry_name(FP_B)}.tmp-999999-aa")
    with open(dead, "wb") as f:
        f.write(b"torn")
    live = os.path.join(st.dir, f"{FB._entry_name(FP_B)}.tmp-{os.getpid()}-bb")
    with open(live, "wb") as f:
        f.write(b"in-flight")
    quar = os.path.join(st.dir, f"quarantine-{FB._entry_name(FP_B)}.1")
    with open(quar, "wb") as f:
        f.write(b"bad")
    removed = st.vacuum()
    assert removed == 2  # the dead temp + the quarantine; never the live
    assert os.path.exists(live) and not os.path.exists(dead)
    assert not os.path.exists(quar)
    assert st.lookup(FP_A) is not None  # committed entries survive
    os.unlink(live)
    assert st.vacuum(drop_all=True) >= 1
    assert st.usage() == (0, 0)
    st2 = _store(tmp_path)
    assert st2.lookup(FP_A) is None


def test_lru_eviction_holds_byte_budget(tmp_path):
    st = _store(tmp_path)
    st.record(FP_A, rows=7, est_rows=7)
    assert st.flush() == 1
    _, size_a = st.usage()
    # budget admits ~one entry: the NEXT flush must evict the older one
    st.budget = int(size_a * 1.5)
    old = os.path.join(st.dir, FB._entry_name(FP_A))
    os.utime(old, (1, 1))  # backdate: FP_A is the LRU victim
    st.record(FP_B, rows=9, est_rows=9)
    assert st.flush() == 1
    assert st.stats["evictions"] >= 1
    assert not os.path.exists(old)
    names = [n for n in os.listdir(st.dir) if n.startswith("fb-")]
    assert names == [FB._entry_name(FP_B)]
    entries, total = st.usage()
    assert entries == 1 and total <= st.budget
