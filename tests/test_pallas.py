"""Pallas kernel tests (interpret mode — runs the real kernel logic on the
CPU mesh; the compiled TPU lowering needs real hardware and is exercised by
enabling engine.pallas_agg=on in a power run on-chip)."""

import jax.numpy as jnp
import numpy as np
import pytest

from nds_tpu.ops.pallas_kernels import (
    dense_build_pallas,
    segment_extreme_pallas,
    segment_sums,
    segment_sums_pallas,
)


def _oracle(vals, gid, n_groups):
    sums = np.zeros(n_groups, np.float64)
    counts = np.zeros(n_groups, np.float64)
    for v, g in zip(vals, gid):
        if g >= 0:
            sums[g] += v
            counts[g] += 1
    return sums, counts


@pytest.mark.parametrize(
    "n,n_groups",
    [
        (1000, 10),       # row padding, tiny group count
        (4096, 300),      # multiple row tiles, group padding
        (2048, 700),      # multiple group tiles
        (100, 1),         # single group
    ],
)
def test_segment_sums_pallas_matches_oracle(n, n_groups):
    rng = np.random.default_rng(n + n_groups)
    vals = rng.integers(0, 1000, n).astype(np.float32)  # exact in f32
    gid = rng.integers(-1, n_groups, n).astype(np.int32)  # -1 = dead
    sums, counts = segment_sums_pallas(
        jnp.asarray(vals), jnp.asarray(gid), n_groups, interpret=True
    )
    ref_s, ref_c = _oracle(vals, gid, n_groups)
    np.testing.assert_allclose(np.asarray(sums), ref_s, rtol=0, atol=0)
    np.testing.assert_array_equal(np.asarray(counts), ref_c)


def test_segment_sums_dispatcher_cpu_path():
    rng = np.random.default_rng(0)
    n, g = 5000, 37
    vals = rng.random(n).astype(np.float32)
    gid = rng.integers(-1, g, n).astype(np.int32)
    sums, counts = segment_sums(jnp.asarray(vals), jnp.asarray(gid), g)
    ref_s, ref_c = _oracle(vals, gid, g)
    np.testing.assert_allclose(np.asarray(sums), ref_s, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(counts), ref_c)


def test_segment_sums_all_dead_rows():
    gid = jnp.full(256, -1, jnp.int32)
    vals = jnp.ones(256, jnp.float32)
    sums, counts = segment_sums_pallas(vals, gid, 8, interpret=True)
    assert float(sums.sum()) == 0.0 and float(counts.sum()) == 0.0


def _extreme_oracle(vals, gid, n_groups, is_max):
    ext = np.full(n_groups, -np.inf if is_max else np.inf, np.float64)
    counts = np.zeros(n_groups, np.float64)
    for v, g in zip(vals, gid):
        if g >= 0:
            ext[g] = max(ext[g], v) if is_max else min(ext[g], v)
            counts[g] += 1
    return ext, counts


@pytest.mark.parametrize("is_max", [False, True])
@pytest.mark.parametrize(
    "n,n_groups",
    [
        (1000, 10),       # row padding, tiny group count
        (4096, 300),      # multiple row tiles, group padding
        (2048, 700),      # multiple group tiles
        (100, 1),         # single group
    ],
)
def test_segment_extreme_pallas_matches_oracle(n, n_groups, is_max):
    rng = np.random.default_rng(n + n_groups + is_max)
    vals = rng.integers(-500, 500, n).astype(np.float32)  # exact in f32
    gid = rng.integers(-1, n_groups, n).astype(np.int32)  # -1 = dead
    ext, counts = segment_extreme_pallas(
        jnp.asarray(vals), jnp.asarray(gid), n_groups, is_max,
        interpret=True,
    )
    ref_e, ref_c = _extreme_oracle(vals, gid, n_groups, is_max)
    np.testing.assert_array_equal(np.asarray(counts), ref_c)
    # empty groups hold the ±inf identity; callers mask via count
    live = ref_c > 0
    np.testing.assert_allclose(
        np.asarray(ext)[live], ref_e[live], rtol=0, atol=0
    )
    assert np.all(np.isinf(np.asarray(ext)[~live]))


def test_segment_extreme_all_dead_rows():
    gid = jnp.full(256, -1, jnp.int32)
    vals = jnp.ones(256, jnp.float32)
    ext, counts = segment_extreme_pallas(vals, gid, 8, True, interpret=True)
    assert float(counts.sum()) == 0.0
    assert bool(jnp.all(jnp.isinf(ext)))
    # n == 0 short-circuit
    ext0, cnt0 = segment_extreme_pallas(
        jnp.zeros(0, jnp.float32), jnp.zeros(0, jnp.int32), 4, False,
        interpret=True,
    )
    assert ext0.shape == (4,) and float(cnt0.sum()) == 0.0


@pytest.mark.parametrize(
    "n,table_cap",
    [(500, 128), (4096, 1024), (100, 2048), (0, 256)],
)
def test_dense_build_pallas_matches_jnp(n, table_cap):
    from nds_tpu.ops import kernels as K

    rng = np.random.default_rng(n + table_cap)
    rmin = 10
    # unique keys (the dense path's caller contract), some out of range
    keys = rng.permutation(6 * max(table_cap, 64))[:n].astype(np.int64) + rmin - 8
    live = rng.random(n) > 0.2
    presence_j, rows_j = K.dense_build(
        jnp.asarray(keys), jnp.asarray(live), rmin, table_cap
    )
    presence_p, rows_p = dense_build_pallas(
        jnp.asarray(keys), jnp.asarray(live), rmin, table_cap,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(presence_j),
                                  np.asarray(presence_p))
    # row indices only meaningful where present
    pj = np.asarray(presence_j)
    np.testing.assert_array_equal(
        np.asarray(rows_j)[pj], np.asarray(rows_p)[pj]
    )


def test_pallas_join_wired_through_sql():
    """engine.pallas_join=on routes the dense-join build table through the
    Pallas tile kernel (interpret mode off-TPU) with EXACT results; auto
    mode memoizes a measured verdict."""
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(9)
    n = 3000
    dim = pa.table({
        "dk": pa.array(range(200), pa.int32()),
        "dv": pa.array([int(x) for x in rng.integers(0, 50, 200)],
                       pa.int64()),
    })
    fact = pa.table({
        "fk": pa.array([int(x) for x in rng.integers(0, 200, n)],
                       pa.int32()),
        "m": pa.array([int(x) for x in rng.integers(0, 1000, n)],
                      pa.int64()),
    })
    plain = Session()
    pj_on = Session(conf={"engine.pallas_join": "on"})
    pj_auto = Session(conf={"engine.pallas_join": "auto"})
    for s in (plain, pj_on, pj_auto):
        s.register_arrow("dim", dim)
        s.register_arrow("fact", fact)
    q = ("select d.dv, sum(f.m) s from fact f, dim d where f.fk = d.dk "
         "group by d.dv order by d.dv")
    expect = plain.sql(q).collect()
    assert pj_on.sql(q).collect().equals(expect)
    assert pj_auto.sql(q).collect().equals(expect)
    dense_keys = [
        k for k in pj_auto.pallas_promotions if k[0] == "dense_build"
    ]
    assert dense_keys, "auto mode never reached the dense-join A/B"


def test_pallas_agg_wired_through_sql():
    """engine.pallas_agg=on routes float SUMs through the kernel (interpret
    mode off-TPU) and matches the exact path within float32 tolerance."""
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(4)
    n = 4096
    t = pa.table({
        "k": rng.integers(0, 20, n),
        "v": (rng.random(n) * 100).astype(np.float64),
    })
    exact = Session()
    fast = Session(conf={"engine.pallas_agg": "on"})
    for s in (exact, fast):
        s.register_arrow("t", t)
    q = "select k, sum(v) s, count(*) c from t group by k order by k"
    a = exact.sql(q).collect().to_pylist()
    b = fast.sql(q).collect().to_pylist()
    assert len(a) == len(b) == 20
    for ra, rb in zip(a, b):
        assert ra["k"] == rb["k"] and ra["c"] == rb["c"]
        assert abs(ra["s"] - rb["s"]) / max(abs(ra["s"]), 1) < 1e-5
    # min/max now route through the VPU tile kernel under the same knob
    q2 = "select k, min(v) mn, max(v) mx from t group by k order by k"
    a2 = exact.sql(q2).collect().to_pylist()
    b2 = fast.sql(q2).collect().to_pylist()
    assert len(a2) == len(b2) == 20
    for ra, rb in zip(a2, b2):
        assert ra["k"] == rb["k"]
        assert abs(ra["mn"] - rb["mn"]) / max(abs(ra["mn"]), 1) < 1e-5
        assert abs(ra["mx"] - rb["mx"]) / max(abs(ra["mx"]), 1) < 1e-5


@pytest.mark.parametrize(
    "n,dom",
    [
        (1, 4),         # single row
        (700, 1),       # constant key (all-equal: stability visible)
        (1000, 129),    # domain padding
        (4096, 2000),   # multiple row tiles, near the domain cap
    ],
)
def test_sort_perm_pallas_matches_canonical_kernel(n, dom):
    """The counting-sort permutation must be IDENTICAL to the canonical
    stable kv-sort kernel — both are stable ascending, so the whole
    permutation (tie order included) must agree element for element."""
    from nds_tpu.ops.kernels import kv_sort_perm
    from nds_tpu.ops.pallas_kernels import sort_perm_pallas

    rng = np.random.default_rng(n + dom)
    w = jnp.asarray(rng.integers(0, dom, n).astype(np.int64))
    ref = kv_sort_perm(w)
    got = sort_perm_pallas(w, dom, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_pallas_sort_wired_through_sql():
    """engine.pallas_sort=on/auto routes eligible single-word ORDER BYs
    through the counting sort with IDENTICAL rows; ineligible shapes
    (multi-word keys, wide spans) fall back to the canonical kernel."""
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(11)
    n = 3000
    t = pa.table({
        "k": pa.array([int(x) for x in rng.integers(0, 12, n)], pa.int32()),
        "v": pa.array([int(x) for x in rng.integers(-90, 90, n)],
                      pa.int64()),
        "wide": pa.array([int(x) for x in rng.integers(0, 1 << 40, n)],
                         pa.int64()),
    })
    plain = Session()
    ps_on = Session(conf={"engine.pallas_sort": "on"})
    ps_auto = Session(conf={"engine.pallas_sort": "auto"})
    for s in (plain, ps_on, ps_auto):
        s.register_arrow("t", t)
    # eligible: one small-span key (ties keep arrival order via the
    # stable contract, so full-row equality is meaningful)
    q = "select k, v from t where v > 0 order by k"
    expect = plain.sql(q).collect()
    assert ps_on.sql(q).collect().equals(expect)
    assert ps_auto.sql(q).collect().equals(expect)
    assert any(
        k[0] == "sort_perm" for k in ps_auto.pallas_promotions
    ), "auto mode never reached the sort A/B"
    # ineligible shapes still produce identical results via the fallback
    for q2 in (
        "select k, v from t order by k, v",        # multi-field word
        "select wide from t order by wide",        # span >> counting cap
    ):
        assert ps_on.sql(q2).collect().equals(plain.sql(q2).collect())
