"""Pallas kernel tests (interpret mode — runs the real kernel logic on the
CPU mesh; the compiled TPU lowering needs real hardware and is exercised by
enabling engine.pallas_agg=on in a power run on-chip)."""

import jax.numpy as jnp
import numpy as np
import pytest

from nds_tpu.ops.pallas_kernels import segment_sums, segment_sums_pallas


def _oracle(vals, gid, n_groups):
    sums = np.zeros(n_groups, np.float64)
    counts = np.zeros(n_groups, np.float64)
    for v, g in zip(vals, gid):
        if g >= 0:
            sums[g] += v
            counts[g] += 1
    return sums, counts


@pytest.mark.parametrize(
    "n,n_groups",
    [
        (1000, 10),       # row padding, tiny group count
        (4096, 300),      # multiple row tiles, group padding
        (2048, 700),      # multiple group tiles
        (100, 1),         # single group
    ],
)
def test_segment_sums_pallas_matches_oracle(n, n_groups):
    rng = np.random.default_rng(n + n_groups)
    vals = rng.integers(0, 1000, n).astype(np.float32)  # exact in f32
    gid = rng.integers(-1, n_groups, n).astype(np.int32)  # -1 = dead
    sums, counts = segment_sums_pallas(
        jnp.asarray(vals), jnp.asarray(gid), n_groups, interpret=True
    )
    ref_s, ref_c = _oracle(vals, gid, n_groups)
    np.testing.assert_allclose(np.asarray(sums), ref_s, rtol=0, atol=0)
    np.testing.assert_array_equal(np.asarray(counts), ref_c)


def test_segment_sums_dispatcher_cpu_path():
    rng = np.random.default_rng(0)
    n, g = 5000, 37
    vals = rng.random(n).astype(np.float32)
    gid = rng.integers(-1, g, n).astype(np.int32)
    sums, counts = segment_sums(jnp.asarray(vals), jnp.asarray(gid), g)
    ref_s, ref_c = _oracle(vals, gid, g)
    np.testing.assert_allclose(np.asarray(sums), ref_s, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(counts), ref_c)


def test_segment_sums_all_dead_rows():
    gid = jnp.full(256, -1, jnp.int32)
    vals = jnp.ones(256, jnp.float32)
    sums, counts = segment_sums_pallas(vals, gid, 8, interpret=True)
    assert float(sums.sum()) == 0.0 and float(counts.sum()) == 0.0


def test_pallas_agg_wired_through_sql():
    """engine.pallas_agg=on routes float SUMs through the kernel (interpret
    mode off-TPU) and matches the exact path within float32 tolerance."""
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(4)
    n = 4096
    t = pa.table({
        "k": rng.integers(0, 20, n),
        "v": (rng.random(n) * 100).astype(np.float64),
    })
    exact = Session()
    fast = Session(conf={"engine.pallas_agg": "on"})
    for s in (exact, fast):
        s.register_arrow("t", t)
    q = "select k, sum(v) s, count(*) c from t group by k order by k"
    a = exact.sql(q).collect().to_pylist()
    b = fast.sql(q).collect().to_pylist()
    assert len(a) == len(b) == 20
    for ra, rb in zip(a, b):
        assert ra["k"] == rb["k"] and ra["c"] == rb["c"]
        assert abs(ra["s"] - rb["s"]) / max(abs(ra["s"]), 1) < 1e-5
