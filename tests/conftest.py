"""Test configuration: force a virtual 8-device CPU mesh so multi-chip
sharding paths compile and execute without TPU hardware.

NOTE: the environment's sitecustomize imports jax at interpreter startup and
selects the axon TPU platform, so env vars are too late here — only
jax.config.update() works. XLA_FLAGS still applies because the CPU client
initializes lazily at the first jax.devices() call."""

import os
import sys

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    + os.environ.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", ""
    )
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

assert jax.devices()[0].platform == "cpu", jax.devices()
assert len(jax.devices()) == 8, jax.devices()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); "
        "ci/tier1-check still runs these standalone",
    )
