"""Test configuration: force a virtual 8-device CPU mesh so multi-chip
sharding paths compile and execute without TPU hardware."""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "true")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
