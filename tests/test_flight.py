"""Flight recorder + trace context: the cross-process diagnosis layer.

Covers the ISSUE-14 contracts: the ring is always on (bundle with NO
trace dir configured), an injected hang (watchdog) and an injected crash
each flush a schema-valid self-contained bundle, concurrent emitters are
never blocked by a flush (and every live thread's last events land in
the bundle), trace contexts propagate through the environment and stamp
every event, and the critical-path profiler attributes wall to named
causes (straggler device included)."""

import json
import os
import threading
import time

import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.engine.session import Session
from nds_tpu.obs import critpath as CP
from nds_tpu.obs import flight as FL
from nds_tpu.obs import metrics as M
from nds_tpu.obs import reader as R
from nds_tpu.obs.trace import (
    TraceContext, Tracer, bind, resolve_trace_context, tracer_from_conf,
)
from nds_tpu.report import BenchReport


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.delenv("NDS_TRACE_DIR", raising=False)
    monkeypatch.delenv("NDS_TRACE_CONTEXT", raising=False)
    monkeypatch.delenv("NDS_FAULT_SPEC", raising=False)
    monkeypatch.delenv("NDS_FLIGHT_RECORDER", raising=False)
    # bundles land in a per-test dir, never the repo cwd
    monkeypatch.setenv("NDS_FLIGHT_DIR", str(tmp_path / "flight"))
    faults.reset()
    FL.reset_shared()
    yield
    faults.reset()
    FL.reset_shared()
    M.reset_shared()


def _session():
    s = Session()
    s.register_arrow(
        "t", pa.table({"a": [1, 2, 3, 4], "b": [10, 20, 30, 40]})
    )
    return s


def _bundles(tmp_path):
    d = tmp_path / "flight"
    if not d.is_dir():
        return []
    return sorted(str(d / f) for f in os.listdir(d)
                  if FL.is_bundle_path(f))


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


def test_trace_context_env_roundtrip(monkeypatch):
    ctx = TraceContext.mint("power")
    child = ctx.child("stream3")
    assert child.parent == ctx.trace_id
    env = child.export({})
    monkeypatch.setenv("NDS_TRACE_CONTEXT", env["NDS_TRACE_CONTEXT"])
    adopted = resolve_trace_context("ignored")
    # a launcher-minted context is adopted VERBATIM (fold-by-trace_id
    # requires the parent to know the child's exact id)
    assert adopted.trace_id == child.trace_id
    assert adopted.parent == ctx.trace_id


def test_every_event_carries_the_trace_id(tmp_path):
    tr = tracer_from_conf({"engine.trace_dir": str(tmp_path / "tr")})
    tr.emit("plan_cache", node="Aggregate", hit=False)
    tr.emit("io_retry", path="/x", error="e", delay_s=0.0)
    tr.close()
    evs = R.read_events(tr.path)
    assert len(evs) == 3  # trace_meta + 2
    assert {e["trace_id"] for e in evs} == {tr.context.trace_id}
    assert evs[0]["kind"] == "trace_meta"
    meta = R.trace_meta_of(tr.path)
    assert meta["trace_id"] == tr.context.trace_id


def test_traced_run_is_greppable_by_one_trace_id(tmp_path, monkeypatch):
    """End-to-end: a query's whole event stream — catalog loads, op
    spans, query span — carries exactly ONE trace_id."""
    conf = {"engine.trace_dir": str(tmp_path / "tr")}
    s = Session(conf=conf)
    s.register_arrow("t", pa.table({"a": [1, 2, 2], "b": [5, 6, 7]}))
    with bind(s.tracer), faults.scope("q1"):
        s.sql("select a, sum(b) sb from t group by a").collect()
    s.tracer.close()
    evs = R.read_events(str(tmp_path / "tr"))
    assert {e["kind"] for e in evs} >= {"trace_meta", "op_span",
                                       "catalog_load"}
    assert {e["trace_id"] for e in evs} == {s.tracer.context.trace_id}


# ---------------------------------------------------------------------------
# flight recorder: bundles with NO trace dir configured
# ---------------------------------------------------------------------------


def test_watchdog_fire_flushes_bundle_without_trace_dir(tmp_path,
                                                        monkeypatch):
    s = _session()
    assert s.tracer is not None and s.tracer.path is None  # ring-only
    s.conf["engine.query_timeout"] = 0.3
    faults.install("hang:q_hang:5")

    def hang():
        faults.maybe_fire("q_hang")

    with bind(s.tracer):
        summary = BenchReport(s).report_on(hang, name="q_hang")
    assert summary["queryStatus"] == ["Failed"]
    assert summary["failureKind"] == faults.TIMEOUT
    paths = _bundles(tmp_path)
    assert len(paths) == 1
    b = FL.read_bundle(paths[0])
    assert FL.validate_bundle(b) == []
    assert b["reason"] == "watchdog"
    assert b["query"] == "q_hang"
    assert b["trace_id"] == s.tracer.context.trace_id
    assert os.path.basename(paths[0]) == (
        f"failure-bundle-{b['trace_id']}.json"
    )
    kinds = {e["kind"] for e in b["events"]}
    assert "watchdog_fire" in kinds and "fault_injected" in kinds
    assert isinstance(b["conf"], dict)
    assert b["memory"] is not None and "rss_bytes" in b["memory"]


def test_injected_crash_flushes_bundle_before_dying(tmp_path):
    s = _session()
    faults.install("crash:exec:q_crash")
    with bind(s.tracer), faults.scope("exec:q_crash"):
        with pytest.raises(faults.InjectedCrash):
            faults.maybe_fire("exec:q_crash")
    paths = _bundles(tmp_path)
    assert len(paths) == 1
    b = FL.read_bundle(paths[0])
    assert FL.validate_bundle(b) == []
    assert b["reason"] == "crash"
    # the fault_injected event itself is the ring's crash evidence
    assert any(e["kind"] == "fault_injected" for e in b["events"])


def test_ladder_exhaustion_flushes_bundle_with_history(tmp_path):
    s = _session()
    faults.install("oom:q_oom:99")  # OOMs forever: ladder exhausts

    def boom():
        faults.maybe_fire("q_oom")

    with bind(s.tracer):
        summary = BenchReport(s).report_on(boom, retry_oom=True,
                                           name="q_oom")
    assert summary["queryStatus"] == ["Failed"]
    paths = _bundles(tmp_path)
    assert len(paths) == 1
    b = FL.read_bundle(paths[0])
    assert FL.validate_bundle(b) == []
    assert b["reason"] == "ladder_exhausted"
    assert [r["rung"] for r in b["ladder"]] == [
        r["rung"] for r in summary["ladder"]
    ]
    assert len(b["ladder"]) >= 1
    # rung events in the ring carry the failed attempt's wall
    rungs = [e for e in b["events"] if e["kind"] == "ladder_rung"]
    assert rungs and all("attempt_ms" in e for e in rungs)


def test_ring_is_bounded_and_plan_notes_windowed(monkeypatch):
    monkeypatch.setenv("NDS_FLIGHT_RING_EVENTS", "32")
    FL.reset_shared()
    rec = FL.recorder()
    assert rec.capacity == 32
    tr = Tracer()  # in-memory + ring
    for i in range(100):
        tr.emit("plan_cache", node="Aggregate", hit=False)
    assert len(rec.snapshot()) == 32
    assert rec.events_recorded == 100
    for i in range(20):
        rec.note_plan(f"q{i}", f"explain {i}")
    assert rec.plan_for("q19") == "explain 19"
    assert rec.plan_for("q0") is None  # windowed out


def test_concurrent_emitters_never_block_on_flush(tmp_path, monkeypatch):
    """N threads emit through the ring while a crash-triggered flush
    snapshots it: the bundle is valid JSON, carries the failing query's
    last events AND every live thread's recent events, and emitters are
    never blocked by the flush (they keep completing against a
    deadline)."""
    monkeypatch.setenv("NDS_FLIGHT_RING_EVENTS", "8192")
    FL.reset_shared()
    s = _session()
    n_threads = 6
    per_thread = 400
    done = []

    def emitter(tid):
        tr = tracer_from_conf({})  # ring-only, own app id
        with bind(tr):
            for _ in range(per_thread):
                tr.emit(
                    "plan_cache", node=f"N{tid}", hit=False,
                    query=f"bg{tid}",
                )
        done.append(tid)

    threads = [
        threading.Thread(target=emitter, args=(t,)) for t in range(n_threads)
    ]
    # the crash (and its flush) races the emitters on another thread
    def crasher():
        time.sleep(0.001)
        faults.install("crash:exec:fg")
        with bind(s.tracer), faults.scope("exec:fg"):
            try:
                faults.maybe_fire("exec:fg")
            except faults.InjectedCrash:
                done.append(-1)

    ct = threading.Thread(target=crasher)
    for t in threads:
        t.start()
    ct.start()
    deadline = time.monotonic() + 20
    for t in threads + [ct]:
        t.join(timeout=max(deadline - time.monotonic(), 0.1))
    assert sorted(d for d in done if d >= 0) == list(range(n_threads)), (
        "emitter threads starved — the ring (or the flush) blocked them"
    )
    assert -1 in done
    paths = _bundles(tmp_path)
    assert len(paths) == 1
    with open(paths[0]) as f:
        b = json.load(f)  # schema-valid JSON despite racing emitters
    assert FL.validate_bundle(b) == []
    queries = {e.get("query") for e in b["events"]}
    # the crash evidence is in the ring...
    assert any(e["kind"] == "fault_injected" for e in b["events"])
    # ...and at the 8192-event capacity every thread's events survived;
    # run the foreground crash again AFTER all emits to also assert the
    # post-quiescence view (flush during the race may predate laggards)
    rec = FL.recorder()
    b2 = rec.bundle("on_demand")
    q2 = {e.get("query") for e in b2["events"]}
    for t in range(n_threads):
        assert f"bg{t}" in q2, f"thread {t}'s events missing from ring"


def test_debug_flight_endpoint_on_shared_listener(monkeypatch, tmp_path):
    import urllib.request

    monkeypatch.setenv("NDS_METRICS_PORT", "0")
    s = _session()
    server = M.active_server()
    assert server is not None
    with bind(s.tracer), faults.scope("q_live"):
        s.sql("select a from t").collect()

    def get(path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=5
        ) as r:
            return json.loads(r.read().decode())

    b = get("/debug/flight")
    assert b["bundle"] == 1 and b["reason"] == "on_demand"
    assert any(e["kind"] == "op_span" for e in b["events"])
    assert FL.validate_bundle(b) == []
    # ?write=1 persists it
    b2 = get("/debug/flight?write=1")
    assert b2["written"] and os.path.exists(b2["written"])
    # jaxprof status answers (start/stop exercised in the serve suite to
    # avoid a process-wide profiler session in the unit tier)
    st = get("/debug/jaxprof")
    assert st["running"] is False


def test_statusz_mesh_section(monkeypatch):
    sink = M.MetricsSink()
    sink.record({
        "ts": 1, "kind": "exchange", "app": "a", "op": "join",
        "partitions": 8, "bytes_moved": 4096, "skew": 2.5, "retries": 1,
        "per_device": [10, 10, 500, 10, 10, 10, 10, 10],
    })
    sink.record({
        "ts": 2, "kind": "heartbeat", "app": "a", "query": "q",
        "elapsed_ms": 5.0, "rss_bytes": 100,
        "dev_bytes": [1000, 2000, 9000, 1000],
    })
    sink.record({
        "ts": 3, "kind": "heartbeat", "app": "a", "query": "q",
        "elapsed_ms": 6.0, "rss_bytes": 100,
        "dev_bytes": [2000, 1000, 3000, 1000],
    })
    st = sink.status_snapshot()
    mesh = st["mesh"]
    assert mesh["last_exchange"]["skew"] == 2.5
    assert mesh["last_exchange"]["bytes_moved"] == 4096
    assert mesh["last_exchange"]["per_device"][2] == 500
    # per-device high-water max-merges across samples
    assert mesh["device_mem_hw"] == [2000, 2000, 9000, 1000]


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------


def _ev(kind, **kw):
    base = {"ts": 1, "kind": kind, "app": "a", "trace_id": "t1"}
    base.update(kw)
    return base


def test_critical_path_attributes_causes_and_names_straggler():
    events = [
        _ev("query_span", query="q1", dur_ms=1000.0, status="Completed",
            retries=1),
        _ev("op_span", query="q1", exec_id=1, seq=1, depth=1, node="Scan",
            explain="Scan t", dur_ms=200.0, rows=10, est_bytes=80),
        _ev("op_span", query="q1", exec_id=1, seq=2, depth=0,
            node="MultiJoin", explain="join", dur_ms=700.0, rows=5,
            est_bytes=40),
        _ev("exchange", query="q1", op="join", partitions=4,
            bytes_moved=1 << 20, skew=2.0, retries=0, dur_ms=300.0,
            per_device=[10, 10, 10, 400]),
        _ev("catalog_load", query="q1", table="t", columns=2, loaded=2,
            rows=10, dur_ms=50.0, cache="miss"),
        _ev("ladder_rung", query="q1", rung="recover_retry",
            failure_kind="device_oom", attempt_ms=100.0),
    ]
    cp = CP.critical_path(events)
    q = cp["queries"]["q1"]
    c = q["causes"]
    assert c["exchange-wait"] == 300.0
    assert c["catalog-load"] == 50.0
    assert c["ladder-retry"] == 100.0
    # execute = root incl (700) - exchange (300) - catalog (50)
    assert c["execute"] == 350.0
    # residual (wall 1000 - measured 800) lands in plan-host
    assert c["plan-host"] == 200.0
    assert q["attributed_frac"] == 1.0
    # chain walks root -> heaviest child
    assert [h["node"] for h in q["chain"]] == ["MultiJoin", "Scan"]
    # straggler: device 3 received 400 of 430 rows
    assert q["exchange"]["straggler_device"] == 3
    assert q["exchange"]["skew_ms"] == pytest.approx(150.0)  # 300*(1-1/2)
    assert cp["mesh"]["straggler_device"] == 3
    assert cp["mesh"]["skew_share"] == pytest.approx(0.5)


def test_critical_path_attributes_watchdog_hang():
    """A terminal watchdog failure: the hang budget is the dominant
    cause, capped only by what the OTHER measured causes leave of the
    wall (regression: an earlier cut subtracted hung time twice and left
    a fully-explained hang 'unattributed')."""
    events = [
        _ev("query_span", query="qh", dur_ms=2150.0, status="Failed",
            retries=0, failure_kind="timeout"),
        _ev("op_span", query="qh", exec_id=1, seq=1, depth=0, node="Scan",
            explain="s", dur_ms=100.0, rows=1, est_bytes=8),
        _ev("watchdog_fire", query="qh", budget_s=2.0),
    ]
    cp = CP.critical_path(events)
    q = cp["queries"]["qh"]
    assert q["causes"]["hung-wait"] == 2000.0
    assert q["causes"]["execute"] == 100.0
    assert q["attributed_frac"] >= 0.97


def test_critical_path_honest_about_missing_evidence():
    # a query with a wall but almost no spans: the residual majority must
    # NOT be laundered into plan-host
    events = [
        _ev("query_span", query="q2", dur_ms=1000.0, status="Completed",
            retries=0),
        _ev("op_span", query="q2", exec_id=1, seq=1, depth=0, node="Scan",
            explain="s", dur_ms=100.0, rows=1, est_bytes=8),
    ]
    cp = CP.critical_path(events)
    q = cp["queries"]["q2"]
    assert q["causes"]["plan-host"] == 0.0
    assert q["unattributed_ms"] == 900.0
    assert q["attributed_frac"] == pytest.approx(0.1)
    assert CP.min_attributed_frac(cp) == pytest.approx(0.1)


def test_profile_cli_critical_path_and_bundle_check(tmp_path, capsys):
    from nds_tpu.cli import profile as profile_cli

    trace = tmp_path / "tr"
    s = Session(conf={"engine.trace_dir": str(trace)})
    s.register_arrow("t", pa.table({"a": [1, 2, 2], "b": [3, 4, 5]}))
    def run():
        # the harness always scopes queries (power.run_one_query); the
        # scope is what keys op spans to the query for attribution
        with faults.scope("q_cp"):
            s.sql("select a, sum(b) sb from t group by a").collect()

    with bind(s.tracer):
        BenchReport(s).report_on(run, name="q_cp")
    s.tracer.close()
    profile_cli.main([str(trace), "--critical-path",
                      "--min_attributed", "0.9"])
    out = capsys.readouterr().out
    assert "critical path" in out and "q_cp" in out
    assert "execute" in out
    # bundle validation through the same CLI
    rec = FL.recorder()
    path = rec.flush("on_demand", trace_id="cli-test",
                     out_dir=str(tmp_path / "fl"))
    profile_cli.main([path, "--check"])
    out = capsys.readouterr().out
    assert "bundle" in out and "cli-test" in out
    # a truncated bundle fails --check with exit 2
    bad = tmp_path / "fl" / "failure-bundle-bad.json"
    bad.write_text(json.dumps({"bundle": 1, "events": "nope"}))
    with pytest.raises(SystemExit) as exc:
        profile_cli.main([str(bad), "--check"])
    assert exc.value.code == 2
