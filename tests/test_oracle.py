"""Independent-oracle differential tests: the engine vs sqlite3 over the same
generated data.

The reference validates CPU-Spark vs GPU-Spark (nds/nds_validate.py); beyond
that two-backend differential (tests/test_dist_sql.py does mesh-vs-single
chip), this file checks the engine against a wholly independent SQL
implementation on a representative query battery."""

import math
import os
import sqlite3
import subprocess
import sys

import pytest

from nds_tpu.engine.session import Session
from nds_tpu.io.csv import read_dat_dir
from nds_tpu.schema import get_schemas

DATA = "/tmp/nds_test_sf001"
TABLES = ("store_sales", "store_returns", "item", "date_dim", "store", "customer")

# Every dialect difference is lowered by _to_sqlite below (ROLLUP ->
# UNION ALL of GROUP BY prefixes, interval arithmetic, typed date
# literals, date casts) or bridged by a registered Python aggregate
# (stddev_samp), so the list of templates the independent oracle cannot
# express is empty.
_SQLITE_INCOMPATIBLE = ()


def _depth_profile(s: str):
    """Paren depth at every index of s."""
    out = []
    d = 0
    for c in s:
        if c == "(":
            d += 1
        elif c == ")":
            d -= 1
        out.append(d)
    return out


def _lower_rollup(sql: str) -> str:
    """GROUP BY ROLLUP(k1..kk) -> UNION ALL of the k+1 GROUP BY prefixes,
    with rolled-away keys replaced by NULL and grouping(ki) by 0/1 in the
    select list (sqlite has no GROUPING SETS). Keys are plain identifiers
    in every TPC-DS rollup template; windows partitioned by grouping()
    levels stay correct because each branch is exactly one level, so no
    window partition ever spans branches."""
    import re

    low = sql.lower()
    m = re.search(r"group\s+by\s+rollup\s*\(", low)
    if m is None:
        return sql
    depth = _depth_profile(low)
    gdepth = depth[m.start()]
    kstart = low.index("(", m.start())
    kend = kstart
    while not (low[kend] == ")" and depth[kend] == gdepth):
        kend += 1
    keys = [k.strip() for k in sql[kstart + 1:kend].split(",")]

    sel = None  # owning SELECT: last same-depth 'select' before the rollup
    for sm in re.finditer(r"\bselect\b", low):
        if sm.start() < m.start() and depth[sm.start()] == gdepth:
            sel = sm.start()
    assert sel is not None

    # end of the rollup SELECT block: closing paren of the enclosing
    # subquery, or a same-depth ORDER BY / LIMIT, or end of statement
    end = len(sql)
    j = kend + 1
    while j < len(sql):
        if low[j] == ")" and depth[j] < gdepth:
            end = j
            break
        if depth[j] == gdepth and re.match(r"order\s+by\b|limit\b", low[j:]):
            end = j
            break
        j += 1
    assert sql[kend + 1:end].strip() == "", (
        "unsupported clause between ROLLUP and block end",
        sql[kend + 1:end],
    )

    head = sql[sel:m.start()]  # 'select ... from ... where ...'
    hlow = head.lower()
    hdepth = _depth_profile(hlow)
    fpos = next(
        fm.start()
        for fm in re.finditer(r"\bfrom\b", hlow)
        if hdepth[fm.start()] == 0
    )
    select_list = head[len("select"):fpos]
    from_where = head[fpos:]

    branches = []
    for p in range(len(keys), -1, -1):
        sl = select_list
        for ki, k in enumerate(keys):
            g = "0" if ki < p else "1"
            sl = re.sub(
                rf"grouping\s*\(\s*{re.escape(k)}\s*\)", g, sl, flags=re.I
            )
        for k in keys[p:]:
            sl = re.sub(rf"\b{re.escape(k)}\b", "null", sl, flags=re.I)
        gb = f" group by {', '.join(keys[:p])}" if p else ""
        branches.append(f"select {sl} {from_where}{gb}")
    union = " union all ".join(branches)
    if end < len(sql) and sql[end] == ")":
        lowered = sql[:sel] + union + sql[end:]
    else:
        lowered = sql[:sel] + f"select * from ({union}) " + sql[end:]
    return _lower_rollup(lowered)  # a script part may hold several rollups


def _to_sqlite(sql: str) -> str:
    """Lower the engine dialect into sqlite-executable SQL. Dates live as
    ISO strings in the sqlite tables, so date(...) results (also ISO
    strings) compare lexicographically == chronologically."""
    import re

    sql = _lower_rollup(sql)

    # cast(expr as date) -> date(expr); sqlite CAST has numeric affinity
    # ('2000-01-01' AS DATE -> 2000), date() normalizes ISO strings
    s = re.sub(
        r"cast\s*\(\s*('[^']*'|[\w.]+)\s+as\s+date\s*\)",
        lambda m: f"date({m.group(1)})",
        sql,
        flags=re.I,
    )
    # typed literal: date '2000-01-01' -> '2000-01-01'
    s = re.sub(r"\bdate\s+'([^']+)'", r"'\1'", s, flags=re.I)
    # cast(x as decimal(p,s)) -> cast(x as real): sqlite's decimal cast
    # keeps INTEGER affinity, so int/int ratios would integer-divide
    s = re.sub(
        r"cast\s*\(\s*([^()]+?)\s+as\s+decimal\s*\(\s*\d+\s*,\s*\d+\s*\)\s*\)",
        r"cast(\1 as real)",
        s,
        flags=re.I,
    )

    # expr +/- interval N days -> date(expr, '+N days')
    def interval(m):
        expr, op, n = m.group(1), m.group(2), m.group(3)
        return f"date({expr}, '{op}{n} days')"

    operand = r"(date\([^()]*(?:\([^()]*\))?[^()]*\)|'[^']*'|[\w.]+)"
    s = re.sub(
        operand + r"\s*([+-])\s*interval\s+(\d+)\s+days?",
        interval,
        s,
        flags=re.I,
    )
    return s


class _StddevSamp:
    """Sample standard deviation for sqlite (sqlite ships no stddev)."""

    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        mean = sum(self.vals) / n
        return math.sqrt(sum((x - mean) ** 2 for x in self.vals) / (n - 1))


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


def _load_engines(data_dir, tables):
    sess = Session(use_decimal=False)
    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("stddev_samp", 1, _StddevSamp)
    for t in tables:
        schema = get_schemas(use_decimal=False)[t]
        path = os.path.join(data_dir, t)
        if not os.path.isdir(path):
            continue
        sess.register_csv_dir(t, path, schema)
        arrow = read_dat_dir(path, schema, use_decimal=False)
        cols = ", ".join(f'"{f.name}"' for f in schema)
        conn.execute(
            f"create table {t} ({', '.join(f.name for f in schema)})"
        )
        import datetime

        def plain(v):
            return v.isoformat() if isinstance(v, datetime.date) else v

        rows = [
            tuple(plain(v) for v in row)
            for row in zip(*(arrow.column(f.name).to_pylist() for f in schema))
        ]
        ph = ", ".join("?" for _ in schema)
        conn.executemany(f"insert into {t} ({cols}) values ({ph})", rows)
    return sess, conn


@pytest.fixture(scope="module")
def engines(data_dir):
    """(engine session, sqlite connection) over identical float-typed data."""
    return _load_engines(data_dir, TABLES)


# Queries valid in BOTH dialects (dates as ISO strings: sqlite compares them
# lexicographically, the engine coerces string to date).
QUERIES = [
    # star join + group agg + order
    """select d_year, i_brand_id, sum(ss_ext_sales_price) s
       from date_dim, store_sales, item
       where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
         and i_manager_id = 10 and d_moy = 11
       group by d_year, i_brand_id
       order by d_year, s desc, i_brand_id""",
    # global aggregates
    """select count(*) c, sum(ss_quantity) sq, avg(ss_ext_sales_price) av,
              min(ss_sales_price) mn, max(ss_sales_price) mx
       from store_sales""",
    # IN subquery (semi)
    """select count(*) c from store_sales
       where ss_item_sk in (select i_item_sk from item where i_manager_id < 20)""",
    # NOT IN (anti with 3VL on non-null key set)
    """select count(*) c from store_sales
       where ss_store_sk not in (select s_store_sk from store where s_state = 'TN')""",
    # scalar subquery comparison
    """select count(*) c from store_sales
       where ss_ext_sales_price > (select avg(ss_ext_sales_price) from store_sales)""",
    # left join + group + having + order
    """select s_state, count(*) c from store_sales
       left join store on ss_store_sk = s_store_sk
       group by s_state having count(*) > 100 order by s_state""",
    # distinct + order + limit
    """select distinct ss_quantity from store_sales
       where ss_quantity is not null order by ss_quantity limit 10""",
    # correlated EXISTS
    """select count(*) c from item i
       where exists (select 1 from store_sales where ss_item_sk = i.i_item_sk
                     and ss_quantity > 90)""",
    # union all + outer aggregate
    """select count(*) c from (
         select ss_ticket_number x from store_sales
         union all
         select sr_ticket_number x from store_returns) t""",
    # window function over partition
    """select d_year, d_moy, rank() over (partition by d_year order by d_moy) r
       from (select distinct d_year, d_moy from date_dim
             where d_year = 2000 and d_moy <= 6) t
       order by d_year, d_moy""",
    # case + arithmetic
    """select sum(case when ss_quantity > 50 then 1 else 0 end) hi,
              sum(case when ss_quantity <= 50 then 1 else 0 end) lo
       from store_sales""",
    # date range on string-coerced dates
    """select count(*) c from date_dim
       where d_date between '1999-01-01' and '1999-12-31'""",
    # NOT IN under OR (mark-join lowering; binder regression)
    """select count(*) c from store_sales
       where ss_store_sk = 1
          or ss_item_sk not in (select i_item_sk from item
                                where i_manager_id < 5)""",
    # correlated EXISTS with a non-equi residual (q16/q94 shape)
    """select count(*) c from store_sales s1
       where exists (select 1 from store_sales s2
                     where s1.ss_ticket_number = s2.ss_ticket_number
                       and s1.ss_item_sk <> s2.ss_item_sk)""",
]


def _rows_close(a, b, eps=1e-6):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if x is None and y is None:
                continue
            if x is None or y is None:
                return False
            if isinstance(x, float) or isinstance(y, float):
                fx, fy = float(x), float(y)
                if math.isnan(fx) and math.isnan(fy):
                    continue
                if not math.isclose(fx, fy, rel_tol=1e-6, abs_tol=1e-9):
                    return False
            elif x != y:
                return False
    return True


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_engine_matches_sqlite(engines, qi):
    sess, conn = engines
    q = QUERIES[qi]
    ours = [list(r.values()) for r in sess.sql(q).to_pylist()]
    oracle = [list(r) for r in conn.execute(q).fetchall()]
    if "order by" not in q.lower():
        ours.sort(key=str)
        oracle.sort(key=str)
    assert _rows_close(ours, oracle), (
        f"query {qi} mismatch:\nengine: {ours[:5]}\nsqlite: {oracle[:5]}"
    )


# ---------------------------------------------------------------------------
# The actual instantiated templates vs sqlite (VERDICT r2 item #5): every
# template whose dialect sqlite can express runs on both engines at SF0.01.
# ---------------------------------------------------------------------------


def _template_sql(qnum):
    import numpy as np

    from nds_tpu.datagen import query_streams as QS

    rng = np.random.default_rng(1000 + qnum)
    return QS.instantiate(qnum, rng, 0.01)


# sqlite divides int/int as integer (1/2 = 0); the engine follows the
# reference's Spark dialect (int/int -> double). These templates divide
# integer columns, so the two dialects legitimately disagree:
_INT_DIVISION_TEMPLATES = {34, 78, 83}

# templates whose sqlite plans are un-indexed nested loops over the 1.9M-row
# demographics tables (q13-class OR-joins): they hit the 60s abort deadline
# on every run, so skip upfront instead of burning 2x60s per suite run to
# rediscover it. The deadline below still guards any template not listed.
_SQLITE_NESTED_LOOP_TEMPLATES = {13, 48}


def _sqlite_compatible():
    """(template, part_index) pairs runnable on sqlite. Two-part templates
    (14/23/24/39) contribute each standalone part separately."""
    from nds_tpu.datagen import query_streams as QS

    out = []
    for q in QS.available_templates():
        if q in _INT_DIVISION_TEMPLATES:
            continue
        sql = _template_sql(q).lower()
        if any(tok in sql for tok in _SQLITE_INCOMPATIBLE):
            continue
        parts = [p for p in sql.split(";") if "select" in p]
        for pi in range(len(parts)):
            out.append((q, pi))
    return out


@pytest.fixture(scope="module")
def all_engines(data_dir):
    from nds_tpu.schema import get_schemas as _gs

    return _load_engines(data_dir, sorted(_gs(use_decimal=False)))


@pytest.mark.parametrize("qnum,part", _sqlite_compatible())
def test_template_matches_sqlite(all_engines, qnum, part):
    import datetime
    import time as _time

    if qnum in _SQLITE_NESTED_LOOP_TEMPLATES:
        pytest.skip(
            f"sqlite nested-loop plan for query{qnum} exceeds the 60s "
            f"deadline on every run (see _SQLITE_NESTED_LOOP_TEMPLATES)"
        )
    sess, conn = all_engines
    whole = _template_sql(qnum)
    parts = [p for p in whole.split(";") if "select" in p.lower()]
    sql = parts[part]
    # abort sqlite after 60s: its un-indexed nested-loop plans (q13-class
    # OR-joins against the 1.9M-row demographics tables) would run for hours
    deadline = _time.monotonic() + 60

    def _abort_if_late():
        return 1 if _time.monotonic() > deadline else 0

    conn.set_progress_handler(_abort_if_late, 100_000)
    try:
        oracle = [list(r) for r in conn.execute(_to_sqlite(sql)).fetchall()]
    except sqlite3.OperationalError as e:
        pytest.skip(f"sqlite can't run query{qnum} part {part}: {e}")
    finally:
        conn.set_progress_handler(None, 0)

    def plain(v):
        return v.isoformat() if isinstance(v, datetime.date) else v

    ours = [
        [plain(v) for v in r.values()] for r in sess.sql(sql).to_pylist()
    ]
    if "order by" not in sql.lower():
        ours.sort(key=str)
        oracle.sort(key=str)
    assert _rows_close(ours, oracle, eps=1e-4), (
        f"query{qnum} mismatch ({len(ours)} vs {len(oracle)} rows):\n"
        f"engine: {ours[:3]}\nsqlite: {oracle[:3]}"
    )
