"""Static-analysis subsystem: plan-IR verifier + engine lint.

The verifier must (a) pass every legitimately bound + rewritten plan —
queries run identically with `engine.verify_plans=all` — and (b) catch each
seeded invariant violation: unresolved/duplicate schema, a Pipeline
wrapping a shared or still-attached node (the deliberately-broken-rewrite
acceptance case), out-of-scope join keys, SetOp arity drift, a top-k sort
key missing from the Sort input, a blocked_union annotation on a
non-decomposable aggregate, and a LEFT->INNER promotion whose conjunct is
not null-rejecting. PlanVerifyError classifies as a `planner` failure and
the report ladder fails fast (no retry).

The lint must fire on a seeded violation of every rule, honor the
`# nds-lint: disable=<rule>` pragma, and run CLEAN over the real tree —
the same gate ci/tier1-check enforces. The golden-sync test keeps every
emitted `kind` literal and obs/trace.py:EVENT_SCHEMA equal, so schema
drift breaks tier-1 instead of the tolerant reader.
"""

import ast
import dataclasses
import importlib.util
import json
import os
import textwrap

import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.analysis import lint as L
from nds_tpu.analysis.verifier import (
    PlanVerifier,
    PlanVerifyError,
    resolve_level,
    verify_plan,
)
from nds_tpu.engine import expr as E
from nds_tpu.engine import plan as P
from nds_tpu.engine.binder import Binder
from nds_tpu.engine.session import Session
from nds_tpu.engine.sql.parser import parse_sql
from nds_tpu.obs.trace import DEPRECATED_EVENT_KINDS, EVENT_SCHEMA, Tracer
from nds_tpu.report import BenchReport

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _session(conf=None):
    s = Session(conf=conf)
    s.register_arrow(
        "t1",
        pa.table(
            {
                "k": pa.array([1, 2, 2, None, 5], pa.int32()),
                "v": pa.array([10, 20, 30, 40, 50], pa.int32()),
                "s": pa.array(["a", "b", "b", "c", "a"]),
            }
        ),
    )
    s.register_arrow(
        "t2",
        pa.table(
            {
                "k": pa.array([2, 2, 5, 7], pa.int32()),
                "w": pa.array([1, 2, 3, None], pa.int32()),
            }
        ),
    )
    return s


def _find_node(plan, typ):
    seen = set()

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if id(v) in seen:
                return None
            seen.add(id(v))
            if isinstance(v, typ):
                return v
            for f in dataclasses.fields(v):
                r = visit(getattr(v, f.name))
                if r is not None:
                    return r
        elif isinstance(v, (list, tuple)):
            for x in v:
                r = visit(x)
                if r is not None:
                    return r
        return None

    return visit(plan)


# ---------------------------------------------------------------------------
# verifier: clean plans stay clean (and still execute)
# ---------------------------------------------------------------------------


def test_verified_queries_execute_identically():
    plain = _session()
    checked = _session(conf={"engine.verify_plans": "all"})
    queries = [
        "select k, sum(v) sv from t1 group by k order by k",
        "select t1.k, t1.v, t2.w from t1, t2 where t1.k = t2.k order by 1, 2, 3",
        # LEFT->INNER promotion shape (records promotion evidence)
        "select count(*) c from t1 left join t2 on t1.k = t2.k where t2.w > 0",
        # blocked-union annotation shape
        "select k, sum(v) sv from (select k, v from t1 union all "
        "select k, v from t1) u group by k order by k",
        # top-k over sort
        "select k, v from t1 order by v desc limit 2",
        "select s, rank() over (partition by s order by v) r from t1 "
        "order by s, r",
    ]
    for q in queries:
        assert checked.sql(q).to_pylist() == plain.sql(q).to_pylist(), q


def test_resolve_level_validates():
    assert resolve_level(None) == "off"
    assert resolve_level({"engine.verify_plans": "final"}) == "final"
    assert resolve_level({"engine.verify_plans": "ALL"}) == "all"
    with pytest.raises(ValueError):
        resolve_level({"engine.verify_plans": "sometimes"})


def test_verify_level_env_knob(monkeypatch):
    monkeypatch.setenv("NDS_VERIFY_PLANS", "final")
    assert resolve_level({}) == "final"
    monkeypatch.delenv("NDS_VERIFY_PLANS")


# ---------------------------------------------------------------------------
# verifier: seeded violations
# ---------------------------------------------------------------------------


def test_unresolved_column_flagged():
    s = _session()
    plan = P.Project([(E.Col("zzz"), "x")], P.Scan("t1", "t1"))
    v = PlanVerifier(s.catalog).verify(plan)
    assert len(v) == 1 and "unresolved column 'zzz'" in v[0]


def test_duplicate_output_names_flagged():
    s = _session()
    plan = P.Project(
        [(E.Col("t1.k"), "x"), (E.Col("t1.v"), "x")], P.Scan("t1", "t1")
    )
    v = PlanVerifier(s.catalog).verify(plan)
    assert v and "duplicate output column 'x'" in v[0]


def test_pipeline_wrapping_shared_node_flagged():
    # the deliberately-broken-rewrite acceptance case: one detached stage
    # object referenced by two Pipelines is a shared wrapper absorbed by
    # mistake (it defeats the executor's by-identity result reuse)
    s = _session()
    stage = P.Filter(E.BinOp(">", E.Col("t1.k"), E.Lit(1)), None)
    p1 = P.Pipeline(stages=[stage], child=P.Scan("t1", "t1"))
    p2 = P.Pipeline(stages=[stage], child=P.Scan("t1", "u1"))
    root = P.SetOp(
        "union_all",
        P.Project([(E.Col("t1.k"), "a")], p1),
        P.Project([(E.Col("u1.k"), "a")], p2),
    )
    v = PlanVerifier(s.catalog).verify(root)
    assert any("shared node" in x for x in v)
    with pytest.raises(PlanVerifyError, match="shared node"):
        verify_plan(root, s.catalog, stage="mark_pipelines")


def test_pipeline_attached_stage_child_flagged():
    s = _session()
    scan = P.Scan("t1", "t1")
    stage = P.Filter(E.BinOp(">", E.Col("t1.k"), E.Lit(1)), scan)
    root = P.Pipeline(stages=[stage], child=scan)
    v = PlanVerifier(s.catalog).verify(root)
    assert any("attached child" in x for x in v)


def test_pipeline_unfusible_stage_expr_flagged():
    s = _session()
    sub = E.ScalarSubquery(
        plan=P.Aggregate([], [(E.Agg("count", None), "_n")], P.Scan("t2", "t2")),
        out_name="_n",
    )
    stage = P.Filter(E.BinOp(">", E.Col("t1.k"), sub), None)
    root = P.Pipeline(stages=[stage], child=P.Scan("t1", "t1"))
    v = PlanVerifier(s.catalog).verify(root)
    assert any("not fusible" in x for x in v)


def test_join_keys_outside_child_flagged():
    s = _session()
    j = P.Join(
        "inner", P.Scan("t1", "t1"), P.Scan("t2", "t2"),
        [E.Col("t1.k")], [E.Col("t1.k")],  # right key binds to LEFT child
    )
    v = PlanVerifier(s.catalog).verify(j)
    assert any("right join key" in x and "t1.k" in x for x in v)


def test_multijoin_edge_scope_flagged():
    s = _session()
    mj = P.MultiJoin(
        relations=[P.Scan("t1", "t1"), P.Scan("t2", "t2")],
        edges=[(0, 1, E.Col("t2.k"), E.Col("t2.k"))],  # left expr: wrong rel
    )
    v = PlanVerifier(s.catalog).verify(mj)
    assert any("must bind to relation 0" in x for x in v)


def test_setop_arity_and_alignment_flagged():
    s = _session()
    a = P.Project([(E.Col("t1.k"), "a")], P.Scan("t1", "t1"))
    b = P.Project(
        [(E.Col("t2.k"), "a"), (E.Col("t2.w"), "b")], P.Scan("t2", "t2")
    )
    v = PlanVerifier(s.catalog).verify(P.SetOp("union_all", a, b))
    assert any("1 vs 2 columns" in x for x in v)
    c = P.Project([(E.Col("t2.k"), "renamed")], P.Scan("t2", "t2"))
    v2 = PlanVerifier(s.catalog).verify(P.SetOp("union_all", a, c))
    assert any("misaligned column names" in x for x in v2)


def test_limit_over_sort_missing_key_flagged():
    s = _session()
    root = P.Limit(3, P.Sort([(E.Col("nope"), True, None)], P.Scan("t1", "t1")))
    v = PlanVerifier(s.catalog).verify(root)
    assert any("unresolved column 'nope'" in x for x in v)


def test_shared_sort_marked_topk_safe_flagged():
    # cross-pass invariant: fuse.mark_pipelines may only set _topk_safe on
    # a single-consumer Sort — a shared Sort gathered top-k for one parent
    # would truncate the other parent's input
    s = _session()
    sort = P.Sort([(E.Col("t1.v"), True, None)], P.Scan("t1", "t1"))
    sort._topk_safe = True
    root = P.SetOp(
        "union_all",
        P.Project([(E.Col("t1.k"), "a")], P.Limit(2, sort)),
        P.Project([(E.Col("t1.k"), "a")], sort),
    )
    v = PlanVerifier(s.catalog).verify(root)
    assert any("multiple consumers" in x for x in v)
    # single-consumer _topk_safe is clean
    ok = P.Limit(2, P.Sort([(E.Col("t1.v"), True, None)], P.Scan("t1", "t1")))
    ok.child._topk_safe = True
    assert PlanVerifier(s.catalog).verify(ok) == []


def test_pipeline_agg_tail_clean_and_seeded_violations():
    """The PR-6 invariant class 1: an aggregate-tail Pipeline must carry a
    detached, unshared, plain-shaped, fully decomposable aggregate."""
    s = _session(conf={"engine.verify_plans": "all"})
    # the organic fused plan verifies clean at `all` strictness (executes
    # through _finish_plan's per-pass verification) and executes correctly
    r = s.sql("select k, sum(v) sv from t1 where v > 10 group by k "
              "order by k")
    assert r.collect() is not None
    pipes = []

    def walk(n):
        if isinstance(n, P.Pipeline) and n.agg is not None:
            pipes.append(n)
        for c in n.children():
            if c is not None:
                walk(c)

    walk(r.plan)
    assert pipes, "aggregate did not fuse into a Pipeline tail"
    pipe = pipes[0]
    # seed 1: non-decomposable aggregate set in the tail
    good_aggs = pipe.agg.aggs
    pipe.agg.aggs = [(E.Agg("sum", E.Col("t1.v"), distinct=True), "sv")]
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("non-decomposable" in x and "pipeline-agg" in x for x in v)
    pipe.agg.aggs = good_aggs
    # seed 2: non-plain shape (grouping sets / blocked_union on the tail)
    pipe.agg.grouping_sets = [[0], []]
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("plain-shaped" in x for x in v)
    pipe.agg.grouping_sets = None
    pipe.agg.blocked_union = True
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("plain-shaped" in x for x in v)
    pipe.agg.blocked_union = False
    # seed 3: the tail still attached to a child subtree
    pipe.agg.child = P.Scan("t1", "t1")
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("attached child" in x for x in v)
    pipe.agg.child = None
    # seed 4: the aggregate tail shared with another plan site
    shared_root = P.SetOp(
        "union_all",
        P.Project([(E.Col("k"), "a")], pipe),
        P.Project([(E.Col("sv"), "a")],
                  P.Pipeline(stages=[], child=P.Scan("t1", "u1"),
                             agg=pipe.agg)),
    )
    v = PlanVerifier(s.catalog).verify(shared_root)
    assert any("referenced elsewhere" in x for x in v)
    # restored plan verifies clean again
    assert PlanVerifier(s.catalog).verify(r.plan) == []


def test_donate_ok_seeded_violations():
    """The PR-6 invariant class 2: donate_ok never where another consumer
    or a cross-statement cache can still observe the child's buffers."""
    s = _session()
    # multi-consumer child: one subtree feeding two donating pipelines
    scan = P.Scan("t1", "t1")
    shared = P.Filter(E.BinOp(">", E.Col("t1.k"), E.Lit(0)), scan)
    p1 = P.Pipeline(
        stages=[P.Filter(E.BinOp(">", E.Col("t1.v"), E.Lit(1)), None)],
        child=shared, donate_ok=True,
    )
    p2 = P.Pipeline(
        stages=[P.Filter(E.BinOp(">", E.Col("t1.v"), E.Lit(2)), None)],
        child=shared, donate_ok=False,
    )
    root = P.SetOp(
        "union_all",
        P.Project([(E.Col("t1.k"), "a")], p1),
        P.Project([(E.Col("t1.k"), "a")], p2),
    )
    v = PlanVerifier(s.catalog).verify(root)
    assert any("donate" in x and "multiple consumers" in x for x in v)
    # cache-retained child: an Aggregate's result lives in the session
    # plan cache beyond this call — donating its buffers corrupts it
    agg = P.Aggregate(
        [(E.Col("t1.k"), "k")], [(E.Agg("sum", E.Col("t1.v")), "sv")],
        P.Scan("t1", "t1"),
    )
    bad = P.Pipeline(
        stages=[P.Filter(E.BinOp(">", E.Col("sv"), E.Lit(1)), None)],
        child=agg, donate_ok=True,
    )
    v = PlanVerifier(s.catalog).verify(bad)
    assert any("donate" in x and "retains" in x for x in v)
    # the same shape without the flag is clean
    bad.donate_ok = False
    assert PlanVerifier(s.catalog).verify(bad) == []


def test_lint_undocumented_conf_knob():
    # a knob no doc mentions flags; every documented knob passes
    bad = 'x = conf.get("engine.definitely_not_a_real_knob")\n'
    fs = L.lint_source(bad, "engine/session.py")
    assert [f.rule for f in fs] == ["undocumented-conf-knob"]
    good = 'x = conf.get("engine.fuse", "on")\n'
    assert L.lint_source(good, "engine/session.py") == []
    # subscript writes count as reads of the knob too
    bad2 = 'conf["engine.not_documented_either"] = 1\n'
    fs = L.lint_source(bad2, "power.py")
    assert [f.rule for f in fs] == ["undocumented-conf-knob"]
    # pragma silences with justification
    ok = ('# internal probe knob, never user-facing\n'
          '# nds-lint: disable=undocumented-conf-knob\n'
          'x = conf.get("engine.secret_internal_probe")\n')
    assert L.lint_source(ok, "engine/session.py") == []


def test_unimplemented_scalar_function_flagged():
    # the verifier's function table must not drift AHEAD of the evaluator:
    # ifnull/nvl are not implemented by Evaluator._eval_func, so a plan
    # using them must fail verification, not crash at execution
    s = _session()
    plan = P.Project(
        [(E.Func("ifnull", (E.Col("t1.k"), E.Lit(0))), "x")],
        P.Scan("t1", "t1"),
    )
    v = PlanVerifier(s.catalog).verify(plan)
    assert any("unknown scalar function 'ifnull'" in x for x in v)


def test_blocked_union_nondecomposable_flagged_and_not_annotated():
    s = _session()
    # regression (satellite fix): the annotation pass itself now applies
    # plan.aggs_decomposable — a distinct aggregate over a union shape is
    # NOT marked
    r = s.sql(
        "select k, count(distinct v) dv from (select k, v from t1 "
        "union all select k, v from t1) u group by k"
    )
    agg = _find_node(r.plan, P.Aggregate)
    assert agg is not None and not agg.blocked_union
    # verifier half: a hand-forced annotation on that aggregate is flagged
    agg.blocked_union = True
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("non-decomposable aggregate" in x for x in v)
    # and the decomposable shape still annotates + verifies clean
    r2 = s.sql(
        "select k, sum(v) sv from (select k, v from t1 "
        "union all select k, v from t1) u group by k"
    )
    agg2 = _find_node(r2.plan, P.Aggregate)
    assert agg2 is not None and agg2.blocked_union
    assert PlanVerifier(s.catalog).verify(r2.plan) == []


def test_blocked_union_on_non_union_input_flagged():
    # fuse_agg off: keep the raw Aggregate in the plan (fusion would absorb
    # it into a Pipeline tail, where the plain-shape check fires instead)
    s = _session(conf={"engine.fuse_agg": "off"})
    r = s.sql("select k, sum(v) sv from t1 group by k")
    agg = _find_node(r.plan, P.Aggregate)
    agg.blocked_union = True  # no union_all anywhere below
    v = PlanVerifier(s.catalog).verify(r.plan)
    assert any("not a union_all chain" in x for x in v)


def test_left_inner_promotion_cross_check():
    s = _session()
    stmt = parse_sql(
        "select count(*) c from t1 left join t2 on t1.k = t2.k "
        "where t2.w > 0"
    )
    binder = Binder(s.catalog)
    plan = binder.bind(stmt)
    # the binder recorded evidence, and the evidence verifies clean
    assert binder.promotions and binder.promotions[0]["refs"]
    verify_plan(plan, s.catalog, promotions=binder.promotions)
    # a promotion claimed from a null-TOLERANT conjunct must be flagged
    bad = [{"conjunct": E.UnaryOp("isnull", E.Col("w")), "refs": ["t2.w"]},
           {"conjunct": E.BinOp(">", E.Col("w"), E.Lit(0)), "refs": []}]
    v = PlanVerifier(s.catalog).verify(plan, promotions=bad)
    assert any("NOT null-rejecting" in x for x in v)
    assert any("without any reference" in x for x in v)


def test_plan_verify_events_emitted():
    s = _session(conf={"engine.verify_plans": "all"})
    s.tracer = Tracer()  # in-memory
    s.sql("select k from t1 where v > 10")
    evs = [e for e in s.tracer.events if e["kind"] == "plan_verify"]
    stages = [e["stage"] for e in evs]
    assert stages == [
        "bind", "prune_columns", "mark_blocked_union_aggs",
        "mark_pipelines", "plan_budget",
    ]
    assert all(e["ok"] for e in evs)
    assert "plan_verify" in EVENT_SCHEMA
    # failing verification still emits its event (ok=False) before raising
    t = Tracer()
    bad = P.Project([(E.Col("zzz"), "x")], P.Scan("t1", "t1"))
    with pytest.raises(PlanVerifyError):
        verify_plan(bad, s.catalog, stage="bind", tracer=t)
    ev = [e for e in t.events if e["kind"] == "plan_verify"][0]
    assert ev["ok"] is False and ev["violations"] == 1
    assert "unresolved column" in ev["first"]


def test_planverifyerror_is_planner_and_fails_fast():
    err = PlanVerifyError("bind", ["schema: unresolved column 'x'"])
    assert faults.classify(err) == faults.PLANNER
    # the ladder must NOT retry a deterministic verifier hit even with
    # retry_oom granted
    s = _session()
    calls = []

    def boom():
        calls.append(1)
        raise err

    rep = BenchReport(s)
    summary = rep.report_on(boom, retry_oom=True, name="q")
    assert summary["queryStatus"] == ["Failed"]
    assert summary["failureKind"] == faults.PLANNER
    assert summary["retries"] == 0
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# lint rules: seeded violations + pragma mechanism
# ---------------------------------------------------------------------------


def test_lint_mutable_module_global():
    src = "CACHE = {}\n"
    assert [f.rule for f in L.lint_source(src, "engine/foo.py")] == [
        "mutable-module-global"
    ]
    assert L.lint_source(src, "io/fs.py") == []  # out of scope
    ok = "CACHE = {}  # nds-lint: disable=mutable-module-global\n"
    assert L.lint_source(ok, "engine/foo.py") == []
    g = "def f():\n    global STATE\n    STATE = 1\n"
    assert [f.rule for f in L.lint_source(g, "ops/k.py")] == [
        "mutable-module-global"
    ]


def test_lint_perf_counter():
    src = "import time\nt0 = time.time()\nd = time.time() - t0\n"
    fs = L.lint_source(src, "power.py")
    assert [f.rule for f in fs] == ["perf-counter"] and fs[0].line == 3
    # epoch stamps without subtraction are fine
    assert L.lint_source(
        "import time\nts = int(time.time() * 1000)\n", "power.py"
    ) == []
    # pragma on the line above disables
    ok = (
        "import time\nt0 = time.time()\n"
        "# nds-lint: disable=perf-counter\nd = time.time() - t0\n"
    )
    assert L.lint_source(ok, "power.py") == []


def test_lint_atomic_write():
    src = "f = open(p, 'w')\n"
    assert [f.rule for f in L.lint_source(src, "report.py")] == [
        "atomic-write"
    ]
    assert L.lint_source(src, "engine/exec.py") == []  # harness scope only
    assert L.lint_source("f = open(p)\n", "report.py") == []  # read mode


def test_lint_host_sync_in_fuse():
    src = textwrap.dedent(
        """
        class FusedPipeline:
            def _run_full(self, *flat):
                n = int(flat[0].shape[0])  # static shape: fine
                return np.asarray(flat[1])
        """
    )
    fs = L.lint_source(src, "engine/fuse.py")
    assert [f.rule for f in fs] == ["host-sync-in-fuse"]
    assert "np.asarray" in fs[0].message
    # same code outside the traced bodies is not flagged
    assert L.lint_source(src.replace("_run_full", "call"),
                         "engine/fuse.py") == []


def test_lint_local_import():
    src = "def f():\n    import os\n    return os\n"
    assert [f.rule for f in L.lint_source(src, "engine/exec.py")] == [
        "local-import"
    ]
    assert L.lint_source(src, "power.py") == []  # hot modules only
    # an import inside a NESTED function reports exactly once (ast.walk
    # reaches it from both the outer and inner FunctionDef)
    nested = "def outer():\n    def inner():\n        import os\n"
    assert len(L.lint_source(nested, "engine/exec.py")) == 1


def test_lint_trace_event_schema():
    bad_kind = "tracer.emit('no_such_kind', a=1)\n"
    fs = L.lint_source(bad_kind, "engine/exec.py")
    assert [f.rule for f in fs] == ["trace-event-schema"]
    missing = "tracer.emit('query_span', query=q)\n"
    fs = L.lint_source(missing, "report.py")
    assert fs and "dur_ms" in fs[0].message
    # **fields forwards are only checkable at runtime (profile --check)
    assert L.lint_source("tracer.emit('query_span', **ev)\n", "report.py") == []
    good = (
        "tracer.emit('plan_cache', node=n, hit=True)\n"
    )
    assert L.lint_source(good, "engine/exec.py") == []


def test_lint_metric_names_derive_from_event_kinds():
    """trace-event-schema's obs/metrics.py half: the live-metric taxonomy
    must anchor to EVENT_SCHEMA (ISSUE 8 satellite)."""
    # family mapped to a kind that is not in EVENT_SCHEMA
    bad_kind = 'METRIC_KINDS = {"nds_bogus_total": "bogus"}\n'
    fs = L.lint_source(bad_kind, "obs/metrics.py")
    assert [f.rule for f in fs] == ["trace-event-schema"]
    assert "not an obs/trace.py:EVENT_SCHEMA kind" in fs[0].message
    # family whose name does not embed its source kind
    free = 'METRIC_KINDS = {"nds_free_total": "query_span"}\n'
    fs = L.lint_source(free, "obs/metrics.py")
    assert fs and "does not embed its source event kind" in fs[0].message
    # a registry mutator called with an unregistered literal name
    unreg = (
        'METRIC_KINDS = {"nds_query_span_total": "query_span"}\n'
        'reg.inc("nds_query_span_total", status=s)\n'
        'reg.inc("nds_rogue_total")\n'
    )
    fs = L.lint_source(unreg, "obs/metrics.py")
    assert len(fs) == 1 and "nds_rogue_total" in fs[0].message
    # the same source outside obs/metrics.py is not metric-checked
    assert L.lint_source(bad_kind, "obs/reader.py") == []
    # clean: derived names, registered mutator calls
    clean = (
        'METRIC_KINDS = {"nds_exec_cache_total": "exec_cache"}\n'
        'reg.inc("nds_exec_cache_total", result="hit")\n'
    )
    assert L.lint_source(clean, "obs/metrics.py") == []


def test_metric_kinds_sync_with_event_schema():
    """Golden sync for the live-metric taxonomy: the shipped METRIC_KINDS
    maps every family to a live EVENT_SCHEMA kind and embeds the kind in
    the family name — and the AST view the lint rule checks agrees with
    the runtime dict (no drift between what lint sees and what runs)."""
    from nds_tpu.obs.metrics import METRIC_KINDS

    for name, kind in METRIC_KINDS.items():
        assert kind in EVENT_SCHEMA, (name, kind)
        assert kind in name, (name, kind)
    path = os.path.join(L.package_root(), "obs", "metrics.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    parsed = {k: v for k, (v, _line) in L.metric_kinds_literal(tree).items()}
    assert parsed == dict(METRIC_KINDS)


def test_lint_clean_over_real_tree():
    findings = L.run_lint()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_rebases_repo_root_onto_package():
    # linting from the REPO root must not silently skip the path-scoped
    # rules (a false-clean) — run_lint rebases onto the nds_tpu package
    assert L.run_lint(ROOT) == []
    pkg = L.run_lint()
    # and the rebase sees the same files the direct package run sees
    assert {f.path for f in pkg} == {f.path for f in L.run_lint(ROOT)}


def test_emitted_kinds_sync_with_event_schema():
    """Golden sync: every kind literal emitted anywhere in nds_tpu/ is in
    EVENT_SCHEMA, and every non-deprecated EVENT_SCHEMA kind has a live
    emission site — schema drift breaks tier-1, not the tolerant reader."""
    emitted = set()
    for path in L.iter_py_files(L.package_root()):
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for kind, _kwargs, _star, _line in L.iter_emit_calls(tree):
            emitted.add(kind)
    assert emitted - set(EVENT_SCHEMA) == set(), (
        f"emitted kinds missing from EVENT_SCHEMA: "
        f"{emitted - set(EVENT_SCHEMA)}"
    )
    live_required = set(EVENT_SCHEMA) - set(DEPRECATED_EVENT_KINDS)
    assert live_required - emitted == set(), (
        f"EVENT_SCHEMA kinds with no emission site (deprecate or emit): "
        f"{live_required - emitted}"
    )


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------


def test_validate_summary_update_is_atomic(tmp_path, monkeypatch):
    from nds_tpu import validate

    f = tmp_path / "pfx-query1-123.json"
    original = {"queryStatus": ["Completed"]}
    f.write_text(json.dumps(original))
    validate.update_summary(str(tmp_path), [], ["query1"])
    assert json.loads(f.read_text())["queryValidationStatus"] == ["Pass"]

    # crash mid-dump: the destination must keep the previous COMPLETE file
    before = f.read_text()

    def boom(*a, **k):
        raise RuntimeError("disk full mid-write")

    monkeypatch.setattr(validate.json, "dump", boom)
    with pytest.raises(RuntimeError):
        validate.update_summary(str(tmp_path), ["query1"], ["query1"])
    monkeypatch.undo()
    assert f.read_text() == before  # not torn, not truncated
    assert list(tmp_path.glob("*.tmp-*")) == []  # temp discarded


def test_hot_path_imports_hoisted():
    """Regression for the PR-3 hot-path import class: the modules the lint
    holds to module-level imports actually resolved them at import time."""
    import nds_tpu.engine.exec as xc
    import nds_tpu.engine.expr as xp

    assert hasattr(xc, "fuse") and hasattr(xc, "faults")
    assert hasattr(xc, "pc") and hasattr(xc, "_share_dictionary")
    assert hasattr(xp, "unify_dictionaries")


def test_plan_verify_corpus_subset():
    """The CI corpus tool binds + rewrites + verifies templates without
    data or execution (full 99-template run lives in ci/tier1-check)."""
    spec = importlib.util.spec_from_file_location(
        "plan_verify_corpus",
        os.path.join(ROOT, "tools", "plan_verify_corpus.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # 14 is a two-statement template; 93 is the LEFT->INNER promotion shape
    assert mod.main(["--queries", "3,14,93"]) == 0
