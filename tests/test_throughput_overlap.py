"""Throughput concurrency: prove streams genuinely overlap in time.

The reference forks one Power Run per stream (nds/nds-throughput:18-23);
our thread mode runs streams as threads whose device dispatches release
the GIL. This asserts the overlap is real — each stream's [start, end]
window (from its time log) intersects every other's — and exercises the
fork-per-process mode end-to-end as well.
"""

import csv
import os
import subprocess
import sys

import pytest

from nds_tpu.schema import get_schemas
from nds_tpu.throughput import run_throughput

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_QUERY = """
select d_year, d_moy, count(*) c, sum(ss_ext_sales_price) s
from store_sales, date_dim
where ss_sold_date_sk = d_date_sk group by d_year, d_moy
order by d_year, d_moy
"""


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    out = tmp_path_factory.mktemp("wh")
    from nds_tpu.transcode import transcode_table

    for t in ("store_sales", "date_dim"):
        transcode_table(DATA, str(out), t, get_schemas()[t],
                        output_format="parquet", partition=False)
    return str(out)


def _write_stream(path, n_queries):
    parts = []
    for i in range(n_queries):
        # vary a constant per query so the session plan-result cache can't
        # collapse the stream into one execution + 7 dict hits
        q = SMOKE_QUERY.replace(
            "group by", f"and d_moy <= {12 - (i % 12)} group by"
        )
        parts.append(
            f"-- start query {i + 1} in stream 0 using template query3.tpl\n"
            f"{q}\n;\n"
            f"-- end query {i + 1} in stream 0 using template query3.tpl\n"
        )
    with open(path, "w") as f:
        f.write("\n".join(parts))


def _window(log):
    start = end = None
    with open(log) as f:
        for row in csv.reader(f):
            if len(row) >= 3 and row[1] == "Power Start Time":
                start = float(row[2])
            if len(row) >= 3 and row[1] == "Power End Time":
                end = float(row[2])
    return start, end


def _summary_window_ms(folder):
    """[first query start, last query end] in ms from a stream's per-query
    JSON summaries — fractional evidence of when the stream actually ran,
    independent of the int-second time log."""
    import glob
    import json

    lo = hi = None
    for p in glob.glob(os.path.join(folder, "*.json")):
        with open(p) as f:
            s = json.load(f)
        start = s["startTime"]
        end = start + sum(s["queryTimes"])
        lo = start if lo is None else min(lo, start)
        hi = end if hi is None else max(hi, end)
    assert lo is not None, f"no summaries in {folder}"
    return lo, hi


def test_thread_streams_overlap(warehouse, tmp_path):
    # The streams rendezvous on run_throughput's start gate after setup, so
    # the int-second time-log windows share one start by construction. The
    # genuine-concurrency proof uses the per-query JSON summaries' ms
    # timestamps: if a regression serialized the streams (whole-stream GIL
    # hold), stream A's last query would end before stream B's first began
    # and the strict window intersection below would fail.
    for n in (1, 2):
        _write_stream(tmp_path / f"query_{n}.sql", 8)
    base = str(tmp_path / "tt")
    ttt = run_throughput(
        warehouse,
        {1: str(tmp_path / "query_1.sql"), 2: str(tmp_path / "query_2.sql")},
        base,
        input_format="parquet",
        json_summary_folder=str(tmp_path / "summaries"),
    )
    assert ttt > 0
    s1, e1 = _window(f"{base}_1.csv")
    s2, e2 = _window(f"{base}_2.csv")
    # gate-aligned starts: both streams record the shared release timestamp
    assert s1 == s2, (s1, e1, s2, e2)
    # Ttt spans the union of the windows (reference Ttt semantics)
    assert ttt >= max(e1, e2) - min(s1, s2)
    # strict fractional-window intersection: each stream ran a query while
    # the other was still mid-stream
    f1 = _summary_window_ms(str(tmp_path / "summaries" / "stream_1"))
    f2 = _summary_window_ms(str(tmp_path / "summaries" / "stream_2"))
    assert f1[0] < f2[1] and f2[0] < f1[1], (f1, f2)


def test_process_mode_streams(warehouse, tmp_path):
    for n in (1, 2):
        _write_stream(tmp_path / f"query_{n}.sql", 2)
    base = str(tmp_path / "tp")
    ttt = run_throughput(
        warehouse,
        {1: str(tmp_path / "query_1.sql"), 2: str(tmp_path / "query_2.sql")},
        base,
        input_format="parquet",
        mode="process",
    )
    assert ttt > 0
    for n in (1, 2):
        s, e = _window(f"{base}_{n}.csv")
        assert s is not None and e is not None and e >= s
