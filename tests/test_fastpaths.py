"""Dense-join and direct-aggregation fast paths vs the sort-based fallback:
both physical strategies must produce identical results (the engine's AQE-ish
plan choice must never change answers)."""

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.exec import Executor
from nds_tpu.engine.session import Session


def _sess(seed=0, dup_keys=False, sparse=False):
    rng = np.random.default_rng(seed)
    n_dim, n_fact = 64, 2048
    keys = np.arange(1, n_dim + 1, dtype=np.int64)
    if sparse:
        keys = keys * 1_000_003  # domain too wide for the dense table
    if dup_keys:
        keys[n_dim // 2 :] = keys[: n_dim // 2]  # non-unique build side
    dim = pa.table(
        {
            "d_sk": keys,
            "d_grp": rng.integers(0, 5, n_dim),
        }
    )
    fact = pa.table(
        {
            "f_sk": rng.choice(keys, n_fact),
            "f_val": rng.integers(0, 1000, n_fact),
        }
    )
    s = Session()
    s.register_arrow("dim", dim)
    s.register_arrow("fact", fact)
    return s


QUERIES = [
    "select d_grp, sum(f_val) s, count(*) c from fact, dim where f_sk = d_sk group by d_grp order by d_grp",
    "select count(*) c from fact where f_sk in (select d_sk from dim where d_grp = 2)",
    "select count(*) c from fact where f_sk not in (select d_sk from dim where d_grp = 2)",
    "select d_grp, count(*) c from fact left join dim on f_sk = d_sk group by d_grp order by d_grp",
]


@pytest.mark.parametrize("variant", ["plain", "dup_keys", "sparse"])
@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_fast_and_fallback_agree(variant, qi, monkeypatch):
    s = _sess(dup_keys=variant == "dup_keys", sparse=variant == "sparse")
    q = QUERIES[qi]
    fast = s.sql(q).collect()
    # force the sort-based paths
    monkeypatch.setattr(Executor, "_DENSE_MAX_DOMAIN", 0)
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.num_rows == slow.num_rows
    for col in fast.schema.names:
        assert fast.column(col).to_pylist() == slow.column(col).to_pylist(), (
            variant,
            q,
            col,
        )


def test_direct_agg_null_keys(monkeypatch):
    rng = np.random.default_rng(3)
    n = 512
    vals = rng.integers(0, 50, n)
    grp = np.where(rng.random(n) < 0.2, None, rng.integers(0, 4, n).astype(object))
    t = pa.table({"g": pa.array(grp, type=pa.int64()), "v": vals})
    s = Session()
    s.register_arrow("t", t)
    q = "select g, count(*) c, sum(v) sv, min(v) mn from t group by g order by g"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_direct_agg_string_and_bool_keys(monkeypatch):
    rng = np.random.default_rng(4)
    n = 512
    t = pa.table(
        {
            "s": pa.array(rng.choice(["a", "b", None], n)),
            "b": pa.array(rng.random(n) < 0.5),
            "v": rng.integers(0, 50, n),
        }
    )
    s = Session()
    s.register_arrow("t", t)
    q = "select s, b, count(*) c, sum(v) sv from t group by s, b order by s, b"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_oom_retry_reloads_all_requested_columns(monkeypatch):
    """A RESOURCE_EXHAUSTED mid-load must drop caches and reload the FULL
    requested column set (not just the missing subset), and surface a
    task-failure event."""
    t = pa.table({"a": np.arange(8, dtype=np.int64), "b": np.arange(8, dtype=np.int64)})
    s = Session()
    s.register_arrow("t", t)
    s.catalog.load("t", ["a"])  # cache column a
    failures = []
    s.register_listener(failures.append)
    from nds_tpu.engine.session import Catalog

    real = Catalog._to_device
    calls = {"n": 0}

    def flaky(self, name, arrow, e):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return real(self, name, arrow, e)

    monkeypatch.setattr(Catalog, "_to_device", flaky)
    out = s.catalog.load("t", ["a", "b"])
    assert set(out.columns) == {"a", "b"}
    assert failures and "device memory exhausted" in failures[0]


def test_negative_keys(monkeypatch):
    t = pa.table(
        {
            "g": np.array([-5, -5, -3, 0, 2, 2, -3], dtype=np.int64),
            "v": np.arange(7, dtype=np.int64),
        }
    )
    s = Session()
    s.register_arrow("t", t)
    q = "select g, sum(v) sv from t group by g order by g"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_group_key_packing_matches_unpacked():
    """Multi-key group-bys pack into mixed-radix int64 words (the 8-key
    lexsort comparator made XLA TPU compiles explode); packed and unpacked
    paths must group identically, nulls and strings included."""
    import pyarrow as pa
    from nds_tpu.engine import exec as X
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(11)
    n = 3000
    # `a` spans a huge domain so _try_direct_agg declines and the SORTED
    # grouping path (the one that packs) is what runs
    t = pa.table({
        "a": rng.integers(-(2 ** 40), 2 ** 40, n),
        "b": pa.array(np.where(rng.random(n) < 0.1, None,
                               rng.integers(0, 9, n).astype(object))
                      ).cast(pa.int64()),
        "c": pa.array(rng.choice(["x", "y", "z", None], n)),
        "d": rng.integers(1990, 2005, n),
        "e": rng.integers(0, 2, n).astype(bool),
        "v": rng.integers(0, 100, n),
    })
    q = ("select a, b, c, d, e, count(*) cnt, sum(v) s from t "
         "group by a, b, c, d, e order by a, b, c, d, e")

    def run(min_operands):
        import unittest.mock as um
        s = Session()
        s.register_arrow("t", t)
        with um.patch.object(X.Executor, "_PACK_MIN_OPERANDS", min_operands):
            return s.sql(q).collect().to_pylist()

    packed = run(1)       # force packing
    unpacked = run(10**6)  # force plain lexsort
    assert packed == unpacked
    assert len(packed) > 100


def test_sort_key_packing_preserves_order():
    """ORDER BY packing folds direction and null position into monotone
    codes; every asc/desc x nulls-first/last combination must order rows
    identically to the unpacked lexsort, with floats left standalone."""
    import pyarrow as pa
    import unittest.mock as um
    from nds_tpu.engine import exec as X
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(23)
    n = 2500
    t = pa.table({
        "a": rng.integers(-(2 ** 35), 2 ** 35, n),
        "b": pa.array(np.where(rng.random(n) < 0.15, None,
                               rng.integers(0, 7, n).astype(object))
                      ).cast(pa.int64()),
        "s": pa.array(rng.choice(["ab", "cd", "ef", None], n)),
        "f": rng.random(n) * 10,
        "d": rng.integers(0, 4, n),
    })
    queries = [
        "select * from t order by a, b, s, d",
        "select * from t order by b desc, a, d desc, s",
        "select * from t order by b asc nulls last, d desc, a, s desc",
        "select * from t order by d, f desc, b, a",  # float splits the run
        "select * from t order by s desc nulls first, b, d, a",
    ]

    def run(min_ops):
        s = Session()
        s.register_arrow("t", t)
        with um.patch.object(X.Executor, "_SORT_PACK_MIN_OPERANDS", min_ops):
            return [s.sql(q).collect().to_pylist() for q in queries]

    packed = run(1)
    unpacked = run(10 ** 6)
    for q, pv, uv in zip(queries, packed, unpacked):
        assert pv == uv, q
