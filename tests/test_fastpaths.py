"""Dense-join and direct-aggregation fast paths vs the sort-based fallback:
both physical strategies must produce identical results (the engine's AQE-ish
plan choice must never change answers)."""

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.exec import Executor
from nds_tpu.engine.session import Session


def _sess(seed=0, dup_keys=False, sparse=False):
    rng = np.random.default_rng(seed)
    n_dim, n_fact = 64, 2048
    keys = np.arange(1, n_dim + 1, dtype=np.int64)
    if sparse:
        keys = keys * 1_000_003  # domain too wide for the dense table
    if dup_keys:
        keys[n_dim // 2 :] = keys[: n_dim // 2]  # non-unique build side
    dim = pa.table(
        {
            "d_sk": keys,
            "d_grp": rng.integers(0, 5, n_dim),
        }
    )
    fact = pa.table(
        {
            "f_sk": rng.choice(keys, n_fact),
            "f_val": rng.integers(0, 1000, n_fact),
        }
    )
    s = Session()
    s.register_arrow("dim", dim)
    s.register_arrow("fact", fact)
    return s


QUERIES = [
    "select d_grp, sum(f_val) s, count(*) c from fact, dim where f_sk = d_sk group by d_grp order by d_grp",
    "select count(*) c from fact where f_sk in (select d_sk from dim where d_grp = 2)",
    "select count(*) c from fact where f_sk not in (select d_sk from dim where d_grp = 2)",
    "select d_grp, count(*) c from fact left join dim on f_sk = d_sk group by d_grp order by d_grp",
]


@pytest.mark.parametrize("variant", ["plain", "dup_keys", "sparse"])
@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_fast_and_fallback_agree(variant, qi, monkeypatch):
    s = _sess(dup_keys=variant == "dup_keys", sparse=variant == "sparse")
    q = QUERIES[qi]
    fast = s.sql(q).collect()
    # force the sort-based paths
    monkeypatch.setattr(Executor, "_DENSE_MAX_DOMAIN", 0)
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.num_rows == slow.num_rows
    for col in fast.schema.names:
        assert fast.column(col).to_pylist() == slow.column(col).to_pylist(), (
            variant,
            q,
            col,
        )


def test_direct_agg_null_keys(monkeypatch):
    rng = np.random.default_rng(3)
    n = 512
    vals = rng.integers(0, 50, n)
    grp = np.where(rng.random(n) < 0.2, None, rng.integers(0, 4, n).astype(object))
    t = pa.table({"g": pa.array(grp, type=pa.int64()), "v": vals})
    s = Session()
    s.register_arrow("t", t)
    q = "select g, count(*) c, sum(v) sv, min(v) mn from t group by g order by g"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_direct_agg_string_and_bool_keys(monkeypatch):
    rng = np.random.default_rng(4)
    n = 512
    t = pa.table(
        {
            "s": pa.array(rng.choice(["a", "b", None], n)),
            "b": pa.array(rng.random(n) < 0.5),
            "v": rng.integers(0, 50, n),
        }
    )
    s = Session()
    s.register_arrow("t", t)
    q = "select s, b, count(*) c, sum(v) sv from t group by s, b order by s, b"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_oom_retry_reloads_all_requested_columns(monkeypatch):
    """A RESOURCE_EXHAUSTED mid-load must drop caches and reload the FULL
    requested column set (not just the missing subset), and surface a
    task-failure event."""
    t = pa.table({"a": np.arange(8, dtype=np.int64), "b": np.arange(8, dtype=np.int64)})
    s = Session()
    s.register_arrow("t", t)
    s.catalog.load("t", ["a"])  # cache column a
    failures = []
    s.register_listener(failures.append)
    from nds_tpu.engine.session import Catalog

    real = Catalog._to_device
    calls = {"n": 0}

    def flaky(self, name, arrow, e):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return real(self, name, arrow, e)

    monkeypatch.setattr(Catalog, "_to_device", flaky)
    out = s.catalog.load("t", ["a", "b"])
    assert set(out.columns) == {"a", "b"}
    assert failures and "device memory exhausted" in failures[0]


def test_negative_keys(monkeypatch):
    t = pa.table(
        {
            "g": np.array([-5, -5, -3, 0, 2, 2, -3], dtype=np.int64),
            "v": np.arange(7, dtype=np.int64),
        }
    )
    s = Session()
    s.register_arrow("t", t)
    q = "select g, sum(v) sv from t group by g order by g"
    fast = s.sql(q).collect()
    monkeypatch.setattr(Executor, "_DIRECT_AGG_MAX_DOMAIN", 0)
    slow = s.sql(q).collect()
    assert fast.to_pylist() == slow.to_pylist()


def test_group_key_words_match_pandas():
    """Multi-key group-bys encode keys into mixed-radix int64 words sorted
    by the canonical kv kernel; the grouping must match an independent
    pandas oracle, nulls and strings included."""
    import pandas as pd
    import pyarrow as pa
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(11)
    n = 3000
    # `a` spans a huge domain so _try_direct_agg declines and the SORTED
    # grouping path (the word-encoded one) is what runs
    t = pa.table({
        "a": rng.integers(-(2 ** 40), 2 ** 40, n),
        "b": pa.array(np.where(rng.random(n) < 0.1, None,
                               rng.integers(0, 9, n).astype(object))
                      ).cast(pa.int64()),
        "c": pa.array(rng.choice(["x", "y", "z", None], n)),
        "d": rng.integers(1990, 2005, n),
        "e": rng.integers(0, 2, n).astype(bool),
        "v": rng.integers(0, 100, n),
    })
    q = ("select a, b, c, d, e, count(*) cnt, sum(v) s from t "
         "group by a, b, c, d, e order by a, b, c, d, e")
    s = Session()
    s.register_arrow("t", t)
    got = s.sql(q).collect().to_pylist()

    df = t.to_pandas()
    exp = (
        df.groupby(["a", "b", "c", "d", "e"], dropna=False)
        .agg(cnt=("v", "size"), s=("v", "sum"))
        .reset_index()
        .sort_values(["a", "b", "c", "d", "e"], na_position="first")
    )
    expected = [
        {
            "a": int(r.a),
            "b": None if pd.isna(r.b) else int(r.b),
            "c": None if pd.isna(r.c) else r.c,
            "d": int(r.d),
            "e": bool(r.e),
            "cnt": int(r.cnt),
            "s": int(r.s),
        }
        for r in exp.itertuples()
    ]
    assert got == expected
    assert len(got) > 100


def test_sort_key_words_preserve_order():
    """ORDER BY word encoding folds direction and null position into
    monotone codes (floats via the order-preserving bit transform); every
    asc/desc x nulls-first/last combination must order rows identically to
    an independent Python comparator."""
    import pyarrow as pa
    from functools import cmp_to_key
    from nds_tpu.engine.session import Session

    rng = np.random.default_rng(23)
    n = 2500
    t = pa.table({
        "a": rng.integers(-(2 ** 35), 2 ** 35, n),
        "b": pa.array(np.where(rng.random(n) < 0.15, None,
                               rng.integers(0, 7, n).astype(object))
                      ).cast(pa.int64()),
        "s": pa.array(rng.choice(["ab", "cd", "ef", None], n)),
        "f": rng.random(n) * 10,
        "d": rng.integers(0, 4, n),
    })
    # every spec ends in `a` (effectively unique), so each ordering is total
    queries = [
        ("select * from t order by a, b, s, d",
         [("a", 1, 1), ("b", 1, 1), ("s", 1, 1), ("d", 1, 1)]),
        ("select * from t order by b desc, a, d desc, s",
         [("b", 0, 0), ("a", 1, 1), ("d", 0, 0), ("s", 1, 1)]),
        ("select * from t order by b asc nulls last, d desc, a, s desc",
         [("b", 1, 0), ("d", 0, 0), ("a", 1, 1), ("s", 0, 0)]),
        ("select * from t order by d, f desc, b, a",  # float standalone word
         [("d", 1, 1), ("f", 0, 0), ("b", 1, 1), ("a", 1, 1)]),
        ("select * from t order by s desc nulls first, b, d, a",
         [("s", 0, 1), ("b", 1, 1), ("d", 1, 1), ("a", 1, 1)]),
    ]
    s = Session()
    s.register_arrow("t", t)
    rows = t.to_pylist()
    for q, spec in queries:
        got = s.sql(q).collect().to_pylist()

        def cmp(ra, rb):
            for col, asc, nf in spec:
                va, vb = ra[col], rb[col]
                if va is None and vb is None:
                    continue
                if va is None:
                    return -1 if nf else 1
                if vb is None:
                    return 1 if nf else -1
                if va == vb:
                    continue
                lt = va < vb
                return (-1 if lt else 1) if asc else (1 if lt else -1)
            return 0

        expected = sorted(rows, key=cmp_to_key(cmp))
        assert got == expected, q
