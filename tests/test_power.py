"""Power Run driver + bench report tests (reference behavior:
nds/nds_power.py:50-77,184-299 and nds/PysparkBenchReport.py:58-119)."""

import csv
import json
import os
import subprocess
import sys

import pytest

from nds_tpu.power import (
    gen_sql_from_stream,
    get_query_subset,
    load_properties,
    run_query_stream,
)
from nds_tpu.report import BenchReport
from nds_tpu.engine.session import Session

DATA = "/tmp/nds_test_sf001"


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


STREAM = """-- start query 1 in stream 0 using template query96.tpl
select count(*) cnt from store_sales where ss_quantity > 0
;
-- end query 1 in stream 0 using template query96.tpl

-- start query 2 in stream 0 using template query3.tpl
select d_year, count(*) c from date_dim group by d_year order by d_year limit 5
;
-- end query 2 in stream 0 using template query3.tpl
"""

TWO_PART_STREAM = """-- start query 1 in stream 0 using template query23.tpl
select 1 as a
;
select 2 as b
;
-- end query 1 in stream 0 using template query23.tpl
"""


def test_gen_sql_from_stream(tmp_path):
    p = tmp_path / "query_0.sql"
    p.write_text(STREAM)
    qd = gen_sql_from_stream(str(p))
    assert list(qd) == ["query96", "query3"]
    assert qd["query96"].startswith("-- start query 1")
    assert "select count(*)" in qd["query96"]


def test_gen_sql_two_part_split(tmp_path):
    p = tmp_path / "query_0.sql"
    p.write_text(TWO_PART_STREAM)
    qd = gen_sql_from_stream(str(p))
    assert list(qd) == ["query23_part1", "query23_part2"]
    assert "select 1" in qd["query23_part1"]
    assert "select 2" in qd["query23_part2"]
    assert "query23_part1.tpl" in qd["query23_part1"]
    assert "query23_part2.tpl" in qd["query23_part2"]


def test_get_query_subset(tmp_path):
    p = tmp_path / "query_0.sql"
    p.write_text(STREAM)
    qd = gen_sql_from_stream(str(p))
    sub = get_query_subset(qd, ["query3"])
    assert list(sub) == ["query3"]
    with pytest.raises(Exception, match="not found"):
        get_query_subset(qd, ["query999"])


def test_load_properties(tmp_path):
    f = tmp_path / "x.properties"
    f.write_text("a.b=1\n# comment\n\nc.d = hello \n")
    assert load_properties(str(f)) == {"a.b": "1", "c.d": "hello"}


def test_run_query_stream_end_to_end(data_dir, tmp_path):
    stream = tmp_path / "query_0.sql"
    stream.write_text(STREAM)
    time_log = tmp_path / "time.csv"
    jdir = tmp_path / "json"
    out = tmp_path / "out"
    qd = gen_sql_from_stream(str(stream))
    run_query_stream(
        input_prefix=data_dir,
        property_file=None,
        query_dict=qd,
        time_log_output_path=str(time_log),
        input_format="csv",
        output_path=str(out),
        output_format="parquet",
        json_summary_folder=str(jdir),
    )
    rows = list(csv.reader(time_log.open()))
    assert rows[0] == ["application_id", "query", "time/milliseconds"]
    names = [r[1] for r in rows[1:]]
    assert "query96" in names and "query3" in names
    assert "Power Test Time" in names and "Total Time" in names
    summaries = sorted(os.listdir(jdir))
    assert len(summaries) == 2
    s = json.load(open(os.path.join(jdir, summaries[0])))
    assert s["queryStatus"] == ["Completed"]
    assert s["queryTimes"] and isinstance(s["queryTimes"][0], int)
    assert "sparkConf" in s["env"] and "envVars" in s["env"]
    # filename contract: <prefix>-<query>-<startTime>.json
    assert s["filename"].endswith(f"-{s['query']}-{s['startTime']}.json")
    # written outputs exist per query
    assert os.path.exists(out / "query96" / "part-0.parquet")


def test_failed_query_continues(data_dir, tmp_path):
    bad_stream = (
        "-- start query 1 in stream 0 using template query1.tpl\n"
        "select nonexistent_col from store_sales\n;\n"
        "-- end query 1 in stream 0 using template query1.tpl\n"
        "-- start query 2 in stream 0 using template query3.tpl\n"
        "select count(*) c from item\n;\n"
        "-- end query 2 in stream 0 using template query3.tpl\n"
    )
    stream = tmp_path / "query_0.sql"
    stream.write_text(bad_stream)
    jdir = tmp_path / "json"
    qd = gen_sql_from_stream(str(stream))
    run_query_stream(
        input_prefix=data_dir,
        property_file=None,
        query_dict=qd,
        time_log_output_path=str(tmp_path / "t.csv"),
        input_format="csv",
        json_summary_folder=str(jdir),
    )
    st = {}
    for f in os.listdir(jdir):
        s = json.load(open(os.path.join(jdir, f)))
        st[s["query"]] = s
    assert st["query1"]["queryStatus"] == ["Failed"]
    assert st["query1"]["exceptions"]
    assert st["query3"]["queryStatus"] == ["Completed"]


def test_report_redacts_secrets(monkeypatch):
    monkeypatch.setenv("MY_SECRET_KEY", "hunter2")
    monkeypatch.setenv("API_TOKEN", "tok")
    monkeypatch.setenv("SAFE_VAR", "ok")
    r = BenchReport(Session())
    r.report_on(lambda: None)
    env = r.summary["env"]["envVars"]
    assert "MY_SECRET_KEY" not in env
    assert "API_TOKEN" not in env
    assert env.get("SAFE_VAR") == "ok"


def test_report_task_failures_status():
    sess = Session()

    def flaky():
        sess.notify_failure("task retry: exchange capacity doubled")

    r = BenchReport(sess)
    summary = r.report_on(flaky)
    assert summary["queryStatus"] == ["CompletedWithTaskFailures"]
    assert summary["taskFailures"]
