"""Validator semantics tests (reference behavior: nds/nds_validate.py)."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.validate import (
    compare,
    compare_results,
    iterate_queries,
    row_equal,
    update_summary,
)


def test_compare_scalar_semantics():
    assert compare(1.0, 1.0 + 1e-9)
    assert not compare(1.0, 1.1)
    assert compare(float("nan"), float("nan"))
    assert compare(None, None)
    assert not compare(None, 1.0)
    assert not compare(1.0, None)
    assert compare("a", "a")
    assert not compare("a", "b")
    from decimal import Decimal

    assert compare(Decimal("10.00"), Decimal("10.0000001"))
    assert compare(Decimal("10.00"), 10.0)  # cross-engine numeric


def test_q78_fourth_column_tolerance():
    r1 = [1, "a", 2, 0.50, 9.0]
    r2 = [1, "a", 2, 0.505, 9.0]
    assert row_equal(r1, r2, 1e-5, is_q78=True)
    r3 = [1, "a", 2, 0.52, 9.0]
    assert not row_equal(r1, r3, 1e-5, is_q78=True)
    assert row_equal([1, 2, 3, None], [1, 2, 3, None], 1e-5, is_q78=True)
    assert not row_equal([1, 2, 3, None], [1, 2, 3, 0.5], 1e-5, is_q78=True)


def _write(path, table):
    os.makedirs(path, exist_ok=True)
    pq.write_table(table, os.path.join(path, "part-0.parquet"))


def test_compare_results_ordering(tmp_path):
    t1 = pa.table({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    t2 = pa.table({"k": [3, 1, 2], "v": [3.0, 1.0, 2.0]})
    _write(tmp_path / "a", t1)
    _write(tmp_path / "b", t2)
    assert not compare_results(str(tmp_path / "a"), str(tmp_path / "b"))
    assert compare_results(
        str(tmp_path / "a"), str(tmp_path / "b"), ignore_ordering=True
    )


def test_compare_results_count_mismatch(tmp_path):
    _write(tmp_path / "a", pa.table({"k": [1, 2]}))
    _write(tmp_path / "b", pa.table({"k": [1]}))
    assert not compare_results(str(tmp_path / "a"), str(tmp_path / "b"))


def test_iterate_and_update_summary(tmp_path):
    ok = pa.table({"k": [1], "v": [1.0]})
    bad = pa.table({"k": [1], "v": [9.0]})
    for q, (l, r) in {
        "query1": (ok, ok),
        "query2": (ok, bad),
        "query65": (ok, bad),  # always skipped
    }.items():
        _write(tmp_path / "run1" / q, l)
        _write(tmp_path / "run2" / q, r)
    queries = ["query1", "query2", "query65"]
    unmatch = iterate_queries(
        str(tmp_path / "run1"), str(tmp_path / "run2"), queries
    )
    assert unmatch == ["query2"]
    jdir = tmp_path / "json"
    os.makedirs(jdir)
    for q, status in [("query1", "Completed"), ("query2", "Completed"), ("query65", "Failed")]:
        with open(jdir / f"-{q}-123.json", "w") as f:
            json.dump({"query": q, "queryStatus": [status]}, f)
    update_summary(str(jdir), unmatch + ["query65"], queries)
    got = {}
    for f in os.listdir(jdir):
        s = json.load(open(jdir / f))
        got[s["query"]] = s["queryValidationStatus"]
    assert got == {
        "query1": ["Pass"],
        "query2": ["Fail"],
        "query65": ["NotAttempted"],
    }
