"""Transcode / Load Test phase tests (reference behavior:
nds/nds_transcode.py:45-53 partitioning, :146-215 report contract)."""

import os
import subprocess
import sys
from argparse import Namespace

import pyarrow.dataset as pads
import pytest

from nds_tpu.io.csv import iter_dat_batches, read_dat_dir
from nds_tpu.schema import get_schemas
from nds_tpu.transcode import TABLE_PARTITIONING, transcode, transcode_table

DATA = "/tmp/nds_test_sf001"


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


def _args(data_dir, out, report, **kw):
    base = dict(
        input_prefix=data_dir, output_prefix=str(out), report_file=str(report),
        output_mode="errorifexists", output_format="parquet", tables=None,
        floats=False, update=False, compression=None,
    )
    base.update(kw)
    return Namespace(**base)


def test_iter_dat_batches_streams(data_dir):
    sch = get_schemas()["store_sales"]
    n_stream = sum(
        b.num_rows
        for b in iter_dat_batches(os.path.join(data_dir, "store_sales"), sch,
                                  block_size=1 << 16)
    )
    n_bulk = read_dat_dir(os.path.join(data_dir, "store_sales"), sch).num_rows
    assert n_stream == n_bulk > 0


def test_fact_table_partitioned_layout(data_dir, tmp_path):
    sch = get_schemas()["store_returns"]
    rows = transcode_table(data_dir, str(tmp_path), "store_returns", sch)
    part_col = TABLE_PARTITIONING["store_returns"]
    dirs = os.listdir(tmp_path / "store_returns")
    assert any(d.startswith(part_col + "=") for d in dirs)
    assert rows > 0


def test_dim_table_single_file(data_dir, tmp_path):
    sch = get_schemas()["item"]
    transcode_table(data_dir, str(tmp_path), "item", sch)
    files = os.listdir(tmp_path / "item")
    assert files == ["part-0.parquet"]


def test_roundtrip_equals_source(data_dir, tmp_path):
    """Parquet warehouse read-back must match the raw CSV read (including the
    hive-partition column restored with its schema dtype)."""
    table = "store_returns"
    sch = get_schemas()[table]
    transcode_table(data_dir, str(tmp_path), table, sch)
    from nds_tpu.engine.session import Session

    sess = Session()
    sess.register_parquet(table, str(tmp_path / table), sch)
    back = sess.sql(f"select * from {table}").collect()
    src = read_dat_dir(os.path.join(data_dir, table), sch)
    assert back.num_rows == src.num_rows
    key = "sr_item_sk"
    part_col = TABLE_PARTITIONING[table]
    b = back.sort_by([(part_col, "ascending"), (key, "ascending"), ("sr_ticket_number", "ascending")])
    s = src.sort_by([(part_col, "ascending"), (key, "ascending"), ("sr_ticket_number", "ascending")])
    for col in (part_col, key, "sr_return_amt"):
        assert b.column(col).to_pylist() == s.column(col).to_pylist(), col


def test_csv_warehouse_roundtrip(data_dir, tmp_path):
    """A csv-format warehouse (transcode --output_format csv) must be
    readable by the power-run session (reference parity: nds_power.py csv
    input_format reads the transcoded warehouse, not raw .dat)."""
    table = "warehouse"
    sch = get_schemas()[table]
    transcode_table(
        data_dir, str(tmp_path), table, sch, output_format="csv"
    )
    from nds_tpu.engine.session import Session

    sess = Session()
    sess.register_csv_warehouse(table, str(tmp_path / table), sch)
    back = sess.sql(f"select * from {table}").collect()
    src = read_dat_dir(os.path.join(data_dir, table), sch)
    assert back.num_rows == src.num_rows
    b = back.sort_by("w_warehouse_sk")
    s = src.sort_by("w_warehouse_sk")
    assert b.column("w_warehouse_id").to_pylist() == s.column("w_warehouse_id").to_pylist()


def test_append_mode_preserves_existing(data_dir, tmp_path):
    sch = get_schemas()["warehouse"]
    n1 = transcode_table(data_dir, str(tmp_path), "warehouse", sch)
    n2 = transcode_table(
        data_dir, str(tmp_path), "warehouse", sch, output_mode="append"
    )
    ds = pads.dataset(str(tmp_path / "warehouse"), format="parquet")
    assert ds.count_rows() == n1 + n2


def test_transcode_report_contract(data_dir, tmp_path):
    report = tmp_path / "load.report"
    out = tmp_path / "wh"
    transcode(_args(data_dir, out, report, tables=["item", "warehouse"]))
    text = report.read_text()
    assert "Load Test Time:" in text
    assert "RNGSEED used:" in text
    assert "Time to convert 'item'" in text
    assert "Time to convert 'warehouse'" in text


def test_output_mode_guard(data_dir, tmp_path):
    sch = get_schemas()["warehouse"]
    transcode_table(data_dir, str(tmp_path), "warehouse", sch)
    with pytest.raises(FileExistsError):
        transcode_table(data_dir, str(tmp_path), "warehouse", sch)
    # overwrite succeeds
    transcode_table(
        data_dir, str(tmp_path), "warehouse", sch, output_mode="overwrite"
    )
    # ignore is a no-op
    assert (
        transcode_table(
            data_dir, str(tmp_path), "warehouse", sch, output_mode="ignore"
        )
        == 0
    )


def test_orc_roundtrip(data_dir, tmp_path):
    """ORC output format parity (reference: nds_transcode.py:100-112)."""
    from nds_tpu.engine.session import Session

    schema = get_schemas()["store"]
    n = transcode_table(data_dir, str(tmp_path), "store", schema,
                        output_format="orc")
    assert n > 0
    s = Session()
    s.register_orc("store", os.path.join(str(tmp_path), "store"), schema)
    out = s.sql("select count(*) c from store").to_pylist()
    assert out == [{"c": n}]


def test_dbgen_version_table(tmp_path):
    """The generator emits the one-row dbgen_version audit table
    (reference: nds_gen_data.py:50-51)."""
    from nds_tpu.engine.session import Session

    d = str(tmp_path / "gen")
    subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
         "--parallel", "2", "--data_dir", d, "--table", "store",
         "--overwrite_output"],
        check=True, capture_output=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    path = os.path.join(d, "dbgen_version")
    assert os.path.isdir(path)
    s = Session()
    s.register_csv_dir("dbgen_version", path, get_schemas()["dbgen_version"])
    rows = s.sql(
        "select dv_version, dv_cmdline_args from dbgen_version"
    ).to_pylist()
    assert len(rows) == 1 and rows[0]["dv_version"] == "1.0.0"


def test_json_output(data_dir, tmp_path):
    """Line-delimited JSON output (reference: nds_transcode.py:61-144)."""
    import json

    schema = get_schemas()["warehouse"]
    n = transcode_table(data_dir, str(tmp_path), "warehouse", schema,
                        output_format="json")
    assert n > 0
    path = os.path.join(str(tmp_path), "warehouse", "part-0.json")
    rows = [json.loads(line) for line in open(path)]
    assert len(rows) == n and "w_warehouse_sk" in rows[0]


def test_avro_roundtrip(data_dir, tmp_path):
    """Avro container output (reference: nds_transcode.py:241-249 via the
    spark-avro plugin) — written by our own spec-subset writer and read back
    byte-exactly through the paired reader."""
    from nds_tpu.io.avro import read_avro
    from nds_tpu.io.csv import read_dat_dir

    schema = get_schemas()["store"]
    n = transcode_table(data_dir, str(tmp_path), "store", schema,
                        output_format="avro")
    assert n > 0
    files = os.listdir(os.path.join(str(tmp_path), "store"))
    assert files == ["part-0.avro"]
    rows = read_avro(os.path.join(str(tmp_path), "store", files[0]))
    src = read_dat_dir(os.path.join(data_dir, "store"), schema).to_pylist()
    assert len(rows) == len(src) == n
    for got, want in zip(rows, src):
        for k, v in want.items():
            g = got[k]
            if isinstance(v, float):
                assert abs(g - v) < 1e-12
            else:
                assert g == v, (k, g, v)
