"""Blocked (morsel-style) union-aggregation: the executor evaluates a
union_all feeding an aggregate in bounded row windows with partial-aggregate
merging instead of materializing the full concat (the SF10 HBM ceiling,
bench.py). Blocked-path results must equal the unblocked path exactly;
non-decomposable aggregates must stay on the unblocked path.

Plus regression tests for the satellite fixes that rode along with the
blocked path (ISSUE 1): SF10 bench data-dir derivation, the throughput
start-gate timeout fallback, _to_ts_ms epoch windows, the join-expansion
int32 guard, and _null_rejecting_shape vs nested boolean connectives.
"""

import threading
import time
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import plan as P
from nds_tpu.engine.columnar import bucket_cap
from nds_tpu.engine.session import Session

rng = np.random.default_rng(42)


def _channel(n, seed):
    r = np.random.default_rng(seed)
    ks = r.integers(1, 6, n)
    vs = r.integers(-50, 50, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 13 == 0 else int(v) for i, v in enumerate(ks)],
                pa.int32(),
            ),
            "cat": pa.array(
                [["Books", "Music", "Shoes"][int(v) % 3] for v in ks]
            ),
            "v": pa.array(
                [None if i % 7 == 0 else int(v) for i, v in enumerate(vs)],
                pa.int32(),
            ),
            "amt": pa.array(
                [Decimal(int(v) * 7) / 100 for v in vs], pa.decimal128(7, 2)
            ),
        }
    )


def _session(window_rows=None):
    conf = {}
    if window_rows is not None:
        conf["engine.union_agg_window_rows"] = window_rows
    s = Session(conf=conf)
    for i, t in enumerate(("t1", "t2", "t3")):
        s.register_arrow(t, _channel(3000, seed=100 + i))
    return s


UNION_AGG = """
select k, sum(v) sv, min(v) mn, max(v) mx, count(v) cv, count(*) c,
       avg(v) av, sum(amt) sa
from (select k, cat, v, amt from t1
      union all
      select k, cat, v, amt from t2 where v > -40
      union all
      select k, cat, v, amt from t3) u
where v < 45
group by k
order by k
"""


def _find_agg(plan):
    out = []

    def visit(n):
        if isinstance(n, P.Aggregate):
            out.append(n)
        if isinstance(n, P.Pipeline) and n.agg is not None:
            # a fused aggregate tail is the Aggregate, detached
            out.append(n.agg)
        for c in n.children():
            if c is not None:
                visit(c)

    visit(plan)
    assert out, "no Aggregate in plan"
    return out[0]


def _run(sql, window_rows):
    s = _session(window_rows)
    r = s.sql(sql)
    return r.collect(), _find_agg(r.plan)


def test_blocked_equals_unblocked_all_decomposable_aggs():
    # huge window -> single window -> unblocked path is taken
    unblocked, agg_u = _run(UNION_AGG, 10**9)
    assert getattr(agg_u, "blocked_windows", None) is None
    # tiny window -> multi-window blocked execution
    blocked, agg_b = _run(UNION_AGG, 600)
    assert agg_b.blocked_union
    assert agg_b.blocked_windows > 1
    assert unblocked.to_pylist() == blocked.to_pylist()


def test_plan_annotation_and_bounded_window_caps():
    window = 600
    blocked, agg = _run(UNION_AGG, window)
    stats = agg.blocked_stats
    wcap = bucket_cap(window)
    assert stats["window_cap"] == wcap
    # window count is per-branch ceil-division over the window bucket
    assert stats["windows"] >= stats["total_rows"] // wcap
    # peak table capacity is bounded by the window bucket (merge concats
    # stay within 2x: window partial + group-sized accumulator), never by
    # the total union row count
    assert stats["max_table_cap"] <= 2 * wcap
    assert stats["max_table_cap"] < bucket_cap(stats["total_rows"])


def test_blocked_string_group_key():
    q = """
    select cat, sum(v) sv, count(*) c, avg(v) av
    from (select cat, v from t1 union all select cat, v from t2) u
    group by cat order by cat
    """
    unblocked, _ = _run(q, 10**9)
    blocked, agg = _run(q, 700)
    assert agg.blocked_windows > 1
    assert unblocked.to_pylist() == blocked.to_pylist()


def test_blocked_global_aggregate():
    q = """
    select sum(v) sv, min(v) mn, count(v) cv, count(*) c, avg(v) av
    from (select v from t1 union all select v from t2 where v > 0) u
    """
    unblocked, _ = _run(q, 10**9)
    blocked, agg = _run(q, 512)
    assert agg.blocked_windows > 1
    assert unblocked.to_pylist() == blocked.to_pylist()


def test_blocked_empty_after_filter():
    # every window filters to nothing: grouped output must be empty, like
    # the unblocked path's
    q = """
    select k, sum(v) sv from
    (select k, v from t1 union all select k, v from t2) u
    where v > 1000 group by k
    """
    unblocked, _ = _run(q, 10**9)
    blocked, agg = _run(q, 512)
    assert agg.blocked_windows > 1
    assert blocked.num_rows == unblocked.num_rows == 0


def test_blocked_union_through_inner_join():
    # the query5 channel shape: fact-scale union joined to a dimension
    # before aggregation — windows flow through the inner join, so the
    # full union concat (and its join pair table) never materializes
    dim = pa.table(
        {
            "dk": pa.array(range(1, 6), pa.int32()),
            "dname": pa.array([f"d{i}" for i in range(1, 6)]),
            "flag": pa.array([i % 2 for i in range(1, 6)], pa.int32()),
        }
    )
    q = """
    select d.dname, sum(u.v) sv, count(*) c, avg(u.v) av
    from (select k, v from t1 union all select k, v from t2) u, dim d
    where u.k = d.dk and d.flag = 1
    group by d.dname order by d.dname
    """

    def run(window):
        s = _session(window)
        s.register_arrow("dim", dim)
        r = s.sql(q)
        return r.collect(), _find_agg(r.plan)

    unblocked, agg_u = run(10**9)
    assert getattr(agg_u, "blocked_windows", None) is None
    blocked, agg = run(500)
    assert agg.blocked_union
    assert agg.blocked_windows > 1
    assert agg.blocked_stats["max_table_cap"] < bucket_cap(
        agg.blocked_stats["total_rows"]
    )
    assert unblocked.to_pylist() == blocked.to_pylist()


def test_blocked_rollup_over_union():
    # the query5 shape: GROUP BY ROLLUP over a multi-channel union — the
    # finest level runs windowed, coarser levels cascade from its (small)
    # output, and the full union concat never materializes
    q = """
    select cat, k, sum(v) sv, count(*) c, avg(v) av
    from (select cat, k, v from t1
          union all select cat, k, v from t2
          union all select cat, k, v from t3) u
    group by rollup(cat, k)
    order by cat, k
    """
    unblocked, agg_u = _run(q, 10**9)
    assert getattr(agg_u, "blocked_windows", None) is None
    blocked, agg = _run(q, 600)
    assert agg.blocked_union
    assert agg.blocked_windows > 1
    # only the finest level is windowed: the cascade handles the rest, so
    # the window count stays one pass over the input, not one per set
    assert agg.blocked_windows <= agg.blocked_stats["total_rows"] // bucket_cap(
        600
    ) + len(("t1", "t2", "t3"))
    assert agg.blocked_stats["max_table_cap"] < bucket_cap(
        agg.blocked_stats["total_rows"]
    )
    ul, bl = unblocked.to_pylist(), blocked.to_pylist()
    assert len(ul) == len(bl)
    for x, y in zip(ul, bl):
        for col in x:
            if isinstance(x[col], float):
                assert abs(x[col] - y[col]) < 1e-9 * max(1.0, abs(x[col]))
            else:
                assert x[col] == y[col]


def test_non_decomposable_stays_unblocked():
    q = """
    select k, count(distinct v) dv
    from (select k, v from t1 union all select k, v from t2) u
    group by k order by k
    """
    out_small, agg = _run(q, 512)
    # NOT annotated: the shape matches but count distinct does not
    # decompose over row windows, and the annotation pass now applies the
    # same plan.aggs_decomposable rule the executor's blocked path uses
    # (the verifier flags a blocked_union mark on a non-decomposable
    # aggregate as a planner violation — analysis/verifier.py)
    assert not agg.blocked_union
    assert getattr(agg, "blocked_windows", None) is None
    out_big, _ = _run(q, 10**9)
    assert out_small.to_pylist() == out_big.to_pylist()


def test_union_distinct_not_annotated():
    s = _session(512)
    r = s.sql(
        """
        select k, sum(v) sv
        from (select k, v from t1 union select k, v from t2) u
        group by k order by k
        """
    )
    agg = _find_agg(r.plan)
    assert not agg.blocked_union
    r.collect()  # still executes correctly
    assert getattr(agg, "blocked_windows", None) is None


def test_derived_window_rows_honors_conf_and_env(monkeypatch):
    s = Session(conf={"engine.union_agg_window_rows": 123})
    assert s.union_agg_window_rows(row_bytes=100) == 123
    s2 = Session()
    monkeypatch.setenv("NDS_UNION_AGG_WINDOW_ROWS", "456")
    assert s2.union_agg_window_rows(row_bytes=100) == 456
    monkeypatch.delenv("NDS_UNION_AGG_WINDOW_ROWS")
    derived = s2.union_agg_window_rows(row_bytes=90)
    # power of two within the clamp range, derived from the device budget
    assert derived & (derived - 1) == 0
    assert (1 << 16) <= derived <= (1 << 24)
    # wider rows -> same or smaller windows
    assert s2.union_agg_window_rows(row_bytes=900) <= derived


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------


def test_sf10_data_dir_derivation(monkeypatch):
    import bench

    monkeypatch.delenv("NDS_BENCH_DATA", raising=False)
    monkeypatch.delenv("NDS_BENCH_DATA_SF10", raising=False)
    assert bench._sf10_data_dir() == "/tmp/nds_bench_sf10.0"
    monkeypatch.setenv("NDS_BENCH_DATA", "/data/nds_sf1/")
    assert bench._sf10_data_dir() == "/data/nds_sf1_sf10.0"
    monkeypatch.setenv("NDS_BENCH_DATA_SF10", "/big/nds_sf10")
    assert bench._sf10_data_dir() == "/big/nds_sf10"


def test_start_gate_pure_timeout_falls_back_ungated():
    from nds_tpu.throughput import _StartGate

    gate = _StartGate(2, timeout=0.3)  # second party never arrives
    t0 = time.time()
    got = gate.wait()
    assert isinstance(got, float) and got >= t0  # ungated start, no raise
    # a sibling arriving after the breakage also degrades, not raises
    assert isinstance(gate.wait(), float)


def test_start_gate_abort_raises_gate_broken():
    from nds_tpu.throughput import _GateBroken, _StartGate

    gate = _StartGate(2, timeout=30)
    box = {}

    def parked():
        try:
            gate.wait()
        except _GateBroken as exc:
            box["exc"] = exc

    th = threading.Thread(target=parked)
    th.start()
    time.sleep(0.05)
    gate.abort()
    th.join(5)
    assert isinstance(box.get("exc"), _GateBroken)


def test_start_gate_releases_all_with_shared_epoch():
    from nds_tpu.throughput import _StartGate

    gate = _StartGate(2, timeout=30)
    out = {}

    def one(n):
        out[n] = gate.wait()

    ts = [threading.Thread(target=one, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5)
    assert out[0] == out[1]  # one shared release timestamp


def test_to_ts_ms_epoch_windows():
    from nds_tpu.lakehouse.dml import LakehouseError, _to_ts_ms

    assert _to_ts_ms("1700000000") == 1_700_000_000_000  # epoch seconds
    assert _to_ts_ms("1700000000000") == 1_700_000_000_000  # epoch ms
    assert _to_ts_ms(1700000000) == 1_700_000_000_000
    assert _to_ts_ms("2024-01-01 12:00:00") > 0
    # 12-digit compact datetime (~2e11) must NOT parse as epoch seconds in
    # year ~8383 — it falls through to the date parser and errors loudly
    with pytest.raises(LakehouseError):
        _to_ts_ms("202401011200")
    # 14-digit compact datetime (~2e13) likewise
    with pytest.raises(LakehouseError):
        _to_ts_ms("20240101120000")
    with pytest.raises(LakehouseError):
        _to_ts_ms("20240101")


def test_join_expand_int32_guard():
    from nds_tpu.ops.kernels import _check_pair_count

    _check_pair_count(0)
    _check_pair_count(1 << 30)  # largest safe bucket
    with pytest.raises(ValueError, match="int32"):
        _check_pair_count((1 << 30) + 1)


def test_null_rejecting_shape_boolean_connectives():
    from nds_tpu.engine import expr as E
    from nds_tpu.engine.binder import _null_rejecting_shape

    plain = E.BinOp("=", E.Col("x", "a"), E.Col("y", "b"))
    assert _null_rejecting_shape(plain)
    # null-tolerant OR nested inside an operand: NOT strict (b.y NULL can
    # still yield TRUE), must not promote a LEFT JOIN to INNER
    nested_or = E.BinOp(
        "=", E.Col("x", "a"), E.BinOp("or", E.Col("y", "b"), E.Lit(True))
    )
    assert not _null_rejecting_shape(nested_or)
    nested_and = E.BinOp(
        "<", E.BinOp("and", E.Col("y", "b"), E.Lit(False)), E.Col("x", "a")
    )
    assert not _null_rejecting_shape(nested_and)
    # the top-level comparison itself is still fine when wrapped in AND at
    # the conjunct level (callers split conjuncts before calling)
    assert not _null_rejecting_shape(
        E.BinOp("and", plain, plain)
    )  # not a comparison at the root
