import pyarrow as pa
import pytest

from nds_tpu import schema
from nds_tpu.dtypes import parse_dtype, DType, common_numeric, FLOAT64, INT64


def test_source_table_count_and_columns():
    s = schema.get_schemas()
    assert len(s) == 25  # 24 data tables + dbgen_version (reference: nds_gen_data.py:50-51)
    assert len(s["store_sales"]) == 23
    assert len(s["date_dim"]) == 28
    assert len(s["catalog_sales"]) == 34
    assert len(s["web_sales"]) == 34
    assert len(s["item"]) == 22
    # sr_ticket_number is int64 (reference: nds/nds_schema.py:322-325)
    assert s["store_returns"].field("sr_ticket_number").dtype.kind == "int64"
    assert not s["store_returns"].field("sr_ticket_number").nullable


def test_maintenance_table_count():
    m = schema.get_maintenance_schemas()
    assert len(m) == 12
    assert "s_purchase_lineitem" in m and "delete" in m and "inventory_delete" in m


def test_decimal_float_switch():
    dec = schema.get_schemas(use_decimal=True)
    flt = schema.get_schemas(use_decimal=False)
    f_dec = dec["store_sales"].field("ss_list_price")
    f_flt = flt["store_sales"].field("ss_list_price")
    assert f_dec.dtype == DType("decimal", 7, 2)
    assert f_flt.dtype.kind == "float64"


def test_arrow_conversion():
    s = schema.get_schemas()["customer_address"]
    arrow = s.to_arrow()
    assert arrow.field("ca_address_sk").type == pa.int32()
    assert arrow.field("ca_gmt_offset").type == pa.decimal128(5, 2)
    assert arrow.field("ca_city").type == pa.string()
    assert not arrow.field("ca_address_sk").nullable
    arrow_f = s.to_arrow(use_decimal=False)
    assert arrow_f.field("ca_gmt_offset").type == pa.float64()


def test_dtype_parse_roundtrip():
    for s in ["int32", "int64", "float64", "date", "string", "decimal(7,2)", "char(16)", "varchar(60)"]:
        assert str(parse_dtype(s)) == s
    with pytest.raises(ValueError):
        parse_dtype("int16")


def test_device_mapping():
    import numpy as np

    assert parse_dtype("decimal(7,2)").device_np_dtype() == np.int64
    assert parse_dtype("decimal(7,2)").device_np_dtype(use_decimal=False) == np.float64
    assert parse_dtype("char(10)").device_np_dtype() == np.int32
    assert parse_dtype("date").device_np_dtype() == np.int32


def test_numeric_promotion():
    d72 = parse_dtype("decimal(7,2)")
    d152 = parse_dtype("decimal(15,2)")
    assert common_numeric(d72, FLOAT64) == FLOAT64
    assert common_numeric(d72, d152) == DType("decimal", 16, 2)
    assert common_numeric(INT64, parse_dtype("int32")) == INT64


def test_partitioning_map():
    assert set(schema.TABLE_PARTITIONING) == {
        "catalog_sales", "catalog_returns", "inventory", "store_sales",
        "store_returns", "web_sales", "web_returns",
    }
