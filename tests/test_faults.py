"""Failure-domain subsystem: fault injection, the classified retry/
degradation ladder, the query watchdog, atomic report writes, and
checkpointed full_bench resume.

Every recovery path is driven deterministically through the fault registry
(nds_tpu/faults.py) instead of hoping it fires under a real OOM — the
chaos-harness practice the reference gets for free from Spark's scheduler
(executor loss -> task retry; TaskFailureListener chain)."""

import json
import os
import subprocess
import sys
import time

import pytest

from nds_tpu import faults
from nds_tpu import full_bench as FB
from nds_tpu.io.fs import fs_open, fs_open_atomic
from nds_tpu.power import gen_sql_from_stream, run_query_stream
from nds_tpu.report import BenchReport
from nds_tpu.engine.session import Session

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_faults(monkeypatch):
    monkeypatch.delenv("NDS_FAULT_SPEC", raising=False)
    monkeypatch.delenv("NDS_QUERY_TIMEOUT", raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# taxonomy + registry units
# ---------------------------------------------------------------------------


def test_classify_taxonomy():
    assert faults.classify("RESOURCE_EXHAUSTED: out of HBM") == faults.DEVICE_OOM
    assert faults.classify(MemoryError()) == faults.HOST_OOM
    assert faults.classify("MemoryError") == faults.HOST_OOM
    assert faults.classify("query watchdog: exceeded budget") == faults.TIMEOUT
    assert faults.classify("OSError: Connection reset by peer") == faults.IO_TRANSIENT
    assert faults.classify(ConnectionResetError("x")) == faults.IO_TRANSIENT
    assert faults.classify("BindError: unknown column foo") == faults.PLANNER
    assert faults.classify("ExecError: bad plan") == faults.PLANNER
    assert faults.classify("ValueError: malformed stream file") == faults.DATA
    assert faults.classify("something else entirely") == faults.UNKNOWN
    # order: the watchdog marker must win over the io "timed out" pattern
    assert faults.classify("query watchdog: timed out") == faults.TIMEOUT
    # injected faults classify like their real counterparts even after the
    # report layer stringifies them
    assert (
        faults.classify("InjectedHostOOM: injected host OOM at 'q1'")
        == faults.HOST_OOM
    )
    # anchored transient patterns: a number or deterministic XLA error
    # containing "503"/"InternalError" must NOT look transient
    assert faults.classify("ValueError: shape (1503, 4) mismatch") == faults.UNKNOWN
    assert faults.classify("XlaRuntimeError: InternalError: crash") == faults.UNKNOWN
    assert faults.classify("HTTP 503 from object store") == faults.IO_TRANSIENT


def test_spec_parse_and_counts():
    r = faults.FaultRegistry.parse("oom:query5:2;io:store_sales;hang:q:30")
    assert [x.kind for x in r.rules] == ["oom", "io", "hang"]
    assert r.rules[0].remaining == 2
    assert r.rules[1].remaining == 1  # default count
    assert r.rules[2].remaining == 1  # hang fires once; arg is seconds
    assert r.rules[2].arg == 30
    # sites may contain ':' — a trailing segment is the arg only if numeric
    r2 = faults.FaultRegistry.parse("oom:exec:query3:2;io:commit:store_sales")
    assert (r2.rules[0].site, r2.rules[0].remaining) == ("exec:query3", 2)
    assert (r2.rules[1].site, r2.rules[1].remaining) == ("commit:store_sales", 1)
    with pytest.raises(ValueError, match="bad fault rule"):
        faults.FaultRegistry.parse("explode:query5")
    with pytest.raises(ValueError, match="bad fault rule"):
        faults.FaultRegistry.parse("oom")


def test_registry_fire_counts_and_kinds():
    faults.install("oom:a:1;io:b:2;crash:c")
    with pytest.raises(faults.InjectedOOM, match="RESOURCE_EXHAUSTED"):
        faults.maybe_fire("a")
    faults.maybe_fire("a")  # count exhausted -> inert
    for _ in range(2):
        with pytest.raises(faults.TransientIOError):
            faults.maybe_fire("b")
    faults.maybe_fire("b")
    with pytest.raises(faults.InjectedCrash):
        faults.maybe_fire("c")
    # crash derives from BaseException so `except Exception` can't eat it
    assert not issubclass(faults.InjectedCrash, Exception)


def test_fire_path_substring_match():
    faults.install("io:store_sales:1")
    with pytest.raises(faults.TransientIOError):
        faults.maybe_fire_path("/wh/store_sales/part-0.parquet")
    faults.maybe_fire_path("/wh/store_sales/part-1.parquet")  # exhausted
    faults.maybe_fire_path("/wh/item/part-0.parquet")  # never matched


def test_install_idempotent_keeps_counts():
    faults.install("oom:a:1")
    with pytest.raises(faults.InjectedOOM):
        faults.maybe_fire("a")
    # same spec re-installed (e.g. a second stream's Session): counts keep
    faults.install("oom:a:1")
    faults.maybe_fire("a")
    # a DIFFERENT spec rebuilds
    faults.install("oom:a:1;oom:z:1")
    with pytest.raises(faults.InjectedOOM):
        faults.maybe_fire("a")


def test_backoff_delays_jitter_bounds():
    ds = list(faults.backoff_delays(4, 0.5, cap=2.0))
    assert len(ds) == 4
    for i, d in enumerate(ds):
        assert 0 <= d <= min(0.5 * 2 ** i, 2.0)
    assert list(faults.backoff_delays(3, 0.0)) == [0.0, 0.0, 0.0]


# ---------------------------------------------------------------------------
# atomic writes + remote-open backoff
# ---------------------------------------------------------------------------


def test_fs_open_atomic_commit_and_discard(tmp_path):
    p = tmp_path / "sub" / "report.json"
    with fs_open_atomic(str(p), "w") as f:
        f.write('{"ok": 1}')
    assert json.load(open(p)) == {"ok": 1}
    # a crash mid-write must leave the previous complete content intact
    with pytest.raises(RuntimeError):
        with fs_open_atomic(str(p), "w") as f:
            f.write('{"torn"')
            raise RuntimeError("simulated crash mid-write")
    assert json.load(open(p)) == {"ok": 1}
    assert [x.name for x in p.parent.iterdir()] == ["report.json"]  # no tmp


def test_fs_open_atomic_remote(tmp_path):
    import fsspec

    url = "memory://atomic_test/report.csv"
    with fs_open_atomic(url, "w") as f:
        f.write("a,b\n1,2\n")
    with fs_open(url) as f:
        assert f.read() == "a,b\n1,2\n"
    fs = fsspec.filesystem("memory")
    assert not [p for p in fs.ls("/atomic_test") if ".tmp-" in str(p)]


def test_remote_open_retries_transient_faults(monkeypatch):
    import fsspec

    monkeypatch.setenv("NDS_IO_BACKOFF", "0")
    monkeypatch.setenv("NDS_IO_RETRIES", "3")
    fs = fsspec.filesystem("memory")
    with fs.open("/retry_test/data.txt", "w") as f:
        f.write("payload")
    faults.install("io:retry_test:2")
    with fs_open("memory://retry_test/data.txt") as f:  # 2 faults then opens
        assert f.read() == "payload"
    # budget exhausted -> the transient error surfaces
    faults.install("io:retry_test2:9")
    with fs.open("/retry_test2/data.txt", "w") as f:
        f.write("x")
    with pytest.raises(faults.TransientIOError):
        fs_open("memory://retry_test2/data.txt")


# ---------------------------------------------------------------------------
# the degradation ladder (BenchReport.report_on)
# ---------------------------------------------------------------------------


def _flaky(sequence):
    """fn failing with sequence[i] on call i (None = succeed)."""
    calls = {"n": 0}

    def fn():
        i = calls["n"]
        calls["n"] += 1
        err = sequence[i] if i < len(sequence) else None
        if err is not None:
            raise err

    fn.calls = calls
    return fn


def test_ladder_oom_recovers_once():
    sess = Session()
    fn = _flaky([faults.InjectedOOM("RESOURCE_EXHAUSTED: injected")])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert s["retries"] == 1
    assert [r["rung"] for r in s["ladder"]] == ["recover_retry"]
    assert len(s["exceptions"]) == 1 and "RESOURCE_EXHAUSTED" in s["exceptions"][0]
    assert "failureKind" not in s
    assert fn.calls["n"] == 2


def test_ladder_oom_exhausts_to_shrunken_window():
    sess = Session()
    oom = lambda: faults.InjectedOOM("RESOURCE_EXHAUSTED: injected")
    fn = _flaky([oom(), oom(), oom()])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["Failed"]
    assert s["failureKind"] == faults.DEVICE_OOM
    assert [r["rung"] for r in s["ladder"]] == [
        "recover_retry", "shrink_union_window",
    ]
    # the degraded blocked-union window persists on the session for the
    # rest of the stream
    assert int(sess.conf["engine.union_agg_window_rows"]) > 0
    assert s["retries"] == 2
    # EVERY attempt's error is recorded, not just the last one
    assert len(s["exceptions"]) == 3


def test_ladder_shrink_halves_explicit_window():
    sess = Session(conf={"engine.union_agg_window_rows": 65536})
    oom = lambda: faults.InjectedOOM("RESOURCE_EXHAUSTED: x")
    BenchReport(sess).report_on(_flaky([oom(), oom(), oom()]), retry_oom=True)
    assert sess.conf["engine.union_agg_window_rows"] == 32768


def test_ladder_host_oom_recovers():
    sess = Session()
    fn = _flaky([faults.InjectedHostOOM("injected host OOM at 'q1'")])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in s["ladder"]] == ["recover_retry"]
    # a second host OOM is terminal (no window shrink: the pressure is on
    # the host, not HBM)
    fn2 = _flaky([faults.InjectedHostOOM("injected host OOM at 'q1'")] * 2)
    s2 = BenchReport(sess).report_on(fn2, retry_oom=True)
    assert s2["queryStatus"] == ["Failed"]
    assert s2["failureKind"] == faults.HOST_OOM


def test_ladder_io_transient_backoff(monkeypatch):
    monkeypatch.setenv("NDS_IO_RETRIES", "2")
    monkeypatch.setenv("NDS_IO_BACKOFF", "0")
    sess = Session()
    fn = _flaky([faults.TransientIOError("injected transient io"),
                 faults.TransientIOError("injected transient io")])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["CompletedWithTaskFailures"]
    assert [r["rung"] for r in s["ladder"]] == [
        "io_backoff_retry", "io_backoff_retry",
    ]
    # a third transient failure would exhaust the 2-retry budget
    fn2 = _flaky([faults.TransientIOError("injected transient io")] * 3)
    s2 = BenchReport(sess).report_on(fn2, retry_oom=True)
    assert s2["queryStatus"] == ["Failed"]
    assert s2["failureKind"] == faults.IO_TRANSIENT


def test_ladder_deterministic_failures_never_retry():
    sess = Session()
    fn = _flaky([ValueError("BindError-ish nope"), None])
    s = BenchReport(sess).report_on(fn, retry_oom=True)
    assert s["queryStatus"] == ["Failed"]
    assert s["retries"] == 0
    assert fn.calls["n"] == 1  # exactly one attempt


def test_ladder_respects_non_idempotent_callers():
    sess = Session()
    fn = _flaky([faults.InjectedOOM("RESOURCE_EXHAUSTED: x"), None])
    s = BenchReport(sess).report_on(fn)  # DML tier: no retry_oom
    assert s["queryStatus"] == ["Failed"]
    assert s["retries"] == 0
    assert fn.calls["n"] == 1


def test_watchdog_timeout_classification():
    sess = Session(conf={"engine.query_timeout": "0.3"})

    def hang():
        time.sleep(3)

    t0 = time.time()
    s = BenchReport(sess).report_on(hang, retry_oom=True)
    elapsed = time.time() - t0
    assert s["queryStatus"] == ["Failed"]
    assert s["failureKind"] == faults.TIMEOUT
    assert s["retries"] == 0  # a hang would likely just hang again
    assert elapsed < 2.5  # the stream moved on well before the 3s hang ended


# ---------------------------------------------------------------------------
# stream-level integration: injected faults inside a real Power Run
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    # mini warehouse with only the tables the smoke stream touches: the
    # power driver's table setup eagerly reads every .dat dir it finds, and
    # these tests care about failure plumbing, not 25-table ingestion time
    mini = tmp_path_factory.mktemp("mini_wh")
    for t in ("store_sales", "date_dim"):
        os.symlink(os.path.join(DATA, t), mini / t)
    return str(mini)


STREAM = """-- start query 1 in stream 0 using template query96.tpl
select count(*) cnt from store_sales where ss_quantity > 0
;
-- end query 1 in stream 0 using template query96.tpl

-- start query 2 in stream 0 using template query3.tpl
select d_year, count(*) c from date_dim group by d_year order by d_year limit 5
;
-- end query 2 in stream 0 using template query3.tpl
"""


def _run_stream(data_dir, tmp_path, **kw):
    stream = tmp_path / "query_0.sql"
    stream.write_text(STREAM)
    jdir = tmp_path / "json"
    run_query_stream(
        input_prefix=data_dir,
        property_file=None,
        query_dict=gen_sql_from_stream(str(stream)),
        time_log_output_path=str(tmp_path / "time.csv"),
        input_format="csv",
        json_summary_folder=str(jdir),
        **kw,
    )
    out = {}
    for f in os.listdir(jdir):
        s = json.load(open(os.path.join(jdir, f)))
        out[s["query"]] = s
    return out


@pytest.mark.slow
def test_injected_oom_degrades_without_poisoning_stream(data_dir, tmp_path):
    """Acceptance: an injected OOM on one query walks the ladder, the query
    recovers, and the rest of the stream completes untouched."""
    faults.install("oom:query96:1")
    st = _run_stream(data_dir, tmp_path)
    assert st["query96"]["queryStatus"] == ["CompletedWithTaskFailures"]
    assert st["query96"]["retries"] == 1
    assert [r["rung"] for r in st["query96"]["ladder"]] == ["recover_retry"]
    assert any("RESOURCE_EXHAUSTED" in e for e in st["query96"]["exceptions"])
    assert st["query3"]["queryStatus"] == ["Completed"]
    assert st["query3"]["retries"] == 0


@pytest.mark.slow
def test_injected_persistent_oom_records_classified_failure(data_dir, tmp_path):
    faults.install("oom:query96:99")  # never stops OOMing
    st = _run_stream(data_dir, tmp_path)
    assert st["query96"]["queryStatus"] == ["Failed"]
    assert st["query96"]["failureKind"] == faults.DEVICE_OOM
    assert [r["rung"] for r in st["query96"]["ladder"]] == [
        "recover_retry", "shrink_union_window",
    ]
    assert st["query3"]["queryStatus"] == ["Completed"]  # stream unpoisoned


@pytest.mark.slow
def test_injected_hang_becomes_timeout_failure(data_dir, tmp_path):
    """Acceptance: a hung query becomes a classified `timeout` failure and
    the stream's remaining queries still run."""
    faults.install("hang:query96:30")
    st = _run_stream(data_dir, tmp_path, query_timeout=6.0)
    assert st["query96"]["queryStatus"] == ["Failed"]
    assert st["query96"]["failureKind"] == faults.TIMEOUT
    assert st["query3"]["queryStatus"] == ["Completed"]
    # the watchdog cut query96 off at ~6s instead of the 30s hang
    assert st["query96"]["queryTimes"][0] < 15000


@pytest.mark.slow
def test_exec_scoped_injection_site(data_dir, tmp_path):
    """exec:<query> faults fire at the executor root, past parse/bind —
    the engine-internal injection point."""
    faults.install("oom:exec:query3:1")
    st = _run_stream(data_dir, tmp_path)
    assert st["query3"]["queryStatus"] == ["CompletedWithTaskFailures"]
    assert st["query3"]["retries"] == 1
    assert st["query96"]["queryStatus"] == ["Completed"]


def test_gen_sql_malformed_stream_entry(tmp_path):
    p = tmp_path / "query_0.sql"
    p.write_text(
        "-- start query 1 in stream 0 using template query42.tpl\n"
        "select 1 as a\n"  # no ';' terminator
    )
    with pytest.raises(ValueError, match="malformed stream file.*query42"):
        gen_sql_from_stream(str(p))


# ---------------------------------------------------------------------------
# checkpointed full_bench resume
# ---------------------------------------------------------------------------


def _stub_phases(monkeypatch, tmp_path, calls):
    """Replace every phase runner with a fake that writes the report files
    the parsers re-read, so orchestrator logic (checkpoint/resume/retry/
    metric math) runs for real without subprocess phases."""

    def note(name):
        calls.append(name)

    def fake_load(params):
        note("load_test")
        with open(params["load_test"]["report_path"], "w") as f:
            f.write("Load Test Time: 10.0 seconds\nRNGSEED used: 123\n")

    def fake_power(params):
        note("power_test")
        with open(params["power_test"]["report_path"], "w") as f:
            f.write("app-1,Power Test Time,60000\n")

    def fake_tt(params, num_streams, which):
        note(f"throughput_test_{which}")
        for n in FB.get_stream_range(num_streams, which):
            with open(f"{params['throughput_test']['report_base_path']}_{n}.csv", "w") as f:
                f.write("app,Power Start Time,100\napp,Power End Time,200\n")

    def fake_dm(params, num_streams, which):
        note(f"maintenance_test_{which}")
        for n in FB.get_stream_range(num_streams, which):
            base = params["maintenance_test"]["maintenance_report_base_path"]
            with open(f"{base}_{n}.csv", "w") as f:
                f.write("app,Data Maintenance Time,30\n")

    monkeypatch.setattr(FB, "run_data_gen", lambda p, n: note("data_gen"))
    monkeypatch.setattr(FB, "run_load_test", fake_load)
    monkeypatch.setattr(FB, "gen_streams", lambda p, n, s: note("gen_streams"))
    monkeypatch.setattr(FB, "power_test", fake_power)
    monkeypatch.setattr(FB, "throughput_test", fake_tt)
    monkeypatch.setattr(FB, "maintenance_test", fake_dm)


def _bench_params(tmp_path):
    return {
        "data_gen": {"scale_factor": 1, "parallel": 2,
                     "raw_data_path": str(tmp_path / "raw")},
        "load_test": {"output_path": str(tmp_path / "wh"),
                      "report_path": str(tmp_path / "load.txt")},
        "generate_query_stream": {"num_streams": 3,
                                  "stream_output_path": str(tmp_path / "st")},
        "power_test": {"report_path": str(tmp_path / "power.csv")},
        "throughput_test": {"report_base_path": str(tmp_path / "tt")},
        "maintenance_test": {
            "maintenance_report_base_path": str(tmp_path / "dm")},
        "metrics_report_path": str(tmp_path / "metrics.csv"),
    }


def test_full_bench_crash_then_resume_completes(monkeypatch, tmp_path):
    """Acceptance: with a crash:power_test injection the orchestrator dies
    at its checkpoint; --resume finishes from it, completed phases never
    re-run, and metrics.csv matches an uninterrupted run."""
    calls = []
    _stub_phases(monkeypatch, tmp_path, calls)
    params = _bench_params(tmp_path)
    faults.install("crash:power_test")
    with pytest.raises(faults.InjectedCrash):
        FB.run_full_bench(params)
    state_file = tmp_path / "bench_state.json"
    assert state_file.exists()
    done = set(json.load(open(state_file))["phases"])
    assert done == {"data_gen", "load_test", "gen_streams"}
    assert not (tmp_path / "metrics.csv").exists()

    # operator reruns with --resume (fault spec cleared)
    faults.reset()
    calls.clear()
    metrics = FB.run_full_bench(params, resume=True)
    # checkpointed phases were NOT re-run; the rest ran exactly once
    assert calls == ["power_test", "throughput_test_1", "maintenance_test_1",
                     "throughput_test_2", "maintenance_test_2"]
    assert metrics["perf_metric"] > 0

    # identical to an uninterrupted run over the same (stubbed) phase times
    clean = tmp_path / "clean"
    clean.mkdir()
    calls.clear()
    m2 = FB.run_full_bench(_bench_params(clean))
    assert m2["perf_metric"] == metrics["perf_metric"]
    got = (tmp_path / "metrics.csv").read_text()
    want = (clean / "metrics.csv").read_text()
    assert {l.split(",")[0]: l for l in got.splitlines()} == {
        l.split(",")[0]: l for l in want.splitlines()
    }


def test_full_bench_phase_transient_retry(monkeypatch, tmp_path):
    """A classified-transient phase failure retries within budget instead
    of killing the run."""
    calls = []
    _stub_phases(monkeypatch, tmp_path, calls)
    monkeypatch.setenv("NDS_PHASE_RETRIES", "2")
    monkeypatch.setenv("NDS_PHASE_BACKOFF", "0")
    params = _bench_params(tmp_path)
    faults.install("io:power_test:2")  # fails twice, third attempt clean
    metrics = FB.run_full_bench(params)
    assert metrics["perf_metric"] > 0
    assert calls.count("power_test") == 1  # faults fired before the runner
    state = json.load(open(tmp_path / "bench_state.json"))
    assert "power_test" in state["phases"]


def test_full_bench_phase_deterministic_failure_no_retry(monkeypatch, tmp_path):
    calls = []
    _stub_phases(monkeypatch, tmp_path, calls)
    monkeypatch.setenv("NDS_PHASE_RETRIES", "3")

    def boom(params):
        calls.append("power_test")
        raise RuntimeError("query produced wrong answer")  # not transient

    monkeypatch.setattr(FB, "power_test", boom)
    with pytest.raises(FB.PhaseError, match="power_test.*unknown"):
        FB.run_full_bench(_bench_params(tmp_path))
    assert calls.count("power_test") == 1


def test_bench_state_fingerprint_mismatch(monkeypatch, tmp_path):
    calls = []
    _stub_phases(monkeypatch, tmp_path, calls)
    params = _bench_params(tmp_path)
    FB.run_full_bench(params)
    params2 = dict(params, metrics_report_path=str(tmp_path / "metrics.csv"))
    params2["data_gen"] = dict(params["data_gen"], scale_factor=100)
    with pytest.raises(ValueError, match="different.*config"):
        FB.run_full_bench(params2, resume=True)


def test_bench_state_resume_without_checkpoint(monkeypatch, tmp_path):
    calls = []
    _stub_phases(monkeypatch, tmp_path, calls)
    metrics = FB.run_full_bench(_bench_params(tmp_path), resume=True)
    assert metrics["perf_metric"] > 0  # missing checkpoint == fresh run


# ---------------------------------------------------------------------------
# process-mode stream watchdog budget
# ---------------------------------------------------------------------------


def test_stream_wait_budget(monkeypatch):
    from nds_tpu.throughput import stream_wait_budget

    monkeypatch.delenv("NDS_STREAM_TIMEOUT", raising=False)
    monkeypatch.delenv("NDS_QUERY_TIMEOUT", raising=False)
    assert stream_wait_budget() is None  # unbounded by default
    assert stream_wait_budget(query_timeout=10, n_queries=5) == 10 * 5 + 600
    monkeypatch.setenv("NDS_QUERY_TIMEOUT", "2")
    assert stream_wait_budget(n_queries=103) == 2 * 103 + 600
    monkeypatch.setenv("NDS_STREAM_TIMEOUT", "42")
    assert stream_wait_budget(query_timeout=10) == 42
