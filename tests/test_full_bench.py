"""Whole-benchmark orchestrator: metric math, report parsing, stream
ranges, and a full 8-phase end-to-end run at SF0.01 producing metrics.csv
(reference: nds/nds_bench.py:334-357 metric, :367-497 phase sequencing)."""

import os
import subprocess
import sys

import pytest

from nds_tpu import full_bench as FB

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fast, engine-friendly queries for the smoke streams (real templates are
# exercised by test_query_streams; here the orchestrator is under test)
SMOKE_QUERY = """
select d_year, count(*) c from store_sales, date_dim
where ss_sold_date_sk = d_date_sk group by d_year order by d_year
"""


def test_stream_range():
    assert FB.get_stream_range(9, 1) == [1, 2, 3, 4]
    assert FB.get_stream_range(9, 2) == [5, 6, 7, 8]
    assert FB.get_stream_range(3, 1) == [1]
    assert FB.get_stream_range(3, 2) == [2]
    assert FB.get_throughput_stream_nums(9, 2) == "5,6,7,8"


def test_perf_metric_matches_formula():
    # SF=1, Sq=2: Q=198; all phase times 3600s -> each factor in hours
    m = FB.get_perf_metric(1, 2, 3600, 1800, 900, 900, 450, 450, )
    tpt = (1800 * 2) / 3600
    ttt = (900 + 900) / 3600
    tdm = (450 + 450) / 3600
    tld = (0.01 * 2 * 3600) / 3600
    assert m == int(1 * 198 / (tpt * ttt * tdm * tld) ** 0.25)


def test_report_parsers(tmp_path):
    load = tmp_path / "load.txt"
    load.write_text(
        "Load Test Time: 12.5 seconds\n"
        "Load Test Finished at: 2026-01-01\n"
        "RNGSEED used: 07300207223\n"
    )
    assert FB.get_load_time(str(load)) == 12.5
    assert FB.get_load_end_timestamp(str(load)) == 7300207223
    power = tmp_path / "power.csv"
    power.write_text(
        "application_id,query,time/milliseconds\n"
        "app-1,query1,100\n"
        "app-1,Power Test Time,12345\n"
    )
    assert FB.get_power_time(str(power)) == 12.4
    dm = tmp_path / "dm_1.csv"
    dm.write_text("app-1,Data Maintenance Time,7.5\n")
    assert FB.get_refresh_time(str(dm)) == 7.5
    assert FB.get_maintenance_time(str(tmp_path / "dm"), 3, 1) == 7.5


def test_num_streams_must_be_odd():
    with pytest.raises(ValueError):
        FB.run_full_bench({"generate_query_stream": {"num_streams": 4}})


@pytest.fixture(scope="module")
def data_dir():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


def _write_stream(path, n_queries=2):
    parts = []
    for i in range(n_queries):
        parts.append(
            f"-- start query {i + 1} in stream 0 using template query3.tpl\n"
            f"{SMOKE_QUERY}\n;\n"
            f"-- end query {i + 1} in stream 0 using template query3.tpl\n"
        )
    with open(path, "w") as f:
        f.write("\n".join(parts))


def test_full_bench_end_to_end(data_dir, tmp_path, monkeypatch):
    """All 8 phases through the real CLIs (subprocess boundaries), metric
    printed and written to metrics.csv."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    num_streams = 3
    for i in (1, 2):
        upd = f"{data_dir}_update{i}"
        if not os.path.isdir(upd):
            subprocess.run(
                [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale",
                 "0.01", "--parallel", "2", "--data_dir", upd,
                 "--update", str(i), "--overwrite_output"],
                check=True, capture_output=True, cwd=REPO,
            )
    streams = tmp_path / "streams"
    streams.mkdir()
    for n in range(num_streams):
        _write_stream(streams / f"query_{n}.sql")
    params = {
        "data_gen": {
            "scale_factor": 0.01, "parallel": 2,
            "raw_data_path": data_dir, "skip": True,
        },
        "load_test": {
            "output_path": str(tmp_path / "warehouse"),
            "warehouse_format": "lakehouse",
            "report_path": str(tmp_path / "load.txt"),
            "skip": False,
        },
        "generate_query_stream": {
            "num_streams": num_streams,
            "query_template_dir": None,
            "stream_output_path": str(streams),
            "skip": True,  # hand-written smoke streams above
        },
        "power_test": {
            "report_path": str(tmp_path / "power.csv"),
            "property_path": None,
            "output_path": None,
            "skip": False,
        },
        "throughput_test": {
            "report_base_path": str(tmp_path / "throughput"),
            "skip": False,
        },
        "maintenance_test": {
            "maintenance_report_base_path": str(tmp_path / "maintenance"),
            # all 11 functions run in test_maintenance; 2 keep this fast
            "maintenance_queries": "LF_SS,DF_SS",
            "skip": False,
        },
        "metrics_report_path": str(tmp_path / "metrics.csv"),
    }
    monkeypatch.chdir(REPO)
    metrics = FB.run_full_bench(params)
    assert metrics["perf_metric"] > 0
    assert os.path.exists(tmp_path / "metrics.csv")
    content = (tmp_path / "metrics.csv").read_text()
    assert "perf_metric" in content
    # skip/resume: re-run with every phase skipped; times re-read from the
    # report files on disk produce the same metric
    for phase in ("load_test", "power_test", "throughput_test",
                  "maintenance_test"):
        params[phase]["skip"] = True
    metrics2 = FB.run_full_bench(params)
    assert metrics2["perf_metric"] == metrics["perf_metric"]


def test_full_bench_real_generated_streams(data_dir, tmp_path, monkeypatch):
    """The pipeline with REAL generated streams (VERDICT r3 #4): stream
    generation runs for real (skip=False), and the power + throughput
    phases consume the generated stream files (a fast template subset via
    sub_queries), so stream-file -> power-driver integration (template
    ordering, the two-part query14/23/24/39 split) is exercised outside
    the timed bench (reference: nds/nds_bench.py:249-304)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    num_streams = 3
    for i in (1, 2):
        upd = f"{data_dir}_update{i}"
        if not os.path.isdir(upd):
            subprocess.run(
                [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale",
                 "0.01", "--parallel", "2", "--data_dir", upd,
                 "--update", str(i), "--overwrite_output"],
                check=True, capture_output=True, cwd=REPO,
            )
    subset = ("query3,query7,query12,query15,query19,query26,query42,"
              "query52,query96,query14_part1")
    params = {
        "data_gen": {
            "scale_factor": 0.01, "parallel": 2,
            "raw_data_path": data_dir, "skip": True,
        },
        "load_test": {
            "output_path": str(tmp_path / "warehouse"),
            "warehouse_format": "lakehouse",
            "report_path": str(tmp_path / "load.txt"),
            "skip": False,
        },
        "generate_query_stream": {
            "num_streams": num_streams,
            "query_template_dir": None,
            "stream_output_path": str(tmp_path / "streams"),
            "skip": False,  # REAL stream generation under test
        },
        "power_test": {
            "report_path": str(tmp_path / "power.csv"),
            "property_path": None,
            "output_path": None,
            "sub_queries": subset,
            "skip": False,
        },
        "throughput_test": {
            "report_base_path": str(tmp_path / "throughput"),
            "sub_queries": subset,
            "skip": False,
        },
        "maintenance_test": {
            "maintenance_report_base_path": str(tmp_path / "maintenance"),
            "maintenance_queries": "LF_SS,DF_SS",
            "skip": False,
        },
        "metrics_report_path": str(tmp_path / "metrics.csv"),
    }
    monkeypatch.chdir(REPO)
    metrics = FB.run_full_bench(params)
    assert metrics["perf_metric"] > 0
    # the generated stream files are real 99-template permutations (the
    # two-part templates split into _part1/_part2 at parse time)
    stream0 = (tmp_path / "streams" / "query_0.sql").read_text()
    assert stream0.count("-- start query") == 99
    assert "query14_part1" not in stream0  # parts carry the template name
    # power consumed the generated stream: its log holds the subset queries
    power_log = (tmp_path / "power.csv").read_text()
    for q in ("query3", "query96", "query14_part1"):
        assert q in power_log
