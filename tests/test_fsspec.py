"""Shared-filesystem seam: the full transcode -> power -> maintenance cycle
against a non-local (memory://) warehouse URL.

The reference reaches HDFS/S3/GS in every phase (nds/nds_gen_data.py:130-180;
nds/nds_power.py:296-299 writes the extra time log via Spark precisely so it
can land on cloud storage). Here every phase exercises fsspec through
io/fs.py: lakehouse create/append/delete/rollback, stream-file reads, and
time-log/report writes all target memory:// paths.
"""

import os
import subprocess
import sys

import pytest

from nds_tpu.engine.session import Session
from nds_tpu.lakehouse.table import LakehouseTable
from nds_tpu.schema import get_schemas
from nds_tpu.transcode import transcode_table

DATA = "/tmp/nds_test_sf001"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TABLES = ("store_sales", "date_dim", "item")


@pytest.fixture(scope="module")
def raw_data():
    if not os.path.exists(os.path.join(DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", DATA, "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(DATA, ".complete"), "w").close()
    return DATA


@pytest.fixture(scope="module")
def mem_warehouse(raw_data):
    """Transcode three tables into a memory:// lakehouse warehouse."""
    wh = "memory://fsspec_wh"
    for t in TABLES:
        transcode_table(
            raw_data, wh, t, get_schemas()[t], output_format="lakehouse",
            output_mode="overwrite",
        )
    return wh


def test_remote_plain_formats_rejected(raw_data):
    with pytest.raises(ValueError, match="lakehouse"):
        transcode_table(
            raw_data, "memory://nope", "item", get_schemas()["item"],
            output_format="parquet", output_mode="overwrite",
        )


def test_transcode_then_power_on_memory_url(mem_warehouse, tmp_path):
    from nds_tpu.power import gen_sql_from_stream, run_query_stream

    # stream file itself on memory://
    from nds_tpu.io.fs import fs_open

    stream_url = "memory://streams/query_0.sql"
    q = (
        "select d_year, count(*) c, sum(ss_ext_sales_price) s\n"
        "from store_sales, date_dim where ss_sold_date_sk = d_date_sk\n"
        "group by d_year order by d_year\n"
    )
    with fs_open(stream_url, "w") as f:
        f.write(
            "-- start query 1 in stream 0 using template query3.tpl\n"
            f"{q};\n"
            "-- end query 1 in stream 0 using template query3.tpl\n"
        )
    queries = gen_sql_from_stream(stream_url)
    assert len(queries) == 1

    time_log_url = "memory://logs/time.csv"
    run_query_stream(
        mem_warehouse,
        None,
        queries,
        time_log_url,
        input_format="lakehouse",
        json_summary_folder=str(tmp_path / "summaries"),
    )
    with fs_open(time_log_url) as f:
        log = f.read()
    # query named after its template (reference stream-file contract)
    assert "query3" in log and "Power Test Time" in log


def test_maintenance_cycle_on_memory_url(mem_warehouse):
    """INSERT + copy-on-write DELETE + timestamp rollback on memory://."""
    import pyarrow as pa

    t = LakehouseTable(f"{mem_warehouse}/store_sales")
    rows0 = t.num_rows()
    v0 = t.current_version()
    ts0 = t._manifest(v0)["timestamp_ms"]

    sess = Session()
    sess.register_lakehouse("store_sales", f"{mem_warehouse}/store_sales")

    # INSERT (LF_SS shape): append a copy of 5 rows
    sample = t.dataset().head(5)
    t.append(sample)
    assert LakehouseTable(f"{mem_warehouse}/store_sales").num_rows() == rows0 + 5

    # DELETE (DF_SS shape): copy-on-write delete of a date range
    ds = t.dataset()
    lo = ds.head(1).column("ss_sold_date_sk")[0].as_py()
    kept = ds.to_table().filter(
        pa.compute.field("ss_sold_date_sk") != lo
    )
    t.replace(kept, operation="delete")
    assert LakehouseTable(f"{mem_warehouse}/store_sales").num_rows() == kept.num_rows

    # rollback to the pre-maintenance snapshot (nds_rollback.py semantics)
    t.rollback_to_timestamp(ts0)
    assert LakehouseTable(f"{mem_warehouse}/store_sales").num_rows() == rows0

    # and the engine reads the rolled-back snapshot
    sess2 = Session()
    sess2.register_lakehouse("store_sales", f"{mem_warehouse}/store_sales")
    out = sess2.sql("select count(*) c from store_sales").to_pylist()
    assert out[0]["c"] == rows0
