"""Fleet-catalog tests (lakehouse/catalog.py): commit arbitration over
both backends, epoch fencing, cross-host lease visibility, coordinator
WAL recovery and crash-mid-commit exactly-once, graceful degradation
when the coordinator is unreachable, the two-PROCESS writer conflict
oracle, heartbeat lease renewal, the manifest-write-seam lint rule, and
the catalog observability surface (events, metrics, /statusz)."""

import json
import os
import posixpath
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

from nds_tpu import faults
from nds_tpu.analysis import lint as L
from nds_tpu.lakehouse import catalog as C
from nds_tpu.lakehouse import table as TBL
from nds_tpu.lakehouse.leases import LEASES
from nds_tpu.lakehouse.table import (
    CommitConflictError,
    LakehouseTable,
)
from nds_tpu.obs import metrics as M
from nds_tpu.obs import trace as obs_trace
from nds_tpu.obs.trace import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENV_KEYS = (
    "NDS_LAKE_CATALOG", "NDS_LAKE_COMMIT_BACKOFF", "NDS_LAKE_WRITER_TTL_S",
    "NDS_LAKE_CATALOG_POLL_S", "NDS_LAKE_CATALOG_TIMEOUT_S",
    "NDS_LAKE_LEASE_TTL_S", "NDS_HEARTBEAT_INTERVAL_MS",
    "NDS_LAKE_COMMIT_RETRIES",
)


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    C.reset_clients()
    M.reset_shared()
    os.environ["NDS_LAKE_COMMIT_BACKOFF"] = "0"
    yield
    faults.reset()
    C.reset_clients()
    M.reset_shared()
    for k in _ENV_KEYS:
        os.environ.pop(k, None)


def _ints(*vals):
    return pa.table({"a": pa.array(list(vals), type=pa.int64())})


def _vals(path):
    return sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )


def _versions(path):
    return [v for v, _, _ in LakehouseTable(path).versions()]


def _make_fs_table(tmp_path, *vals):
    os.environ["NDS_LAKE_CATALOG"] = "fs"
    C.reset_clients()
    path = str(tmp_path / "t")
    return LakehouseTable.create(path, _ints(*vals)), path


def _start_coordinator(tracer=None):
    """In-process coordinator behind a real ephemeral listener (the same
    obs/httpserv.py seam production uses). Returns (coordinator, server,
    url)."""
    from nds_tpu.obs.httpserv import MetricsServer
    from nds_tpu.obs.metrics import MetricsSink

    server = MetricsServer(MetricsSink(), 0, host="127.0.0.1")
    coord = C.CatalogCoordinator(tracer=tracer)
    server.attach_app(coord)
    server.start()
    return coord, server, f"http://127.0.0.1:{server.port}"


# ---------------------------------------------------------------------------
# fs backend: commits, epochs, fencing
# ---------------------------------------------------------------------------


def test_fs_catalog_commit_roundtrip_and_epoch_names(tmp_path):
    lt, path = _make_fs_table(tmp_path, 1, 2)
    lt2 = LakehouseTable(path)
    lt2.append(_ints(3))
    assert _vals(path) == [1, 2, 3]
    assert _versions(path) == [1, 2]
    # staged names carry the fencing epoch and still match the generic
    # data-file scheme (old readers keep reading them)
    names = sorted(os.listdir(os.path.join(path, "data")))
    assert all(TBL._DATA_FILE_RE.match(n) for n in names)
    assert all("-e" in n for n in names)
    m = TBL._STAGED_RE.match(names[0])
    assert m is not None and m.group(2) is not None
    # catalog state lives NEXT to the manifests, not inside them
    assert os.path.isdir(os.path.join(path, "_catalog"))


def test_fs_catalog_conflict_matrix_preserved(tmp_path):
    """Append/append rebase and overwrite abort behave exactly as the
    legacy path — the catalog arbitrates the same OCC matrix."""
    lt, path = _make_fs_table(tmp_path, 0)

    def land_append(name, op, version):
        TBL._COMMIT_HOOK = None
        LakehouseTable(path).append(_ints(100))

    TBL._COMMIT_HOOK = land_append
    try:
        LakehouseTable(path).append(_ints(200))
    finally:
        TBL._COMMIT_HOOK = None
    assert _vals(path) == [0, 100, 200]

    def land_replace(name, op, version):
        TBL._COMMIT_HOOK = None
        LakehouseTable(path).replace(_ints(77))

    TBL._COMMIT_HOOK = land_replace
    try:
        with pytest.raises(CommitConflictError):
            LakehouseTable(path).replace(_ints(88))
    finally:
        TBL._COMMIT_HOOK = None
    assert _vals(path) == [77]


def test_fence_advances_past_dead_writers_only(tmp_path):
    lt, path = _make_fs_table(tmp_path, 1)
    cat = lt.catalog
    live = cat.writer_register(lt, ttl_s=60)
    dead = cat.writer_register(lt, ttl_s=0.01)
    time.sleep(0.05)
    fence = cat.bump_fence(lt)
    # the live writer's epoch is protected; the dead one is fenceable
    assert fence <= live["epoch"]
    assert fence == live["epoch"]  # min over live epochs
    # with no live writers at all the fence passes every issued epoch
    cat.writer_renew(lt, live, 0.0)
    fence2 = cat.bump_fence(lt)
    assert fence2 > live["epoch"] and fence2 > dead["epoch"]


def test_fenced_zombie_never_publishes_and_stage_is_collected(tmp_path):
    """The epoch-fencing acceptance: a writer whose lease expired (zombie)
    loses its never-referenced stage to vacuum AND has its eventual
    publish refused — on a REMOTE-mode warehouse where pid liveness is
    meaningless."""
    lt, path = _make_fs_table(tmp_path, 1, 2)
    os.environ["NDS_LAKE_WRITER_TTL_S"] = "0.05"
    zombie = LakehouseTable(path)
    staged = zombie._stage(_ints(99))  # registers epoch, writes the stage
    stage_name = posixpath.basename(staged[0][0])
    time.sleep(0.1)  # writer lease expires: zombie presumption
    orig = LakehouseTable._is_local
    LakehouseTable._is_local = lambda self: False  # remote-mode warehouse
    try:
        os.environ.pop("NDS_LAKE_WRITER_TTL_S")
        res = LakehouseTable(path).vacuum(retain_last=2)
        assert stage_name not in os.listdir(os.path.join(path, "data"))
        assert posixpath.join("data", stage_name) in res["removed"]
        # the zombie's publish is refused (classified commit_conflict)
        with pytest.raises(CommitConflictError) as ei:
            zombie._commit(staged, "append")
        assert faults.classify(ei.value) == faults.COMMIT_CONFLICT
    finally:
        LakehouseTable._is_local = orig
    # nothing committed was harmed
    assert _vals(path) == [1, 2]


def test_vacuum_never_deletes_under_remote_host_lease(tmp_path):
    """The cross-host lease acceptance: with `_is_local() == False` a
    lease registered by ANOTHER process/host (catalog state only — this
    process's in-process lease table knows nothing about it) keeps its
    files through vacuum until released."""
    lt, path = _make_fs_table(tmp_path, *range(5))
    snap1 = lt.snapshot(1)
    # "another host": a bare catalog client, bypassing the local table
    other = C.FsCatalog()
    remote = other.lease_acquire(
        C._TableRef(path), 1, snap1.rel_files, ttl_s=60
    )
    assert remote is not None
    LakehouseTable(path).replace(_ints(9))
    orig = LakehouseTable._is_local
    LakehouseTable._is_local = lambda self: False
    try:
        # the leased VERSION keeps its manifest through expiry, so its
        # files stay referenced — nothing removed
        res = LakehouseTable(path).vacuum(retain_last=1)
        assert res["files_removed"] == 0
        assert os.path.exists(os.path.join(path, "_manifests",
                                           "v000001.json"))
        # even with the manifest forcibly gone, the remote lease's FILE
        # list still protects the data (the deeper layer of the contract)
        os.unlink(os.path.join(path, "_manifests", "v000001.json"))
        res = LakehouseTable(path).vacuum(retain_last=1)
        assert res["files_removed"] == 0 and res["files_leased"] >= 1
        for f in snap1.files():
            assert os.path.exists(f)
        remote.release()
        res2 = LakehouseTable(path).vacuum(retain_last=1)
        assert posixpath.basename(snap1.rel_files[0]) in {
            posixpath.basename(r) for r in res2["removed"]
        }
    finally:
        LakehouseTable._is_local = orig


def test_catalog_lease_ttl_and_sweep(tmp_path):
    lt, path = _make_fs_table(tmp_path, 1)
    cat = lt.catalog
    snap = lt.snapshot()
    remote = cat.lease_acquire(lt, snap.version, snap.rel_files, ttl_s=0.05)
    assert cat.held_files(lt) == set(snap.rel_files)
    assert cat.held_versions(lt) == {1}
    time.sleep(0.1)
    assert cat.held_files(lt) == set()
    assert cat.sweep_expired(lt) == 1
    # renew after expiry fails (caller re-acquires)
    assert remote.renew(60) is False


def test_session_pin_writes_through_to_catalog(tmp_path):
    """pin_lakehouse registers the lease locally AND in the catalog, so
    another host's vacuum sees it; releasing the pin releases both."""
    jax = pytest.importorskip("jax")  # noqa: F841 (session needs jax)
    from nds_tpu.engine.session import Session

    lt, path = _make_fs_table(tmp_path, 1, 2, 3)
    s = Session(conf={"engine.lake_catalog": "fs"})
    s.register_lakehouse("t", path)
    s.sql("select count(*) c from t").collect()
    cat = C.FsCatalog()
    ref = C._TableRef(path)
    assert cat.held_versions(ref) == {1}
    s.catalog.invalidate("t")  # releases the pin -> both halves
    assert cat.held_versions(ref) == set()


# ---------------------------------------------------------------------------
# tcp backend: coordinator
# ---------------------------------------------------------------------------


def test_coordinator_commit_lease_fence_roundtrip(tmp_path):
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        os.environ["NDS_LAKE_CATALOG"] = url
        C.reset_clients()
        t = LakehouseTable(path)
        assert t.catalog.backend == "tcp"
        t.append(_ints(2))
        t.append(_ints(3))
        assert _vals(path) == [1, 2, 3]
        assert _versions(path) == [1, 2, 3]
        # manifest carries the coordinator-stamped txid
        with open(os.path.join(path, "_manifests", "v000003.json")) as fh:
            assert json.load(fh).get("txid")
        snap = t.snapshot()
        lease = t.acquire_reader_lease(snap, 60)
        assert len(t._held_files()) == len(snap.rel_files)
        t.replace(_ints(9))
        assert t.vacuum(retain_last=1)["files_removed"] == 0
        LEASES.release(lease)  # forwards to the coordinator half
        assert t.vacuum(retain_last=1)["files_removed"] >= 1
        assert _vals(path) == [9]
    finally:
        server.stop()


def test_coordinator_releases_writer_epochs_for_fencing(tmp_path):
    """_release_writer sends ttl 0 over the wire: the coordinator must
    honor it (0 is a VALUE, not an absent field), so published writers'
    epochs stop pinning the fence on the tcp backend."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        os.environ["NDS_LAKE_CATALOG"] = url
        C.reset_clients()
        t = LakehouseTable(path)
        t.append(_ints(2))  # registers epoch, publishes, releases writer
        last_epoch = t.catalog.read_fence(t)  # may still be 0
        fence = t.catalog.bump_fence(t)
        # no live writers remain, so the fence passes every issued epoch
        assert fence >= 1 and fence > last_epoch
        # and a NEW transaction still works (fresh registration)
        LakehouseTable(path).append(_ints(3))
        assert _vals(path) == [1, 2, 3]
    finally:
        server.stop()


def test_slow_coordinator_refuses_publish_past_client_deadline(tmp_path):
    """The double-apply guard: a coordinator that is merely SLOW (hang
    fault holds it inside the commit critical section) past the client's
    timeout + poll budget must NOT complete the publish later — the
    client has already classified the commit failed-retryable, and its
    re-run would otherwise land the rows twice."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        os.environ["NDS_LAKE_CATALOG"] = url
        os.environ["NDS_LAKE_CATALOG_TIMEOUT_S"] = "0.4"
        os.environ["NDS_LAKE_CATALOG_POLL_S"] = "0.2"
        C.reset_clients()
        t = LakehouseTable(path)
        # a 1.5s stall inside the commit critical section (between WAL
        # intent and publish) outlives timeout (0.4s) + poll (0.2s): the
        # client gives up while the coordinator is still in flight. (A
        # subprocess coordinator would take the hang fault here — see
        # tools/catalog_check.py; in-process the registry is shared, so
        # the stall is injected directly.)
        orig_commit = coord._fs.commit
        stalled = {"n": 0}

        def slow_commit(*a, **kw):
            stalled["n"] += 1
            time.sleep(1.5)
            return orig_commit(*a, **kw)

        coord._fs.commit = slow_commit
        try:
            with pytest.raises(C.CatalogUnreachableError):
                t.append(_ints(2))
        finally:
            coord._fs.commit = orig_commit
        # let the stalled commit finish: its publish must be REFUSED
        time.sleep(1.8)
        assert stalled["n"] == 1
        assert _versions(path) == [1]
        # the retried transaction lands exactly once
        LakehouseTable(path).append(_ints(2))
        assert _vals(path) == [1, 2]
        assert _versions(path) == [1, 2]
    finally:
        server.stop()


def test_coordinator_wal_recovery_rolls_back_unpublished(tmp_path):
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        ref = coord._ref(path)
        # a published intent (manifest exists) -> pruned
        coord._fs._write_json(ref, "wal/txdone.json", {
            "version": 1, "txid": "txdone",
        })
        # an unpublished intent (no manifest) -> rolled back, because it
        # was never acknowledged and replay would double-apply
        coord._fs._write_json(ref, "wal/txlost.json", {
            "version": 7, "txid": "txlost",
        })
        rep = coord.recover(path)
        assert rep["pruned"] == 1 and rep["rolled_back"] == 1
        assert coord._fs._ls(ref, "wal") == []
        assert _versions(path) == [1]  # head untouched, nothing torn
    finally:
        server.stop()


def test_coordinator_idempotent_txid_replay(tmp_path):
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        client = C.HttpCatalog(url)
        manifest = {
            "version": 2, "timestamp_ms": 1, "operation": "append",
            "files": [], "num_rows": 0, "schema_hex": None,
        }
        r1 = client._post("/catalog/commit", {
            "root": path, "manifest": manifest, "epoch": None,
            "txid": "tx-same",
        })
        # the retry of an ambiguous send: same txid -> idempotent success,
        # no duplicate version burned
        r2 = client._post("/catalog/commit", {
            "root": path, "manifest": manifest, "epoch": None,
            "txid": "tx-same",
        })
        assert r1 == {"published": True, "version": 2}
        assert r2 == {"published": True, "version": 2}
        assert _versions(path) == [1, 2]
    finally:
        server.stop()


def test_coordinator_crash_mid_commit_exactly_once(tmp_path):
    """The chaos acceptance, in-process: the coordinator dies BETWEEN the
    WAL intent and the manifest publish (crash fault at catalog:commit —
    the client-side tcp path only fires io/hang there, so the rule lands
    on the coordinator). The client classifies the loss retryable,
    recovery rolls the intent back, and the retried transaction lands
    its rows EXACTLY once with a linear history and no torn manifest."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1))
    coord, server, url = _start_coordinator()
    try:
        os.environ["NDS_LAKE_CATALOG"] = url
        os.environ["NDS_LAKE_CATALOG_TIMEOUT_S"] = "2"
        os.environ["NDS_LAKE_CATALOG_POLL_S"] = "0.2"
        C.reset_clients()
        t = LakehouseTable(path)
        faults.install("crash:catalog:commit")
        with pytest.raises(C.CatalogUnreachableError) as ei:
            t.append(_ints(2))
        assert faults.classify(ei.value) == faults.IO_TRANSIENT
        faults.reset()
        ref = coord._ref(path)
        # the WAL intent survived the crash; the manifest did not publish
        assert len(coord._fs._ls(ref, "wal")) == 1
        assert _versions(path) == [1]
        # "restart": recovery rolls the unacknowledged intent back
        rep = coord.recover(path)
        assert rep["rolled_back"] == 1
        # the ladder-style retry re-runs the transaction: exactly once
        LakehouseTable(path).append(_ints(2))
        assert _vals(path) == [1, 2]
        assert _versions(path) == [1, 2]
        for v in _versions(path):  # no torn manifest anywhere
            LakehouseTable(path).snapshot(v)
    finally:
        server.stop()


def test_unreachable_coordinator_degrades_gracefully(tmp_path):
    """Writes fail classified-retryable, pinned reads keep serving, lease
    registration degrades to process-local, vacuum fails conservative."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(1, 2))
    # a port nothing listens on
    os.environ["NDS_LAKE_CATALOG"] = "http://127.0.0.1:9"
    os.environ["NDS_LAKE_CATALOG_TIMEOUT_S"] = "0.3"
    os.environ["NDS_LAKE_CATALOG_POLL_S"] = "0.1"
    C.reset_clients()
    t = LakehouseTable(path)
    with pytest.raises(C.CatalogUnreachableError) as ei:
        t.append(_ints(3))
    assert faults.classify(ei.value) == faults.IO_TRANSIENT
    # reads never need the coordinator
    assert t.num_rows() == 2
    snap = t.snapshot()
    lease = t.acquire_reader_lease(snap, 60)  # local-only, with a warning
    assert lease in (lease,) and LEASES.held_versions(t.root) == {1}
    # vacuum must not delete blind when it cannot see remote leases
    with pytest.raises(C.CatalogUnreachableError):
        t.vacuum(retain_last=1)


# ---------------------------------------------------------------------------
# the two-PROCESS writer conflict oracle (satellite)
# ---------------------------------------------------------------------------

_WRITER_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
import pyarrow as pa
from nds_tpu.lakehouse.table import LakehouseTable
t = LakehouseTable({path!r})
base = int(sys.argv[1])
for i in range({commits}):
    t.append(pa.table({{"a": pa.array([base + i])}}))
"""


def _run_writers(path, n_writers, commits, extra_env):
    env = {
        **os.environ, "JAX_PLATFORMS": "cpu",
        "NDS_LAKE_COMMIT_RETRIES": "64",
        "NDS_LAKE_COMMIT_BACKOFF": "0.005",
        **extra_env,
    }
    script = _WRITER_SCRIPT.format(repo=REPO, path=path, commits=commits)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(1000 * (w + 1))],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for w in range(n_writers)
    ]
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, err.decode()[-2000:]


@pytest.mark.parametrize("mode", ["off", "fs", "tcp"])
def test_two_process_writer_conflict_oracle(tmp_path, mode):
    """Two writer PROCESSES race appends: every commit claims exactly one
    version (linear history, one winner per version), both row sets land
    exactly once, and no loser's staged file leaks — against both catalog
    backends AND the legacy filesystem mode (the PR-10 test was
    two-IN-PROCESS-writers only)."""
    path = str(tmp_path / "t")
    LakehouseTable.create(path, _ints(0))
    server = None
    extra = {"NDS_LAKE_CATALOG": ""}
    try:
        if mode == "fs":
            extra = {"NDS_LAKE_CATALOG": "fs"}
        elif mode == "tcp":
            _coord, server, url = _start_coordinator()
            extra = {"NDS_LAKE_CATALOG": url}
        commits = 3
        _run_writers(path, 2, commits, extra)
        expected = [0] + [
            1000 * (w + 1) + i for w in range(2) for i in range(commits)
        ]
        assert _vals(path) == sorted(expected)  # exactly once, both sets
        assert _versions(path) == list(range(1, 2 * commits + 2))
        # loser staged files were rebased into commits, never leaked:
        # every data file is referenced by the head
        head = set(
            posixpath.basename(f)
            for f in LakehouseTable(path).current_files()
        )
        on_disk = {
            n for n in os.listdir(os.path.join(path, "data"))
            if TBL._STAGED_RE.match(n)
        }
        assert on_disk == head
    finally:
        if server is not None:
            server.stop()


# ---------------------------------------------------------------------------
# heartbeat lease renewal (satellite)
# ---------------------------------------------------------------------------


def test_heartbeat_renews_lease_through_slow_statement(tmp_path):
    """A statement outliving the lease TTL keeps its pinned snapshot
    vacuum-safe: the memwatch heartbeat renews the session's lakehouse
    leases every beat. TTL 0.4s, hang fault 1.2s, vacuum fired past the
    TTL mid-statement — without renewal the pinned files would be
    deleted and the re-read would fail."""
    pytest.importorskip("jax")
    from nds_tpu.engine.session import Session
    from nds_tpu.report import BenchReport

    lt, path = _make_fs_table(tmp_path, *range(8))
    os.environ["NDS_LAKE_LEASE_TTL_S"] = "0.4"
    os.environ["NDS_HEARTBEAT_INTERVAL_MS"] = "50"
    s = Session(conf={"engine.lake_catalog": "fs"})
    s.register_lakehouse("t", path)
    r = s.sql("select a from t order by a")  # pins v1, leases its files
    baseline = r.collect()
    # the cold collect() above can outlive the tiny TTL on its own;
    # refresh the pin so the statement ENTERS the report with a live
    # lease — from there only the heartbeat renewal can keep it alive
    # through the 1.3s hang (TTL 0.4s, vacuum fired at 0.8s)
    s.catalog.pin_lakehouse("t")
    vacuum_result = {}

    def racing_maintenance():
        time.sleep(0.8)  # well past the 0.4s TTL
        LakehouseTable(path).replace(_ints(9))
        vacuum_result["res"] = LakehouseTable(path).vacuum(retain_last=1)

    faults.install("hang:renewal_probe:1.3")
    racer = threading.Thread(target=racing_maintenance)

    def slow_statement():
        racer.start()
        faults.maybe_fire("renewal_probe")  # the 1.3s hang
        racer.join(10)

    summary = BenchReport(s).report_on(slow_statement, name="renewal_probe")
    assert summary["queryStatus"] == ["Completed"]
    assert "res" in vacuum_result
    # the pinned snapshot's files survived the mid-statement vacuum
    assert vacuum_result["res"]["files_removed"] == 0
    s.recover_memory("test: force re-read through the pin")
    r._table = None  # force a fresh execution of the same pinned plan
    assert r.collect().equals(baseline)


# ---------------------------------------------------------------------------
# lint: manifest-write-seam
# ---------------------------------------------------------------------------


def test_manifest_write_seam_rule():
    bad_call = "def f(fs, tmp, dest):\n    return put_if_absent(fs, tmp, dest)\n"
    fs = L.lint_source(bad_call, "maintenance.py")
    assert any(f.rule == "manifest-write-seam" for f in fs)
    bad_path = 'MANIFESTS = "_manifests"\n'
    fs = L.lint_source(bad_path, "serve/service.py")
    assert any(f.rule == "manifest-write-seam" for f in fs)
    # the committer modules are the rule's two legitimate homes
    for home in ("lakehouse/table.py", "lakehouse/catalog.py"):
        assert L.lint_source(bad_call + bad_path, home) == []
    # docstring prose never trips it
    doc = '"""the _manifests dir layout"""\nX = 1\n'
    assert not any(
        f.rule == "manifest-write-seam"
        for f in L.lint_source(doc, "io/fs.py")
    )
    # a pragma acknowledges a justified exception
    pragma = (
        "# nds-lint: disable=manifest-write-seam\n"
        'MANIFESTS = "_manifests"\n'
    )
    assert not any(
        f.rule == "manifest-write-seam"
        for f in L.lint_source(pragma, "maintenance.py")
    )


def test_real_tree_is_manifest_seam_clean():
    findings = [
        f for f in L.run_lint(os.path.join(REPO, "nds_tpu"))
        if f.rule == "manifest-write-seam"
    ]
    assert findings == []


# ---------------------------------------------------------------------------
# observability: events, metrics, /statusz
# ---------------------------------------------------------------------------


def test_catalog_events_metrics_and_statusz(tmp_path):
    from nds_tpu.obs.metrics import MetricsSink
    from nds_tpu.obs.reader import validate_events
    from nds_tpu.obs.trace import EVENT_SCHEMA

    sink = MetricsSink()
    tracer = Tracer(sink=sink)
    with obs_trace.bind(tracer):
        lt, path = _make_fs_table(tmp_path, 1)
        lt2 = LakehouseTable(path)
        lt2.append(_ints(2))
        snap = lt2.snapshot()
        lease = lt2.catalog.lease_acquire(lt2, snap.version,
                                          snap.rel_files, 60)
        lease.release()
        lt2.vacuum(retain_last=1)
    kinds = [e["kind"] for e in tracer.events]
    assert "catalog_commit" in kinds and "catalog_lease" in kinds
    assert validate_events(tracer.events) == []
    for e in tracer.events:
        if e["kind"] in ("catalog_commit", "catalog_lease"):
            for field in EVENT_SCHEMA[e["kind"]]:
                assert field in e, (e["kind"], field)
    reg = sink.registry
    assert reg.counter_value(
        "nds_catalog_commit_total", backend="fs", outcome="ok"
    ) >= 2
    lease_series = reg.counter_series("nds_catalog_lease_total")
    assert sum(lease_series.values()) >= 3  # register/acquire/release/bump
    st = sink.status_snapshot()
    assert st["catalog"]["backend"] == "fs"
    assert st["catalog"]["commits"] >= 2
    assert st["catalog"]["fence"] is not None
    assert st["catalog"]["last_version"] >= 2


def test_catalog_fault_sites_io_classification(tmp_path):
    lt, path = _make_fs_table(tmp_path, 1)
    faults.install("io:catalog:commit:1")
    with pytest.raises(faults.TransientIOError) as ei:
        LakehouseTable(path).append(_ints(2))
    assert faults.classify(ei.value) == faults.IO_TRANSIENT
    faults.reset()
    # the retry lands (the rule burned out): nothing was published before
    LakehouseTable(path).append(_ints(2))
    assert _vals(path) == [1, 2]
    faults.install("io:catalog:fence:1")
    with pytest.raises(faults.TransientIOError):
        LakehouseTable(path).vacuum(retain_last=1)
    faults.reset()


def test_cli_recover_only_build(tmp_path):
    """The CLI construction path: recovery over a warehouse of tables
    (argparse namespace, no subprocess)."""
    import argparse

    from nds_tpu.cli.catalog import build_coordinator

    wh = tmp_path / "wh"
    wh.mkdir()
    LakehouseTable.create(str(wh / "t1"), _ints(1))
    LakehouseTable.create(str(wh / "t2"), _ints(2))
    args = argparse.Namespace(
        warehouse_path=str(wh), port=0, property_file=None,
        recover_only=True,
    )
    coordinator, server, recovered = build_coordinator(args)
    assert {r["table"] for r in recovered} == {"t1", "t2"}
    assert all(r["rolled_back"] == 0 for r in recovered)
