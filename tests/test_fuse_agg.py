"""Fused aggregate tails, buffer-donation ownership, per-window fused
wrappers, and kernel-span tracing (the PR-6 tentpole).

Contract under test: a decomposable aggregate absorbed into a Pipeline
(`fuse.FusedAggPipeline` — chain + partial-aggregate scatter in ONE
dispatch) produces results identical to the eager path across grouped/
global shapes, nulls, strings, decimals, empty inputs and bucket
boundaries; ineligible aggregates (ROLLUP, DISTINCT, blocked unions) pin
to the eager path UNMARKED; blocked union-aggregation windows ride one
fused wrapper executable instead of eager per-wrapper dispatches; full-
column donation (`Column.owned` + `donate_ok`) stays safe under OOM wipes
and multi-consumer plans; and `kernel_span` events land on schema and
aggregate in the profiler.
"""

import json
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import plan as P
from nds_tpu.engine.session import Session


def _table(n, seed=0):
    r = np.random.default_rng(seed)
    ks = r.integers(0, 15, n)
    vs = r.integers(-80, 80, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 11 == 0 else int(v) for i, v in enumerate(ks)],
                pa.int32(),
            ),
            "v": pa.array(
                [None if i % 7 == 3 else int(v) for i, v in enumerate(vs)],
                pa.int64(),
            ),
            "cat": pa.array(
                [
                    None if i % 13 == 5
                    else ["Books", "Music", "Shoes", "Home"][int(x) % 4]
                    for i, x in enumerate(ks)
                ],
                pa.string(),
            ),
            "amt": pa.array(
                [Decimal(int(v) * 3) / 100 for v in vs], pa.decimal128(7, 2)
            ),
        }
    )


def _sessions(n=2000, conf=None):
    on = Session(conf=dict(conf or {}))
    off = Session(conf={"engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(n))
        s.register_arrow("u", _table(n, seed=1))
    return on, off


def _agg_pipelines(plan):
    out = []

    def walk(n):
        if isinstance(n, P.Pipeline) and n.agg is not None:
            out.append(n)
        for c in n.children():
            if c is not None:
                walk(c)

    walk(plan)
    return out


def _raw_aggregates(plan):
    out = []

    def walk(n):
        if isinstance(n, P.Aggregate):
            out.append(n)
        for c in n.children():
            if c is not None:
                walk(c)

    walk(plan)
    return out


AGG_EQUALITY_QUERIES = [
    # grouped: int key with nulls, mixed aggregate set
    "select k, sum(v) sv, count(*) c, count(v) cv, min(v) mn, max(v) mx "
    "from t where v > -60 group by k order by k",
    # grouped: STRING key (dictionary + nulls) and string min/max
    "select cat, count(*) c, min(cat) mn, max(cat) mx from t "
    "where v > -70 group by cat order by cat",
    # multi-key (int x string), decimal sum/avg
    "select k, cat, sum(amt) sa, avg(amt) aa from t where v > -50 "
    "group by k, cat order by k, cat",
    # global aggregate (no keys; one output row)
    "select count(*) c, sum(v) sv, avg(v) av, min(v) mn from t "
    "where v between -40 and 40",
    # global over an EMPTY filter result (count 0, null sum)
    "select count(*) c, sum(v) sv from t where v > 1000",
    # grouped over an empty filter result (zero groups)
    "select k, sum(v) sv from t where v > 1000 group by k order by k",
    # projection-computed aggregate argument and key
    "select k + 1 k1, sum(v * 2) sv, avg(v) av from t where v > -60 "
    "group by k + 1 order by k1",
    # HAVING chain over the fused aggregate (plain Pipeline over agg tail)
    "select k, sum(v) sv from t group by k having sum(v) > 10 order by k",
]


@pytest.mark.parametrize("qi", range(len(AGG_EQUALITY_QUERIES)))
def test_fused_agg_path_equality(qi):
    q = AGG_EQUALITY_QUERIES[qi]
    on, off = _sessions()
    assert on.sql(q).collect().equals(off.sql(q).collect()), q


@pytest.mark.parametrize("n", [1023, 1024, 1025])
def test_fused_agg_bucket_boundaries(n):
    on, off = _sessions(n=n)
    q = ("select k, sum(v) sv, count(*) c from t where v > -70 "
         "group by k order by k")
    assert on.sql(q).collect().equals(off.sql(q).collect())


def test_fused_agg_over_empty_table():
    on, off = _sessions()
    for s in (on, off):
        s.register_arrow("e", _table(0))
    q = "select k, sum(v) sv from e group by k order by k"
    assert on.sql(q).collect().equals(off.sql(q).collect())
    q2 = "select count(*) c, sum(v) sv from e"
    assert on.sql(q2).collect().equals(off.sql(q2).collect())


def test_fused_agg_plan_shape_and_reuse():
    on, _ = _sessions()
    q = ("select k, sum(v) sv, avg(amt) aa from t where v > -60 "
         "group by k order by k")
    r = on.sql(q)
    pipes = _agg_pipelines(r.plan)
    assert len(pipes) == 1
    pipe = pipes[0]
    assert pipe.agg.child is None  # detached tail
    assert not _raw_aggregates(r.plan)  # the Aggregate was absorbed
    assert "Pipeline" in r.explain() and "+A" in r.explain()
    a = r.collect()
    # steady re-run rides the executable cache
    on.conf["engine.plan_cache"] = "off"
    hits0 = on.exec_cache.hits
    assert on.sql(q).collect().equals(a)
    assert on.exec_cache.hits > hits0


def test_rollup_and_distinct_stay_eager_unmarked():
    on, off = _sessions()
    # ROLLUP: grouping sets never fuse
    q1 = "select k, sum(v) sv from t group by rollup(k) order by k"
    assert not _agg_pipelines(on.sql(q1).plan)
    assert on.sql(q1).collect().equals(off.sql(q1).collect())
    # DISTINCT aggregate: non-decomposable, never fuses
    q2 = "select k, count(distinct cat) dc from t group by k order by k"
    assert not _agg_pipelines(on.sql(q2).plan)
    assert on.sql(q2).collect().equals(off.sql(q2).collect())
    # stddev: non-decomposable
    q3 = "select k, stddev_samp(v) sd from t group by k order by k"
    assert not _agg_pipelines(on.sql(q3).plan)


def test_fuse_agg_conf_off_keeps_chain_fusion():
    s = Session(conf={"engine.fuse_agg": "off"})
    s.register_arrow("t", _table(1000))
    r = s.sql("select k, sum(v) sv from t where v > 0 group by k order by k")
    assert not _agg_pipelines(r.plan)
    assert _raw_aggregates(r.plan)  # the aggregate stayed raw...
    on, off = _sessions(n=1000)
    assert r.collect().equals(
        off.sql("select k, sum(v) sv from t where v > 0 group by k "
                "order by k").collect()
    )


def test_blocked_union_windows_ride_fused_wrappers(tmp_path):
    """The blocked union-agg per-window path compiles its wrapper chain
    once and re-rides the executable across windows (PR-4 leftover: the
    windowed path was eager per wrapper per window). Oracle: identical
    result to the unfused session; evidence: exec_cache hits inside one
    blocked execution."""
    conf = {"engine.union_agg_window_rows": 512,
            "engine.trace_dir": str(tmp_path)}
    on = Session(conf=dict(conf))
    off = Session(conf={"engine.union_agg_window_rows": 512,
                        "engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(3000))
        s.register_arrow("u", _table(3000, seed=1))
    q = """
    select k, sum(v) sv, count(*) c, avg(v) av
    from (select k, v * 1 v from t where v > -70
          union all
          select k, v * 1 v from u) x
    where v < 70
    group by k order by k
    """
    ra = on.sql(q)
    a = ra.collect()
    assert a.equals(off.sql(q).collect())
    assert ra.executor.last_blocked_union is not None
    assert ra.executor.last_blocked_union["windows"] > 1
    evs = [
        json.loads(line)
        for line in open(on.tracer.path, encoding="utf-8")
        if line.strip()
    ]
    ec = [e for e in evs if e["kind"] == "exec_cache"]
    # first window misses (build), later windows hit the same executable
    assert any(e["hit"] for e in ec)


def test_full_column_donation_join_fed_pipeline():
    """fuse_donate=on over a join-fed chain: the join's gather outputs are
    owned buffers, so full-column donation engages — results must stay
    identical across reruns and after an OOM wipe."""
    on = Session(conf={"engine.fuse_donate": "on"})
    off = Session(conf={"engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(2000))
        s.register_arrow("u", _table(2000, seed=1))
    q = ("select x.k, sum(x.s) ss from (select t.k \"k\", t.v + u.v s "
         "from t, u where t.k = u.k and t.v > u.v) x where x.s > 10 "
         "group by x.k order by x.k")
    expect = off.sql(q).collect()
    assert on.sql(q).collect().equals(expect)
    on.conf["engine.plan_cache"] = "off"
    assert on.sql(q).collect().equals(expect)
    assert on.sql(q).collect().equals(expect)  # donated buffers not reread
    on.recover_memory("test: simulated OOM wipe")
    assert on.sql(q).collect().equals(expect)


def test_multi_consumer_child_never_donates():
    """A CTE consumed twice: its pipelines must carry donate_ok=False (the
    verifier's `donate` rule backs this), and execution under
    fuse_donate=on must not corrupt the second consumer's input."""
    on = Session(conf={"engine.fuse_donate": "on"})
    off = Session(conf={"engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(2000))
    q = """
    with base as (select k, v from t where v > -50)
    select a.k, a.v from base a, base b
    where a.k = b.k and a.v > b.v order by a.k, a.v
    """
    ra = on.sql(q)

    shared_pipes = []

    def walk(n, seen):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, P.Pipeline):
            shared_pipes.append(n)
        for c in n.children():
            if c is not None:
                walk(c, seen)

    walk(ra.plan, set())
    assert ra.collect().equals(off.sql(q).collect())


def test_owned_flag_semantics():
    """Catalog scan columns are never owned (they alias base-table
    buffers); join pair-gather outputs are owned."""
    s = Session()
    s.register_arrow("t", _table(500))
    base = s.catalog.load("t")
    assert all(not c.owned for c in base.columns.values())


def test_kernel_span_schema_and_profiler_aggregation(tmp_path):
    """NDS_TRACE_KERNELS mode: kernel entry points emit schema-valid
    kernel_span events, and the profiler aggregates them into
    kernel_totals (count/dur/rows per kernel)."""
    from nds_tpu.obs import reader as R
    from nds_tpu.obs import trace as obs_trace

    s = Session(conf={
        "engine.trace_dir": str(tmp_path),
        "engine.trace_kernels": "on",
        "engine.fuse": "off",  # eager path: kernels dispatch outside jit
    })
    assert s.tracer.kernel_spans is True
    s.register_arrow("t", _table(2000))
    with obs_trace.bind(s.tracer):
        s.sql("select k, sum(v) sv, min(v) mn from t where v > 0 "
              "group by k order by k").collect()
    s.tracer.close()
    events = R.read_events([str(tmp_path)], strict=True)
    assert R.validate_events(events) == []
    spans = [e for e in events if e["kind"] == "kernel_span"]
    assert spans, "no kernel_span events recorded"
    for ev in spans:
        assert isinstance(ev["kernel"], str)
        assert isinstance(ev["dur_ms"], (int, float))
        assert isinstance(ev["n"], int)
    prof = R.profile_events(events)
    kt = prof["kernel_totals"]
    assert "segment_reduce_with_count" in kt
    for rec in kt.values():
        assert rec["count"] >= 1 and rec["dur_ms"] >= 0.0


def test_kernel_span_off_by_default(tmp_path):
    from nds_tpu.obs import reader as R
    from nds_tpu.obs import trace as obs_trace

    s = Session(conf={"engine.trace_dir": str(tmp_path),
                      "engine.fuse": "off"})
    assert s.tracer.kernel_spans is False
    s.register_arrow("t", _table(500))
    with obs_trace.bind(s.tracer):
        s.sql("select k, sum(v) sv from t group by k").collect()
    s.tracer.close()
    events = R.read_events([str(tmp_path)], strict=True)
    assert not [e for e in events if e["kind"] == "kernel_span"]


def test_pallas_auto_promotion_memo():
    """engine.pallas_agg=auto: the first float64 sum at a shape measures
    both routes, memoizes the verdict per (fn, rows, gcap), and produces
    results matching the default path (CPU interpret mode: jnp wins, so
    the promotion memo records use=False — the measurement itself is the
    contract under test)."""
    on = Session(conf={"engine.pallas_agg": "auto"})
    off = Session()
    t = pa.table({
        "k": pa.array([i % 5 for i in range(800)], pa.int32()),
        "f": pa.array([float(i) * 0.25 for i in range(800)], pa.float64()),
    })
    for s in (on, off):
        s.register_arrow("tf", t)
    q = "select k, sum(f) sf from tf group by k order by k"
    a = on.sql(q).collect().to_pylist()
    b = off.sql(q).collect().to_pylist()
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x["k"] == y["k"]
        assert x["sf"] == pytest.approx(y["sf"], rel=1e-6)
    assert on.pallas_promotions, "auto mode recorded no A/B measurement"
    for key, rec in on.pallas_promotions.items():
        assert rec["jnp_ms"] >= 0.0
        assert isinstance(rec["use"], bool)
    # steady re-run reuses the memo (no new entries)
    n_entries = len(on.pallas_promotions)
    on.conf["engine.plan_cache"] = "off"
    on.sql(q).collect()
    assert len(on.pallas_promotions) == n_entries


def test_cached_cte_survives_join_passthrough_donation():
    """A CTE aggregate consumed twice, once through a join feeding a
    donating chain: the join passes the CTE's columns through BY REFERENCE
    (exec._augment_join_output), so ownership must not ride along — a
    donation there would free buffers the CTE cache still holds for the
    second consumer. Both consumers must match the fuse=off oracle, with
    no unusable-donation warnings requested along the way."""
    import warnings

    on = Session(conf={"engine.fuse_donate": "on"})
    off = Session(conf={"engine.fuse": "off"})
    for s in (on, off):
        s.register_arrow("t", _table(2000))
        s.register_arrow("u", _table(2000, seed=1))
    q = """
    with g as (select k, sum(v) sv from t where v > -60 group by k)
    select g.k, g.sv * 2 d, g.sv + u.v s from g, u
    where g.k = u.k and u.v > 0 and g.sv + u.v > -500
    union all
    select k, sv, sv from g
    order by 1, 2, 3
    """
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "error", message=".*donated buffers.*", category=UserWarning
        )
        expect = off.sql(q).collect()
        assert on.sql(q).collect().equals(expect)
        on.conf["engine.plan_cache"] = "off"
        assert on.sql(q).collect().equals(expect)
        assert on.sql(q).collect().equals(expect)


def test_node_boundary_passthrough_disowns_columns():
    """The donation-safety mechanism behind the CTE test above, pinned at
    the unit level: every executor path that shares Column OBJECTS into a
    new table across a plan-node boundary (_masked filters, _project_table
    renames) must strip ownership — the source table may be cache-retained,
    so the buffer no longer has a single exclusive owner. The `transient`
    escape hatch (join-internal pair tables) keeps it."""
    import jax.numpy as jnp

    from nds_tpu.dtypes import INT64
    from nds_tpu.engine.columnar import Column, Table
    from nds_tpu.engine.exec import Executor
    from nds_tpu.engine import expr as E

    s = Session()
    s.register_arrow("t", _table(100))
    ex = Executor(s.catalog)
    owned_col = Column(jnp.arange(8, dtype=jnp.int64), INT64, owned=True)
    t = Table({"a": owned_col}, 8)
    mask = jnp.arange(8) < 4

    masked = ex._masked(t, mask)
    assert not masked.columns["a"].owned, "_masked leaked ownership"
    assert masked.columns["a"].data is owned_col.data  # still shared
    assert t.columns["a"].owned  # source table untouched

    kept = ex._masked(t, mask, transient=True)
    assert kept.columns["a"].owned, "transient=True must keep ownership"

    proj = ex._project_table(t, [(E.Col("a"), "b")])
    assert not proj.columns["b"].owned, "_project_table rename leaked"


def test_pallas_mode_keeps_chain_fusion():
    """engine.pallas_agg != off pins aggregates to the eager per-aggregate
    seam at PLAN time — the feeding Filter/Project chain must still fuse
    (a plain Pipeline under a separate Aggregate, not a lost fusion)."""
    on = Session(conf={"engine.pallas_agg": "auto"})
    off = Session()
    t = pa.table({
        "k": pa.array([i % 5 for i in range(800)], pa.int32()),
        "f": pa.array([float(i) * 0.25 for i in range(800)], pa.float64()),
    })
    for s in (on, off):
        s.register_arrow("tf", t)
    q = ("select k, sum(f) sf from tf where f > 10 and k < 4 "
         "group by k order by k")
    r = on.sql(q)
    pipes, aggs = [], []

    def walk(n, seen):
        if n is None or id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, P.Pipeline):
            pipes.append(n)
        if isinstance(n, P.Aggregate):
            aggs.append(n)
        for c in n.children():
            walk(c, seen)

    walk(r.plan, set())
    assert aggs, "aggregate missing from the plan"
    assert all(p.agg is None for p in pipes), (
        "agg tail fused despite a Pallas mode"
    )
    assert pipes, "chain fusion lost under a Pallas mode"
    a = r.collect().to_pylist()
    b = off.sql(q).collect().to_pylist()
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x["k"] == y["k"]
        assert x["sf"] == pytest.approx(y["sf"], rel=1e-9)
