"""Concurrency contract analyzer (analysis/concurrency.py) + the runtime
lock sanitizer (engine/lockdebug.py).

Static half: one seeded violation per rule (unguarded mutation of
declared state, undeclared shared attr, blocking call under a lock,
lock-order cycle, thread leak), the `cache-lock-discipline` alias, and
the lock-order golden sync — plus a lint-clean-tree assertion, the same
gate ci/tier1-check enforces.

Runtime half: the order assertion fires on a deliberately inverted
acquisition, the `lock_contention` event matches its EVENT_SCHEMA row,
and the hold-budget watchdog's suspected-deadlock dump lands in a flight
bundle with the `threads` (stacks + held-lock table) section.

Satellite regressions pin the real fixes this analyzer surfaced: the
serve/router drain flips now run under their state locks, the
promotion-store write moved its file IO outside the planning-path lock,
and the catalog coordinator's `_ref` no longer races duplicate
`_TableRef`s.
"""

import ast
import json
import os
import textwrap
import threading
import time

import pytest

from nds_tpu.analysis import concurrency as C
from nds_tpu.analysis import lint as L
from nds_tpu.engine import lockdebug as ld
from nds_tpu.obs import trace as obs_trace
from nds_tpu.obs.trace import EVENT_SCHEMA, Tracer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(src, relpath):
    return [f.rule for f in L.lint_source(textwrap.dedent(src), relpath)]


# ---------------------------------------------------------------------------
# guarded-by: declarations + span discipline
# ---------------------------------------------------------------------------


def test_unguarded_mutation_of_declared_state_fires():
    src = """
    import threading

    class QueryRouter:
        def __init__(self):
            self._lock = threading.Lock()
            self.draining = False  # nds-guarded-by: _lock

        def close(self):
            self.draining = True
    """
    assert _rules(src, "serve/router.py") == ["guarded-by"]


def test_undeclared_shared_attr_fires():
    src = """
    import threading

    class QueryRouter:
        def __init__(self):
            self._lock = threading.Lock()
            self._rr = 0

        def bump(self):
            with self._lock:
                self._rr += 1
    """
    # mutated outside __init__ with no declaration: the model demands the
    # contract be WRITTEN even when this one site happens to hold a lock
    assert _rules(src, "serve/router.py") == ["guarded-by"]


def test_declared_and_spanned_is_clean():
    src = """
    import threading

    class QueryRouter:
        def __init__(self):
            self._lock = threading.Lock()
            self.draining = False  # nds-guarded-by: _lock

        def close(self):
            with self._lock:
                self.draining = True
    """
    assert _rules(src, "serve/router.py") == []


def test_guarded_by_none_and_locked_suffix_pass():
    src = """
    import threading

    class QueryRouter:
        def __init__(self):
            self._lock = threading.Lock()
            # single atomic store, readers tolerate staleness
            self.beat = None  # nds-guarded-by: none
            self.n = 0  # nds-guarded-by: _lock

        def stamp(self):
            self.beat = 1.0

        def _bump_locked(self):
            self.n += 1
    """
    assert _rules(src, "serve/router.py") == []


def test_non_multithread_class_is_exempt():
    src = """
    class Helper:
        def poke(self):
            self.x = 1
    """
    assert _rules(src, "serve/router.py") == []


# ---------------------------------------------------------------------------
# cache-lock-discipline, retired into guarded-by
# ---------------------------------------------------------------------------

_CACHE_SRC = """
class Runner:
    def go(self, session):
        session.plan_cache.clear()
"""


def test_session_cache_rule_lives_on_in_guarded_by():
    fs = L.lint_source(_CACHE_SRC, "power.py")
    assert [f.rule for f in fs] == ["guarded-by"]
    assert "cache_lock" in fs[0].message


def test_cache_lock_discipline_alias_pragma_still_silences():
    src = _CACHE_SRC.replace(
        "session.plan_cache.clear()",
        "session.plan_cache.clear()  # nds-lint: disable=cache-lock-discipline",
    )
    assert L.lint_source(src, "power.py") == []
    assert C.RULE_ALIASES["cache-lock-discipline"] == "guarded-by" or \
        L.RULE_ALIASES["cache-lock-discipline"] == "guarded-by"


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def test_blocking_call_under_lock_fires():
    src = """
    import json, os, threading

    class PromotionStore:
        def __init__(self):
            self._lock = threading.Lock()
            self._cache = None  # nds-guarded-by: _lock

        def record(self, rec):
            with self._lock:
                self._cache = rec
                with open("/tmp/x", "w") as f:
                    json.dump(rec, f)
    """
    rules = _rules(src, "engine/aotcache.py")
    assert "blocking-under-lock" in rules


def test_blocking_call_outside_lock_is_clean():
    src = """
    import json, threading

    class PromotionStore:
        def __init__(self):
            self._lock = threading.Lock()
            self._cache = None  # nds-guarded-by: _lock

        def record(self, rec):
            with self._lock:
                self._cache = rec
            with open("/tmp/x", "w") as f:
                json.dump(rec, f)
    """
    assert _rules(src, "engine/aotcache.py") == []


# ---------------------------------------------------------------------------
# thread-leak
# ---------------------------------------------------------------------------


def test_thread_leak_fires():
    src = """
    import threading

    def go():
        threading.Thread(target=print).start()
    """
    assert _rules(src, "power.py") == ["thread-leak"]


def test_thread_leak_daemon_and_join_pass():
    src = """
    import threading

    def go():
        t = threading.Thread(target=print)
        t.start()
        t.join()
        threading.Thread(target=print, daemon=True).start()
    """
    assert _rules(src, "power.py") == []


def test_thread_leak_joined_via_list_iteration_passes():
    # the throughput.py shape: handles built in a comprehension, joined
    # through the loop variable — the loop-var -> iterable mapping must
    # not flag it
    src = """
    import threading

    def go(items):
        threads = [threading.Thread(target=print, args=(i,)) for i in items]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    """
    assert _rules(src, "power.py") == []


# ---------------------------------------------------------------------------
# lock-order: cycles + golden sync
# ---------------------------------------------------------------------------


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def test_lock_order_cycle_detected(tmp_path):
    root = _write_tree(tmp_path, {
        "mod.py": """
        import threading

        _A_LOCK = threading.Lock()
        _B_LOCK = threading.Lock()

        def forward():
            with _A_LOCK:
                with _B_LOCK:
                    pass

        def backward():
            with _B_LOCK:
                with _A_LOCK:
                    pass
        """,
    })
    model = C.build_lock_model(root)
    assert model.cycles, "inverted nestings must form a cycle"
    fs = C.run_lock_order_lint(root)
    assert any(f.rule == "lock-order" and "cycle" in f.message for f in fs)


def test_lock_order_nested_in_branches_and_call_edges(tmp_path):
    # spans inside an `if` and acquisitions via a call edge both count
    root = _write_tree(tmp_path, {
        "mod.py": """
        import threading

        _A_LOCK = threading.Lock()
        _B_LOCK = threading.Lock()

        def inner():
            with _B_LOCK:
                pass

        def outer(flag):
            if flag:
                with _A_LOCK:
                    inner()
        """,
    })
    model = C.build_lock_model(root)
    assert ("mod.py:_A_LOCK", "mod.py:_B_LOCK") in model.edges
    assert not model.cycles


def test_golden_file_in_sync_with_tree():
    # the checked-in golden IS the current model: regenerating must be a
    # no-op (anything else fails lint before it fails here)
    assert C.run_lock_order_lint() == []
    model = C.build_lock_model()
    assert not model.cycles
    with open(C.golden_path(), encoding="utf-8") as f:
        assert f.read() == C.format_golden(model)


def test_golden_roundtrip_and_pinned_order():
    order, edges = C.load_golden(C.golden_path())
    model = C.build_lock_model()
    assert order == model.order
    assert edges == set(model.edges)
    ranks = C.load_pinned_order()
    # the runtime sanitizer consumes exactly this mapping
    assert ranks["Session.cache_lock"] < ranks["FeedbackStore._lock"]
    assert set(ranks) == set(order)


def test_lint_clean_over_real_tree():
    findings = L.run_lint(ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_shared_state_report_smoke(capsys):
    assert C.main(["--report"]) == 0
    out = capsys.readouterr().out
    assert "QueryRouter" in out


# ---------------------------------------------------------------------------
# runtime sanitizer (engine/lockdebug.py)
# ---------------------------------------------------------------------------


@pytest.fixture
def lockdebug_on(monkeypatch):
    monkeypatch.setenv("NDS_LOCK_DEBUG", "1")
    ld.reset_for_tests()
    yield
    ld.reset_for_tests()


def test_make_lock_is_plain_when_off(monkeypatch):
    monkeypatch.delenv("NDS_LOCK_DEBUG", raising=False)
    lk = ld.make_lock("Session.cache_lock")
    assert not isinstance(lk, ld.DebugLock)
    with lk:  # still a working lock
        pass


def test_sanitizer_catches_inverted_acquisition(lockdebug_on):
    a = ld.make_lock("Session.cache_lock", reentrant=True)
    b = ld.make_lock("FeedbackStore._lock")
    assert isinstance(a, ld.DebugLock) and isinstance(b, ld.DebugLock)
    with a:
        with b:  # pinned order: cache_lock before the store lock
            pass
        with a:  # re-entrant re-acquire must not trip the assertion
            pass
    with b:
        with pytest.raises(ld.LockOrderError, match="inversion"):
            a.acquire()
    assert ld.held_locks() == []  # bookkeeping unwound on both paths


def test_unpinned_lock_names_skip_order_assertions(lockdebug_on):
    a = ld.make_lock("Session.cache_lock")
    x = ld.make_lock("NotInTheGolden._lock")
    with x:
        with a:  # no rank for x: nothing to assert
            pass


def test_contention_event_matches_schema(lockdebug_on):
    lk = ld.DebugLock(
        "SpillPool._lock", threading.Lock(),
        contention_ms=5.0, hold_budget_s=0.0,
    )
    tr = Tracer(collect=True)

    def holder():
        with lk:
            time.sleep(0.05)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.01)
    with obs_trace.bind(tr):
        with lk:
            pass
    t.join()
    evs = [e for e in tr.events if e["kind"] == "lock_contention"]
    assert evs, "a >=5ms wait must emit lock_contention"
    for field in EVENT_SCHEMA["lock_contention"]:
        assert field in evs[0], field
    assert evs[0]["lock"] == "SpillPool._lock"
    assert evs[0]["wait_ms"] >= 5.0


def test_deadlock_dump_lands_in_flight_bundle(lockdebug_on, monkeypatch,
                                              tmp_path):
    monkeypatch.setenv("NDS_FLIGHT_DIR", str(tmp_path))
    lk = ld.make_lock("AotCache._lock")
    lk.acquire()
    try:
        time.sleep(0.02)
        over = ld.check_holds(budget_s=0.01)
    finally:
        lk.release()
    assert over and over[0]["lock"] == "AotCache._lock"
    bundles = list(tmp_path.glob("failure-bundle-*.json"))
    assert bundles, "the suspected-deadlock dump must write a bundle"
    b = json.loads(bundles[0].read_text())
    assert b["reason"].startswith("lock hold budget exceeded")
    locks = b["threads"]["locks"]
    assert any(r["lock"] == "AotCache._lock" for r in locks)
    assert b["threads"]["stacks"], "all-thread stacks must be captured"
    # one dump per hold: a second sweep over the same hold stays quiet
    assert ld.check_holds(budget_s=0.01) == []


def test_knob_resolvers():
    assert ld.resolve_lock_debug({"engine.lock_debug": "on"}) is True
    assert ld.resolve_lock_debug({}) is False
    assert ld.resolve_contention_ms({"engine.lock_contention_ms": 7}) == 7.0
    assert ld.resolve_contention_ms({"engine.lock_contention_ms": "junk"}) \
        == 50.0
    assert ld.resolve_hold_budget_s({"engine.lock_hold_budget_s": 0}) == 0.0


# ---------------------------------------------------------------------------
# satellite regressions: the real unguarded sites the analyzer surfaced
# ---------------------------------------------------------------------------


def _with_span_covering(path, cls_name, fn_name, attr, lock_attr):
    """True when every `self.<attr> = ...` in <cls>.<fn> sits inside a
    `with self.<lock_attr>` span — the shape of each drain-flag fix."""
    with open(os.path.join(ROOT, "nds_tpu", path), encoding="utf-8") as f:
        tree = ast.parse(f.read())
    cls = next(n for n in ast.walk(tree)
               if isinstance(n, ast.ClassDef) and n.name == cls_name)
    fn = next(n for n in ast.walk(cls)
              if isinstance(n, ast.FunctionDef) and n.name == fn_name)
    spans = [
        (node.lineno, max(
            x.lineno for x in ast.walk(node) if hasattr(x, "lineno")
        ))
        for node in ast.walk(fn) if isinstance(node, ast.With)
        if any(
            isinstance(it.context_expr, ast.Attribute)
            and it.context_expr.attr == lock_attr
            for it in node.items
        )
    ]
    writes = [
        n.lineno for n in ast.walk(fn) if isinstance(n, ast.Assign)
        for t in n.targets
        if isinstance(t, ast.Attribute) and t.attr == attr
    ]
    assert writes, f"{cls_name}.{fn_name} no longer writes {attr}"
    return all(any(s <= w <= e for s, e in spans) for w in writes)


def test_service_close_flips_draining_under_state_lock():
    assert _with_span_covering(
        "serve/service.py", "QueryService", "close", "draining", "_state_lock"
    )


def test_router_drain_flips_run_under_router_lock():
    assert _with_span_covering(
        "serve/router.py", "QueryRouter", "close", "draining", "_lock"
    )
    assert _with_span_covering(
        "serve/router.py", "QueryRouter", "handle_drain", "draining", "_lock"
    )


def test_promotion_store_record_does_io_outside_lock(tmp_path):
    # the ISSUE-named blocking-under-lock fix: the JSON write happens
    # after the lock is released, and the record still lands
    from nds_tpu.engine.aotcache import PromotionStore

    store = PromotionStore(str(tmp_path / "promotions.json"))
    store.record("k1", {"winner": "pallas", "speedup": 1.4})
    assert store.get("k1")["winner"] == "pallas"
    # structurally: no fs call inside the record() lock span
    fs = [
        f for f in L.lint_source(
            open(os.path.join(ROOT, "nds_tpu", "engine", "aotcache.py"),
                 encoding="utf-8").read(),
            "engine/aotcache.py",
        ) if f.rule == "blocking-under-lock"
    ]
    assert fs == []


def test_catalog_ref_no_duplicate_tableref_under_race(tmp_path):
    from nds_tpu.lakehouse.catalog import CatalogCoordinator

    coord = CatalogCoordinator.__new__(CatalogCoordinator)
    coord._lock = threading.Lock()
    coord._refs = {}
    seen = []
    gate = threading.Barrier(4)

    def grab():
        gate.wait()
        for _ in range(50):
            seen.append(coord._ref("/tables/store_sales"))

    threads = [threading.Thread(target=grab) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(r) for r in seen}) == 1, (
        "racing handlers must resolve one shared _TableRef per path"
    )


def test_session_listener_registration_is_thread_safe():
    from nds_tpu.engine.session import Session

    s = Session(conf={})
    try:
        gate = threading.Barrier(4)

        def add(n):
            gate.wait()
            for i in range(50):
                s.register_listener(lambda reason, n=n, i=i: None)

        threads = [
            threading.Thread(target=add, args=(n,)) for n in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(s._listeners) == 200, "no lost registrations under races"
    finally:
        s.close() if hasattr(s, "close") else None
