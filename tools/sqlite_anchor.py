"""External perf anchor: run the identical generated SF1 stream through
sqlite3 and record its per-query times next to the engine's.

The engine's geomean was previously self-referential (compared only to its
own earlier rounds). sqlite is the one wholly independent SQL engine baked
into this image (duckdb is not available), so its wall-clock over the same
data, same stream, same host gives an external ratio from which the
"A100-parity" north star can be extrapolated. sqlite gets a fair shake:
indexes on every surrogate-key column plus ANALYZE before timing, 60 s
per-query abort (its unindexable plans would otherwise run for hours).

Usage: python tools/sqlite_anchor.py [out.json]
Writes anchors/sqlite_sf1.json (read by bench.py into the OUT line).
"""

import json
import math
import os
import sqlite3
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests")
)

from nds_tpu.datagen.query_streams import generate_streams  # noqa: E402
from nds_tpu.io.csv import read_dat_dir  # noqa: E402
from nds_tpu.power import gen_sql_from_stream  # noqa: E402
from nds_tpu.schema import get_schemas  # noqa: E402
from test_oracle import _StddevSamp, _to_sqlite  # noqa: E402

DATA = os.environ.get("NDS_BENCH_DATA", "/tmp/nds_bench_sf1.0")
BUDGET_S = int(os.environ.get("NDS_SQLITE_BUDGET", "60"))


def load(conn):
    import datetime

    schemas = get_schemas(use_decimal=False)
    for t, schema in schemas.items():
        path = os.path.join(DATA, t)
        if not os.path.isdir(path):
            continue
        arrow = read_dat_dir(path, schema, use_decimal=False)
        conn.execute(
            f"create table {t} ({', '.join(f.name for f in schema)})"
        )
        ph = ",".join("?" * len(schema))
        # stream per record batch: to_pylist() of a whole SF1 fact table
        # would box tens of millions of Python values at once
        for batch in arrow.to_batches(max_chunksize=1 << 17):
            rows = (
                tuple(
                    v.isoformat() if isinstance(v, (datetime.date,)) else v
                    for v in r.values()
                )
                for r in batch.to_pylist()
            )
            conn.executemany(f"insert into {t} values ({ph})", rows)
        print(f"loaded {t}: {arrow.num_rows} rows", flush=True)
        # index every surrogate-key column: sqlite's nested-loop joins need
        # them; this is the fair (favorable-to-sqlite) configuration
        for f in schema:
            if f.name.endswith("_sk") or f.name.endswith("_number"):
                conn.execute(f"create index idx_{t}_{f.name} on {t}({f.name})")
    conn.execute("analyze")
    conn.commit()


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "anchors", "sqlite_sf1.json",
    )
    with tempfile.TemporaryDirectory() as d:
        generate_streams(d, 1, 1, rngseed=19620718)
        queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))

    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("stddev_samp", 1, _StddevSamp)
    t0 = time.perf_counter()
    load(conn)
    print(f"load+index: {time.perf_counter() - t0:.1f}s", flush=True)

    per_query = {}
    failed = {}
    deadline = [0.0]

    def abort_if_late():
        return 1 if time.monotonic() > deadline[0] else 0

    conn.set_progress_handler(abort_if_late, 100_000)
    for i, (name, q) in enumerate(queries.items()):
        try:
            sql = _to_sqlite(q)
        except Exception as exc:
            failed[name] = f"lowering: {exc}"
            continue
        deadline[0] = time.monotonic() + BUDGET_S
        t0 = time.perf_counter()
        try:
            for stmt in [s for s in sql.split(";") if s.strip()]:
                cur = conn.execute(stmt)
                cur.fetchall()
            per_query[name] = time.perf_counter() - t0
            print(f"[{i+1}/{len(queries)}] {name}: {per_query[name]:.2f}s",
                  flush=True)
        except sqlite3.OperationalError as exc:
            if "interrupted" in str(exc):
                failed[name] = f"timeout (> {BUDGET_S}s)"
            else:
                failed[name] = str(exc)
            print(f"[{i+1}/{len(queries)}] {name}: {failed[name]}", flush=True)
        except Exception as exc:
            failed[name] = str(exc)
            print(f"[{i+1}/{len(queries)}] {name}: {failed[name]}", flush=True)

    result = {
        "engine": f"sqlite {sqlite3.sqlite_version} (indexed, in-memory)",
        "scale_factor": 1.0,
        "per_query_budget_s": BUDGET_S,
        "completed": len(per_query),
        "timeout_or_failed": len(failed),
        "geomean_completed_sec": (
            round(
                math.exp(
                    sum(math.log(max(t, 1e-4)) for t in per_query.values())
                    / len(per_query)
                ),
                4,
            )
            if per_query
            else None
        ),
        "per_query": {n: round(t, 3) for n, t in sorted(per_query.items())},
        "failed": failed,
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ("per_query", "failed")}))


if __name__ == "__main__":
    main()
