#!/usr/bin/env python
"""Parallel-ingest chaos gate (ci/tier1-check).

The PR-16 ingest contract over REAL processes: N writer processes shard
one table's generator chunks and commit concurrently through the ledgered
`ingest_chunk` path; one writer is SIGKILLed mid-chunk (staged, hung at
the commit point); the run must come back exactly-once.

Checks, per catalog backend (legacy off, fs CAS, tcp coordinator):

1. **N-writer convergence** — 3 writers x 3 chunks each over one table:
   every surviving writer's chunks land exactly once under OCC rebase
   churn, version history stays linear.
2. **Kill mid-chunk** — the victim commits its first chunk clean, then a
   `hang:commit:<table>` fault holds its second chunk between staging
   and manifest publish; SIGKILL. The chunk must NOT be in the ledger,
   its staged files are unreferenced debris, no rows appear.
3. **Vacuum collects the debris** — with the victim dead (and, under a
   catalog, its writer lease expired + fence advanced), vacuum removes
   the below-fence stage and touches nothing committed.
4. **Exactly-once resume** — `_lakehouse_ingest` re-run over the same
   source replays ONLY the unledgered chunks; the final table holds
   every generated row exactly once and the ledger is complete.

Usage: python tools/ingest_check.py [--keep]
"""

import argparse
import os
import posixpath
import re
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import pyarrow as pa  # noqa: E402

from nds_tpu.lakehouse import catalog as C  # noqa: E402
from nds_tpu.lakehouse.table import LakehouseTable  # noqa: E402
from nds_tpu.schema import get_schemas  # noqa: E402

WRITERS = 3
CHUNKS_PER_WRITER = 3
ROWS_PER_CHUNK = 25
TABLE = "income_band"

_WRITER_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
from nds_tpu.schema import get_schemas
from nds_tpu.transcode import _ingest_chunks
schema = get_schemas(True)[{table!r}]
shard = sys.argv[1].split(",")
rows, committed = _ingest_chunks({dst!r}, {table!r}, schema, True, shard, None)
print("DONE", rows, committed)
"""

# the victim: first chunk commits clean, then a hang fault pins the second
# chunk INSIDE the commit critical section (staged, pre-publish) so the
# parent's SIGKILL is a deterministic death mid-commit
_VICTIM_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
from nds_tpu import faults
from nds_tpu.schema import get_schemas
from nds_tpu.transcode import _ingest_chunks
schema = get_schemas(True)[{table!r}]
shard = sys.argv[1].split(",")
_ingest_chunks({dst!r}, {table!r}, schema, True, shard[:1], None)
print("CHUNK0-DONE", flush=True)
faults.install("hang:commit:" + {table!r} + ":600")
_ingest_chunks({dst!r}, {table!r}, schema, True, shard[1:2], None)
print("VICTIM-SURVIVED-THE-HANG", flush=True)
"""

_RESUME_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
import pyarrow as pa
from nds_tpu.schema import get_schemas
from nds_tpu.transcode import _lakehouse_ingest
schema = get_schemas(True)[{table!r}]
arrow_schema = pa.schema(
    [(f.name, f.dtype.to_arrow(True)) for f in schema]
)
rows = _lakehouse_ingest(
    {src!r}, {dst!r}, {table!r}, schema, arrow_schema, True, 1
)
print("RESUMED", rows)
"""


def _check(ok, label):
    print(f"  {'OK ' if ok else 'FAIL'} {label}")
    if not ok:
        raise SystemExit(f"ingest_check: FAILED: {label}")


def _env(**extra):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "NDS_LAKE_COMMIT_RETRIES": "128",
        "NDS_LAKE_COMMIT_BACKOFF": "0.005",
    }
    env.pop("NDS_FAULT_SPEC", None)
    env.update(extra)
    return env


def _spawn_coordinator(warehouse):
    env = _env(NDS_METRICS_HOST="127.0.0.1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "nds_tpu.cli.catalog", warehouse,
         "--port", "0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.monotonic() + 60
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"coordinating .* on [^:]+:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    if port is None:
        proc.kill()
        raise SystemExit("ingest_check: coordinator never announced a port")
    return proc, f"http://127.0.0.1:{port}"


def _gen_chunks(src):
    """Generator chunk files: every row's surrogate key is globally unique,
    so 'exactly once' is one sorted-list equality at the end."""
    os.makedirs(src)
    sk = 0
    total = WRITERS * CHUNKS_PER_WRITER
    for c in range(total):
        with open(os.path.join(src, f"{TABLE}_{c + 1}_{total}.dat"),
                  "w") as f:
            for _ in range(ROWS_PER_CHUNK):
                f.write(f"{sk}|{sk * 10}|{sk * 10 + 9}|\n")
                sk += 1


def _expected_sks():
    return list(range(WRITERS * CHUNKS_PER_WRITER * ROWS_PER_CHUNK))


def _table_sks(dst):
    return sorted(
        x["ib_income_band_sk"]
        for x in LakehouseTable(dst).dataset().to_table().to_pylist()
    )


def _referenced_basenames(dst):
    lt = LakehouseTable(dst)
    refs = set()
    for v, _, _ in lt.versions():
        for f in lt.snapshot(v).rel_files:
            refs.add(posixpath.basename(f))
    return refs


def _data_basenames(dst):
    d = os.path.join(dst, "data")
    return set(os.listdir(d)) if os.path.isdir(d) else set()


def _ledger(dst):
    return LakehouseTable(dst).snapshot().ingest_chunks()


def _chunk_id(path):
    return f"{TABLE}:{os.path.basename(path)}"


def check_mode(workdir, mode, src):
    print(f"ingest chaos [{mode}]: {WRITERS} writers x "
          f"{CHUNKS_PER_WRITER} chunks, SIGKILL one mid-chunk")
    wh = os.path.join(workdir, f"wh-{mode}")
    os.makedirs(wh)
    dst = os.path.join(wh, TABLE)
    schema = get_schemas(True)[TABLE]
    arrow_schema = pa.schema(
        [(f.name, f.dtype.to_arrow(True)) for f in schema]
    )
    LakehouseTable.create(dst, schema=arrow_schema)
    chunks = sorted(
        os.path.join(src, f) for f in os.listdir(src) if f.endswith(".dat")
    )
    shards = [chunks[w::WRITERS] for w in range(WRITERS)]

    coord = None
    try:
        if mode == "tcp":
            coord, url = _spawn_coordinator(wh)
            extra = {"NDS_LAKE_CATALOG": url}
        elif mode == "fs":
            extra = {"NDS_LAKE_CATALOG": "fs"}
        else:
            extra = {"NDS_LAKE_CATALOG": ""}

        # short writer TTL for the VICTIM only: once killed, its lease
        # expires fast and the vacuum fence can advance past its epoch
        # (survivors keep the default TTL — they release on exit anyway)
        victim = subprocess.Popen(
            [sys.executable, "-c",
             _VICTIM_SCRIPT.format(repo=REPO, dst=dst, table=TABLE),
             ",".join(shards[0])],
            env=_env(NDS_LAKE_WRITER_TTL_S="0.05", **extra),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        survivors = [
            subprocess.Popen(
                [sys.executable, "-c",
                 _WRITER_SCRIPT.format(repo=REPO, dst=dst, table=TABLE),
                 ",".join(shards[w])],
                env=_env(**extra), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for w in range(1, WRITERS)
        ]
        for p in survivors:
            _out, err = p.communicate(timeout=300)
            if p.returncode != 0:
                raise SystemExit(
                    f"ingest_check: writer failed:\n{err.decode()[-3000:]}"
                )

        # wait for the victim's clean first commit...
        line = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = victim.stdout.readline()
            if "CHUNK0-DONE" in line or not line:
                break
        _check("CHUNK0-DONE" in line, "victim committed its first chunk")
        # ...then for its second chunk's stage to appear (the hang holds it
        # between staging and publish); unreferenced data files are the tell
        staged = set()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            staged = _data_basenames(dst) - _referenced_basenames(dst)
            if staged:
                break
            time.sleep(0.05)
        _check(bool(staged), "victim staged its second chunk (hung pre-publish)")
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
    finally:
        if coord is not None:
            coord.terminate()
            coord.wait(timeout=30)

    led = _ledger(dst)
    _check(_chunk_id(shards[0][0]) in led, "victim's clean chunk is ledgered")
    _check(_chunk_id(shards[0][1]) not in led,
           "killed chunk is NOT in the ledger (no torn publish)")
    committed = {_chunk_id(p) for s in shards for p in s} - {
        _chunk_id(shards[0][1]), _chunk_id(shards[0][2])
    }
    _check(led == committed, "ledger holds exactly the committed chunks")

    # vacuum collects the dead victim's below-fence stage, keeps all data
    os.environ["NDS_LAKE_WRITER_TTL_S"] = "0.05"
    if mode == "tcp":
        # the killed coordinator took its fence state with it; the fs
        # catalog arbitrates the same warehouse for the cleanup pass
        os.environ["NDS_LAKE_CATALOG"] = "fs"
    elif mode == "fs":
        os.environ["NDS_LAKE_CATALOG"] = "fs"
    else:
        os.environ.pop("NDS_LAKE_CATALOG", None)
    C.reset_clients()
    try:
        time.sleep(0.2)  # writer-lease TTL elapses; the zombie is fenceable
        LakehouseTable(dst).vacuum()
        remaining = _data_basenames(dst)
        _check(not (staged & remaining),
               "vacuum collected the killed writer's stage")
        _check(_referenced_basenames(dst) <= remaining | staged,
               "vacuum kept every referenced file")

        # resume: only the unledgered chunks replay; exactly-once overall
        res = subprocess.run(
            [sys.executable, "-c",
             _RESUME_SCRIPT.format(repo=REPO, src=src, dst=dst, table=TABLE)],
            env=_env(), capture_output=True, text=True, timeout=300,
        )
        if res.returncode != 0:
            raise SystemExit(
                f"ingest_check: resume failed:\n{res.stderr[-3000:]}"
            )
        resumed = int(res.stdout.split("RESUMED", 1)[1].strip())
        _check(resumed == 2 * ROWS_PER_CHUNK,
               "resume replayed exactly the two missing chunks")
    finally:
        os.environ.pop("NDS_LAKE_WRITER_TTL_S", None)
        os.environ.pop("NDS_LAKE_CATALOG", None)
        C.reset_clients()

    _check(_table_sks(dst) == _expected_sks(),
           "every generated row present exactly once after resume")
    _check(_ledger(dst) == {_chunk_id(p) for s in shards for p in s},
           "ledger complete after resume")
    versions = [v for v, _, _ in LakehouseTable(dst).versions()]
    _check(versions == sorted(versions), "version history is linear")
    # a second resume is a no-op (the whole-run idempotence contract)
    res2 = subprocess.run(
        [sys.executable, "-c",
         _RESUME_SCRIPT.format(repo=REPO, src=src, dst=dst, table=TABLE)],
        env=_env(), capture_output=True, text=True, timeout=300,
    )
    _check(res2.returncode == 0 and "RESUMED 0" in res2.stdout,
           "re-running resume commits nothing (idempotent)")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    ap.add_argument("--modes", default="off,fs,tcp",
                    help="comma-separated catalog backends to exercise")
    args = ap.parse_args()
    workdir = tempfile.mkdtemp(prefix="nds-ingest-check-")
    t0 = time.perf_counter()
    try:
        src = os.path.join(workdir, "raw", TABLE)
        _gen_chunks(src)
        for mode in args.modes.split(","):
            check_mode(workdir, mode.strip(), src)
    finally:
        if args.keep:
            print(f"ingest_check: scratch kept at {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)
    print(f"ingest_check: OK ({time.perf_counter() - t0:.1f}s)")


if __name__ == "__main__":
    main()
