#!/usr/bin/env python
"""CI diagnosis gate (ISSUE 14): the flight recorder + critical-path
profiler proven against real runs.

    python tools/diagnosis_check.py [--data_dir D] [--mesh_trace DIR]

Four checks, all against the marker-cached SF0.01 generated data:

1. WATCHDOG BUNDLE — a power-CLI subprocess runs one query with an
   injected hang and a 2 s watchdog, WITH NO TRACE DIR: the run must
   leave a `failure-bundle-<trace_id>.json` in the flight dir and
   `profile --check` must validate it (bundle keys + ring schema).
2. CRASH BUNDLE — same stream with an injected `crash:exec` rule: the
   process dies nonzero, and the bundle it flushed on the way down must
   exist and validate.
3. CRITICAL-PATH ATTRIBUTION — a traced mini power stream, then
   `profile --critical-path --min_attributed 0.9` over its trace dir:
   >= 90% of every query's wall must land on named causes. With
   `--mesh_trace` (the mesh gate's dumped trace) the same assertion runs
   over the 8-device stream AND the hot-key probe's straggler device
   must be named.
4. FLIGHT-RING OVERHEAD — the ring-only default must cost < 2% of the
   SF0.01 stream's wall: the gate runs the stream with the ring on,
   counts the events it actually recorded, measures the per-event
   ring-emit cost in isolation, and asserts the modeled share
   (events * cost / wall) stays under the budget. (A direct A/B of two
   stream runs would drown the signal in CPU timing noise; the modeled
   share is deterministic and errs high — emit cost is measured with
   dict build included.)

Exit 0 on success; nonzero with a diagnosis on any failure.
"""

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA_DEFAULT = "/tmp/nds_test_sf001"

STREAM = """-- start query 1 in stream 0 using template query96.tpl
select count(*) cnt from store_sales where ss_quantity > 0
;
-- end query 1 in stream 0 using template query96.tpl

-- start query 2 in stream 0 using template query3.tpl
select d_year, count(*) c from date_dim group by d_year order by d_year limit 5
;
-- end query 2 in stream 0 using template query3.tpl

-- start query 3 in stream 0 using template query42.tpl
select d_moy, sum(ss_ext_sales_price) s from store_sales, date_dim
where ss_sold_date_sk = d_date_sk and d_year = 2000
group by d_moy order by d_moy
;
-- end query 3 in stream 0 using template query42.tpl
"""


def ensure_data(data_dir):
    marker = os.path.join(data_dir, ".complete")
    if os.path.exists(marker):
        return
    subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
         "--parallel", "2", "--data_dir", data_dir, "--overwrite_output"],
        check=True, cwd=REPO, capture_output=True,
    )
    open(marker, "w").close()


def mini_warehouse(data_dir, dest):
    os.makedirs(dest, exist_ok=True)
    for t in ("store_sales", "date_dim"):
        link = os.path.join(dest, t)
        if not os.path.exists(link):
            os.symlink(os.path.join(data_dir, t), link)
    return dest


def run_power(wh, stream_path, workdir, env_extra, expect_rc0=True):
    env = dict(os.environ)
    env.pop("NDS_TRACE_DIR", None)
    env.pop("NDS_TRACE_CONTEXT", None)
    env.update(env_extra)
    p = subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.power", wh, stream_path,
         os.path.join(workdir, "time.csv"), "--input_format", "csv"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    if expect_rc0 and p.returncode != 0:
        fail(f"power run unexpectedly failed (rc={p.returncode}):\n"
             f"{p.stdout[-2000:]}\n{p.stderr[-2000:]}")
    return p


def profile_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.profile"] + args,
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )


def fail(msg):
    print(f"diagnosis_check: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def one_bundle(flight_dir):
    bundles = glob.glob(os.path.join(flight_dir, "failure-bundle-*.json"))
    if len(bundles) < 1:
        fail(f"no failure bundle under {flight_dir}")
    return bundles[0]


def check_watchdog_bundle(wh, tmp):
    flight = os.path.join(tmp, "flight-hang")
    stream = os.path.join(tmp, "hang_stream.sql")
    with open(stream, "w") as f:
        f.write(STREAM)
    run_power(wh, stream, tmp, {
        "NDS_FAULT_SPEC": "hang:query96:30",
        "NDS_QUERY_TIMEOUT": "2",
        "NDS_FLIGHT_DIR": flight,
    })
    bundle = one_bundle(flight)
    with open(bundle) as f:
        b = json.load(f)
    if b.get("reason") != "watchdog" or b.get("query") != "query96":
        fail(f"watchdog bundle misattributed: reason={b.get('reason')} "
             f"query={b.get('query')}")
    p = profile_cli([bundle, "--check"])
    if p.returncode != 0:
        fail(f"profile --check rejected the watchdog bundle:\n{p.stderr}")
    print(f"diagnosis_check: watchdog bundle ok ({os.path.basename(bundle)},"
          f" {len(b['events'])} ring events)")


def check_crash_bundle(wh, tmp):
    flight = os.path.join(tmp, "flight-crash")
    stream = os.path.join(tmp, "crash_stream.sql")
    with open(stream, "w") as f:
        f.write(STREAM)
    p = run_power(wh, stream, tmp, {
        "NDS_FAULT_SPEC": "crash:exec:query3",
        "NDS_FLIGHT_DIR": flight,
    }, expect_rc0=False)
    if p.returncode == 0:
        fail("crash-injected power run exited 0 (crash never fired?)")
    bundle = one_bundle(flight)
    with open(bundle) as f:
        b = json.load(f)
    if b.get("reason") != "crash":
        fail(f"crash bundle reason={b.get('reason')}")
    pc = profile_cli([bundle, "--check"])
    if pc.returncode != 0:
        fail(f"profile --check rejected the crash bundle:\n{pc.stderr}")
    print(f"diagnosis_check: crash bundle ok ({len(b['events'])} ring "
          f"events from the dying process)")


def check_critical_path(wh, tmp, mesh_trace=None):
    trace = os.path.join(tmp, "trace-cp")
    stream = os.path.join(tmp, "cp_stream.sql")
    with open(stream, "w") as f:
        f.write(STREAM)
    run_power(wh, stream, tmp, {"NDS_TRACE_DIR": trace})
    p = profile_cli([trace, "--critical-path", "--min_attributed", "0.9"])
    if p.returncode != 0:
        fail(f"single-device critical path under 90% attribution:\n"
             f"{p.stdout[-3000:]}\n{p.stderr}")
    print("diagnosis_check: single-device critical path ok "
          "(>= 90% of every query's wall attributed)")
    if not mesh_trace:
        return
    if not glob.glob(os.path.join(mesh_trace, "events-*.jsonl")):
        fail(f"mesh trace dir {mesh_trace} has no event files (did the "
             f"mesh gate run with --trace_dir?)")
    p = profile_cli(
        [mesh_trace, "--critical-path", "--min_attributed", "0.9", "--json"]
    )
    if p.returncode != 0:
        fail(f"mesh critical path under 90% attribution:\n"
             f"{p.stdout[-3000:]}\n{p.stderr}")
    cp = json.loads(p.stdout)
    probe = cp["queries"].get("hotkey_probe")
    if not probe or not probe.get("exchange"):
        fail("mesh trace has no hot-key probe exchange evidence")
    if probe["exchange"].get("straggler_device") is None:
        fail("critical path failed to name the hot-key probe's straggler "
             "device")
    if (cp.get("mesh") or {}).get("straggler_device") is None:
        fail("mesh summary names no straggler device")
    print(f"diagnosis_check: mesh critical path ok (straggler device "
          f"{probe['exchange']['straggler_device']} on the hot-key probe, "
          f"skew share "
          f"{(cp['mesh'] or {}).get('skew_share')})")


def check_ring_overhead(wh, tmp):
    # in-process: run the mini stream ring-only and model the ring's share
    os.environ.pop("NDS_TRACE_DIR", None)
    os.environ["NDS_FLIGHT_DIR"] = os.path.join(tmp, "flight-oh")
    from nds_tpu.obs import flight as FL
    from nds_tpu.obs.trace import Tracer
    from nds_tpu.power import gen_sql_from_stream, run_query_stream

    FL.reset_shared()
    rec = FL.recorder()
    stream = os.path.join(tmp, "oh_stream.sql")
    with open(stream, "w") as f:
        f.write(STREAM)
    before = rec.events_recorded
    t0 = time.perf_counter()
    run_query_stream(
        input_prefix=wh, property_file=None,
        query_dict=gen_sql_from_stream(stream),
        time_log_output_path=os.path.join(tmp, "oh_time.csv"),
        input_format="csv",
    )
    wall_s = time.perf_counter() - t0
    n_events = rec.events_recorded - before
    if n_events <= 0:
        fail("ring-only stream recorded no events (ring wired wrong?)")
    # isolated per-event cost of the ring path (dict build + stamp + append)
    tr = Tracer(None, collect=False)  # ring-only shape
    n_bench = 50_000
    t0 = time.perf_counter()
    for i in range(n_bench):
        tr.emit("plan_cache", node="Aggregate", hit=False, query="q")
    per_event_s = (time.perf_counter() - t0) / n_bench
    share = (per_event_s * n_events) / wall_s
    print(f"diagnosis_check: flight ring recorded {n_events} events over "
          f"{wall_s:.2f}s; {per_event_s * 1e6:.1f}us/event -> modeled "
          f"share {share:.3%} of wall (budget 2%)")
    if share >= 0.02:
        fail(f"flight-ring overhead {share:.2%} exceeds the 2% budget")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--data_dir", default=os.environ.get(
        "NDS_DIAG_DATA", DATA_DEFAULT))
    ap.add_argument("--mesh_trace", default=None,
                    help="mesh gate trace dir (mesh_stream_check "
                    "--trace_dir) for the mesh-mode attribution check")
    args = ap.parse_args(argv)
    ensure_data(args.data_dir)
    tmp = tempfile.mkdtemp(prefix="nds_diag_")
    try:
        wh = mini_warehouse(args.data_dir, os.path.join(tmp, "wh"))
        check_watchdog_bundle(wh, tmp)
        check_crash_bundle(wh, tmp)
        check_critical_path(wh, tmp, mesh_trace=args.mesh_trace)
        check_ring_overhead(wh, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("diagnosis_check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
