"""CI microbench guard: fused-pipeline executable reuse across a stream.

Runs a small synthetic query stream TWICE in one session — first pass
untraced (it compiles the executables), second pass traced — then gates on
the profiler's executable-cache hit rate over the traced pass:

    python tools/fuse_microbench.py        # exits nonzero below 80%

A steady-state re-run of a stream must reuse the compiled pipelines (the
whole point of shape-bucketed executable reuse); a refactor that silently
changes pipeline fingerprints, input signatures, or the cache keying drops
the rate to ~0 and fails this gate. Wired into ci/tier1-check.
"""

import os
import sys
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

MIN_HIT_RATE = float(os.environ.get("NDS_FUSE_MICROBENCH_MIN_RATE", "0.8"))

# a miniature "stream": the chain shapes the fuser must keep compiled —
# numeric filters, string predicates over dictionaries, computed
# projections, chains feeding aggregates, post-join wrappers, sort+limit
STREAM = [
    "select k, v from t where v > 10 and k is not null order by k, v",
    "select k, v * 2 vv, cat from t where cat like 'B%' order by k, vv",
    "select k, sum(v) sv, avg(v) av from t where v > -50 group by k "
    "order by k",
    "select x.k, x.s from (select t.k \"k\", t.v + u.v s from t, u "
    "where t.k = u.k and t.v > u.v) x where x.s > 5 order by x.k, x.s "
    "limit 20",
    "select k, case when v > 0 then v else -v end a from t "
    "where cat in ('Books', 'Shoes') order by k, a limit 50",
]


def _table(n, seed):
    r = np.random.default_rng(seed)
    ks = r.integers(0, 12, n)
    vs = r.integers(-90, 90, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 9 == 0 else int(x) for i, x in enumerate(ks)],
                pa.int32(),
            ),
            "v": pa.array(vs, pa.int64()),
            "cat": pa.array(
                [["Books", "Music", "Shoes"][int(x) % 3] for x in ks],
                pa.string(),
            ),
        }
    )


def main():
    from nds_tpu.engine.session import Session
    from nds_tpu.obs.trace import tracer_from_conf

    with tempfile.TemporaryDirectory(prefix="nds_fuse_mb_") as trace_dir:
        sess = Session()
        sess.register_arrow("t", _table(3000, 1))
        sess.register_arrow("u", _table(3000, 2))
        # pass 1 (untraced): compile the stream's pipeline executables
        for q in STREAM:
            sess.sql(q).collect()
        # pass 2 (traced, plan-result cache off so every pipeline really
        # executes): must ride the executable cache
        sess.conf["engine.plan_cache"] = "off"
        sess.tracer = tracer_from_conf({"engine.trace_dir": trace_dir})
        for q in STREAM:
            sess.sql(q).collect()
        sess.tracer.close()

        from nds_tpu.cli import profile as profile_cli

        try:
            profile_cli.main(
                [
                    trace_dir,
                    "--check",
                    "--min_exec_cache_hit_rate",
                    str(MIN_HIT_RATE),
                ]
            )
        except SystemExit as exc:
            code = int(exc.code or 0)
            if code:
                print(
                    f"fuse_microbench: FAILED (profiler gate exit {code})",
                    file=sys.stderr,
                )
            sys.exit(code)
    print("fuse_microbench: OK")


if __name__ == "__main__":
    main()
