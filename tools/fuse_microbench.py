"""CI microbench guard: fused-pipeline executable reuse across a stream,
plus a measured dispatch-count reduction from aggregate-tail fusion,
plus the TWO-PROCESS persistent-AOT-cache gate.

Part 1 runs a small synthetic query stream (Filter/Project chains AND
agg-chain shapes) TWICE in one session — first pass untraced (it compiles
the executables), second pass traced — then gates on the profiler's
executable-cache hit rate over the traced pass:

    python tools/fuse_microbench.py        # exits nonzero below 80%

A steady-state re-run of a stream must reuse the compiled pipelines (the
whole point of shape-bucketed executable reuse); a refactor that silently
changes pipeline fingerprints, input signatures, or the cache keying drops
the rate to ~0 and fails this gate.

Part 2 measures steady-state device-dispatch counts (kernel_span events +
fused pipeline calls under NDS_TRACE_KERNELS-style tracing) for the plan
shapes of the bench's tail queries — the multi-key grouped sum/avg chain
(q4/q14's year_total), the global filtered aggregate (q9's bucket
probes), and the join-fed grouped sum (q78) — eager vs fused, and
requires the fused path to dispatch strictly fewer times on every shape.

Part 3 is the cold-start kill gate (ISSUE 11): process A runs the stream
against a fresh AOT cache dir (engine/aotcache.py) — compiling and
SERIALIZING every pipeline executable — then a separate process B runs
the same stream cold against the same dir with the XLA persistent cache
disabled. B's cold pass must resolve its executables FROM DISK (>= 80%
aot_cache disk-hit rate, read from B's trace events) and land within
1.15x of A's steady-pass wall (NDS_AOT_MB_MAX_RATIO; a small absolute
grace, NDS_AOT_MB_GRACE_S, absorbs constant per-process overhead like
tracing and table upload — recompiles cost seconds, not fractions).
All three are wired into ci/tier1-check.
"""

import os
import sys
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

MIN_HIT_RATE = float(os.environ.get("NDS_FUSE_MICROBENCH_MIN_RATE", "0.8"))

# a miniature "stream": the chain shapes the fuser must keep compiled —
# numeric filters, string predicates over dictionaries, computed
# projections, chains feeding aggregates, post-join wrappers, sort+limit
STREAM = [
    "select k, v from t where v > 10 and k is not null order by k, v",
    "select k, v * 2 vv, cat from t where cat like 'B%' order by k, vv",
    "select k, sum(v) sv, avg(v) av from t where v > -50 group by k "
    "order by k",
    "select x.k, x.s from (select t.k \"k\", t.v + u.v s from t, u "
    "where t.k = u.k and t.v > u.v) x where x.s > 5 order by x.k, x.s "
    "limit 20",
    "select k, case when v > 0 then v else -v end a from t "
    "where cat in ('Books', 'Shoes') order by k, a limit 50",
    # agg-chain shapes: the aggregate tail must compile INTO the pipeline
    # and its executable must be reused on the second pass
    "select k, k2, sum(v) s, count(*) c from t where v > -60 "
    "group by k, k2 order by k, k2",
    "select count(*) c, avg(v) a, sum(v) s from t where v between 0 and 40",
]

# steady-state dispatch A/B: synthetic stand-ins for the tail queries'
# plan shapes (same operator chains, toy data) — eager must dispatch more
TAIL_SHAPES = {
    # q4/q14 year_total: filter + computed projection feeding a multi-key
    # grouped sum/avg
    "q4_year_total": (
        "select k, k2, sum(v) s, avg(v) a, count(*) c from t "
        "where v > -50 and k is not null group by k, k2 order by k, k2"
    ),
    # q9: ranged global aggregates over the fact scan
    "q9_global": (
        "select count(*) c, avg(v) a, sum(v) s from t "
        "where v between 0 and 40"
    ),
    # q78: join output feeding a grouped sum
    "q78_join_group": (
        "select t.k, sum(t.v) sv, sum(u.v) uv from t, u "
        "where t.k = u.k group by t.k order by t.k"
    ),
}


def _table(n, seed):
    r = np.random.default_rng(seed)
    ks = r.integers(0, 12, n)
    k2s = r.integers(0, 6, n)
    vs = r.integers(-90, 90, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 9 == 0 else int(x) for i, x in enumerate(ks)],
                pa.int32(),
            ),
            "k2": pa.array(k2s, pa.int32()),
            "v": pa.array(vs, pa.int64()),
            "cat": pa.array(
                [["Books", "Music", "Shoes"][int(x) % 3] for x in ks],
                pa.string(),
            ),
        }
    )


def _steady_dispatches(query, fuse_conf, trace_dir):
    """Counted device dispatches of one steady-state execution: kernel
    entry points (kernel_span, synchronized) + fused pipeline calls. An
    undercount of the eager path (per-stage elementwise ops are not kernel
    entry points) — which only makes the fused<eager assertion stricter."""
    from nds_tpu.engine.session import Session
    from nds_tpu.obs import reader as R
    from nds_tpu.obs import trace as obs_trace

    sess = Session(conf=dict(fuse_conf, **{
        "engine.plan_cache": "off",
        "engine.trace_dir": trace_dir,
        "engine.trace_kernels": "on",
    }))
    sess.register_arrow("t", _table(3000, 1))
    sess.register_arrow("u", _table(3000, 2))
    warm_tracer, sess.tracer = sess.tracer, None
    sess.sql(query).collect()  # cold: compiles; dispatches untraced
    sess.tracer = warm_tracer
    with obs_trace.bind(sess.tracer):
        sess.sql(query).collect()  # steady: every dispatch traced
    sess.tracer.close()
    events = R.read_events([trace_dir], strict=True)
    n = 0
    for ev in events:
        if ev.get("kind") == "kernel_span":
            n += 1
        elif ev.get("kind") == "pipeline_span" and ev.get("fused"):
            n += 1
    return n


def dispatch_ab():
    """Eager-vs-fused steady dispatch counts per tail shape; fails unless
    the fused path dispatches strictly fewer times on EVERY shape."""
    import tempfile

    failures = []
    for name, q in TAIL_SHAPES.items():
        with tempfile.TemporaryDirectory(prefix="nds_mb_e_") as de, \
                tempfile.TemporaryDirectory(prefix="nds_mb_f_") as df:
            eager = _steady_dispatches(q, {"engine.fuse": "off"}, de)
            fused = _steady_dispatches(q, {}, df)
        verdict = "OK" if fused < eager else "NO REDUCTION"
        print(f"fuse_microbench: {name}: eager {eager} -> fused {fused} "
              f"dispatches ({verdict})")
        if fused >= eager:
            failures.append(name)
    if failures:
        print(
            f"fuse_microbench: FAILED (no steady dispatch reduction on: "
            f"{', '.join(failures)})",
            file=sys.stderr,
        )
        sys.exit(1)


def _aot_table(n, seed):
    """Fact-shaped tables for the two-process gate: the same columns as
    _table, but the join key's cardinality scales with n (a 12-value key
    at gate scale would make the t-join-u shape quadratic) — steady-state
    work stays meaningful next to the constant per-process overheads the
    wall-ratio gate must not be dominated by."""
    r = np.random.default_rng(seed)
    kdom = max(12, n // 16)
    ks = r.integers(0, kdom, n)
    return pa.table(
        {
            "k": pa.array(
                [None if i % 9 == 0 else int(x) for i, x in enumerate(ks)],
                pa.int32(),
            ),
            "k2": pa.array(r.integers(0, 6, n), pa.int32()),
            "v": pa.array(r.integers(-90, 90, n), pa.int64()),
            "cat": pa.array(
                [["Books", "Music", "Shoes"][int(x) % 3] for x in ks],
                pa.string(),
            ),
        }
    )


def aot_child_main():
    """One process of the two-process AOT gate (NDS_MB_AOT_ROLE=child):
    run the stream cold (wall-timed), then steady (plan cache off so every
    pipeline really executes), and report walls + the session's AOT cache
    stats as one JSON line on stdout."""
    import json
    import time

    from nds_tpu.engine.session import Session

    rows = int(os.environ.get("NDS_AOT_MB_ROWS", "200000"))
    sess = Session(conf={
        "engine.aot_cache_dir": os.environ["NDS_MB_CACHE_DIR"],
        "engine.trace_dir": os.environ["NDS_MB_TRACE_DIR"],
    })
    sess.register_arrow("t", _aot_table(rows, 1))
    sess.register_arrow("u", _aot_table(rows, 2))
    t0 = time.perf_counter()
    for q in STREAM:
        sess.sql(q).collect()
    cold_wall = time.perf_counter() - t0
    sess.conf["engine.plan_cache"] = "off"
    t0 = time.perf_counter()
    for q in STREAM:
        sess.sql(q).collect()
    steady_wall = time.perf_counter() - t0
    if sess.tracer is not None:
        sess.tracer.close()
    print(json.dumps({
        "cold_wall": cold_wall,
        "steady_wall": steady_wall,
        "aot": dict(sess.aot_cache.stats) if sess.aot_cache else None,
    }), flush=True)


def _run_aot_child(cache_dir, trace_dir, xla_cache_dir):
    import json
    import subprocess

    env = dict(os.environ)
    env["NDS_MB_AOT_ROLE"] = "child"
    env["NDS_MB_CACHE_DIR"] = cache_dir
    env["NDS_MB_TRACE_DIR"] = trace_dir
    # the gate models the PRODUCTION cold-start pair: this engine's AOT
    # cache serves the fused-pipeline executables (trace-verified below —
    # the XLA cache cannot produce aot_cache hit events) while a shared
    # XLA persistent cache covers the canonical kernels (sort/join/agg
    # entry points) the AOT layer deliberately does not own. A fresh
    # temp dir per gate run keeps both halves honest: nothing is warm
    # until process A warms it.
    env["NDS_XLA_CACHE_DIR"] = xla_cache_dir
    # persist even sub-100ms kernel compiles: on CPU the canonical
    # kernels each compile in ~10ms, and 100+ of them ARE the cold start
    env["NDS_XLA_CACHE_MIN_COMPILE_S"] = "0"
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if p.returncode != 0:
        print(p.stdout, file=sys.stderr)
        print(p.stderr[-2000:], file=sys.stderr)
        raise RuntimeError(f"aot child exited rc={p.returncode}")
    for line in reversed(p.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError("aot child produced no JSON line")


def two_process_aot():
    """Process A warms the shared cache dir; a FRESH process B's cold pass
    must deserialize from disk (>= 80% aot disk-hit rate, trace-event
    evidence) and land within NDS_AOT_MB_MAX_RATIO (1.15) of A's steady
    wall (+ a small constant grace for per-process setup)."""
    import tempfile

    from nds_tpu.obs import reader as R

    # the wall bound: max(ratio x steady, steady + grace). The ratio is
    # the headline contract (recompiles cost SECONDS); the grace absorbs
    # the constant per-process cost a warmed process still pays at gate
    # scale — kernel re-tracing, catalog upload, disk loads — measured at
    # ~1.8s on the 1-core CI host against a ~2.7s steady pass. The teeth
    # check below proves the bound still catches an UNWARMED process.
    max_ratio = float(os.environ.get("NDS_AOT_MB_MAX_RATIO", "1.15"))
    grace_s = float(os.environ.get("NDS_AOT_MB_GRACE_S", "2.5"))
    min_rate = float(os.environ.get("NDS_AOT_MB_MIN_RATE", "0.8"))
    with tempfile.TemporaryDirectory(prefix="nds_mb_aot_") as root:
        cache_dir = os.path.join(root, "cache")
        xla_dir = os.path.join(root, "xla")
        trace_a = os.path.join(root, "trace_a")
        trace_b = os.path.join(root, "trace_b")
        a = _run_aot_child(cache_dir, trace_a, xla_dir)
        b = _run_aot_child(cache_dir, trace_b, xla_dir)
        prof_b = R.load_profile([trace_b], strict=True)
        rate = R.aot_disk_hit_rate(prof_b)
        print(
            f"fuse_microbench: aot two-process: A cold {a['cold_wall']:.2f}s "
            f"steady {a['steady_wall']:.2f}s; B cold {b['cold_wall']:.2f}s; "
            f"B disk-hit rate "
            f"{'-' if rate is None else f'{rate:.1%}'} (stats {b['aot']})"
        )
        failures = []
        if rate is None or rate < min_rate:
            failures.append(
                f"fresh process resolved executables from disk at rate "
                f"{rate if rate is None else round(rate, 3)} < {min_rate} "
                f"(cold start still recompiles)"
            )
        bound = max(max_ratio * a["steady_wall"], a["steady_wall"] + grace_s)
        if b["cold_wall"] > bound:
            failures.append(
                f"warmed cold wall {b['cold_wall']:.2f}s exceeds "
                f"{bound:.2f}s (= max({max_ratio} x steady, steady + "
                f"{grace_s}s))"
            )
        if a["cold_wall"] <= bound:
            # teeth check: the UNWARMED process A must exceed the bound,
            # or this gate could pass with the cache doing nothing.
            # Informational (A's cold cost shrinks as compiles get
            # cheaper, which is not a defect) — but visible in CI logs.
            print(
                f"fuse_microbench: WARNING: aot gate bound {bound:.2f}s "
                f"would not catch the unwarmed cold wall "
                f"{a['cold_wall']:.2f}s (gate losing teeth)",
                file=sys.stderr,
            )
        if failures:
            for f in failures:
                print(f"fuse_microbench: FAILED ({f})", file=sys.stderr)
            sys.exit(1)


def main():
    from nds_tpu.engine.session import Session
    from nds_tpu.obs.trace import tracer_from_conf

    with tempfile.TemporaryDirectory(prefix="nds_fuse_mb_") as trace_dir:
        sess = Session()
        sess.register_arrow("t", _table(3000, 1))
        sess.register_arrow("u", _table(3000, 2))
        # pass 1 (untraced): compile the stream's pipeline executables
        for q in STREAM:
            sess.sql(q).collect()
        # pass 2 (traced, plan-result cache off so every pipeline really
        # executes): must ride the executable cache
        sess.conf["engine.plan_cache"] = "off"
        sess.tracer = tracer_from_conf({"engine.trace_dir": trace_dir})
        for q in STREAM:
            sess.sql(q).collect()
        sess.tracer.close()

        from nds_tpu.cli import profile as profile_cli

        try:
            profile_cli.main(
                [
                    trace_dir,
                    "--check",
                    "--min_exec_cache_hit_rate",
                    str(MIN_HIT_RATE),
                ]
            )
        except SystemExit as exc:
            code = int(exc.code or 0)
            if code:
                print(
                    f"fuse_microbench: FAILED (profiler gate exit {code})",
                    file=sys.stderr,
                )
                sys.exit(code)
    dispatch_ab()
    two_process_aot()
    print("fuse_microbench: OK")


if __name__ == "__main__":
    if os.environ.get("NDS_MB_AOT_ROLE") == "child":
        aot_child_main()
    else:
        main()
