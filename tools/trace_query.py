"""Dev tool: per-plan-node steady-state timing for one query on the chip.

Usage: python tools/trace_query.py query4 [query14_part2 ...]
Runs each query twice (cold then traced steady) and prints the slowest
plan nodes with INCLUSIVE wall time, output rows, and estimated output
bytes — read from the obs subsystem's in-memory tracer (the same op_span
events `NDS_TRACE_DIR` + `nds_tpu.cli.profile` consume at run scale).
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nds_tpu.engine.session import Session
from nds_tpu.obs.trace import Tracer
from nds_tpu.schema import get_schemas
from nds_tpu.datagen.query_streams import generate_streams
from nds_tpu.power import gen_sql_from_stream

DATA_DIR = os.environ.get("NDS_BENCH_DATA", "/tmp/nds_bench_sf1.0")

with tempfile.TemporaryDirectory() as d:
    generate_streams(d, 1, 1, rngseed=19620718)
    queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))

sess = Session()
sess.conf["engine.plan_cache"] = "off"
for t, schema in get_schemas().items():
    p = os.path.join(DATA_DIR, t)
    if os.path.isdir(p):
        sess.register_csv_dir(t, p, schema)

for qname in sys.argv[1:]:
    r = sess.run_script(queries[qname])  # warm compile caches
    if r is not None:
        r.collect()
    sess.tracer = tracer = Tracer()  # in-memory mode: events collect in a list
    t0 = time.perf_counter()
    r = sess.run_script(queries[qname])
    if r is not None:
        r.collect()
    total = time.perf_counter() - t0
    sess.tracer = None
    spans = [e for e in tracer.events if e["kind"] == "op_span"]
    print(f"\n=== {qname}: steady {total:.2f}s, {len(spans)} nodes ===")
    for ev in sorted(spans, key=lambda e: -e["dur_ms"])[:18]:
        rows = "-" if ev["rows"] is None else f"{ev['rows']:,}"
        print(
            f"  {ev['dur_ms'] / 1000:7.3f}s  {ev['node']:12s} "
            f"rows={rows:>12s}  ~{ev['est_bytes'] / 1e6:8.1f}MB  "
            f"{ev['explain']}"
        )
