"""q3 regression closer (docs/q3_regression.md): assert the join-order
memo holds the q3 shape's steady-state throughput.

Round 5 measured q3 at 2.92M fact-rows/s vs round 4's 3.31M — one extra
blocking device->host sync per steady run from the MultiJoin greedy cost
scan. `Session.join_order_cache` replays the recorded order instead; this
tool closes the loop with an executable assertion in two modes:

    python tools/q3_check.py              # structural (CI; synthetic data)
    python tools/q3_check.py --real       # measured (bench data required)

Structural mode builds a synthetic q3-shaped star (date_dim ⋈ store_sales
⋈ item, the exact bench QUERY text) and asserts the memo records the join
order on the cold run and replays it — unchanged, no re-record — on the
steady run with an identical result. Measured mode runs the real bench
measurement (NDS_BENCH_DATA, same protocol as bench.bench_q3) and fails
below NDS_Q3_MIN_ROWS_PER_SEC (default 3.2M rows/s — the round-4 rate the
memo must restore). Structural is wired into ci/tier1-check; measured
belongs to bench rounds on real data.
"""

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if "--real" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MIN_ROWS_PER_SEC = float(
    os.environ.get("NDS_Q3_MIN_ROWS_PER_SEC", "3200000")
)


def _q3_query():
    from bench import QUERY

    return QUERY


def _synthetic_star(n_fact=200_000, seed=11):
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(seed)
    n_dates, n_items = 400, 300
    date_dim = pa.table(
        {
            "d_date_sk": pa.array(range(n_dates), pa.int32()),
            "d_year": pa.array(
                [1998 + (i // 120) for i in range(n_dates)], pa.int32()
            ),
            "d_moy": pa.array([1 + i % 12 for i in range(n_dates)],
                              pa.int32()),
        }
    )
    item = pa.table(
        {
            "i_item_sk": pa.array(range(n_items), pa.int32()),
            "i_brand_id": pa.array(
                [int(x) for x in r.integers(1, 40, n_items)], pa.int32()
            ),
            "i_brand": pa.array([f"brand#{i % 40}" for i in range(n_items)]),
            "i_manager_id": pa.array(
                [int(x) for x in r.integers(1, 20, n_items)], pa.int32()
            ),
        }
    )
    store_sales = pa.table(
        {
            "ss_sold_date_sk": pa.array(
                [int(x) for x in r.integers(0, n_dates, n_fact)], pa.int32()
            ),
            "ss_item_sk": pa.array(
                [int(x) for x in r.integers(0, n_items, n_fact)], pa.int32()
            ),
            "ss_ext_sales_price": pa.array(
                [round(float(x), 2) for x in r.uniform(0, 500, n_fact)],
                pa.float64(),
            ),
        }
    )
    return {"date_dim": date_dim, "store_sales": store_sales, "item": item}


def structural():
    from nds_tpu.engine.session import Session

    sess = Session(conf={"engine.plan_cache": "off"})
    for name, t in _synthetic_star().items():
        sess.register_arrow(name, t)
    q = _q3_query()
    cold = sess.sql(q).collect()
    recorded = {
        fp: dict(v) for fp, v in sess.join_order_cache.items() if "steps" in v
    }
    if not recorded:
        print("q3_check: FAILED (cold run recorded no join order — the "
              "memo is not engaging on the q3 shape)", file=sys.stderr)
        sys.exit(1)
    steady = sess.sql(q).collect()
    if not steady.equals(cold):
        print("q3_check: FAILED (replayed join order changed the result)",
              file=sys.stderr)
        sys.exit(1)
    for fp, v in recorded.items():
        now = sess.join_order_cache.get(fp)
        if now is None or now.get("steps") != v["steps"]:
            print("q3_check: FAILED (steady run re-recorded the join "
                  "order instead of replaying the memo)", file=sys.stderr)
            sys.exit(1)
    print(f"q3_check: OK (structural: {len(recorded)} join order(s) "
          f"recorded cold, replayed steady, identical result)")


def real():
    import statistics

    from bench import DATA_DIR, ensure_data
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    ensure_data()
    sess = Session(conf={"engine.plan_cache": "off"})
    for t, schema in get_schemas().items():
        path = os.path.join(DATA_DIR, t)
        if os.path.isdir(path):
            sess.register_csv_dir(t, path, schema)
    fact_rows = sess.catalog.load("store_sales").nrows
    q = _q3_query()
    sess.sql(q).collect()  # cold: transfer + compile + memo record
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        sess.sql(q).collect()
        times.append(time.perf_counter() - t0)
    rate = fact_rows / statistics.median(times)
    verdict = "OK" if rate >= MIN_ROWS_PER_SEC else "FAILED"
    print(f"q3_check: {verdict} (measured {rate:,.0f} fact-rows/s steady, "
          f"floor {MIN_ROWS_PER_SEC:,.0f})")
    if rate < MIN_ROWS_PER_SEC:
        sys.exit(1)


def main():
    if "--real" in sys.argv:
        real()
    else:
        structural()


if __name__ == "__main__":
    main()
