#!/usr/bin/env python
"""Fleet-catalog chaos gate (ci/tier1-check).

Three acceptance checks over REAL processes:

1. **Multi-process commit convergence** — 3 writer processes x N commits
   against one table converge to exactly 3xN applied appends with a
   LINEAR version history (one winner per version, loser rebases, no
   rows lost or doubled), for the legacy filesystem mode AND both
   catalog backends (fs CAS, tcp coordinator subprocess).
2. **Coordinator crash mid-commit** — the coordinator process is
   SIGKILLed between its WAL intent and the manifest publish (hang fault
   at `catalog:commit` opens the window); restart recovery rolls the
   unacknowledged intent back, no committed version is lost, no manifest
   is torn, and the retried transaction lands its rows exactly once.
3. **Vacuum under a remote-host lease** — with `_is_local() == False`
   (remote-warehouse mode) vacuum never removes a file a lease from
   ANOTHER host covers, and epoch fencing collects a fenced zombie's
   stage without pid liveness.

Usage: python tools/catalog_check.py [--keep]
"""

import argparse
import json
import os
import posixpath
import re
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import pyarrow as pa  # noqa: E402

from nds_tpu.lakehouse import catalog as C  # noqa: E402
from nds_tpu.lakehouse.table import LakehouseTable  # noqa: E402

WRITERS = 3
COMMITS = 4

_WRITER_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
import pyarrow as pa
from nds_tpu.lakehouse.table import LakehouseTable
t = LakehouseTable({path!r})
base = int(sys.argv[1])
for i in range({commits}):
    t.append(pa.table({{"a": pa.array([base + i])}}))
"""


def _ints(*vals):
    return pa.table({"a": pa.array(list(vals), type=pa.int64())})


def _vals(path):
    return sorted(
        x["a"] for x in LakehouseTable(path).dataset().to_table().to_pylist()
    )


def _versions(path):
    return [v for v, _, _ in LakehouseTable(path).versions()]


def _check(ok, label):
    print(f"  {'OK ' if ok else 'FAIL'} {label}")
    if not ok:
        raise SystemExit(f"catalog_check: FAILED: {label}")


def _env(**extra):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "NDS_LAKE_COMMIT_RETRIES": "128",
        "NDS_LAKE_COMMIT_BACKOFF": "0.005",
    }
    env.update(extra)
    return env


def _spawn_coordinator(warehouse, fault_spec=None):
    """Start a REAL coordinator subprocess on an ephemeral port; returns
    (proc, url)."""
    env = _env(NDS_METRICS_HOST="127.0.0.1")
    env.pop("NDS_FAULT_SPEC", None)
    if fault_spec:
        env["NDS_FAULT_SPEC"] = fault_spec
    proc = subprocess.Popen(
        [sys.executable, "-m", "nds_tpu.cli.catalog", warehouse,
         "--port", "0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.monotonic() + 60
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"coordinating .* on [^:]+:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    if port is None:
        proc.kill()
        raise SystemExit("catalog_check: coordinator never announced a port")
    return proc, f"http://127.0.0.1:{port}"


def _run_writers(path, extra_env):
    script = _WRITER_SCRIPT.format(repo=REPO, path=path, commits=COMMITS)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(1000 * (w + 1))],
            env=_env(**extra_env), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for w in range(WRITERS)
    ]
    for p in procs:
        _out, err = p.communicate(timeout=300)
        if p.returncode != 0:
            raise SystemExit(
                f"catalog_check: writer failed:\n{err.decode()[-3000:]}"
            )


def check_convergence(workdir):
    """3 writer processes x N commits -> exactly 3xN appended rows, one
    winner per version, linear history — every mode."""
    for mode in ("off", "fs", "tcp"):
        print(f"convergence [{mode}]: {WRITERS} procs x {COMMITS} commits")
        wh = os.path.join(workdir, f"wh-{mode}")
        os.makedirs(wh)
        path = os.path.join(wh, "t")
        LakehouseTable.create(path, _ints(0))
        coord = None
        try:
            if mode == "tcp":
                coord, url = _spawn_coordinator(wh)
                extra = {"NDS_LAKE_CATALOG": url}
            elif mode == "fs":
                extra = {"NDS_LAKE_CATALOG": "fs"}
            else:
                extra = {"NDS_LAKE_CATALOG": ""}
            _run_writers(path, extra)
        finally:
            if coord is not None:
                coord.terminate()
                coord.wait(timeout=30)
        expected = sorted([0] + [
            1000 * (w + 1) + i for w in range(WRITERS)
            for i in range(COMMITS)
        ])
        _check(_vals(path) == expected,
               f"{WRITERS * COMMITS} appends all applied exactly once")
        _check(_versions(path) == list(range(1, WRITERS * COMMITS + 2)),
               "version history is linear (one winner per version)")
        # every manifest parses whole (no torn publish anywhere)
        for v in _versions(path):
            LakehouseTable(path).snapshot(v)
        _check(True, "every manifest parses (no torn publish)")


def check_crash_mid_commit(workdir):
    """SIGKILL the coordinator between WAL intent and publish; restart
    recovery must lose no committed version, tear no manifest, and the
    retried transaction must land exactly once."""
    print("coordinator crash mid-commit -> restart recovery")
    wh = os.path.join(workdir, "wh-crash")
    os.makedirs(wh)
    path = os.path.join(wh, "t")
    LakehouseTable.create(path, _ints(1))
    # the hang fault holds the coordinator INSIDE the commit critical
    # section (after the WAL intent, before the publish) long enough for
    # a deterministic SIGKILL — a crash exactly mid-commit
    coord, url = _spawn_coordinator(wh, fault_spec="hang:catalog:commit:60")
    client_conf = {"engine.lake_catalog": url}
    os.environ["NDS_LAKE_CATALOG_TIMEOUT_S"] = "3"
    os.environ["NDS_LAKE_CATALOG_POLL_S"] = "0.5"
    try:
        t = LakehouseTable(path, conf=client_conf)
        try:
            t.append(_ints(2))
            _check(False, "commit must not complete under the crash")
        except Exception as exc:
            from nds_tpu import faults

            _check(faults.classify(exc) == faults.IO_TRANSIENT,
                   f"cut-off commit classified retryable ({type(exc).__name__})")
    finally:
        coord.send_signal(signal.SIGKILL)
        coord.wait(timeout=30)
        os.environ.pop("NDS_LAKE_CATALOG_TIMEOUT_S", None)
        os.environ.pop("NDS_LAKE_CATALOG_POLL_S", None)
    wal_dir = os.path.join(path, "_catalog", "wal")
    wal = [f for f in os.listdir(wal_dir) if f.endswith(".json")]
    _check(len(wal) == 1, "WAL intent survived the kill")
    _check(_versions(path) == [1],
           "no manifest published by the killed commit (head intact)")
    # restart: recovery rolls the unacknowledged intent back
    rec = subprocess.run(
        [sys.executable, "-m", "nds_tpu.cli.catalog", wh, "--port", "0",
         "--recover_only"],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    if rec.returncode != 0:
        raise SystemExit(f"catalog_check: recovery failed:\n{rec.stdout}"
                         f"\n{rec.stderr}")
    _check("rolled back" in rec.stdout, "recovery reported the rollback")
    wal = [f for f in os.listdir(wal_dir) if f.endswith(".json")]
    _check(wal == [], "WAL empty after recovery")
    # the ladder-style retry: a fresh coordinator serves the re-run
    coord2, url2 = _spawn_coordinator(wh)
    try:
        C.reset_clients()
        LakehouseTable(path, conf={"engine.lake_catalog": url2}).append(
            _ints(2)
        )
    finally:
        coord2.terminate()
        coord2.wait(timeout=30)
    _check(_vals(path) == [1, 2], "retried transaction applied exactly once")
    _check(_versions(path) == [1, 2], "history linear after recovery")
    for v in _versions(path):
        m = LakehouseTable(path)._manifest(v)
        json.dumps(m)  # parses + re-serializes whole
    _check(True, "no torn manifest after kill + recovery")


def check_remote_lease_vacuum(workdir):
    """Remote-warehouse mode: vacuum must never remove files under
    another host's lease, and must collect a fenced zombie's stage."""
    print("remote-mode vacuum: cross-host lease + zombie fencing")
    wh = os.path.join(workdir, "wh-remote")
    os.makedirs(wh)
    path = os.path.join(wh, "t")
    os.environ["NDS_LAKE_CATALOG"] = "fs"
    C.reset_clients()
    try:
        LakehouseTable.create(path, _ints(1, 2, 3))
        lt = LakehouseTable(path)
        snap1 = lt.snapshot(1)
        # "another host": a lease that exists ONLY as catalog state (this
        # process's in-memory lease table never sees it — exactly what a
        # second host looks like)
        other_host = subprocess.run(
            [sys.executable, "-c", (
                f"import sys; sys.path.insert(0, {REPO!r})\n"
                f"from nds_tpu.lakehouse import catalog as C\n"
                f"ref = C._TableRef({path!r})\n"
                f"lease = C.FsCatalog().lease_acquire("
                f"ref, 1, {snap1.rel_files!r}, 120)\n"
                f"print('LEASE', lease.lease_id)\n"
            )],
            env=_env(), capture_output=True, text=True, timeout=120,
        )
        if other_host.returncode != 0:
            raise SystemExit(f"catalog_check: lease process failed:\n"
                             f"{other_host.stderr[-2000:]}")
        lease_id = other_host.stdout.split("LEASE", 1)[1].strip()
        # a zombie writer's never-referenced stage (expired writer lease)
        os.environ["NDS_LAKE_WRITER_TTL_S"] = "0.05"
        zombie = LakehouseTable(path)
        staged = zombie._stage(_ints(99))
        stage_base = posixpath.basename(staged[0][0])
        time.sleep(0.2)
        os.environ.pop("NDS_LAKE_WRITER_TTL_S")
        LakehouseTable(path).replace(_ints(9))  # v2: v1 collectable-but-leased
        orig = LakehouseTable._is_local
        LakehouseTable._is_local = lambda self: False
        try:
            # force the file-layer check: expire v1's manifest first
            os.unlink(os.path.join(path, "_manifests", "v000001.json"))
            res = LakehouseTable(path).vacuum(retain_last=1)
            survivors = set(os.listdir(os.path.join(path, "data")))
            _check(
                all(posixpath.basename(f) in survivors
                    for f in snap1.rel_files),
                "files under the other host's lease survived vacuum",
            )
            _check(res["files_leased"] >= 1, "vacuum counted the kept leased files")
            _check(stage_base not in survivors,
                   "fenced zombie's stage collected without pid liveness")
            # the zombie can never publish the deleted stage
            try:
                zombie._commit(staged, "append")
                _check(False, "fenced zombie must not publish")
            except Exception as exc:
                from nds_tpu import faults

                _check(faults.classify(exc) == faults.COMMIT_CONFLICT,
                       "fenced publish refused, classified commit_conflict")
            # released -> collectable
            rel = subprocess.run(
                [sys.executable, "-c", (
                    f"import sys; sys.path.insert(0, {REPO!r})\n"
                    f"from nds_tpu.lakehouse import catalog as C\n"
                    f"ref = C._TableRef({path!r})\n"
                    f"print(C.FsCatalog().lease_release(ref, {lease_id!r}))\n"
                )],
                env=_env(), capture_output=True, text=True, timeout=120,
            )
            _check("True" in rel.stdout, "other host released its lease")
            res2 = LakehouseTable(path).vacuum(retain_last=1)
            _check(res2["files_removed"] >= 1,
                   "released files collected on the next vacuum")
        finally:
            LakehouseTable._is_local = orig
        _check(_vals(path) == [9], "committed data intact throughout")
    finally:
        os.environ.pop("NDS_LAKE_CATALOG", None)
        C.reset_clients()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()
    workdir = tempfile.mkdtemp(prefix="nds-catalog-check-")
    t0 = time.perf_counter()
    try:
        check_convergence(workdir)
        check_crash_mid_commit(workdir)
        check_remote_lease_vacuum(workdir)
    finally:
        if args.keep:
            print(f"catalog_check: scratch kept at {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)
    print(f"catalog_check: OK ({time.perf_counter() - t0:.1f}s)")


if __name__ == "__main__":
    main()
