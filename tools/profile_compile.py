"""Dev tool: profile per-jit compile time for one query on the real chip.

Usage: python profile_compile.py query34 [query22 ...]
Runs each query cold (fresh in-process cache; NDS_XLA_CACHE_DIR should point
somewhere empty to measure true cold) and logs every XLA compile with its
duration, sorted descending.
"""
import logging
import os
import sys
import time

os.environ.setdefault("NDS_XLA_CACHE_DIR", "/tmp/nds_profile_cache")

import jax

jax.config.update("jax_log_compiles", True)

records = []


class Handler(logging.Handler):
    def emit(self, record):
        msg = record.getMessage()
        records.append((time.perf_counter(), msg))


for name in ("jax._src.interpreters.pxla", "jax._src.dispatch",
             "jax._src.compiler", "jax"):
    lg = logging.getLogger(name)
    lg.setLevel(logging.DEBUG)
    lg.addHandler(Handler())

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nds_tpu.engine.session import Session  # noqa: E402
from nds_tpu.schema import get_schemas  # noqa: E402
from nds_tpu.datagen.query_streams import generate_streams  # noqa: E402
from nds_tpu.power import gen_sql_from_stream  # noqa: E402
import tempfile  # noqa: E402

DATA_DIR = os.environ.get("NDS_BENCH_DATA", "/tmp/nds_bench_sf1.0")

with tempfile.TemporaryDirectory() as d:
    generate_streams(d, 1, 1, rngseed=19620718)
    queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))

sess = Session()
for t, schema in get_schemas().items():
    path = os.path.join(DATA_DIR, t)
    if os.path.isdir(path):
        sess.register_csv_dir(t, path, schema)

for qname in sys.argv[1:]:
    records.clear()
    t0 = time.perf_counter()
    r = sess.run_script(queries[qname])
    if r is not None:
        r.collect()
    total = time.perf_counter() - t0
    print(f"\n=== {qname}: total {total:.1f}s, {len(records)} log events ===")
    # pair "Finished XLA compilation of X in Y sec" lines with the most
    # recent "Compiling <name> with global shapes and types [...]" line
    compiles = []
    last_shapes = ""
    for ts, msg in records:
        if "global shapes and types" in msg:
            last_shapes = msg.split("global shapes and types", 1)[1][:180]
        if "Finished XLA compilation" in msg:
            try:
                head, tail = msg.rsplit(" in ", 1)
                secs = float(tail.split(" sec")[0])
                nm = head.split("Finished XLA compilation of ", 1)[1]
                compiles.append((secs, nm + " " + last_shapes))
            except Exception:
                print("??", msg[:200])
    compiles.sort(reverse=True)
    print(f"compiles: {len(compiles)}, sum {sum(s for s, _ in compiles):.1f}s")
    for secs, nm in compiles[:25]:
        print(f"  {secs:8.2f}s  {nm[:220]}")
