#!/usr/bin/env python
"""Serving-fleet chaos gate (ci/tier1-check).

Four acceptance checks for the router's robustness fronts, over REAL
replica processes (SIGKILL means SIGKILL):

1. **Failover on replica death mid-query** — a replica holding a SELECT
   open (hang fault at `replica:kill`) is SIGKILLed mid-stream; the
   request must complete on the surviving replica with exactly one
   classified retry, and ONE trace_id must span the router's retry
   evidence and the surviving replica's execution.
2. **Retry-storm containment** — with every forward hop failing
   (`io:route:forward`), N concurrent clients must all fail classified
   503 with total upstream attempts bounded by N + the retry-token
   burst, and jittered Retry-After values (no lockstep re-arrival).
3. **Rolling /fleet/reload** — drain + reload rolls across the replicas
   under continuous client traffic with ZERO dropped requests.
4. **Coordinator loss** — SIGKILL the tcp lakehouse coordinator: DML
   fails classified-retryable and opens the router's degraded-DML
   circuit (further DML fast-fails AT THE EDGE, no replica round trip)
   while pinned reads keep serving; restarting the coordinator on the
   same port closes the circuit through the half-open probe.

Usage: python tools/fleet_check.py [--keep]
"""

import argparse
import json
import os
import re
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

from nds_tpu import faults  # noqa: E402
from nds_tpu.lakehouse.table import LakehouseTable  # noqa: E402
from nds_tpu.obs import trace as obs_trace  # noqa: E402
from nds_tpu.serve.router import QueryRouter  # noqa: E402

QUERY = "select k, count(*) c, sum(v) s from fact group by k order by k"
POINT = "select k, v from fact where v = 3 limit 1"

#: one replica process: a real Session + QueryService behind the real
#: process-wide listener (conf/fault-spec/trace dir arrive via env)
_REPLICA_SCRIPT = """
import sys, threading
sys.path.insert(0, {repo!r})
from nds_tpu.engine.session import Session
from nds_tpu.obs import metrics as M
from nds_tpu.serve.service import QueryService
session = Session(conf={{"engine.metrics_port": 0}})
session.register_lakehouse("fact", sys.argv[1])
service = QueryService(session)
server = M.active_server()
assert server is not None, "replica listener failed to bind"
server.attach_app(service)
print(f"replica: listening on 127.0.0.1:{{server.port}}", flush=True)
threading.Event().wait()
"""


def _fact_table(rows=64):
    return pa.table({
        "k": pa.array(np.arange(rows) % 8, type=pa.int64()),
        "v": pa.array(np.arange(rows), type=pa.int64()),
    })


def _check(ok, label):
    print(f"  {'OK ' if ok else 'FAIL'} {label}")
    if not ok:
        raise SystemExit(f"fleet_check: FAILED: {label}")


def _env(**extra):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "NDS_METRICS_HOST": "127.0.0.1"}
    env.pop("NDS_FAULT_SPEC", None)
    env.update(extra)
    return env


def _wait_port(proc, pattern, what):
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(pattern, line)
        if m:
            return int(m.group(1))
    proc.kill()
    raise SystemExit(f"fleet_check: {what} never announced a port")


def _spawn_replica(table_path, fault_spec=None, extra_env=None):
    env = _env(**(extra_env or {}))
    if fault_spec:
        env["NDS_FAULT_SPEC"] = fault_spec
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _REPLICA_SCRIPT.format(repo=REPO), table_path],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    return proc, _wait_port(proc, r"listening on [^:]+:(\d+)", "replica")


def _spawn_coordinator(warehouse, port=0):
    proc = subprocess.Popen(
        [sys.executable, "-m", "nds_tpu.cli.catalog", warehouse,
         "--port", str(port)],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    return proc, _wait_port(
        proc, r"coordinating .* on [^:]+:(\d+)", "coordinator"
    )


def _mk_router(ports, trace_dir=None, **knobs):
    conf = {
        "engine.route_health_interval_s": 0,
        "engine.route_backoff_base_s": 0.01,
        "engine.route_backoff_cap_s": 0.05,
    }
    conf.update(knobs)
    tracer = None
    if trace_dir:
        tracer = obs_trace.tracer_from_conf(
            {"engine.trace_dir": trace_dir}, app_id="nds-route"
        )
    return QueryRouter(
        [f"127.0.0.1:{p}" for p in ports], conf=conf, tracer=tracer
    )


def _route(router, payload, tenant="default"):
    status, _ctype, body, _hdrs = router.handle_query(payload, tenant)
    return status, json.loads(body)


def check_failover_sigkill(workdir, table, trace, surviving_port):
    """SIGKILL a replica mid-SELECT: one classified retry, traceable."""
    print("failover: SIGKILL a replica mid-query -> one classified retry")
    victim, vport = _spawn_replica(
        table, fault_spec="hang:replica:kill:120",
        extra_env={"NDS_TRACE_DIR": trace},
    )
    router = _mk_router(
        [vport, surviving_port], trace_dir=trace,
        **{"engine.route_verdict_cache": 0},  # the FORWARD hop discovers
    )
    try:
        router._rr = 0  # deterministic: the victim is picked first
        box = {}

        def req():
            box["resp"] = _route(router, {"sql": QUERY}, tenant="chaos")

        t = threading.Thread(target=req, daemon=True)
        t.start()
        time.sleep(2.0)  # inside the victim's 120s replica:kill hang
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        t.join(90)
        _check("resp" in box, "request returned after the SIGKILL")
        status, body = box["resp"]
        _check(status == 200 and body["status"] == "completed",
               "query survived the replica death (200)")
        _check(body["route"]["attempts"] == 2,
               "exactly ONE failover retry (attempts=2)")
        _check(body["route"]["replica"] == f"127.0.0.1:{surviving_port}",
               "answered by the surviving replica")
        rid = body["request_id"]
        from nds_tpu.obs import reader as R

        evs = R.read_events(trace, strict=False)
        mine = [e for e in evs if e.get("trace_id") == rid]
        kinds = {e.get("kind") for e in mine}
        _check({"route_request", "route_retry", "serve_request"} <= kinds,
               "ONE trace_id spans router retry + surviving replica")
        retry = [e for e in mine if e.get("kind") == "route_retry"][0]
        _check(retry["reason"] == "midstream"
               and retry["replica"] == f"127.0.0.1:{vport}",
               "retry classified mid-stream against the killed replica")
    finally:
        router.close()
        if victim.poll() is None:
            victim.kill()


def check_retry_storm(ports):
    """Every forward hop fails: the token bucket caps amplification."""
    print("retry storm: token bucket caps fleet amplification")
    burst = 2
    faults.install("io:route:forward:1000")
    router = _mk_router(ports, **{
        "engine.route_retry_burst": burst, "engine.route_retry_rate": 0,
    })
    try:
        n = 6
        results = []
        lock = threading.Lock()

        def client():
            r = _route(router, {"sql": POINT}, tenant="storm")
            with lock:
                results.append(r)

        threads = [threading.Thread(target=client) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        _check(len(results) == n and all(s == 503 for s, _ in results),
               f"all {n} storm requests failed fast (503, none hung)")
        _check(all(b["failure_kind"] == faults.IO_TRANSIENT
                   for _, b in results),
               "failures classified io_transient")
        attempts = sum(b["route"]["attempts"] for _, b in results)
        _check(attempts <= n + burst,
               f"total attempts {attempts} <= requests({n}) + burst({burst})")
        ras = {b["retry_after_s"] for _, b in results}
        _check(len(ras) >= 2,
               "Retry-After jittered (no lockstep re-arrival)")
    finally:
        faults.reset()
        router.close()


def check_rolling_reload(ports):
    """Drain + reload rolls the fleet under load; nothing drops."""
    print("rolling /fleet/reload: zero dropped requests under load")
    router = _mk_router(ports)
    try:
        stop = threading.Event()
        results = []
        lock = threading.Lock()

        def client():
            while not stop.is_set():
                r = _route(router, {"sql": POINT}, tenant="roll")
                with lock:
                    results.append(r)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # traffic in flight before the roll starts
        status, _ctype, body, _h = router.handle_fleet_reload()
        roll = json.loads(body)
        stop.set()
        for t in threads:
            t.join(60)
        _check(status == 200 and roll["ok"]
               and roll["rolled"] == len(ports),
               "every replica drained and reloaded")
        bad = [(s, b.get("status")) for s, b in results if s != 200]
        _check(bool(results) and not bad,
               f"zero dropped requests across the roll "
               f"({len(results)} served{', bad: ' + repr(bad[:3]) if bad else ''})")
        view = router.fleet_snapshot()
        _check(all(not r["draining"] for r in view["replicas"]),
               "replicas back in rotation after the roll")
    finally:
        router.close()


def check_coordinator_loss(workdir):
    """Kill the tcp catalog coordinator: DML degrades at the edge,
    pinned reads keep serving, restart closes the circuit."""
    print("coordinator loss: DML degrades at the edge, reads keep serving")
    wh = os.path.join(workdir, "wh-coord")
    os.makedirs(wh)
    table = os.path.join(wh, "fact")
    LakehouseTable.create(table, _fact_table())
    coord, cport = _spawn_coordinator(wh)
    replica, rport = _spawn_replica(table, extra_env={
        "NDS_LAKE_CATALOG": f"http://127.0.0.1:{cport}",
        "NDS_LAKE_CATALOG_TIMEOUT_S": "1",
        "NDS_LAKE_CATALOG_POLL_S": "0.2",
    })
    router = _mk_router(
        [rport], **{"engine.route_catalog_cooldown_s": 1.0}
    )
    dml = {"sql": "insert into fact select k, v + 1000 from fact "
                  "where v < 4"}
    coord2 = None
    try:
        status, body = _route(router, {"sql": QUERY})
        _check(status == 200, "SELECT serves with the coordinator up")
        status, body = _route(router, dml, tenant="w")
        _check(status == 200 and body["status"] == "completed",
               "DML commits through the coordinator")
        coord.send_signal(signal.SIGKILL)
        coord.wait(timeout=30)
        status, body = _route(router, dml, tenant="w")
        _check(status >= 500
               and body.get("failure_kind") == faults.IO_TRANSIENT
               and "catalog unreachable" in str(body.get("error", "")),
               "coordinator-down DML fails classified-retryable")
        _check("dml" in router.fleet_snapshot()["degraded"],
               "degraded capability named in the fleet view")
        reqs = router.fleet_snapshot()["replicas"][0]["requests"]
        status, body = _route(router, dml, tenant="w")
        _check(status == 503 and body.get("degraded") == "dml",
               "further DML fast-fails at the edge (503 + degraded)")
        _check(router.fleet_snapshot()["replicas"][0]["requests"] == reqs,
               "edge fast-fail consumed no replica round trip")
        status, body = _route(router, {"sql": QUERY})
        _check(status == 200,
               "pinned reads keep serving through the outage")
        # the coordinator comes back on the SAME port; the half-open
        # probe rides through after the cooldown and closes the circuit
        coord2, _ = _spawn_coordinator(wh, port=cport)
        deadline = time.monotonic() + 90
        ok = False
        while time.monotonic() < deadline:
            status, body = _route(router, dml, tenant="w")
            if status == 200 and body.get("status") == "completed":
                ok = True
                break
            time.sleep(0.5)
        _check(ok, "half-open probe closed the circuit after restart")
        _check(router.fleet_snapshot()["degraded"] == {},
               "degraded capability cleared")
    finally:
        router.close()
        for p in (coord, coord2, replica):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()
    workdir = tempfile.mkdtemp(prefix="nds-fleet-check-")
    t0 = time.perf_counter()
    trace = os.path.join(workdir, "trace")
    wh = os.path.join(workdir, "wh")
    os.makedirs(wh)
    table = os.path.join(wh, "fact")
    LakehouseTable.create(table, _fact_table())
    b = c = None
    try:
        b, bport = _spawn_replica(
            table, extra_env={"NDS_TRACE_DIR": trace}
        )
        c, cport = _spawn_replica(
            table, extra_env={"NDS_TRACE_DIR": trace}
        )
        check_failover_sigkill(workdir, table, trace, bport)
        check_retry_storm([bport, cport])
        check_rolling_reload([bport, cport])
    finally:
        for p in (b, c):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
    check_coordinator_loss(workdir)
    if args.keep:
        print(f"fleet_check: scratch kept at {workdir}")
    else:
        shutil.rmtree(workdir, ignore_errors=True)
    print(f"fleet_check: OK ({time.perf_counter() - t0:.1f}s)")


if __name__ == "__main__":
    main()
