#!/usr/bin/env python
"""Closed-loop serve-mode benchmark: sustained QPS x p50/p99 under a
mixed multi-tenant workload, beside the TPC-DS composite.

The batch bench rounds measure one stream at a time; a query SERVICE is
measured by what it sustains under concurrent mixed load without falling
over. This driver stands up the real `nds_tpu/serve` service (the same
construction path `nds-tpu-submit serve` uses) over a marker-cached
SF0.01 lakehouse warehouse, then runs N closed-loop clients (each sends,
waits, sends again — no open-loop request storms) with a request mix of:

  * point lookups        (dimension single-row probes)
  * heavy aggregates     (the q3 star-join/group/sort shape)
  * snapshot-consistency reads over a DM-churned table
  * DM writes            (lakehouse INSERT commits racing the readers)

and reports sustained QPS, client-side p50/p99 per class, HTTP outcome
counts, and the SERVER-side p99 scraped from the live
`nds_serve_request_dur_ms` histogram on /metrics mid-run. The
consistency readers assert per-snapshot invariants (every key's count
identical within one response), so "queries are snapshot-consistent
under racing DM commits" is a measured number (violations == 0), not a
claim.

    python tools/serve_bench.py [--clients 4] [--duration 30] [--out F]
    python tools/serve_bench.py --smoke     # the CI gate: a short run
        that must finish with zero 5xx, zero snapshot violations, zero
        admission-rejected requests, and p99 under a generous bound
    python tools/serve_bench.py --smoke --fleet [--fleet_replicas 2]
        # the same mixed load sent THROUGH the fleet router over N real
        # replica processes: fleet QPS x p99 from the router-side
        # histogram, plus an edge-reject probe (a cross-join whose
        # modeled peak is beyond the admission reject line) that must
        # come back 429 from the ROUTER with the probe tenant absent
        # from every replica's /statusz — the proof an edge-rejected
        # request never consumed a replica worker slot

Env: NDS_SERVE_BENCH_DIR (default /tmp/nds_serve_bench) for the
warehouse; the raw SF0.01 set is shared with the test suite's
marker-cached /tmp/nds_test_sf001.
"""

import argparse
import json
import math
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RAW_DATA = os.environ.get("NDS_SERVE_BENCH_RAW", "/tmp/nds_test_sf001")
BASE = os.environ.get("NDS_SERVE_BENCH_DIR", "/tmp/nds_serve_bench")

#: the q3 star shape (scan -> join -> group -> sort): the heavy class
HEAVY_SQL = """
select d.d_year, i.i_brand_id brand_id, i.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim d, store_sales, item i
where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
  and i.i_manager_id = 10 and d.d_moy = 11
group by d.d_year, i.i_brand, i.i_brand_id
order by d.d_year, sum_agg desc, brand_id
limit 100
"""

POINT_SQL = (
    "select i_item_id, i_brand from item where i_item_sk = 1",
    "select d_date_id from date_dim where d_date_sk = 2450815",
    "select count(*) c from store",
)

#: the DM-churned table: 8 keys, one row per key at version 1; every DM
#: append adds exactly one more row PER KEY (v+1000 marks copies so they
#: are never re-copied), so in ANY committed snapshot all 8 per-key
#: counts are equal — a torn (non-snapshot) read shows unequal counts
CONSISTENCY_SQL = "select k, count(*) c from serve_dm group by k order by k"
DM_SQL = "insert into serve_dm select k, v + 1000 from serve_dm where v < 8"

#: the edge-reject probe: a full-width self-join + sort whose modeled
#: peak (~32 MB at SF0.01) is beyond the fleet replicas' admission
#: reject line (_FLEET_BUDGET_PROPS) with no windowing seam — the
#: router's /plan verdict probe sees `reject` and answers 429 at the
#: edge without a replica ever admitting (or even accounting) it
FLEET_REJECT_SQL = """
select a.*, b.* from store_sales a
join store_sales b on a.ss_ticket_number = b.ss_ticket_number
order by a.ss_ticket_number
"""

#: fleet replicas run with budget lines sized so the whole smoke mix is
#: verdict `direct` (heaviest shape models ~4.6 MB) while the reject
#: probe is beyond the reject line even windowed — measured values, see
#: the FLEET_REJECT_SQL note
_FLEET_BUDGET_PROPS = (
    f"engine.plan_budget_bytes={8 << 20}\n"
    f"engine.plan_budget_reject_bytes={16 << 20}\n"
)

#: one fleet replica: the real CLI construction path in a child process
#: (build_service + the serve_dm registration _start_service does)
_REPLICA_SCRIPT = """
import argparse, sys, threading
sys.path.insert(0, {repo!r})
from nds_tpu.cli.serve import build_service
ns = argparse.Namespace(
    warehouse_path=sys.argv[1], input_format="lakehouse", port=0,
    property_file=sys.argv[3], stream=None, job_dir=None, floats=False,
    aot_cache_dir=None,
)
service, server = build_service(ns)
service.session.register_lakehouse("serve_dm", sys.argv[2])
service.writer_session.register_lakehouse("serve_dm", sys.argv[2])
print(f"replica: listening on 127.0.0.1:{{server.port}}", flush=True)
threading.Event().wait()
"""


def _ensure_assets():
    """Marker-cached SF0.01 raw set + lakehouse warehouse + serve_dm."""
    if not os.path.exists(os.path.join(RAW_DATA, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale", "0.01",
             "--parallel", "2", "--data_dir", RAW_DATA,
             "--overwrite_output"],
            check=True, capture_output=True, cwd=REPO,
        )
        open(os.path.join(RAW_DATA, ".complete"), "w").close()
    wh = os.path.join(BASE, "warehouse")
    if not os.path.exists(os.path.join(wh, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.transcode", RAW_DATA, wh,
             os.path.join(wh, "load.report"), "--output_format", "lakehouse",
             "--output_mode", "overwrite"],
            check=True, capture_output=True, cwd=REPO,
            env={**os.environ, "NDS_PLATFORM": "cpu"},
        )
        open(os.path.join(wh, ".complete"), "w").close()
    dm_path = os.path.join(wh, "serve_dm")
    from nds_tpu.lakehouse.table import LakehouseTable

    if not LakehouseTable.is_table(dm_path):
        import numpy as np
        import pyarrow as pa

        LakehouseTable.create(dm_path, pa.table({
            "k": pa.array(np.arange(8), type=pa.int64()),
            "v": pa.array(np.arange(8), type=pa.int64()),
        }))
    return wh, dm_path


def _start_service(wh, dm_path, workers=None, job_dir=None):
    """The real CLI construction path, in-process on an ephemeral port."""
    from nds_tpu.cli.serve import build_service
    from nds_tpu.obs import metrics as obs_metrics

    obs_metrics.reset_shared()
    ns = argparse.Namespace(
        warehouse_path=wh, input_format="lakehouse", port=0,
        property_file=None, stream=None, job_dir=job_dir, floats=False,
    )
    if workers:
        os.environ["NDS_SERVE_WORKERS"] = str(workers)
    service, server = build_service(ns)
    # the DM-churn table is benchmark furniture, not a TPC-DS schema
    # table, so register_nds_tables skipped it
    service.session.register_lakehouse("serve_dm", dm_path)
    service.writer_session.register_lakehouse("serve_dm", dm_path)
    return service, server


def _spawn_replica(wh, dm_path, property_file):
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPLICA_SCRIPT.format(repo=REPO),
         wh, dm_path, property_file],
        env={**os.environ, "NDS_METRICS_HOST": "127.0.0.1"},
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"listening on [^:]+:(\d+)", line)
        if m:
            return proc, int(m.group(1))
    proc.kill()
    raise SystemExit("serve_bench: fleet replica never announced a port")


def _start_fleet(wh, dm_path, n):
    """N real replica processes behind an in-process QueryRouter on its
    own listener; clients talk HTTP to the router, never a replica."""
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.obs import trace as obs_trace
    from nds_tpu.serve.router import QueryRouter

    pf = os.path.join(BASE, "fleet.properties")
    with open(pf, "w") as f:
        f.write(_FLEET_BUDGET_PROPS)
    procs, ports = [], []
    for _ in range(n):
        proc, port = _spawn_replica(wh, dm_path, pf)
        procs.append(proc)
        ports.append(port)
    obs_metrics.reset_shared()
    tracer = obs_trace.tracer_from_conf(
        {"engine.metrics_port": 0}, app_id="nds-route"
    )
    router = QueryRouter(
        [f"127.0.0.1:{p}" for p in ports], conf={}, tracer=tracer
    )
    server = obs_metrics.active_server()
    if server is None:
        raise SystemExit("serve_bench: router listener failed to bind")
    server.attach_app(router)
    obs_metrics.shared_sink().set_fleet_provider(router.fleet_snapshot)
    return procs, ports, router, server


def _stop_fleet(procs, router):
    router.close()
    for p in procs:
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


def _get_statusz(port):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=10
    ) as r:
        return json.loads(r.read().decode())


def _post(port, payload, tenant, timeout=300.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/query",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 "X-NDS-Tenant": tenant},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except ValueError:
            body = {}
        return e.code, body


def _p(times, q):
    """Nearest-rank percentile of a ms list; None when empty."""
    if not times:
        return None
    ts = sorted(times)
    idx = max(int(math.ceil(q * len(ts))) - 1, 0)
    return round(float(ts[idx]), 3)


def _scrape_hist_p99(port, family="nds_serve_request_dur_ms"):
    """Server-side p99 estimate by inverting the live histogram's
    cumulative bucket counts (the upper bound of the bucket holding the
    99th-percentile rank)."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        text = r.read().decode()
    buckets = []
    for m in re.finditer(
        rf'{family}_bucket{{le="([^"]+)"}} (\d+)', text
    ):
        le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
        buckets.append((le, int(m.group(2))))
    if not buckets:
        return None, 0, text
    buckets.sort(key=lambda b: b[0])
    total = buckets[-1][1]
    if total == 0:
        return None, 0, text
    rank = max(int(math.ceil(0.99 * total)), 1)
    for le, cum in buckets:
        if cum >= rank:
            return (None if le == float("inf") else le), total, text
    return None, total, text


def run_bench(clients=4, duration_s=30.0, smoke=False, workers=None,
              fleet=0):
    """The closed-loop run; returns the report dict. `fleet=N` sends the
    same mix through a QueryRouter over N replica processes instead of
    one in-process service."""
    wh, dm_path = _ensure_assets()
    if fleet:
        procs, rports, router, server = _start_fleet(wh, dm_path, fleet)
        service = None
    else:
        service, server = _start_service(wh, dm_path, workers=workers)
    port = server.port
    results = []  # (class, tenant, status, ms, violation)
    results_lock = threading.Lock()
    stop = threading.Event()
    # per-client request budget in smoke mode (bounded, not timed): the
    # CI gate must be deterministic-ish in wall time
    smoke_requests = 6

    def record(cls, tenant, status, ms, violation=False):
        with results_lock:
            results.append((cls, tenant, status, ms, violation))

    def one_request(i, n):
        tenant = f"tenant-{i}"
        if i == 0 and n % 2 == 0:
            cls, payload = "dm", {"sql": DM_SQL}
        elif n % 3 == 0:
            cls, payload = "heavy", {"sql": HEAVY_SQL}
        elif n % 3 == 1:
            cls = "consistency"
            payload = {"sql": CONSISTENCY_SQL}
        else:
            cls = "point"
            payload = {"sql": POINT_SQL[n % len(POINT_SQL)]}
        t0 = time.perf_counter()
        status, body = _post(port, payload, tenant)
        ms = (time.perf_counter() - t0) * 1000.0
        violation = False
        if cls == "consistency" and status == 200:
            counts = {row[0]: row[1] for row in body.get("rows") or []}
            # one snapshot => every key appended the same number of times
            violation = len(set(counts.values())) > 1
        record(cls, tenant, status, ms, violation)

    def client(i):
        # warm this client's shapes once (cold XLA compile must not be
        # the only thing p99 measures), then the closed loop
        n = 0
        while not stop.is_set():
            one_request(i, n)
            n += 1
            if smoke and n >= smoke_requests:
                return

    what = (f"the fleet router over {fleet} replica(s)" if fleet
            else f":{port} ({service.workers} workers)")
    print(f"serve_bench: {clients} closed-loop clients against {what}",
          flush=True)
    wall_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    # the edge-reject probes ride WHILE the mix is in flight, so "never
    # consumed a worker slot" is measured under real contention; kept
    # out of `results` — these 429s are the deliberate success case
    probe_results = []
    if fleet:
        for _ in range(3):
            try:
                probe_results.append(
                    _post(port, {"sql": FLEET_REJECT_SQL}, "edge-probe",
                          timeout=120.0)
                )
            except OSError:
                probe_results.append((599, {}))
    scraped_p99 = None
    scraped_total = 0
    exposition = None
    hist_family = ("nds_route_request_dur_ms" if fleet
                   else "nds_serve_request_dur_ms")
    deadline = time.monotonic() + (duration_s if not smoke else 600)
    # mid-run scrape loop: the server-side histogram must be live WHILE
    # clients are still sending (that is the "scraped mid-run" contract)
    while any(t.is_alive() for t in threads):
        if time.monotonic() >= deadline and not smoke:
            stop.set()
        try:
            p99, total, text = _scrape_hist_p99(port, family=hist_family)
            if total:
                scraped_p99, scraped_total, exposition = p99, total, text
        except OSError:
            pass
        time.sleep(0.5)
    for t in threads:
        t.join(120)
    wall_s = time.perf_counter() - wall_start
    # post-run churn check: the DM table's final state is itself one
    # consistent snapshot
    if fleet:
        status, body = _post(port, {"sql": CONSISTENCY_SQL}, "final")
        final_counts = {r[0]: r[1] for r in (body.get("rows") or [])}
        final_ok = status == 200 and len(set(final_counts.values())) == 1
    else:
        final = service.session.sql(CONSISTENCY_SQL).collect().to_pylist()
        final_counts = {r["k"]: r["c"] for r in final}
        final_ok = len(set(final_counts.values())) == 1
    from nds_tpu.obs.metrics import validate_exposition

    exposition_problems = (
        validate_exposition(exposition) if exposition else ["never scraped"]
    )
    by_class = {}
    for cls in ("point", "heavy", "consistency", "dm"):
        times = [r[3] for r in results if r[0] == cls and r[2] == 200]
        by_class[cls] = {
            "requests": sum(1 for r in results if r[0] == cls),
            "completed": len(times),
            "p50_ms": _p(times, 0.50),
            "p99_ms": _p(times, 0.99),
        }
    ok_times = [r[3] for r in results if r[2] == 200]
    report = {
        "clients": clients,
        "workers": None if fleet else service.workers,
        "wall_s": round(wall_s, 2),
        "requests": len(results),
        "completed": len(ok_times),
        "qps": round(len(ok_times) / wall_s, 3) if wall_s else None,
        "p50_ms": _p(ok_times, 0.50),
        "p99_ms": _p(ok_times, 0.99),
        "http_5xx": sum(1 for r in results if r[2] >= 500),
        "rejected_429": sum(1 for r in results if r[2] == 429),
        "snapshot_violations": sum(1 for r in results if r[4]),
        "final_snapshot_consistent": final_ok,
        "dm_commits": by_class["dm"]["completed"],
        "by_class": by_class,
        "scraped_p99_ms": scraped_p99,
        "scraped_requests": scraped_total,
        "exposition_valid": exposition_problems == [],
    }
    if fleet:
        # the never-consumed-a-slot proof: the probe tenant must be 429
        # at the router AND absent from every replica's own /statusz
        # accounting (the /plan verdict probe is slotless by contract)
        leaked = []
        for rp in rports:
            try:
                tenants = _get_statusz(rp).get("tenants") or {}
            except OSError:
                tenants = {}
            if "edge-probe" in tenants:
                leaked.append(rp)
        from nds_tpu.obs import metrics as obs_metrics

        fleet_acct = (
            obs_metrics.shared_sink().status_snapshot().get("fleet") or {}
        )
        report["fleet"] = {
            "replicas": fleet,
            "router_view": router.fleet_snapshot()["replicas"],
            "edge_probe_statuses": [s for s, _ in probe_results],
            "edge_probe_rejected": all(
                s == 429 and b.get("status") == "rejected"
                for s, b in probe_results
            ),
            "edge_rejected_total": fleet_acct.get("edge_rejected", 0),
            "slot_leak_replicas": leaked,
        }
        _stop_fleet(procs, router)
    else:
        service.close()
    from nds_tpu.obs import metrics as obs_metrics

    obs_metrics.reset_shared()
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="closed-loop serve-mode QPS x p99 benchmark"
    )
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--duration", type=float, default=30.0,
                        help="measured seconds (ignored with --smoke)")
    parser.add_argument("--workers", type=int, default=None,
                        help="override engine.serve_workers")
    parser.add_argument("--out", help="write the report JSON here too")
    parser.add_argument(
        "--smoke", action="store_true",
        help="CI gate: short bounded run; exit 1 on any 5xx, any "
        "snapshot violation, any admission reject, or p99 over the bound",
    )
    parser.add_argument(
        "--smoke_p99_ms", type=float, default=120_000.0,
        help="generous smoke p99 bound (CPU cold compiles included)",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="send the mix through the fleet router over real replica "
        "processes; adds the edge-reject slot-leak probe",
    )
    parser.add_argument(
        "--fleet_replicas", type=int, default=2,
        help="replica process count for --fleet (default 2)",
    )
    args = parser.parse_args(argv)
    report = run_bench(
        clients=args.clients, duration_s=args.duration, smoke=args.smoke,
        workers=args.workers,
        fleet=args.fleet_replicas if args.fleet else 0,
    )
    print(json.dumps(report, indent=2, default=str))
    if args.out:
        from nds_tpu.io.fs import fs_open_atomic

        with fs_open_atomic(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
    if args.smoke:
        problems = []
        if report["http_5xx"]:
            problems.append(f"{report['http_5xx']} 5xx response(s)")
        if report["snapshot_violations"] or not (
            report["final_snapshot_consistent"]
        ):
            problems.append("snapshot-consistency violation under DM churn")
        if report["rejected_429"]:
            problems.append(
                f"{report['rejected_429']} unexpected 429(s) in the smoke "
                f"mix (nothing here should reject or shed)"
            )
        if report["completed"] == 0:
            problems.append("no request completed")
        p99 = report["p99_ms"] or 0
        if p99 > args.smoke_p99_ms:
            problems.append(
                f"p99 {p99:.0f} ms over the {args.smoke_p99_ms:.0f} ms bound"
            )
        if not report["exposition_valid"]:
            problems.append("/metrics exposition invalid or never scraped")
        fl = report.get("fleet")
        if fl:
            if not fl["edge_probe_rejected"]:
                problems.append(
                    f"edge-reject probe not 429/rejected at the router "
                    f"(statuses {fl['edge_probe_statuses']})"
                )
            if fl["slot_leak_replicas"]:
                problems.append(
                    f"edge-rejected tenant leaked into replica worker "
                    f"accounting on port(s) {fl['slot_leak_replicas']}"
                )
            if fl["edge_rejected_total"] < len(fl["edge_probe_statuses"]):
                problems.append("router edge_rejected counter undercounts")
        if problems:
            print("serve_bench --smoke FAILED: " + "; ".join(problems),
                  file=sys.stderr)
            return 1
        print("serve_bench --smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
