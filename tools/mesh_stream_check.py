#!/usr/bin/env python
"""The SF0.01 mesh-vs-oracle CI gate (ISSUE 13, tier-1-adjacent).

Runs the FULL SF0.01 query stream twice in one process — once on the
8-device virtual CPU mesh (fact tables row-sharded over the `data` axis,
dimensions replicated, exchange joins / samplesort / partial-agg merge all
live) and once on a single-device oracle session — and requires every
statement's result to be value-identical (rows canonically ordered; the
engine runs decimals as scaled int64, so partial-aggregate merge order
cannot perturb sums).

The mesh session runs traced: the gate asserts `exchange` trace evidence
(bytes moved, partitions, skew ratio) was recorded by the stream, then runs
one deliberately hot-keyed join at realistic row counts to prove the
overflow-retry path fires (capacity doubling + retry evidence) — the two
paths the old dryrun row caps never exercised.

Artifact: a compact JSON metrics block (the new MULTICHIP round shape) is
written to --out and printed, with a fail-soft `baseline_compare` against
the newest stored MULTICHIP_r*.json via the profiler's --bench comparison
(the same pattern bench.py applies to BENCH_r*.json).

Env knobs: NDS_MESH_GATE_DATA (data dir, default /tmp/nds_mesh_gate_sf0.01),
NDS_MESH_GATE_QUERIES (comma-separated subset, debug aid).
"""

import argparse
import json
import math
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEV_DEFAULT = 8


def _force_cpu_mesh(n_dev: int):
    # virtual device count must land in XLA_FLAGS BEFORE the CPU client
    # initializes; the platform switch must go through jax.config because
    # sitecustomize may have imported jax already (conftest.py pattern)
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_dev} "
        + re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if len(jax.devices()) < n_dev:
        raise RuntimeError(
            f"need {n_dev} CPU devices, have {len(jax.devices())}"
        )


def ensure_data(data_dir: str):
    marker = os.path.join(data_dir, ".complete")
    if os.path.exists(marker):
        return
    subprocess.run(
        [
            sys.executable, "-m", "nds_tpu.cli.gen_data",
            "--scale", "0.01", "--parallel", "2",
            "--data_dir", data_dir, "--overwrite_output",
        ],
        check=True, cwd=REPO, capture_output=True,
    )
    open(marker, "w").close()


def _sessions(data_dir: str, n_dev: int):
    from nds_tpu.engine.session import Session
    from nds_tpu.obs.trace import Tracer
    from nds_tpu.parallel.dist import make_mesh
    from nds_tpu.schema import get_schemas

    oracle = Session()
    dist = Session(mesh=make_mesh(n_dev))
    tracer = Tracer(None)  # in-memory: the gate reads events directly
    dist.tracer = tracer
    schemas = get_schemas()
    for t, schema in schemas.items():
        path = os.path.join(data_dir, t)
        if os.path.isdir(path):
            oracle.register_csv_dir(t, path, schema)
            dist.register_csv_dir(t, path, schema)
    return oracle, dist, tracer


def _canon_rows(arrow):
    """Canonical (sorted) row list: SQL leaves tie order undefined and the
    samplesort may place equal-key rows differently than the single-device
    stable sort — value equality is the contract, not tie order."""
    rows = [tuple(r.values()) for r in arrow.to_pylist()]

    def key(row):
        out = []
        for v in row:
            if v is None:
                out.append((0, ""))
            elif isinstance(v, float) and math.isnan(v):
                out.append((2, "nan"))
            else:
                out.append((1, str(v)))
        return out

    return sorted(rows, key=key)


def run_stream(oracle, dist, queries, tracer=None):
    from nds_tpu import faults

    matched, mismatched, failed = [], {}, {}
    wall_oracle = wall_mesh = 0.0

    def span(name, dur_s, status):
        # the mesh half runs outside BenchReport, so the gate emits the
        # query_span itself — `profile --critical-path` over the dumped
        # trace needs per-query wall to attribute against
        if tracer is not None:
            tracer.emit(
                "query_span", query=name,
                dur_ms=round(dur_s * 1000.0, 3), status=status, retries=0,
            )

    for i, (name, sql) in enumerate(queries.items()):
        try:
            t0 = time.perf_counter()
            a = oracle.run_script(sql)
            a_rows = _canon_rows(a.collect()) if a is not None else []
            wall_oracle += time.perf_counter() - t0
            t0 = time.perf_counter()
            try:
                with faults.scope(name):  # query-scoped exchange evidence
                    b = dist.run_script(sql)
                    b_rows = _canon_rows(b.collect()) if b is not None else []
            except Exception:
                span(name, time.perf_counter() - t0, "Failed")
                raise
            mesh_dur = time.perf_counter() - t0
            wall_mesh += mesh_dur
            span(name, mesh_dur, "Completed")
        except Exception as exc:
            failed[name] = f"{type(exc).__name__}: {str(exc)[:300]}"
            print(f"[{i + 1}/{len(queries)}] {name}: FAILED {exc}",
                  file=sys.stderr)
            continue
        if a_rows == b_rows:
            matched.append(name)
            print(f"[{i + 1}/{len(queries)}] {name}: ok "
                  f"({len(a_rows)} rows)", file=sys.stderr)
        else:
            diff = next(
                (
                    (x, y)
                    for x, y in zip(a_rows, b_rows)
                    if x != y
                ),
                (len(a_rows), len(b_rows)),
            )
            mismatched[name] = f"first difference: {str(diff)[:300]}"
            print(f"[{i + 1}/{len(queries)}] {name}: MISMATCH {diff}",
                  file=sys.stderr)
    return matched, mismatched, failed, wall_oracle, wall_mesh


def overflow_retry_probe(n_dev: int):
    """Hot-key exchange at realistic rows: >50% of a 64k-row fact on ONE
    key overflows the balanced capacity guess, so the overflow-retry
    (cap doubling) path MUST fire — asserted via the task-failure listener
    and the exchange event's retries field — and the result must equal the
    single-device oracle."""
    import numpy as np
    import pyarrow as pa

    from nds_tpu.engine.session import Session
    from nds_tpu.obs.trace import Tracer
    from nds_tpu.parallel.dist import make_mesh

    rng = np.random.default_rng(41)
    n = 1 << 16
    hot = rng.random(n) < 0.6
    k = np.where(hot, 17, rng.integers(0, 4096, n)) * 1_000_003
    left = pa.table({"k": k, "lv": np.arange(n, dtype=np.int64)})
    right = pa.table({
        "k": np.arange(4096, dtype=np.int64) * 1_000_003,
        "rv": np.arange(4096, dtype=np.int64),
    })
    oracle = Session()
    dist = Session(mesh=make_mesh(n_dev))
    tracer = Tracer(None)
    dist.tracer = tracer
    retries_seen = []
    dist.register_listener(
        lambda r: retries_seen.append(r) if "exchange join" in r else None
    )
    for s in (oracle, dist):
        s.register_arrow("l", left)
        s.register_arrow("r", right)
    from nds_tpu import faults

    q = ("select count(*) c, sum(lv) sl, sum(rv) sr from l, r "
         "where l.k = r.k")
    a = oracle.sql(q).to_pylist()
    t0 = time.perf_counter()
    with faults.scope("hotkey_probe"):
        b = dist.sql(q).to_pylist()
    tracer.emit(
        "query_span", query="hotkey_probe",
        dur_ms=round((time.perf_counter() - t0) * 1000.0, 3),
        status="Completed", retries=0,
    )
    if a != b:
        raise AssertionError(f"overflow probe mismatch: {a} vs {b}")
    ev = [e for e in tracer.events if e["kind"] == "exchange"]
    if not ev:
        raise AssertionError("overflow probe recorded no exchange event")
    retried = [e for e in ev if e["retries"] > 0]
    if not retried and not retries_seen:
        raise AssertionError(
            "hot-key probe never exercised the overflow-retry path"
        )
    skew = max(e["skew"] for e in ev)
    return {
        "retries": max(
            [e["retries"] for e in ev] + [1 if retries_seen else 0]
        ),
        "skew": skew,
        # the probe tracer's raw events ride back so --trace_dir can dump
        # them (main pops this key before the JSON artifact is written)
        "events": (tracer.events, tracer.app_id,
                   tracer.context.trace_id),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="SF0.01 mesh-vs-oracle stream gate (MULTICHIP round)"
    )
    ap.add_argument("--devices", type=int, default=N_DEV_DEFAULT)
    ap.add_argument(
        "--data_dir",
        default=os.environ.get(
            "NDS_MESH_GATE_DATA", "/tmp/nds_mesh_gate_sf0.01"
        ),
    )
    ap.add_argument(
        "--out", default="/tmp/multichip_gate.json",
        help="metrics artifact path (the new MULTICHIP round block; a "
        "bench round stores it as the repo's next MULTICHIP_r*.json)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="MULTICHIP_r*.json to compare against (default: newest in "
        "the repo root; comparison is fail-soft)",
    )
    ap.add_argument(
        "--trace_dir", default=None,
        help="also dump the gate's collected events (stream + hot-key "
        "probe) as event files under this dir — ci/tier1-check runs "
        "`profile --critical-path` over it",
    )
    args = ap.parse_args(argv)

    _force_cpu_mesh(args.devices)
    t_start = time.monotonic()
    ensure_data(args.data_dir)

    from nds_tpu.datagen.query_streams import generate_streams
    from nds_tpu.obs.reader import validate_events
    from nds_tpu.power import gen_sql_from_stream

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        generate_streams(d, 1, 0.01, rngseed=19620718)
        queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))
    subset = os.environ.get("NDS_MESH_GATE_QUERIES")
    if subset:
        keep = {s.strip() for s in subset.split(",") if s.strip()}
        queries = {n: q for n, q in queries.items() if n in keep}

    oracle, dist, tracer = _sessions(args.data_dir, args.devices)
    matched, mismatched, failed, w_oracle, w_mesh = run_stream(
        oracle, dist, queries, tracer=tracer
    )

    # stream-level exchange evidence: the retired dryrun caps mean the
    # collective paths must actually fire inside the real stream
    problems = validate_events(tracer.events)
    ex = [e for e in tracer.events if e["kind"] == "exchange"]
    probe = {}
    probe_error = None
    try:
        probe = overflow_retry_probe(args.devices)
    except Exception as exc:  # recorded below; fails the gate
        probe_error = f"{type(exc).__name__}: {str(exc)[:300]}"
    probe_events = probe.pop("events", None)

    if args.trace_dir:
        # dump the in-memory streams as regular event files (meta line
        # first) so the profiler CLI reads them like any trace dir
        os.makedirs(args.trace_dir, exist_ok=True)
        chains = [(tracer.events, tracer.app_id, tracer.context.trace_id)]
        if probe_events is not None:
            chains.append(probe_events)
        from nds_tpu import __version__ as _v

        for evs, app, tid in chains:
            path = os.path.join(args.trace_dir, f"events-{app}.jsonl")
            with open(path, "w") as f:
                f.write(json.dumps({
                    "ts": int(time.time() * 1000), "kind": "trace_meta",
                    "app": app, "trace_id": tid, "pid": os.getpid(),
                    "version": _v,
                }) + "\n")
                for ev in evs:
                    f.write(json.dumps(ev, default=str) + "\n")

    ok = (
        not mismatched
        and not failed
        and not problems
        and bool(ex)
        and probe_error is None
    )
    out = {
        "metric": "nds_mesh_stream_vs_oracle",
        "n_devices": args.devices,
        "ok": ok,
        "queries": len(queries),
        "matched": len(matched),
        "mismatched": mismatched,
        "failed": failed,
        "schema_problems": problems[:5],
        "exchange_ops": len(ex),
        "exchange_bytes": sum(int(e["bytes_moved"]) for e in ex),
        "exchange_retries": sum(int(e["retries"]) for e in ex),
        "exchange_max_skew": max([float(e["skew"]) for e in ex] or [0.0]),
        "exchange_join_ops": sum(1 for e in ex if e["op"] == "join"),
        "exchange_sort_ops": sum(1 for e in ex if e["op"] == "sort"),
        "overflow_probe": probe if probe_error is None else probe_error,
        "oracle_wall_s": round(w_oracle, 2),
        "mesh_wall_s": round(w_mesh, 2),
        # summed-wall ratio (NOT a per-query geomean): one number for "how
        # much slower is the whole stream on the virtual CPU mesh"
        "mesh_vs_oracle_wall_ratio": (
            round(w_mesh / w_oracle, 3) if w_oracle > 0 else None
        ),
        "wall_s": round(time.monotonic() - t_start, 1),
    }

    # fail-soft round comparison against the newest stored MULTICHIP round
    # (same contract as bench.py's BENCH_r* baseline_compare)
    try:
        import glob

        base = args.baseline
        if not base:
            rounds = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
            base = rounds[-1] if rounds else None
        if base:
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(out, f)
            from nds_tpu.cli.profile import _compare_multichip

            recs = _compare_multichip(base, tmp)
            os.unlink(tmp)
            rec = next((r for r in recs if "old_ratio" in r), None)
            if rec is not None:
                out["baseline_compare"] = {
                    "baseline": os.path.basename(base),
                    "old_ratio": rec.get("old_ratio"),
                    "new_ratio": rec.get("new_ratio"),
                    "old_ok": rec.get("old_ok"),
                    "regressed": rec.get("change") == "regression",
                }
    except Exception as exc:
        out["baseline_compare"] = {"error": str(exc)[:200]}

    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    os.replace(tmp, args.out)
    print(json.dumps(out))
    if not ok:
        print(
            f"mesh_stream_check: FAILED — mismatched={sorted(mismatched)} "
            f"failed={sorted(failed)} schema_problems={len(problems)} "
            f"exchange_ops={len(ex)} probe={probe_error}",
            file=sys.stderr,
        )
        return 1
    print(
        f"mesh_stream_check ok: {len(matched)}/{len(queries)} queries "
        f"match the oracle on the {args.devices}-device mesh; "
        f"{len(ex)} exchanges moved "
        f"{out['exchange_bytes'] >> 20} MiB (max skew "
        f"{out['exchange_max_skew']:.2f}x); overflow probe retried "
        f"{probe.get('retries')}x at skew {probe.get('skew'):.2f}x",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
