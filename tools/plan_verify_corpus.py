#!/usr/bin/env python
"""Static plan-IR corpus check: verify ALL 99 TPC-DS query templates.

Instantiates every template (seeded parameters, no data), parses, binds and
runs the full rewrite stack (prune_columns -> mark_blocked_union_aggs ->
mark_pipelines) through a schema-only Session with `engine.verify_plans=all`
— so the PlanVerifier (nds_tpu/analysis/verifier.py) re-checks structural
invariants after binding and after EVERY rewrite pass, for the whole query
surface, on every CI run. Nothing executes: Results stay lazy, no table is
ever loaded, the check is CPU-only and finishes in seconds.

This is the SQLancer-style lesson applied statically: a planner bug that a
unit test's three queries miss is usually visible somewhere across the full
99-template corpus, and verifying the corpus costs less than running one
query.

Usage:
    python tools/plan_verify_corpus.py [--queries 5,14,93] [--scale 1.0]

Exit status: 0 when every template binds, rewrites and verifies clean;
1 otherwise (per-template failures listed). Wired into ci/tier1-check.
"""

from __future__ import annotations

import argparse
import os
import sys
from time import perf_counter

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nds_tpu.datagen.query_streams import (  # noqa: E402
    available_templates,
    instantiate,
)
from nds_tpu.engine.session import Session, _Entry  # noqa: E402
from nds_tpu.engine.sql import ast as A  # noqa: E402
from nds_tpu.engine.sql.parser import parse_script  # noqa: E402
from nds_tpu.schema import get_schemas  # noqa: E402


def build_session(use_decimal: bool = True) -> Session:
    """A Session whose catalog knows every TPC-DS schema but holds no data:
    binding and plan rewriting only ever touch catalog.schema()."""
    sess = Session(
        use_decimal=use_decimal, conf={"engine.verify_plans": "all"}
    )
    for name, schema in get_schemas(use_decimal).items():
        sess.catalog.entries[name] = _Entry(schema=schema)
    return sess


def check_template(sess: Session, qnum: int, scale: float, rngseed: int) -> int:
    """Bind + rewrite + verify one template; returns the statement count
    (templates 14/23/24/39 carry two). Raises on any parse/bind/verify
    failure."""
    rng = np.random.default_rng(np.random.SeedSequence([rngseed, 0]))
    sql = instantiate(qnum, rng, scale)
    n = 0
    for stmt in parse_script(sql):
        if not isinstance(stmt, A.SelectStmt):
            raise TypeError(
                f"query{qnum}: expected SELECT statements only, got "
                f"{type(stmt).__name__}"
            )
        sess.run_stmt(stmt)  # binds + rewrites + verifies; never executes
        n += 1
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bind + rewrite + verify all TPC-DS query templates"
    )
    ap.add_argument(
        "--queries", default=None,
        help="comma-separated template numbers (default: all 99)",
    )
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--rngseed", type=int, default=0)
    ap.add_argument(
        "--float", dest="floats", action="store_true",
        help="verify under the float (non-decimal) type mapping too",
    )
    args = ap.parse_args(argv)
    qnums = (
        [int(x) for x in args.queries.split(",")]
        if args.queries
        else available_templates()
    )
    sess = build_session(use_decimal=not args.floats)
    t0 = perf_counter()
    failures = []
    statements = 0
    for q in qnums:
        try:
            statements += check_template(sess, q, args.scale, args.rngseed)
        except Exception as exc:
            failures.append((q, exc))
            print(f"FAIL query{q}: {type(exc).__name__}: {exc}")
    dt = perf_counter() - t0
    ok = len(qnums) - len(failures)
    print(
        f"plan_verify_corpus: {ok}/{len(qnums)} templates "
        f"({statements} statements) verified at strictness=all "
        f"in {dt:.1f}s"
    )
    if failures:
        print(
            f"plan_verify_corpus: {len(failures)} template(s) FAILED: "
            f"{[q for q, _ in failures]}"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
