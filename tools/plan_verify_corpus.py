#!/usr/bin/env python
"""Static plan-IR corpus check: verify ALL 99 TPC-DS query templates.

Instantiates every template (seeded parameters, no data), parses, binds and
runs the full rewrite stack (prune_columns -> mark_blocked_union_aggs ->
mark_pipelines) through a schema-only Session with `engine.verify_plans=all`
— so the PlanVerifier (nds_tpu/analysis/verifier.py) re-checks structural
invariants after binding and after EVERY rewrite pass, for the whole query
surface, on every CI run. Nothing executes: Results stay lazy, no table is
ever loaded, the check is CPU-only and finishes in seconds.

This is the SQLancer-style lesson applied statically: a planner bug that a
unit test's three queries miss is usually visible somewhere across the full
99-template corpus, and verifying the corpus costs less than running one
query.

`--budget` adds the static-budgeter calibration pass (analysis/budget.py):
every template is estimated schema-only against the SF1 AND SF10 TPC-DS
catalogs, and the two load-bearing calibration points are gated — at SF1
every statement must be admitted `direct` (SF1 is known to fit 103/103:
zero false positives), and at SF10 the round-5 per-query map's device-OOM
set (query5/6/7, BENCH_r05.json) must be flagged over-budget (>= 90%
coverage). A model change that drifts either way fails CI here, not in a
bench round. NDS_PLAN_BUDGET_STRICT is set for the whole run, so a
budgeter crash on any template is a hard failure too.

Usage:
    python tools/plan_verify_corpus.py [--queries 5,14,93] [--scale 1.0]
    python tools/plan_verify_corpus.py --budget

Exit status: 0 when every template binds, rewrites and verifies clean (and
the budget calibration holds); 1 otherwise. Wired into ci/tier1-check.
"""

from __future__ import annotations

import argparse
import os
import sys
from time import perf_counter

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# a budgeter crash on ANY template is a CI failure, not a degraded verdict
os.environ.setdefault("NDS_PLAN_BUDGET_STRICT", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nds_tpu.datagen.query_streams import (  # noqa: E402
    available_templates,
    instantiate,
)
from nds_tpu.engine.session import Session, _Entry  # noqa: E402
from nds_tpu.engine.sql import ast as A  # noqa: E402
from nds_tpu.engine.sql.parser import parse_script  # noqa: E402
from nds_tpu.schema import get_schemas  # noqa: E402


def build_session(use_decimal: bool = True) -> Session:
    """A Session whose catalog knows every TPC-DS schema but holds no data:
    binding and plan rewriting only ever touch catalog.schema()."""
    sess = Session(
        use_decimal=use_decimal, conf={"engine.verify_plans": "all"}
    )
    for name, schema in get_schemas(use_decimal).items():
        sess.catalog.entries[name] = _Entry(schema=schema)
    return sess


def check_template(sess: Session, qnum: int, scale: float, rngseed: int) -> int:
    """Bind + rewrite + verify one template; returns the statement count
    (templates 14/23/24/39 carry two). Raises on any parse/bind/verify
    failure."""
    rng = np.random.default_rng(np.random.SeedSequence([rngseed, 0]))
    sql = instantiate(qnum, rng, scale)
    n = 0
    for stmt in parse_script(sql):
        if not isinstance(stmt, A.SelectStmt):
            raise TypeError(
                f"query{qnum}: expected SELECT statements only, got "
                f"{type(stmt).__name__}"
            )
        sess.run_stmt(stmt)  # binds + rewrites + verifies; never executes
        n += 1
    return n


#: the queries that device-OOM'd in the round-5 SF10 per-query map
#: (BENCH_r05.json sf10.failed); the budgeter must flag >= 90% of them
ROUND5_SF10_OOM = (5, 6, 7)

#: verdicts that carry a PLANNED degradation (statically sized windows /
#: partition counts) — the round-5 OOM set must pin onto these, not onto
#: the passive `over` (which only arms the runtime ladder)
PLANNED_DEGRADATION = ("blocked", "spill")

_VERDICT_RANK = {"direct": 0, "unknown": 1, "blocked": 2, "spill": 3,
                 "over": 4, "reject": 5}


def budget_pass(use_decimal: bool, rngseed: int) -> int:
    """Schema-only budget estimates for every template at SF1 and SF10
    (plus the SF10 per-device mesh model — the same plans analyzed under
    mesh_devices=MESH_DEVICES in the one sweep, so the corpus plans each
    template once); returns the number of calibration failures (0 ==
    gate passes)."""
    from nds_tpu.analysis import budget as B

    failures = 0
    for sf in (1.0, 10.0):
        sess = build_session(use_decimal)
        # analysis is explicit below; the in-session hook would reject
        # over-budget SF10 templates before we could record their verdicts
        sess.conf["engine.plan_budget"] = "off"
        verdicts = {}
        peaks = {}
        mesh_verdicts = {}
        mesh_peaks = {}
        t0 = perf_counter()
        for q in available_templates():
            rng = np.random.default_rng(np.random.SeedSequence([rngseed, 0]))
            sql = instantiate(q, rng, sf)
            worst = "direct"
            peak = 0
            m_worst = "direct"
            m_peak = 0
            for stmt in parse_script(sql):
                res = sess.run_stmt(stmt)
                pb = B.analyze_plan(
                    res.plan, sess.catalog, scale_factor=sf
                )
                if _VERDICT_RANK[pb.verdict] > _VERDICT_RANK[worst]:
                    worst = pb.verdict
                peak = max(peak, pb.peak_bytes)
                if sf == 10.0:
                    mb = B.analyze_plan(
                        res.plan, sess.catalog, scale_factor=sf,
                        mesh_devices=MESH_DEVICES,
                    )
                    if _VERDICT_RANK[mb.verdict] > _VERDICT_RANK[m_worst]:
                        m_worst = mb.verdict
                    m_peak = max(m_peak, mb.peak_bytes)
            verdicts[q] = worst
            peaks[q] = peak
            if sf == 10.0:
                mesh_verdicts[q] = m_worst
                mesh_peaks[q] = m_peak
        dt = perf_counter() - t0
        flagged = sorted(q for q, v in verdicts.items() if v != "direct")
        print(
            f"plan_budget_corpus: SF{sf:g}: {len(flagged)}/{len(verdicts)} "
            f"templates flagged over-budget in {dt:.1f}s "
            f"(max modeled peak {max(peaks.values()) / (1 << 30):.2f} GiB)"
        )
        if sf == 1.0:
            if flagged:
                failures += 1
                print(
                    f"plan_budget_corpus: FAIL: SF1 false positives "
                    f"{flagged} (SF1 is known to fit 103/103; every "
                    f"template must be admitted direct): "
                    + ", ".join(
                        f"q{q}={verdicts[q]}@{peaks[q] / (1 << 30):.2f}G"
                        for q in flagged
                    )
                )
        else:
            # the OOM set must land on a PLANNED degradation verdict —
            # blocked (windowed union-agg) or spill (out-of-core partition
            # counts) — so the first SF10 attempt already runs degraded
            # instead of discovering the misfit as a device OOM
            hits = [
                q for q in ROUND5_SF10_OOM
                if verdicts[q] in PLANNED_DEGRADATION
            ]
            coverage = len(hits) / len(ROUND5_SF10_OOM)
            detail = ", ".join(
                f"q{q}={verdicts[q]}@{peaks[q] / (1 << 30):.2f}G"
                for q in ROUND5_SF10_OOM
            )
            print(
                f"plan_budget_corpus: SF10 round-5 OOM set coverage "
                f"{coverage:.0%} ({detail})"
            )
            if coverage < 0.9:
                failures += 1
                print(
                    "plan_budget_corpus: FAIL: the budgeter must pin "
                    ">= 90% of the round-5 SF10 device-OOM set onto the "
                    f"{PLANNED_DEGRADATION} verdicts"
                )
            failures += _check_mesh_pins(mesh_verdicts, mesh_peaks)
    return failures


#: mesh width of the per-device calibration pass (the CI mesh gate's and
#: the virtual CPU test mesh's width)
MESH_DEVICES = 8

#: templates still rejected per-device at SF10 on the 8-wide mesh: q47's
#: fact-scale window function all-gathers under the generic rewrite (the
#: budgeter charges it in full per chip — honestly), so it stays beyond
#: the reject line until a distributed window rewrite lands. Everything
#: else admits — incl. the single-device reject set (q14/q23 and kin).
EXPECTED_MESH_REJECTS = (47,)


def _check_mesh_pins(verdicts: dict, peaks: dict) -> int:
    """Per-device calibration pins at SF10 over the 8-device mesh
    (ISSUE 13; verdicts/peaks computed in budget_pass's SF10 sweep so
    templates plan once): sharded node bytes divide by the mesh width,
    replicated dims are charged per chip. The round-5 device-OOM set
    (q5/q6/q7 — blocked/spill single-device) must re-derive to per-device
    `direct` (each chip's share fits), and the reject set must equal the
    pinned EXPECTED_MESH_REJECTS — scale-out admits everything else.
    Returns the number of calibration failures."""
    failures = 0
    detail = ", ".join(
        f"q{q}={verdicts[q]}@{peaks[q] / (1 << 30):.2f}G"
        for q in ROUND5_SF10_OOM
    )
    rejects = sorted(q for q, v in verdicts.items() if v == "reject")
    print(
        f"plan_budget_corpus: SF10 x {MESH_DEVICES}-device mesh "
        f"(per-device): OOM set {detail}; {len(rejects)} reject(s)"
    )
    bad = [q for q in ROUND5_SF10_OOM if verdicts[q] != "direct"]
    if bad:
        failures += 1
        print(
            f"plan_budget_corpus: FAIL: the round-5 SF10 OOM set must "
            f"re-derive to per-device `direct` on the {MESH_DEVICES}-wide "
            f"mesh (each chip's share of the sharded fact work fits): "
            + ", ".join(f"q{q}={verdicts[q]}" for q in bad)
        )
    if list(rejects) != list(EXPECTED_MESH_REJECTS):
        failures += 1
        print(
            f"plan_budget_corpus: FAIL: per-device SF10 reject set "
            f"{rejects} != pinned {list(EXPECTED_MESH_REJECTS)} — "
            f"scale-out must admit everything except the known "
            f"window-all-gather shape (a new reject is a model/plan "
            f"regression; an admitted q47 means the dist-window rewrite "
            f"landed and the pin should move)"
        )
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bind + rewrite + verify all TPC-DS query templates"
    )
    ap.add_argument(
        "--queries", default=None,
        help="comma-separated template numbers (default: all 99)",
    )
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--rngseed", type=int, default=0)
    ap.add_argument(
        "--float", dest="floats", action="store_true",
        help="verify under the float (non-decimal) type mapping too",
    )
    ap.add_argument(
        "--budget", action="store_true",
        help="also run the static-budgeter SF1/SF10 calibration gate",
    )
    args = ap.parse_args(argv)
    qnums = (
        [int(x) for x in args.queries.split(",")]
        if args.queries
        else available_templates()
    )
    sess = build_session(use_decimal=not args.floats)
    t0 = perf_counter()
    failures = []
    statements = 0
    for q in qnums:
        try:
            statements += check_template(sess, q, args.scale, args.rngseed)
        except Exception as exc:
            failures.append((q, exc))
            print(f"FAIL query{q}: {type(exc).__name__}: {exc}")
    dt = perf_counter() - t0
    ok = len(qnums) - len(failures)
    print(
        f"plan_verify_corpus: {ok}/{len(qnums)} templates "
        f"({statements} statements) verified at strictness=all "
        f"in {dt:.1f}s"
    )
    if failures:
        print(
            f"plan_verify_corpus: {len(failures)} template(s) FAILED: "
            f"{[q for q, _ in failures]}"
        )
        return 1
    if args.budget:
        if budget_pass(not args.floats, args.rngseed):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
