"""Per-query parameter generators for the query templates.

The dsqgen `define` equivalents: each generator draws this query's
substitution values from a seeded RNG, using the same categorical
vocabularies the data generator emits (nds_tpu/datagen/native/vocab.hpp), so
predicates hit real data. Sales dates span 1998-01-01..2002-12-31
(kSalesFirstSk..kSalesLastSk in rowcounts.hpp).
"""

from __future__ import annotations

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]

CLASSES = {
    "Books": ["arts", "business", "computers", "cooking", "history", "mystery", "romance", "science"],
    "Children": ["infants", "newborn", "school-uniforms", "toddlers", "accessories", "shirts", "pants", "swimwear"],
    "Electronics": ["audio", "cameras", "dvd/vcr players", "karoke", "memory", "monitors", "portable", "televisions"],
    "Home": ["bathroom", "bedding", "blinds/shades", "curtains/drapes", "decor", "flatware", "furniture", "kids"],
    "Jewelry": ["birdal", "costume", "diamonds", "estate", "gold", "loose stones", "pendants", "rings"],
    "Men": ["accessories", "pants", "shirts", "sports-apparel", "underwear", "dress shirts", "suits", "casual"],
    "Music": ["classical", "country", "pop", "rock", "jazz", "blues", "folk", "world"],
    "Shoes": ["athletic", "dress", "kids", "mens", "womens", "work", "sandals", "boots"],
    "Sports": ["archery", "baseball", "basketball", "camping", "fishing", "fitness", "golf", "hockey"],
    "Women": ["dresses", "fragrances", "intimates", "maternity", "swimwear", "accessories", "shirts", "pants"],
}

STATES = [
    "AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA", "HI", "ID", "IL",
    "IN", "IA", "KS", "KY", "LA", "ME", "MD", "MA", "MI", "MN", "MS", "MO", "MT",
    "NE", "NV", "NH", "NJ", "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI",
    "SC", "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY",
]

COUNTIES = [
    "Williamson County", "Walker County", "Ziebach County", "Richland County",
    "Barrow County", "Bronx County", "Maricopa County", "Jackson County",
    "Franklin County", "Jefferson County", "Washington County", "Lincoln County",
    "Madison County", "Montgomery County", "Clay County", "Marion County",
]

CITIES = [
    "Fairview", "Midway", "Pleasant Hill", "Centerville", "Riverside",
    "Five Points", "Oak Grove", "Pleasant Valley", "Mountain View", "Salem",
    "Union", "Liberty", "Greenville", "Franklin", "Springfield",
]

EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
GENDERS = ["M", "F"]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blue", "blush", "brown", "chartreuse", "chocolate", "coral", "cream",
    "cyan", "firebrick", "forest", "gainsboro", "goldenrod", "green", "grey",
    "honeydew", "indian", "ivory", "khaki", "lavender", "lemon", "light",
    "lime", "linen", "magenta", "maroon", "medium", "midnight", "mint",
    "misty", "navy", "olive", "orange", "orchid", "pale", "papaya", "peach",
    "pink", "plum", "powder", "puff", "purple", "red", "rose", "rosy",
    "royal", "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
    "slate", "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato",
    "turquoise", "violet", "wheat", "white", "yellow",
]

SALES_YEARS = (1998, 2002)


def _year(rng, lo=None, hi=None):
    lo = lo or SALES_YEARS[0]
    hi = hi or SALES_YEARS[1]
    return int(rng.integers(lo, hi + 1))


def _choice(rng, xs):
    return xs[int(rng.integers(0, len(xs)))]


def _distinct(rng, xs, n):
    idx = rng.permutation(len(xs))[:n]
    return [xs[i] for i in idx]


def _date_in_year(rng, year, latest_month=11):
    m = int(rng.integers(1, latest_month + 1))
    d = int(rng.integers(1, 29))
    return f"{year}-{m:02d}-{d:02d}"


# columns q4/q11/q74 can project for the year-over-year report
SELECT_ONE = [
    "customer_preferred_cust_flag", "customer_birth_country",
    "customer_login", "customer_email_address",
]


def _zip5(rng, n):
    """n distinct 5-digit zip prefixes (dsqgen ZIPLIST equivalent)."""
    zips = set()
    while len(zips) < n:
        zips.add(f"{int(rng.integers(0, 100000)):05d}")
    return ", ".join(f"'{z}'" for z in sorted(zips))


def q1(rng, scale):
    return {"YEAR": _year(rng), "STATE": _choice(rng, STATES), "AGG_FIELD": "sr_return_amt"}


def q2(rng, scale):
    return {"YEAR": _year(rng, hi=SALES_YEARS[1] - 1)}


def q4(rng, scale):
    return {"YEAR": _year(rng, hi=SALES_YEARS[1] - 1),
            "SELECTONE": _choice(rng, SELECT_ONE)}


def q5(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year, 8)}


def q8(rng, scale):
    return {"YEAR": _year(rng), "QOY": int(rng.integers(1, 3)),
            "ZIPLIST": _zip5(rng, 400)}


def q9(rng, scale):
    # bucket thresholds near each quantity-range's expected row count so the
    # CASE exercises both branches (reference: dsqgen RC distributions)
    base = int(2_880_404 * scale * 0.2)
    out = {}
    for i in range(1, 6):
        out[f"RC{i}"] = max(1, int(base * rng.uniform(0.5, 1.5)))
    return out


def q10(rng, scale):
    counties = _distinct(rng, COUNTIES, 5)
    out = {"YEAR": _year(rng), "MONTH": int(rng.integers(1, 5))}
    for i, c in enumerate(counties, 1):
        out[f"COUNTY{i}"] = c
    return out


def q11(rng, scale):
    return q4(rng, scale)


def q16(rng, scale):
    counties = _distinct(rng, COUNTIES, 5)
    out = {"YEAR": _year(rng), "MONTH": int(rng.integers(2, 6)),
           "STATE": _choice(rng, STATES)}
    for i, c in enumerate(counties, 1):
        out[f"COUNTY{i}"] = c
    return out


def q17(rng, scale):
    return {"YEAR": _year(rng)}


def q18(rng, scale):
    months = _distinct(rng, list(range(1, 13)), 6)
    states = _distinct(rng, STATES, 7)
    out = {"YEAR": _year(rng), "GEN": _choice(rng, GENDERS),
           "ES": _choice(rng, EDUCATION[:6])}
    for i, m in enumerate(months, 1):
        out[f"MONTH{i}"] = m
    for i, s in enumerate(states, 1):
        out[f"STATE{i}"] = s
    return out


def q3(rng, scale):
    return {
        "MANUFACT": int(rng.integers(1, 1001)),
        "MONTH": int(rng.integers(11, 13)),
        "AGGC": _choice(
            rng,
            ["ss_ext_sales_price", "ss_sales_price", "ss_ext_discount_amt", "ss_net_profit"],
        ),
    }


def q6(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(1, 8))}


def q7(rng, scale):
    return {
        "YEAR": _year(rng),
        "GEN": _choice(rng, GENDERS),
        "MS": _choice(rng, MARITAL),
        "ES": _choice(rng, EDUCATION[:6]),
    }


def q12(rng, scale):
    year = _year(rng)
    cats = _distinct(rng, CATEGORIES, 3)
    return {
        "YEAR": year,
        "SDATE": _date_in_year(rng, year, 7),
        "CAT_A": cats[0], "CAT_B": cats[1], "CAT_C": cats[2],
    }


def q13(rng, scale):
    ms = _distinct(rng, MARITAL, 3)
    es = _distinct(rng, EDUCATION[:6], 3)
    st = [_distinct(rng, STATES, 3) for _ in range(3)]
    out = {"MS1": ms[0], "MS2": ms[1], "MS3": ms[2],
           "ES1": es[0], "ES2": es[1], "ES3": es[2]}
    for g, group in enumerate(st, 1):
        for i, s in enumerate(group, 1):
            out[f"STATE{g}{i}"] = s
    return out


def q15(rng, scale):
    return {"YEAR": _year(rng), "QOY": int(rng.integers(1, 3))}


def q19(rng, scale):
    return {
        "YEAR": _year(rng),
        "MONTH": int(rng.integers(11, 13)),
        "MANAGER": int(rng.integers(1, 101)),
    }


def q20(rng, scale):
    return q12(rng, scale)


def q25(rng, scale):
    return {"YEAR": _year(rng)}


def q26(rng, scale):
    return q7(rng, scale)


def q42(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13))}


def q43(rng, scale):
    return {"YEAR": _year(rng), "GMT": "-5"}


def q52(rng, scale):
    return q42(rng, scale)


def q55(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13)),
            "MANAGER": int(rng.integers(1, 101))}


def q96(rng, scale):
    return {"HOUR": int(rng.integers(15, 21)), "DEPCNT": int(rng.integers(0, 10))}


def q98(rng, scale):
    return q12(rng, scale)


def q37(rng, scale):
    year = _year(rng)
    return {
        "SDATE": _date_in_year(rng, year, 6),
        "PRICE": int(rng.integers(10, 61)),
        "MANU_A": int(rng.integers(1, 1001)),
        "MANU_B": int(rng.integers(1, 1001)),
        "MANU_C": int(rng.integers(1, 1001)),
        "MANU_D": int(rng.integers(1, 1001)),
    }


def q82(rng, scale):
    return q37(rng, scale)


def q41(rng, scale):
    return {"MANUFACT": int(rng.integers(600, 701))}


def q45(rng, scale):
    return {"YEAR": _year(rng), "QOY": int(rng.integers(1, 3))}


def q48(rng, scale):
    ms = _distinct(rng, MARITAL, 3)
    es = _distinct(rng, EDUCATION[:6], 3)
    st = [_distinct(rng, STATES, 3) for _ in range(3)]
    out = {"YEAR": _year(rng),
           "MS1": ms[0], "MS2": ms[1], "MS3": ms[2],
           "ES1": es[0], "ES2": es[1], "ES3": es[2]}
    for g, group in enumerate(st, 1):
        for i, s in enumerate(group, 1):
            out[f"STATE{g}{i}"] = s
    return out


def q61(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13)),
            "GMT": "-5", "CATEGORY": _choice(rng, CATEGORIES)}


def q65(rng, scale):
    return {"YEAR": _year(rng)}


def q68(rng, scale):
    cities = _distinct(rng, CITIES, 2)
    return {"YEAR": _year(rng), "CITY_A": cities[0], "CITY_B": cities[1],
            "DEPCNT": int(rng.integers(0, 10)), "VEHCNT": int(rng.integers(-1, 5))}


def q73(rng, scale):
    return {"YEAR": _year(rng),
            "BP1": _choice(rng, BUY_POTENTIAL), "BP2": _choice(rng, BUY_POTENTIAL),
            "COUNTY1": _choice(rng, COUNTIES), "COUNTY2": _choice(rng, COUNTIES),
            "COUNTY3": _choice(rng, COUNTIES), "COUNTY4": _choice(rng, COUNTIES)}


def q79(rng, scale):
    return {"YEAR": _year(rng), "DEPCNT": int(rng.integers(0, 10)),
            "VEHCNT": int(rng.integers(-1, 5))}


def q88(rng, scale):
    return {"STORE": "Unknown", "DEPCNT1": int(rng.integers(0, 5)),
            "DEPCNT2": int(rng.integers(0, 5)), "DEPCNT3": int(rng.integers(0, 5))}


def q93(rng, scale):
    return {"REASON": "reason 28"}


# i_brand = PROMO[cat] + PROMO[cls] + ' #n' (datagen/native/dims.hpp gen_item)
PROMO_NAMES = ["ese", "anti", "ought", "able", "pri", "bar", "cally",
               "ation", "eing", "n st"]
CARRIERS = [
    "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "ZOUROS",
    "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES",
    "RUPEKSA", "HARMSTORF", "PRIVATECARRIER", "DIAMOND", "GREAT EASTERN",
    "GERMA",
]

# d_month_seq = (year-1900)*12 + (month-1); sales span 1998..2002
DMS_RANGE = (1176, 1224)


def _dms(rng):
    return int(rng.integers(DMS_RANGE[0], DMS_RANGE[1] + 1))


def _brand(rng, cat_ix=None, cls_ix=None):
    cat = cat_ix if cat_ix is not None else int(rng.integers(0, 10))
    cls = cls_ix if cls_ix is not None else int(rng.integers(0, 8))
    return f"{PROMO_NAMES[cat]}{PROMO_NAMES[cls]} #{int(rng.integers(1, 11))}"


def _gmt(rng):
    return str(int(rng.integers(-8, -4)))


def _cat_class_brand_group(rng, prefix):
    """Coherent category/class/brand IN-lists over the generated item vocab
    (the reference hardcodes dsdgen's syllable brands; ours differ)."""
    cat_ix = _distinct(rng, list(range(10)), 3)
    out = {}
    for i, ci in enumerate(cat_ix, 1):
        out[f"CAT_{prefix}{i}"] = CATEGORIES[ci]
    cls_ix = [int(rng.integers(0, 8)) for _ in range(4)]
    for i, ki in enumerate(cls_ix, 1):
        out[f"CLASS_{prefix}{i}"] = CLASSES[CATEGORIES[cat_ix[(i - 1) % 3]]][ki]
    for i in range(1, 5):
        out[f"BRAND_{prefix}{i}"] = _brand(rng, cat_ix[(i - 1) % 3],
                                           cls_ix[i - 1])
    return out


def q14(rng, scale):
    return {"YEAR": _year(rng, hi=2000), "DAY": int(rng.integers(1, 29))}


def q21(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year, 10)}


def q22(rng, scale):
    return {"DMS": _dms(rng)}


def q23(rng, scale):
    return {"YEAR": _year(rng, hi=1999), "MONTH": int(rng.integers(1, 8))}


def q24(rng, scale):
    colors = _distinct(rng, COLORS, 2)
    return {"MARKET": int(rng.integers(5, 11)),
            "COLOR1": colors[0], "COLOR2": colors[1]}


def q27(rng, scale):
    out = {"YEAR": _year(rng), "GEN": _choice(rng, GENDERS),
           "MS": _choice(rng, MARITAL), "ES": _choice(rng, EDUCATION[:6])}
    for i, s in enumerate(_distinct(rng, STATES, 6), 1):
        out[f"STATE{i}"] = s
    return out


def q28(rng, scale):
    out = {}
    for i in range(1, 7):
        out[f"LP{i}"] = int(rng.integers(90, 191))
        out[f"CA{i}"] = int(rng.integers(0, 12001))
        out[f"WC{i}"] = int(rng.integers(0, 81))
    return out


def q29(rng, scale):
    return {"YEAR": _year(rng, hi=2000), "MONTH": int(rng.integers(1, 10))}


def q30(rng, scale):
    return {"YEAR": _year(rng), "STATE": _choice(rng, STATES)}


def q31(rng, scale):
    return {"YEAR": _year(rng)}


def q32(rng, scale):
    year = _year(rng)
    return {"IMID": int(rng.integers(1, 1001)),
            "SDATE": _date_in_year(rng, year, 9)}


def q33(rng, scale):
    return {"CATEGORY": _choice(rng, CATEGORIES), "YEAR": _year(rng),
            "MONTH": int(rng.integers(1, 13)), "GMT": _gmt(rng)}


def q34(rng, scale):
    bps = _distinct(rng, BUY_POTENTIAL, 2)
    out = {"YEAR": _year(rng, hi=2000), "BP1": bps[0], "BP2": bps[1]}
    for i, c in enumerate(_distinct(rng, COUNTIES, 8), 1):
        out[f"COUNTY{i}"] = c
    return out


def q35(rng, scale):
    return {"YEAR": _year(rng)}


def q36(rng, scale):
    out = {"YEAR": _year(rng)}
    for i, s in enumerate(_distinct(rng, STATES, 8), 1):
        out[f"STATE{i}"] = s
    return out


def q38(rng, scale):
    return {"DMS": _dms(rng)}


def q39(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(1, 12))}


def q40(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year, 10)}


def q44(rng, scale):
    n_stores = max(1, min(12, int(12 * scale))) if scale < 1 else 12
    return {"STORE": int(rng.integers(1, n_stores + 1))}


def q46(rng, scale):
    out = {"YEAR": _year(rng, hi=2000), "DEPCNT": int(rng.integers(0, 10)),
           "VEHCNT": int(rng.integers(-1, 5))}
    for i, c in enumerate(_distinct(rng, CITIES, 5), 1):
        out[f"CITY{i}"] = c
    return out


def q47(rng, scale):
    return {"YEAR": _year(rng, lo=1999, hi=2001)}


def q49(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13))}


def q50(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(8, 11))}


def q51(rng, scale):
    return {"DMS": _dms(rng)}


def q53(rng, scale):
    out = {"DMS": _dms(rng)}
    out.update(_cat_class_brand_group(rng, "A"))
    out.update(_cat_class_brand_group(rng, "B"))
    return out


def q54(rng, scale):
    cat = _choice(rng, CATEGORIES)
    return {"CATEGORY": cat, "CLASS": _choice(rng, CLASSES[cat]),
            "YEAR": _year(rng, hi=2001), "MONTH": int(rng.integers(1, 8))}


def q56(rng, scale):
    colors = _distinct(rng, COLORS, 3)
    return {"COLOR1": colors[0], "COLOR2": colors[1], "COLOR3": colors[2],
            "YEAR": _year(rng), "MONTH": int(rng.integers(1, 13)),
            "GMT": _gmt(rng)}


def q57(rng, scale):
    return {"YEAR": _year(rng, lo=1999, hi=2001)}


def q58(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year)}


def q59(rng, scale):
    return {"DMS": int(rng.integers(DMS_RANGE[0], DMS_RANGE[1] - 11))}


def q60(rng, scale):
    return {"CATEGORY": _choice(rng, CATEGORIES), "YEAR": _year(rng),
            "MONTH": int(rng.integers(8, 11)), "GMT": _gmt(rng)}


def q62(rng, scale):
    return {"DMS": _dms(rng)}


def q63(rng, scale):
    return q53(rng, scale)


def q64(rng, scale):
    out = {"YEAR": _year(rng, hi=2001), "PRICE": int(rng.integers(0, 86))}
    for i, c in enumerate(_distinct(rng, COLORS, 6), 1):
        out[f"COLOR{i}"] = c
    return out


def q66(rng, scale):
    carriers = _distinct(rng, CARRIERS, 2)
    return {"YEAR": _year(rng), "TIME": int(rng.integers(0, 57600)),
            "CARRIER_A": carriers[0], "CARRIER_B": carriers[1]}


def q67(rng, scale):
    return {"DMS": _dms(rng)}


def q69(rng, scale):
    out = {"YEAR": _year(rng), "MONTH": int(rng.integers(1, 5))}
    for i, s in enumerate(_distinct(rng, STATES, 3), 1):
        out[f"STATE{i}"] = s
    return out


def q70(rng, scale):
    return {"DMS": _dms(rng)}


def q71(rng, scale):
    return {"MANAGER": int(rng.integers(1, 101)), "YEAR": _year(rng),
            "MONTH": int(rng.integers(11, 13))}


def q72(rng, scale):
    return {"BP": _choice(rng, BUY_POTENTIAL), "YEAR": _year(rng),
            "MS": _choice(rng, MARITAL)}


def q74(rng, scale):
    return {"YEAR": _year(rng, hi=2001)}


def q75(rng, scale):
    return {"CATEGORY": _choice(rng, CATEGORIES),
            "YEAR": _year(rng, lo=1999)}


def q77(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year, 10)}


def q78(rng, scale):
    return {"YEAR": _year(rng)}


def q80(rng, scale):
    year = _year(rng)
    return {"SDATE": _date_in_year(rng, year, 10)}


def q81(rng, scale):
    return {"YEAR": _year(rng), "STATE": _choice(rng, STATES)}


def q83(rng, scale):
    year = _year(rng)
    return {"DATE1": _date_in_year(rng, year),
            "DATE2": _date_in_year(rng, year),
            "DATE3": _date_in_year(rng, year)}


def q84(rng, scale):
    return {"CITY": _choice(rng, CITIES),
            "INCOME": int(rng.integers(0, 8)) * 10000}


def q85(rng, scale):
    ms = _distinct(rng, MARITAL, 3)
    es = _distinct(rng, EDUCATION[:6], 3)
    states = _distinct(rng, STATES, 9)
    out = {"YEAR": _year(rng)}
    for i in range(1, 4):
        out[f"MS{i}"] = ms[i - 1]
        out[f"ES{i}"] = es[i - 1]
        for j in range(1, 4):
            out[f"STATE{i}{j}"] = states[(i - 1) * 3 + (j - 1)]
    return out


def q86(rng, scale):
    return {"DMS": _dms(rng)}


def q87(rng, scale):
    return {"DMS": _dms(rng)}


def q89(rng, scale):
    out = {"YEAR": _year(rng)}
    for p in ("A", "B"):
        cats = _distinct(rng, list(range(10)), 3)
        for i, ci in enumerate(cats, 1):
            out[f"CAT_{p}{i}"] = CATEGORIES[ci]
            out[f"CLASS_{p}{i}"] = _choice(rng, CLASSES[CATEGORIES[ci]])
    return out


def q90(rng, scale):
    return {"HOUR_AM": int(rng.integers(6, 12)),
            "HOUR_PM": int(rng.integers(14, 21)),
            "DEPCNT": int(rng.integers(0, 10))}


def q91(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13)),
            "GMT": _gmt(rng)}


def q92(rng, scale):
    year = _year(rng)
    return {"IMID": int(rng.integers(1, 1001)),
            "SDATE": _date_in_year(rng, year, 9)}


def q94(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(2, 11)),
            "STATE": _choice(rng, STATES)}


def q95(rng, scale):
    return q94(rng, scale)


def q97(rng, scale):
    return {"DMS": _dms(rng)}


def q99(rng, scale):
    return {"DMS": _dms(rng)}


PARAM_GENERATORS = {
    1: q1, 2: q2, 3: q3, 4: q4, 5: q5, 6: q6, 7: q7, 8: q8, 9: q9, 10: q10,
    11: q11, 12: q12, 13: q13, 14: q14, 15: q15, 16: q16, 17: q17, 18: q18,
    19: q19, 20: q20, 21: q21, 22: q22, 23: q23, 24: q24, 25: q25, 26: q26,
    27: q27, 28: q28, 29: q29, 30: q30, 31: q31, 32: q32, 33: q33, 34: q34,
    35: q35, 36: q36, 37: q37, 38: q38, 39: q39, 40: q40, 41: q41, 42: q42,
    43: q43, 44: q44, 45: q45, 46: q46, 47: q47, 48: q48, 49: q49, 50: q50,
    51: q51, 52: q52, 53: q53, 54: q54, 55: q55, 56: q56, 57: q57, 58: q58,
    59: q59, 60: q60, 61: q61, 62: q62, 63: q63, 64: q64, 65: q65, 66: q66,
    67: q67, 68: q68, 69: q69, 70: q70, 71: q71, 72: q72, 73: q73, 74: q74,
    75: q75, 77: q77, 78: q78, 79: q79, 80: q80, 81: q81, 82: q82, 83: q83,
    84: q84, 85: q85, 86: q86, 87: q87, 88: q88, 89: q89, 90: q90, 91: q91,
    92: q92, 93: q93, 94: q94, 95: q95, 96: q96, 97: q97, 98: q98, 99: q99,
}
