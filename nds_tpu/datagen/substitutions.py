"""Per-query parameter generators for the query templates.

The dsqgen `define` equivalents: each generator draws this query's
substitution values from a seeded RNG, using the same categorical
vocabularies the data generator emits (nds_tpu/datagen/native/vocab.hpp), so
predicates hit real data. Sales dates span 1998-01-01..2002-12-31
(kSalesFirstSk..kSalesLastSk in rowcounts.hpp).
"""

from __future__ import annotations

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]

CLASSES = {
    "Books": ["arts", "business", "computers", "cooking", "history", "mystery", "romance", "science"],
    "Children": ["infants", "newborn", "school-uniforms", "toddlers", "accessories", "shirts", "pants", "swimwear"],
    "Electronics": ["audio", "cameras", "dvd/vcr players", "karoke", "memory", "monitors", "portable", "televisions"],
    "Home": ["bathroom", "bedding", "blinds/shades", "curtains/drapes", "decor", "flatware", "furniture", "kids"],
    "Jewelry": ["birdal", "costume", "diamonds", "estate", "gold", "loose stones", "pendants", "rings"],
    "Men": ["accessories", "pants", "shirts", "sports-apparel", "underwear", "dress shirts", "suits", "casual"],
    "Music": ["classical", "country", "pop", "rock", "jazz", "blues", "folk", "world"],
    "Shoes": ["athletic", "dress", "kids", "mens", "womens", "work", "sandals", "boots"],
    "Sports": ["archery", "baseball", "basketball", "camping", "fishing", "fitness", "golf", "hockey"],
    "Women": ["dresses", "fragrances", "intimates", "maternity", "swimwear", "accessories", "shirts", "pants"],
}

STATES = [
    "AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA", "HI", "ID", "IL",
    "IN", "IA", "KS", "KY", "LA", "ME", "MD", "MA", "MI", "MN", "MS", "MO", "MT",
    "NE", "NV", "NH", "NJ", "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI",
    "SC", "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY",
]

COUNTIES = [
    "Williamson County", "Walker County", "Ziebach County", "Richland County",
    "Barrow County", "Bronx County", "Maricopa County", "Jackson County",
    "Franklin County", "Jefferson County", "Washington County", "Lincoln County",
    "Madison County", "Montgomery County", "Clay County", "Marion County",
]

CITIES = [
    "Fairview", "Midway", "Pleasant Hill", "Centerville", "Riverside",
    "Five Points", "Oak Grove", "Pleasant Valley", "Mountain View", "Salem",
    "Union", "Liberty", "Greenville", "Franklin", "Springfield",
]

EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
GENDERS = ["M", "F"]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blue", "blush", "brown", "chartreuse", "chocolate", "coral", "cream",
    "cyan", "firebrick", "forest", "gainsboro", "goldenrod", "green", "grey",
    "honeydew", "indian", "ivory", "khaki", "lavender", "lemon", "light",
    "lime", "linen", "magenta", "maroon", "medium", "midnight", "mint",
    "misty", "navy", "olive", "orange", "orchid", "pale", "papaya", "peach",
    "pink", "plum", "powder", "puff", "purple", "red", "rose", "rosy",
    "royal", "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
    "slate", "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato",
    "turquoise", "violet", "wheat", "white", "yellow",
]

SALES_YEARS = (1998, 2002)


def _year(rng, lo=None, hi=None):
    lo = lo or SALES_YEARS[0]
    hi = hi or SALES_YEARS[1]
    return int(rng.integers(lo, hi + 1))


def _choice(rng, xs):
    return xs[int(rng.integers(0, len(xs)))]


def _distinct(rng, xs, n):
    idx = rng.permutation(len(xs))[:n]
    return [xs[i] for i in idx]


def _date_in_year(rng, year, latest_month=11):
    m = int(rng.integers(1, latest_month + 1))
    d = int(rng.integers(1, 29))
    return f"{year}-{m:02d}-{d:02d}"


def q1(rng, scale):
    return {"YEAR": _year(rng), "STATE": _choice(rng, STATES), "AGG_FIELD": "sr_return_amt"}


def q3(rng, scale):
    return {
        "MANUFACT": int(rng.integers(1, 1001)),
        "MONTH": int(rng.integers(11, 13)),
        "AGGC": _choice(
            rng,
            ["ss_ext_sales_price", "ss_sales_price", "ss_ext_discount_amt", "ss_net_profit"],
        ),
    }


def q6(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(1, 8))}


def q7(rng, scale):
    return {
        "YEAR": _year(rng),
        "GEN": _choice(rng, GENDERS),
        "MS": _choice(rng, MARITAL),
        "ES": _choice(rng, EDUCATION[:6]),
    }


def q12(rng, scale):
    year = _year(rng)
    cats = _distinct(rng, CATEGORIES, 3)
    return {
        "YEAR": year,
        "SDATE": _date_in_year(rng, year, 7),
        "CAT_A": cats[0], "CAT_B": cats[1], "CAT_C": cats[2],
    }


def q13(rng, scale):
    ms = _distinct(rng, MARITAL, 3)
    es = _distinct(rng, EDUCATION[:6], 3)
    st = [_distinct(rng, STATES, 3) for _ in range(3)]
    out = {"MS1": ms[0], "MS2": ms[1], "MS3": ms[2],
           "ES1": es[0], "ES2": es[1], "ES3": es[2]}
    for g, group in enumerate(st, 1):
        for i, s in enumerate(group, 1):
            out[f"STATE{g}{i}"] = s
    return out


def q15(rng, scale):
    return {"YEAR": _year(rng), "QOY": int(rng.integers(1, 3))}


def q19(rng, scale):
    return {
        "YEAR": _year(rng),
        "MONTH": int(rng.integers(11, 13)),
        "MANAGER": int(rng.integers(1, 101)),
    }


def q20(rng, scale):
    return q12(rng, scale)


def q25(rng, scale):
    return {"YEAR": _year(rng)}


def q26(rng, scale):
    return q7(rng, scale)


def q42(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13))}


def q43(rng, scale):
    return {"YEAR": _year(rng), "GMT": "-5"}


def q52(rng, scale):
    return q42(rng, scale)


def q55(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13)),
            "MANAGER": int(rng.integers(1, 101))}


def q96(rng, scale):
    return {"HOUR": int(rng.integers(15, 21)), "DEPCNT": int(rng.integers(0, 10))}


def q98(rng, scale):
    return q12(rng, scale)


def q37(rng, scale):
    year = _year(rng)
    return {
        "SDATE": _date_in_year(rng, year, 6),
        "PRICE": int(rng.integers(10, 61)),
        "MANU_A": int(rng.integers(1, 1001)),
        "MANU_B": int(rng.integers(1, 1001)),
        "MANU_C": int(rng.integers(1, 1001)),
        "MANU_D": int(rng.integers(1, 1001)),
    }


def q82(rng, scale):
    return q37(rng, scale)


def q41(rng, scale):
    return {"MANUFACT": int(rng.integers(600, 701))}


def q45(rng, scale):
    return {"YEAR": _year(rng), "QOY": int(rng.integers(1, 3))}


def q48(rng, scale):
    ms = _distinct(rng, MARITAL, 3)
    es = _distinct(rng, EDUCATION[:6], 3)
    st = [_distinct(rng, STATES, 3) for _ in range(3)]
    out = {"YEAR": _year(rng),
           "MS1": ms[0], "MS2": ms[1], "MS3": ms[2],
           "ES1": es[0], "ES2": es[1], "ES3": es[2]}
    for g, group in enumerate(st, 1):
        for i, s in enumerate(group, 1):
            out[f"STATE{g}{i}"] = s
    return out


def q61(rng, scale):
    return {"YEAR": _year(rng), "MONTH": int(rng.integers(11, 13)),
            "GMT": "-5", "CATEGORY": _choice(rng, CATEGORIES)}


def q65(rng, scale):
    return {"YEAR": _year(rng)}


def q68(rng, scale):
    cities = _distinct(rng, CITIES, 2)
    return {"YEAR": _year(rng), "CITY_A": cities[0], "CITY_B": cities[1],
            "DEPCNT": int(rng.integers(0, 10)), "VEHCNT": int(rng.integers(-1, 5))}


def q73(rng, scale):
    return {"YEAR": _year(rng),
            "BP1": _choice(rng, BUY_POTENTIAL), "BP2": _choice(rng, BUY_POTENTIAL),
            "COUNTY1": _choice(rng, COUNTIES), "COUNTY2": _choice(rng, COUNTIES),
            "COUNTY3": _choice(rng, COUNTIES), "COUNTY4": _choice(rng, COUNTIES)}


def q79(rng, scale):
    return {"YEAR": _year(rng), "DEPCNT": int(rng.integers(0, 10)),
            "VEHCNT": int(rng.integers(-1, 5))}


def q88(rng, scale):
    return {"STORE": "Unknown", "DEPCNT1": int(rng.integers(0, 5)),
            "DEPCNT2": int(rng.integers(0, 5)), "DEPCNT3": int(rng.integers(0, 5))}


def q93(rng, scale):
    return {"REASON": "reason 28"}


PARAM_GENERATORS = {
    1: q1, 3: q3, 6: q6, 7: q7, 12: q12, 13: q13, 15: q15, 19: q19, 20: q20,
    25: q25, 26: q26, 37: q37, 41: q41, 42: q42, 43: q43, 45: q45, 48: q48,
    52: q52, 55: q55, 61: q61, 65: q65, 68: q68, 73: q73, 79: q79, 82: q82,
    88: q88, 93: q93, 96: q96, 98: q98,
}
