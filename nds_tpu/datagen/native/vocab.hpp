// Categorical vocabularies for ndsgen. These mirror the value domains the
// TPC-DS spec defines for low-cardinality columns (the values queries filter
// and group on), so generated data exercises the same predicates.
#pragma once

#include <cstddef>

namespace ndsgen::vocab {

inline constexpr const char* kCategories[] = {
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women"};

// i_class values per category (flattened; index = cat*8 + k, 8 classes each).
inline constexpr const char* kClasses[] = {
    // Books
    "arts", "business", "computers", "cooking", "history", "mystery", "romance", "science",
    // Children
    "infants", "newborn", "school-uniforms", "toddlers", "accessories", "shirts", "pants", "swimwear",
    // Electronics
    "audio", "cameras", "dvd/vcr players", "karoke", "memory", "monitors", "portable", "televisions",
    // Home
    "bathroom", "bedding", "blinds/shades", "curtains/drapes", "decor", "flatware", "furniture", "kids",
    // Jewelry
    "birdal", "costume", "diamonds", "estate", "gold", "loose stones", "pendants", "rings",
    // Men
    "accessories", "pants", "shirts", "sports-apparel", "underwear", "dress shirts", "suits", "casual",
    // Music
    "classical", "country", "pop", "rock", "jazz", "blues", "folk", "world",
    // Shoes
    "athletic", "dress", "kids", "mens", "womens", "work", "sandals", "boots",
    // Sports
    "archery", "baseball", "basketball", "camping", "fishing", "fitness", "golf", "hockey",
    // Women
    "dresses", "fragrances", "intimates", "maternity", "swimwear", "accessories", "shirts", "pants"};

inline constexpr const char* kColors[] = {
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black", "blanched",
    "blue", "blush", "brown", "burlywood", "burnished", "chartreuse", "chiffon", "chocolate",
    "coral", "cornflower", "cornsilk", "cream", "cyan", "dark", "deep", "dim",
    "dodger", "drab", "firebrick", "floral", "forest", "frosted", "gainsboro", "ghost",
    "goldenrod", "green", "grey", "honeydew", "hot", "indian", "ivory", "khaki",
    "lace", "lavender", "lawn", "lemon", "light", "lime", "linen", "magenta",
    "maroon", "medium", "metallic", "midnight", "mint", "misty", "moccasin", "navajo",
    "navy", "olive", "orange", "orchid", "pale", "papaya", "peach", "peru",
    "pink", "plum", "powder", "puff", "purple", "red", "rose", "rosy",
    "royal", "saddle", "salmon", "sandy", "seashell", "sienna", "sky", "slate",
    "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato", "turquoise",
    "violet", "wheat", "white", "yellow"};

inline constexpr const char* kSizes[] = {
    "petite", "small", "medium", "large", "extra large", "economy", "N/A"};

inline constexpr const char* kUnits[] = {
    "Unknown", "Oz", "Bunch", "Ton", "N/A", "Dozen", "Box", "Pound", "Pallet",
    "Gross", "Cup", "Dram", "Each", "Tbl", "Lb", "Bundle", "Tsp", "Ounce", "Case", "Carton"};

inline constexpr const char* kEducation[] = {
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown"};

inline constexpr const char* kMarital[] = {"M", "S", "D", "W", "U"};

inline constexpr const char* kCreditRating[] = {
    "Low Risk", "High Risk", "Good", "Unknown"};

inline constexpr const char* kBuyPotential[] = {
    "0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"};

inline constexpr const char* kFirstNames[] = {
    "James", "John", "Robert", "Michael", "William", "David", "Richard", "Charles",
    "Joseph", "Thomas", "Mary", "Patricia", "Linda", "Barbara", "Elizabeth", "Jennifer",
    "Maria", "Susan", "Margaret", "Dorothy", "Daniel", "Paul", "Mark", "Donald",
    "George", "Kenneth", "Steven", "Edward", "Brian", "Ronald", "Anthony", "Kevin",
    "Jason", "Matthew", "Gary", "Timothy", "Jose", "Larry", "Jeffrey", "Frank",
    "Lisa", "Nancy", "Karen", "Betty", "Helen", "Sandra", "Donna", "Carol",
    "Ruth", "Sharon", "Michelle", "Laura", "Sarah", "Kimberly", "Deborah", "Jessica",
    "Shirley", "Cynthia", "Angela", "Melissa", "Brenda", "Amy", "Anna", "Rebecca"};

inline constexpr const char* kLastNames[] = {
    "Smith", "Johnson", "Williams", "Jones", "Brown", "Davis", "Miller", "Wilson",
    "Moore", "Taylor", "Anderson", "Thomas", "Jackson", "White", "Harris", "Martin",
    "Thompson", "Garcia", "Martinez", "Robinson", "Clark", "Rodriguez", "Lewis", "Lee",
    "Walker", "Hall", "Allen", "Young", "Hernandez", "King", "Wright", "Lopez",
    "Hill", "Scott", "Green", "Adams", "Baker", "Gonzalez", "Nelson", "Carter",
    "Mitchell", "Perez", "Roberts", "Turner", "Phillips", "Campbell", "Parker", "Evans",
    "Edwards", "Collins", "Stewart", "Sanchez", "Morris", "Rogers", "Reed", "Cook",
    "Morgan", "Bell", "Murphy", "Bailey", "Rivera", "Cooper", "Richardson", "Cox"};

inline constexpr const char* kStreetNames[] = {
    "Main", "Oak", "Park", "First", "Second", "Third", "Fourth", "Fifth",
    "Cedar", "Elm", "View", "Washington", "Lake", "Hill", "Walnut", "Spring",
    "North", "Ridge", "Church", "Willow", "Mill", "Sunset", "Railroad", "Jackson",
    "Maple", "Pine", "Highland", "Johnson", "Dogwood", "Chestnut", "Laurel", "Poplar",
    "College", "Woodland", "Franklin", "Meadow", "Forest", "Hickory", "Green", "River",
    "Valley", "Smith", "Lincoln", "Davis", "Locust", "Wilson", "Broadway", "Center",
    "Lee", "Birch", "Adams", "Jefferson", "Sycamore", "Miller", "Madison", "Cherry",
    "Eighth", "Sixth", "Seventh", "Ninth", "Tenth", "Eleventh", "Twelfth", "Thirteenth"};

inline constexpr const char* kStreetTypes[] = {
    "Street", "ST", "Avenue", "Ave", "Boulevard", "Blvd", "Road", "RD", "Circle",
    "Cir", "Court", "Ct", "Drive", "Dr", "Lane", "Ln", "Parkway", "Pkwy", "Way", "Wy"};

inline constexpr const char* kCities[] = {
    "Fairview", "Midway", "Oak Grove", "Five Points", "Pleasant Hill", "Centerville",
    "Liberty", "Salem", "Riverside", "Greenville", "Franklin", "Springfield",
    "Farmington", "Union", "Oakland", "Glendale", "Bethel", "Clinton", "Georgetown",
    "Marion", "Greenfield", "Oakdale", "Jamestown", "Kingston", "Waterloo",
    "Summit", "Ashland", "Newport", "Clifton", "Lakeside", "Sunnyside", "Woodville",
    "Glenwood", "Mount Pleasant", "Harmony", "Concord", "Belmont", "Antioch",
    "Arlington", "Bridgeport", "Brownsville", "Buena Vista", "Crossroads", "Deerfield",
    "Edgewood", "Enterprise", "Florence", "Forest Hills", "Friendship", "Hamilton",
    "Highland Park", "Hillcrest", "Hopewell", "Lakeview", "Lebanon", "Lincoln",
    "Macedonia", "Maple Grove", "Mount Olive", "Mount Zion", "New Hope", "Pine Grove",
    "Pleasant Valley", "Providence", "Red Hill", "Riverdale", "Rockwood", "Shady Grove",
    "Shiloh", "Stringtown", "Unionville", "Walnut Grove", "White Oak", "Wildwood"};

// (county, state) pairs; ~30 states weighted toward the populous ones.
inline constexpr const char* kCounties[] = {
    "Williamson County", "Walker County", "Ziebach County", "Richland County",
    "Barrow County", "Bronx County", "Maricopa County", "Jackson County",
    "Franklin County", "Jefferson County", "Washington County", "Lincoln County",
    "Madison County", "Montgomery County", "Clay County", "Marion County",
    "Monroe County", "Greene County", "Wayne County", "Union County",
    "Perry County", "Fairfield County", "Huron County", "Luce County",
    "Dauphin County", "San Miguel County", "Pennington County", "Mobile County",
    "Kittitas County", "Terrell County", "Pipestone County", "Levy County"};

inline constexpr const char* kStates[] = {
    "AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA", "HI", "ID", "IL",
    "IN", "IA", "KS", "KY", "LA", "ME", "MD", "MA", "MI", "MN", "MS", "MO", "MT",
    "NE", "NV", "NH", "NJ", "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI",
    "SC", "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY"};

inline constexpr const char* kCountry = "United States";

inline constexpr const char* kLocationTypes[] = {"apartment", "condo", "single family"};

inline constexpr const char* kShipModeTypes[] = {
    "EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"};
inline constexpr const char* kShipModeCodes[] = {"AIR", "SURFACE", "SEA"};
inline constexpr const char* kShipModeCarriers[] = {
    "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "ZOUROS",
    "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES", "RUPEKSA",
    "HARMSTORF", "PRIVATECARRIER", "DIAMOND", "GREAT EASTERN", "GERMA"};

inline constexpr const char* kReasons[] = {
    "Package was damaged", "Stopped working", "Did not get it on time", "Not the product that was ordred",
    "Parts missing", "Does not work with a product that I have", "Gift exchange", "Did not like the color",
    "Did not like the model", "Did not like the make", "Did not like the warranty", "No service location in my area",
    "Found a better price in a store", "Found a better extended warranty in a store", "reason 15", "reason 16",
    "reason 17", "reason 18", "reason 19", "reason 20", "reason 21", "reason 22", "reason 23", "reason 24",
    "reason 25", "reason 26", "reason 27", "reason 28", "reason 29", "reason 30", "reason 31", "reason 32",
    "reason 33", "reason 34", "reason 35", "reason 36", "reason 37", "reason 38", "reason 39", "reason 40",
    "reason 41", "reason 42", "reason 43", "reason 44", "reason 45", "reason 46", "reason 47", "reason 48",
    "reason 49", "reason 50", "reason 51", "reason 52", "reason 53", "reason 54", "reason 55", "reason 56",
    "reason 57", "reason 58", "reason 59", "reason 60", "reason 61", "reason 62", "reason 63", "reason 64",
    "reason 65"};

inline constexpr const char* kPromoNames[] = {
    "ese", "anti", "ought", "able", "pri", "bar", "cally", "ation", "eing", "n st"};
inline constexpr const char* kWebSiteNames[] = {"site_0", "site_1", "site_2", "site_3"};
inline constexpr const char* kMarketClasses[] = {
    "A bit narrow forms matter animals. Consist", "Largely blank years put substantially deaf, new others. Question",
    "Wrong troops shall work sometimes in a opti", "Bites followed via the tough, keen candidates. Beds need other, true l",
    "Admit forms. Tests act curiously. For",  "Express, sorry conditions mean as well gay arms. Real materials ra"};

inline constexpr const char* kMealTimes[] = {"breakfast", "lunch", "dinner"};
inline constexpr const char* kShifts[] = {"first", "second", "third"};
inline constexpr const char* kSubShifts[] = {"morning", "afternoon", "evening", "night"};

inline constexpr const char* kStoreNames[] = {
    "ought", "able", "pri", "ese", "anti", "cally", "ation", "eing", "bar", "n st"};

inline constexpr const char* kDivisionNames[] = {"Unknown", "ably", "ation", "bar", "eing", "ese"};
inline constexpr const char* kCompanyNames[] = {"Unknown", "ally", "ble", "cally", "ought", "pri"};

inline constexpr const char* kCcClass[] = {"small", "medium", "large"};
inline constexpr const char* kCcHours[] = {"8AM-4PM", "8AM-12AM", "8AM-8AM"};
inline constexpr const char* kManagers[] = {
    "Bob Belcher", "Felipe Perkins", "Mark Hightower", "Larry Mccray", "Gary Colburn",
    "Matthew Clifton", "Daniel Weller", "William Ward", "Gregory Altman", "Brandon Moore",
    "Kenneth Harlan", "Scott Smith", "Robert Thompson", "David Lamontagne", "Steven Barnes",
    "Jonathan Smith", "Eric Hoffman", "Phillip Sanders", "Dustin Gamble", "Harold Jones"};

template <typename T, size_t N>
constexpr size_t len(const T (&)[N]) {
  return N;
}

}  // namespace ndsgen::vocab
