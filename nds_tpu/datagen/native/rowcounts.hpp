// Row-count scaling model. Fact tables are derived from order counts that
// scale linearly with SF; dimension tables follow the spec's published
// row counts at the defined scale points with geometric interpolation
// in between (exact at SF=1). Fractional SF < 1 is supported for smoke
// tests (the reference toolkit does not allow this; we do, because fast
// tiny-scale runs are how the test suite stays green).
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <string>

namespace ndsgen {

struct ScalePoints {
  // row counts at SF = 1, 10, 100, 1000, 3000, 10000, 100000
  int64_t at[7];
};

inline constexpr double kScaleKnots[7] = {1, 10, 100, 1000, 3000, 10000, 100000};

inline int64_t interp_count(const ScalePoints& p, double sf) {
  if (sf <= 1.0) {
    // sub-SF1 smoke scales: shrink smoothly but keep at least a handful of rows
    double v = static_cast<double>(p.at[0]) * sf;
    return std::max<int64_t>(static_cast<int64_t>(std::ceil(v)), std::min<int64_t>(p.at[0], 2));
  }
  for (int i = 0; i < 6; ++i) {
    if (sf <= kScaleKnots[i + 1]) {
      double t = (std::log(sf) - std::log(kScaleKnots[i])) /
                 (std::log(kScaleKnots[i + 1]) - std::log(kScaleKnots[i]));
      double lo = std::log(static_cast<double>(p.at[i]));
      double hi = std::log(static_cast<double>(p.at[i + 1]));
      return static_cast<int64_t>(std::llround(std::exp(lo + t * (hi - lo))));
    }
  }
  return p.at[6];
}

// Spec row counts (TPC-DS v3.2.0 table 3-2) at the defined scale points.
inline int64_t dim_rows(const std::string& table, double sf) {
  static const struct {
    const char* name;
    ScalePoints p;
  } kCounts[] = {
      {"call_center", {{6, 24, 30, 42, 48, 54, 60}}},
      {"catalog_page", {{11718, 12000, 20400, 30000, 36000, 40000, 50000}}},
      {"customer", {{100000, 500000, 2000000, 12000000, 30000000, 65000000, 100000000}}},
      {"customer_address", {{50000, 250000, 1000000, 6000000, 15000000, 32500000, 50000000}}},
      {"item", {{18000, 102000, 204000, 300000, 360000, 402000, 502000}}},
      {"promotion", {{300, 500, 1000, 1500, 1800, 2000, 2500}}},
      {"reason", {{35, 45, 55, 65, 67, 70, 75}}},
      {"store", {{12, 102, 402, 1002, 1350, 1500, 1902}}},
      {"warehouse", {{5, 10, 15, 20, 22, 25, 30}}},
      {"web_page", {{60, 200, 2040, 3000, 3600, 4002, 5004}}},
      {"web_site", {{30, 42, 54, 60, 66, 78, 96}}},
  };
  for (const auto& e : kCounts) {
    if (table == e.name) return interp_count(e.p, sf);
  }
  // fixed-size tables
  if (table == "customer_demographics") return 1920800;  // full cross product
  if (table == "household_demographics") return 7200;    // full cross product
  if (table == "date_dim") return kDateDimRows;
  if (table == "time_dim") return 86400;
  if (table == "income_band") return 20;
  if (table == "ship_mode") return 20;
  return -1;
}

// Order (purchase-unit) counts for the three sales channels; lines per order
// are drawn uniformly from [lo,hi] so expected row counts match the spec
// (store 2,880,404 @SF1 via 240k orders x avg 12 lines, etc.).
struct Channel {
  int64_t orders_sf1;
  int lines_lo, lines_hi;
};
inline constexpr Channel kStore{240000, 8, 16};
inline constexpr Channel kCatalog{160000, 4, 14};
inline constexpr Channel kWeb{60000, 8, 16};

inline int64_t channel_orders(const Channel& c, double sf) {
  return std::max<int64_t>(1, static_cast<int64_t>(std::llround(c.orders_sf1 * sf)));
}

// Inventory is a full cross product: 261 weekly snapshots x items/2 x warehouses.
inline constexpr int64_t kInventoryWeeks = 261;
inline int64_t inventory_items(double sf) { return std::max<int64_t>(1, dim_rows("item", sf) / 2); }

}  // namespace ndsgen
