// ndsgen: native TPC-DS-shaped data generator for the nds-tpu framework.
//
// Counterpart of the external dsdgen toolkit the reference builds/patches
// (reference: nds/tpcds-gen/Makefile:14-22, nds/tpcds-gen/patches/code.patch).
// Unlike dsdgen's stateful stream RNG, generation here is COUNTER-BASED:
// every field value is a pure function hash(seed, table, unit, line, col),
// so any chunk [child of parallel] is generated independently with no
// skip-ahead cost, and re-generating a sales chunk lets the matching
// returns chunk be derived without storing the sales rows.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace ndsgen {

// ---------------------------------------------------------------------------
// Counter-based RNG: splitmix64 finalizer over a mixed key.
// ---------------------------------------------------------------------------
inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Rng {
  uint64_t key;
  explicit Rng(uint64_t seed, uint64_t table, uint64_t unit, uint64_t line = 0)
      : key(mix64(mix64(mix64(seed ^ (table << 48)) ^ unit) ^ (line * 0x9e3779b97f4a7c15ULL))) {}

  // Independent draw for column `col`, draw index `n` (for multi-draw columns).
  uint64_t raw(uint32_t col, uint32_t n = 0) const {
    return mix64(key ^ (static_cast<uint64_t>(col) << 32) ^ n);
  }
  // uniform integer in [lo, hi] inclusive
  int64_t range(uint32_t col, int64_t lo, int64_t hi, uint32_t n = 0) const {
    return lo + static_cast<int64_t>(raw(col, n) % static_cast<uint64_t>(hi - lo + 1));
  }
  // uniform double in [0,1)
  double unit_f(uint32_t col, uint32_t n = 0) const {
    return (raw(col, n) >> 11) * (1.0 / 9007199254740992.0);
  }
  // true with probability pct/100
  bool chance(uint32_t col, int pct, uint32_t n = 0) const {
    return static_cast<int>(raw(col, n) % 100) < pct;
  }
  // decimal with `scale` implied digits, uniform in [lo, hi] (as doubles)
  int64_t dec(uint32_t col, double lo, double hi, int64_t pow10, uint32_t n = 0) const {
    double v = lo + unit_f(col, n) * (hi - lo);
    return static_cast<int64_t>(v * static_cast<double>(pow10) + 0.5);
  }
};

// ---------------------------------------------------------------------------
// Civil-date helpers (days_from_civil / civil_from_days, Hinnant algorithm).
// TPC-DS date surrogate keys are Julian day numbers; d_date_sk 2415022 is
// 1900-01-02, the first row of date_dim.
// ---------------------------------------------------------------------------
constexpr int64_t kJulianOfEpoch = 2440588;  // Julian day number of 1970-01-01

inline int64_t days_from_civil(int y, int m, int d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153u * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + static_cast<int64_t>(doe) - 719468;
}

inline void civil_from_days(int64_t z, int* y, unsigned* m, unsigned* d) {
  z += 719468;
  const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  const unsigned doe = static_cast<unsigned>(z - era * 146097);
  const unsigned yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const int yy = static_cast<int>(yoe) + static_cast<int>(era) * 400;
  const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const unsigned mp = (5 * doy + 2) / 153;
  *d = doy - (153 * mp + 2) / 5 + 1;
  *m = mp + (mp < 10 ? 3 : -9);
  *y = yy + (*m <= 2);
}

inline int64_t julian_from_civil(int y, unsigned m, unsigned d) {
  return days_from_civil(y, static_cast<int>(m), static_cast<int>(d)) + kJulianOfEpoch;
}

// date_dim span: 1900-01-02 .. 2100-01-01, 73049 rows (spec row count).
constexpr int64_t kDateDimFirstSk = 2415022;  // 1900-01-02
constexpr int64_t kDateDimRows = 73049;
// Sales activity window used for fact-table date keys: 1998-01-01..2003-01-01.
constexpr int64_t kSalesFirstSk = 2450815;   // julian of 1998-01-01
constexpr int64_t kSalesLastSk = 2452642;    // julian of 2002-12-31

// ---------------------------------------------------------------------------
// Buffered pipe-delimited row writer (trailing '|' per dsdgen convention).
// ---------------------------------------------------------------------------
class RowWriter {
 public:
  explicit RowWriter(FILE* f) : f_(f) { buf_.reserve(1 << 16); }
  ~RowWriter() { flush(); }

  void null_field() { buf_.push_back('|'); }
  void i64(int64_t v) {
    char tmp[24];
    int n = snprintf(tmp, sizeof(tmp), "%lld", static_cast<long long>(v));
    buf_.append(tmp, n);
    buf_.push_back('|');
  }
  void str(const char* s) {
    buf_.append(s);
    buf_.push_back('|');
  }
  void str(const std::string& s) {
    buf_.append(s);
    buf_.push_back('|');
  }
  // scaled decimal with 2 fraction digits (the only scale TPC-DS uses)
  void dec2(int64_t scaled) {
    char tmp[32];
    int64_t a = scaled < 0 ? -scaled : scaled;
    int n = snprintf(tmp, sizeof(tmp), "%s%lld.%02lld", scaled < 0 ? "-" : "",
                     static_cast<long long>(a / 100), static_cast<long long>(a % 100));
    buf_.append(tmp, n);
    buf_.push_back('|');
  }
  void date_from_julian(int64_t jd) {
    int y;
    unsigned m, d;
    civil_from_days(jd - kJulianOfEpoch, &y, &m, &d);
    char tmp[16];
    int n = snprintf(tmp, sizeof(tmp), "%04d-%02u-%02u", y, m, d);
    buf_.append(tmp, n);
    buf_.push_back('|');
  }
  void end_row() {
    buf_.push_back('\n');
    if (buf_.size() > (1u << 20)) flush();
  }
  void flush() {
    if (!buf_.empty()) {
      fwrite(buf_.data(), 1, buf_.size(), f_);
      buf_.clear();
    }
  }

 private:
  FILE* f_;
  std::string buf_;
};

// 16-char business id: "AAAAAAAA" + base-16 suffix over A..P, per-table unique.
inline std::string business_id(int64_t idx) {
  char out[17];
  for (int i = 15; i >= 0; --i) {
    out[i] = static_cast<char>('A' + (idx & 0xF));
    idx >>= 4;
  }
  return std::string(out, 16);
}

}  // namespace ndsgen
