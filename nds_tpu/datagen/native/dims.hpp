// Dimension-table row generators. Each generator is a pure function of
// (seed, table, row index) via the counter RNG, so chunks are independent.
// Column order matches nds_tpu/_schema_data.py exactly (TPC-DS spec order).
#pragma once

#include <string>

#include "ndsgen.hpp"
#include "rowcounts.hpp"
#include "vocab.hpp"

namespace ndsgen {

using namespace vocab;

struct Ctx {
  double sf = 1.0;
  uint64_t seed = 19620718;  // default RNGSEED, overridable via CLI
  // cached dimension cardinalities for FK draws
  int64_t n_customer, n_address, n_item, n_store, n_warehouse, n_web_page;
  int64_t n_web_site, n_call_center, n_catalog_page, n_promotion, n_reason;
  int64_t n_inv_items;

  explicit Ctx(double scale, uint64_t s) : sf(scale), seed(s) {
    n_customer = dim_rows("customer", sf);
    n_address = dim_rows("customer_address", sf);
    n_item = dim_rows("item", sf);
    n_store = dim_rows("store", sf);
    n_warehouse = dim_rows("warehouse", sf);
    n_web_page = dim_rows("web_page", sf);
    n_web_site = dim_rows("web_site", sf);
    n_call_center = dim_rows("call_center", sf);
    n_catalog_page = dim_rows("catalog_page", sf);
    n_promotion = dim_rows("promotion", sf);
    n_reason = dim_rows("reason", sf);
    n_inv_items = inventory_items(sf);
  }
};

enum TableId : uint64_t {
  T_CUSTOMER_ADDRESS = 1, T_CUSTOMER_DEMOGRAPHICS, T_DATE_DIM, T_WAREHOUSE,
  T_SHIP_MODE, T_TIME_DIM, T_REASON, T_INCOME_BAND, T_ITEM, T_STORE,
  T_CALL_CENTER, T_CUSTOMER, T_WEB_SITE, T_STORE_RETURNS, T_HOUSEHOLD_DEMOGRAPHICS,
  T_WEB_PAGE, T_PROMOTION, T_CATALOG_PAGE, T_INVENTORY, T_CATALOG_RETURNS,
  T_WEB_RETURNS, T_WEB_SALES, T_CATALOG_SALES, T_STORE_SALES,
  T_S_PURCHASE = 40, T_S_CATALOG_ORDER, T_S_WEB_ORDER, T_S_INVENTORY, T_DELETE,
};

// ---- small shared helpers -------------------------------------------------

inline const char* pick(const Rng& r, uint32_t col, const char* const* list, size_t n,
                        uint32_t draw = 0) {
  return list[r.raw(col, draw) % n];
}

inline std::string rand_word_text(const Rng& r, uint32_t col, int min_words, int max_words) {
  static const char* kWords[] = {
      "found", "early", "important", "public", "different", "small", "large", "national",
      "young", "major", "quiet", "certain", "social", "only", "special", "right",
      "results", "things", "years", "members", "police", "parts", "eyes", "forces",
      "levels", "times", "areas", "hands", "services", "words", "studies", "books",
      "come", "show", "take", "make", "give", "look", "work", "seem",
      "get", "feel", "pass", "carry", "remain", "however", "again", "never"};
  int n = min_words + static_cast<int>(r.raw(col, 900) % (max_words - min_words + 1));
  std::string out;
  for (int i = 0; i < n; ++i) {
    if (i) out.push_back(' ');
    out += kWords[r.raw(col, 901 + i) % vocab::len(kWords)];
  }
  return out;
}

inline std::string zip_for(const Rng& r, uint32_t col) {
  char z[6];
  snprintf(z, sizeof(z), "%05d", static_cast<int>(r.raw(col) % 100000));
  return z;
}

// Emits the 10-column address block used (in this order) by customer_address,
// warehouse, store, call_center, web_site: street_number, street_name,
// street_type, suite_number, city, county, state, zip, country, gmt_offset.
inline void emit_address(RowWriter& w, const Rng& r, uint32_t c0) {
  w.i64(r.range(c0 + 0, 1, 1000));
  {
    // street name: one or two words
    std::string name = pick(r, c0 + 1, kStreetNames, len(kStreetNames));
    if (r.chance(c0 + 1, 40, 7)) {
      name += " ";
      name += pick(r, c0 + 1, kStreetNames, len(kStreetNames), 8);
    }
    w.str(name);
  }
  w.str(pick(r, c0 + 2, kStreetTypes, len(kStreetTypes)));
  {
    char suite[16];
    if (r.chance(c0 + 3, 50))
      snprintf(suite, sizeof(suite), "Suite %d", static_cast<int>(r.raw(c0 + 3, 1) % 500));
    else
      snprintf(suite, sizeof(suite), "Suite %c", static_cast<char>('A' + r.raw(c0 + 3, 1) % 26));
    w.str(suite);
  }
  size_t state_ix = r.raw(c0 + 6) % len(kStates);
  w.str(pick(r, c0 + 4, kCities, len(kCities)));
  w.str(pick(r, c0 + 5, kCounties, len(kCounties)));
  w.str(kStates[state_ix]);
  w.str(zip_for(r, c0 + 7));
  w.str(kCountry);
  w.dec2(-500 - 100 * static_cast<int64_t>(state_ix % 4));  // gmt offset -5..-8
}

// ---- dimension generators -------------------------------------------------

inline void gen_customer_address(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_CUSTOMER_ADDRESS, row);
  w.i64(row + 1);
  w.str(business_id(row + 1));
  emit_address(w, r, 10);
  w.str(pick(r, 30, kLocationTypes, len(kLocationTypes)));
  w.end_row();
}

inline void gen_customer_demographics(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  // full cross product, decomposed most-significant-first:
  // gender(2) x marital(5) x education(7) x purchase_estimate(20) x
  // credit_rating(4) x dep(7) x dep_employed(7) x dep_college(7) = 1,920,800
  int64_t ix = row;
  int dep_college = ix % 7; ix /= 7;
  int dep_emp = ix % 7; ix /= 7;
  int dep = ix % 7; ix /= 7;
  int credit = ix % 4; ix /= 4;
  int purch = ix % 20; ix /= 20;
  int edu = ix % 7; ix /= 7;
  int marital = ix % 5; ix /= 5;
  int gender = ix % 2;
  w.i64(row + 1);
  w.str(gender ? "F" : "M");
  w.str(kMarital[marital]);
  w.str(kEducation[edu]);
  w.i64((purch + 1) * 500);
  w.str(kCreditRating[credit]);
  w.i64(dep);
  w.i64(dep_emp);
  w.i64(dep_college);
  w.end_row();
}

inline void gen_date_dim(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  const int64_t jd = kDateDimFirstSk + row;
  int y; unsigned m, d;
  civil_from_days(jd - kJulianOfEpoch, &y, &m, &d);
  const int dow = static_cast<int>((jd + 1) % 7);  // 0=Sunday .. 6=Saturday
  static const char* kDays[] = {"Sunday", "Monday", "Tuesday", "Wednesday",
                                "Thursday", "Friday", "Saturday"};
  const int qoy = (m - 1) / 3 + 1;
  const bool holiday = (m == 7 && d == 4) || (m == 12 && d == 25) || (m == 1 && d == 1) ||
                       (m == 12 && d == 31);
  // previous day's holiday flag for d_following_holiday
  int py; unsigned pm, pd;
  civil_from_days(jd - 1 - kJulianOfEpoch, &py, &pm, &pd);
  const bool prev_holiday = (pm == 7 && pd == 4) || (pm == 12 && pd == 25) ||
                            (pm == 1 && pd == 1) || (pm == 12 && pd == 31);
  static const int kMonthDays[] = {31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31};
  const bool leap = (y % 4 == 0 && y % 100 != 0) || y % 400 == 0;
  const int dim = kMonthDays[m - 1] + (m == 2 && leap ? 1 : 0);

  w.i64(jd);
  w.str(business_id(jd));
  w.date_from_julian(jd);
  w.i64((y - 1900) * 12 + (m - 1));          // d_month_seq
  w.i64((row + 1) / 7 + 1);                  // d_week_seq (1900-01-02 was a Tuesday; weeks from 1900-01-01)
  w.i64((y - 1900) * 4 + (qoy - 1) + 1);     // d_quarter_seq
  w.i64(y);
  w.i64(dow);
  w.i64(m);
  w.i64(d);
  w.i64(qoy);
  w.i64(y);                                  // d_fy_year
  w.i64((y - 1900) * 4 + (qoy - 1) + 1);     // d_fy_quarter_seq
  w.i64((row + 1) / 7 + 1);                  // d_fy_week_seq
  w.str(kDays[dow]);
  {
    char q[8];
    snprintf(q, sizeof(q), "%04dQ%d", y, qoy);
    w.str(q);
  }
  w.str(holiday ? "Y" : "N");
  w.str(dow == 0 || dow == 6 ? "Y" : "N");
  w.str(prev_holiday ? "Y" : "N");
  w.i64(jd - d + 1);                         // d_first_dom
  w.i64(jd - d + dim);                       // d_last_dom
  w.i64(jd - 365);                           // d_same_day_ly
  w.i64(jd - 91);                            // d_same_day_lq
  w.str("N"); w.str("N"); w.str("N"); w.str("N"); w.str("N");
  w.end_row();
}

inline void gen_warehouse(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_WAREHOUSE, row);
  w.i64(row + 1);
  w.str(business_id(row + 1));
  w.str(rand_word_text(r, 2, 2, 3));
  w.i64(r.range(3, 50000, 1000000));
  emit_address(w, r, 10);
  w.end_row();
}

inline void gen_ship_mode(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_SHIP_MODE, row);
  w.i64(row + 1);
  w.str(business_id(row + 1));
  w.str(kShipModeTypes[row % len(kShipModeTypes)]);
  w.str(kShipModeCodes[row % len(kShipModeCodes)]);
  w.str(kShipModeCarriers[row % len(kShipModeCarriers)]);
  {
    char contract[21];
    for (int i = 0; i < 20; ++i)
      contract[i] = static_cast<char>('a' + r.raw(5, i) % 26);
    contract[20] = 0;
    w.str(contract);
  }
  w.end_row();
}

inline void gen_time_dim(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  const int hour = static_cast<int>(row / 3600);
  const int minute = static_cast<int>((row / 60) % 60);
  const int second = static_cast<int>(row % 60);
  w.i64(row);
  w.str(business_id(row));
  w.i64(row);
  w.i64(hour);
  w.i64(minute);
  w.i64(second);
  w.str(hour < 12 ? "AM" : "PM");
  w.str(hour >= 6 && hour < 14 ? kShifts[0] : (hour >= 14 && hour < 22 ? kShifts[1] : kShifts[2]));
  w.str(hour < 6 ? kSubShifts[3]
                 : (hour < 12 ? kSubShifts[0] : (hour < 18 ? kSubShifts[1] : kSubShifts[2])));
  if (hour >= 6 && hour <= 8) w.str(kMealTimes[0]);
  else if (hour >= 11 && hour <= 13) w.str(kMealTimes[1]);
  else if (hour >= 17 && hour <= 20) w.str(kMealTimes[2]);
  else w.null_field();
  w.end_row();
}

inline void gen_reason(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  w.i64(row + 1);
  w.str(business_id(row + 1));
  w.str(kReasons[row % len(kReasons)]);
  w.end_row();
}

inline void gen_income_band(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  w.i64(row + 1);
  w.i64(row == 0 ? 0 : row * 10000 + 1);
  w.i64((row + 1) * 10000);
  w.end_row();
}

inline void gen_item(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_ITEM, row);
  const int cat = static_cast<int>(r.raw(12) % len(kCategories));       // 0..9
  const int cls = static_cast<int>(r.raw(10) % 8);                      // 0..7 within category
  const int manufact = static_cast<int>(r.raw(13) % 1000) + 1;          // 1..1000
  const int brand_no = static_cast<int>(r.raw(8) % 10) + 1;
  const int64_t price = r.dec(5, 0.09, 99.99, 100);
  w.i64(row + 1);
  w.str(business_id(row + 1));
  // SCD-2 convention shared by all history-keeping dims: ODD sks (even row
  // index) are the current rows (null rec_end_date); fact generators and
  // inventory only reference odd sks.
  if (row % 2 == 0) {
    w.date_from_julian(julian_from_civil(1999, 10, 28));
    w.null_field();
  } else {
    w.date_from_julian(julian_from_civil(1997, 10, 27));
    w.date_from_julian(julian_from_civil(1999, 10, 27));
  }
  w.str(rand_word_text(r, 4, 5, 20));
  w.dec2(price);
  w.dec2(static_cast<int64_t>(price * 6 / 10));
  w.i64((cat + 1) * 1000000 + (cls + 1) * 1000 + brand_no);  // i_brand_id encodes cat/class/brand
  {
    char brand[32];
    snprintf(brand, sizeof(brand), "%s%s #%d", kPromoNames[cat], kPromoNames[cls], brand_no);
    w.str(brand);
  }
  w.i64(cat * 8 + cls + 1);
  w.str(kClasses[cat * 8 + cls]);
  w.i64(cat + 1);
  w.str(kCategories[cat]);
  w.i64(manufact);
  {
    char mfg[32];
    snprintf(mfg, sizeof(mfg), "%s%s", kPromoNames[manufact % 10], kPromoNames[(manufact / 10) % 10]);
    w.str(mfg);
  }
  w.str(pick(r, 15, kSizes, len(kSizes)));
  {
    char formulation[21];
    for (int i = 0; i < 20; ++i)
      formulation[i] = static_cast<char>('0' + r.raw(16, i) % 10);
    formulation[20] = 0;
    w.str(formulation);
  }
  w.str(pick(r, 17, kColors, len(kColors)));
  w.str(pick(r, 18, kUnits, len(kUnits)));
  w.str("Unknown");
  w.i64(r.range(20, 1, 100));
  {
    char pname[64];
    snprintf(pname, sizeof(pname), "%s%s%s%s", kPromoNames[r.raw(21, 0) % 10],
             kPromoNames[r.raw(21, 1) % 10], kPromoNames[r.raw(21, 2) % 10],
             kPromoNames[r.raw(21, 3) % 10]);
    w.str(pname);
  }
  w.end_row();
}

inline void gen_store(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_STORE, row);
  w.i64(row + 1);
  w.str(business_id(row / 2 + 1));  // SCD pairs share business id
  if (row % 2 == 0) {
    w.date_from_julian(julian_from_civil(1997, 3, 13));
    w.null_field();
  } else {
    w.date_from_julian(julian_from_civil(1997, 3, 13));
    w.date_from_julian(julian_from_civil(2000, 3, 12));
  }
  if (r.chance(4, 10)) w.i64(kSalesFirstSk + r.raw(4, 1) % 1500); else w.null_field();
  w.str(kStoreNames[row % len(kStoreNames)]);
  w.i64(r.range(6, 200, 300));
  w.i64(r.range(7, 5000000, 10000000));
  w.str(kCcHours[r.raw(8) % len(kCcHours)]);
  w.str(kManagers[r.raw(9) % len(kManagers)]);
  w.i64(r.range(10, 1, 10));
  w.str("Unknown");
  w.str(rand_word_text(r, 12, 6, 15));
  w.str(kManagers[r.raw(13) % len(kManagers)]);
  {
    int division = static_cast<int>(r.raw(14) % len(kDivisionNames));
    w.i64(division + 1);
    w.str(kDivisionNames[division]);
  }
  {
    int company = static_cast<int>(r.raw(16) % len(kCompanyNames));
    w.i64(company + 1);
    w.str(kCompanyNames[company]);
  }
  emit_address(w, r, 20);
  w.dec2(r.raw(31) % 12);  // s_tax_precentage 0.00..0.11
  w.end_row();
}

inline void gen_call_center(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_CALL_CENTER, row);
  w.i64(row + 1);
  w.str(business_id(row / 2 + 1));
  w.date_from_julian(julian_from_civil(1998, 1, 1));
  if (row % 2 == 0) w.null_field();
  else w.date_from_julian(julian_from_civil(2000, 12, 31));
  w.null_field();                                   // cc_closed_date_sk
  w.i64(kSalesFirstSk - r.raw(5) % 1000);           // cc_open_date_sk
  {
    static const char* kCcNames[] = {"NY Metro", "Mid Atlantic", "North Midwest", "California",
                                     "Pacific Northwest", "Hawaii/Alaska"};
    w.str(kCcNames[(row / 2) % 6]);
  }
  w.str(kCcClass[r.raw(7) % len(kCcClass)]);
  w.i64(r.range(8, 1, 7) * 100000);
  w.i64(r.range(9, 1, 25) * 1225);
  w.str(kCcHours[r.raw(10) % len(kCcHours)]);
  w.str(kManagers[r.raw(11) % len(kManagers)]);
  w.i64(r.range(12, 1, 6));
  w.str(kMarketClasses[r.raw(13) % len(kMarketClasses)]);
  w.str(rand_word_text(r, 14, 6, 15));
  w.str(kManagers[r.raw(15) % len(kManagers)]);
  {
    int division = static_cast<int>(r.raw(16) % len(kDivisionNames));
    w.i64(division + 1);
    w.str(kDivisionNames[division]);
  }
  {
    int company = static_cast<int>(r.raw(18) % len(kCompanyNames));
    w.i64(company + 1);
    w.str(kCompanyNames[company]);
  }
  emit_address(w, r, 20);
  w.dec2(r.raw(31) % 12);
  w.end_row();
}

inline void gen_customer(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_CUSTOMER, row);
  static const char* kSalutations[] = {"Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"};
  const char* first = kFirstNames[r.raw(8) % len(kFirstNames)];
  const char* last = kLastNames[r.raw(9) % len(kLastNames)];
  w.i64(row + 1);
  w.str(business_id(row + 1));
  if (r.chance(2, 96)) w.i64(r.range(2, 1, 1920800, 1)); else w.null_field();
  if (r.chance(3, 96)) w.i64(r.range(3, 1, 7200, 1)); else w.null_field();
  w.i64(r.range(4, 1, ctx.n_address));
  {
    int64_t first_sales = kSalesFirstSk + static_cast<int64_t>(r.raw(6) % 1000);
    if (r.chance(5, 96)) w.i64(first_sales + 30); else w.null_field();
    if (r.chance(6, 96)) w.i64(first_sales); else w.null_field();
  }
  if (r.chance(7, 96)) w.str(kSalutations[r.raw(7, 1) % 6]); else w.null_field();
  if (r.chance(8, 96)) w.str(first); else w.null_field();
  if (r.chance(9, 96)) w.str(last); else w.null_field();
  w.str(r.chance(10, 50) ? "Y" : "N");
  w.i64(r.range(11, 1, 28));
  w.i64(r.range(12, 1, 12));
  w.i64(r.range(13, 1924, 1992));
  w.str(kCountry);
  w.null_field();  // c_login is always null in dsdgen output
  {
    char email[64];
    snprintf(email, sizeof(email), "%s.%s@example.com", first, last);
    w.str(email);
  }
  {
    char buf[16];
    snprintf(buf, sizeof(buf), "%lld",
             static_cast<long long>(kSalesLastSk - r.raw(17) % 400));
    w.str(buf);  // c_last_review_date_sk is char(10) in the spec schema
  }
  w.end_row();
}

inline void gen_web_site(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_WEB_SITE, row);
  w.i64(row + 1);
  w.str(business_id(row / 2 + 1));
  w.date_from_julian(julian_from_civil(1997, 8, 16));
  if (row % 2 == 0) w.null_field();
  else w.date_from_julian(julian_from_civil(2000, 8, 15));
  {
    char name[16];
    snprintf(name, sizeof(name), "site_%d", static_cast<int>((row / 2) % 100));
    w.str(name);
  }
  w.i64(kSalesFirstSk - r.raw(5) % 1000);
  w.null_field();  // web_close_date_sk
  w.str("Unknown");
  w.str(kManagers[r.raw(8) % len(kManagers)]);
  w.i64(r.range(9, 1, 6));
  w.str(kMarketClasses[r.raw(10) % len(kMarketClasses)]);
  w.str(rand_word_text(r, 11, 6, 15));
  w.str(kManagers[r.raw(12) % len(kManagers)]);
  {
    int company = static_cast<int>(r.raw(13) % len(kCompanyNames));
    w.i64(company + 1);
    w.str(kCompanyNames[company]);
  }
  emit_address(w, r, 20);
  w.dec2(r.raw(31) % 12);
  w.end_row();
}

inline void gen_household_demographics(RowWriter& w, const Ctx& ctx, int64_t row) {
  (void)ctx;
  // cross product: income_band(20) x buy_potential(6) x dep_count(10) x vehicle(6)
  int64_t ix = row;
  int vehicle = static_cast<int>(ix % 6) - 1;  // -1..4
  ix /= 6;
  int dep = ix % 10; ix /= 10;
  int buy = ix % 6; ix /= 6;
  int band = static_cast<int>(ix % 20) + 1;
  w.i64(row + 1);
  w.i64(band);
  w.str(kBuyPotential[buy]);
  w.i64(dep);
  w.i64(vehicle);
  w.end_row();
}

inline void gen_web_page(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_WEB_PAGE, row);
  static const char* kPageTypes[] = {"ad", "dynamic", "feedback", "general",
                                     "order", "protected", "welcome"};
  w.i64(row + 1);
  w.str(business_id(row / 2 + 1));
  w.date_from_julian(julian_from_civil(1997, 9, 3));
  if (row % 2 == 0) w.null_field();
  else w.date_from_julian(julian_from_civil(2000, 9, 2));
  w.i64(kSalesFirstSk - r.raw(4) % 500);
  w.i64(kSalesFirstSk + r.raw(5) % 500);
  const bool autogen = r.chance(6, 30);
  w.str(autogen ? "Y" : "N");
  if (autogen) w.i64(r.range(7, 1, ctx.n_customer)); else w.null_field();
  {
    char url[32];
    snprintf(url, sizeof(url), "http://www.foo.com");
    w.str(url);
  }
  w.str(kPageTypes[r.raw(9) % len(kPageTypes)]);
  w.i64(r.range(10, 100, 8000));
  w.i64(r.range(11, 2, 25));
  w.i64(r.range(12, 1, 7));
  w.i64(r.range(13, 0, 4));
  w.end_row();
}

inline void gen_promotion(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_PROMOTION, row);
  w.i64(row + 1);
  w.str(business_id(row + 1));
  {
    int64_t start = kSalesFirstSk + r.raw(2) % 1600;
    w.i64(start);
    w.i64(start + r.raw(3) % 60);
  }
  w.i64(r.range(4, 1, ctx.n_item));
  w.dec2(100000);  // p_cost constant 1000.00
  w.i64(1);
  {
    char name[24];
    snprintf(name, sizeof(name), "%s%s", kPromoNames[r.raw(7, 0) % 10],
             kPromoNames[r.raw(7, 1) % 10]);
    w.str(name);
  }
  for (uint32_t c = 8; c < 16; ++c) w.str(r.chance(c, 50) ? "Y" : "N");
  w.str(rand_word_text(r, 16, 4, 12));
  w.str("Unknown");
  w.str(r.chance(18, 50) ? "Y" : "N");
  w.end_row();
}

inline void gen_catalog_page(RowWriter& w, const Ctx& ctx, int64_t row) {
  Rng r(ctx.seed, T_CATALOG_PAGE, row);
  static const char* kCpTypes[] = {"bi-annual", "quarterly", "monthly"};
  // catalogs are issued periodically; ~100 pages per catalog number
  const int64_t catalog_number = row / 100 + 1;
  const int64_t page_number = row % 100 + 1;
  w.i64(row + 1);
  w.str(business_id(row + 1));
  {
    int64_t start = julian_from_civil(1998, 1, 1) + (catalog_number - 1) * 30;
    w.i64(start);
    w.i64(start + 90);
  }
  w.str("DEPARTMENT");
  w.i64(catalog_number);
  w.i64(page_number);
  w.str(rand_word_text(r, 7, 4, 12));
  w.str(kCpTypes[catalog_number % 3]);
  w.end_row();
}

}  // namespace ndsgen
