// Fact-table generators: the three sales channels, their returns, and
// inventory. The generation unit is the ORDER index; rows are (order, line).
// Returns are derived by re-deriving the matching sales line from the same
// counter RNG (no stored state), so a returns chunk only needs the order
// range of the corresponding sales chunk — the property that makes
// distributed generation embarrassingly parallel.
#pragma once

#include <numeric>

#include "dims.hpp"

namespace ndsgen {

// Shared per-line economics. All monetary values are scaled x100 int64.
struct LineVals {
  int64_t item_sk = 0, promo_sk = 0, quantity = 0;
  int64_t wholesale = 0, list = 0, sales = 0;        // per-unit prices
  int64_t ext_discount = 0, ext_sales = 0, ext_wholesale = 0, ext_list = 0;
  int64_t ext_tax = 0, coupon = 0, ext_ship = 0;
  int64_t net_paid = 0, net_paid_inc_tax = 0, net_paid_inc_ship = 0;
  int64_t net_paid_inc_ship_tax = 0, net_profit = 0;
  bool has_promo = false;
};

inline LineVals compute_line(const Ctx& ctx, uint64_t table, int64_t order, int line,
                             bool with_ship) {
  Rng r(ctx.seed, table, order, line + 1);
  LineVals v;
  // Items are distinct within an order (TPC-DS PK: (item_sk, ticket/order
  // number); dsdgen samples per-ticket items without replacement). Stateless
  // equivalent: an order-keyed modular arithmetic progression — returns
  // chunks re-derive the same items from (seed, table, order, line) alone.
  {
    const int64_t half = (ctx.n_item + 1) / 2;  // odd sks = current SCD rows
    Rng ro(ctx.seed, table, order, 0);
    // random start + random stride COPRIME to the domain: (s + l*t) mod H
    // cycles through all H items, so lines are distinct whenever the
    // order has fewer lines than items, and the marginal item
    // distribution stays uniform over the whole domain
    int64_t stride = 1;
    for (uint32_t k = 0; k < 64; ++k) {
      const int64_t t = 1 + static_cast<int64_t>(
          ro.raw(90, k) % static_cast<uint64_t>(half > 1 ? half - 1 : 1));
      if (std::gcd(t, half) == 1) { stride = t; break; }
    }
    const int64_t start = static_cast<int64_t>(ro.raw(91) % static_cast<uint64_t>(half));
    const int64_t idx = (start + stride * line) % half;
    v.item_sk = idx * 2 + 1;
  }
  // dsdgen keeps nullable fact FKs ~96% populated; promo follows suit
  // (a 30% rate here made ss_promo_sk 70% null — spec-shape violation)
  v.has_promo = r.chance(101, 96);
  v.promo_sk = r.range(101, 1, ctx.n_promotion, 1);
  v.quantity = r.range(102, 1, 100);
  v.wholesale = r.dec(103, 1.00, 100.00, 100);
  const double markup = r.unit_f(104) * 2.0;            // 0..200% markup
  v.list = static_cast<int64_t>(v.wholesale * (1.0 + markup));
  const double discount = r.unit_f(105);                // 0..100% off list
  v.sales = static_cast<int64_t>(v.list * (1.0 - discount));
  v.ext_discount = (v.list - v.sales) * v.quantity;
  v.ext_sales = v.sales * v.quantity;
  v.ext_wholesale = v.wholesale * v.quantity;
  v.ext_list = v.list * v.quantity;
  const int tax_pct = static_cast<int>(r.raw(106) % 10);  // 0..9 %
  v.ext_tax = v.ext_sales * tax_pct / 100;
  v.coupon = r.chance(107, 20) ? static_cast<int64_t>(v.ext_sales * r.unit_f(107, 1) * 0.5) : 0;
  v.net_paid = v.ext_sales - v.coupon;
  v.net_paid_inc_tax = v.net_paid + v.ext_tax;
  if (with_ship) {
    const int64_t ship_per_unit = static_cast<int64_t>(v.list * r.unit_f(108) * 0.75);
    v.ext_ship = ship_per_unit * v.quantity;
  }
  v.net_paid_inc_ship = v.net_paid + v.ext_ship;
  v.net_paid_inc_ship_tax = v.net_paid + v.ext_ship + v.ext_tax;
  v.net_profit = v.net_paid - v.ext_wholesale;
  return v;
}

inline int lines_of(const Ctx& ctx, uint64_t table, int64_t order, const Channel& ch) {
  Rng r(ctx.seed, table, order, 0);
  return ch.lines_lo + static_cast<int>(r.raw(0) % (ch.lines_hi - ch.lines_lo + 1));
}

// nullable FK emit: ~4% null rate on nullable fact FKs, dsdgen-style
inline void fk(RowWriter& w, const Rng& r, uint32_t col, int64_t hi) {
  if (r.chance(col, 96))
    w.i64(r.range(col, 1, hi, 1));
  else
    w.null_field();
}

// nullable FK into an SCD-2 dim: only odd (current) sks are referenced
inline void fk_odd(RowWriter& w, const Rng& r, uint32_t col, int64_t hi) {
  if (r.chance(col, 96))
    w.i64(r.range(col, 1, (hi + 1) / 2, 1) * 2 - 1);
  else
    w.null_field();
}

// ---- store channel --------------------------------------------------------

struct StoreOrder {
  int64_t date_sk, time_sk, customer, cdemo, hdemo, addr, store;
  bool d_null, t_null, c_null, cd_null, hd_null, a_null, s_null;
};

inline StoreOrder store_order(const Ctx& ctx, int64_t order) {
  Rng r(ctx.seed, T_STORE_SALES, order, 0);
  StoreOrder o;
  o.date_sk = kSalesFirstSk + static_cast<int64_t>(r.raw(1) % (kSalesLastSk - kSalesFirstSk + 1));
  o.time_sk = 28800 + static_cast<int64_t>(r.raw(2) % (79200 - 28800));  // store hours 8:00-22:00
  o.customer = r.range(3, 1, ctx.n_customer);
  o.cdemo = r.range(4, 1, 1920800);
  o.hdemo = r.range(5, 1, 7200);
  o.addr = r.range(6, 1, ctx.n_address);
  o.store = (r.range(7, 1, (ctx.n_store + 1) / 2)) * 2 - 1;  // odd sks = current SCD rows
  o.d_null = !r.chance(1, 96, 9);
  o.t_null = !r.chance(2, 96, 9);
  o.c_null = !r.chance(3, 96, 9);
  o.cd_null = !r.chance(4, 96, 9);
  o.hd_null = !r.chance(5, 96, 9);
  o.a_null = !r.chance(6, 96, 9);
  o.s_null = !r.chance(7, 96, 9);
  return o;
}

inline void gen_store_sales_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const StoreOrder o = store_order(ctx, order);
  const int nlines = lines_of(ctx, T_STORE_SALES, order, kStore);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_STORE_SALES, order, l, false);
    if (o.d_null) w.null_field(); else w.i64(o.date_sk);
    if (o.t_null) w.null_field(); else w.i64(o.time_sk);
    w.i64(v.item_sk);
    if (o.c_null) w.null_field(); else w.i64(o.customer);
    if (o.cd_null) w.null_field(); else w.i64(o.cdemo);
    if (o.hd_null) w.null_field(); else w.i64(o.hdemo);
    if (o.a_null) w.null_field(); else w.i64(o.addr);
    if (o.s_null) w.null_field(); else w.i64(o.store);
    if (v.has_promo) w.i64(v.promo_sk); else w.null_field();
    w.i64(order + 1);  // ss_ticket_number
    w.i64(v.quantity);
    w.dec2(v.wholesale);
    w.dec2(v.list);
    w.dec2(v.sales);
    w.dec2(v.ext_discount);
    w.dec2(v.ext_sales);
    w.dec2(v.ext_wholesale);
    w.dec2(v.ext_list);
    w.dec2(v.ext_tax);
    w.dec2(v.coupon);
    w.dec2(v.net_paid);
    w.dec2(v.net_paid_inc_tax);
    w.dec2(v.net_profit);
    w.end_row();
  }
}

// Return decision for (channel-table, order, line); ~10% of lines return.
inline bool is_returned(const Ctx& ctx, uint64_t sales_table, int64_t order, int line) {
  Rng r(ctx.seed, sales_table + 100, order, line + 1);
  return r.chance(0, 10);
}

inline void gen_store_returns_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const StoreOrder o = store_order(ctx, order);
  const int nlines = lines_of(ctx, T_STORE_SALES, order, kStore);
  for (int l = 0; l < nlines; ++l) {
    if (!is_returned(ctx, T_STORE_SALES, order, l)) continue;
    const LineVals v = compute_line(ctx, T_STORE_SALES, order, l, false);
    Rng r(ctx.seed, T_STORE_RETURNS, order, l + 1);
    const int64_t ret_date = o.date_sk + 1 + static_cast<int64_t>(r.raw(1) % 90);
    const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
    const int64_t ret_amt = v.sales * rq;
    const int64_t ret_tax = v.ext_tax * rq / v.quantity;
    const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
    const int64_t ship = static_cast<int64_t>(r.raw(4) % 5000);
    // split refund across cash / reversed charge / store credit
    const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
    const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
    const int64_t credit = ret_amt - cash - charge;
    if (o.d_null) w.null_field(); else w.i64(ret_date);
    if (o.t_null) w.null_field(); else w.i64(o.time_sk);
    w.i64(v.item_sk);
    // 10% of returns are made by a different customer than the purchaser
    const bool other = r.chance(7, 10);
    if (o.c_null) w.null_field();
    else w.i64(other ? r.range(7, 1, ctx.n_customer, 1) : o.customer);
    if (o.cd_null) w.null_field(); else w.i64(o.cdemo);
    if (o.hd_null) w.null_field(); else w.i64(o.hdemo);
    if (o.a_null) w.null_field(); else w.i64(o.addr);
    if (o.s_null) w.null_field(); else w.i64(o.store);
    fk(w, r, 8, ctx.n_reason);
    w.i64(order + 1);  // sr_ticket_number
    w.i64(rq);
    w.dec2(ret_amt);
    w.dec2(ret_tax);
    w.dec2(ret_amt + ret_tax);
    w.dec2(fee);
    w.dec2(ship);
    w.dec2(cash);
    w.dec2(charge);
    w.dec2(credit);
    w.dec2(ret_tax + fee + ship);  // sr_net_loss
    w.end_row();
  }
}

// ---- catalog channel ------------------------------------------------------

struct CatalogOrder {
  int64_t date_sk, time_sk, bill_customer, bill_cdemo, bill_hdemo, bill_addr;
  int64_t ship_customer, ship_cdemo, ship_hdemo, ship_addr;
  int64_t call_center, ship_mode;
  bool d_null, cc_null;
};

inline CatalogOrder catalog_order(const Ctx& ctx, int64_t order) {
  Rng r(ctx.seed, T_CATALOG_SALES, order, 0);
  CatalogOrder o;
  o.date_sk = kSalesFirstSk + static_cast<int64_t>(r.raw(1) % (kSalesLastSk - kSalesFirstSk + 1));
  o.time_sk = static_cast<int64_t>(r.raw(2) % 86400);
  o.bill_customer = r.range(3, 1, ctx.n_customer);
  o.bill_cdemo = r.range(4, 1, 1920800);
  o.bill_hdemo = r.range(5, 1, 7200);
  o.bill_addr = r.range(6, 1, ctx.n_address);
  if (r.chance(7, 85)) {  // ship-to == bill-to for 85% of orders
    o.ship_customer = o.bill_customer;
    o.ship_cdemo = o.bill_cdemo;
    o.ship_hdemo = o.bill_hdemo;
    o.ship_addr = o.bill_addr;
  } else {
    o.ship_customer = r.range(8, 1, ctx.n_customer);
    o.ship_cdemo = r.range(9, 1, 1920800);
    o.ship_hdemo = r.range(10, 1, 7200);
    o.ship_addr = r.range(11, 1, ctx.n_address);
  }
  o.call_center = (r.range(12, 1, (ctx.n_call_center + 1) / 2)) * 2 - 1;  // current SCD rows
  o.ship_mode = r.range(13, 1, 20);
  o.d_null = !r.chance(1, 96, 9);
  o.cc_null = !r.chance(12, 96, 9);
  return o;
}

inline void gen_catalog_sales_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const CatalogOrder o = catalog_order(ctx, order);
  const int nlines = lines_of(ctx, T_CATALOG_SALES, order, kCatalog);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_CATALOG_SALES, order, l, true);
    Rng r(ctx.seed, T_CATALOG_SALES, order, l + 1);
    if (o.d_null) w.null_field(); else w.i64(o.date_sk);
    w.i64(o.time_sk);
    w.i64(o.date_sk + 2 + static_cast<int64_t>(r.raw(120) % 90));  // cs_ship_date_sk
    w.i64(o.bill_customer);
    w.i64(o.bill_cdemo);
    w.i64(o.bill_hdemo);
    w.i64(o.bill_addr);
    w.i64(o.ship_customer);
    w.i64(o.ship_cdemo);
    w.i64(o.ship_hdemo);
    w.i64(o.ship_addr);
    if (o.cc_null) w.null_field(); else w.i64(o.call_center);
    fk(w, r, 121, ctx.n_catalog_page);
    w.i64(o.ship_mode);
    w.i64(r.range(122, 1, ctx.n_warehouse));
    w.i64(v.item_sk);
    if (v.has_promo) w.i64(v.promo_sk); else w.null_field();
    w.i64(order + 1);  // cs_order_number
    w.i64(v.quantity);
    w.dec2(v.wholesale);
    w.dec2(v.list);
    w.dec2(v.sales);
    w.dec2(v.ext_discount);
    w.dec2(v.ext_sales);
    w.dec2(v.ext_wholesale);
    w.dec2(v.ext_list);
    w.dec2(v.ext_tax);
    w.dec2(v.coupon);
    w.dec2(v.ext_ship);
    w.dec2(v.net_paid);
    w.dec2(v.net_paid_inc_tax);
    w.dec2(v.net_paid_inc_ship);
    w.dec2(v.net_paid_inc_ship_tax);
    w.dec2(v.net_profit);
    w.end_row();
  }
}

inline void gen_catalog_returns_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const CatalogOrder o = catalog_order(ctx, order);
  const int nlines = lines_of(ctx, T_CATALOG_SALES, order, kCatalog);
  for (int l = 0; l < nlines; ++l) {
    if (!is_returned(ctx, T_CATALOG_SALES, order, l)) continue;
    const LineVals v = compute_line(ctx, T_CATALOG_SALES, order, l, true);
    Rng r(ctx.seed, T_CATALOG_RETURNS, order, l + 1);
    const int64_t ret_date = o.date_sk + 3 + static_cast<int64_t>(r.raw(1) % 90);
    const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
    const int64_t ret_amt = v.sales * rq;
    const int64_t ret_tax = v.ext_tax * rq / v.quantity;
    const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
    const int64_t ship = v.ext_ship * rq / v.quantity;
    const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
    const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
    const int64_t credit = ret_amt - cash - charge;
    const bool other = r.chance(7, 10);
    const int64_t ret_cust = other ? r.range(7, 1, ctx.n_customer, 1) : o.ship_customer;
    w.i64(ret_date);
    w.i64(o.time_sk);
    w.i64(v.item_sk);
    w.i64(o.bill_customer);
    w.i64(o.bill_cdemo);
    w.i64(o.bill_hdemo);
    w.i64(o.bill_addr);
    w.i64(ret_cust);
    w.i64(o.ship_cdemo);
    w.i64(o.ship_hdemo);
    w.i64(o.ship_addr);
    if (o.cc_null) w.null_field(); else w.i64(o.call_center);
    fk(w, r, 8, ctx.n_catalog_page);
    w.i64(o.ship_mode);
    w.i64(r.range(9, 1, ctx.n_warehouse));
    fk(w, r, 10, ctx.n_reason);
    w.i64(order + 1);
    w.i64(rq);
    w.dec2(ret_amt);
    w.dec2(ret_tax);
    w.dec2(ret_amt + ret_tax);
    w.dec2(fee);
    w.dec2(ship);
    w.dec2(cash);
    w.dec2(charge);
    w.dec2(credit);
    w.dec2(ret_tax + fee + ship);
    w.end_row();
  }
}

// ---- web channel ----------------------------------------------------------

struct WebOrder {
  int64_t date_sk, time_sk, bill_customer, bill_cdemo, bill_hdemo, bill_addr;
  int64_t ship_customer, ship_cdemo, ship_hdemo, ship_addr;
  int64_t web_site, ship_mode;
  bool d_null;
};

inline WebOrder web_order(const Ctx& ctx, int64_t order) {
  Rng r(ctx.seed, T_WEB_SALES, order, 0);
  WebOrder o;
  o.date_sk = kSalesFirstSk + static_cast<int64_t>(r.raw(1) % (kSalesLastSk - kSalesFirstSk + 1));
  o.time_sk = static_cast<int64_t>(r.raw(2) % 86400);
  o.bill_customer = r.range(3, 1, ctx.n_customer);
  o.bill_cdemo = r.range(4, 1, 1920800);
  o.bill_hdemo = r.range(5, 1, 7200);
  o.bill_addr = r.range(6, 1, ctx.n_address);
  if (r.chance(7, 85)) {
    o.ship_customer = o.bill_customer;
    o.ship_cdemo = o.bill_cdemo;
    o.ship_hdemo = o.bill_hdemo;
    o.ship_addr = o.bill_addr;
  } else {
    o.ship_customer = r.range(8, 1, ctx.n_customer);
    o.ship_cdemo = r.range(9, 1, 1920800);
    o.ship_hdemo = r.range(10, 1, 7200);
    o.ship_addr = r.range(11, 1, ctx.n_address);
  }
  o.web_site = (r.range(12, 1, (ctx.n_web_site + 1) / 2)) * 2 - 1;
  o.ship_mode = r.range(13, 1, 20);
  o.d_null = !r.chance(1, 96, 9);
  return o;
}

inline void gen_web_sales_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const WebOrder o = web_order(ctx, order);
  const int nlines = lines_of(ctx, T_WEB_SALES, order, kWeb);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_WEB_SALES, order, l, true);
    Rng r(ctx.seed, T_WEB_SALES, order, l + 1);
    if (o.d_null) w.null_field(); else w.i64(o.date_sk);
    w.i64(o.time_sk);
    w.i64(o.date_sk + 1 + static_cast<int64_t>(r.raw(120) % 120));  // ws_ship_date_sk
    w.i64(v.item_sk);
    w.i64(o.bill_customer);
    w.i64(o.bill_cdemo);
    w.i64(o.bill_hdemo);
    w.i64(o.bill_addr);
    w.i64(o.ship_customer);
    w.i64(o.ship_cdemo);
    w.i64(o.ship_hdemo);
    w.i64(o.ship_addr);
    fk_odd(w, r, 121, ctx.n_web_page);
    w.i64(o.web_site);
    w.i64(o.ship_mode);
    w.i64(r.range(122, 1, ctx.n_warehouse));
    if (v.has_promo) w.i64(v.promo_sk); else w.null_field();
    w.i64(order + 1);  // ws_order_number
    w.i64(v.quantity);
    w.dec2(v.wholesale);
    w.dec2(v.list);
    w.dec2(v.sales);
    w.dec2(v.ext_discount);
    w.dec2(v.ext_sales);
    w.dec2(v.ext_wholesale);
    w.dec2(v.ext_list);
    w.dec2(v.ext_tax);
    w.dec2(v.coupon);
    w.dec2(v.ext_ship);
    w.dec2(v.net_paid);
    w.dec2(v.net_paid_inc_tax);
    w.dec2(v.net_paid_inc_ship);
    w.dec2(v.net_paid_inc_ship_tax);
    w.dec2(v.net_profit);
    w.end_row();
  }
}

inline void gen_web_returns_order(RowWriter& w, const Ctx& ctx, int64_t order) {
  const WebOrder o = web_order(ctx, order);
  const int nlines = lines_of(ctx, T_WEB_SALES, order, kWeb);
  for (int l = 0; l < nlines; ++l) {
    if (!is_returned(ctx, T_WEB_SALES, order, l)) continue;
    const LineVals v = compute_line(ctx, T_WEB_SALES, order, l, true);
    Rng r(ctx.seed, T_WEB_RETURNS, order, l + 1);
    const int64_t ret_date = o.date_sk + 1 + static_cast<int64_t>(r.raw(1) % 120);
    const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
    const int64_t ret_amt = v.sales * rq;
    const int64_t ret_tax = v.ext_tax * rq / v.quantity;
    const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
    const int64_t ship = v.ext_ship * rq / v.quantity;
    const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
    const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
    const int64_t credit = ret_amt - cash - charge;
    const bool other = r.chance(7, 10);
    const int64_t ret_cust = other ? r.range(7, 1, ctx.n_customer, 1) : o.ship_customer;
    w.i64(ret_date);
    w.i64(o.time_sk);
    w.i64(v.item_sk);
    w.i64(o.bill_customer);
    w.i64(o.bill_cdemo);
    w.i64(o.bill_hdemo);
    w.i64(o.bill_addr);
    w.i64(ret_cust);
    w.i64(o.ship_cdemo);
    w.i64(o.ship_hdemo);
    w.i64(o.ship_addr);
    fk_odd(w, r, 8, ctx.n_web_page);
    fk(w, r, 10, ctx.n_reason);
    w.i64(order + 1);
    w.i64(rq);
    w.dec2(ret_amt);
    w.dec2(ret_tax);
    w.dec2(ret_amt + ret_tax);
    w.dec2(fee);
    w.dec2(ship);
    w.dec2(cash);
    w.dec2(charge);
    w.dec2(credit);
    w.dec2(ret_tax + fee + ship);
    w.end_row();
  }
}

// ---- inventory ------------------------------------------------------------
// Full cross product: weekly snapshot x (items with odd sk) x warehouses.
inline void gen_inventory(RowWriter& w, const Ctx& ctx, int64_t row) {
  const int64_t n_items = ctx.n_inv_items;
  const int64_t nw = ctx.n_warehouse;
  const int64_t week = row / (n_items * nw);
  const int64_t rem = row % (n_items * nw);
  const int64_t item_ix = rem / nw;
  const int64_t wh = rem % nw;
  Rng r(ctx.seed, T_INVENTORY, row);
  w.i64(kSalesFirstSk + week * 7);
  w.i64(item_ix * 2 + 1);
  w.i64(wh + 1);
  if (r.chance(3, 96))
    w.i64(r.raw(3, 1) % 1000);
  else
    w.null_field();
  w.end_row();
}

}  // namespace ndsgen
