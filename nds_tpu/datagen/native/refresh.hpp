// Refresh-set (--update) generators: the s_* staging tables consumed by the
// Data Maintenance phase (LF_* inserts join these to dims; DF_* deletes use
// the delete/inventory_delete date ranges). Insert orders get ids beyond the
// base order range so LF inserts add genuinely new tickets; return staging
// rows re-derive base sales lines for referential integrity.
#pragma once

#include "facts.hpp"

namespace ndsgen {

inline std::string date_str(int64_t jd) {
  int y; unsigned m, d;
  civil_from_days(jd - kJulianOfEpoch, &y, &m, &d);
  char tmp[16];
  snprintf(tmp, sizeof(tmp), "%04d-%02u-%02u", y, m, d);
  return tmp;
}

inline int64_t refresh_orders(const Channel& ch, double sf) {
  return std::max<int64_t>(1, channel_orders(ch, sf) / 1000);
}

// Refresh date window for update set u: a 30-day slice after the base window.
inline int64_t refresh_date(const Ctx& ctx, uint64_t table, int update, int64_t unit) {
  Rng r(ctx.seed, table, unit, 777);
  return kSalesLastSk + 1 + static_cast<int64_t>(update - 1) * 30 + r.raw(0) % 30;
}

// ---- insert staging: store channel ---------------------------------------

inline void gen_s_purchase(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kStore, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kStore, ctx.sf) + j;
  const StoreOrder o = store_order(ctx, order);
  Rng r(ctx.seed, T_S_PURCHASE, order);
  w.i64(order + 1);
  w.str(business_id((o.store + 1) / 2));      // store business id of SCD pair
  w.str(business_id(o.customer));
  w.str(date_str(refresh_date(ctx, T_S_PURCHASE, update, order)));
  w.i64(o.time_sk);
  w.i64(r.range(1, 1, 17));
  w.i64(r.range(2, 1, 1000));
  w.str(rand_word_text(r, 3, 4, 12));
  w.end_row();
}

inline void gen_s_purchase_lineitem(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kStore, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kStore, ctx.sf) + j;
  const int nlines = lines_of(ctx, T_STORE_SALES, order, kStore);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_STORE_SALES, order, l, false);
    Rng r(ctx.seed, T_S_PURCHASE, order, l + 1);
    w.i64(order + 1);
    w.i64(l + 1);
    w.str(business_id(v.item_sk));
    if (v.has_promo) w.str(business_id(v.promo_sk)); else w.null_field();
    w.i64(v.quantity);
    w.dec2(v.sales);
    w.dec2(v.coupon);
    w.str(rand_word_text(r, 1, 4, 12));
    w.end_row();
  }
}

// ---- insert staging: catalog channel --------------------------------------

inline void gen_s_catalog_order(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kCatalog, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kCatalog, ctx.sf) + j;
  const CatalogOrder o = catalog_order(ctx, order);
  Rng r(ctx.seed, T_S_CATALOG_ORDER, order);
  w.i64(order + 1);
  w.str(business_id(o.bill_customer));
  w.str(business_id(o.ship_customer));
  w.str(date_str(refresh_date(ctx, T_S_CATALOG_ORDER, update, order)));
  w.i64(o.time_sk);
  w.str(business_id(o.ship_mode));
  w.str(business_id((o.call_center + 1) / 2));
  w.str(rand_word_text(r, 1, 4, 12));
  w.end_row();
}

inline void gen_s_catalog_order_lineitem(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kCatalog, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kCatalog, ctx.sf) + j;
  const int nlines = lines_of(ctx, T_CATALOG_SALES, order, kCatalog);
  const int64_t odate = refresh_date(ctx, T_S_CATALOG_ORDER, update, order);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_CATALOG_SALES, order, l, true);
    Rng r(ctx.seed, T_S_CATALOG_ORDER, order, l + 1);
    w.i64(order + 1);
    w.i64(l + 1);
    w.str(business_id(v.item_sk));
    if (v.has_promo) w.str(business_id(v.promo_sk)); else w.null_field();
    w.i64(v.quantity);
    w.dec2(v.sales);
    w.dec2(v.coupon);
    w.str(business_id(r.range(1, 1, ctx.n_warehouse)));
    w.str(date_str(odate + 2 + r.raw(2) % 90));
    {
      const int64_t page = r.range(3, 1, ctx.n_catalog_page);
      w.i64(page / 100 + 1);   // catalog number
      w.i64(page % 100 + 1);   // page within catalog
    }
    w.dec2(v.ext_ship / v.quantity);
    w.end_row();
  }
}

// ---- insert staging: web channel ------------------------------------------

inline void gen_s_web_order(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kWeb, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kWeb, ctx.sf) + j;
  const WebOrder o = web_order(ctx, order);
  Rng r(ctx.seed, T_S_WEB_ORDER, order);
  w.i64(order + 1);
  w.str(business_id(o.bill_customer));
  w.str(business_id(o.ship_customer));
  w.str(date_str(refresh_date(ctx, T_S_WEB_ORDER, update, order)));
  w.i64(o.time_sk);
  w.str(business_id(o.ship_mode));
  w.str(business_id((o.web_site + 1) / 2));
  w.str(rand_word_text(r, 1, 4, 12));
  w.end_row();
}

inline void gen_s_web_order_lineitem(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t base = channel_orders(kWeb, ctx.sf);
  const int64_t order = base + (update - 1) * refresh_orders(kWeb, ctx.sf) + j;
  const int nlines = lines_of(ctx, T_WEB_SALES, order, kWeb);
  const int64_t odate = refresh_date(ctx, T_S_WEB_ORDER, update, order);
  for (int l = 0; l < nlines; ++l) {
    const LineVals v = compute_line(ctx, T_WEB_SALES, order, l, true);
    Rng r(ctx.seed, T_S_WEB_ORDER, order, l + 1);
    w.i64(order + 1);
    w.i64(l + 1);
    w.str(business_id(v.item_sk));
    if (v.has_promo) w.str(business_id(v.promo_sk)); else w.null_field();
    w.i64(v.quantity);
    w.dec2(v.sales);
    w.dec2(v.coupon);
    w.str(business_id(r.range(1, 1, ctx.n_warehouse)));
    w.str(date_str(odate + 1 + r.raw(2) % 120));
    w.dec2(v.ext_ship / v.quantity);
    w.str(business_id(r.range(3, 1, (ctx.n_web_page + 1) / 2)));
    w.end_row();
  }
}

// ---- return staging -------------------------------------------------------
// Each update returns lines from a pseudo-random sample of BASE orders.

// Format-preserving permutation of [0, n): 4-round Feistel on the smallest
// even bit-width covering n, cycle-walked back into range. Collision-free by
// construction, so two sample indices can never map to the same base order
// (a plain hash-mod here emitted byte-identical duplicate return rows).
inline int64_t permute_into(uint64_t key, uint64_t j, uint64_t n) {
  int k = 2;
  while ((uint64_t(1) << k) < n) k += 2;
  const int h = k / 2;
  const uint64_t half_mask = (uint64_t(1) << h) - 1;
  uint64_t x = j;
  do {
    for (int rd = 0; rd < 4; ++rd) {
      const uint64_t L = x >> h, R = x & half_mask;
      const uint64_t f = mix64(R ^ key ^ (uint64_t(rd) << 56)) & half_mask;
      x = (R << h) | (L ^ f);
    }
  } while (x >= n);
  return static_cast<int64_t>(x);
}

inline int64_t sampled_base_order(const Ctx& ctx, const Channel& ch, uint64_t table,
                                  int update, int64_t j) {
  const uint64_t key = mix64(ctx.seed ^ (table << 40) ^ update);
  return permute_into(key, static_cast<uint64_t>(j),
                      static_cast<uint64_t>(channel_orders(ch, ctx.sf)));
}

inline void gen_s_store_returns(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t order = sampled_base_order(ctx, kStore, T_STORE_RETURNS, update, j);
  const StoreOrder o = store_order(ctx, order);
  const int nlines = lines_of(ctx, T_STORE_SALES, order, kStore);
  const int l = static_cast<int>(j % nlines);
  const LineVals v = compute_line(ctx, T_STORE_SALES, order, l, false);
  Rng r(ctx.seed, T_STORE_RETURNS + 50, order, l + 1);
  const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
  const int64_t ret_amt = v.sales * rq;
  const int64_t ret_tax = v.ext_tax * rq / v.quantity;
  const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
  const int64_t ship = static_cast<int64_t>(r.raw(4) % 5000);
  const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
  const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
  const int64_t credit = ret_amt - cash - charge;
  const int64_t rdate = kSalesLastSk + 1 + (update - 1) * 30 + r.raw(7) % 30;
  w.str(business_id((o.store + 1) / 2));
  w.str(business_id(order + 1));
  w.i64(l + 1);
  w.str(business_id(v.item_sk));
  w.str(business_id(o.customer));
  w.str(date_str(rdate));
  {
    char t[12];
    int64_t sec = o.time_sk;
    snprintf(t, sizeof(t), "%02d:%02d:%02d", static_cast<int>(sec / 3600),
             static_cast<int>((sec / 60) % 60), static_cast<int>(sec % 60));
    w.str(t);
  }
  w.i64(order + 1);
  w.i64(rq);
  w.dec2(ret_amt);
  w.dec2(ret_tax);
  w.dec2(fee);
  w.dec2(ship);
  w.dec2(cash);
  w.dec2(charge);
  w.dec2(credit);
  w.str(business_id(1 + r.raw(8) % ctx.n_reason));
  w.end_row();
}

inline void gen_s_catalog_returns(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t order = sampled_base_order(ctx, kCatalog, T_CATALOG_RETURNS, update, j);
  const CatalogOrder o = catalog_order(ctx, order);
  const int nlines = lines_of(ctx, T_CATALOG_SALES, order, kCatalog);
  const int l = static_cast<int>(j % nlines);
  const LineVals v = compute_line(ctx, T_CATALOG_SALES, order, l, true);
  Rng r(ctx.seed, T_CATALOG_RETURNS + 50, order, l + 1);
  const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
  const int64_t ret_amt = v.sales * rq;
  const int64_t ret_tax = v.ext_tax * rq / v.quantity;
  const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
  const int64_t ship = v.ext_ship * rq / v.quantity;
  const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
  const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
  const int64_t credit = ret_amt - cash - charge;
  const int64_t rdate = kSalesLastSk + 1 + (update - 1) * 30 + r.raw(7) % 30;
  w.str(business_id((o.call_center + 1) / 2));
  w.i64(order + 1);
  w.i64(l + 1);
  w.str(business_id(v.item_sk));
  w.str(business_id(o.ship_customer));
  w.str(business_id(o.bill_customer));
  w.str(date_str(rdate));
  {
    char t[12];
    snprintf(t, sizeof(t), "%02d:%02d:%02d", static_cast<int>(o.time_sk / 3600),
             static_cast<int>((o.time_sk / 60) % 60), static_cast<int>(o.time_sk % 60));
    w.str(t);
  }
  w.i64(rq);
  w.dec2(ret_amt);
  w.dec2(ret_tax);
  w.dec2(fee);
  w.dec2(ship);
  w.dec2(cash);
  w.dec2(charge);
  w.dec2(credit);
  w.str(business_id(1 + r.raw(8) % ctx.n_reason));
  w.str(business_id(o.ship_mode));
  w.str(business_id(1 + r.raw(9) % ctx.n_catalog_page));
  w.str(business_id(1 + r.raw(10) % ctx.n_warehouse));
  w.end_row();
}

inline void gen_s_web_returns(RowWriter& w, const Ctx& ctx, int update, int64_t j) {
  const int64_t order = sampled_base_order(ctx, kWeb, T_WEB_RETURNS, update, j);
  const WebOrder o = web_order(ctx, order);
  const int nlines = lines_of(ctx, T_WEB_SALES, order, kWeb);
  const int l = static_cast<int>(j % nlines);
  const LineVals v = compute_line(ctx, T_WEB_SALES, order, l, true);
  Rng r(ctx.seed, T_WEB_RETURNS + 50, order, l + 1);
  const int64_t rq = 1 + static_cast<int64_t>(r.raw(2) % v.quantity);
  const int64_t ret_amt = v.sales * rq;
  const int64_t ret_tax = v.ext_tax * rq / v.quantity;
  const int64_t fee = 50 + static_cast<int64_t>(r.raw(3) % 9950);
  const int64_t ship = v.ext_ship * rq / v.quantity;
  const int64_t cash = static_cast<int64_t>(ret_amt * r.unit_f(5));
  const int64_t charge = static_cast<int64_t>((ret_amt - cash) * r.unit_f(6));
  const int64_t credit = ret_amt - cash - charge;
  const int64_t rdate = kSalesLastSk + 1 + (update - 1) * 30 + r.raw(7) % 30;
  w.str(business_id(1 + r.raw(9) % std::max<int64_t>(1, (ctx.n_web_page + 1) / 2)));
  w.i64(order + 1);
  w.i64(l + 1);
  w.str(business_id(v.item_sk));
  w.str(business_id(o.ship_customer));
  w.str(business_id(o.bill_customer));
  w.str(date_str(rdate));
  {
    char t[12];
    snprintf(t, sizeof(t), "%02d:%02d:%02d", static_cast<int>(o.time_sk / 3600),
             static_cast<int>((o.time_sk / 60) % 60), static_cast<int>(o.time_sk % 60));
    w.str(t);
  }
  w.i64(rq);
  w.dec2(ret_amt);
  w.dec2(ret_tax);
  w.dec2(fee);
  w.dec2(ship);
  w.dec2(cash);
  w.dec2(charge);
  w.dec2(credit);
  w.str(business_id(1 + r.raw(8) % ctx.n_reason));
  w.end_row();
}

// ---- inventory + delete staging -------------------------------------------

inline void gen_s_inventory(RowWriter& w, const Ctx& ctx, int update, int64_t row) {
  const int64_t nw = ctx.n_warehouse;
  const int64_t item_ix = row / nw;
  const int64_t wh = row % nw;
  Rng r(ctx.seed, T_S_INVENTORY, row ^ (static_cast<uint64_t>(update) << 40));
  w.str(business_id(wh + 1));
  w.str(business_id(item_ix * 2 + 1));
  w.str(date_str(kSalesFirstSk + (kInventoryWeeks + update - 1) * 7));
  w.i64(r.raw(1) % 1000);
  w.end_row();
}

// 3 date-range tuples per update set (the reference's maintenance driver
// substitutes DATE1/DATE2 three times per DF function:
// reference nds/nds_maintenance.py:75-96).
inline void gen_delete_range(RowWriter& w, int update, int64_t k, bool inventory) {
  const int64_t span = inventory ? 21 : 30;
  const int64_t start = kSalesFirstSk + ((update - 1) * 3 + k) * 60 + (inventory ? 7 : 0);
  w.str(date_str(start));
  w.str(date_str(start + span));
  w.end_row();
}

}  // namespace ndsgen
