// ndsgen CLI: chunked TPC-DS-shaped data generation.
//
//   ndsgen -scale SF -dir DIR [-parallel N -child I] [-table T] [-update U]
//          [-seed S] [-counts]
//
// Emits {table}_{child}_{parallel}.dat pipe-delimited files into DIR
// (dsdgen's naming convention, which the Python driver relies on when
// assembling per-table directories; reference: nds/nds_gen_data.py:234-242).
// With -update U it emits the refresh-set staging tables instead.

#include <cstdlib>
#include <string>
#include <vector>

#include "refresh.hpp"

namespace ndsgen {

using RowGen = void (*)(RowWriter&, const Ctx&, int64_t);
using UpdateGen = void (*)(RowWriter&, const Ctx&, int, int64_t);

struct TableDef {
  const char* name;
  RowGen gen;              // per-unit generator; unit is a row, or an order
                           // (multi-row) when channel != nullptr
  const Channel* channel;
};

int64_t unit_count(const TableDef& t, const Ctx& ctx) {
  if (t.channel) return channel_orders(*t.channel, ctx.sf);
  if (std::string(t.name) == "inventory")
    return kInventoryWeeks * ctx.n_inv_items * ctx.n_warehouse;
  return dim_rows(t.name, ctx.sf);
}

static const TableDef kTables[] = {
    {"call_center", gen_call_center, nullptr},
    {"catalog_page", gen_catalog_page, nullptr},
    {"catalog_returns", gen_catalog_returns_order, &kCatalog},
    {"catalog_sales", gen_catalog_sales_order, &kCatalog},
    {"customer", gen_customer, nullptr},
    {"customer_address", gen_customer_address, nullptr},
    {"customer_demographics", gen_customer_demographics, nullptr},
    {"date_dim", gen_date_dim, nullptr},
    {"household_demographics", gen_household_demographics, nullptr},
    {"income_band", gen_income_band, nullptr},
    {"inventory", gen_inventory, nullptr},
    {"item", gen_item, nullptr},
    {"promotion", gen_promotion, nullptr},
    {"reason", gen_reason, nullptr},
    {"ship_mode", gen_ship_mode, nullptr},
    {"store", gen_store, nullptr},
    {"store_returns", gen_store_returns_order, &kStore},
    {"store_sales", gen_store_sales_order, &kStore},
    {"time_dim", gen_time_dim, nullptr},
    {"warehouse", gen_warehouse, nullptr},
    {"web_page", gen_web_page, nullptr},
    {"web_returns", gen_web_returns_order, &kWeb},
    {"web_sales", gen_web_sales_order, &kWeb},
    {"web_site", gen_web_site, nullptr},
};

struct UpdateDef {
  const char* name;
  UpdateGen gen;
  int which;  // 0: store-orders count, 1: catalog, 2: web, 3: inventory-week, 4: delete
};

static const UpdateDef kUpdateTables[] = {
    {"s_purchase", gen_s_purchase, 0},
    {"s_purchase_lineitem", gen_s_purchase_lineitem, 0},
    {"s_catalog_order", gen_s_catalog_order, 1},
    {"s_catalog_order_lineitem", gen_s_catalog_order_lineitem, 1},
    {"s_web_order", gen_s_web_order, 2},
    {"s_web_order_lineitem", gen_s_web_order_lineitem, 2},
    {"s_store_returns", gen_s_store_returns, 0},
    {"s_catalog_returns", gen_s_catalog_returns, 1},
    {"s_web_returns", gen_s_web_returns, 2},
    {"s_inventory", gen_s_inventory, 3},
};

int64_t update_unit_count(const UpdateDef& t, const Ctx& ctx) {
  switch (t.which) {
    case 0: return refresh_orders(kStore, ctx.sf);
    case 1: return refresh_orders(kCatalog, ctx.sf);
    case 2: return refresh_orders(kWeb, ctx.sf);
    case 3: return inventory_items(ctx.sf) * ctx.n_warehouse;
  }
  return 0;
}

struct Args {
  double scale = 1.0;
  int parallel = 1;
  int child = 1;
  int update = 0;
  uint64_t seed = 19620718;
  std::string dir = ".";
  std::string table;
  bool counts_only = false;
};

FILE* open_chunk(const Args& a, const std::string& table) {
  std::string path = a.dir + "/" + table + "_" + std::to_string(a.child) + "_" +
                     std::to_string(a.parallel) + ".dat";
  FILE* f = fopen(path.c_str(), "w");
  if (!f) {
    fprintf(stderr, "ndsgen: cannot open %s\n", path.c_str());
    exit(2);
  }
  return f;
}

// chunk [child-1] of [parallel] over n units
void chunk_bounds(int64_t n, int parallel, int child, int64_t* lo, int64_t* hi) {
  *lo = n * (child - 1) / parallel;
  *hi = n * child / parallel;
}

bool known_table(const std::string& name, bool update) {
  if (name.empty()) return true;
  if (update) {
    if (name == "delete" || name == "inventory_delete") return true;
    for (const auto& t : kUpdateTables)
      if (name == t.name) return true;
    return false;
  }
  for (const auto& t : kTables)
    if (name == t.name) return true;
  return false;
}

int run(const Args& a) {
  Ctx ctx(a.scale, a.seed);
  if (!known_table(a.table, a.update > 0)) {
    fprintf(stderr, "ndsgen: unknown table %s%s\n", a.table.c_str(),
            a.update > 0 ? " (update mode generates s_* staging tables)" : "");
    return 2;
  }
  if (a.counts_only) {
    for (const auto& t : kTables) {
      int64_t units = unit_count(t, ctx);
      printf("%s %lld %s\n", t.name, static_cast<long long>(units),
             t.channel ? "orders" : "rows");
    }
    return 0;
  }
  if (a.update > 0) {
    for (const auto& t : kUpdateTables) {
      if (!a.table.empty() && a.table != t.name) continue;
      int64_t lo, hi;
      chunk_bounds(update_unit_count(t, ctx), a.parallel, a.child, &lo, &hi);
      FILE* f = open_chunk(a, t.name);
      {
        RowWriter w(f);
        for (int64_t u = lo; u < hi; ++u) t.gen(w, ctx, a.update, u);
      }
      fclose(f);
    }
    // delete-date tables: chunk 1 only (3 tuples each)
    if (a.child == 1 && (a.table.empty() || a.table == "delete" || a.table == "inventory_delete")) {
      for (const char* name : {"delete", "inventory_delete"}) {
        if (!a.table.empty() && a.table != name) continue;
        FILE* f = open_chunk(a, name);
        {
          RowWriter w(f);
          for (int k = 0; k < 3; ++k)
            gen_delete_range(w, a.update, k, std::string(name) == "inventory_delete");
        }
        fclose(f);
      }
    }
    return 0;
  }
  for (const auto& t : kTables) {
    if (!a.table.empty() && a.table != t.name) continue;
    int64_t lo, hi;
    chunk_bounds(unit_count(t, ctx), a.parallel, a.child, &lo, &hi);
    if (lo >= hi && a.table.empty()) continue;  // tiny dims: child >1 may own nothing
    FILE* f = open_chunk(a, t.name);
    {
      RowWriter w(f);
      for (int64_t u = lo; u < hi; ++u) t.gen(w, ctx, u);
    }
    fclose(f);
  }
  return 0;
}

}  // namespace ndsgen

int main(int argc, char** argv) {
  ndsgen::Args a;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "ndsgen: missing value for %s\n", arg.c_str());
        exit(2);
      }
      return argv[++i];
    };
    if (arg == "-scale") a.scale = atof(next());
    else if (arg == "-parallel") a.parallel = atoi(next());
    else if (arg == "-child") a.child = atoi(next());
    else if (arg == "-update") a.update = atoi(next());
    else if (arg == "-seed") a.seed = strtoull(next(), nullptr, 10);
    else if (arg == "-dir") a.dir = next();
    else if (arg == "-table") a.table = next();
    else if (arg == "-counts") a.counts_only = true;
    else {
      fprintf(stderr,
              "usage: ndsgen -scale SF -dir DIR [-parallel N -child I] [-table T]"
              " [-update U] [-seed S] [-counts]\n");
      return 2;
    }
  }
  if (a.child < 1 || a.child > a.parallel) {
    fprintf(stderr, "ndsgen: need 1 <= child <= parallel\n");
    return 2;
  }
  return ndsgen::run(a);
}
