"""Self-building native generator.

The reference requires a manual `make` against an externally-downloaded
toolkit (reference: nds/tpcds-gen/Makefile:14-22, checked by nds/check.py:47-66);
we instead vendor the generator source and compile it on first use, caching
the binary next to the sources.
"""

from __future__ import annotations

import os
import subprocess

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
BINARY = os.path.join(NATIVE_DIR, "ndsgen")
_SOURCES = ["ndsgen.cpp"]
_HEADERS = ["ndsgen.hpp", "vocab.hpp", "rowcounts.hpp", "dims.hpp", "facts.hpp", "refresh.hpp"]


def _stale() -> bool:
    if not os.path.exists(BINARY):
        return True
    bin_mtime = os.path.getmtime(BINARY)
    for f in _SOURCES + _HEADERS:
        if os.path.getmtime(os.path.join(NATIVE_DIR, f)) > bin_mtime:
            return True
    return False


def ensure_built() -> str:
    """Compile ndsgen if missing or out of date; returns the binary path.

    Compiles to a process-unique temp path and os.replace()s it in, so
    concurrent builders can't truncate a binary another process is executing.
    """
    if _stale():
        tmp = f"{BINARY}.build.{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-o", tmp] + [
            os.path.join(NATIVE_DIR, s) for s in _SOURCES
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"ndsgen build failed:\n{proc.stderr}")
        os.replace(tmp, BINARY)
    return BINARY
