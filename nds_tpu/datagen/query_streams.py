"""Query-stream generation: the dsqgen equivalent.

Substitutes seeded random parameters into the query templates under
templates/ and emits permuted query streams `query_0.sql .. query_N.sql`
(reference: nds/nds_gen_query_stream.py:42-89 forks `dsqgen -dialect spark`;
the template patch mechanism is nds/tpcds-gen/patches/templates.patch).

Stream-file format parity: every query is wrapped in
  -- start query N in stream S using template queryK.tpl
  <sql>;
  -- end query N in stream S using template queryK.tpl
which is what the Power Run driver splits on (reference: nds/nds_power.py:50-77).
"""

from __future__ import annotations

import os
import re

import numpy as np

from .substitutions import PARAM_GENERATORS

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "templates")

_PARAM_RE = re.compile(r"\[([A-Z][A-Z0-9_.]*)\]")


def available_templates(template_dir=None):
    d = template_dir or TEMPLATE_DIR
    out = []
    for f in sorted(os.listdir(d)):
        m = re.match(r"query(\d+)\.tpl$", f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def load_template(qnum, template_dir=None):
    d = template_dir or TEMPLATE_DIR
    with open(os.path.join(d, f"query{qnum}.tpl")) as f:
        return f.read()


def instantiate(qnum, rng, scale, template_dir=None) -> str:
    """Fill one template's parameters from the seeded rng."""
    text = load_template(qnum, template_dir)
    gen = PARAM_GENERATORS.get(qnum)
    params = gen(rng, scale) if gen else {}
    missing = set()

    def sub(m):
        key = m.group(1)
        if key in params:
            return str(params[key])
        missing.add(key)
        return m.group(0)

    out = _PARAM_RE.sub(sub, text)
    if missing:
        raise KeyError(f"query{qnum}.tpl: no substitution for {sorted(missing)}")
    return out.strip().rstrip(";").strip()


def stream_permutation(qnums, rng):
    """Permuted query order for one stream (dsqgen-style per-stream shuffle)."""
    idx = rng.permutation(len(qnums))
    return [qnums[i] for i in idx]


def generate_streams(
    output_dir,
    streams: int,
    scale: float,
    rngseed: int,
    template_dir=None,
    qnums=None,
):
    """Write query_0.sql .. query_{streams-1}.sql; returns template list."""
    os.makedirs(output_dir, exist_ok=True)
    qnums = qnums or available_templates(template_dir)
    for s in range(streams):
        rng = np.random.default_rng(np.random.SeedSequence([rngseed, s]))
        order = stream_permutation(qnums, rng) if s > 0 else list(qnums)
        parts = []
        for n, q in enumerate(order):
            sql = instantiate(q, rng, scale, template_dir)
            parts.append(
                f"-- start query {n + 1} in stream {s} using template query{q}.tpl\n"
                f"{sql}\n;\n"
                f"-- end query {n + 1} in stream {s} using template query{q}.tpl\n"
            )
        with open(os.path.join(output_dir, f"query_{s}.sql"), "w") as f:
            f.write("\n".join(parts))
    return qnums


def split_special_query(q: str):
    """Split a two-statement stream entry (templates 14/23/24/39) into
    _part1/_part2 pieces, renaming the .tpl tag in each header (reference:
    nds/nds_gen_query_stream.py:91-103)."""
    pieces = q.split(";")
    part_1 = pieces[0].replace(".tpl", "_part1.tpl") + ";"
    head = pieces[0].split("\n")[0]
    part_2 = head.replace(".tpl", "_part2.tpl") + "\n" + pieces[1] + ";"
    return part_1, part_2


def generate_single(output_dir, template_name, scale, rngseed, template_dir=None):
    """Generate one query from one template (reference: --template flag,
    nds/nds_gen_query_stream.py:115-119)."""
    m = re.match(r"query(\d+)\.tpl$", template_name)
    if not m:
        raise ValueError(f"template name must be queryN.tpl, got {template_name}")
    q = int(m.group(1))
    os.makedirs(output_dir, exist_ok=True)
    rng = np.random.default_rng(np.random.SeedSequence([rngseed, 0]))
    sql = instantiate(q, rng, scale, template_dir)
    path = os.path.join(output_dir, f"query_{q}.sql")
    with open(path, "w") as f:
        f.write(
            f"-- start query 1 in stream 0 using template query{q}.tpl\n"
            f"{sql}\n;\n"
            f"-- end query 1 in stream 0 using template query{q}.tpl\n"
        )
    return path
