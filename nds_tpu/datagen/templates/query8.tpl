select s_store_name, sum(ss_net_profit)
from store_sales, date_dim, store,
     (select ca_zip
      from (
        (select substr(ca_zip, 1, 5) ca_zip
         from customer_address
         where substr(ca_zip, 1, 5) in ([ZIPLIST]))
        intersect
        (select ca_zip
         from (select substr(ca_zip, 1, 5) ca_zip, count(*) cnt
               from customer_address, customer
               where ca_address_sk = c_current_addr_sk
                 and c_preferred_cust_flag = 'Y'
               group by ca_zip
               having count(*) > 10) a1)) a2) v1
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_qoy = [QOY] and d_year = [YEAR]
  and (substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2))
group by s_store_name
order by s_store_name
limit 100
