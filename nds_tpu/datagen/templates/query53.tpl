select *
from (select i_manufact_id,
             sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price))
               over (partition by i_manufact_id) avg_quarterly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in ([DMS], [DMS] + 1, [DMS] + 2, [DMS] + 3,
                            [DMS] + 4, [DMS] + 5, [DMS] + 6, [DMS] + 7,
                            [DMS] + 8, [DMS] + 9, [DMS] + 10, [DMS] + 11)
        and ((i_category in ('[CAT_A1]', '[CAT_A2]', '[CAT_A3]')
              and i_class in ('[CLASS_A1]', '[CLASS_A2]', '[CLASS_A3]', '[CLASS_A4]')
              and i_brand in ('[BRAND_A1]', '[BRAND_A2]',
                              '[BRAND_A3]', '[BRAND_A4]'))
          or (i_category in ('[CAT_B1]', '[CAT_B2]', '[CAT_B3]')
              and i_class in ('[CLASS_B1]', '[CLASS_B2]', '[CLASS_B3]', '[CLASS_B4]')
              and i_brand in ('[BRAND_B1]', '[BRAND_B2]',
                              '[BRAND_B3]', '[BRAND_B4]')))
      group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
