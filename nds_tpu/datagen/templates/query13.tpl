select avg(ss_quantity) avg1, avg(ss_ext_sales_price) avg2,
       avg(ss_ext_wholesale_cost) avg3, sum(ss_ext_wholesale_cost) sum1
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS1]' and cd_education_status = '[ES1]'
        and ss_sales_price between 100.00 and 150.00 and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS2]' and cd_education_status = '[ES2]'
        and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS3]' and cd_education_status = '[ES3]'
        and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('[STATE11]', '[STATE12]', '[STATE13]')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('[STATE21]', '[STATE22]', '[STATE23]')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('[STATE31]', '[STATE32]', '[STATE33]')
        and ss_net_profit between 50 and 250))
