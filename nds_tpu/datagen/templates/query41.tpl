select distinct (i_product_name)
from item i1
where i_manufact_id between [MANUFACT] and [MANUFACT] + 40
  and (select count(*) as item_cnt
       from item
       where i_manufact = i1.i_manufact
         and ((i_category = 'Women'
               and (i_color = 'powder' or i_color = 'khaki')
               and (i_units = 'Ounce' or i_units = 'Oz')
               and (i_size = 'medium' or i_size = 'extra large'))
           or (i_category = 'Women'
               and (i_color = 'brown' or i_color = 'honeydew')
               and (i_units = 'Bunch' or i_units = 'Ton')
               and (i_size = 'N/A' or i_size = 'small'))
           or (i_category = 'Men'
               and (i_color = 'floral' or i_color = 'deep')
               and (i_units = 'N/A' or i_units = 'Dozen')
               and (i_size = 'petite' or i_size = 'large'))
           or (i_category = 'Men'
               and (i_color = 'light' or i_color = 'cornflower')
               and (i_units = 'Box' or i_units = 'Pound')
               and (i_size = 'medium' or i_size = 'extra large')))) > 0
order by i_product_name
limit 100
