select *
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between [LP1] and [LP1] + 10
             or ss_coupon_amt between [CA1] and [CA1] + 1000
             or ss_wholesale_cost between [WC1] and [WC1] + 20)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between [LP2] and [LP2] + 10
             or ss_coupon_amt between [CA2] and [CA2] + 1000
             or ss_wholesale_cost between [WC2] and [WC2] + 20)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between [LP3] and [LP3] + 10
             or ss_coupon_amt between [CA3] and [CA3] + 1000
             or ss_wholesale_cost between [WC3] and [WC3] + 20)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between [LP4] and [LP4] + 10
             or ss_coupon_amt between [CA4] and [CA4] + 1000
             or ss_wholesale_cost between [WC4] and [WC4] + 20)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between [LP5] and [LP5] + 10
             or ss_coupon_amt between [CA5] and [CA5] + 1000
             or ss_wholesale_cost between [WC5] and [WC5] + 20)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between [LP6] and [LP6] + 10
             or ss_coupon_amt between [CA6] and [CA6] + 1000
             or ss_wholesale_cost between [WC6] and [WC6] + 20)) b6
limit 100
