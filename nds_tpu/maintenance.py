"""Data Maintenance phase: the 11 TPC-DS refresh functions over the lakehouse.

TPU-native counterpart of the reference maintenance driver (reference:
nds/nds_maintenance.py — function lists :45-58, get_delete_date :60-73,
replace_date :75-96, get_maintenance_queries :118-144, run_query :204-265,
register_temp_views :267-271). The warehouse is our snapshot-manifest
lakehouse (Iceberg/Delta analogue); the refresh staging tables register
straight from the generated `--update` CSV data.
"""

from __future__ import annotations

import csv
import math
import os
import threading
import time
from datetime import datetime

from . import faults
from .check import check_json_summary_folder
from .engine.session import Session
from .io.fs import fs_open_atomic
from .obs import trace as obs_trace
from .power import load_properties
from .report import BenchReport
from .schema import get_maintenance_schemas, get_schemas

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR", "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNC = ["DF_I"]
DM_FUNCS = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNC

MAINTENANCE_SQL_DIR = os.path.join(os.path.dirname(__file__), "data_maintenance")


def get_valid_query_names(spec_queries):
    if spec_queries:
        for q in spec_queries:
            if q not in DM_FUNCS:
                raise Exception(
                    f"invalid Data Maintenance query: {q}. Valid are: {DM_FUNCS}"
                )
        return spec_queries
    return list(DM_FUNCS)


def get_delete_date(session):
    """Delete-date tuples from the generated delete tables (3 per function,
    TPC-DS spec 5.3.11)."""
    date_dict = {}
    for table in ("delete", "inventory_delete"):
        rows = session.sql(f"select * from {table}").collect().to_pylist()
        date_dict[table] = [(r["date1"], r["date2"]) for r in rows]
    return date_dict


def replace_date(query_list, date_tuple_list):
    """Apply every (DATE1, DATE2) tuple to the query list, normalizing tuple
    order so DATE1 <= DATE2."""
    q_updated = []
    for date1, date2 in date_tuple_list:
        d1 = datetime.strptime(str(date1), "%Y-%m-%d")
        d2 = datetime.strptime(str(date2), "%Y-%m-%d")
        earlier, later = (date1, date2) if d1 <= d2 else (date2, date1)
        for q in query_list:
            q_updated.append(
                q.replace("DATE1", str(earlier)).replace("DATE2", str(later))
            )
    return q_updated


def get_maintenance_queries(session, folder, valid_queries):
    """{function name: [statements]} with delete dates substituted."""
    delete_date_dict = get_delete_date(session)
    q_dict = {}
    for q in valid_queries:
        with open(os.path.join(folder, q + ".sql")) as f:
            text = f.read()
        stmts = [
            s.strip() + ";"
            for s in text.split(";")
            if s.strip() and not all(
                line.strip().startswith("--") or not line.strip()
                for line in s.splitlines()
            )
        ]
        if q in DELETE_FUNCS:
            stmts = replace_date(stmts, delete_date_dict["delete"])
        if q in INVENTORY_DELETE_FUNC:
            stmts = replace_date(stmts, delete_date_dict["inventory_delete"])
        q_dict[q] = stmts
    return q_dict


def run_dm_query(session, query_list, query_name):
    # scope labels the engine's trace events (op_span/catalog_load/...)
    # with the refresh function, exactly like power's per-query scope
    with faults.scope(query_name):
        for q in query_list:
            _run_dm_statement(session, q)


def _run_dm_statement(session, q):
    """One refresh statement with bounded commit-conflict re-runs.

    The retry has to live at STATEMENT granularity: a DM function is a
    list of statements, and re-running the whole function after its Nth
    statement's commit aborted would double-apply statements 1..N-1. A
    single aborted statement published nothing (staged files discarded),
    so re-running it re-derives its writes from the fresh head — the
    same semantics the report ladder's `commit_rebase_retry` rung gives
    idempotent whole-query callables. Budget/backoff share the ladder's
    knobs (NDS_LAKE_CONFLICT_RETRIES / NDS_LAKE_COMMIT_BACKOFF), parsed
    in their one home: lakehouse/table.py."""
    from .lakehouse.table import (
        CommitConflictError,
        commit_backoff_base,
        resolve_conflict_retries,
    )

    delays = faults.backoff_delays(
        resolve_conflict_retries(), commit_backoff_base()
    )
    while True:
        try:
            return session.run_script(q)
        except CommitConflictError as exc:
            delay = next(delays, None)
            if delay is None:
                raise
            print(
                f"maintenance: commit conflict ({exc}); re-running the "
                f"statement against the new head in {delay:.2f}s"
            )
            time.sleep(delay)


# staging tables each refresh function reads (spec 5.3.11); the delete-date
# tables are always needed for DATE1/DATE2 substitution
_FUNC_STAGING = {
    "LF_SS": ["s_purchase", "s_purchase_lineitem"],
    "LF_SR": ["s_store_returns"],
    "LF_CS": ["s_catalog_order", "s_catalog_order_lineitem"],
    "LF_CR": ["s_catalog_returns"],
    "LF_WS": ["s_web_order", "s_web_order_lineitem"],
    "LF_WR": ["s_web_returns"],
    "LF_I": ["s_inventory"],
}


def register_refresh_views(session, refresh_data_path, valid_queries=None):
    """Register the s_* staging tables + delete tables from raw CSV
    (reference: nds_maintenance.register_temp_views :267-271). Only the
    staging tables the selected functions read are materialized."""
    needed = {"delete", "inventory_delete"}
    for q in valid_queries or DM_FUNCS:
        needed.update(_FUNC_STAGING.get(q, []))
    schemas = get_maintenance_schemas(session.use_decimal)
    for table in sorted(needed):
        path = os.path.join(refresh_data_path, table)
        if not os.path.isdir(path):
            # fail now with the expected path, not mid-run as an opaque
            # binder "unknown table" inside the timed maintenance window
            raise FileNotFoundError(
                f"staging table {table!r} required by the selected "
                f"maintenance functions is missing: expected directory "
                f"{path} (generate it with gen_data --update)"
            )
        session.register_csv_dir(table, path, schemas[table])


def vacuum_warehouse(warehouse_path, tables=None, retain_last=None,
                     conf=None):
    """Expire old snapshots and delete unreferenced data files across the
    warehouse's lakehouse tables (Iceberg's expire_snapshots + orphan
    cleanup). Files a live reader lease covers are never deleted —
    vacuum can run while query streams are mid-flight (the
    maintenance-under-load phase does exactly that). Returns the
    per-table vacuum result dicts."""
    from .lakehouse.table import LakehouseTable

    results = []
    names = tables
    if names is None:
        try:
            names = sorted(os.listdir(warehouse_path))
        except OSError:
            names = []
    for name in names:
        path = os.path.join(str(warehouse_path), name)
        if not LakehouseTable.is_table(path):
            continue
        res = LakehouseTable(path, conf=conf).vacuum(retain_last=retain_last)
        if res["files_removed"] or res["manifests_removed"]:
            print(
                f"vacuum {name}: removed {res['files_removed']} data "
                f"file(s), {res['manifests_removed']} manifest(s)"
                + (
                    f", kept {res['files_leased']} leased file(s)"
                    if res["files_leased"] else ""
                )
            )
        results.append(res)
    return results


def optimize_warehouse(warehouse_path, tables=None, target_bytes=None,
                       min_input_files=None, conf=None):
    """Compact small files across the warehouse's lakehouse tables
    (Delta's OPTIMIZE / Iceberg's rewrite_data_files). Chunked parallel
    ingest and per-statement DM commits both fragment tables into many
    small files; compaction bin-packs them back toward
    `engine.lake_compact_target_bytes` under the same OCC commit path as
    any writer, regenerating each rewritten file's zone map. Snapshot
    isolation keeps concurrent pinned readers on the pre-compaction
    manifest, and a racing commit aborts the compaction (retried with the
    shared conflict backoff), never the other writer. Returns the
    per-table result dicts."""
    from .lakehouse.table import (
        CommitConflictError,
        LakehouseTable,
        commit_backoff_base,
        resolve_conflict_retries,
    )

    results = []
    names = tables
    if names is None:
        try:
            names = sorted(os.listdir(warehouse_path))
        except OSError:
            names = []
    for name in names:
        path = os.path.join(str(warehouse_path), name)
        if not LakehouseTable.is_table(path):
            continue
        lt = LakehouseTable(path, conf=conf)
        delays = faults.backoff_delays(
            resolve_conflict_retries(), commit_backoff_base()
        )
        while True:
            try:
                res = lt.compact(
                    target_bytes=target_bytes,
                    min_input_files=min_input_files,
                )
                break
            except CommitConflictError as exc:
                delay = next(delays, None)
                if delay is None:
                    raise
                print(
                    f"optimize {name}: commit conflict ({exc}); "
                    f"re-planning against the new head in {delay:.2f}s"
                )
                time.sleep(delay)
        if res["version"] is not None:
            print(
                f"optimize {name}: compacted {res['files_in']} file(s) "
                f"into {res['files_out']} "
                f"({res['bytes_in']} bytes rewritten) "
                f"-> v{res['version']}"
            )
        results.append(res)
    return results


def run_maintenance(
    warehouse_path,
    refresh_data_path,
    time_log_output_path,
    json_summary_folder=None,
    property_file=None,
    spec_queries=None,
    use_decimal=True,
    maintenance_sql_dir=None,
    vacuum_after=False,
    optimize_after=False,
):
    """Run the maintenance functions with per-function timing + reports.

    Returns the Data Maintenance Time in seconds (Tdm contribution).
    `optimize_after` compacts the small files the per-statement DM
    commits fragmented (target: `engine.lake_compact_target_bytes` /
    NDS_LAKE_COMPACT_TARGET_BYTES); `vacuum_after` then expires old
    snapshots + sweeps unreferenced data files (retention:
    `engine.lake_vacuum_retain` / NDS_LAKE_VACUUM_RETAIN, default 2).
    Compaction runs first so its superseded inputs age into the same
    vacuum horizon as every other dead snapshot."""
    valid_queries = get_valid_query_names(spec_queries)
    app_name = (
        "NDS - Data Maintenance - " + valid_queries[0]
        if len(valid_queries) == 1
        else "NDS - Data Maintenance"
    )
    conf = {"app.name": app_name, "lakehouse.warehouse": warehouse_path}
    if property_file:
        conf.update(load_properties(property_file))
    check_json_summary_folder(json_summary_folder)
    session = Session(use_decimal=use_decimal, conf=conf)
    try:
        return _run_maintenance_body(
            session, warehouse_path, refresh_data_path,
            time_log_output_path, json_summary_folder, property_file,
            valid_queries, maintenance_sql_dir, vacuum_after,
            optimize_after,
        )
    finally:
        # this maintenance run is its tracer's ONLY emitter: closing here
        # (success or crash) flushes the final line so a child dying
        # mid-phase folds cleanly into the parent's event view — the same
        # contract as power.run_query_stream (PR-8)
        if session.tracer is not None:
            session.tracer.close()


def _run_maintenance_body(
    session, warehouse_path, refresh_data_path, time_log_output_path,
    json_summary_folder, property_file, valid_queries, maintenance_sql_dir,
    vacuum_after, optimize_after=False,
):
    app_id = f"nds-tpu-dm-{os.getpid()}-{int(time.time())}"

    # warehouse fact/dim tables (lakehouse) + refresh staging views (csv)
    session.register_nds_tables(warehouse_path, fmt="lakehouse")
    register_refresh_views(session, refresh_data_path, valid_queries)

    query_dict = get_maintenance_queries(
        session, maintenance_sql_dir or MAINTENANCE_SQL_DIR, valid_queries
    )

    execution_time_list = []
    total_time_start = datetime.now()
    dm_start = datetime.now()
    # bind the session tracer to this thread: session-less layers (the
    # lakehouse commit/vacuum event sites, fault registry, fs retries)
    # find it through the thread-local binding
    with obs_trace.bind(session.tracer):
        for query_name, q_content in query_dict.items():
            print(f"====== Run {query_name} ======")
            q_report = BenchReport(session)
            summary = q_report.report_on(
                run_dm_query, session, q_content, query_name, name=query_name
            )
            print(
                f"Time taken: {summary['queryTimes']} millis for {query_name}"
            )
            execution_time_list.append(
                (app_id, query_name, summary["queryTimes"])
            )
            if json_summary_folder:
                if property_file:
                    summary_prefix = os.path.join(
                        json_summary_folder,
                        os.path.basename(property_file).split(".")[0],
                    )
                else:
                    summary_prefix = os.path.join(json_summary_folder, "")
                q_report.write_summary(query_name, prefix=summary_prefix)
        if optimize_after:
            o_start = time.perf_counter()
            optimize_warehouse(warehouse_path, conf=session.conf)
            execution_time_list.append(
                (app_id, "Optimize Time",
                 round(time.perf_counter() - o_start, 3))
            )
        if vacuum_after:
            v_start = time.perf_counter()
            vacuum_warehouse(warehouse_path, conf=session.conf)
            execution_time_list.append(
                (app_id, "Vacuum Time",
                 round(time.perf_counter() - v_start, 3))
            )
    dm_end = datetime.now()
    dm_elapse = (dm_end - dm_start).total_seconds()
    total_elapse = (dm_end - total_time_start).total_seconds()
    print(f"====== Data Maintenance Start Time: {dm_start}")
    print(f"====== Data Maintenance Time: {dm_elapse} s ======")
    print(f"====== Total Time: {total_elapse} s ======")
    execution_time_list.append((app_id, "Data Maintenance Start Time", dm_start))
    execution_time_list.append((app_id, "Data Maintenance End Time", dm_end))
    execution_time_list.append((app_id, "Data Maintenance Time", dm_elapse))
    execution_time_list.append((app_id, "Total Time", total_elapse))

    header = ["application_id", "query", "time/s"]
    # atomic: full_bench resume re-parses this log for Tdm, so a crash
    # mid-write must never leave a torn CSV behind
    with fs_open_atomic(
        time_log_output_path, "w", encoding="UTF8", newline=""
    ) as f:
        writer = csv.writer(f)
        writer.writerow(header)
        writer.writerows(execution_time_list)
    return dm_elapse


# ---------------------------------------------------------------------------
# maintenance under load: DM_* commits racing a live query stream
# ---------------------------------------------------------------------------


def _p99_ms(times):
    """p99 of a list of per-query milliseconds (nearest-rank); None when
    empty. Small streams degenerate to the max — the right tail either way."""
    if not times:
        return None
    ts = sorted(times)
    idx = max(int(math.ceil(0.99 * len(ts))) - 1, 0)
    return round(float(ts[idx]), 3)


def run_maintenance_under_load(
    warehouse_path,
    refresh_data_path,
    stream_file,
    time_log_output_path,
    report_path=None,
    property_file=None,
    spec_queries=None,
    sub_queries=None,
    use_decimal=True,
    vacuum_retain=None,
):
    """Maintenance-under-load: DM_* refresh functions (and a vacuum)
    commit against the warehouse WHILE a query stream reads it — the
    scenario the reference gets exercised for free by Spark+Iceberg
    concurrency and this engine previously never ran (full_bench
    serialized maintenance against query streams; ROADMAP item 5).

    Two passes over the stream: a SOLO baseline, then the same stream
    with the maintenance thread racing it. Reported as maintenance
    throughput (functions/s) x query p99 degradation (under-load p99 /
    solo p99). Snapshot pins keep every in-flight query on one manifest
    version across the racing commits; the concurrent vacuum respects
    the readers' leases. Returns the report dict (also written to
    `report_path` atomically when given)."""
    from .power import gen_sql_from_stream, get_query_subset, run_one_query

    valid_queries = get_valid_query_names(spec_queries)
    conf = {
        "app.name": "NDS - Maintenance Under Load",
        "lakehouse.warehouse": warehouse_path,
    }
    if property_file:
        conf.update(load_properties(property_file))
    query_dict = gen_sql_from_stream(stream_file)
    if sub_queries:
        query_dict = get_query_subset(query_dict, sub_queries)
    app_id = f"nds-tpu-mul-{os.getpid()}-{int(time.time())}"

    # reader and writer run on SEPARATE sessions (each with its own
    # snapshot pins and tracer) but share the process-wide reader-lease
    # table — which is exactly what makes the writer's vacuum safe while
    # the reader is mid-query
    qconf = dict(conf)
    qconf["app.name"] = "NDS - MUL query stream"
    qsession = Session(use_decimal=use_decimal, conf=qconf)
    msession = Session(use_decimal=use_decimal, conf=dict(conf))
    try:
        qsession.register_nds_tables(warehouse_path, fmt="lakehouse")
        msession.register_nds_tables(warehouse_path, fmt="lakehouse")
        register_refresh_views(msession, refresh_data_path, valid_queries)
        dm_queries = get_maintenance_queries(
            msession, MAINTENANCE_SQL_DIR, valid_queries
        )
        rows = []

        def run_stream(tag):
            times, failed = [], 0
            with obs_trace.bind(qsession.tracer):
                for qname, qtext in query_dict.items():
                    rep = BenchReport(qsession)
                    s = rep.report_on(
                        run_one_query, qsession, qtext, qname, None,
                        "parquet", retry_oom=True, name=qname,
                    )
                    ms = s["queryTimes"][0]
                    rows.append((app_id, f"{tag}:{qname}", ms))
                    if s["queryStatus"][-1] == "Failed":
                        failed += 1
                    else:
                        times.append(float(ms))
            return times, failed

        dm_stats = {"functions": 0, "failed": 0, "elapsed_s": None,
                    "vacuums": 0, "vacuum_files_removed": 0,
                    "error": None}

        def run_dm():
            # any escape here would otherwise die silently on the daemon
            # thread and the phase would report a clean run — record it,
            # finish the report, and let the caller re-raise
            t0 = time.perf_counter()
            try:
                with obs_trace.bind(msession.tracer):
                    for fname, stmts in dm_queries.items():
                        rep = BenchReport(msession)
                        s = rep.report_on(
                            run_dm_query, msession, stmts, fname, name=fname
                        )
                        rows.append(
                            (app_id, f"dm:{fname}", s["queryTimes"][0])
                        )
                        if s["queryStatus"][-1] == "Failed":
                            dm_stats["failed"] += 1
                        else:
                            dm_stats["functions"] += 1
                    # vacuum WHILE the stream still reads: reader leases
                    # are the safety contract under test
                    for res in vacuum_warehouse(
                        warehouse_path, conf=msession.conf,
                        retain_last=vacuum_retain,
                    ):
                        dm_stats["vacuums"] += 1
                        dm_stats["vacuum_files_removed"] += (
                            res["files_removed"]
                        )
            except BaseException as exc:
                dm_stats["error"] = f"{type(exc).__name__}: {exc}"
            finally:
                dm_stats["elapsed_s"] = round(time.perf_counter() - t0, 3)

        # warmup pass (recorded but unmeasured): the solo baseline must be
        # steady-state, or cold XLA compiles land entirely in the solo p99
        # and the degradation ratio reads as a nonsense speedup
        print("====== maintenance_under_load: warmup stream ======")
        run_stream("warmup")
        print("====== maintenance_under_load: solo baseline stream ======")
        solo_times, solo_failed = run_stream("solo")
        print("====== maintenance_under_load: stream + racing DM_* ======")
        dm_thread = threading.Thread(
            target=run_dm, name="nds-maintenance-under-load", daemon=True
        )
        overlap_start = time.perf_counter()
        dm_thread.start()
        load_times, load_failed = run_stream("under_load")
        dm_thread.join()
        overlap_s = round(time.perf_counter() - overlap_start, 3)

        solo_p99 = _p99_ms(solo_times)
        load_p99 = _p99_ms(load_times)
        report = {
            "queries": len(query_dict),
            "solo_failed": solo_failed,
            "under_load_failed": load_failed,
            "query_p99_ms_solo": solo_p99,
            "query_p99_ms_under_load": load_p99,
            # the headline: how much the racing maintenance hurt the
            # stream's tail (1.0 = not at all)
            "query_p99_degradation": (
                round(load_p99 / solo_p99, 3)
                if solo_p99 and load_p99 else None
            ),
            "dm_functions": dm_stats["functions"],
            "dm_failed": dm_stats["failed"],
            "dm_elapsed_s": dm_stats["elapsed_s"],
            "dm_functions_per_s": (
                round(dm_stats["functions"] / dm_stats["elapsed_s"], 4)
                if dm_stats["elapsed_s"] else None
            ),
            "vacuums": dm_stats["vacuums"],
            "vacuum_files_removed": dm_stats["vacuum_files_removed"],
            "overlap_wall_s": overlap_s,
        }
        if dm_stats["error"]:
            report["dm_error"] = dm_stats["error"]
        rows.append((app_id, "Maintenance Under Load Time", overlap_s))
        header = ["application_id", "query", "time/s"]
        with fs_open_atomic(
            time_log_output_path, "w", encoding="UTF8", newline=""
        ) as f:
            writer = csv.writer(f)
            writer.writerow(header)
            writer.writerows(rows)
        if report_path:
            import json

            with fs_open_atomic(report_path, "w") as f:
                json.dump(report, f, indent=2)
        print(f"====== maintenance_under_load: {report} ======")
        if dm_stats["error"]:
            # evidence is on disk; now fail the phase loudly — a broken
            # maintenance thread must not read as a clean completion
            raise RuntimeError(
                f"maintenance-under-load DM thread failed: "
                f"{dm_stats['error']} (report written to "
                f"{report_path or time_log_output_path})"
            )
        return report
    finally:
        # both sessions own their tracers (PR-8 contract: close in
        # finally so child event segments fold cleanly on any exit)
        for s in (qsession, msession):
            if s.tracer is not None:
                s.tracer.close()


def rollback(warehouse_path, timestamp, tables=None):
    """Roll the mutated fact tables back to a pre-maintenance snapshot
    (reference: nds/nds_rollback.py:37-51)."""
    from .lakehouse.table import LakehouseTable

    tables = tables or [
        "catalog_sales",
        "catalog_returns",
        "inventory",
        "store_returns",
        "store_sales",
        "web_returns",
        "web_sales",
    ]
    session = Session(conf={"lakehouse.warehouse": warehouse_path})
    session.register_nds_tables(warehouse_path, fmt="lakehouse")
    for table in tables:
        if not LakehouseTable.is_table(os.path.join(warehouse_path, table)):
            continue
        print(f"Rolling back {table} to {timestamp}")
        session.sql(
            f"call system.rollback_to_timestamp('{table}', timestamp '{timestamp}')"
        )
