"""Data Maintenance phase: the 11 TPC-DS refresh functions over the lakehouse.

TPU-native counterpart of the reference maintenance driver (reference:
nds/nds_maintenance.py — function lists :45-58, get_delete_date :60-73,
replace_date :75-96, get_maintenance_queries :118-144, run_query :204-265,
register_temp_views :267-271). The warehouse is our snapshot-manifest
lakehouse (Iceberg/Delta analogue); the refresh staging tables register
straight from the generated `--update` CSV data.
"""

from __future__ import annotations

import csv
import os
import time
from datetime import datetime

from . import faults
from .check import check_json_summary_folder
from .engine.session import Session
from .io.fs import fs_open_atomic
from .power import load_properties
from .report import BenchReport
from .schema import get_maintenance_schemas, get_schemas

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR", "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNC = ["DF_I"]
DM_FUNCS = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNC

MAINTENANCE_SQL_DIR = os.path.join(os.path.dirname(__file__), "data_maintenance")


def get_valid_query_names(spec_queries):
    if spec_queries:
        for q in spec_queries:
            if q not in DM_FUNCS:
                raise Exception(
                    f"invalid Data Maintenance query: {q}. Valid are: {DM_FUNCS}"
                )
        return spec_queries
    return list(DM_FUNCS)


def get_delete_date(session):
    """Delete-date tuples from the generated delete tables (3 per function,
    TPC-DS spec 5.3.11)."""
    date_dict = {}
    for table in ("delete", "inventory_delete"):
        rows = session.sql(f"select * from {table}").collect().to_pylist()
        date_dict[table] = [(r["date1"], r["date2"]) for r in rows]
    return date_dict


def replace_date(query_list, date_tuple_list):
    """Apply every (DATE1, DATE2) tuple to the query list, normalizing tuple
    order so DATE1 <= DATE2."""
    q_updated = []
    for date1, date2 in date_tuple_list:
        d1 = datetime.strptime(str(date1), "%Y-%m-%d")
        d2 = datetime.strptime(str(date2), "%Y-%m-%d")
        earlier, later = (date1, date2) if d1 <= d2 else (date2, date1)
        for q in query_list:
            q_updated.append(
                q.replace("DATE1", str(earlier)).replace("DATE2", str(later))
            )
    return q_updated


def get_maintenance_queries(session, folder, valid_queries):
    """{function name: [statements]} with delete dates substituted."""
    delete_date_dict = get_delete_date(session)
    q_dict = {}
    for q in valid_queries:
        with open(os.path.join(folder, q + ".sql")) as f:
            text = f.read()
        stmts = [
            s.strip() + ";"
            for s in text.split(";")
            if s.strip() and not all(
                line.strip().startswith("--") or not line.strip()
                for line in s.splitlines()
            )
        ]
        if q in DELETE_FUNCS:
            stmts = replace_date(stmts, delete_date_dict["delete"])
        if q in INVENTORY_DELETE_FUNC:
            stmts = replace_date(stmts, delete_date_dict["inventory_delete"])
        q_dict[q] = stmts
    return q_dict


def run_dm_query(session, query_list, query_name):
    # scope labels the engine's trace events (op_span/catalog_load/...)
    # with the refresh function, exactly like power's per-query scope
    with faults.scope(query_name):
        for q in query_list:
            session.run_script(q)


# staging tables each refresh function reads (spec 5.3.11); the delete-date
# tables are always needed for DATE1/DATE2 substitution
_FUNC_STAGING = {
    "LF_SS": ["s_purchase", "s_purchase_lineitem"],
    "LF_SR": ["s_store_returns"],
    "LF_CS": ["s_catalog_order", "s_catalog_order_lineitem"],
    "LF_CR": ["s_catalog_returns"],
    "LF_WS": ["s_web_order", "s_web_order_lineitem"],
    "LF_WR": ["s_web_returns"],
    "LF_I": ["s_inventory"],
}


def register_refresh_views(session, refresh_data_path, valid_queries=None):
    """Register the s_* staging tables + delete tables from raw CSV
    (reference: nds_maintenance.register_temp_views :267-271). Only the
    staging tables the selected functions read are materialized."""
    needed = {"delete", "inventory_delete"}
    for q in valid_queries or DM_FUNCS:
        needed.update(_FUNC_STAGING.get(q, []))
    schemas = get_maintenance_schemas(session.use_decimal)
    for table in sorted(needed):
        path = os.path.join(refresh_data_path, table)
        if not os.path.isdir(path):
            # fail now with the expected path, not mid-run as an opaque
            # binder "unknown table" inside the timed maintenance window
            raise FileNotFoundError(
                f"staging table {table!r} required by the selected "
                f"maintenance functions is missing: expected directory "
                f"{path} (generate it with gen_data --update)"
            )
        session.register_csv_dir(table, path, schemas[table])


def run_maintenance(
    warehouse_path,
    refresh_data_path,
    time_log_output_path,
    json_summary_folder=None,
    property_file=None,
    spec_queries=None,
    use_decimal=True,
    maintenance_sql_dir=None,
):
    """Run the maintenance functions with per-function timing + reports.

    Returns the Data Maintenance Time in seconds (Tdm contribution)."""
    valid_queries = get_valid_query_names(spec_queries)
    app_name = (
        "NDS - Data Maintenance - " + valid_queries[0]
        if len(valid_queries) == 1
        else "NDS - Data Maintenance"
    )
    conf = {"app.name": app_name, "lakehouse.warehouse": warehouse_path}
    if property_file:
        conf.update(load_properties(property_file))
    check_json_summary_folder(json_summary_folder)
    session = Session(use_decimal=use_decimal, conf=conf)
    app_id = f"nds-tpu-dm-{os.getpid()}-{int(time.time())}"

    # warehouse fact/dim tables (lakehouse) + refresh staging views (csv)
    session.register_nds_tables(warehouse_path, fmt="lakehouse")
    register_refresh_views(session, refresh_data_path, valid_queries)

    query_dict = get_maintenance_queries(
        session, maintenance_sql_dir or MAINTENANCE_SQL_DIR, valid_queries
    )

    execution_time_list = []
    total_time_start = datetime.now()
    dm_start = datetime.now()
    for query_name, q_content in query_dict.items():
        print(f"====== Run {query_name} ======")
        q_report = BenchReport(session)
        summary = q_report.report_on(
            run_dm_query, session, q_content, query_name, name=query_name
        )
        print(f"Time taken: {summary['queryTimes']} millis for {query_name}")
        execution_time_list.append((app_id, query_name, summary["queryTimes"]))
        if json_summary_folder:
            if property_file:
                summary_prefix = os.path.join(
                    json_summary_folder,
                    os.path.basename(property_file).split(".")[0],
                )
            else:
                summary_prefix = os.path.join(json_summary_folder, "")
            q_report.write_summary(query_name, prefix=summary_prefix)
    dm_end = datetime.now()
    dm_elapse = (dm_end - dm_start).total_seconds()
    total_elapse = (dm_end - total_time_start).total_seconds()
    print(f"====== Data Maintenance Start Time: {dm_start}")
    print(f"====== Data Maintenance Time: {dm_elapse} s ======")
    print(f"====== Total Time: {total_elapse} s ======")
    execution_time_list.append((app_id, "Data Maintenance Start Time", dm_start))
    execution_time_list.append((app_id, "Data Maintenance End Time", dm_end))
    execution_time_list.append((app_id, "Data Maintenance Time", dm_elapse))
    execution_time_list.append((app_id, "Total Time", total_elapse))

    header = ["application_id", "query", "time/s"]
    # atomic: full_bench resume re-parses this log for Tdm, so a crash
    # mid-write must never leave a torn CSV behind
    with fs_open_atomic(
        time_log_output_path, "w", encoding="UTF8", newline=""
    ) as f:
        writer = csv.writer(f)
        writer.writerow(header)
        writer.writerows(execution_time_list)
    return dm_elapse


def rollback(warehouse_path, timestamp, tables=None):
    """Roll the mutated fact tables back to a pre-maintenance snapshot
    (reference: nds/nds_rollback.py:37-51)."""
    from .lakehouse.table import LakehouseTable

    tables = tables or [
        "catalog_sales",
        "catalog_returns",
        "inventory",
        "store_returns",
        "store_sales",
        "web_returns",
        "web_sales",
    ]
    session = Session(conf={"lakehouse.warehouse": warehouse_path})
    session.register_nds_tables(warehouse_path, fmt="lakehouse")
    for table in tables:
        if not LakehouseTable.is_table(os.path.join(warehouse_path, table)):
            continue
        print(f"Rolling back {table} to {timestamp}")
        session.sql(
            f"call system.rollback_to_timestamp('{table}', timestamp '{timestamp}')"
        )
