"""Distributed execution primitives: device mesh + sharded relational steps.

The reference scales queries via Spark executors and shuffle partitions
(reference: nds/base.template:28-31, power_run_cpu.template:20-27); the TPU
equivalent is SPMD over a jax.sharding.Mesh. The core patterns:

  * fact tables shard over the `data` mesh axis (rows), dimensions replicate;
  * star joins against dense surrogate-key dims are pure gathers;
  * aggregation is local partial segment-sum + psum over ICI (the
    shuffle-free TPC-DS groupby: group cardinality << row count);
  * large fact-fact joins hash-partition both sides with all_to_all
    (ppermute rounds) before local join.

`fused_query_step` is the single-chip jittable hot loop; `sharded_query_step`
is the same step laid out over a mesh via shard_map.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 top-level API; older releases: experimental module
    from jax import shard_map as _sm

    def shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

jax.config.update("jax_enable_x64", True)


def make_mesh(n_devices=None, axis="data"):
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


# ---------------------------------------------------------------------------
# The flagship compiled step: star-join + filter + group aggregation.
# This is the shape of the NDS Power Run hot path (q3/q7/q19/...): scan a
# fact shard, gather dimension attributes through dense surrogate keys,
# apply dim predicates, segment-reduce measures by group key.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_groups",))
def fused_query_step(
    fact_date_idx,  # int32[n]   fact FK -> dim row index (0-based)
    fact_item_idx,  # int32[n]
    fact_measure,   # int64[n]   scaled decimal measure
    fact_valid,     # bool[n]    live & non-null rows
    dim_date_flag,  # bool[n_dates]   date predicate (e.g. d_moy = 11)
    dim_item_group, # int32[n_items]  group key per item (-1 = filtered out)
    n_groups: int,
):
    """One fused scan->join->filter->aggregate step (single chip)."""
    ok = fact_valid
    ok = ok & dim_date_flag[fact_date_idx]
    g = dim_item_group[fact_item_idx]
    ok = ok & (g >= 0)
    vals = jnp.where(ok, fact_measure, 0)
    gids = jnp.where(ok, g, n_groups)  # dead rows -> overflow bucket
    sums = jax.ops.segment_sum(vals, gids, num_segments=n_groups + 1)
    counts = jax.ops.segment_sum(ok.astype(jnp.int64), gids, num_segments=n_groups + 1)
    return sums[:n_groups], counts[:n_groups]


def sharded_query_step(mesh: Mesh, n_groups: int):
    """Build the mesh-parallel version: fact sharded on rows, dims replicated,
    partial aggregation per chip + psum over ICI."""

    def local_step(fd, fi, fm, fv, ddf, dig):
        sums, counts = fused_query_step(fd, fi, fm, fv, ddf, dig, n_groups=n_groups)
        return jax.lax.psum(sums, "data"), jax.lax.psum(counts, "data")

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P(), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Hash-partitioned exchange: the all_to_all shuffle for fact-fact joins
# (reference's Spark shuffle, rebuilt on XLA collectives).
# ---------------------------------------------------------------------------


def partition_exchange(mesh: Mesh, cap_per_dev: int):
    """Returns a jitted fn that redistributes (key, value) rows so that every
    key lands on device hash(key) % n_devices. Rows are bucketed locally,
    padded to a fixed per-destination capacity, then exchanged with
    all_to_all over ICI.

    Returns (recv_keys, recv_vals, dropped): `dropped` is the global count of
    live rows that exceeded cap_per_dev in some destination bucket (replicated
    scalar). Callers MUST check dropped == 0 and retry with a larger capacity
    on overflow — under key skew a fixed cap silently truncating would corrupt
    join/aggregate results."""
    n_dev = mesh.devices.size

    def local(keys, vals, live):
        # keys,vals,live: [n_local]; returns [n_dev * cap] received rows
        dest = (keys % n_dev).astype(jnp.int32)
        rlive, (rk, rv), overflow = _route_by_dest(
            dest, live, n_dev, cap_per_dev, [keys, vals]
        )
        # contract: dead received slots carry key -1
        return jnp.where(rlive, rk, -1), rv, overflow

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P("data"), P("data"), P()),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Distributed hash join: the full shuffle join for fact-fact shapes
# (store_sales x store_returns and friends). Both sides hash-partition on the
# join-key hash with all_to_all over ICI, then every device joins its
# partition locally with static shapes — no host round-trips inside the
# compiled step. The executor drives capacity-overflow retries.
# ---------------------------------------------------------------------------


def _route_by_dest(dest, live, n_dev, cap, cols):
    """Pack rows into [n_dev, cap] buckets by destination device and exchange
    with all_to_all. Returns (recv_live, recv_cols, overflow)."""
    mdest = jnp.where(live, dest, n_dev)
    order = jnp.argsort(mdest)
    msorted = mdest[order]
    base = jnp.searchsorted(msorted, jnp.arange(n_dev), side="left")
    row = jnp.where(msorted < n_dev, msorted, n_dev)
    pos = jnp.arange(dest.shape[0]) - base[jnp.clip(row, 0, n_dev - 1)]
    overflow = ((msorted < n_dev) & (pos >= cap)).sum()
    row = jnp.where(pos < cap, row, n_dev)

    def scatter(x, fill):
        buf = jnp.full((n_dev, cap), fill, x.dtype)
        buf = buf.at[row, pos].set(x[order], mode="drop")
        return jax.lax.all_to_all(buf, "data", 0, 0, tiled=True).reshape(-1)

    rlive = scatter(live, False)
    rcols = [scatter(c, jnp.zeros((), c.dtype)) for c in cols]
    return rlive, rcols, jax.lax.psum(overflow, "data")


def _route(h, live, n_dev, cap, cols):
    """Hash routing: key lands on device hash % n_dev.
    Returns (recv_hash [n_dev*cap], recv_live, recv_cols, overflow)."""
    dest = (h.astype(jnp.uint64) % jnp.uint64(n_dev)).astype(jnp.int32)
    rlive, rcols, overflow = _route_by_dest(dest, live, n_dev, cap, [h] + cols)
    return rcols[0], rlive, rcols[1:], overflow


def exchange_hash_join(
    mesh: Mesh,
    n_lkeys: int,
    n_lcols: int,
    n_rcols: int,
    cap_l: int,
    cap_r: int,
    pair_cap: int,
    kind: str = "inner",
):
    """Factory for the mesh fact-fact join step (inner or left).

    The returned jitted fn takes
      (l_hash, l_live, l_keys..., l_cols...),
      (r_hash, r_live, r_keys..., r_cols...)
    as flat tuples and returns per-device-concatenated outputs:

      inner: (pair_ok [n_dev*pair_cap], l_out cols..., r_out cols...,
              recv_counts [n_dev], overflow scalar)
      left:  inner's outputs plus, before recv_counts:
             (l_recv_live [n_dev*cap_l], l_matched [n_dev*cap_l],
              l_recv cols... [n_dev*cap_l])

    pair_ok marks verified join pairs (hash candidates re-checked against
    the real key columns, so collisions can never fabricate rows). For a
    LEFT join the caller null-extends `l_recv_live & ~l_matched` rows (the
    shipped-but-unmatched left rows; null-keyed rows never route and stay
    the caller's problem). `recv_counts` is the per-device count of live
    received left rows — the skew evidence the `exchange` trace event
    reports (max/mean > 1 means the hash partitioning is imbalanced).
    overflow > 0 means some bucket or pair capacity was exceeded — the
    caller must retry with larger caps (executor emits a task-failure event
    and doubles, like a Spark shuffle-spill retry) and must not trust any
    other output of that attempt.
    """
    n_dev = mesh.devices.size
    imax = jnp.iinfo(jnp.int64).max
    imin = jnp.iinfo(jnp.int64).min

    def local(largs, rargs):
        lh, llive, *lrest = largs
        rh, rlive, *rrest = rargs
        lkeys, lcols = lrest[:n_lkeys], lrest[n_lkeys:]
        rkeys, rcols = rrest[:n_lkeys], rrest[n_lkeys:]
        lh2, llive2, lship, ovl = _route(
            lh, llive, n_dev, cap_l, list(lkeys) + list(lcols)
        )
        rh2, rlive2, rship, ovr = _route(
            rh, rlive, n_dev, cap_r, list(rkeys) + list(rcols)
        )
        lkeys2, lcols2 = lship[:n_lkeys], lship[n_lkeys:]
        rkeys2, rcols2 = rship[:n_lkeys], rship[n_lkeys:]
        # local sorted-probe join with a fixed pair capacity
        rh_m = jnp.where(rlive2, rh2, imax)
        order = jnp.argsort(rh_m).astype(jnp.int32)
        rh_sorted = rh_m[order]
        lh_m = jnp.where(llive2, lh2, imin)
        lo = jnp.searchsorted(rh_sorted, lh_m, side="left").astype(jnp.int32)
        hi = jnp.searchsorted(rh_sorted, lh_m, side="right").astype(jnp.int32)
        counts = jnp.where(llive2, hi - lo, 0)
        offs = jnp.cumsum(counts) - counts
        total = jnp.sum(counts)
        p = jnp.arange(pair_cap, dtype=jnp.int64)
        li = jnp.searchsorted(offs + counts, p, side="right").astype(jnp.int32)
        li = jnp.clip(li, 0, lh2.shape[0] - 1)
        j = (p - offs[li]).astype(jnp.int32)
        ri = order[jnp.clip(lo[li] + j, 0, rh2.shape[0] - 1)]
        ok = (p < total) & llive2[li] & rlive2[ri]
        for a, b in zip(lkeys2, rkeys2):
            ok = ok & (a[li] == b[ri])
        ov_pairs = jnp.maximum(total - pair_cap, 0)
        overflow = ovl + ovr + jax.lax.psum(ov_pairs, "data")
        # per-device received-row counts as a psum'd one-hot (psum output
        # is provably replicated, which shard_map's rep check can infer;
        # a bare all_gather here is not)
        d_idx = jax.lax.axis_index("data")
        recv_counts = jax.lax.psum(
            jnp.zeros(n_dev, jnp.int64).at[d_idx].set(llive2.sum()), "data"
        )
        l_out = [c[li] for c in lcols2]
        r_out = [c[ri] for c in rcols2]
        if kind == "left":
            # matched = >= 1 verified pair enumerated for the received row
            # (only trustworthy when overflow == 0 — truncated pair
            # enumeration could miss a row's single match)
            lmatched = jnp.zeros(lh2.shape[0], bool).at[li].max(ok)
            return (
                ok, *l_out, *r_out, llive2, lmatched, *lcols2,
                recv_counts, overflow,
            )
        return (ok, *l_out, *r_out, recv_counts, overflow)

    left_extra = (
        tuple(P("data") for _ in range(2 + n_lcols)) if kind == "left" else ()
    )
    out_specs = (
        (P("data"),)
        + tuple(P("data") for _ in range(n_lcols + n_rcols))
        + left_extra
        + (P(), P())
    )
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            tuple(P("data") for _ in range(2 + n_lkeys + n_lcols)),
            tuple(P("data") for _ in range(2 + n_lkeys + n_rcols)),
        ),
        out_specs=out_specs,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Distributed sort: range-partitioned samplesort + global rank compaction.
# The scalable ORDER BY for sharded tables — Spark's range-partitioning
# sort-shuffle (reference: spark.sql.shuffle.partitions,
# nds/power_run_cpu.template:20-27) rebuilt on XLA collectives: no device
# ever materializes the whole table.
# ---------------------------------------------------------------------------


def sample_sort(mesh: Mesh, n_keys: int, n_cols: int, cap_route: int,
                n_samples: int = 64):
    """Factory for the mesh samplesort step.

    The returned jitted fn takes (route, live, key..., col...), all sharded on
    the `data` axis, and returns
    (live_out, col_out..., recv_counts [n_dev], overflow):

      * `route` — one comparable value per row, monotone in the most-
        significant sort key (nulls pre-folded to that dtype's extremes);
      * `key...` — the transformed lexsort keys, major->minor, dead rows
        anywhere;
      * rows are range-partitioned by splitters sampled from `route`
        (equal values always colocate, so ties never straddle a device
        boundary), locally lexsorted, then shipped to their global rank
        position with a second all_to_all. The output is globally sorted
        with all live rows first — the Table layout — and no step gathers
        the full table onto one device.

    overflow > 0 means a routing bucket exceeded cap_route (key skew); the
    caller must retry with a doubled cap (cap_route == local rows can never
    overflow). `recv_counts` is the per-device count of live rows received
    in the range-partitioning pass — the skew evidence for the `exchange`
    trace event (splitter sampling keeps it near-balanced except under
    heavy duplicate-key mass).
    """
    n_dev = mesh.devices.size

    def local(route, live, *rest):
        keys = rest[:n_keys]
        cols = rest[n_keys:]
        n = route.shape[0]  # rows per device; also the output block size
        big = (
            jnp.asarray(jnp.inf, route.dtype)
            if jnp.issubdtype(route.dtype, jnp.floating)
            else jnp.asarray(jnp.iinfo(route.dtype).max, route.dtype)
        )
        rm = jnp.where(live, route, big)
        # splitters: every device samples evenly from its sorted live keys,
        # all_gathers the (tiny) sample set, and derives identical quantile
        # splitters — one collective over n_dev*n_samples scalars
        rs = jnp.sort(rm)
        nl = live.sum()
        pos = (jnp.arange(n_samples) * jnp.maximum(nl, 1)) // n_samples
        samp = rs[jnp.clip(pos, 0, n - 1)]
        samp_valid = jnp.full(n_samples, nl > 0)
        all_s = jax.lax.all_gather(samp, "data").reshape(-1)
        all_v = jax.lax.all_gather(samp_valid, "data").reshape(-1)
        ss = jnp.sort(jnp.where(all_v, all_s, big))
        v_total = all_v.sum()
        qpos = (jnp.arange(1, n_dev) * jnp.maximum(v_total, 1)) // n_dev
        splitters = ss[jnp.clip(qpos, 0, ss.shape[0] - 1)]
        dest = jnp.searchsorted(splitters, rm, side="right").astype(jnp.int32)
        rlive, shipped, overflow = _route_by_dest(
            dest, live, n_dev, cap_route, list(keys) + list(cols)
        )
        rkeys = shipped[:n_keys]
        rcols = shipped[n_keys:]
        # local full-key sort: live rows first, then by keys major->minor
        order = jnp.lexsort(tuple(reversed(rkeys)) + (~rlive,))
        live2 = rlive[order]
        cols2 = [c[order] for c in rcols]
        # global rank of each live row = my devices' live-count prefix + local
        # position (live rows are first after the sort)
        nl2 = live2.sum()
        counts = jax.lax.all_gather(nl2, "data")
        d_idx = jax.lax.axis_index("data")
        # skew evidence output: psum'd one-hot (provably replicated under
        # the rep check, unlike the all_gather above)
        recv_counts = jax.lax.psum(
            jnp.zeros(n_dev, jnp.int64).at[d_idx].set(nl2), "data"
        )
        start = jnp.where(jnp.arange(n_dev) < d_idx, counts, 0).sum()
        rank = start + jnp.arange(live2.shape[0], dtype=jnp.int64)
        dest2 = jnp.where(live2, (rank // n).astype(jnp.int32), n_dev)
        pos2 = (rank % n).astype(jnp.int32)

        def scatter2(x, fill):
            buf = jnp.full((n_dev, n), fill, x.dtype)
            buf = buf.at[dest2, pos2].set(x, mode="drop")
            r = jax.lax.all_to_all(buf, "data", 0, 0, tiled=True)
            return r.reshape(n_dev, n)

        # ranks are globally unique, so at most one source placed a row in
        # each output slot: merge across sources by masked sum / any
        placed = scatter2(live2, False)
        outs = []
        for c in cols2:
            buf = scatter2(c, jnp.zeros((), c.dtype))
            if c.dtype == jnp.bool_:
                outs.append(jnp.where(placed, buf, False).any(axis=0))
            else:
                outs.append(
                    jnp.where(placed, buf, jnp.zeros((), c.dtype)).sum(axis=0)
                )
        live_out = placed.any(axis=0)
        return (live_out, *outs, recv_counts, overflow)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(P("data") for _ in range(2 + n_keys + n_cols)),
        out_specs=(P("data"),)
        + tuple(P("data") for _ in range(n_cols))
        + (P(), P()),
    )
    return jax.jit(fn)


_SORT_CACHE = {}


def get_sample_sort(mesh, n_keys, n_cols, cap_route, n_samples=64):
    """Cached factory: one compiled samplesort per signature (see
    get_exchange_hash_join for the topology-keyed cache rationale)."""
    topo = tuple(d.id for d in mesh.devices.flat)
    key = (topo, n_keys, n_cols, cap_route, n_samples)
    if key not in _SORT_CACHE:
        _SORT_CACHE[key] = sample_sort(mesh, n_keys, n_cols, cap_route, n_samples)
    return _SORT_CACHE[key]


_XJOIN_CACHE = {}


def get_exchange_hash_join(mesh, n_lkeys, n_lcols, n_rcols, cap_l, cap_r,
                           pair_cap, kind="inner"):
    """Cached factory: one compiled exchange-join step per signature, so
    repeated joins across a query stream reuse the XLA executable. Keyed by
    the mesh's device topology (not object identity, which a recycled id()
    could alias after GC)."""
    topo = tuple(d.id for d in mesh.devices.flat)
    key = (topo, n_lkeys, n_lcols, n_rcols, cap_l, cap_r, pair_cap, kind)
    if key not in _XJOIN_CACHE:
        _XJOIN_CACHE[key] = exchange_hash_join(
            mesh, n_lkeys, n_lcols, n_rcols, cap_l, cap_r, pair_cap,
            kind=kind,
        )
    return _XJOIN_CACHE[key]
