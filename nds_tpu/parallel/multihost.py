"""Multi-host execution: the DCN tier of the distributed backend.

The reference scales across hosts with YARN-scheduled Spark executors and a
Netty shuffle service (reference: nds/base.template:26-31 `MASTER=yarn`,
8 executors; shuffle config power_run_cpu.template:20-27). The TPU-native
counterpart is jax.distributed: one engine process per host VM, every process
sees the global device set, GSPMD collectives ride ICI inside a slice and DCN
between slices — the same `Mesh`/`shard_map` code in `dist.py` runs unchanged
on a multi-host mesh.

Data ingestion is host-parallel by construction: the generator writes
per-chunk files (`<table>_<child>_<parallel>.dat`) and each host reads only
its own chunks, so a global sharded table is assembled with
`jax.make_array_from_process_local_data` instead of replicating the whole
table through one coordinator (the reference's HDFS-read equivalent).
"""

from __future__ import annotations

import os

import numpy as np


def _enable_cpu_collectives(jax) -> None:
    """Cross-process collectives on the CPU backend need an explicit
    implementation — jax's default ("none") raises "Multiprocess
    computations aren't implemented on the CPU backend", which kept the
    two-process DCN tier skipped on CPU since PR 3. Gloo rides the same
    TCP world the distributed coordinator already set up, so a CPU fleet
    (and the CI gate) gets real cross-process psum/all_to_all. Config
    must land BEFORE the backend initializes; only touched when the
    process is pinned to the CPU platform — TPU pods keep native ICI/DCN
    collectives."""
    try:
        platforms = str(
            getattr(jax.config, "jax_platforms", None)
            or os.environ.get("JAX_PLATFORMS")
            or ""
        )
        if "cpu" in platforms.lower():
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        # older/newer jax without the knob: initialize() then surfaces the
        # real capability error instead of this helper masking it
        pass


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Idempotent jax.distributed bring-up.

    With no arguments, relies on TPU pod auto-detection (the runtime
    environment provides coordinator/process ids on Cloud TPU VMs). Explicit
    arguments support bare-metal/ssh fleets — the same host-list world as
    `cli/gen_data.py cluster` mode. Safe to call in single-process runs:
    initialization is skipped when no cluster environment is configured."""
    import jax

    # NOTE: do not touch jax.devices()/process_count() here — any backend
    # query initializes XLA, after which distributed.initialize() refuses to
    # run. Detect prior initialization through the distributed client state.
    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:
            return  # already initialized
    except Exception:
        pass
    if coordinator_address is not None:
        if num_processes is None or process_id is None:
            raise ValueError(
                "coordinator_address requires num_processes and process_id"
            )
        _enable_cpu_collectives(jax)
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as exc:
            # keep the documented idempotency even if the private
            # global_state probe above stops working on a future jax
            if "already" not in str(exc).lower():
                raise
        return
    if num_processes is not None or process_id is not None:
        raise ValueError(
            "num_processes/process_id need an explicit coordinator_address"
        )
    # no arguments: rely on cluster auto-detection (TPU pod metadata, SLURM).
    # A plain single-host environment has nothing to detect — initialize()
    # raises there, which is the expected no-op path.
    _enable_cpu_collectives(jax)
    try:
        jax.distributed.initialize()
    except Exception:
        pass


def worker_env(process_id=None, base: dict | None = None) -> dict:
    """Subprocess environment for a spawned multihost worker: a copy of
    this process's env (or `base`) carrying a per-worker trace context
    (NDS_TRACE_CONTEXT) minted as a child of the launcher's — the
    worker's event files then fold by trace_id, the same pid-proof
    attribution the throughput parent uses for its stream children."""
    from ..obs import trace as obs_trace

    env = dict(os.environ if base is None else base)
    ctx = obs_trace.current_context() or obs_trace.resolve_trace_context(
        "multihost"
    )
    entry = (
        f"worker{process_id}" if process_id is not None else "worker"
    )
    ctx.child(entry).export(env)
    return env


def global_mesh(axis: str = "data"):
    """Mesh over the global device set (all processes). On one host this is
    exactly dist.make_mesh(); on a pod it spans every chip of every host."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


def shard_rows_across_hosts(mesh, local_rows: np.ndarray):
    """Assemble a globally row-sharded array from per-host local rows.

    Each process contributes the rows it loaded from its own generator
    chunks; the result is one global jax.Array sharded over the mesh's
    `data` axis with no cross-host replication of the table. In a
    single-process run this degenerates to a plain device_put with the
    row-sharded spec (the path the tests cover)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("data"))
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)
